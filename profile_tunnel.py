"""Measure tunnel RTT + concurrency scaling: N threads doing tiny
device_put+device_get rounds. If aggregate round rate scales with
threads, the link is latency-bound and pipelinable.

`--watchdog-selftest` is a fast no-accelerator mode: it exercises the
mesh-serving TunnelWatchdog (parallel/mesh_resident.py) against the CPU
backend — two deliberate deadline overruns must trip it, a healthy
dispatch must recover it — and exits 0 on PASS. CI can run this in
seconds to prove a wedged tunnel degrades instead of hanging.
"""
import os
import sys
import time
import threading

if "--watchdog-selftest" in sys.argv[1:]:
    # keep the selftest off any real accelerator: force the CPU
    # platform BEFORE jax initializes so a wedged tunnel can't wedge us
    os.environ["JAX_PLATFORMS"] = "cpu"
    from pegasus_tpu.parallel.mesh_resident import (
        TunnelWatchdog, _TUNNEL_WEDGED)

    wd = TunnelWatchdog(deadline_s=0.05, trip_after=2)
    # two consecutive overruns: the second must trip
    for i in (1, 2):
        out = wd.run(lambda: time.sleep(0.5) or "late")
        assert out is None, f"overrun {i} returned {out!r}, wanted None"
    assert wd.trips == 1, f"trips={wd.trips}, wanted 1 after 2 overruns"
    assert wd.failures == 0, "trip must reset the consecutive streak"
    assert _TUNNEL_WEDGED.value() == 1.0, "wedged gauge not raised"
    # a healthy dispatch after recover() must pass through its result
    wd.recover()
    assert _TUNNEL_WEDGED.value() == 0.0, "recover left gauge raised"
    assert wd.run(lambda: 42) == 42, "post-recovery dispatch lost"
    assert wd.dispatches == 1 and wd.failures == 0
    print("watchdog selftest: PASS (tripped after 2 overruns, "
          "recovered, healthy dispatch returned)")
    sys.exit(0)

import numpy as np
import jax, jax.numpy as jnp

dev = jax.devices()[0]
print("device:", dev)
x = np.zeros(128, np.uint8)

@jax.jit
def bump(a):
    return a + 1

# warm
with jax.default_device(dev):
    xb = jax.device_put(x, dev)
    np.asarray(bump(xb))

def rounds(n):
    with jax.default_device(dev):
        for _ in range(n):
            xb = jax.device_put(x, dev)
            np.asarray(bump(xb))

# serial RTT
t0 = time.perf_counter(); rounds(10); dt = time.perf_counter() - t0
print(f"serial RTT: {dt/10*1000:.1f} ms/round")

for nthreads in (2, 4, 8, 16, 32):
    per = 6
    ts = [threading.Thread(target=rounds, args=(per,)) for _ in range(nthreads)]
    t0 = time.perf_counter()
    for t in ts: t.start()
    for t in ts: t.join()
    dt = time.perf_counter() - t0
    total = nthreads * per
    print(f"{nthreads:2d} threads: {total} rounds in {dt:.2f}s -> "
          f"{dt/total*1000:.1f} ms/round effective, "
          f"{total/dt:.1f} rounds/s")
