"""Measure tunnel RTT + concurrency scaling: N threads doing tiny
device_put+device_get rounds. If aggregate round rate scales with
threads, the link is latency-bound and pipelinable."""
import time, threading
import numpy as np
import jax, jax.numpy as jnp

dev = jax.devices()[0]
print("device:", dev)
x = np.zeros(128, np.uint8)

@jax.jit
def bump(a):
    return a + 1

# warm
with jax.default_device(dev):
    xb = jax.device_put(x, dev)
    np.asarray(bump(xb))

def rounds(n):
    with jax.default_device(dev):
        for _ in range(n):
            xb = jax.device_put(x, dev)
            np.asarray(bump(xb))

# serial RTT
t0 = time.perf_counter(); rounds(10); dt = time.perf_counter() - t0
print(f"serial RTT: {dt/10*1000:.1f} ms/round")

for nthreads in (2, 4, 8, 16, 32):
    per = 6
    ts = [threading.Thread(target=rounds, args=(per,)) for _ in range(nthreads)]
    t0 = time.perf_counter()
    for t in ts: t.start()
    for t in ts: t.join()
    dt = time.perf_counter() - t0
    total = nthreads * per
    print(f"{nthreads:2d} threads: {total} rounds in {dt:.2f}s -> "
          f"{dt/total*1000:.1f} ms/round effective, "
          f"{total/dt:.1f} rounds/s")
