"""Minimal client usage example (parity: src/sample/main.cpp).

Run against a live onebox:
    python -m pegasus_tpu.tools.onebox_cluster start --dir /tmp/box
    python -m pegasus_tpu.tools.shell --cluster /tmp/box create_app demo -p 4
    python examples/sample.py /tmp/box demo
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pegasus_tpu.tools.onebox_cluster import connect  # noqa: E402


def main() -> None:
    cluster_dir, table = sys.argv[1], sys.argv[2]
    client = connect(table, cluster_dir)

    # basic set / get / delete
    assert client.set(b"user:42", b"name", b"Ada") == 0
    err, value = client.get(b"user:42", b"name")
    print("get ->", err, value)

    # multiple sort keys under one hash key + ranged read
    client.multi_set(b"user:42", {b"city": b"Zurich", b"lang": b"py"})
    err, kvs = client.multi_get(b"user:42")
    print("multi_get ->", sorted(kvs.items()))

    # TTL + counter
    client.set(b"session:1", b"token", b"abc", ttl_seconds=60)
    print("ttl ->", client.ttl(b"session:1", b"token"))
    print("incr ->", client.incr(b"stats", b"visits", 1).new_value)

    # full-table scan fan-out
    total = sum(1 for sc in client.get_unordered_scanners(4) for _ in sc)
    print("records in table:", total)


if __name__ == "__main__":
    main()
