package PegasusTpu;

# Pure-Perl wire client for pegasus_tpu — no FFI, no C library: the
# PGT1 frame + tagged-value grammar (pegasus_tpu/rpc/message.py) and
# crc64 partition routing implemented directly, proving the wire
# format is speakable from any language with a socket library.
#
# Parity role: one of the reference's native client family
# (go/java/python/nodejs/scala clients, src/include/pegasus/client.h);
# surface: query_config routing + set / get / del / multi_get.
#
# CRC tables re-derive from the same polynomial bit-specs as the other
# implementations (base/crc.py, native/packer.cpp); golden vectors in
# tests/test_perl_client.py pin bit-identity.

use strict;
use warnings;
use IO::Socket::INET;
use Socket qw(IPPROTO_TCP TCP_NODELAY);

# Mid-failover errors worth a config refresh + retry — mirrors
# client/cluster_client.py _RETRYABLE (utils/errors.py values).
# 58/63 = ERR_DISK_IO_ERROR / ERR_CHECKSUM_FAILED: the replica
# quarantined over storage corruption; the refresh lands on the
# healed primary once the guardian's re-learn cure completes.
# 64 = ERR_DUP_FENCED: table draining its duplication for a failover
# drill; transient until the flip.
my %RETRYABLE = map { $_ => 1 } (5, 6, 13, 14, 53, 56, 58, 63, 64);

# ---- crc64 (reflected; ~init/~final) --------------------------------

my @CRC64;
{
    my @bits = (63,61,59,58,56,55,52,49,48,47,46,44,41,37,36,34,32,31,
                28,26,23,22,19,16,13,12,10,9,6,4,3,0);
    my $poly = 0;
    $poly |= (1 << (63 - $_)) for @bits;
    for my $i (0 .. 255) {
        my $k = $i;
        for (1 .. 8) {
            $k = ($k & 1) ? (($k >> 1) ^ $poly) : ($k >> 1);
        }
        $CRC64[$i] = $k;
    }
}

sub crc64 {
    my ($data) = @_;
    my $crc = ~0;
    for my $b (unpack "C*", $data) {
        $crc = $CRC64[($crc ^ $b) & 0xFF] ^ ($crc >> 8);
    }
    return ~$crc & ~0;
}

# ---- crc32c (Castagnoli, the PGT1 frame checksum) --------------------

my @CRC32C;
for my $i (0 .. 255) {
    my $k = $i;
    for (1 .. 8) {
        $k = ($k & 1) ? (($k >> 1) ^ 0x82F63B78) : ($k >> 1);
    }
    $CRC32C[$i] = $k;
}

sub crc32c {
    my ($data) = @_;
    my $crc = 0xFFFFFFFF;
    for my $b (unpack "C*", $data) {
        $crc = $CRC32C[($crc ^ $b) & 0xFF] ^ ($crc >> 8);
    }
    return (~$crc) & 0xFFFFFFFF;
}

# ---- tagged value grammar (encode) -----------------------------------

sub enc_str   { my ($s) = @_; return "s" . pack("V", length $s) . $s }
sub enc_bytes { my ($s) = @_; return "b" . pack("V", length $s) . $s }
sub enc_int   { my ($i) = @_; return "i" . pack("q<", $i) }
sub enc_uint  { my ($u) = @_; return "u" . pack("Q<", $u) }
sub enc_none  { return "N" }

sub enc_list {
    my ($tag, @items) = @_;
    return $tag . pack("V", scalar @items) . join("", @items);
}

# dict from pre-encoded (key, value) pairs, in order
sub enc_dict {
    my (@kv) = @_;
    die "odd kv" if @kv % 2;
    my $out = "m" . pack("V", @kv / 2);
    $out .= $_ for @kv;
    return $out;
}

# registered dataclass from pre-encoded fields IN DECLARATION ORDER
sub enc_dataclass {
    my ($name, @fields) = @_;
    return "D" . pack("V", length $name) . $name
        . pack("V", scalar @fields) . join("", @fields);
}

# ---- tagged value grammar (decode) -----------------------------------
# returns (perl-value, next-pos); dataclasses decode to
# {__dataclass__ => name, 0 => f0, 1 => f1, ...}

sub dec_value {
    my ($buf, $pos) = @_;
    my $tag = substr($buf, $pos, 1);
    $pos++;
    if ($tag eq "N") { return (undef, $pos) }
    if ($tag eq "T") { return (1, $pos) }
    if ($tag eq "F") { return (0, $pos) }
    if ($tag eq "i") { return (unpack("q<", substr($buf, $pos, 8)), $pos + 8) }
    if ($tag eq "u") { return (unpack("Q<", substr($buf, $pos, 8)), $pos + 8) }
    if ($tag eq "d") { return (unpack("d<", substr($buf, $pos, 8)), $pos + 8) }
    if ($tag eq "b" or $tag eq "s") {
        my $n = unpack("V", substr($buf, $pos, 4));
        return (substr($buf, $pos + 4, $n), $pos + 4 + $n);
    }
    if ($tag eq "l" or $tag eq "t") {
        my $n = unpack("V", substr($buf, $pos, 4));
        $pos += 4;
        my @items;
        for (1 .. $n) {
            (my $v, $pos) = dec_value($buf, $pos);
            push @items, $v;
        }
        return (\@items, $pos);
    }
    if ($tag eq "m") {
        my $n = unpack("V", substr($buf, $pos, 4));
        $pos += 4;
        my %h;
        for (1 .. $n) {
            (my $k, $pos) = dec_value($buf, $pos);
            (my $v, $pos) = dec_value($buf, $pos);
            $h{defined $k ? $k : ""} = $v;
        }
        return (\%h, $pos);
    }
    if ($tag eq "D") {
        my $nn = unpack("V", substr($buf, $pos, 4));
        $pos += 4;
        my $name = substr($buf, $pos, $nn);
        $pos += $nn;
        my $nf = unpack("V", substr($buf, $pos, 4));
        $pos += 4;
        my %h = (__dataclass__ => $name);
        for my $i (0 .. $nf - 1) {
            (my $v, $pos) = dec_value($buf, $pos);
            $h{$i} = $v;
        }
        return (\%h, $pos);
    }
    die "unknown value tag '$tag' at $pos";
}

# ---- frame ------------------------------------------------------------

sub make_frame {
    my ($src, $dst, $type, $payload) = @_;
    my $body = enc_str($src) . enc_str($dst) . enc_str($type) . $payload;
    return "PGT1" . pack("V V", length $body, crc32c($body)) . $body;
}

# ---- client -----------------------------------------------------------

sub new {
    my ($class, %args) = @_;
    my $self = {
        name  => $args{name} || "perl-client",
        app   => $args{app},
        book  => $args{book},    # { node => [host, port] }
        metas => $args{metas},   # [node, ...]
        socks => {},
        rid   => 1000,
        app_id => undef,
        partition_count => 0,
        primaries => [],
    };
    return bless $self, $class;
}

sub _sock {
    my ($self, $node) = @_;
    return $self->{socks}{$node} if $self->{socks}{$node};
    my ($host, $port) = @{ $self->{book}{$node} or die "unknown node $node" };
    my $s = IO::Socket::INET->new(
        PeerAddr => $host, PeerPort => $port,
        Proto => "tcp", Timeout => 10) or die "connect $node: $!";
    $s->setsockopt(IPPROTO_TCP, TCP_NODELAY, 1);
    $self->{socks}{$node} = $s;
    return $s;
}

sub _call {
    my ($self, $node, $type, $payload, $reply_type, $rid) = @_;
    my $s = $self->_sock($node);
    print $s make_frame($self->{name}, $node, $type, $payload);
    for (1 .. 64) {   # tolerate unrelated frames
        my $hdr = _read_exact($s, 12);
        die "bad magic" unless substr($hdr, 0, 4) eq "PGT1";
        my ($blen, $want) = unpack("V V", substr($hdr, 4));
        my $body = _read_exact($s, $blen);
        die "crc mismatch" unless crc32c($body) == $want;
        my $pos = 0;
        (my $fsrc, $pos) = dec_value($body, $pos);
        (my $fdst, $pos) = dec_value($body, $pos);
        (my $mt,   $pos) = dec_value($body, $pos);
        (my $pl,   $pos) = dec_value($body, $pos);
        next unless $mt eq $reply_type;
        next unless ($pl->{rid} // -1) == $rid;
        return $pl;
    }
    die "no matching reply for $type";
}

sub _read_exact {
    my ($s, $n) = @_;
    my $buf = "";
    while (length($buf) < $n) {
        my $got = "";
        my $r = $s->sysread($got, $n - length($buf));
        die "connection closed" unless $r;
        $buf .= $got;
    }
    return $buf;
}

sub refresh_config {
    my ($self) = @_;
    for my $meta (@{ $self->{metas} }) {
        my $rid = $self->{rid}++;
        my $payload = enc_dict(
            enc_str("app_name"), enc_str($self->{app}),
            enc_str("rid"),      enc_int($rid));
        my $pl = eval {
            $self->_call($meta, "query_config", $payload,
                         "query_config_reply", $rid);
        };
        next unless $pl && ($pl->{err} // -1) == 0;
        $self->{app_id} = $pl->{app_id};
        $self->{partition_count} = $pl->{partition_count};
        $self->{primaries} =
            [ map { $_->{primary} } @{ $pl->{configs} } ];
        return 1;
    }
    return 0;
}

sub _full_key {
    my ($hk, $sk) = @_;
    return pack("n", length $hk) . $hk . $sk;
}

sub _restore_key {
    my ($full) = @_;
    my $hl = unpack("n", $full);
    return (substr($full, 2, $hl), substr($full, 2 + $hl));
}

# adjacent next key after every key with this prefix — drop trailing
# 0xFF, increment the last byte (base/key_schema.py generate_next_bytes)
sub _next_bytes {
    my ($b) = @_;
    my @c = unpack("C*", $b);
    pop @c while @c && $c[-1] == 0xFF;
    return "" unless @c;
    $c[-1]++;
    return pack("C*", @c);
}

sub _route {
    my ($self, $hk, $sk) = @_;
    unless (defined $self->{app_id}) {
        $self->refresh_config()
            or die "cannot resolve config for app '$self->{app}' "
                 . "(no meta reachable or app missing)";
    }
    # an empty hash key routes by the sort key — key_hash_parts
    # (base/key_schema.py:73-78); multi-key ops pass sk="" like the
    # other clients
    my $h = crc64(length($hk) ? $hk : ($sk // ""));
    my $pidx = $h % $self->{partition_count};
    return ($pidx, $h, $self->{primaries}[$pidx]);
}

sub _gpid {
    my ($self, $pidx) = @_;
    return enc_list("t", enc_int($self->{app_id}), enc_int($pidx));
}

# Refresh-on-error retry around one routed request — the same
# discipline as ClusterClient._read/_write (cluster_client.py:181-243)
# and wire_client.cpp's 4-attempt loop. Every op this client exposes
# (put/remove/get/multi_get) is retry-safe; the non-idempotent ops
# (incr/cas/cam) are not in this surface. $op->($pidx,$h,$primary)
# must return the reply payload (with an `err` field) or die on a
# transport fault.
sub _with_retry {
    my ($self, $hk, $sk, $op) = @_;
    my $last = "no attempt";
    for my $attempt (1 .. 8) {
        select(undef, undef, undef, 0.05 * $attempt) if $attempt > 1;
        my ($pidx, $h, $primary) = eval { $self->_route($hk, $sk) };
        if ($@ or !defined($primary) or $primary eq "") {
            # mid-failover: partition momentarily unowned, or config
            # unresolvable — force a re-resolve next attempt
            $last = $@ || "partition momentarily unowned";
            $self->{app_id} = undef;
            next;
        }
        my $pl = eval { $op->($pidx, $h, $primary) };
        if ($@) {
            $last = $@;
            my $s = delete $self->{socks}{$primary};
            close $s if $s;
            $self->{app_id} = undef;
            next;
        }
        my $err = $pl->{err} // -1;
        if ($err != 0 && $RETRYABLE{$err}) {
            $last = "retryable err $err";
            $self->{app_id} = undef;
            next;
        }
        return $pl;
    }
    die "retries exhausted: $last";
}

# returns the per-op status (0 = OK)
sub set {
    my ($self, $hk, $sk, $value, $expire_ts) = @_;
    $expire_ts ||= 0;
    my $pl = $self->_with_retry($hk, $sk, sub {
        my ($pidx, $h, $primary) = @_;
        my $rid = $self->{rid}++;
        my $wop = enc_list("t", enc_int(1),   # OP_PUT
            enc_list("t", enc_bytes(_full_key($hk, $sk)),
                     enc_bytes($value), enc_int($expire_ts)));
        my $payload = enc_dict(
            enc_str("gpid"), $self->_gpid($pidx),
            enc_str("rid"),  enc_int($rid),
            enc_str("ops"),  enc_list("l", $wop),
            enc_str("auth"), enc_none(),
            enc_str("partition_hash"), enc_uint($h));
        return $self->_call($primary, "client_write", $payload,
                            "client_write_reply", $rid);
    });
    return $pl->{err} if ($pl->{err} // -1) != 0;
    return $pl->{results}[0];
}

sub del {
    my ($self, $hk, $sk) = @_;
    my $pl = $self->_with_retry($hk, $sk, sub {
        my ($pidx, $h, $primary) = @_;
        my $rid = $self->{rid}++;
        my $wop = enc_list("t", enc_int(2),   # OP_REMOVE
            enc_list("t", enc_bytes(_full_key($hk, $sk))));
        my $payload = enc_dict(
            enc_str("gpid"), $self->_gpid($pidx),
            enc_str("rid"),  enc_int($rid),
            enc_str("ops"),  enc_list("l", $wop),
            enc_str("auth"), enc_none(),
            enc_str("partition_hash"), enc_uint($h));
        return $self->_call($primary, "client_write", $payload,
                            "client_write_reply", $rid);
    });
    return $pl->{err} if ($pl->{err} // -1) != 0;
    return $pl->{results}[0];
}

# returns (status, value); status 0 = OK, 1 = NOT_FOUND
sub get {
    my ($self, $hk, $sk) = @_;
    my $pl = $self->_with_retry($hk, $sk, sub {
        my ($pidx, $h, $primary) = @_;
        my $rid = $self->{rid}++;
        my $payload = enc_dict(
            enc_str("gpid"), $self->_gpid($pidx),
            enc_str("rid"),  enc_int($rid),
            enc_str("op"),   enc_str("get"),
            enc_str("args"), enc_bytes(_full_key($hk, $sk)),
            enc_str("auth"), enc_none(),
            enc_str("partition_hash"), enc_uint($h));
        return $self->_call($primary, "client_read", $payload,
                            "client_read_reply", $rid);
    });
    die "read err $pl->{err}" if ($pl->{err} // -1) != 0;
    my ($status, $value) = @{ $pl->{result} };
    return ($status, $value);
}

# returns (status, { sort_key => value }) for ALL sort keys of $hk
sub multi_get {
    my ($self, $hk) = @_;
    my $pl = $self->_with_retry($hk, "", sub {
        my ($pidx, $h, $primary) = @_;
        my $rid = $self->{rid}++;
        # MultiGetRequest in declaration order (server/types.py:160)
        my $req = enc_dataclass("MultiGetRequest",
            enc_bytes($hk), enc_list("l"), enc_int(-1), enc_int(-1),
            "F", enc_bytes(""), enc_bytes(""), "T", "F",
            enc_int(0), enc_bytes(""), "F");
        my $payload = enc_dict(
            enc_str("gpid"), $self->_gpid($pidx),
            enc_str("rid"),  enc_int($rid),
            enc_str("op"),   enc_str("multi_get"),
            enc_str("args"), $req,
            enc_str("auth"), enc_none(),
            enc_str("partition_hash"), enc_uint($h));
        return $self->_call($primary, "client_read", $payload,
                            "client_read_reply", $rid);
    });
    die "read err $pl->{err}" if ($pl->{err} // -1) != 0;
    my $resp = $pl->{result};
    die "unexpected result" unless $resp->{__dataclass__} eq "MultiGetResponse";
    my $status = $resp->{0};
    my %kvs;
    for my $kv (@{ $resp->{1} }) {
        $kvs{ $kv->{0} } = $kv->{1};   # KeyValue: key (=sortkey), value
    }
    return ($status, \%kvs);
}

# Paged hash-key scanner (parity: client.h get_scanner/scan —
# pegasus_scanner paging over RPC_RRDB_RRDB_SCAN; the sibling
# implementations are cluster_client.ClusterScanner and
# wire_client.cpp's scanner). Returns [[sort_key, value], ...] in key
# order across however many server pages the range needs; the scan
# context pages against the SAME primary (contexts are per-server).
# opts: start/stop sort keys (stop exclusive), batch_size.
sub scan_hashkey {
    my ($self, $hk, %opt) = @_;
    my $stop = (defined $opt{stop} && length $opt{stop})
        ? _full_key($hk, $opt{stop})
        : _next_bytes(_full_key($hk, ""));
    my $start = _full_key($hk, $opt{start} // "");
    my @rows;
    # Restart discipline mirrors cluster_client.ClusterScanner._fetch:
    # the first page goes through the refresh-on-error retry; any
    # paging fault afterwards (failover, server scan-context eviction,
    # transport error) reissues get_scanner from just past the last
    # served key instead of dying — server contexts are per-primary
    # and evictable, never a correctness anchor.
    my $restarts = 0;
    RESTART: while (1) {
        die "scan: too many restarts" if $restarts++ > 32;
        # GetScannerRequest in declaration order (server/types.py:273)
        my $req = enc_dataclass("GetScannerRequest",
            enc_bytes($start), enc_bytes($stop), "T", "F",
            enc_int($opt{batch_size} // 1000), "F",
            enc_int(0), enc_bytes(""), enc_int(0), enc_bytes(""),
            "T", "F", "F", "F", "F");
        my $primary_used;
        my $pl = $self->_with_retry($hk, "", sub {
            my ($pidx, $h, $primary) = @_;
            $primary_used = $primary;
            my $rid = $self->{rid}++;
            my $payload = enc_dict(
                enc_str("gpid"), $self->_gpid($pidx),
                enc_str("rid"),  enc_int($rid),
                enc_str("op"),   enc_str("get_scanner"),
                enc_str("args"), $req,
                enc_str("auth"), enc_none(),
                enc_str("partition_hash"), enc_uint($h));
            return $self->_call($primary, "client_read", $payload,
                                "client_read_reply", $rid);
        });
        my $pidx = ($self->_route($hk, ""))[0];
        while (1) {
            die "scan err $pl->{err}" if ($pl->{err} // -1) != 0;
            my $resp = $pl->{result};
            if ($resp->{0} != 0) {
                # 1 = NOT_FOUND: the server evicted this scan context
                # (partition_server on_scan) — restart past the last
                # key this scan already served; other errors are real
                die "scan resp err $resp->{0}" if $resp->{0} != 1;
                $start = @rows ? $rows[-1][0] . "\x00" : $start;
                next RESTART;
            }
            push @rows, @{ _page_rows($resp->{1}) };
            my $ctx = $resp->{2};
            last RESTART if $ctx < 0;   # COMPLETED
            my $rid = $self->{rid}++;
            my $payload = enc_dict(
                enc_str("gpid"), $self->_gpid($pidx),
                enc_str("rid"),  enc_int($rid),
                enc_str("op"),   enc_str("scan"),
                enc_str("args"), enc_int($ctx),
                enc_str("auth"), enc_none(),
                enc_str("partition_hash"), enc_none());
            $pl = eval {
                $self->_call($primary_used, "client_read", $payload,
                             "client_read_reply", $rid);
            };
            if ($@ or ($pl->{err} // -1) != 0) {
                die "scan err $pl->{err}"
                    if !$@ and !$RETRYABLE{$pl->{err} // -1};
                # transport fault or retryable error mid-page: drop the
                # (possibly desynced) socket and restart the range
                my $s = delete $self->{socks}{$primary_used};
                close $s if $s;
                $self->{app_id} = undef;
                $start = @rows ? $rows[-1][0] . "\x00" : $start;
                next RESTART;
            }
        }
    }
    return [ map { my ($fhk, $sk) = _restore_key($_->[0]);
                   [$sk, $_->[1]] } @rows ];
}

# a response page's [full_key, value] pairs: either a KeyValue list or
# ONE columnar ScanPage (offset-sliced blobs — server/types.py:64)
sub _page_rows {
    my ($kvs) = @_;
    my @out;
    if (ref $kvs eq "ARRAY") {
        push @out, [$_->{0}, $_->{1} // ""] for @$kvs;
    } elsif (ref $kvs eq "HASH") {
        my @ko = unpack("V*", $kvs->{0});
        my @vo = unpack("V*", $kvs->{2});
        for my $i (0 .. $#ko - 1) {
            push @out, [
                substr($kvs->{1}, $ko[$i], $ko[$i + 1] - $ko[$i]),
                substr($kvs->{3}, $vo[$i], $vo[$i + 1] - $vo[$i])];
        }
    }
    return \@out;
}

sub close_all {
    my ($self) = @_;
    close $_ for values %{ $self->{socks} };
    $self->{socks} = {};
}

1;
