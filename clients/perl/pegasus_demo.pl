#!/usr/bin/perl
# Demo / test driver for the pure-Perl wire client: reads the onebox
# cluster config, performs set/get/del/multi_get against the live
# cluster, and prints TAP-ish OK lines the test asserts on.
#
#   perl pegasus_demo.pl <cluster.json> <app_name>

use strict;
use warnings;
use FindBin;
use lib $FindBin::Bin;
use PegasusTpu;

my ($config_path, $app) = @ARGV;
die "usage: $0 <cluster.json> <app>" unless $config_path && $app;

# minimal JSON parse for the onebox config (flat, known shape; no
# non-core JSON module needed)
open my $fh, "<", $config_path or die "open $config_path: $!";
my $json = do { local $/; <$fh> };
close $fh;

my (%book, @metas);
while ($json =~ /"([a-z0-9]+)":\s*\{([^{}]*)\}/g) {
    my ($name, $body) = ($1, $2);
    next unless $body =~ /"host":\s*"([^"]+)"/;
    my $host = $1;
    next unless $body =~ /"port":\s*(\d+)/;
    my $port = $1;
    $book{$name} = [$host, $port];
    push @metas, $name if $body =~ /"role":\s*"meta"/;
}
die "no meta in config" unless @metas;

my $c = PegasusTpu->new(app => $app, book => \%book, metas => \@metas,
                        name => "perl-demo");
$c->refresh_config() or die "refresh_config failed";
print "ok config partitions=$c->{partition_count}\n";

for my $i (0 .. 19) {
    my $st = $c->set("phk$i", "s", "perl-value-$i");
    die "set $i: status $st" if $st != 0;
}
print "ok set 20\n";

for my $i (0 .. 19) {
    my ($st, $v) = $c->get("phk$i", "s");
    die "get $i: status $st" if $st != 0;
    die "get $i: got '$v'" if $v ne "perl-value-$i";
}
print "ok get 20\n";

my ($st, $v) = $c->get("phk-missing", "s");
die "missing: status $st" unless $st == 1;
print "ok notfound\n";

for my $i (0 .. 9) {
    my $s = $c->set("pmulti", sprintf("s%02d", $i), "mv$i");
    die "multi set $i: $s" if $s != 0;
}
my ($mst, $kvs) = $c->multi_get("pmulti");
die "multi_get status $mst" if $mst != 0;
my $n = scalar keys %$kvs;
die "multi_get count $n" if $n != 10;
die "multi_get s03" unless $kvs->{"s03"} eq "mv3";
print "ok multi_get 10\n";

$st = $c->del("phk0", "s");
die "del: $st" if $st != 0;
($st, $v) = $c->get("phk0", "s");
die "del visible: $st" unless $st == 1;
print "ok del\n";

# paged scanner: 30 rows under one hash key, tiny pages force real
# server-side context paging; a ranged scan narrows by sort key
for my $i (0 .. 29) {
    my $s = $c->set("pscan", sprintf("k%03d", $i), "sv$i");
    die "scan set $i: $s" if $s != 0;
}
my $rows = $c->scan_hashkey("pscan", batch_size => 7);
die "scan count " . scalar(@$rows) unless @$rows == 30;
for my $i (0 .. 29) {
    my ($sk, $v) = @{ $rows->[$i] };
    die "scan row $i: $sk=$v"
        unless $sk eq sprintf("k%03d", $i) && $v eq "sv$i";
}
print "ok scan 30 paged\n";
$rows = $c->scan_hashkey("pscan", start => "k010", stop => "k020");
die "ranged scan count " . scalar(@$rows) unless @$rows == 10;
die "ranged first " . $rows->[0][0] unless $rows->[0][0] eq "k010";
print "ok scan ranged 10\n";

# leave one marker the python side reads back (cross-language interop)
$st = $c->set("perl-wrote", "s", "hello-from-perl");
die "marker: $st" if $st != 0;
print "ok marker\n";

$c->close_all();
print "PERL CLIENT OK\n";
