#!/usr/bin/env python
"""YCSB-E-shaped scan benchmark for pegasus_tpu.

Workload (BASELINE.md config #2): a 64-partition table, zipfian-start range
scans of up to 100 records each, 95% scan / 5% insert, with the realistic
per-record read predicates Pegasus applies (TTL expiry on every record,
partition-hash validation) running on the accelerator. 10% of the loaded
records carry expired TTLs so expiry filtering does real work.

Prints ONE JSON line to stdout:
    {"metric": ..., "value": ops/sec, "unit": ..., "vs_baseline": ratio}
vs_baseline = accelerator throughput / XLA-CPU throughput for the same
workload in the same process (the CPU baseline the reference's scalar C++
loop competes with — see BASELINE.md "measure CPU baseline").

Env knobs: PEGBENCH_RECORDS (default 100_000), PEGBENCH_OPS (default 300),
PEGBENCH_PARTITIONS (default 64), PEGBENCH_SEED.
"""

import json
import os
import sys
import tempfile
import time


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def setup_jax():
    """Make both the accelerator and CPU platforms available."""
    import jax

    try:
        current = jax.config.jax_platforms or ""
    except AttributeError:
        current = os.environ.get("JAX_PLATFORMS", "")
    if current and "cpu" not in current.split(","):
        jax.config.update("jax_platforms", current + ",cpu")
    return jax


def build_table(tmpdir, n_records, n_partitions, seed):
    import numpy as np

    from pegasus_tpu.base.value_schema import epoch_now
    from pegasus_tpu.client import PegasusClient, Table

    rng = np.random.default_rng(seed)
    table = Table(tmpdir, app_name="bench", partition_count=n_partitions)
    client = PegasusClient(table)
    now = epoch_now()

    t0 = time.perf_counter()
    n_hashkeys = max(1, n_records // 10)
    # direct write-service loads grouped per partition (bulk-load style)
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.base.value_schema import generate_value
    from pegasus_tpu.storage.engine import WriteBatchItem
    from pegasus_tpu.storage.wal import OP_PUT

    per_server_items = {p.pidx: [] for p in table.all_partitions()}
    i = 0
    for h in range(n_hashkeys):
        hk = b"user%08d" % h
        server = table.resolve(hk)
        items = per_server_items[server.pidx]
        for s in range(10):
            if i >= n_records:
                break
            ets = 0 if rng.random() > 0.10 else max(1, now - 100)
            value = b"field0=%064d" % i
            key = generate_key(hk, b"s%02d" % s)
            items.append(WriteBatchItem(
                OP_PUT, key, generate_value(1, value, ets), ets))
            i += 1
    for p in table.all_partitions():
        items = per_server_items[p.pidx]
        for off in range(0, len(items), 1000):
            p.engine.write_batch(items[off:off + 1000],
                                 p.engine.last_committed_decree + 1)
    _log(f"loaded {i} records in {time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    table.manual_compact_all()
    _log(f"compacted in {time.perf_counter() - t0:.1f}s")
    return table, client


def run_scans(table, n_ops, n_partitions, n_hashkeys, seed, record_goal=100,
              insert_frac=0.05):
    """95% scans / 5% inserts; returns (ops, records, elapsed_s)."""
    import numpy as np

    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.server.types import GetScannerRequest

    rng = np.random.default_rng(seed)
    partitions = table.all_partitions()
    # zipfian-ish partition popularity
    ranks = rng.permutation(n_partitions)
    weights = 1.0 / (1.0 + ranks.astype(float))
    weights /= weights.sum()
    # zipfian-ish start-key popularity within the loaded keyspace
    zipf_u = rng.random(n_ops) ** 2.0

    records = 0
    inserts = 0
    t0 = time.perf_counter()
    for op in range(n_ops):
        if rng.random() < insert_frac:
            hk = b"user%08d" % int(rng.integers(0, 1 << 30))
            server = table.resolve(hk)
            server.on_put(generate_key(hk, b"s00"), b"inserted")
            inserts += 1
            continue
        pidx = int(rng.choice(n_partitions, p=weights))
        server = partitions[pidx]
        start_hk = b"user%08d" % int(zipf_u[op] * n_hashkeys)
        scan_len = int(rng.integers(1, record_goal + 1))
        resp = server.on_get_scanner(GetScannerRequest(
            start_key=generate_key(start_hk, b""),
            batch_size=scan_len,
            validate_partition_hash=True))
        records += len(resp.kvs)
        if resp.context_id >= 0:
            server.on_clear_scanner(resp.context_id)
    elapsed = time.perf_counter() - t0
    return n_ops, records, elapsed


def main() -> None:
    n_records = int(os.environ.get("PEGBENCH_RECORDS", 100_000))
    n_ops = int(os.environ.get("PEGBENCH_OPS", 300))
    n_partitions = int(os.environ.get("PEGBENCH_PARTITIONS", 64))
    seed = int(os.environ.get("PEGBENCH_SEED", 7))

    jax = setup_jax()
    accel = jax.devices()[0]
    cpu = jax.local_devices(backend="cpu")[0]
    _log(f"accelerator: {accel}, baseline: {cpu}")

    with tempfile.TemporaryDirectory(prefix="pegbench") as tmpdir:
        table, client = build_table(tmpdir, n_records, n_partitions, seed)
        n_hashkeys = max(1, n_records // 10)
        def reset_store():
            # both measured phases start from the identical fully-compacted
            # state (the 5% inserts during a phase otherwise leave the
            # store different for the second phase)
            table.manual_compact_all()

        try:
            # each phase: reset store -> warmup (compile + populate device
            # block caches on the fresh files) -> measure
            with jax.default_device(accel):
                reset_store()
                run_scans(table, 60, n_partitions, n_hashkeys, seed + 2, insert_frac=0)
                ops, recs, accel_s = run_scans(table, n_ops, n_partitions,
                                               n_hashkeys, seed + 2)
            accel_qps = ops / accel_s
            _log(f"accel: {ops} ops / {recs} records in {accel_s:.2f}s "
                 f"-> {accel_qps:.1f} ops/s, {recs / accel_s:.0f} rec/s")

            # CPU baseline: identical workload, XLA-CPU executes the
            # predicate programs
            with jax.default_device(cpu):
                reset_store()
                run_scans(table, 60, n_partitions, n_hashkeys, seed + 2, insert_frac=0)
                ops_c, recs_c, cpu_s = run_scans(table, n_ops, n_partitions,
                                                 n_hashkeys, seed + 2)
            cpu_qps = ops_c / cpu_s
            _log(f"cpu:   {ops_c} ops / {recs_c} records in {cpu_s:.2f}s "
                 f"-> {cpu_qps:.1f} ops/s")

            print(json.dumps({
                "metric": "YCSB-E scan ops/sec/chip (64-partition, "
                          "TTL+hash-validated)",
                "value": round(accel_qps, 2),
                "unit": "ops/s",
                "vs_baseline": round(accel_qps / cpu_qps, 3) if cpu_qps else 0,
            }))
        finally:
            table.close()


if __name__ == "__main__":
    main()
