#!/usr/bin/env python
"""YCSB-E-shaped scan benchmark for pegasus_tpu.

Workload (BASELINE.md config #2): a 64-partition table, zipfian-start range
scans of up to 100 records each, 95% scan / 5% insert, with the realistic
per-record read predicates Pegasus applies (TTL expiry on every record,
partition-hash validation) running on the accelerator. 10% of the loaded
records carry expired TTLs so expiry filtering does real work.

Prints ONE JSON line to stdout:
    {"metric": ..., "value": ops/sec, "unit": ..., "vs_baseline": ratio}
vs_baseline = accelerator throughput / XLA-CPU throughput for the same
workload in the same process (the CPU baseline the reference's scalar C++
loop competes with — see BASELINE.md "measure CPU baseline").

Secondary phases — YCSB-C point gets (BASELINE config #1; always on),
round-8 filtered reads (point_get_miss / point_get_hot: bloom pruning +
the node row cache vs the unfiltered baseline, byte-identity gated,
persisted to BENCH_r08.json),
manual-compaction GB/s (configs #3/#4), geo radius search (config #5)
— all ON by default (PEGBENCH_COMPACT=0 / PEGBENCH_GEO=0 to skip) — land
in BENCH_DETAILS.json
next to this script plus stderr; stdout stays one line.

The accelerator in this image sits behind a tunnel whose backend init can
fail transiently (or hang for hours if a previous claim was killed), so
device bring-up happens in a probe SUBPROCESS with bounded retries and
backoff; on permanent failure the run degrades to a measured CPU-only
pass whose one JSON line carries the fault ("error": ..., "platform":
"cpu-fallback") — a measured number with provenance instead of value=0.

Env knobs: PEGBENCH_RECORDS (default 1_000_000), PEGBENCH_OPS (default
12_000), PEGBENCH_COMPACT_GB (default 1.0), PEGBENCH_EXPIRED (default 0.5),
PEGBENCH_PARTITIONS (default 64), PEGBENCH_SEED, PEGBENCH_COMPACT=0 /
PEGBENCH_GEO=0 (skip those phases),
PEGBENCH_SCAN_BATCH (default 32: scans coalesced per device dispatch —
the request-batching unit of SURVEY §2.6; 1 disables coalescing),
PEGBENCH_GET_BATCH (default 32: point gets coalesced per read-
coordinator flush in the point_get_batch phase),
PEGBENCH_WRITE_BATCH (default 32: puts coalesced per write_multi flush
in the write_put_batch phase),
PEGBENCH_PROBE_TIMEOUT (s, default 120), PEGBENCH_PROBE_RETRIES (default 4),
PEGBENCH_FORCE_CPU=1 (CPU-only dry run: never dials the TPU tunnel),
PEGBENCH_MESH=0 (skip the mesh_scan phase) / PEGBENCH_MESH_RECORDS
(default 240_000) / PEGBENCH_MESH_PARTITIONS (default 8) — the
mesh_scan phase always runs on a CPU-device mesh in a subprocess
(--mesh-phase), so it needs no accelerator.
PEGBENCH_MESH_COMPACT=0 (skip the mesh_compact phase) /
PEGBENCH_MESH_COMPACT_RECORDS (default 192_000) — the compaction
FILTER-stage twin of mesh_scan, same CPU-device-mesh subprocess shape
(--mesh-compact-phase).
"""

import json
import os
import subprocess
import sys
import tempfile
import time


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


_ISOLATE_SRC = r"""
import os
if os.environ.get("PEGBENCH_FORCE_CPU") == "1":
    # CPU-only run (CI / wedged-tunnel dry runs): never dial the axon
    # TPU tunnel — its plugin dials the pool even under
    # JAX_PLATFORMS=cpu. Self-contained copy of
    # pegasus_tpu/utils/cpu_isolation.force_cpu (this source string is
    # exec'd in subprocess probes before the package is importable)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    import jax._src.xla_bridge as _xb
    jax.config.update("jax_platforms", "cpu")
    _xb._backend_factories.pop("axon", None)
"""

exec(_ISOLATE_SRC)

_PROBE_SRC = _ISOLATE_SRC + r"""
import sys
import jax
devs = jax.devices()
accel = [d for d in devs if d.platform != "cpu"]
print("PROBE_OK", devs[0].platform, len(devs), flush=True)
if accel:
    # one tiny dispatch proves the chip executes, not just enumerates
    import jax.numpy as jnp
    x = jnp.arange(8)
    print("PROBE_EXEC", int((x * 2).sum()), flush=True)
"""


def probe_accelerator(timeout_s: float, retries: int) -> dict:
    """Bring up the accelerator backend in a subprocess.

    Returns {"ok": True, "platform": ...} or {"ok": False, "error": ...}.
    A subprocess is the only safe way to bound this: a wedged tunnel blocks
    inside the PJRT C client where no Python-level timeout can interrupt.
    The probe holds no claim until init succeeds, and exits cleanly right
    after, so killing it on timeout does not wedge the chip.
    """
    last = ""
    for attempt in range(retries):
        if attempt:
            backoff = min(60, 10 * (2 ** (attempt - 1)))
            _log(f"probe retry {attempt + 1}/{retries} in {backoff}s "
                 f"(last: {last.strip()[:200]})")
            time.sleep(backoff)
        t0 = time.perf_counter()
        try:
            r = subprocess.run([sys.executable, "-c", _PROBE_SRC],
                               capture_output=True, text=True,
                               timeout=timeout_s)
        except subprocess.TimeoutExpired:
            last = f"probe timed out after {timeout_s}s (tunnel wedged?)"
            continue
        out = r.stdout or ""
        if r.returncode == 0 and "PROBE_OK" in out:
            platform = out.split("PROBE_OK", 1)[1].split()[0]
            _log(f"probe ok in {time.perf_counter() - t0:.1f}s: "
                 f"platform={platform}")
            return {"ok": True, "platform": platform,
                    "executed": "PROBE_EXEC" in out}
        last = (r.stderr or "")[-500:] or f"rc={r.returncode}"
    return {"ok": False, "error": last}


def pallas_smoke() -> str:
    """Compile+run the fused Pallas scan kernel on the default backend.

    Distinguishes a mosaic-lowering failure from a tunnel failure: the
    caller already proved the backend is alive. Returns "ok", "fallback"
    (interpret mode used — not a TPU), or the error string.
    """
    try:
        import numpy as np

        from pegasus_tpu.base.key_schema import generate_key
        from pegasus_tpu.ops.record_block import build_record_block
        import pegasus_tpu.ops.pallas_scan as ps

        keys = [generate_key(b"hk%d" % i, b"s%02d" % i) for i in range(64)]
        ets = [0 if i % 2 else 1 for i in range(64)]
        block = build_record_block(keys, ets, capacity=64, key_width=32)
        keep, expired = ps.fused_scan_block(block, now=100)
        n = int(np.asarray(keep).sum())
        assert 0 <= n <= 64
        return "ok"
    except Exception as e:  # noqa: BLE001 - diagnostic path
        return f"{type(e).__name__}: {e}"[:300]


class BenchCluster:
    """Replicated-path bench target: a SimCluster onebox whose measured
    ops go client -> sim transport -> replica-stub gates -> storage app
    (VERDICT r1: the benched path must be the replicated path). Single
    replica per partition (BASELINE config #1 "onebox single-replica") so
    load cost stays in the storage engine, not the sim scheduler."""

    def __init__(self, tmpdir, n_partitions):
        from pegasus_tpu.tools.cluster import SimCluster

        self.cluster = SimCluster(tmpdir, n_nodes=1)
        self.app_id = self.cluster.create_table(
            "bench", partition_count=n_partitions, replica_count=1)
        self.client = self.cluster.client("bench")
        self.client.refresh_config()
        node = next(iter(self.cluster.stubs.values()))
        self.servers = [node.get_replica((self.app_id, pidx)).server
                        for pidx in range(n_partitions)]
        self.replicas = [node.get_replica((self.app_id, pidx))
                         for pidx in range(n_partitions)]

    def manual_compact_all(self, rules_filter=None, device=None):
        """Partitions overlap on a thread pool: each one's device-filter
        round-trip would otherwise serialize (64 x tunnel RTT)."""
        from pegasus_tpu.client.table import compact_partitions_parallel

        compact_partitions_parallel(self.servers, device=device,
                                    rules_filter=rules_filter)

    def close(self):
        self.cluster.close()


def build_cluster(tmpdir, n_records, n_partitions, seed):
    import numpy as np

    from pegasus_tpu.base.key_schema import generate_key, key_hash_parts
    from pegasus_tpu.base.value_schema import epoch_now
    from pegasus_tpu.replica.mutation import WriteOp
    from pegasus_tpu.rpc.codec import OP_PUT

    rng = np.random.default_rng(seed)
    bc = BenchCluster(tmpdir, n_partitions)
    now = epoch_now()

    t0 = time.perf_counter()
    n_hashkeys = max(1, n_records // 10)
    # load through the REPLICA WRITE PATH (batched mutations: many puts
    # share one mutation, parity mutation.cpp:390) grouped per partition
    per_pidx_ops = {pidx: [] for pidx in range(n_partitions)}
    i = 0
    for h in range(n_hashkeys):
        hk = b"user%08d" % h
        ops = per_pidx_ops[key_hash_parts(hk) % n_partitions]
        for sk_i in range(10):
            if i >= n_records:
                break
            ets = 0 if rng.random() > 0.10 else max(1, now - 100)
            value = b"field0=%064d" % i
            key = generate_key(hk, b"s%02d" % sk_i)
            ops.append(WriteOp(OP_PUT, (key, value, ets)))
            i += 1
    for pidx, ops in per_pidx_ops.items():
        r = bc.replicas[pidx]
        for off in range(0, len(ops), 1000):
            r.client_write(ops[off:off + 1000])
        bc.cluster.loop.run_until_idle()
    load_s = time.perf_counter() - t0
    bc.load_write_qps = round(i / load_s, 1)  # replicated write path rate
    _log(f"loaded {i} records in {load_s:.1f}s "
         f"({bc.load_write_qps:.0f} writes/s through 2PC)")

    t0 = time.perf_counter()
    bc.manual_compact_all()
    _log(f"compacted in {time.perf_counter() - t0:.1f}s")
    return bc


def run_scans(bc, n_ops, n_partitions, n_hashkeys, seed, record_goal=100,
              insert_frac=0.05, scan_batch=None):
    """95% scans / 5% inserts THROUGH the cluster read/write gates;
    returns (ops, records, elapsed_s).

    Scans are coalesced into per-partition batches of up to
    `scan_batch` (PEGBENCH_SCAN_BATCH): the server evaluates each
    unique touched block ONCE per batch on the device — the request-
    batching dispatch model (SURVEY §2.6), which is what amortizes
    per-dispatch latency on a real accelerator."""
    import numpy as np

    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.server.types import GetScannerRequest

    if scan_batch is None:
        scan_batch = int(os.environ.get("PEGBENCH_SCAN_BATCH", 32))
    rng = np.random.default_rng(seed)
    client = bc.client
    # zipfian-ish partition popularity
    ranks = rng.permutation(n_partitions)
    weights = 1.0 / (1.0 + ranks.astype(float))
    weights /= weights.sum()
    # zipfian-ish start-key popularity within the loaded keyspace
    zipf_u = rng.random(n_ops) ** 2.0
    pidx_choices = rng.choice(n_partitions, size=n_ops, p=weights)
    insert_draw = rng.random(n_ops)
    # pre-drawn so the per-op stream is IDENTICAL whatever insert_frac
    # is: the warmup/pre-touch passes (insert_frac=0) must plan the
    # same scans as the measured pass or blocks go un-pre-touched
    scan_lens = rng.integers(1, record_goal + 1, size=n_ops)
    insert_hks = rng.integers(0, 1 << 30, size=n_ops)

    records = 0
    pending: dict = {}
    pending_n = 0

    def flush_pending():
        nonlocal records, pending_n
        if not pending:
            return
        results = client.scan_multi(dict(pending))
        for pidx, resps in results.items():
            for resp in resps:
                records += len(resp.kvs)
                if resp.context_id >= 0:  # defensive: one_page set
                    client._read("clear_scanner", resp.context_id, pidx)
        pending.clear()
        pending_n = 0

    t0 = time.perf_counter()
    for op in range(n_ops):
        if insert_draw[op] < insert_frac:
            flush_pending()  # writes serialize against in-flight scans
            hk = b"user%08d" % int(insert_hks[op])
            client.set(hk, b"s00", b"inserted")
            continue
        pidx = int(pidx_choices[op])
        start_hk = b"user%08d" % int(zipf_u[op] * n_hashkeys)
        scan_len = int(scan_lens[op])
        pending.setdefault(pidx, []).append(GetScannerRequest(
            start_key=generate_key(start_hk, b""),
            batch_size=scan_len,
            validate_partition_hash=True,
            one_page=True))
        pending_n += 1
        if pending_n >= scan_batch:
            flush_pending()
    flush_pending()
    elapsed = time.perf_counter() - t0
    return n_ops, records, elapsed


def _point_get_stream(n_ops, n_hashkeys, seed):
    """The YCSB-C op stream (zipfian-ish key popularity, BASELINE
    config #1) as (partition_hash, (hash_key, sort_key)) pairs — the
    ONE derivation every point-get flavor (solo, client-batched,
    server-side) measures against, so the cross-flavor ratios always
    compare identical workloads."""
    import numpy as np

    from pegasus_tpu.base.key_schema import key_hash_parts

    rng = np.random.default_rng(seed)
    zipf_u = rng.random(n_ops) ** 2.0
    sk_draw = rng.integers(0, 10, size=n_ops)
    return [(key_hash_parts(b"user%08d" % int(zipf_u[op] * n_hashkeys)),
             (b"user%08d" % int(zipf_u[op] * n_hashkeys),
              b"s%02d" % int(sk_draw[op])))
            for op in range(n_ops)]


def run_point_gets(bc, n_ops, n_hashkeys, seed):
    """YCSB-C: 100% single-request point gets through the cluster read
    gate (the round-5 baseline shape)."""
    stream = _point_get_stream(n_ops, n_hashkeys, seed)
    client = bc.client
    hits = 0
    t0 = time.perf_counter()
    for _ph, (hk, sk) in stream:
        err, _v = client.get(hk, sk)
        hits += err == 0
    return n_ops, hits, time.perf_counter() - t0


def run_point_gets_batched(bc, n_ops, n_hashkeys, seed, batch=32):
    """The same YCSB-C op stream coalesced through the cross-partition
    read coordinator (`batch` gets per flush, client.point_read_multi)
    — the request-batching dispatch model applied to point reads."""
    from pegasus_tpu.base.key_schema import generate_key

    stream = _point_get_stream(n_ops, n_hashkeys, seed)
    client = bc.client
    n_part = client.partition_count
    hits = 0
    pending: dict = {}
    pending_n = 0

    def flush():
        nonlocal hits, pending_n
        if not pending:
            return
        for _pidx, results in client.point_read_multi(
                dict(pending)).items():
            for err, _v in results:
                hits += err == 0
        pending.clear()
        pending_n = 0

    t0 = time.perf_counter()
    for ph, (hk, sk) in stream:
        pending.setdefault(ph % n_part, []).append(
            ("get", generate_key(hk, sk), ph))
        pending_n += 1
        if pending_n >= batch:
            flush()
    flush()
    return n_ops, hits, time.perf_counter() - t0


def run_point_gets_server_side(bc, n_ops, n_hashkeys, seed, batch=0):
    """Server-side only (no client/transport layer): batch=0 drives
    on_get per op — the round-5 single-request hot loop — batch=N
    drives coordinator flushes of N ops spread across partitions."""
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.server.read_coordinator import point_read_multi

    stream = [(ph % len(bc.servers), generate_key(hk, sk), ph)
              for ph, (hk, sk)
              in _point_get_stream(n_ops, n_hashkeys, seed)]
    servers = bc.servers
    hits = 0
    if batch <= 1:
        t0 = time.perf_counter()
        for pidx, key, ph in stream:
            err, _v = servers[pidx].on_get(key, partition_hash=ph)
            hits += err == 0
        return n_ops, hits, time.perf_counter() - t0
    t0 = time.perf_counter()
    for off in range(0, len(stream), batch):
        groups: dict = {}
        for pidx, key, ph in stream[off:off + batch]:
            groups.setdefault(pidx, []).append(("get", key, ph))
        for results in point_read_multi(
                [(servers[pidx], ops) for pidx, ops in groups.items()]):
            for err, _v in results:
                hits += err == 0
    return n_ops, hits, time.perf_counter() - t0


def _point_miss_stream(n_ops, n_hashkeys, seed):
    """Uniform LOADED hashkeys with half the sort-key space absent
    (s00-s09 loaded, s10-s19 never written) — the round-8 miss
    workload. Misses on existing hashkeys fall INSIDE every table's
    key fence (the realistic "existing user, missing field" shape), so
    only a membership structure can skip the block probes; uniform
    draws defeat the location cache (each key is effectively seen
    once)."""
    import numpy as np

    from pegasus_tpu.base.key_schema import key_hash_parts

    rng = np.random.default_rng(seed)
    hk_draw = rng.integers(0, n_hashkeys, size=n_ops)
    sk_draw = rng.integers(0, 20, size=n_ops)
    return [(key_hash_parts(b"user%08d" % int(hk_draw[op])),
             (b"user%08d" % int(hk_draw[op]),
              b"s%02d" % int(sk_draw[op])))
            for op in range(n_ops)]


def _point_hot_stream(n_ops, n_hashkeys, seed, hot_set=256, hot_frac=0.9):
    """Hotspot stream (YCSB-D-ish): `hot_frac` of ops over `hot_set`
    (hash, sort) pairs, the rest uniform — the shape the node row cache
    serves without entering the LSM."""
    import numpy as np

    from pegasus_tpu.base.key_schema import key_hash_parts

    rng = np.random.default_rng(seed)
    hot_hks = rng.integers(0, n_hashkeys, size=hot_set)
    hot_sks = rng.integers(0, 10, size=hot_set)
    pick = rng.integers(0, hot_set, size=n_ops)
    uni_hk = rng.integers(0, n_hashkeys, size=n_ops)
    uni_sk = rng.integers(0, 10, size=n_ops)
    hot_draw = rng.random(n_ops)
    out = []
    for op in range(n_ops):
        if hot_draw[op] < hot_frac:
            hk = b"user%08d" % int(hot_hks[pick[op]])
            sk = b"s%02d" % int(hot_sks[pick[op]])
        else:
            hk = b"user%08d" % int(uni_hk[op])
            sk = b"s%02d" % int(uni_sk[op])
        out.append((key_hash_parts(hk), (hk, sk)))
    return out


def deepen_l0(bc, n_hashkeys, seed, n_l0=4, rows_per_flush=500):
    """Round-8 store state: `n_l0` overlay flushes whose rows interleave
    across the loaded hashkey space (distinct sort keys, so the base
    dataset stays fully visible and identity gates are unaffected).
    Every L0 table's key fence then spans the whole probed range — each
    point get must consider every L0 table, exactly the deep-L0 shape
    the bloom layer answers with a bit probe instead of a block decode."""
    from pegasus_tpu.base.key_schema import generate_key, key_hash_parts
    from pegasus_tpu.replica.mutation import WriteOp
    from pegasus_tpu.rpc.codec import OP_PUT

    step = max(1, n_hashkeys // rows_per_flush)
    for g in range(n_l0):
        per_pidx: dict = {}
        for h in range(g, n_hashkeys, step):
            hk = b"user%08d" % h
            per_pidx.setdefault(
                key_hash_parts(hk) % len(bc.servers), []).append(
                WriteOp(OP_PUT,
                        (generate_key(hk, b"zz%02d" % g),
                         b"l0-%d" % g, 0)))
        for pidx, ops in per_pidx.items():
            bc.replicas[pidx].client_write(ops)
        bc.cluster.loop.run_until_idle()
        for s in bc.servers:
            s.flush()


def run_point_stream_server_side(bc, stream, batch=32):
    """Server-side batched point gets over a prebuilt (ph, (hk, sk))
    stream — the round-8 measurement loop, shared by the baseline and
    filtered passes so only the flag state differs."""
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.server.read_coordinator import point_read_multi

    resolved = [(ph % len(bc.servers), generate_key(hk, sk), ph)
                for ph, (hk, sk) in stream]
    servers = bc.servers
    hits = 0
    t0 = time.perf_counter()
    for off in range(0, len(resolved), batch):
        groups: dict = {}
        for pidx, key, ph in resolved[off:off + batch]:
            groups.setdefault(pidx, []).append(("get", key, ph))
        for results in point_read_multi(
                [(servers[pidx], ops) for pidx, ops in groups.items()]):
            for err, _v in results:
                hits += err == 0
    return len(resolved), hits, time.perf_counter() - t0


def collect_point_results(bc, stream, batch=32):
    """Per-op (err, value) tuples in stream order — the round-8
    byte-identity gate runs this once per flag mode and compares."""
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.server.read_coordinator import point_read_multi

    resolved = [(ph % len(bc.servers), generate_key(hk, sk), ph)
                for ph, (hk, sk) in stream]
    out = []
    for off in range(0, len(resolved), batch):
        groups: dict = {}
        order = []
        for pidx, key, ph in resolved[off:off + batch]:
            lst = groups.setdefault(pidx, [])
            order.append((pidx, len(lst)))
            lst.append(("get", key, ph))
        pidxs = list(groups)
        res = point_read_multi(
            [(bc.servers[p], groups[p]) for p in pidxs])
        by_pidx = dict(zip(pidxs, res))
        out.extend(tuple(by_pidx[p][i]) for p, i in order)
    return out


def _write_put_stream(n_ops, seed, tag=b"wb"):
    """Deterministic put stream over a dedicated keyspace (never
    collides with the loaded scan/get dataset): (partition_hash,
    (hash_key, sort_key), value) triples — the ONE derivation every
    write flavor (solo, client-batched, server-side) measures against."""
    import numpy as np

    from pegasus_tpu.base.key_schema import key_hash_parts

    rng = np.random.default_rng(seed)
    hk_draw = rng.integers(0, max(1, n_ops // 4), size=n_ops)
    out = []
    for op in range(n_ops):
        hk = tag + b"%08d" % int(hk_draw[op])
        sk = b"s%04d" % op
        out.append((key_hash_parts(hk, sk), (hk, sk),
                    b"wval-%06d" % op))
    return out


def run_puts(bc, n_ops, seed, tag=b"wb"):
    """Single-request puts through the full client write path (one
    client_write RPC + one 2PC round per op) — the write-side twin of
    run_point_gets."""
    stream = _write_put_stream(n_ops, seed, tag)
    client = bc.client
    errs = 0
    t0 = time.perf_counter()
    for _ph, (hk, sk), v in stream:
        errs += client.set(hk, sk, v) != 0
    return n_ops, errs, time.perf_counter() - t0


def run_puts_batched(bc, n_ops, seed, batch=32, tag=b"wb"):
    """The same put stream coalesced through write_multi (`batch` ops
    per flush): one client_write_batch RPC per node per flush, one
    mutation per touched partition, one group-commit window."""
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.rpc.codec import OP_PUT

    stream = _write_put_stream(n_ops, seed, tag)
    client = bc.client
    n_part = client.partition_count
    errs = 0
    pending: dict = {}
    pending_n = 0

    def flush():
        nonlocal errs, pending_n
        if not pending:
            return
        for _pidx, results in client.write_multi(dict(pending)).items():
            for err in results:
                errs += err != 0
        pending.clear()
        pending_n = 0

    t0 = time.perf_counter()
    for ph, (hk, sk), v in stream:
        pending.setdefault(ph % n_part, []).append(
            (OP_PUT, (generate_key(hk, sk), v, 0), ph))
        pending_n += 1
        if pending_n >= batch:
            flush()
    flush()
    return n_ops, errs, time.perf_counter() - t0


def run_puts_server_side(bc, n_ops, seed, batch=0, tag=b"wbs"):
    """Server-side only (no client/transport): batch=0 drives one
    replica.client_write (one mutation) per op; batch=N groups each
    window's ops per partition into ONE client_write — the mutation
    coalescing + vectorized-apply path in isolation."""
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.replica.mutation import WriteOp
    from pegasus_tpu.rpc.codec import OP_PUT

    stream = [(ph % len(bc.replicas), generate_key(hk, sk), v)
              for ph, (hk, sk), v in _write_put_stream(n_ops, seed, tag)]
    replicas = bc.replicas
    pump = bc.cluster.loop.run_until_idle
    window = next(iter(bc.cluster.stubs.values())).write_window
    if batch <= 1:
        t0 = time.perf_counter()
        for pidx, key, v in stream:
            replicas[pidx].client_write([WriteOp(OP_PUT, (key, v, 0))])
            pump()
        return n_ops, time.perf_counter() - t0
    t0 = time.perf_counter()
    for off in range(0, len(stream), batch):
        groups: dict = {}
        for pidx, key, v in stream[off:off + batch]:
            groups.setdefault(pidx, []).append(
                WriteOp(OP_PUT, (key, v, 0)))
        # one group-commit window per flush — exactly what a
        # client_write_batch dispatch opens on a serving node
        with window:
            for pidx, ops in groups.items():
                replicas[pidx].client_write(ops)
        pump()
    return n_ops, time.perf_counter() - t0


def verify_write_batch_identity(bc, seed, n=256) -> bool:
    """Acceptance gate: the batched write path must produce the same
    per-op results as the solo handler AND leave identical user-visible
    state — asserted over twin keyspaces carrying the same payloads
    (hits, overwrites, and deletes alike)."""
    from pegasus_tpu.base.key_schema import generate_key, key_hash_parts
    from pegasus_tpu.rpc.codec import OP_PUT, OP_REMOVE

    client = bc.client
    n_part = client.partition_count
    stream = _write_put_stream(n, seed, tag=b"id")
    solo_res = []
    for i, (_ph, (hk, sk), v) in enumerate(stream):
        solo_res.append(client.set(b"solo-" + hk, sk, v))
        if i % 5 == 0:  # overwrite mix
            solo_res.append(client.set(b"solo-" + hk, sk, v + b"!"))
        if i % 9 == 0:
            solo_res.append(client.delete(b"solo-" + hk, sk))
    groups: dict = {}
    order = []
    for i, (_ph, (hk, sk), v) in enumerate(stream):
        def add(op, hk=hk, sk=sk):
            ph = key_hash_parts(b"batch-" + hk, sk)
            pidx = ph % n_part
            lst = groups.setdefault(pidx, [])
            order.append((pidx, len(lst)))
            lst.append((op[0], op[1], ph))
        add((OP_PUT, (generate_key(b"batch-" + hk, sk), v, 0)))
        if i % 5 == 0:
            add((OP_PUT, (generate_key(b"batch-" + hk, sk), v + b"!", 0)))
        if i % 9 == 0:
            add((OP_REMOVE, (generate_key(b"batch-" + hk, sk),)))
    got = client.write_multi(groups)
    batch_res = [got[p][i] for p, i in order]
    if batch_res != solo_res:
        return False
    for _ph, (hk, sk), _v in stream:
        if client.get(b"solo-" + hk, sk) != client.get(b"batch-" + hk, sk):
            return False
    return True


def verify_point_batch_identity(bc, n_hashkeys, seed, n=512) -> bool:
    """Acceptance gate: batched results must be BYTE-identical to the
    single-request path over a sampled key set (hits, misses, and
    expired records alike)."""
    from pegasus_tpu.base.key_schema import generate_key

    stream = _point_get_stream(n, n_hashkeys, seed)
    client = bc.client
    n_part = client.partition_count
    groups: dict = {}
    expect: dict = {}
    for ph, (hk, sk) in stream:
        pidx = ph % n_part
        groups.setdefault(pidx, []).append(
            ("get", generate_key(hk, sk), ph))
        expect.setdefault(pidx, []).append(tuple(client.get(hk, sk)))
    got = client.point_read_multi(groups)
    return all(tuple(map(tuple, got[p])) == tuple(expect[p])
               for p in groups)


def measure_scan_phase(jax, device, bc, n_ops, n_partitions, n_hashkeys,
                      seed):
    """reset -> warmup (compile + device block caches) -> measure.

    A MaskPrefresher runs for the whole phase (as on a production node,
    node_main.py): the per-second mask refresh — the only device work in
    steady-state serving — happens in the background, so the measured
    path is the host assembly speed both backends share plus whatever
    device latency the prefresher FAILS to hide."""
    from pegasus_tpu.server.scan_coordinator import MaskPrefresher

    prefresher = MaskPrefresher(bc.servers, device=device).start()
    try:
        return _measure_scan_phase(jax, device, bc, n_ops, n_partitions,
                                   n_hashkeys, seed)
    finally:
        prefresher.stop()


def _measure_scan_phase(jax, device, bc, n_ops, n_partitions, n_hashkeys,
                        seed):
    with jax.default_device(device):
        bc.manual_compact_all(device=device)
        # warmup covers both compiled stack shapes AND the overlay path
        # (inserts) so the measured phase pays no first-touch compiles
        run_scans(bc, 120, n_partitions, n_hashkeys, seed, insert_frac=0)
        run_scans(bc, 60, n_partitions, n_hashkeys, seed + 1)
        bc.manual_compact_all(device=device)
        # steady-state pre-touch: the compact above rewrote the SSTs, so
        # without this pass the measured run pays one first-touch
        # host->device block upload per block — a load-time cost, not
        # scan throughput. Same seed + insert_frac=0 touches a superset
        # of the measured scans' blocks without mutating anything, so
        # BOTH phases measure with resident device block caches (on a
        # real chip: blocks already in HBM — the serving steady state).
        run_scans(bc, n_ops, n_partitions, n_hashkeys, seed,
                  insert_frac=0)
        # best-of-3: block masks are cached per wall-clock second (TTL
        # validity granularity), so a sub-second pass that happens to
        # straddle a second boundary recomputes part of its masks —
        # taking the best pass measures the steady state, not the luck
        # of the start instant, identically for both phases
        best = None
        for i in range(3):
            if i:
                # re-compact so every pass starts from the same server
                # state — pass 1's 5% inserts would otherwise push later
                # passes onto the overlay-merge path and 'best' would
                # just mean 'first'
                bc.manual_compact_all(device=device)
                run_scans(bc, n_ops, n_partitions, n_hashkeys, seed,
                          insert_frac=0)
            ops, recs, secs = run_scans(bc, n_ops, n_partitions,
                                        n_hashkeys, seed)
            if best is None or secs < best[2]:
                best = (ops, recs, secs)
        ops, recs, secs = best
    return ops, recs, secs


def data_bytes(bc) -> int:
    total = 0
    for srv in bc.servers:
        sst = os.path.join(srv.engine.data_dir, "sst")
        for name in os.listdir(sst):
            total += os.path.getsize(os.path.join(sst, name))
    return total


def _compact_rules_filter():
    """BASELINE config #4: hashkey-prefix delete + a sortkey-range
    delete (compaction_filter_rule.h:99,121,141) plus one
    MATCH_ANYWHERE hashkey pattern — the ruleset class whose per-byte
    matching work the accelerator's upload buys in one pass."""
    from pegasus_tpu.ops.compaction_rules import compile_rules

    return compile_rules([
        {"op": "delete_key",
         "rules": [{"type": "hashkey_pattern", "match": "prefix",
                    "pattern": "user000001"}]},
        {"op": "delete_key",
         "rules": [{"type": "hashkey_pattern", "match": "anywhere",
                    "pattern": "7777"},
                   {"type": "sortkey_pattern", "match": "prefix",
                    "pattern": "s0"}]},
    ])


def build_compact_store(data_dir: str, n_records: int,
                        expired_frac: float, n_parts: int, seed: int,
                        value_kind: str = "random"):
    """Build `n_parts` partition stores totalling n_records directly as
    columnar L1 runs — the bulk-load ingest shape (externally-built
    SSTs adopted whole, parity: bulk load OP_INGEST) — with
    `expired_frac` of records carrying expired TTLs (a TTL-retention
    sweep: the BASELINE config #3 workload at the scale where operators
    actually run manual compaction). Returns [StorageEngine]."""
    import numpy as np

    from pegasus_tpu.base.crc import crc64_batch
    from pegasus_tpu.base.value_schema import epoch_now
    from pegasus_tpu.storage.engine import StorageEngine
    from pegasus_tpu.storage.lsm import L1_RUN_CAPACITY
    from pegasus_tpu.storage.sstable import SSTableWriter

    VALUE = 100
    BLOCK = 4096  # archival-table block size: 4x fewer per-block
    # host round-trips through the rewrite than the serving default
    now = epoch_now()
    per_part = n_records // n_parts
    engines = []
    for part in range(n_parts):
        rng = np.random.default_rng(seed + part)
        pdir = os.path.join(data_dir, f"p{part}")
        sst = os.path.join(pdir, "sst")
        os.makedirs(sst, exist_ok=True)
        names = []
        seq = 0
        writer = None
        in_run = 0
        meta = {"last_flushed_decree": 1, "data_version": 1}
        base0 = part * per_part
        for base in range(0, per_part, BLOCK):
            n = min(BLOCK, per_part - base)
            idx = np.arange(base0 + base, base0 + base + n)
            hks = idx // 10
            sks = idx % 10
            keys = np.zeros((n, 32), dtype=np.uint8)
            keys[:, 1] = 12  # BE u16 hashkey length
            keys[:, 2:14] = np.frombuffer(
                b"".join(b"user%08d" % h for h in hks),
                dtype=np.uint8).reshape(n, 12)
            keys[:, 14:17] = np.frombuffer(
                b"".join(b"s%02d" % s for s in sks),
                dtype=np.uint8).reshape(n, 3)
            key_len = np.full(n, 17, dtype=np.int32)
            ets = np.where(rng.random(n) < expired_frac,
                           np.uint32(max(1, now - 100)),
                           np.uint32(0)).astype(np.uint32)
            flags = np.zeros(n, dtype=np.uint8)
            offs = np.arange(n + 1, dtype=np.uint32) * VALUE
            if value_kind == "templated":
                # realistic structured payloads (field names + bounded
                # enumerations + a short random tail) — the workload
                # class where value compression actually pays, vs the
                # incompressible uniform-random default
                tails = rng.integers(97, 123, size=(n, 24),
                                     dtype=np.uint8)

                def _tv(j, i):
                    head = (b"ts=1700000000|city=%03d|tier=%d|"
                            b"status=active|score=%02d|"
                            % (i % 997, i % 5, i % 100)) \
                        + tails[j].tobytes()
                    return head + b"." * (VALUE - len(head))

                heap = b"".join(_tv(j, int(i))
                                for j, i in enumerate(idx))
            else:
                heap = rng.integers(32, 126, size=n * VALUE,
                                    dtype=np.uint8).tobytes()
            hash_lo = (crc64_batch(keys, np.full(n, 12, dtype=np.int64),
                                   start=2)
                       & np.uint64(0xFFFFFFFF)).astype(np.uint32)
            if writer is None:
                writer = SSTableWriter(os.path.join(sst, f"l1-{seq}.sst"),
                                       meta=meta, async_io=True,
                                       block_capacity=BLOCK)
                seq += 1
            writer.add_block_columnar(keys, key_len, ets, hash_lo,
                                      flags, offs, heap)
            in_run += n
            if in_run >= L1_RUN_CAPACITY:
                writer.finish()
                names.append(os.path.basename(writer.path))
                writer = None
                in_run = 0
        if writer is not None:
            writer.finish()
            names.append(os.path.basename(writer.path))
        with open(os.path.join(sst, "MANIFEST.json"), "w") as f:
            json.dump({"seq": seq, "l1": names}, f)
        engines.append(StorageEngine(pdir))
    return engines


def _store_bytes(engines) -> int:
    total = 0
    for eng in engines:
        sst = os.path.join(eng.data_dir, "sst")
        for name in os.listdir(sst):
            if name.endswith(".sst"):
                total += os.path.getsize(os.path.join(sst, name))
    return total


def measure_compaction_scaled(jax, device, tmpdir, mode: str,
                              gb: float, expired_frac: float,
                              seed: int, n_parts: int = 8):
    """Manual compaction GB/s at BASELINE scale (config #3/#4).

    Builds a fresh deterministic table PER (mode, backend) — the
    measured pass must face identical drop work on both backends — then
    times ONE full bulk compaction of every partition on a thread pool
    (disk IO + native gathers overlap the device/XLA filter waves).
    Returns (input_gb_per_s, seconds, in_bytes, out_bytes)."""
    import shutil
    from concurrent.futures import ThreadPoolExecutor

    rules_filter = _compact_rules_filter() if mode == "rules" else None
    n_records = int(gb * 1e9 / 145)  # ~145 B/record on disk
    data_dir = os.path.join(tmpdir, f"compact-{mode}")
    if os.path.exists(data_dir):
        shutil.rmtree(data_dir)
    t0 = time.perf_counter()
    # n_parts + 1 IDENTICAL partitions: partition 0 is the untimed
    # compile/warm pass. Same record count -> the same chunk row-bucket
    # sequence -> every XLA program shape the timed partitions will use
    # compiles on this backend BEFORE the clock starts (a tiny warm
    # store missed the 256k-row bucket, so the first measured pass paid
    # device compiles inside the timing — observed as a consistent
    # first-slot deficit on identical backends).
    per_part = n_records // n_parts
    engines = build_compact_store(
        data_dir, per_part * (n_parts + 1),
        expired_frac if mode == "ttl" else 0.05, n_parts + 1, seed)
    _log(f"compact[{mode}] fixture: {per_part * n_parts} records + "
         f"1 warm partition built in {time.perf_counter() - t0:.1f}s")
    warm_engine = engines[0]
    engines = engines[1:]
    with jax.default_device(device):
        warm_engine.manual_compact(rules_filter=rules_filter)
    warm_engine.close()

    # settle the fixture's dirty pages before timing: the measured pass
    # must compete with its OWN writeback, not the builder's
    os.sync()

    size_before = _store_bytes(engines)
    with jax.default_device(device):
        t0 = time.perf_counter()

        def one(eng):
            with jax.default_device(device):
                eng.manual_compact(rules_filter=rules_filter)

        with ThreadPoolExecutor(max_workers=min(8, n_parts)) as ex:
            for f in [ex.submit(one, e) for e in engines]:
                f.result()
        secs = time.perf_counter() - t0
    size_after = _store_bytes(engines)
    for eng in engines:
        eng.close()
    shutil.rmtree(data_dir, ignore_errors=True)
    return size_before / max(secs, 1e-9) / 1e9, secs, size_before, \
        size_after


def _compact_sample_digest(engines, seed, per_part=3000):
    """Deterministic record-level digest of post-compaction contents:
    a bounded iterate() prefix plus scattered point gets per partition
    — the identity gate between the compressed and uncompressed
    same-run stores."""
    import hashlib
    import itertools

    import numpy as np

    from pegasus_tpu.base.key_schema import generate_key

    h = hashlib.sha256()
    rng = np.random.default_rng(seed)
    for eng in engines:
        for key, value, ets in itertools.islice(eng.iterate(),
                                                per_part):
            h.update(key)
            h.update(value)
            h.update(b"%d" % ets)
        for _ in range(64):
            k = generate_key(b"user%08d" % int(rng.integers(0, 1 << 24)),
                             b"s%02d" % int(rng.integers(0, 10)))
            h.update(repr(eng.get(k)).encode())
    return h.hexdigest()


def measure_compressed_compact(jax, device, tmpdir, gb: float,
                               expired_frac: float, seed: int,
                               n_parts: int = 8):
    """compact_compressed phase (round-11): the SAME logical dataset is
    built twice — block_codec=none and =dcz — and one full bulk
    compaction of every partition is timed on each. Reported per codec:
    wall seconds, on-disk input/output bytes, disk GB/s, and EFFECTIVE
    input GB/s (logical uncompressed bytes / seconds — the number that
    can pass the raw-disk ceiling when compressed output shrinks the
    write side). Identity-gated record-for-record between the two
    stores."""
    import shutil
    from concurrent.futures import ThreadPoolExecutor

    from pegasus_tpu.utils.flags import FLAGS

    n_records = int(gb * 1e9 / 145)  # ~145 B/record in the raw format
    per_part = n_records // n_parts
    out = {}
    logical_in = None
    old_codec = FLAGS.get("pegasus.storage", "block_codec")
    try:
        for codec in ("none", "dcz"):
            FLAGS.set("pegasus.storage", "block_codec", codec)
            data_dir = os.path.join(tmpdir, f"ccompact-{codec}")
            if os.path.exists(data_dir):
                shutil.rmtree(data_dir)
            t0 = time.perf_counter()
            engines = build_compact_store(
                data_dir, per_part * (n_parts + 1), expired_frac,
                n_parts + 1, seed, value_kind="templated")
            _log(f"compact_compressed[{codec}] fixture: "
                 f"{per_part * n_parts} records in "
                 f"{time.perf_counter() - t0:.1f}s")
            warm = engines[0]
            engines = engines[1:]
            with jax.default_device(device):
                warm.manual_compact()
            warm.close()
            os.sync()
            size_before = _store_bytes(engines)
            if codec == "none":
                logical_in = size_before
            with jax.default_device(device):
                t0 = time.perf_counter()

                def one(eng):
                    with jax.default_device(device):
                        eng.manual_compact()

                # pool sized to the machine, not the partition count:
                # dcz compaction is CPU-dense (GIL-released native
                # deflate), and 8 workers on a 2-core box thrash both
                # codecs while taxing dcz hardest (measured 0.83x vs
                # 1.04x at workers=cpu_count on the same fixture)
                with ThreadPoolExecutor(
                        max_workers=min(os.cpu_count() or 4,
                                        n_parts)) as ex:
                    for f in [ex.submit(one, e) for e in engines]:
                        f.result()
                secs = time.perf_counter() - t0
            size_after = _store_bytes(engines)
            ratios = [t.codec_stats for e in engines
                      for t in e.lsm.l1_runs if t.codec_stats]
            raw_b = sum(r["raw_bytes"] for r in ratios)
            stored_b = sum(r["stored_bytes"] for r in ratios)
            digest = _compact_sample_digest(engines, seed + 1)
            for eng in engines:
                eng.close()
            shutil.rmtree(data_dir, ignore_errors=True)
            out[codec] = {
                "seconds": round(secs, 3),
                "in_bytes": size_before,
                "out_bytes": size_after,
                "disk_gb_per_s": round(size_before / secs / 1e9, 4),
                "effective_input_gb_per_s": round(
                    (logical_in or size_before) / secs / 1e9, 4),
                "output_compression_ratio": (
                    round(stored_b / raw_b, 4) if raw_b else None),
                "sample_digest": digest,
            }
            _log(f"compact_compressed[{codec}]: {secs:.1f}s, "
                 f"disk {out[codec]['disk_gb_per_s']:.3f} GB/s, "
                 f"effective {out[codec]['effective_input_gb_per_s']:.3f}"
                 f" GB/s")
    finally:
        FLAGS.set("pegasus.storage", "block_codec", old_codec)
    out["identity_ok"] = (out["none"]["sample_digest"]
                          == out["dcz"]["sample_digest"])
    out["effective_speedup"] = round(
        out["dcz"]["effective_input_gb_per_s"]
        / max(out["none"]["effective_input_gb_per_s"], 1e-9), 3)
    return out


def measure_pipelined_compact(jax, device, tmpdir, gb: float,
                              expired_frac: float, seed: int,
                              n_parts: int = 8):
    """compact_pipelined phase (round-12): the SAME logical dataset is
    built twice and one full bulk compaction of every partition is
    timed with the staged pipeline OFF (serial windowed path) and ON
    (read/filter/write threads + bounded queues). Identity-gated
    record-for-record; also records the placement cost model's
    offload-pays verdict for the phase's filter batches."""
    import shutil

    from pegasus_tpu.ops.placement import offload_breakdown
    from pegasus_tpu.storage.compact_pipeline import pipeline_window
    from pegasus_tpu.utils.flags import FLAGS

    n_records = int(gb * 1e9 / 145)
    per_part = n_records // n_parts
    out = {}
    old = FLAGS.get("pegasus.storage", "compact_pipeline")
    try:
        for mode in ("serial", "pipelined"):
            FLAGS.set("pegasus.storage", "compact_pipeline",
                      mode == "pipelined")
            data_dir = os.path.join(tmpdir, f"pcompact-{mode}")
            if os.path.exists(data_dir):
                shutil.rmtree(data_dir)
            t0 = time.perf_counter()
            engines = build_compact_store(
                data_dir, per_part * (n_parts + 1), expired_frac,
                n_parts + 1, seed, value_kind="templated")
            _log(f"compact_pipelined[{mode}] fixture: "
                 f"{per_part * n_parts} records in "
                 f"{time.perf_counter() - t0:.1f}s")
            warm = engines[0]
            engines = engines[1:]
            with jax.default_device(device):
                warm.manual_compact()
            warm.close()
            os.sync()
            size_before = _store_bytes(engines)
            with jax.default_device(device):
                t0 = time.perf_counter()
                # ONE compaction at a time — the cluster scheduler's
                # staggered shape (the coordinator grants one node's
                # heavy compaction at a time, and intra-compaction
                # overlap is exactly what this phase isolates; the
                # pool-parallel shape is compact_compressed's)
                for eng in engines:
                    eng.manual_compact()
                secs = time.perf_counter() - t0
            size_after = _store_bytes(engines)
            digest = _compact_sample_digest(engines, seed + 1)
            for eng in engines:
                eng.close()
            shutil.rmtree(data_dir, ignore_errors=True)
            out[mode] = {
                "seconds": round(secs, 3),
                "in_bytes": size_before,
                "out_bytes": size_after,
                "input_gb_per_s": round(size_before / secs / 1e9, 4),
                "sample_digest": digest,
            }
            _log(f"compact_pipelined[{mode}]: {secs:.1f}s, "
                 f"{out[mode]['input_gb_per_s']:.3f} GB/s input")
    finally:
        FLAGS.set("pegasus.storage", "compact_pipeline", old)
    out["identity_ok"] = (out["serial"]["sample_digest"]
                          == out["pipelined"]["sample_digest"])
    out["speedup"] = round(out["pipelined"]["input_gb_per_s"]
                           / max(out["serial"]["input_gb_per_s"],
                                 1e-9), 3)
    # offload-pays breakdown for this phase's filter batches: one
    # pipeline window of ~145B records (the TTL workload class) and
    # the rules class at the same size — PERF round-12's table
    window_bytes = pipeline_window() * 4096 * 36  # keys+cols/record
    out["offload_breakdown"] = {
        w: offload_breakdown(w, window_bytes) for w in ("ttl", "rules")}
    return out


def measure_trace_overhead(tmpdir, seed: int):
    """Distributed-tracing overhead phase: the SAME batched point-get
    and write_multi streams through a SimCluster at sample_ratio
    0 / 0.01 / 1.0, against a hard-disabled no-tracing baseline —
    same-run, identity-gated (per-mode result digests must match).
    The acceptance gate: sample_ratio=0 within 2% of the no-tracing
    baseline on both the read and the write phase (median of 3 reps)."""
    import hashlib
    import shutil

    import numpy as np

    from pegasus_tpu.base.key_schema import generate_key, key_hash_parts
    from pegasus_tpu.base.value_schema import expire_ts_from_ttl
    from pegasus_tpu.rpc.codec import OP_PUT
    from pegasus_tpu.tools.cluster import SimCluster
    from pegasus_tpu.utils import tracing
    from pegasus_tpu.utils.flags import FLAGS

    n_keys = int(os.environ.get("PEGBENCH_TRACE_KEYS", 512))
    n_rounds = int(os.environ.get("PEGBENCH_TRACE_ROUNDS", 40))
    reps = 3
    batch = 32
    cdir = os.path.join(tmpdir, "trace_overhead")
    cluster = SimCluster(cdir, n_nodes=3, seed=seed)
    try:
        cluster.create_table("tr", partition_count=4, replica_count=3)
        client = cluster.client("tr")
        keys = [(b"tk%05d" % i, b"s") for i in range(n_keys)]
        # preload so the read stream hits resident data
        rng = np.random.default_rng(seed)
        for start in range(0, n_keys, batch):
            groups = {}
            for hk, sk in keys[start:start + batch]:
                ph = key_hash_parts(hk, sk)
                groups.setdefault(ph % 4, []).append(
                    (OP_PUT, (generate_key(hk, sk), b"v" * 64,
                              expire_ts_from_ttl(0)), ph))
            client.write_multi(groups)

        # ONE fixed op order for every pass: after the warm-up pass the
        # store sits at this order's write fixed point, so every later
        # pass reads IDENTICAL state whatever mode ran before it — the
        # per-mode digests must match exactly
        order = np.random.default_rng(seed + 1).integers(
            0, n_keys, size=n_rounds * batch)

        def one_pass(digest):
            t0 = time.perf_counter()
            for r in range(n_rounds):
                groups = {}
                for j in order[r * batch:(r + 1) * batch]:
                    hk, sk = keys[int(j)]
                    ph = key_hash_parts(hk, sk)
                    groups.setdefault(ph % 4, []).append(
                        ("get", generate_key(hk, sk), ph))
                res = client.point_read_multi(groups)
                for pidx in sorted(res):
                    for st, val in res[pidx]:
                        digest.update(b"%d" % st)
                        digest.update(val)
            t_read = time.perf_counter() - t0
            t0 = time.perf_counter()
            for r in range(n_rounds):
                groups = {}
                for j in order[r * batch:(r + 1) * batch]:
                    hk, sk = keys[int(j)]
                    ph = key_hash_parts(hk, sk)
                    groups.setdefault(ph % 4, []).append(
                        (OP_PUT, (generate_key(hk, sk),
                                  b"w%d" % r, expire_ts_from_ttl(0)),
                         ph))
                res = client.write_multi(groups)
                for pidx in sorted(res):
                    for st in res[pidx]:
                        digest.update(b"%d" % st)
            t_write = time.perf_counter() - t0
            return t_read, t_write

        # one unmeasured warm-up pass: absorbs cold caches AND drives
        # the store to the order's write fixed point, so every measured
        # pass reads identical state
        tracing.hard_disable(True)
        one_pass(hashlib.sha256())
        modes = [("baseline_off", None), ("ratio_0", 0.0),
                 ("ratio_0.01", 0.01), ("ratio_1", 1.0)]
        out = {"keys": n_keys,
               "ops_per_mode": n_rounds * batch * 2 * reps}
        ops_n = n_rounds * batch
        digests = {}
        times = {name: ([], []) for name, _r in modes}
        hashes = {name: hashlib.sha256() for name, _r in modes}
        # modes INTERLEAVE across reps: slow drift (allocator state,
        # page cache, cpu clocks) hits every mode equally instead of
        # biasing whichever mode ran last
        for _rep in range(reps):
            for name, ratio in modes:
                tracing.hard_disable(ratio is None)
                FLAGS.set("pegasus.tracing", "sample_ratio",
                          ratio or 0.0)
                tr, tw = one_pass(hashes[name])
                times[name][0].append(tr)
                times[name][1].append(tw)
        for name, _ratio in modes:
            reads, writes = times[name]
            digests[name] = hashes[name].hexdigest()
            out[name] = {
                "read_qps": round(ops_n * reps / sum(reads), 1),
                "write_qps": round(ops_n * reps / sum(writes), 1),
                "read_s_median": round(sorted(reads)[1], 4),
                "write_s_median": round(sorted(writes)[1], 4),
            }
        tracing.hard_disable(False)
        FLAGS.set("pegasus.tracing", "sample_ratio", 0.0)
        base = out["baseline_off"]
        r0 = out["ratio_0"]
        out["ratio0_read_overhead"] = round(
            r0["read_s_median"] / base["read_s_median"] - 1.0, 4)
        out["ratio0_write_overhead"] = round(
            r0["write_s_median"] / base["write_s_median"] - 1.0, 4)
        out["identity_ok"] = len(set(digests.values())) == 1
        # the bench gate: ratio-0 tracing must cost <=2% on both phases
        out["gate_ok"] = bool(
            out["identity_ok"]
            and out["ratio0_read_overhead"] <= 0.02
            and out["ratio0_write_overhead"] <= 0.02)
        return out
    finally:
        tracing.hard_disable(False)
        FLAGS.set("pegasus.tracing", "sample_ratio", 0.0)
        cluster.close()
        shutil.rmtree(cdir, ignore_errors=True)


def measure_health_overhead(tmpdir, seed: int):
    """Flight-recorder overhead phase (round 16): the SAME batched
    point-get and write_multi streams through a SimCluster with the
    recorder + health rules OFF vs ON at the default cadence —
    same-run, identity-gated (per-mode result digests must match).
    The acceptance gate: recorder-on within 2% of recorder-off on both
    the read and the write phase (median of 3 reps); the ring-memory
    byte cost is recorded alongside."""
    import hashlib
    import shutil

    import numpy as np

    from pegasus_tpu.base.key_schema import generate_key, key_hash_parts
    from pegasus_tpu.base.value_schema import expire_ts_from_ttl
    from pegasus_tpu.rpc.codec import OP_PUT
    from pegasus_tpu.tools.cluster import SimCluster
    from pegasus_tpu.utils.flags import FLAGS

    n_keys = int(os.environ.get("PEGBENCH_HEALTH_KEYS", 512))
    n_rounds = int(os.environ.get("PEGBENCH_HEALTH_ROUNDS", 40))
    reps = 3
    batch = 32
    cdir = os.path.join(tmpdir, "health_overhead")
    cluster = SimCluster(cdir, n_nodes=3, seed=seed)
    try:
        cluster.create_table("ho", partition_count=4, replica_count=3)
        client = cluster.client("ho")
        keys = [(b"hk%05d" % i, b"s") for i in range(n_keys)]
        for start in range(0, n_keys, batch):
            groups = {}
            for hk, sk in keys[start:start + batch]:
                ph = key_hash_parts(hk, sk)
                groups.setdefault(ph % 4, []).append(
                    (OP_PUT, (generate_key(hk, sk), b"v" * 64,
                              expire_ts_from_ttl(0)), ph))
            client.write_multi(groups)

        # ONE fixed op order for every pass (see measure_trace_overhead:
        # the warm-up drives the store to this order's write fixed
        # point, so every measured pass reads identical state)
        order = np.random.default_rng(seed + 1).integers(
            0, n_keys, size=n_rounds * batch)

        def one_pass(digest):
            # the timer round fires on the SAME fixed schedule in both
            # modes (every 8 op rounds); sim time compresses ~1000x, so
            # this schedule ticks the recorder FAR above its deployed
            # cadence — the A/B bounds the always-on hook cost, and the
            # per-tick cost is measured separately below and normalized
            # to the default cadence
            t0 = time.perf_counter()
            for r in range(n_rounds):
                groups = {}
                for j in order[r * batch:(r + 1) * batch]:
                    hk, sk = keys[int(j)]
                    ph = key_hash_parts(hk, sk)
                    groups.setdefault(ph % 4, []).append(
                        ("get", generate_key(hk, sk), ph))
                res = client.point_read_multi(groups)
                for pidx in sorted(res):
                    for st, val in res[pidx]:
                        digest.update(b"%d" % st)
                        digest.update(val)
                if r % 8 == 7:
                    cluster.step()
            t_read = time.perf_counter() - t0
            t0 = time.perf_counter()
            for r in range(n_rounds):
                groups = {}
                for j in order[r * batch:(r + 1) * batch]:
                    hk, sk = keys[int(j)]
                    ph = key_hash_parts(hk, sk)
                    groups.setdefault(ph % 4, []).append(
                        (OP_PUT, (generate_key(hk, sk),
                                  b"w%d" % r, expire_ts_from_ttl(0)),
                         ph))
                res = client.write_multi(groups)
                for pidx in sorted(res):
                    for st in res[pidx]:
                        digest.update(b"%d" % st)
                if r % 8 == 7:
                    cluster.step()
            t_write = time.perf_counter() - t0
            return t_read, t_write

        FLAGS.set("pegasus.health", "recorder_enabled", False)
        one_pass(hashlib.sha256())  # unmeasured warm-up
        modes = [("recorder_off", False), ("recorder_on", True)]
        out = {"keys": n_keys,
               "ops_per_mode": n_rounds * batch * 2 * reps}
        ops_n = n_rounds * batch
        times = {name: ([], []) for name, _e in modes}
        hashes = {name: hashlib.sha256() for name, _e in modes}
        # modes interleave across reps so slow drift hits both equally
        for _rep in range(reps):
            for name, enabled in modes:
                FLAGS.set("pegasus.health", "recorder_enabled", enabled)
                tr, tw = one_pass(hashes[name])
                times[name][0].append(tr)
                times[name][1].append(tw)
        digests = {}
        for name, _e in modes:
            reads, writes = times[name]
            digests[name] = hashes[name].hexdigest()
            out[name] = {
                "read_qps": round(ops_n * reps / sum(reads), 1),
                "write_qps": round(ops_n * reps / sum(writes), 1),
                "read_s_median": round(sorted(reads)[1], 4),
                "write_s_median": round(sorted(writes)[1], 4),
            }
        FLAGS.set("pegasus.health", "recorder_enabled", True)
        base, on = out["recorder_off"], out["recorder_on"]
        out["read_overhead"] = round(
            on["read_s_median"] / base["read_s_median"] - 1.0, 4)
        out["write_overhead"] = round(
            on["write_s_median"] / base["write_s_median"] - 1.0, 4)
        out["identity_ok"] = len(set(digests.values())) == 1
        # per-tick cost, normalized to the DEFAULT cadence: in a real
        # deployment the recorder fires once per interval of WALL time,
        # so its steady-state cost fraction is tick_seconds / interval
        # (the sim A/B above over-ticks by the time-compression factor)
        interval = FLAGS.get("pegasus.health", "recorder_interval_s")
        n_ticks = 30
        tick_s_total = 0.0
        for t in range(n_ticks):
            # touch the store between ticks so the timed tick pays the
            # LOADED cost — percentile windows re-sort, counters append
            # — not the idle fast path (version caches + zero slides)
            groups = {}
            for j in order[(t * 16) % (n_keys - 16):][:16]:
                hk, sk = keys[int(j)]
                ph = key_hash_parts(hk, sk)
                groups.setdefault(ph % 4, []).append(
                    (OP_PUT, (generate_key(hk, sk), b"t%d" % t,
                              expire_ts_from_ttl(0)), ph))
            client.write_multi(groups)
            cluster.loop.run_for(interval)  # advance sim time only
            t0 = time.perf_counter()
            for stub in cluster.stubs.values():
                stub.recorder.tick(force=True)
                stub.health.evaluate()
            tick_s_total += time.perf_counter() - t0
        tick_s = tick_s_total / n_ticks / len(cluster.stubs)
        out["tick_ms"] = round(tick_s * 1000.0, 3)
        out["cadence_overhead"] = round(tick_s / interval, 4)
        # the ring-memory cost of the on-mode rings, per node
        out["ring_bytes"] = {
            name: stub.recorder.nbytes()
            for name, stub in sorted(cluster.stubs.items())}
        out["ring_bytes_total"] = sum(out["ring_bytes"].values())
        out["events_fired"] = sum(
            stub.health.events_total
            for stub in cluster.stubs.values())
        # the bench gate: at the DEFAULT cadence the recorder+rules
        # tick must cost <=2% of a core — cadence_overhead is exactly
        # that fraction; the same-run A/B above is reported for the
        # record but over-ticks by the sim's time-compression factor
        # (~1000x the deployed cadence), so its raw ratio re-measures
        # tick cost at an unrealistic rate and does not gate. Results
        # must be identical and a steady healthy run must fire zero
        # events.
        out["gate_ok"] = bool(
            out["identity_ok"]
            and out["cadence_overhead"] <= 0.02
            and out["events_fired"] == 0)
        return out
    finally:
        FLAGS.set("pegasus.health", "recorder_enabled", True)
        cluster.close()
        shutil.rmtree(cdir, ignore_errors=True)


def measure_perfctx_overhead(tmpdir, seed: int):
    """PerfContext overhead phase (round 18): the SAME batched
    point-get and ranged multi_get streams through a SimCluster with
    per-op cost-vector collection hard-OFF vs ON — same-run,
    identity-gated (per-mode result digests must match). The gate:
    contexts-enabled read AND scan paths within 2% of hard-off (median
    of 3 reps, modes interleaved), per the trace_overhead /
    health_overhead convention."""
    import hashlib
    import shutil

    import numpy as np

    from pegasus_tpu.base.key_schema import generate_key, key_hash_parts
    from pegasus_tpu.base.value_schema import expire_ts_from_ttl
    from pegasus_tpu.rpc.codec import OP_PUT
    from pegasus_tpu.tools.cluster import SimCluster
    from pegasus_tpu.utils.flags import FLAGS

    n_hks = int(os.environ.get("PEGBENCH_PERFCTX_KEYS", 256))
    n_sks = 8  # sort keys per hashkey: the ranged leg reads real pages
    # enough rounds that each leg's median is hundreds of ms — 30-round
    # legs measured ~16 ms and the A/B was pure scheduler noise (±5%)
    n_rounds = int(os.environ.get("PEGBENCH_PERFCTX_ROUNDS", 240))
    reps = 3
    batch = 32
    cdir = os.path.join(tmpdir, "perfctx_overhead")
    cluster = SimCluster(cdir, n_nodes=3, seed=seed)
    try:
        cluster.create_table("pc", partition_count=4, replica_count=3)
        client = cluster.client("pc")
        hks = [b"phk%05d" % i for i in range(n_hks)]
        for start in range(0, n_hks, batch):
            groups = {}
            for hk in hks[start:start + batch]:
                ph = key_hash_parts(hk, b"")
                for j in range(n_sks):
                    groups.setdefault(ph % 4, []).append(
                        (OP_PUT, (generate_key(hk, b"s%02d" % j),
                                  b"v" * 64, expire_ts_from_ttl(0)),
                         ph))
            client.write_multi(groups)
        # compact so the ranged leg rides the columnar scan path (the
        # instrumented mask/kernel pipeline, not the overlay merge)
        for stub in cluster.stubs.values():
            for r in stub.replicas.values():
                r.server.engine.flush()
                r.server.engine.manual_compact()

        # ONE fixed op order for every pass (write fixed point: the
        # data is read-only here, so every pass reads identical state)
        order = np.random.default_rng(seed + 1).integers(
            0, n_hks, size=n_rounds * batch)

        def one_pass(digest):
            t0 = time.perf_counter()
            for r in range(n_rounds):
                groups = {}
                for j in order[r * batch:(r + 1) * batch]:
                    hk = hks[int(j)]
                    ph = key_hash_parts(hk, b"")
                    groups.setdefault(ph % 4, []).append(
                        ("get", generate_key(hk, b"s00"), ph))
                res = client.point_read_multi(groups)
                for pidx in sorted(res):
                    for st, val in res[pidx]:
                        digest.update(b"%d" % st)
                        digest.update(val)
            t_read = time.perf_counter() - t0
            t0 = time.perf_counter()
            for r in range(n_rounds):
                for j in order[r * batch:(r + 1) * batch:4]:
                    hk = hks[int(j)]
                    err, kvs = client.multi_get(hk)
                    digest.update(b"%d%d" % (err, len(kvs)))
                    for sk in sorted(kvs):
                        digest.update(sk)
                        digest.update(kvs[sk])
            t_scan = time.perf_counter() - t0
            return t_read, t_scan

        FLAGS.set("pegasus.perfctx", "enabled", False)
        one_pass(hashlib.sha256())  # unmeasured warm-up
        modes = [("perfctx_off", False), ("perfctx_on", True)]
        ops_read = n_rounds * batch
        ops_scan = n_rounds * (batch // 4)
        out = {"hashkeys": n_hks, "sortkeys_per_hk": n_sks,
               "ops_per_mode": (ops_read + ops_scan) * reps}
        times = {name: ([], []) for name, _e in modes}
        hashes = {name: hashlib.sha256() for name, _e in modes}
        # modes interleave across reps so slow drift hits both equally
        for _rep in range(reps):
            for name, enabled in modes:
                FLAGS.set("pegasus.perfctx", "enabled", enabled)
                tr, ts = one_pass(hashes[name])
                times[name][0].append(tr)
                times[name][1].append(ts)
        digests = {}
        for name, _e in modes:
            reads, scans = times[name]
            digests[name] = hashes[name].hexdigest()
            out[name] = {
                "read_qps": round(ops_read * reps / sum(reads), 1),
                "scan_qps": round(ops_scan * reps / sum(scans), 1),
                "read_s_median": round(sorted(reads)[1], 4),
                "scan_s_median": round(sorted(scans)[1], 4),
            }
        base, on = out["perfctx_off"], out["perfctx_on"]
        out["read_overhead"] = round(
            on["read_s_median"] / base["read_s_median"] - 1.0, 4)
        out["scan_overhead"] = round(
            on["scan_s_median"] / base["scan_s_median"] - 1.0, 4)
        out["identity_ok"] = len(set(digests.values())) == 1
        out["gate_ok"] = bool(
            out["identity_ok"]
            and out["read_overhead"] <= 0.02
            and out["scan_overhead"] <= 0.02)
        return out
    finally:
        FLAGS.set("pegasus.perfctx", "enabled", True)
        cluster.close()
        shutil.rmtree(cdir, ignore_errors=True)


def measure_qos_isolation(tmpdir, seed: int):
    """Multi-tenant QoS phase (round 20), two same-run A/Bs.

    Admission overhead: ONE tenant runs the batched point-get and
    ranged multi_get streams over compacted read-only state with
    budget enforcement hard-OFF vs ON. The tenant's configured budget
    sits far above the workload, so the ON mode pays the real
    per-request resolve + bucket checks without ever gating —
    identity-gated, modes interleaved, median of 3 reps; the gate: ON
    within 2% of OFF on both legs (the perfctx convention; reads and
    scans are the shed-eligible admission classes — writes are
    shed-exempt and their funnel is exercised by the isolation arm
    below). Tenant classification and CU charging run in BOTH modes
    (unconditional data-plane accounting); the A/B isolates what the
    enforce flag adds.

    Isolation: a compliant tenant's batched point-get rounds, timed
    per round, with an abusive tenant absent vs flooding oversized
    writes into a tiny CU budget before every round. Per-tenant
    budgets (not client courtesy) are the mechanism: the gates are
    that the compliant tenant's result digest is IDENTICAL in both
    modes, the abuser actually went over budget, and the compliant
    per-round p99 stays within the gated bound (<=1.5x its solo p99).
    """
    import hashlib
    import shutil

    import numpy as np

    from pegasus_tpu.base.key_schema import generate_key, key_hash_parts
    from pegasus_tpu.base.value_schema import expire_ts_from_ttl
    from pegasus_tpu.rpc.codec import OP_PUT
    from pegasus_tpu.server.tenancy import TENANTS
    from pegasus_tpu.tools.cluster import SimCluster
    from pegasus_tpu.utils.flags import FLAGS

    n_keys = int(os.environ.get("PEGBENCH_QOS_KEYS", 512))
    n_rounds = int(os.environ.get("PEGBENCH_QOS_ROUNDS", 240))
    iso_rounds = int(os.environ.get("PEGBENCH_QOS_ISO_ROUNDS", 160))
    reps = 3
    batch = 32
    out = {}

    def pct(xs, q):
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(len(xs) * q))]

    # ---- A/B 1: single-tenant admission-path overhead ---------------
    cdir = os.path.join(tmpdir, "qos_admission")
    cluster = SimCluster(cdir, n_nodes=3, seed=seed)
    try:
        cluster.create_table(
            "qa", partition_count=4, replica_count=3,
            envs={"qos.tenants": "bench:8:100000000",
                  "qos.default_tenant": "bench"})
        client = cluster.client("qa")  # adopts qos.default_tenant
        n_sks = 4  # sort keys per hashkey: the ranged leg reads pages
        hks = [b"qak%05d" % i for i in range(n_keys)]
        for start in range(0, n_keys, batch):
            groups = {}
            for hk in hks[start:start + batch]:
                ph = key_hash_parts(hk, b"")
                for j in range(n_sks):
                    groups.setdefault(ph % 4, []).append(
                        (OP_PUT, (generate_key(hk, b"s%02d" % j),
                                  b"v" * 64, expire_ts_from_ttl(0)),
                         ph))
            client.write_multi(groups)
        # compact so every measured pass reads the SAME frozen state —
        # a mutating leg would make the A/B measure store drift, not
        # admission cost
        for stub in cluster.stubs.values():
            for r in stub.replicas.values():
                r.server.engine.flush()
                r.server.engine.manual_compact()

        order = np.random.default_rng(seed + 1).integers(
            0, n_keys, size=n_rounds * batch)

        def one_pass(digest):
            t0 = time.perf_counter()
            for r in range(n_rounds):
                groups = {}
                for j in order[r * batch:(r + 1) * batch]:
                    hk = hks[int(j)]
                    ph = key_hash_parts(hk, b"")
                    groups.setdefault(ph % 4, []).append(
                        ("get", generate_key(hk, b"s00"), ph))
                res = client.point_read_multi(groups)
                for pidx in sorted(res):
                    for st, val in res[pidx]:
                        digest.update(b"%d" % st)
                        digest.update(val)
            t_read = time.perf_counter() - t0
            t0 = time.perf_counter()
            for r in range(n_rounds):
                for j in order[r * batch:(r + 1) * batch:4]:
                    hk = hks[int(j)]
                    err, kvs = client.multi_get(hk)
                    digest.update(b"%d%d" % (err, len(kvs)))
                    for sk in sorted(kvs):
                        digest.update(sk)
                        digest.update(kvs[sk])
            t_scan = time.perf_counter() - t0
            return t_read, t_scan

        FLAGS.set("pegasus.qos", "tenant_enforce", False)
        one_pass(hashlib.sha256())  # unmeasured warm-up
        modes = [("enforce_off", False), ("enforce_on", True)]
        # min-of-reps needs several shots per mode to land on a quiet
        # slice of a loaded box (observed pass spread up to ±40% wall)
        admit_reps = int(os.environ.get("PEGBENCH_QOS_REPS", 7))
        ops_n = n_rounds * batch
        out["hashkeys"] = n_keys
        out["admission_ops_per_mode"] = (ops_n + ops_n // 4) * admit_reps
        times = {name: ([], []) for name, _e in modes}
        hashes = {name: hashlib.sha256() for name, _e in modes}
        # modes interleave across reps AND alternate order per rep:
        # whatever warms within a rep (page cache, allocator) benefits
        # the second slot, so a fixed order would bias one mode
        for rep in range(admit_reps):
            for name, enabled in (modes if rep % 2 == 0
                                  else modes[::-1]):
                FLAGS.set("pegasus.qos", "tenant_enforce", enabled)
                tr, ts = one_pass(hashes[name])
                times[name][0].append(tr)
                times[name][1].append(ts)
        digests = {}
        for name, _e in modes:
            reads, scans = times[name]
            digests[name] = hashes[name].hexdigest()
            out[name] = {
                "read_s_median": round(sorted(reads)[admit_reps // 2],
                                       4),
                "scan_s_median": round(sorted(scans)[admit_reps // 2],
                                       4),
                "read_s_min": round(min(reads), 4),
                "scan_s_min": round(min(scans), 4),
            }
        # the overhead estimator is the per-mode MIN over reps (timeit
        # discipline): the pass replays deterministically, so host
        # scheduler/GC noise is strictly additive and the fastest pass
        # sits closest to the true path cost — per-pass wall noise on
        # a loaded box (±5-10%) would drown a 2% gate computed from
        # medians; the medians ride along for the record
        base, on = out["enforce_off"], out["enforce_on"]
        out["admission_read_overhead"] = round(
            on["read_s_min"] / base["read_s_min"] - 1.0, 4)
        out["admission_scan_overhead"] = round(
            on["scan_s_min"] / base["scan_s_min"] - 1.0, 4)
        out["admission_identity_ok"] = len(set(digests.values())) == 1
    finally:
        FLAGS.set("pegasus.qos", "tenant_enforce", True)
        cluster.close()
        shutil.rmtree(cdir, ignore_errors=True)
        TENANTS.reset()  # process singleton: drop the sim-pinned clock

    # ---- A/B 2: abuser on/off isolation -----------------------------
    cdir = os.path.join(tmpdir, "qos_isolation")
    cluster = SimCluster(cdir, n_nodes=3, seed=seed + 9)
    try:
        # weight 8:1 and a ~200 CU/s abuser budget vs 16KB (5 CU)
        # writes: the abuser outruns its refill every round and lives
        # in jittered-backoff retry, the compliant tenant never gates
        cluster.create_table(
            "qi", partition_count=4, replica_count=3,
            envs={"qos.tenants": "abuser:1:200,compliant:8:100000000",
                  "qos.default_tenant": "compliant"})
        compliant = cluster.client("qi", name="bench-qi-compliant",
                                   tenant="compliant")
        abuser = cluster.client("qi", name="bench-qi-abuser",
                                tenant="abuser")
        keys = [(b"qik%05d" % i, b"s") for i in range(n_keys)]
        for start in range(0, n_keys, batch):
            groups = {}
            for hk, sk in keys[start:start + batch]:
                ph = key_hash_parts(hk, sk)
                groups.setdefault(ph % 4, []).append(
                    (OP_PUT, (generate_key(hk, sk), b"v" * 64,
                              expire_ts_from_ttl(0)), ph))
            compliant.write_multi(groups)

        order = np.random.default_rng(seed + 2).integers(
            0, n_keys, size=iso_rounds * batch)
        big = b"A" * 16384  # ~5 CU per write against the 200 CU/s budget

        def iso_pass(with_abuser, digest, round_times):
            # untimed priming round: the inter-pass run_until_idle
            # leaves due periodic work (health ticks, lease renewals)
            # for the next request to pump, and with a few hundred
            # samples the p99 is the top handful of rounds — one
            # scheduling artifact must not own it
            groups = {}
            for j in order[:batch]:
                hk, sk = keys[int(j)]
                ph = key_hash_parts(hk, sk)
                groups.setdefault(ph % 4, []).append(
                    ("get", generate_key(hk, sk), ph))
            compliant.point_read_multi(groups)
            for r in range(iso_rounds):
                if with_abuser:
                    for i in range(3):
                        # a FIXED 97-key abuser working set, disjoint
                        # from the compliant keys and overwritten with
                        # a constant value: the compliant digest stays
                        # mode-independent and the store reaches an
                        # overwrite fixed point instead of growing
                        abuser.set(b"abk%04d" % ((r * 3 + i) % 97),
                                   b"s", big)
                groups = {}
                for j in order[r * batch:(r + 1) * batch]:
                    hk, sk = keys[int(j)]
                    ph = key_hash_parts(hk, sk)
                    groups.setdefault(ph % 4, []).append(
                        ("get", generate_key(hk, sk), ph))
                t0 = time.perf_counter()
                res = compliant.point_read_multi(groups)
                round_times.append(time.perf_counter() - t0)
                for pidx in sorted(res):
                    for st, val in res[pidx]:
                        digest.update(b"%d" % st)
                        digest.update(val)

        # warm up WITH the abuser (populates its working set, settles
        # flush debt), then compact to the steady state every measured
        # pass starts from — without this, monotonic store growth makes
        # later modes slower and the solo/abuse ratio measures drift
        iso_pass(True, hashlib.sha256(), [])
        for stub in cluster.stubs.values():
            for r in stub.replicas.values():
                r.server.engine.flush()
                r.server.engine.manual_compact()
        cluster.loop.run_until_idle()
        # (mode, enforce, abuser present): the unprotected arm shows
        # what the same abuse does with budget enforcement off
        iso_modes = [("abuser_off", True, False),
                     ("abuser_on", True, True),
                     ("abuser_unprotected", False, True)]
        iso_times = {name: [] for name, _e, _w in iso_modes}
        iso_hashes = {name: hashlib.sha256() for name, _e, _w in
                      iso_modes}
        for _rep in range(reps):
            for name, enforce, with_abuser in iso_modes:
                # the unprotected arm charges CU without gating, so it
                # leaves a bucket deficit no continuously-enforced
                # system ever accrues (post-debit deficit is bounded
                # by ONE op there) — restart the abuser's bucket so
                # every arm starts from the same burst allowance
                TENANTS.ensure("abuser", 1.0, 0.0)
                TENANTS.ensure("abuser", 1.0, 200.0)
                FLAGS.set("pegasus.qos", "tenant_enforce", enforce)
                iso_pass(with_abuser, iso_hashes[name],
                         iso_times[name])
                # drain in-flight replication so one mode's leftovers
                # never land inside the next mode's timed rounds
                cluster.loop.run_until_idle()
        FLAGS.set("pegasus.qos", "tenant_enforce", True)
        snap = TENANTS.snapshot()
        for name, _e, _w in iso_modes:
            ts = iso_times[name]
            out[name] = {
                "compliant_p99_ms": round(pct(ts, 0.99) * 1000, 3),
                "compliant_median_ms": round(pct(ts, 0.5) * 1000, 3),
                "rounds": len(ts),
            }
        out["abuser_on"].update({
            "abuser_overbudget": snap.get("abuser", {}).get(
                "overbudget", 0),
            "abuser_shed": snap.get("abuser", {}).get("shed", 0),
            "abuser_cu_total": snap.get("abuser", {}).get("cu_total", 0),
            "compliant_overbudget": snap.get("compliant", {}).get(
                "overbudget", 0),
        })
        out["compliant_p99_ratio"] = round(
            out["abuser_on"]["compliant_p99_ms"]
            / out["abuser_off"]["compliant_p99_ms"], 3)
        # enforcement's value under identical abuse (reported, not
        # gated: a sequential sim understates unprotected queueing)
        out["unprotected_median_ratio"] = round(
            out["abuser_unprotected"]["compliant_median_ms"]
            / out["abuser_on"]["compliant_median_ms"], 3)
        out["identity_ok"] = len(
            {h.hexdigest() for h in iso_hashes.values()}) == 1
        out["gate_ok"] = bool(
            out["admission_identity_ok"]
            and out["admission_read_overhead"] <= 0.02
            and out["admission_scan_overhead"] <= 0.02
            and out["identity_ok"]
            and out["compliant_p99_ratio"] <= 1.5
            and out["abuser_on"]["abuser_overbudget"] > 0
            and out["abuser_on"]["compliant_overbudget"] == 0)
        return out
    finally:
        cluster.close()
        shutil.rmtree(cdir, ignore_errors=True)
        TENANTS.reset()


def measure_follower_read(tmpdir, seed: int):
    """Follower-read capacity phase (round 17): the SAME batched
    point-get stream through a 3-replica SimCluster at linearizable
    (primary-only) vs bounded_stale (round-robin across all three
    replicas under the read lease) — same-run, identity-gated on the
    returned bytes, modes interleaved across 3 reps.

    The table is ONE partition on purpose: a hot partition is the unit
    whose serving capacity the follower fan-out multiplies (per-table
    aggregates just sum partitions). The sim runs every replica on one
    host thread, so wall q/s cannot show the fan-out — the aggregate
    is modeled the way capacity planning does it: the busiest replica
    is the bottleneck, so
        aggregate_read_qps = wall_qps * total_ops / max_per_replica_ops
    (primary-only: one replica serves 100% -> factor 1; follower
    reads: three replicas serve ~1/3 each -> factor ~3). The gate:
    >= 2x aggregate q/s with byte-identical results and ZERO stale
    bounces (every serve was a real lease-checked, watermark-checked
    follower answer, not a bounce-and-retry at the primary)."""
    import hashlib
    import shutil
    from collections import Counter as _Counter

    import numpy as np

    from pegasus_tpu.base.key_schema import generate_key, key_hash_parts
    from pegasus_tpu.base.value_schema import expire_ts_from_ttl
    from pegasus_tpu.client.cluster_client import bounded_stale
    from pegasus_tpu.rpc.codec import OP_PUT
    from pegasus_tpu.tools.cluster import SimCluster

    n_hks = int(os.environ.get("PEGBENCH_FOLLOWER_KEYS", 256))
    n_rounds = int(os.environ.get("PEGBENCH_FOLLOWER_ROUNDS", 120))
    reps = 3
    batch = 32
    cdir = os.path.join(tmpdir, "follower_read")
    cluster = SimCluster(cdir, n_nodes=3, seed=seed)
    try:
        cluster.create_table("fr", partition_count=1, replica_count=3)
        client = cluster.client("fr")
        hks = [b"fhk%05d" % i for i in range(n_hks)]
        for start in range(0, n_hks, batch):
            groups = {0: []}
            for hk in hks[start:start + batch]:
                ph = key_hash_parts(hk, b"")
                groups[0].append(
                    (OP_PUT, (generate_key(hk, b"s"), b"v" * 64,
                              expire_ts_from_ttl(0)), ph))
            client.write_multi(groups)
        for stub in cluster.stubs.values():
            for r in stub.replicas.values():
                r.server.engine.flush()
                r.server.engine.manual_compact()
        # settle: secondaries commit everything and stamp freshness
        cluster.step(rounds=2)

        # per-replica serve tally, read off the wire the client sends
        served = _Counter()
        orig_send = client._send_request

        def counted_send(dst, method, payload, **kw):
            if method == "client_read_batch":
                served[dst] += sum(len(ops)
                                   for _gpid, ops in payload["groups"])
            return orig_send(dst, method, payload, **kw)

        client._send_request = counted_send

        order = np.random.default_rng(seed + 3).integers(
            0, n_hks, size=n_rounds * batch)
        cons = bounded_stale(
            float(os.environ.get("PEGBENCH_FOLLOWER_LAG_MS", 60_000)))

        def one_pass(digest, consistency):
            t0 = time.perf_counter()
            for r in range(n_rounds):
                groups = {0: []}
                for j in order[r * batch:(r + 1) * batch]:
                    hk = hks[int(j)]
                    groups[0].append(
                        ("get", generate_key(hk, b"s"),
                         key_hash_parts(hk, b"")))
                res = client.point_read_multi(groups,
                                              consistency=consistency)
                for st, val in res[0]:
                    digest.update(b"%d" % st)
                    digest.update(val)
            return time.perf_counter() - t0

        one_pass(hashlib.sha256(), None)  # unmeasured warm-up
        served.clear()
        modes = [("linearizable", None), ("follower", cons)]
        ops_pass = n_rounds * batch
        out = {"hashkeys": n_hks, "ops_per_mode": ops_pass * reps,
               "replica_count": 3}
        times = {name: [] for name, _c in modes}
        hashes = {name: hashlib.sha256() for name, _c in modes}
        tallies = {name: _Counter() for name, _c in modes}
        # modes interleave across reps so slow drift hits both equally
        for _rep in range(reps):
            for name, consistency in modes:
                served.clear()
                times[name].append(one_pass(hashes[name], consistency))
                tallies[name] += served
        bounces = sum(stub._stale_bounces.value()
                      for stub in cluster.stubs.values())
        digests = {}
        for name, _c in modes:
            tally = tallies[name]
            total = sum(tally.values())
            # the busiest replica bounds the group's capacity
            fanout = total / max(tally.values())
            wall_qps = ops_pass * reps / sum(times[name])
            digests[name] = hashes[name].hexdigest()
            out[name] = {
                "wall_qps": round(wall_qps, 1),
                "serving_replicas": len(tally),
                "max_replica_share": round(max(tally.values()) / total,
                                           4),
                "fanout": round(fanout, 3),
                "aggregate_read_qps": round(wall_qps * fanout, 1),
                "pass_s_median": round(sorted(times[name])[1], 4),
            }
        base = out["linearizable"]["aggregate_read_qps"]
        # top-level twin of the follower-mode aggregate: the round's
        # headline metric (bench_report scans a phase's top level)
        out["aggregate_read_qps"] = out["follower"]["aggregate_read_qps"]
        out["speedup"] = round(
            out["follower"]["aggregate_read_qps"] / base, 3)
        out["stale_bounces"] = bounces
        out["identity_ok"] = len(set(digests.values())) == 1
        out["gate_ok"] = bool(out["identity_ok"] and bounces == 0
                              and out["speedup"] >= 2.0)
        return out
    finally:
        cluster.close()
        shutil.rmtree(cdir, ignore_errors=True)


def measure_dup_catchup(tmpdir, seed: int):
    """Geo-replication catch-up phase (round 14): batched+compressed
    dup_apply_batch envelope shipping vs the legacy solo-mutation
    client_write shipping, catching a follower cluster up over a
    DELAYED inter-cluster link — same-run, identity-gated on the
    follower table digest. Each mode runs a FRESH two-SimCluster
    topology from the same seed (identical schedules); with every
    inter-cluster hop paying the link delay, catch-up sim-time is
    round-trip-dominated, i.e. it measures shipping efficiency, not
    host speed. A third pass re-runs batched mode under synthetic
    follower pressure (every envelope delivery grows the follower's
    shed counter): the governor's AIMD backoff must ENGAGE
    (backoff_count grows, throttle floors) while catch-up still
    completes — the forward-progress floor."""
    import hashlib
    import shutil

    from pegasus_tpu.runtime.sim import SimLoop, SimNetwork
    from pegasus_tpu.tools.cluster import SimCluster
    from pegasus_tpu.utils.flags import FLAGS
    from pegasus_tpu.utils.metrics import METRICS

    n_records = int(os.environ.get("PEGBENCH_DUP_RECORDS", 400))
    delay_s = 0.03
    flag_keys = ["ship_batch_mutations", "ship_batch_bytes",
                 "ship_governor"]
    import pegasus_tpu.replica.dup_governor  # noqa: F401 - flags
    import pegasus_tpu.replica.duplication_cluster  # noqa: F401

    saved = {k: FLAGS.get("pegasus.dup", k) for k in flag_keys}

    def dup_counters():
        shipped = raw = backoff = 0
        for ent in METRICS.snapshot("duplication"):
            m = ent.get("metrics", {})
            shipped += m.get("dup_shipped_bytes", {}).get("value", 0)
            raw += m.get("dup_shipped_raw_bytes", {}).get("value", 0)
            backoff += m.get("dup_backoff_count", {}).get("value", 0)
        return shipped, raw, backoff

    def one_mode(name, batch, pressure):
        mode_dir = os.path.join(tmpdir, f"dupcatch_{name}")
        loop = SimLoop(seed=seed)
        net = SimNetwork(loop)
        a = SimCluster(os.path.join(mode_dir, "A"), n_nodes=2,
                       name_prefix="a-", loop=loop, net=net,
                       cluster_id=1)
        b = SimCluster(os.path.join(mode_dir, "B"), n_nodes=2,
                       name_prefix="b-", loop=loop, net=net,
                       cluster_id=2)
        try:
            FLAGS.set("pegasus.dup", "ship_batch_mutations", batch)

            def step_both(r=1):
                for _ in range(r):
                    a.step()
                    b.step(advance=False)

            def pump(sim_seconds):
                """Advance shared sim time in 1s slices with timers
                interleaved: a LONG shipping chain spans many sim
                seconds of link delay, and beacons must keep flowing
                through it or the follower's FD lease lapses mid-
                catch-up (a step-quantized-beacon artifact — real
                nodes beacon on wall-clock timers)."""
                for _ in range(int(sim_seconds)):
                    for cl in (a, b):
                        for stub in cl.stubs.values():
                            stub.send_beacon()
                            stub.config_sync()
                            stub.dup_tick()
                    loop.run_for(1.0)
                    for cl in (a, b):
                        for m in cl.metas:
                            m.tick()

            step_both(2)
            a.create_table("t", partition_count=2, replica_count=2)
            b.create_table("t", partition_count=2, replica_count=2)
            ca = a.client("t")
            for i in range(n_records):
                assert ca.set(b"ck%06d" % i, b"s",
                              b"geo-payload-%06d|" % i * 4) == 0
            for s in list(a.stubs) + [m.name for m in a.metas]:
                for d in list(b.stubs) + [m.name for m in b.metas]:
                    net.set_delay(delay_s, src=s, dst=d)
                    net.set_delay(delay_s, src=d, dst=s)
            if pressure:
                # synthetic follower pressure: every envelope delivery
                # grows the shed counter the ack carries back
                shed = METRICS.entity("rpc", "dispatch", {}).counter(
                    "read_shed_count")
                for bn in list(b.stubs):
                    orig = net._handlers[bn]

                    def wrapped(src, mt, pl, orig=orig):
                        if mt == "dup_apply_batch":
                            shed.increment(5)
                        orig(src, mt, pl)

                    net._handlers[bn] = wrapped
            s0, r0, b0 = dup_counters()
            t0_sim, t0 = loop.now, time.perf_counter()
            a.meta.duplication.add_duplication("t", "b-meta", "t")
            drained = False
            for _ in range(600):
                pump(1)
                sessions = [sess for stub in a.stubs.values()
                            for sess in stub._dup_sessions.values()]
                if sessions and all(
                        sess.confirmed_decree > 0
                        and sess._inflight_decree is None
                        and sess.stats()["lag_decrees"] == 0
                        for sess in sessions):
                    drained = True
                    break
            sim_s = loop.now - t0_sim
            wall_s = time.perf_counter() - t0
            s1, r1, b1 = dup_counters()
            cb = b.client("t")
            digest = hashlib.sha256()
            for i in range(n_records):
                st, val = cb.get(b"ck%06d" % i, b"s")
                digest.update(b"%d" % st)
                digest.update(val or b"")
            return {
                "drained": drained,
                "catchup_sim_s": round(sim_s, 2),
                "catchup_wall_s": round(wall_s, 2),
                "shipped_wire_bytes": s1 - s0,
                "shipped_raw_bytes": r1 - r0,
                "compression_ratio": round((s1 - s0) / (r1 - r0), 4)
                if r1 > r0 else None,
                "governor_backoffs": b1 - b0,
                "digest": digest.hexdigest(),
            }
        finally:
            a.close()
            b.close()
            shutil.rmtree(mode_dir, ignore_errors=True)

    try:
        out = {"records": n_records, "link_delay_s": delay_s}
        out["solo"] = one_mode("solo", 1, False)
        out["batched"] = one_mode("batched", 32, False)
        out["governed"] = one_mode("governed", 32, True)
        out["speedup_sim"] = round(
            out["solo"]["catchup_sim_s"]
            / out["batched"]["catchup_sim_s"], 2) \
            if out["batched"]["catchup_sim_s"] else None
        out["identity_ok"] = (
            out["solo"]["digest"] == out["batched"]["digest"]
            == out["governed"]["digest"])
        # the gate: batched+compressed beats solo on the delayed link,
        # byte-identical content, and the governor both ENGAGES under
        # follower pressure and never stalls catch-up (forward floor)
        out["gate_ok"] = bool(
            out["identity_ok"]
            and out["solo"]["drained"] and out["batched"]["drained"]
            and out["governed"]["drained"]
            and (out["speedup_sim"] or 0) > 1.0
            and out["governed"]["governor_backoffs"] > 0)
        return out
    finally:
        for k, v in saved.items():
            FLAGS.set("pegasus.dup", k, v)


def measure_mixed_load(jax, device, tmpdir, seed: int,
                       n_parts: int = 4, fg_seconds: float = 20.0):
    """Mixed-load phase (round-12): foreground point reads against one
    store while background compactions churn `n_parts` sibling stores,
    with the governor's pressure feedback OFF then ON. The foreground
    loop stamps the SAME deadline-violation counter the rpc dispatcher
    stamps (a get exceeding the deadline budget ticks it), so the
    feedback signal is the real one: foreground latency violations
    drive the AIMD backoff. Reported per mode: foreground p50/p99,
    deadline violations, background bytes compacted (forward-progress
    proof), and the governor's backoff count."""
    import shutil
    import threading as _threading

    import numpy as np

    from pegasus_tpu.storage.compact_governor import GOVERNOR
    from pegasus_tpu.utils.flags import FLAGS
    from pegasus_tpu.utils.metrics import METRICS

    from pegasus_tpu.base.key_schema import generate_key

    deadline_ms = float(os.environ.get("PEGBENCH_MIXED_DEADLINE_MS",
                                       "20"))
    # the forward-progress floor must be able to BIND on this fixture
    # (the governor paces on-disk bytes; each bg store is ~25 MB
    # compressed and the CPU-bound natural rate is ~70 MB/s, so the
    # default 32 MB/s floor would never constrain anything): the
    # phase runs with an 8 MB/s floor and records it
    floor_mbps = float(os.environ.get("PEGBENCH_MIXED_FLOOR_MBPS",
                                      "8"))
    old_floor = FLAGS.get("pegasus.storage", "compact_min_mbps")
    FLAGS.set("pegasus.storage", "compact_min_mbps", floor_mbps)
    per_part = int(0.12e9 / 145)
    viol_counter = METRICS.entity("rpc", "dispatch", {}).counter(
        "deadline_expired_count")
    out = {"deadline_ms": deadline_ms, "floor_mbps": floor_mbps}
    for mode in ("sched_off", "sched_on"):
        data_dir = os.path.join(tmpdir, f"mixed-{mode}")
        if os.path.exists(data_dir):
            shutil.rmtree(data_dir)
        engines = build_compact_store(
            data_dir, per_part * (n_parts + 1), 0.4, n_parts + 1,
            seed, value_kind="templated")
        fg_eng, bg_engines = engines[0], engines[1:]
        os.sync()
        bg_bytes = _store_bytes(bg_engines)
        # reset governor adaptation state between modes
        GOVERNOR._pressure_last = None
        GOVERNOR._throttle_mbps = 0.0
        GOVERNOR._engaged_at_mbps = 0.0
        backoff0 = GOVERNOR._c_backoff.value()
        viol0 = viol_counter.value()
        stop = _threading.Event()
        compacted = []

        def bg_run():
            # cycle the background compactions for the WHOLE foreground
            # window (after the first cycle the stores are pure L1 with
            # nothing to drop, so later cycles are verbatim-copy
            # rewrites — still the full read+write disk churn): the
            # foreground p99 must face sustained background IO, not a
            # 2-second burst diluted over the window
            with jax.default_device(device):
                while not stop.is_set():
                    for eng in bg_engines:
                        if stop.is_set():
                            return
                        eng.manual_compact()
                        compacted.append(eng)

        lat = []
        rng = np.random.default_rng(seed + 5)
        t_bg = _threading.Thread(target=bg_run, daemon=True)
        t_bg.start()
        t_end = time.perf_counter() + fg_seconds
        while time.perf_counter() < t_end:
            hk = b"user%08d" % int(rng.integers(0, per_part // 10))
            sk = b"s%02d" % int(rng.integers(0, 10))
            k = generate_key(hk, sk)
            t0 = time.perf_counter()
            fg_eng.get(k)
            dt = (time.perf_counter() - t0) * 1000.0
            lat.append(dt)
            if mode == "sched_on" and dt > deadline_ms:
                # the dispatcher's signal, stamped by the foreground:
                # a read blowing its deadline budget is exactly what
                # the shed/deadline machinery counts
                viol_counter.increment()
        fg_done = time.perf_counter()
        stop.set()
        t_bg.join(timeout=120)
        bg_secs = time.perf_counter() - fg_done
        lat.sort()
        n = len(lat)
        out[mode] = {
            "fg_gets": n,
            "fg_p50_ms": round(lat[n // 2], 3) if n else None,
            "fg_p99_ms": round(lat[int(n * 0.99)], 3) if n else None,
            "fg_deadline_violations": viol_counter.value() - viol0,
            "bg_parts_compacted": len(compacted),
            "bg_bytes": bg_bytes,
            "bg_extra_seconds_after_fg": round(bg_secs, 2),
            "governor_backoffs": GOVERNOR._c_backoff.value() - backoff0,
            "throttle_mbps_final": GOVERNOR.status()["throttle_mbps"],
        }
        for eng in engines:
            eng.close()
        shutil.rmtree(data_dir, ignore_errors=True)
        _log(f"mixed[{mode}]: p99 {out[mode]['fg_p99_ms']}ms over "
             f"{n} gets, {len(compacted)}/{n_parts} bg compactions, "
             f"{out[mode]['governor_backoffs']} backoffs")
    GOVERNOR._pressure_last = None
    GOVERNOR._throttle_mbps = 0.0
    GOVERNOR._engaged_at_mbps = 0.0
    FLAGS.set("pegasus.storage", "compact_min_mbps", old_floor)
    if out["sched_off"]["fg_p99_ms"] and out["sched_on"]["fg_p99_ms"]:
        out["p99_ratio_on_vs_off"] = round(
            out["sched_on"]["fg_p99_ms"]
            / out["sched_off"]["fg_p99_ms"], 3)
    out["forward_progress_ok"] = \
        out["sched_on"]["bg_parts_compacted"] > 0
    return out


def _scan_identity_digest(bc, n_partitions, n_hashkeys, seed, n=96):
    """sha256 over a deterministic scan sample's key/value bytes."""
    import hashlib

    import numpy as np

    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.server.types import GetScannerRequest

    rng = np.random.default_rng(seed)
    h = hashlib.sha256()
    for _ in range(n):
        pidx = int(rng.integers(0, n_partitions))
        start = b"user%08d" % int(rng.integers(0, n_hashkeys))
        res = bc.client.scan_multi({pidx: [GetScannerRequest(
            start_key=generate_key(start, b""), batch_size=40,
            validate_partition_hash=True, one_page=True)]})
        for resp in res[pidx]:
            for kv in resp.kvs:
                h.update(kv.key)
                h.update(b"\x00")
                h.update(kv.value)
                h.update(b"\x01")
    return h.hexdigest()


def measure_compressed_scan(jax, device, tmpdir, n_records: int,
                            n_partitions: int, n_ops: int, seed: int):
    """scan_compressed phase (round-11): the warm YCSB-E scan measured
    over a compressed store vs an uncompressed same-run twin. Direct
    compute means the steady state decodes nothing (masks from the
    encoded probe, blocks resident in the byte-capped cache), so the
    compressed number must sit within noise of the raw one — that IS
    the acceptance gate, alongside a byte-identity scan sample."""
    from pegasus_tpu.utils.flags import FLAGS

    n_hashkeys = max(1, n_records // 10)
    out = {}
    old_codec = FLAGS.get("pegasus.storage", "block_codec")
    try:
        for codec in ("none", "dcz"):
            FLAGS.set("pegasus.storage", "block_codec", codec)
            bdir = os.path.join(tmpdir, f"cscan-{codec}")
            bc = build_cluster(bdir, n_records, n_partitions, seed)
            try:
                ops, recs, secs = _measure_scan_phase(
                    jax, device, bc, n_ops, n_partitions, n_hashkeys,
                    seed)
                digest = _scan_identity_digest(bc, n_partitions,
                                               n_hashkeys, seed + 7)
                out[codec] = {
                    "ops_per_s": round(ops / secs, 1),
                    "records_per_s": round(recs / secs, 1),
                    "seconds": round(secs, 3),
                    "disk_bytes": data_bytes(bc),
                    "sample_digest": digest,
                }
                _log(f"scan_compressed[{codec}]: "
                     f"{out[codec]['ops_per_s']:.0f} ops/s, "
                     f"{out[codec]['records_per_s']:.0f} records/s")
            finally:
                bc.close()
    finally:
        FLAGS.set("pegasus.storage", "block_codec", old_codec)
    out["identity_ok"] = (out["none"]["sample_digest"]
                          == out["dcz"]["sample_digest"])
    out["ops_ratio_dcz_vs_none"] = round(
        out["dcz"]["ops_per_s"] / max(out["none"]["ops_per_s"], 1e-9),
        4)
    out["disk_ratio"] = round(
        out["dcz"]["disk_bytes"] / max(out["none"]["disk_bytes"], 1),
        4)
    return out


def _pushdown_drain(bc, pidx, req):
    """Drive one partition's scan to exhaustion through the cluster
    read path; returns (rows, shipped_wire_bytes, final agg partial)."""
    from pegasus_tpu.server.types import SCAN_CONTEXT_ID_COMPLETED

    rows, shipped = [], 0
    resp = bc.client.scan_multi({pidx: [req]})[pidx][0]
    while True:
        assert resp.error == 0, f"scan error {resp.error}"
        shipped += resp.wire_bytes()
        rows.extend((kv.key, kv.value) for kv in resp.kvs)
        if resp.context_id == SCAN_CONTEXT_ID_COMPLETED:
            return rows, shipped, resp.agg
        resp = bc.client.scan_page(pidx, resp.context_id)


def measure_scan_pushdown(jax, device, tmpdir, n_records: int,
                          n_partitions: int, seed: int):
    """scan_pushdown phase: the SAME full-table value-filtered count,
    measured twice — client-side (plain scans ship every row, the
    client filters and counts) vs pushdown (the server's vectorized
    value-filter kernel prunes pages; aggregate mode ships one tiny
    partial per partition). Swept at ~0.9 / ~0.1 / ~0.01 selectivity;
    the row sets must be byte-identical (that IS the gate) and the
    aggregate wire cost must stay O(partitions), asserted off the
    responses' shipped-bytes accounting."""
    import numpy as np

    from pegasus_tpu.base.key_schema import generate_key, key_hash_parts
    from pegasus_tpu.ops.predicates import FT_MATCH_ANYWHERE, host_match_filter
    from pegasus_tpu.ops.pushdown import PushdownSpec
    from pegasus_tpu.replica.mutation import WriteOp
    from pegasus_tpu.rpc.codec import OP_PUT
    from pegasus_tpu.server.types import GetScannerRequest

    rng = np.random.default_rng(seed)
    bdir = os.path.join(tmpdir, "pushdown")
    bc = BenchCluster(bdir, n_partitions)
    try:
        # token-embedded values: each marker lands independently at its
        # selectivity, so one load serves all three sweep points
        per_pidx = {p: [] for p in range(n_partitions)}
        n_hashkeys = max(1, n_records // 10)
        i = 0
        for h in range(n_hashkeys):
            hk = b"user%08d" % h
            ops = per_pidx[key_hash_parts(hk) % n_partitions]
            for sk_i in range(10):
                if i >= n_records:
                    break
                toks = b"".join(
                    tok for tok, p in ((b" m90", 0.9), (b" m10", 0.1),
                                       (b" m01", 0.01))
                    if rng.random() < p)
                ops.append(WriteOp(OP_PUT, (
                    generate_key(hk, b"s%02d" % sk_i),
                    b"field0=%032d%s" % (i, toks), 0)))
                i += 1
        for pidx, ops in per_pidx.items():
            r = bc.replicas[pidx]
            for off in range(0, len(ops), 1000):
                r.client_write(ops[off:off + 1000])
            bc.cluster.loop.run_until_idle()
        with jax.default_device(device):
            bc.manual_compact_all(device=device)

            out = {"records": i, "partitions": n_partitions}
            plain = GetScannerRequest(batch_size=1000, full_scan=True,
                                      validate_partition_hash=True)
            for name, pat in (("0.9", b"m90"), ("0.1", b"m10"),
                              ("0.01", b"m01")):
                spec = PushdownSpec(value_filter_type=FT_MATCH_ANYWHERE,
                                    value_filter_pattern=pat)
                pushed = GetScannerRequest(
                    batch_size=1000, full_scan=True,
                    validate_partition_hash=True, pushdown=spec)

                def client_arm():
                    rows, shipped = [], 0
                    for pidx in range(n_partitions):
                        r, s, _a = _pushdown_drain(bc, pidx, plain)
                        shipped += s
                        rows.extend(
                            (k, v) for k, v in r
                            if host_match_filter(v, FT_MATCH_ANYWHERE,
                                                 pat))
                    return rows, shipped

                def pushdown_arm():
                    rows, shipped = [], 0
                    for pidx in range(n_partitions):
                        r, s, _a = _pushdown_drain(bc, pidx, pushed)
                        shipped += s
                        rows.extend(r)
                    return rows, shipped

                # warm both arms (block caches, mask caches, compiles),
                # then best-of-3 — same steady-state rule as the other
                # scan phases
                client_arm()
                pushdown_arm()
                c_best = p_best = None
                for _ in range(3):
                    t0 = time.perf_counter()
                    c_rows, c_ship = client_arm()
                    c_s = time.perf_counter() - t0
                    t0 = time.perf_counter()
                    p_rows, p_ship = pushdown_arm()
                    p_s = time.perf_counter() - t0
                    c_best = c_s if c_best is None else min(c_best, c_s)
                    p_best = p_s if p_best is None else min(p_best, p_s)
                identical = sorted(c_rows) == sorted(p_rows)

                # aggregate count: one partial per partition on the wire
                agg_req = GetScannerRequest(
                    batch_size=1000, full_scan=True,
                    validate_partition_hash=True,
                    pushdown=PushdownSpec(
                        value_filter_type=FT_MATCH_ANYWHERE,
                        value_filter_pattern=pat, aggregate="count"))
                agg_shipped, agg_count = 0, 0
                t0 = time.perf_counter()
                for pidx in range(n_partitions):
                    r, s, agg = _pushdown_drain(bc, pidx, agg_req)
                    assert not r, "aggregate reply must carry no rows"
                    agg_shipped += s
                    agg_count += int(agg["count"])
                agg_s = time.perf_counter() - t0
                wire_ok = agg_shipped <= 256 * n_partitions
                assert agg_count == len(c_rows), \
                    f"agg count {agg_count} != {len(c_rows)}"

                out[f"sel_{name}"] = {
                    "matching_rows": len(c_rows),
                    "client_seconds": round(c_best, 4),
                    "pushdown_seconds": round(p_best, 4),
                    "pushdown_speedup": round(c_best / max(p_best, 1e-9),
                                              3),
                    "client_shipped_bytes": c_ship,
                    "pushdown_shipped_bytes": p_ship,
                    "agg_seconds": round(agg_s, 4),
                    "agg_shipped_bytes": agg_shipped,
                    "agg_wire_o_partitions": wire_ok,
                    "identity_ok": identical,
                }
                _log(f"scan_pushdown[sel={name}]: client {c_best:.3f}s "
                     f"vs pushdown {p_best:.3f}s "
                     f"({c_best / max(p_best, 1e-9):.2f}x), agg wire "
                     f"{agg_shipped}B/{n_partitions} parts, "
                     f"identical={identical}")
            out["identity_ok"] = all(
                out[k]["identity_ok"] for k in
                ("sel_0.9", "sel_0.1", "sel_0.01"))
            out["agg_wire_o_partitions"] = all(
                out[k]["agg_wire_o_partitions"] for k in
                ("sel_0.9", "sel_0.1", "sel_0.01"))
            # the ISSUE gate: >=2x at selectivity <= 0.1, identity held
            out["pushdown_speedup"] = out["sel_0.1"]["pushdown_speedup"] \
                if out["identity_ok"] else 0.0
        return out
    finally:
        import shutil

        bc.close()
        shutil.rmtree(bdir, ignore_errors=True)


def measure_mesh_scan(here: str) -> dict:
    """mesh_scan phase (runs in a SUBPROCESS): the resident device-mesh
    SPMD serving arm vs the host kernel wave, same run, byte-identity
    gated. A subprocess because the CPU-device mesh needs
    --xla_force_host_platform_device_count set BEFORE jax initializes,
    and the parent already brought its backend up."""
    env = dict(os.environ)
    env["PEGBENCH_FORCE_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags +
                            " --xla_force_host_platform_device_count=8"
                            ).strip()
    r = subprocess.run(
        [sys.executable, os.path.join(here, "bench.py"), "--mesh-phase"],
        capture_output=True, text=True, env=env, cwd=here, timeout=1800)
    for line in (r.stderr or "").splitlines():
        _log(f"  [mesh] {line}")
    if r.returncode != 0:
        raise RuntimeError(f"mesh phase subprocess rc={r.returncode}: "
                           f"{(r.stderr or '')[-300:]}")
    return json.loads((r.stdout or "").strip().splitlines()[-1])


def _mesh_phase_main() -> None:
    """--mesh-phase subprocess body: one JSON dict on stdout.

    Measures the node-level cross-partition wave (scan_multi's shape:
    every partition's uncached blocks in ONE stacked_block_eval call)
    with the mesh DETACHED (host chunk programs) vs ATTACHED (one
    resident SPMD dispatch answers all partitions), under the REAL
    placement gate — no pinning. Then the whole-range aggregate fold at
    the same selectivity, then the watchdog leg: every dispatch forced
    to overrun its deadline must trip the tunnel and degrade to host
    kernels with identical rows and zero hung scans."""
    import numpy as np

    from pegasus_tpu.client.client import PegasusClient
    from pegasus_tpu.client.table import Table
    from pegasus_tpu.ops.predicates import FT_NO_FILTER, FT_MATCH_ANYWHERE
    from pegasus_tpu.ops.pushdown import PushdownSpec
    from pegasus_tpu.parallel.mesh_resident import MESH_SERVING
    from pegasus_tpu.server.scan_coordinator import stacked_block_eval
    from pegasus_tpu.server.types import (
        GetScannerRequest,
        SCAN_CONTEXT_ID_COMPLETED,
    )
    from pegasus_tpu.utils.flags import FLAGS
    import jax

    n_records = int(os.environ.get("PEGBENCH_MESH_RECORDS", 240_000))
    n_partitions = int(os.environ.get("PEGBENCH_MESH_PARTITIONS", 8))
    seed = int(os.environ.get("PEGBENCH_SEED", 7))
    fkey = (FT_NO_FILTER, b"", FT_NO_FILTER, b"")
    rng = np.random.default_rng(seed)

    tmpdir = tempfile.mkdtemp(prefix="pegbench_mesh")
    # codec none: compressed blocks answer their static probes in the
    # encoded domain host-side and never reach the wave path
    FLAGS.set("pegasus.storage", "block_codec", "none")
    FLAGS.set("pegasus.server", "rocksdb_max_iteration_count", 0)
    table = Table(tmpdir, partition_count=n_partitions)
    client = PegasusClient(table)
    t0 = time.perf_counter()
    for i in range(n_records):
        tok = b" m10" if rng.random() < 0.1 else b""  # selectivity 0.1
        assert client.set(b"user%06d" % (i // 10), b"s%02d" % (i % 10),
                          b"f=%024d%s" % (i, tok)) == 0
    _log(f"loaded {n_records} records in {time.perf_counter() - t0:.1f}s")
    for s in table.partitions.values():
        s.engine.flush()
        s.engine.manual_compact()  # wave serving is over pure sorted runs

    blocks = []
    for p, s in sorted(table.partitions.items()):
        for run in s.engine.lsm.sorted_runs():
            for bm, blk in run.iter_blocks(b"", None):
                ckey = (run.path, bm.offset)
                blocks.append(((p, ckey), s._device_cached_block(ckey, blk),
                               s.pidx, int(blk.count)))
    pv = table.partitions[0].partition_version

    def wave_once():
        masks = {}
        t0 = time.perf_counter()
        for tag, keep in stacked_block_eval(
                [(t, d, p) for t, d, p, _n in blocks], True, pv,
                filter_key=fkey):
            masks[tag] = np.asarray(keep)
        return time.perf_counter() - t0, masks

    def drain_all():
        rows = {}
        for p, s in sorted(table.partitions.items()):
            pd = PushdownSpec(value_filter_type=FT_MATCH_ANYWHERE,
                              value_filter_pattern=b"m10")
            resp = s.on_get_scanner(GetScannerRequest(batch_size=1000,
                                                      pushdown=pd))
            got = []
            while True:
                assert resp.error == 0
                got.extend((kv.key, kv.value) for kv in resp.kvs)
                if resp.context_id == SCAN_CONTEXT_ID_COMPLETED:
                    break
                resp = s.on_scan(resp.context_id)
            rows[p] = got
        return rows

    def drain_all_multi():
        """Node-level coordinated drain: every partition's first wave of
        planned misses evaluates in ONE cross-partition scan_multi call
        — the shape whose program count and byte volume clear the real
        mesh placement gate (solo drains wave in LOOKAHEAD windows and
        stay on host kernels honestly)."""
        def fresh_req():
            return GetScannerRequest(
                batch_size=1000,
                pushdown=PushdownSpec(value_filter_type=FT_MATCH_ANYWHERE,
                                      value_filter_pattern=b"m10"))
        first = client.scan_multi({p: [fresh_req()]
                                   for p in sorted(table.partitions)})
        rows = {}
        for p in sorted(table.partitions):
            s = table.partitions[p]
            resp = first[p][0]
            got = []
            while True:
                assert resp.error == 0
                got.extend((kv.key, kv.value) for kv in resp.kvs)
                if resp.context_id == SCAN_CONTEXT_ID_COMPLETED:
                    break
                resp = s.on_scan(resp.context_id)
            rows[p] = got
        return rows

    def agg_all():
        out = {}
        t0 = time.perf_counter()
        for p, s in sorted(table.partitions.items()):
            pd = PushdownSpec(value_filter_type=FT_MATCH_ANYWHERE,
                              value_filter_pattern=b"m10",
                              aggregate="count")
            resp = s.on_get_scanner(GetScannerRequest(batch_size=1000,
                                                      pushdown=pd))
            while resp.context_id != SCAN_CONTEXT_ID_COMPLETED:
                assert resp.error == 0
                resp = s.on_scan(resp.context_id)
            out[p] = resp.agg
        return time.perf_counter() - t0, out

    def clear_masks():
        for s in table.partitions.values():
            with s._mask_lock:
                s._mask_cache.clear()

    # host arm: mesh detached — the chunked host kernel wave
    MESH_SERVING.reset()
    wave_once()  # warm compiles + block device cache
    host_wave = min(wave_once()[0] for _ in range(3))
    host_masks = wave_once()[1]
    host_rows = drain_all()
    agg_all()
    host_agg_s = min(agg_all()[0] for _ in range(3))
    host_agg = agg_all()[1]

    # mesh arm: attach every partition; the REAL placement gate routes
    for s in table.partitions.values():
        MESH_SERVING.attach(s)
    w0 = MESH_SERVING.wave_dispatches
    wave_once()  # warm: builds the resident image + mesh program
    mesh_served = MESH_SERVING.wave_dispatches > w0
    mesh_wave = min(wave_once()[0] for _ in range(3))
    mesh_masks = wave_once()[1]
    clear_masks()
    w1 = MESH_SERVING.wave_dispatches
    mesh_rows = drain_all_multi()
    mesh_drain_served = MESH_SERVING.wave_dispatches > w1
    a0 = MESH_SERVING.agg_dispatches
    agg_all()
    mesh_agg_served = MESH_SERVING.agg_dispatches > a0
    mesh_agg_s = min(agg_all()[0] for _ in range(3))
    mesh_agg = agg_all()[1]

    wave_identity = all(
        np.array_equal(host_masks[t][:n], mesh_masks[t][:n])
        for t, _d, _p, n in blocks)
    rows_identity = host_rows == mesh_rows
    agg_identity = host_agg == mesh_agg

    # watchdog leg: wedge every dispatch; coordinated serving must
    # degrade to the host kernels (identical rows, bounded wall, zero
    # hung scans). Two overrunning dispatches trip the tunnel, the
    # third drain serves wedged (pure host).
    MESH_SERVING.watchdog.deadline_s = 1e-9
    t0 = time.perf_counter()
    clear_masks()
    drain_all_multi()  # dispatch 1 overruns -> host fallback
    clear_masks()
    drain_all_multi()  # dispatch 2 overruns -> consecutive-failure trip
    clear_masks()
    wedged_rows = drain_all_multi()  # tunnel wedged: host serving
    wedged_wall = time.perf_counter() - t0
    wd = {
        "fallback_identity_ok": wedged_rows == host_rows,
        "wall_s": round(wedged_wall, 3),
        "trips": MESH_SERVING.watchdog.trips,
        "wedged": bool(MESH_SERVING.status()["tunnel_wedged"]),
        "fallbacks": MESH_SERVING.status()["mesh_fallback_count"],
    }
    MESH_SERVING.reset()
    table.close()
    import shutil

    shutil.rmtree(tmpdir, ignore_errors=True)

    speedup = host_wave / max(mesh_wave, 1e-9)
    agg_speedup = host_agg_s / max(mesh_agg_s, 1e-9)
    identity_ok = wave_identity and rows_identity and agg_identity
    out = {
        "records": n_records, "partitions": n_partitions,
        "devices": len(jax.devices()), "blocks": len(blocks),
        "selectivity": 0.1,
        "host_wave_ms": round(host_wave * 1e3, 2),
        "mesh_wave_ms": round(mesh_wave * 1e3, 2),
        "mesh_speedup": round(speedup, 3) if identity_ok else 0.0,
        "agg_host_ms": round(host_agg_s * 1e3, 2),
        "agg_mesh_ms": round(mesh_agg_s * 1e3, 2),
        "agg_speedup": round(agg_speedup, 3),
        "mesh_served": mesh_served,
        "mesh_drain_served": mesh_drain_served,
        "mesh_agg_served": mesh_agg_served,
        "wave_identity_ok": wave_identity,
        "rows_identity_ok": rows_identity,
        "agg_identity_ok": agg_identity,
        "watchdog": wd,
        "gate_ok": bool(identity_ok and mesh_served and mesh_drain_served
                        and speedup >= 1.5 and len(jax.devices()) >= 4
                        and wd["trips"] >= 1
                        and wd["fallback_identity_ok"] and wd["wedged"]),
    }
    print(json.dumps(out), flush=True)


def measure_mesh_compact(here: str) -> dict:
    """mesh_compact phase (runs in a SUBPROCESS): the compaction FILTER
    stage off the resident device-mesh image vs the host kernels, same
    run, identity-digest-gated. A subprocess for the same reason as
    mesh_scan: the CPU-device mesh needs
    --xla_force_host_platform_device_count BEFORE jax initializes."""
    env = dict(os.environ)
    env["PEGBENCH_FORCE_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags +
                            " --xla_force_host_platform_device_count=8"
                            ).strip()
    r = subprocess.run(
        [sys.executable, os.path.join(here, "bench.py"),
         "--mesh-compact-phase"],
        capture_output=True, text=True, env=env, cwd=here, timeout=1800)
    for line in (r.stderr or "").splitlines():
        _log(f"  [mesh_compact] {line}")
    if r.returncode != 0:
        raise RuntimeError(f"mesh_compact subprocess rc={r.returncode}: "
                           f"{(r.stderr or '')[-300:]}")
    return json.loads((r.stdout or "").strip().splitlines()[-1])


def _mesh_compact_phase_main() -> None:
    """--mesh-compact-phase subprocess body: one JSON dict on stdout.

    Measures the bulk-compaction FILTER stage over >=8 partitions with
    the mesh DETACHED (per-partition submit/drain host programs) vs
    ATTACHED (ONE whole-table SPMD dispatch + sibling cache serves),
    under the REAL mesh_compact_pays gate — no pinning. Then three
    full-compaction arms over copies of the same store — host-pipelined,
    mesh-filtered, and wedged-watchdog — must publish byte-identical
    SST files, and the mesh arm's publishes must refresh residency by
    survivor-gather (reuse counter, zero slab builds)."""
    import hashlib
    import shutil

    import numpy as np

    import pegasus_tpu.storage.engine as engine_mod
    from pegasus_tpu.base.value_schema import epoch_now
    from pegasus_tpu.client.client import PegasusClient
    from pegasus_tpu.client.table import Table
    from pegasus_tpu.ops.compaction import (
        compaction_eval_drain,
        compaction_eval_submit,
    )
    from pegasus_tpu.parallel.mesh_resident import MESH_SERVING
    from pegasus_tpu.storage.compact_pipeline import window_count
    from pegasus_tpu.utils.flags import FLAGS
    import jax

    n_records = int(os.environ.get("PEGBENCH_MESH_COMPACT_RECORDS",
                                   192_000))
    n_partitions = int(os.environ.get("PEGBENCH_MESH_PARTITIONS", 8))
    seed = int(os.environ.get("PEGBENCH_SEED", 7))
    rng = np.random.default_rng(seed)

    tmpdir = tempfile.mkdtemp(prefix="pegbench_meshcompact")
    base = os.path.join(tmpdir, "base")
    FLAGS.set("pegasus.storage", "block_codec", "none")
    table = Table(base, partition_count=n_partitions)
    client = PegasusClient(table)
    t0 = time.perf_counter()
    for i in range(n_records):
        # ~30% of rows carry TTLs that will be expired at the arms'
        # shared filter timestamp (BASELINE config #3's retention sweep)
        ttl = 60 if rng.random() < 0.3 else 0
        assert client.set(b"user%06d" % (i // 10), b"s%02d" % (i % 10),
                          b"f=%024d" % i, ttl_seconds=ttl) == 0
    _log(f"loaded {n_records} records in {time.perf_counter() - t0:.1f}s")
    for s in table.partitions.values():
        s.engine.flush()
        s.engine.manual_compact()  # bulk filtering is over pure L1
    fixed_now = epoch_now() + 3600
    # the finish-time stamp lands in the SST index; freeze it so arms
    # can't straddle a second boundary and diverge on non-filter bytes
    engine_mod.epoch_now = lambda: fixed_now
    entries_per = {p: s.engine.lsm.bulk_compact_entries()
                   for p, s in sorted(table.partitions.items())}
    n_blocks = sum(len(e) for e in entries_per.values())
    host_windows = sum(window_count(len(e))
                       for e in entries_per.values())

    def host_filter_once():
        t0 = time.perf_counter()
        masks = {}
        for p, s in sorted(table.partitions.items()):
            blocks = [((run, i), run.read_block(i), p)
                      for run, i, _bm in entries_per[p]]
            pend = compaction_eval_submit(
                blocks, fixed_now, 0, s.partition_version, False,
                operations=None, eval_device=None, want_ets=False)
            for tag, drop, _e in compaction_eval_drain(
                    pend, want_ets=False):
                masks[(p,) + tag] = np.asarray(drop, bool)
        return time.perf_counter() - t0, masks

    def mesh_filter_once():
        MESH_SERVING._compact_cache.clear()
        t0 = time.perf_counter()
        masks = {}
        for p, s in sorted(table.partitions.items()):
            got = MESH_SERVING.try_compact_masks(
                s.engine.lsm, entries_per[p], fixed_now, 0, p,
                s.partition_version, False, None, want_ets=False,
                n_windows=window_count(len(entries_per[p])))
            if got is None:
                return time.perf_counter() - t0, None
            for (run, i), (drop, _e) in got.items():
                masks[(p, run, i)] = np.asarray(drop, bool)
        return time.perf_counter() - t0, masks

    # host arm first: mesh detached, per-partition window programs
    MESH_SERVING.reset()
    host_filter_once()  # warm compiles + OS page cache
    host_filter_s = min(host_filter_once()[0] for _ in range(3))
    host_masks = host_filter_once()[1]

    # mesh arm: attach every partition; the REAL gate routes
    for s in table.partitions.values():
        MESH_SERVING.attach(s)
    mesh_filter_once()  # warm: resident image + program compile
    mesh_filter_s = min(mesh_filter_once()[0] for _ in range(3))
    _t, mesh_masks = mesh_filter_once()
    mesh_served = mesh_masks is not None
    mask_identity = bool(
        mesh_served and host_masks.keys() == mesh_masks.keys()
        and all(np.array_equal(host_masks[k], mesh_masks[k])
                for k in host_masks))
    dispatches = MESH_SERVING.compact_dispatches
    serves = MESH_SERVING.compact_mask_serves
    MESH_SERVING.reset()
    table.close()

    def digest(d):
        out = []
        for root, _dirs, files in os.walk(d):
            for f in sorted(files):
                if f.endswith(".sst"):
                    p = os.path.join(root, f)
                    with open(p, "rb") as fh:
                        out.append((os.path.relpath(p, d),
                                    hashlib.sha256(
                                        fh.read()).hexdigest()))
        return sorted(out)

    def compact_arm(name, mesh=False, wedge=False):
        d = os.path.join(tmpdir, name)
        shutil.copytree(base, d)
        MESH_SERVING.reset()
        t = Table(d, partition_count=n_partitions)
        try:
            if mesh:
                for s in t.partitions.values():
                    MESH_SERVING.attach(s)
                assert MESH_SERVING.ensure_current()
            if wedge:
                MESH_SERVING.watchdog.deadline_s = 1e-9
            builds0 = MESH_SERVING.slab_builds
            t0 = time.perf_counter()
            for s in t.partitions.values():
                s.manual_compact(now=fixed_now)
            wall = time.perf_counter() - t0
            if mesh and not wedge:
                MESH_SERVING.ensure_current()  # publish-side refresh
            st = MESH_SERVING.status()
            st["slab_builds_during"] = MESH_SERVING.slab_builds - builds0
            return digest(d), wall, st
        finally:
            t.close()
            MESH_SERVING.reset()

    host_dig, host_wall, _ = compact_arm("host")
    mesh_dig, mesh_wall, mesh_st = compact_arm("mesh", mesh=True)
    wedge_dig, wedge_wall, wedge_st = compact_arm("wedged", mesh=True,
                                                  wedge=True)
    shutil.rmtree(tmpdir, ignore_errors=True)

    filter_speedup = host_filter_s / max(mesh_filter_s, 1e-9)
    digest_ok = host_dig == mesh_dig
    wedged_ok = host_dig == wedge_dig
    out = {
        "records": n_records, "partitions": n_partitions,
        "devices": len(jax.devices()), "blocks": n_blocks,
        "host_windows": host_windows,
        "host_filter_ms": round(host_filter_s * 1e3, 2),
        "mesh_filter_ms": round(mesh_filter_s * 1e3, 2),
        "filter_speedup": (round(filter_speedup, 3)
                           if mask_identity else 0.0),
        "mesh_served": mesh_served,
        "mask_identity_ok": mask_identity,
        "compact_host_s": round(host_wall, 3),
        "compact_mesh_s": round(mesh_wall, 3),
        "compact_wedged_s": round(wedge_wall, 3),
        "digest_identity_ok": digest_ok,
        "wedged_digest_ok": wedged_ok,
        "dispatches": dispatches,
        "mask_serves": serves,
        "arm_dispatches": mesh_st["compact_dispatches"],
        "refresh_reuses": mesh_st["refresh_reuses"],
        "refresh_rebuilds": mesh_st["refresh_rebuilds"],
        "refresh_slab_builds": mesh_st["slab_builds_during"],
        "wedged_fallbacks": wedge_st["compact_mesh_fallback_count"],
        "wedged_trips": wedge_st["watchdog"]["trips"],
        "gate_ok": bool(mask_identity and digest_ok and wedged_ok
                        and mesh_served and dispatches >= 1
                        and filter_speedup >= 1.5
                        and mesh_st["refresh_reuses"] >= n_partitions
                        and mesh_st["slab_builds_during"] == 0
                        and wedge_st["watchdog"]["trips"] >= 1
                        and len(jax.devices()) >= 4),
    }
    print(json.dumps(out), flush=True)


def measure_geo(jax, device, n_points=20_000, n_searches=150, seed=11):
    """Geo radius-search ops/sec (BASELINE config #5): cell-cover prefix
    scans + one batched device distance predicate per search."""
    import numpy as np

    from pegasus_tpu.client import PegasusClient, Table
    from pegasus_tpu.geo import GeoClient

    rng = np.random.default_rng(seed)
    with tempfile.TemporaryDirectory(prefix="peggeo") as tmp:
        raw = Table(os.path.join(tmp, "raw"), app_id=1, partition_count=8)
        idx = Table(os.path.join(tmp, "idx"), app_id=2, partition_count=8)
        geo = GeoClient(PegasusClient(raw), PegasusClient(idx))
        # ~20km x 20km urban box around (40, -74)
        lats = 40.0 + (rng.random(n_points) - 0.5) * 0.18
        lngs = -74.0 + (rng.random(n_points) - 0.5) * 0.24
        for i in range(n_points):
            geo.set(b"poi%06d" % i, b"s",
                    b"%f|%f|poi-%d" % (lats[i], lngs[i], i))
        raw.flush_all()
        idx.flush_all()
        with jax.default_device(device):
            # L0 -> L1 so the cell scans ride the batched device path
            idx.manual_compact_all(device=device)
        centers = rng.integers(0, n_points, size=n_searches)
        with jax.default_device(device):
            # warmup: full pass so compiles + first-touch block caches
            # are paid before measurement (both backends get the same
            # treatment when the caller measures accel and cpu in turn)
            for ci in centers:
                geo.search_radial(float(lats[ci]), float(lngs[ci]), 500)
            hits = 0
            t0 = time.perf_counter()
            for ci in centers:
                hits += len(geo.search_radial(float(lats[ci]),
                                              float(lngs[ci]), 500))
            secs = time.perf_counter() - t0
        raw.close()
        idx.close()
        return n_searches / secs, hits


def main() -> None:
    n_records = int(os.environ.get("PEGBENCH_RECORDS", 1_000_000))
    n_ops = int(os.environ.get("PEGBENCH_OPS", 12_000))
    n_partitions = int(os.environ.get("PEGBENCH_PARTITIONS", 64))
    seed = int(os.environ.get("PEGBENCH_SEED", 7))
    # 120s covers a healthy-but-cold backend init (~4-40s measured) while
    # keeping the WORST case (wedged tunnel, all retries burned, then the
    # full CPU fallback run) inside a plausible driver timeout
    probe_timeout = float(os.environ.get("PEGBENCH_PROBE_TIMEOUT", 120))
    probe_retries = int(os.environ.get("PEGBENCH_PROBE_RETRIES", 4))
    # all BASELINE.md phases run by default so the recorded details
    # cover every target row; =0 disables one for quick iteration
    do_compact = os.environ.get("PEGBENCH_COMPACT", "1") != "0"
    do_compressed = os.environ.get("PEGBENCH_COMPRESSED", "1") != "0"
    do_pushdown = os.environ.get("PEGBENCH_PUSHDOWN", "1") != "0"
    do_pipeline = os.environ.get("PEGBENCH_PIPELINE", "1") != "0"
    do_mixed = os.environ.get("PEGBENCH_MIXED", "1") != "0"
    do_geo = os.environ.get("PEGBENCH_GEO", "1") != "0"
    do_trace = os.environ.get("PEGBENCH_TRACE", "1") != "0"
    do_dup = os.environ.get("PEGBENCH_DUP", "1") != "0"
    do_health = os.environ.get("PEGBENCH_HEALTH", "1") != "0"
    do_perfctx = os.environ.get("PEGBENCH_PERFCTX", "1") != "0"
    do_follower = os.environ.get("PEGBENCH_FOLLOWER_READ", "1") != "0"
    do_qos = os.environ.get("PEGBENCH_QOS", "1") != "0"
    do_mesh = os.environ.get("PEGBENCH_MESH", "1") != "0"
    do_mesh_compact = os.environ.get("PEGBENCH_MESH_COMPACT", "1") != "0"

    details = {"phases": {}}
    here = os.path.dirname(os.path.abspath(__file__))

    def save_details():
        """Crash-durable phase results: every completed phase lands in
        BENCH_DETAILS.json IMMEDIATELY — a later-phase tunnel wedge must
        not discard numbers already measured (the round-4 failure)."""
        with open(os.path.join(here, "BENCH_DETAILS.json"), "w") as f:
            json.dump(details, f, indent=1)

    probe = probe_accelerator(probe_timeout, probe_retries)
    accel_error = None
    if not probe["ok"]:
        # the TPU tunnel never came up (r4 lost its whole round to
        # exactly this). A measured CPU-only number annotated with the
        # fault beats value=0: switch this process to the CPU-isolation
        # mode (jax is not imported yet at this point) and measure
        # everything on the host backend, reporting the fault in the
        # one JSON line.
        accel_error = (f"accelerator backend unavailable after "
                       f"{probe_retries} probes: {probe['error']} — "
                       f"CPU-only fallback measurement")
        _log(accel_error)
        os.environ["PEGBENCH_FORCE_CPU"] = "1"
        exec(_ISOLATE_SRC)

    import jax
    try:
        current = jax.config.jax_platforms or ""
    except AttributeError:
        current = os.environ.get("JAX_PLATFORMS", "")
    if current and "cpu" not in current.split(","):
        jax.config.update("jax_platforms", current + ",cpu")

    accel = jax.devices()[0]
    cpu = jax.local_devices(backend="cpu")[0]
    _log(f"accelerator: {accel}, baseline: {cpu}")

    with jax.default_device(accel):
        smoke = pallas_smoke()
    _log(f"pallas fused-kernel smoke on {accel.platform}: {smoke}")
    details["pallas_smoke"] = smoke
    details["accel_platform"] = accel.platform

    with tempfile.TemporaryDirectory(prefix="pegbench") as tmpdir:
        bc = build_cluster(tmpdir, n_records, n_partitions, seed)
        n_hashkeys = max(1, n_records // 10)
        try:
            ops, recs, accel_s = measure_scan_phase(
                jax, accel, bc, n_ops, n_partitions, n_hashkeys, seed + 2)
            accel_qps = ops / accel_s
            _log(f"accel: {ops} ops / {recs} records in {accel_s:.2f}s "
                 f"-> {accel_qps:.1f} ops/s, {recs / accel_s:.0f} rec/s")

            ops_c, recs_c, cpu_s = measure_scan_phase(
                jax, cpu, bc, n_ops, n_partitions, n_hashkeys, seed + 2)
            cpu_qps = ops_c / cpu_s
            _log(f"cpu:   {ops_c} ops / {recs_c} records in {cpu_s:.2f}s "
                 f"-> {cpu_qps:.1f} ops/s")
            details["phases"]["load_write"] = {
                "write_qps_2pc": bc.load_write_qps,
                "records": n_records,
            }
            details["phases"]["scan"] = {
                "accel_qps": round(accel_qps, 2),
                "cpu_qps": round(cpu_qps, 2),
                "accel_records_per_s": round(recs / accel_s, 1),
                "ops": n_ops, "records_loaded": n_records,
                "scan_batch": int(os.environ.get("PEGBENCH_SCAN_BATCH",
                                                 32)),
            }
            save_details()

            # later phases must never cost us the scan number already
            # measured (round-4 lost its official line to a tunnel
            # wedge in a later phase): any failure below is recorded
            # and the headline still prints
            phase_error = None
            try:
                # YCSB-C point gets (host-dominated: measures the full
                # client->gate->engine path; the accel/cpu ratio shows the
                # device path does not tax point reads)
                g_ops = max(2000, n_ops)
                # warm once for BOTH phases: the engine builds per-block
                # key lists lazily on first bisect — whichever phase runs
                # first would otherwise pay that construction and read slow
                run_point_gets(bc, g_ops, n_hashkeys, seed + 3)
                with jax.default_device(accel):
                    ops_g, hits_g, accel_g = run_point_gets(
                        bc, g_ops, n_hashkeys, seed + 3)
                with jax.default_device(cpu):
                    _o, _h, cpu_g = run_point_gets(bc, g_ops, n_hashkeys,
                                                   seed + 3)
                details["phases"]["point_get"] = {
                    "accel_qps": round(ops_g / accel_g, 2),
                    "cpu_qps": round(ops_g / cpu_g, 2),
                    "hit_rate": round(hits_g / ops_g, 4),
                }
                save_details()

                # batched point reads (the read-coordinator tentpole):
                # the SAME op stream coalesced 32 per flush through
                # point_read_multi, vs the single-request numbers above
                # — plus the server-side pair (no client/transport) and
                # the byte-identity acceptance gate
                pg_batch = int(os.environ.get("PEGBENCH_GET_BATCH", 32))
                identical = verify_point_batch_identity(
                    bc, n_hashkeys, seed + 3)
                with jax.default_device(accel):
                    run_point_gets_batched(bc, g_ops, n_hashkeys,
                                           seed + 3, batch=pg_batch)
                    ops_b, hits_b, accel_b = run_point_gets_batched(
                        bc, g_ops, n_hashkeys, seed + 3, batch=pg_batch)
                with jax.default_device(cpu):
                    run_point_gets_batched(bc, g_ops, n_hashkeys,
                                           seed + 3, batch=pg_batch)
                    _o, _h, cpu_b = run_point_gets_batched(
                        bc, g_ops, n_hashkeys, seed + 3, batch=pg_batch)
                # server-side: the r5 single-request hot loop vs the
                # coordinator, same stream, both warm (pass 1 warms)
                run_point_gets_server_side(bc, g_ops, n_hashkeys,
                                           seed + 3, batch=0)
                _o, _h, sv_solo = run_point_gets_server_side(
                    bc, g_ops, n_hashkeys, seed + 3, batch=0)
                run_point_gets_server_side(bc, g_ops, n_hashkeys,
                                           seed + 3, batch=pg_batch)
                _o, _h, sv_b = run_point_gets_server_side(
                    bc, g_ops, n_hashkeys, seed + 3, batch=pg_batch)
                details["phases"]["point_get_batch"] = {
                    "batch": pg_batch,
                    "accel_qps": round(ops_b / accel_b, 2),
                    "cpu_qps": round(ops_b / cpu_b, 2),
                    "hit_rate": round(hits_b / ops_b, 4),
                    "vs_single_request": round(
                        (ops_b / accel_b) / (ops_g / accel_g), 3),
                    "server_side_solo_qps": round(g_ops / sv_solo, 2),
                    f"server_side_batch{pg_batch}_qps": round(
                        g_ops / sv_b, 2),
                    "server_side_speedup": round(sv_solo / sv_b, 3),
                    "identical_to_unbatched": identical,
                }
                save_details()
                _log(f"point-get-batch({pg_batch}): "
                     f"{ops_b / accel_b:.0f} q/s client-batched "
                     f"({(ops_b / accel_b) / (ops_g / accel_g):.2f}x "
                     f"single-request); server-side "
                     f"{g_ops / sv_solo:.0f} -> {g_ops / sv_b:.0f} q/s "
                     f"({sv_solo / sv_b:.2f}x); "
                     f"identical={identical}")

                # batching-margin sweep: the same scan workload with
                # coalescing DISABLED (batch=1) on both backends — the
                # accel/cpu margin should GROW with the batch size,
                # since batching is what amortizes device dispatch
                m_ops = max(1500, n_ops // 8)
                with jax.default_device(accel):
                    run_scans(bc, m_ops, n_partitions, n_hashkeys,
                              seed + 5, insert_frac=0, scan_batch=1)
                    o1, _r1, a1 = run_scans(bc, m_ops, n_partitions,
                                            n_hashkeys, seed + 5,
                                            scan_batch=1)
                with jax.default_device(cpu):
                    run_scans(bc, m_ops, n_partitions, n_hashkeys,
                              seed + 5, insert_frac=0, scan_batch=1)
                    _o, _r, c1 = run_scans(bc, m_ops, n_partitions,
                                           n_hashkeys, seed + 5,
                                           scan_batch=1)
                ratio_b1 = (o1 / a1) / (o1 / c1) if a1 and c1 else 0
                base_batch = details["phases"]["scan"]["scan_batch"]
                ratio_bn = (details["phases"]["scan"]["accel_qps"]
                            / max(details["phases"]["scan"]["cpu_qps"],
                                  1e-9))
                details["phases"]["scan_batch_margin"] = {
                    "batch1_accel_qps": round(o1 / a1, 2),
                    "batch1_cpu_qps": round(o1 / c1, 2),
                    "batch1_vs_baseline": round(ratio_b1, 3),
                    "baseline_batch": base_batch,
                    f"batch{base_batch}_vs_baseline": round(ratio_bn, 3),
                }
                save_details()
                _log(f"scan margin: batch=1 ratio {ratio_b1:.3f}, "
                     f"batch={base_batch} ratio {ratio_bn:.3f}")
                _log(f"point-get: accel {ops_g / accel_g:.0f} q/s, "
                     f"cpu {ops_g / cpu_g:.0f} q/s, hits {hits_g}/{ops_g}")

                # batched write hot path (the round-7 write-side
                # tentpole): the same put workload single-request vs
                # coalesced `wb` per flush through write_multi (one
                # client_write_batch RPC per node per flush, one
                # mutation per touched partition, one group-commit
                # window), plus the server-side pair and the
                # results/state identity acceptance gate
                w_ops = max(2000, n_ops // 4)
                wb = int(os.environ.get("PEGBENCH_WRITE_BATCH", 32))
                w_identical = verify_write_batch_identity(bc, seed + 11)
                assert w_identical, \
                    "batched write results/state diverged from solo"
                run_puts(bc, 500, seed + 12, tag=b"wwarm")  # warm path
                ops_ws, errs_ws, solo_w = run_puts(bc, w_ops, seed + 13)
                ops_wb, errs_wb, batch_w = run_puts_batched(
                    bc, w_ops, seed + 14, batch=wb)
                sv_n, sv_solo_s = run_puts_server_side(
                    bc, w_ops, seed + 15, batch=0)
                _svn, sv_b_s = run_puts_server_side(
                    bc, w_ops, seed + 16, batch=wb)
                # short fsync-mode segment: the group-commit window's
                # shared fsync measured against op count, then the
                # default sync mode restored
                from pegasus_tpu.utils.flags import FLAGS as _FLAGS
                from pegasus_tpu.utils.metrics import METRICS as _MET

                fs_counter = _MET.entity("write", "node0").counter(
                    "plog_fsync_count")
                _FLAGS.set("pegasus.replica", "plog_sync_mode", "fsync")
                try:
                    fs0 = fs_counter.value()
                    run_puts_batched(bc, 1024, seed + 17, batch=wb,
                                     tag=b"wfs")
                    w_fsyncs = fs_counter.value() - fs0
                finally:
                    _FLAGS.set("pegasus.replica", "plog_sync_mode",
                               "flush")
                w_ratio = (ops_wb / batch_w) / (ops_ws / solo_w)
                details["phases"]["write_put_batch"] = {
                    "batch": wb,
                    "solo_qps": round(ops_ws / solo_w, 2),
                    "batched_qps": round(ops_wb / batch_w, 2),
                    "vs_single_request": round(w_ratio, 3),
                    "server_side_solo_qps": round(sv_n / sv_solo_s, 2),
                    f"server_side_batch{wb}_qps": round(
                        sv_n / sv_b_s, 2),
                    "server_side_speedup": round(sv_solo_s / sv_b_s, 3),
                    "errors": errs_ws + errs_wb,
                    "identical_to_solo": w_identical,
                    "meets_1_8x": w_ratio >= 1.8,
                    "fsync_mode_segment": {
                        "ops": 1024, "plog_fsyncs": w_fsyncs,
                        "fsyncs_per_op": round(w_fsyncs / 1024, 4)},
                }
                save_details()
                _log(f"write-put-batch({wb}): "
                     f"{ops_ws / solo_w:.0f} -> {ops_wb / batch_w:.0f} "
                     f"w/s client path ({w_ratio:.2f}x); server-side "
                     f"{sv_n / sv_solo_s:.0f} -> {sv_n / sv_b_s:.0f} w/s "
                     f"({sv_solo_s / sv_b_s:.2f}x); "
                     f"identical={w_identical}; fsync-mode segment: "
                     f"{w_fsyncs} fsyncs / 1024 ops")

                # round-8 filtered reads: bloom probe pruning + the
                # node row cache, measured against the UNfiltered
                # baseline IN THE SAME RUN over a deep-L0 store, with
                # byte-identity gates on both workloads (the filters'
                # whole contract is "faster, bit-for-bit the same")
                from pegasus_tpu.utils.flags import FLAGS as _F8

                f_ops = max(3000, n_ops // 2)
                fb = int(os.environ.get("PEGBENCH_FILTER_BATCH", 128))
                # deep-L0 state: 16 overlay tables — the bulk-load /
                # ingest-heavy shape (`rocksdb.usage_scenario =
                # bulk_load` turns auto-compaction OFF, so the overlay
                # grows unboundedly until the load finishes), with rows
                # interleaved across the probed keyspace
                deepen_l0(bc, n_hashkeys, seed + 21, n_l0=16,
                          rows_per_flush=min(2 * n_hashkeys, 50_000))
                miss_stream = _point_miss_stream(f_ops, n_hashkeys,
                                                 seed + 22)
                hot_stream = _point_hot_stream(f_ops, n_hashkeys,
                                               seed + 23)
                id_miss, id_hot = miss_stream[:512], hot_stream[:512]

                def _mode(bloom: bool, rc_bytes: int,
                          phash: bool = False) -> None:
                    _F8.set("pegasus.server", "bloom_probe", bloom)
                    _F8.set("pegasus.server", "phash_probe", phash)
                    _F8.set("pegasus.server", "row_cache_bytes",
                            rc_bytes)
                    for s in bc.servers:
                        s._point_cache = None  # re-plan under this mode

                def _measure(stream, reps=3, fresh_loc=False):
                    """Median-of-reps elapsed (the onebox shares the
                    host with the jax runtime; single runs jitter).
                    `fresh_loc` resets the per-generation location
                    cache before each rep: a uniform miss stream never
                    repeats a key in production, so letting rep 1's
                    locations serve reps 2-3 would measure PR 1's
                    cache, not the probe path — block caches and key
                    lists (state that IS warm in production) keep."""
                    import statistics as _stats

                    run_point_stream_server_side(bc, stream, fb)  # warm
                    out = []
                    for _ in range(reps):
                        if fresh_loc:
                            for s in bc.servers:
                                s._point_cache = None
                        _o, hits, el = run_point_stream_server_side(
                            bc, stream, fb)
                        out.append((el, hits))
                    return (_stats.median(e for e, _h in out),
                            out[0][1])

                _mode(False, 0)  # unfiltered, uncached baseline
                base_miss_id = collect_point_results(bc, id_miss, fb)
                base_hot_id = collect_point_results(bc, id_hot, fb)
                base_miss_s, m_hits = _measure(miss_stream,
                                               fresh_loc=True)
                base_hot_s, h_hits = _measure(hot_stream)
                _mode(True, 0)   # the filter layer alone (miss gate)
                miss_ident = collect_point_results(
                    bc, id_miss, fb) == base_miss_id
                flt_miss_s, m_hits_f = _measure(miss_stream,
                                                fresh_loc=True)
                _mode(True, 33_554_432)  # PR-8 production: bloom + rc
                hot_ident = collect_point_results(
                    bc, id_hot, fb) == base_hot_id
                flt_hot_s, h_hits_f = _measure(hot_stream)

                # round-15: the perfect-hash index against the PR 4
                # bloom+bisect pair — SAME run, same store, same
                # streams, byte-identity gated against the same
                # unfiltered baseline results. Indexed runs answer
                # candidacy AND location in one hash pass: misses die
                # with zero block touches, hits skip both bisects.
                _mode(True, 0, phash=True)
                ph_miss_ident = collect_point_results(
                    bc, id_miss, fb) == base_miss_id
                ph_miss_s, m_hits_p = _measure(miss_stream,
                                               fresh_loc=True)
                _mode(True, 33_554_432, phash=True)  # new production
                ph_hot_ident = collect_point_results(
                    bc, id_hot, fb) == base_hot_id
                ph_hot_s, h_hits_p = _measure(hot_stream)

                # resident index memory, same-store: what the bloom
                # bits cost vs what the phash costs, per key (the
                # bisect path ALSO lazily materializes ~key_width+64
                # bytes/row of key lists / probe tables on hot blocks
                # — memory the phash never allocates; not counted
                # here, so the phash column is its worst case)
                total_keys = bloom_b = phash_b = 0
                runs_all = runs_ph = 0
                for s in bc.servers:
                    _lsm = s.engine.lsm
                    for t in list(_lsm.l0) + list(_lsm.l1_runs):
                        total_keys += t.total_count
                        im = t.index_memory()
                        bloom_b += im["bloom"]
                        phash_b += im["phash"]
                        runs_all += 1
                        runs_ph += t.phash is not None
                index_memory = {
                    "total_keys": total_keys, "runs": runs_all,
                    "runs_with_phash": runs_ph,
                    "bloom_bytes": bloom_b, "phash_bytes": phash_b,
                    "bloom_bytes_per_key": round(
                        bloom_b / max(1, total_keys), 3),
                    "phash_bytes_per_key": round(
                        phash_b / max(1, total_keys), 3),
                }
                details["phases"]["index_memory"] = index_memory

                miss_x = base_miss_s / flt_miss_s
                hot_x = base_hot_s / flt_hot_s
                ph_miss_x = base_miss_s / ph_miss_s
                ph_hot_x = base_hot_s / ph_hot_s
                details["phases"]["point_get_miss"] = {
                    "ops": f_ops, "batch": fb,
                    "hit_rate": round(m_hits_f / f_ops, 4),
                    "unfiltered_qps": round(f_ops / base_miss_s, 2),
                    "filtered_qps": round(f_ops / flt_miss_s, 2),
                    "phash_qps": round(f_ops / ph_miss_s, 2),
                    "speedup": round(miss_x, 3),
                    "phash_speedup": round(ph_miss_x, 3),
                    "phash_vs_bloom": round(flt_miss_s / ph_miss_s, 3),
                    "meets_2x": miss_x >= 2.0,
                    "beats_bloom": ph_miss_x > miss_x,
                    "identical_to_unfiltered": bool(
                        miss_ident and m_hits == m_hits_f),
                    "phash_identical": bool(
                        ph_miss_ident and m_hits == m_hits_p),
                }
                details["phases"]["point_get_hot"] = {
                    "ops": f_ops, "batch": fb,
                    "hit_rate": round(h_hits_f / f_ops, 4),
                    "unfiltered_qps": round(f_ops / base_hot_s, 2),
                    "row_cache_qps": round(f_ops / flt_hot_s, 2),
                    "phash_qps": round(f_ops / ph_hot_s, 2),
                    "speedup": round(hot_x, 3),
                    "phash_speedup": round(ph_hot_x, 3),
                    "phash_vs_bloom": round(flt_hot_s / ph_hot_s, 3),
                    "meets_1_5x": hot_x >= 1.5,
                    "beats_bloom": ph_hot_x > hot_x,
                    "identical_to_uncached": bool(
                        hot_ident and h_hits == h_hits_f),
                    "phash_identical": bool(
                        ph_hot_ident and h_hits == h_hits_p),
                }
                save_details()
                with open(os.path.join(here, "BENCH_r08.json"), "w") as f:
                    json.dump({"phases": {
                        "point_get_miss":
                            details["phases"]["point_get_miss"],
                        "point_get_hot":
                            details["phases"]["point_get_hot"],
                    }, "accel_platform": accel.platform}, f, indent=1)
                with open(os.path.join(here, "BENCH_r15.json"), "w") as f:
                    json.dump({"phases": {
                        "point_get_miss":
                            details["phases"]["point_get_miss"],
                        "point_get_hot":
                            details["phases"]["point_get_hot"],
                        "index_memory": index_memory,
                    }, "accel_platform": accel.platform}, f, indent=1)
                _log(f"point-get-miss: {f_ops / base_miss_s:.0f} -> "
                     f"{f_ops / flt_miss_s:.0f} (bloom, {miss_x:.2f}x)"
                     f" -> {f_ops / ph_miss_s:.0f} q/s (phash, "
                     f"{ph_miss_x:.2f}x, identical={ph_miss_ident}); "
                     f"point-get-hot: {f_ops / base_hot_s:.0f} -> "
                     f"{f_ops / flt_hot_s:.0f} (bloom+rc, {hot_x:.2f}x)"
                     f" -> {f_ops / ph_hot_s:.0f} q/s (phash+rc, "
                     f"{ph_hot_x:.2f}x, identical={ph_hot_ident}); "
                     f"index_memory: bloom "
                     f"{index_memory['bloom_bytes_per_key']} B/key vs "
                     f"phash {index_memory['phash_bytes_per_key']} "
                     f"B/key over {total_keys} keys")

                if do_compact:
                    gb = float(os.environ.get("PEGBENCH_COMPACT_GB", "1.0"))
                    exp_frac = float(os.environ.get("PEGBENCH_EXPIRED",
                                                    "0.5"))
                    for mode in ("ttl", "rules"):
                        a_g, a_s, a_in, a_out = measure_compaction_scaled(
                            jax, accel, tmpdir, mode, gb, exp_frac, seed)
                        _log(f"compact[{mode}]: accel {a_g:.3f} GB/s "
                             f"({a_s:.1f}s, {a_in / 1e9:.2f} GB -> "
                             f"{a_out / 1e9:.2f} GB)")
                        c_g, c_s, _c_in, _c_out = measure_compaction_scaled(
                            jax, cpu, tmpdir, mode, gb, exp_frac, seed)
                        _log(f"compact[{mode}]: cpu   {c_g:.3f} GB/s "
                             f"({c_s:.1f}s)")
                        details["phases"][f"compact_{mode}"] = {
                            "accel_gbps": round(a_g, 4),
                            "cpu_gbps": round(c_g, 4),
                            "vs_baseline": round(a_g / c_g, 3) if c_g else 0,
                            "input_gb": round(a_in / 1e9, 3),
                            "output_gb": round(a_out / 1e9, 3),
                            "expired_frac": exp_frac if mode == "ttl"
                            else 0.05,
                            "accel_seconds": round(a_s, 2),
                            "cpu_seconds": round(c_s, 2),
                        }
                        save_details()

                if do_compressed:
                    # round-11: compressed SST output + direct compute.
                    # Single-backend phases (the codec work is host-side
                    # by design — deflate/inflate and the encoded probes
                    # never touch the device), so each runs once on the
                    # serving backend and compares codec none vs dcz
                    # same-run.
                    gb = float(os.environ.get(
                        "PEGBENCH_COMPRESSED_GB", "1.0"))
                    exp_frac = float(os.environ.get("PEGBENCH_EXPIRED",
                                                    "0.5"))
                    cc = measure_compressed_compact(
                        jax, accel, tmpdir, gb, exp_frac, seed)
                    details["phases"]["compact_compressed"] = cc
                    save_details()
                    _log(f"compact_compressed: effective "
                         f"{cc['dcz']['effective_input_gb_per_s']:.3f} "
                         f"GB/s vs {cc['none']['effective_input_gb_per_s']:.3f}"
                         f" uncompressed ({cc['effective_speedup']:.2f}x,"
                         f" ratio {cc['dcz']['output_compression_ratio']}"
                         f", identical={cc['identity_ok']})")
                    cs = measure_compressed_scan(
                        jax, accel, tmpdir,
                        min(n_records, 200_000), n_partitions,
                        n_ops, seed)
                    details["phases"]["scan_compressed"] = cs
                    save_details()
                    _log(f"scan_compressed: dcz "
                         f"{cs['dcz']['ops_per_s']:.0f} vs none "
                         f"{cs['none']['ops_per_s']:.0f} ops/s "
                         f"({cs['ops_ratio_dcz_vs_none']:.3f}x, disk "
                         f"{cs['disk_ratio']:.3f}, "
                         f"identical={cs['identity_ok']})")

                if do_pushdown:
                    # scan pushdown: server-side value filter +
                    # aggregates vs the same work client-side, swept
                    # across selectivities (host-side kernels — one
                    # serving backend, same-run comparison)
                    sp = measure_scan_pushdown(
                        jax, accel, tmpdir,
                        min(n_records, 100_000), n_partitions, seed)
                    details["phases"]["scan_pushdown"] = sp
                    save_details()
                    _log(f"scan_pushdown: {sp['pushdown_speedup']:.2f}x "
                         f"at sel 0.1 (0.9: "
                         f"{sp['sel_0.9']['pushdown_speedup']:.2f}x, "
                         f"0.01: "
                         f"{sp['sel_0.01']['pushdown_speedup']:.2f}x), "
                         f"identical={sp['identity_ok']}, agg wire "
                         f"O(parts)={sp['agg_wire_o_partitions']}")

                if do_pipeline:
                    # round-12: staged compaction pipeline, serial vs
                    # pipelined same-run (single backend — the overlap
                    # is host-side disk/CPU/filter; the device leg is
                    # inside the filter stage either way)
                    gb = float(os.environ.get(
                        "PEGBENCH_PIPELINE_GB", "1.0"))
                    exp_frac = float(os.environ.get("PEGBENCH_EXPIRED",
                                                    "0.5"))
                    pc = measure_pipelined_compact(
                        jax, accel, tmpdir, gb, exp_frac, seed)
                    details["phases"]["compact_pipelined"] = pc
                    save_details()
                    _log(f"compact_pipelined: "
                         f"{pc['pipelined']['input_gb_per_s']:.3f} vs "
                         f"{pc['serial']['input_gb_per_s']:.3f} GB/s "
                         f"serial ({pc['speedup']:.2f}x, "
                         f"identical={pc['identity_ok']})")

                if do_mixed:
                    ml = measure_mixed_load(jax, accel, tmpdir, seed)
                    details["phases"]["mixed_load"] = ml
                    save_details()
                    _log(f"mixed_load: p99 on/off "
                         f"{ml.get('p99_ratio_on_vs_off')}; forward "
                         f"progress={ml['forward_progress_ok']}")

                if do_trace:
                    to = measure_trace_overhead(tmpdir, seed)
                    details["phases"]["trace_overhead"] = to
                    save_details()
                    _log(f"trace_overhead: ratio-0 read "
                         f"{to['ratio0_read_overhead']:+.2%} / write "
                         f"{to['ratio0_write_overhead']:+.2%} vs "
                         f"no-tracing baseline (gate<=2%: "
                         f"{to['gate_ok']}, "
                         f"identical={to['identity_ok']})")

                if do_health:
                    ho = measure_health_overhead(tmpdir, seed)
                    details["phases"]["health_overhead"] = ho
                    save_details()
                    _log(f"health_overhead: tick {ho['tick_ms']}ms -> "
                         f"{ho['cadence_overhead']:.2%} of a core at "
                         f"the default cadence (sim A/B read "
                         f"{ho['read_overhead']:+.2%} / write "
                         f"{ho['write_overhead']:+.2%} at ~1000x "
                         f"cadence, rings {ho['ring_bytes_total']}B, "
                         f"events={ho['events_fired']}, gate<=2%: "
                         f"{ho['gate_ok']}, "
                         f"identical={ho['identity_ok']})")

                if do_perfctx:
                    po = measure_perfctx_overhead(tmpdir, seed)
                    details["phases"]["perfctx_overhead"] = po
                    save_details()
                    _log(f"perfctx_overhead: contexts-on read "
                         f"{po['read_overhead']:+.2%} / scan "
                         f"{po['scan_overhead']:+.2%} vs hard-off "
                         f"(gate<=2%: {po['gate_ok']}, "
                         f"identical={po['identity_ok']})")

                if do_qos:
                    qi = measure_qos_isolation(tmpdir, seed)
                    details["phases"]["qos_isolation"] = qi
                    save_details()
                    with open(os.path.join(here, "BENCH_r20.json"),
                              "w") as f:
                        json.dump({"phases": {"qos_isolation": qi},
                                   "accel_platform": accel.platform},
                                  f, indent=1)
                    _log(f"qos_isolation: admission read "
                         f"{qi['admission_read_overhead']:+.2%} / scan "
                         f"{qi['admission_scan_overhead']:+.2%} "
                         f"enforce-on vs off; compliant p99 "
                         f"{qi['abuser_off']['compliant_p99_ms']}ms solo"
                         f" -> {qi['abuser_on']['compliant_p99_ms']}ms "
                         f"under abuse ({qi['compliant_p99_ratio']}x, "
                         f"abuser overbudget="
                         f"{qi['abuser_on']['abuser_overbudget']}, "
                         f"identical={qi['identity_ok']}, "
                         f"gate: {qi['gate_ok']})")

                if do_follower:
                    fr = measure_follower_read(tmpdir, seed)
                    details["phases"]["follower_read"] = fr
                    save_details()
                    with open(os.path.join(here, "BENCH_r17.json"),
                              "w") as f:
                        json.dump({"phases": {"follower_read": fr},
                                   "accel_platform": accel.platform},
                                  f, indent=1)
                    _log(f"follower_read: aggregate "
                         f"{fr['linearizable']['aggregate_read_qps']} "
                         f"-> {fr['follower']['aggregate_read_qps']} "
                         f"q/s ({fr['speedup']}x, "
                         f"{fr['follower']['serving_replicas']} serving"
                         f" replicas, bounces={fr['stale_bounces']}, "
                         f"identical={fr['identity_ok']}, "
                         f"gate>=2x: {fr['gate_ok']})")

                if do_dup:
                    dc = measure_dup_catchup(tmpdir, seed)
                    details["phases"]["dup_catchup"] = dc
                    save_details()
                    _log(f"dup_catchup: batched+compressed "
                         f"{dc['batched']['catchup_sim_s']}s vs solo "
                         f"{dc['solo']['catchup_sim_s']}s sim "
                         f"({dc['speedup_sim']}x, wire ratio "
                         f"{dc['batched']['compression_ratio']}, "
                         f"governed backoffs "
                         f"{dc['governed']['governor_backoffs']}, "
                         f"identical={dc['identity_ok']}, "
                         f"gate={dc['gate_ok']})")

                if do_mesh:
                    ms = measure_mesh_scan(here)
                    details["phases"]["mesh_scan"] = ms
                    save_details()
                    with open(os.path.join(here, "BENCH_r18.json"),
                              "w") as f:
                        json.dump({"phases": {"mesh_scan": ms},
                                   "accel_platform": "cpu-mesh"},
                                  f, indent=1)
                    _log(f"mesh_scan: wave {ms['host_wave_ms']}ms host "
                         f"-> {ms['mesh_wave_ms']}ms mesh "
                         f"({ms['mesh_speedup']}x, agg "
                         f"{ms['agg_speedup']}x) over "
                         f"{ms['partitions']} partitions / "
                         f"{ms['devices']} devices, identical="
                         f"{ms['rows_identity_ok']}, watchdog fallback "
                         f"identical={ms['watchdog']['fallback_identity_ok']}"
                         f", gate>=1.5x: {ms['gate_ok']}")

                if do_mesh_compact:
                    mc = measure_mesh_compact(here)
                    details["phases"]["mesh_compact"] = mc
                    save_details()
                    with open(os.path.join(here, "BENCH_r19.json"),
                              "w") as f:
                        json.dump({"phases": {"mesh_compact": mc},
                                   "accel_platform": "cpu-mesh"},
                                  f, indent=1)
                    _log(f"mesh_compact: filter "
                         f"{mc['host_filter_ms']}ms host -> "
                         f"{mc['mesh_filter_ms']}ms mesh "
                         f"({mc['filter_speedup']}x over "
                         f"{mc['partitions']} partitions, "
                         f"{mc['host_windows']} windows -> "
                         f"{mc['dispatches']} dispatch), digests "
                         f"identical={mc['digest_identity_ok']}, wedged "
                         f"identical={mc['wedged_digest_ok']}, refresh "
                         f"reuses={mc['refresh_reuses']}, gate>=1.5x: "
                         f"{mc['gate_ok']}")

                if do_geo:
                    g_accel, g_hits = measure_geo(jax, accel)
                    g_cpu, _ = measure_geo(jax, cpu)
                    details["phases"]["geo_radius_search"] = {
                        "accel_qps": round(g_accel, 2),
                        "cpu_qps": round(g_cpu, 2),
                        "vs_baseline": round(g_accel / g_cpu, 3) if g_cpu
                        else 0,
                        "hits": g_hits,
                    }
                    save_details()
                    _log(f"geo: accel {g_accel:.1f} q/s, cpu {g_cpu:.1f} q/s")

            except Exception as e:  # noqa: BLE001 - phase isolation
                phase_error = f"{type(e).__name__}: {e}"[:300]
                details["error_phase"] = phase_error
                save_details()
                _log(f"later phase failed ({phase_error}) — emitting "
                     "the already-measured scan result")

            out = {
                "metric": "YCSB-E scan ops/sec/chip (64-partition, "
                          "TTL+hash-validated)",
                "value": round(accel_qps, 2),
                "unit": "ops/s",
                "vs_baseline": round(accel_qps / cpu_qps, 3)
                if cpu_qps else 0,
            }
            if phase_error:
                out["error_phase"] = phase_error
            if accel_error:
                out["error"] = accel_error
                out["platform"] = "cpu-fallback"
            print(json.dumps(out))
        finally:
            bc.close()


if __name__ == "__main__":
    if "--mesh-phase" in sys.argv[1:]:
        _mesh_phase_main()
    elif "--mesh-compact-phase" in sys.argv[1:]:
        _mesh_compact_phase_main()
    else:
        main()
