#!/usr/bin/env python
# Diagnostic sidecar (not part of the framework): reproduces the tunnel
# transfer measurements that motivated the MaskPrefresher design.
"""Is the ~85ms fetch per-array or per-sync-round? Test batched fetch
strategies for N fresh computation results."""
import time

import jax
import jax.numpy as jnp
import numpy as np

dev = [d for d in jax.devices() if d.platform != "cpu"][0]
f = jax.jit(lambda x, s: (x + s > 0).astype(jnp.uint8))

with jax.default_device(dev):
    xs = [jnp.zeros((16384,), dtype=jnp.int32) + i for i in range(8)]
    for x in xs:
        x.block_until_ready()

    def fresh(i):
        return [f(x, i) for x in xs]  # 8 fresh results

    # warm compile
    jax.block_until_ready(fresh(0))

    t0 = time.perf_counter()
    outs = fresh(1)
    res = [np.asarray(o) for o in outs]
    print(f"8x asarray loop: {(time.perf_counter()-t0)*1000:.1f} ms",
          flush=True)

    t0 = time.perf_counter()
    outs = fresh(2)
    res = jax.device_get(outs)
    print(f"device_get(list of 8): {(time.perf_counter()-t0)*1000:.1f} ms",
          flush=True)

    cat = jax.jit(lambda *a: jnp.concatenate(a))
    jax.block_until_ready(cat(*fresh(3)))
    t0 = time.perf_counter()
    outs = fresh(4)
    res = np.asarray(cat(*outs))
    print(f"device concat + 1 asarray: "
          f"{(time.perf_counter()-t0)*1000:.1f} ms", flush=True)

    # copy_to_host_async then gather
    t0 = time.perf_counter()
    outs = fresh(5)
    for o in outs:
        o.copy_to_host_async()
    res = [np.asarray(o) for o in outs]
    print(f"copy_to_host_async + gather: "
          f"{(time.perf_counter()-t0)*1000:.1f} ms", flush=True)

    # 64 arrays, async-copy strategy
    f2 = jax.jit(lambda x, s: (x + s > 0).astype(jnp.uint8))
    xs64 = [jnp.zeros((16384,), dtype=jnp.int32) + i for i in range(64)]
    for x in xs64:
        x.block_until_ready()
    outs = [f2(x, 0) for x in xs64]
    jax.block_until_ready(outs)
    t0 = time.perf_counter()
    outs = [f2(x, 1) for x in xs64]
    for o in outs:
        o.copy_to_host_async()
    res = [np.asarray(o) for o in outs]
    print(f"64 arrays async-copy+gather: "
          f"{(time.perf_counter()-t0)*1000:.1f} ms", flush=True)
