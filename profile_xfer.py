#!/usr/bin/env python
# Diagnostic sidecar (not part of the framework): reproduces the tunnel
# transfer measurements that motivated the MaskPrefresher design.
"""Characterize device->host transfer cost through the axon tunnel."""
import time

import jax
import jax.numpy as jnp
import numpy as np

dev = [d for d in jax.devices() if d.platform != "cpu"][0]
print(f"device: {dev}", flush=True)


def bench(label, fn, n=20):
    fn()  # warm
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    print(f"{label}: {(time.perf_counter()-t0)/n*1000:.2f} ms", flush=True)


with jax.default_device(dev):
    for dtype in ("bool", "uint8", "int32"):
        for size in (2048, 16384, 1 << 20):
            x = jnp.zeros((size,), dtype=dtype)
            x.block_until_ready()
            bench(f"asarray {dtype}[{size}]", lambda x=x: np.asarray(x))
    x = jnp.zeros((16384,), dtype="bool")
    y = jnp.zeros((16384,), dtype="bool")
    bench("two separate bool[16384]",
          lambda: (np.asarray(x), np.asarray(y)))
    xy = jnp.stack([x, y])
    bench("one stacked bool[2,16384]", lambda: np.asarray(xy))
    # device_get vs asarray
    bench("device_get bool[16384]", lambda: jax.device_get(x))
    # packed: 16384 bools -> 2048 uint8 on device, then download
    pack = jax.jit(lambda m: jnp.packbits(m))
    p = pack(x)
    p.block_until_ready()
    bench("packbits+download uint8[2048]",
          lambda: np.asarray(pack(x)))
    # jit returning bool vs uint8
    f_bool = jax.jit(lambda a: a > 0)
    f_u8 = jax.jit(lambda a: (a > 0).astype(jnp.uint8))
    a = jnp.zeros((16384,), dtype=jnp.int32)
    f_bool(a).block_until_ready(); f_u8(a).block_until_ready()
    bench("jit->bool[16384] download", lambda: np.asarray(f_bool(a)))
    bench("jit->uint8[16384] download", lambda: np.asarray(f_u8(a)))
