"""bench_report: the perf-trajectory table folded from BENCH_r*.json
artifacts (phase × round → headline metric, ratio vs the prior round
that measured the same metric)."""

import json
import os

from pegasus_tpu.tools.bench_report import (
    headline,
    load_rounds,
    main,
    render,
    trajectory,
)


def _write_round(d, n, phases):
    with open(os.path.join(d, f"BENCH_r{n:02d}.json"), "w") as f:
        json.dump({"phases": phases}, f)


def _fixture(tmp_path):
    d = str(tmp_path)
    _write_round(d, 5, {
        "scan": {"accel_qps": 10000.0, "ops": 5000},
        "point_get": {"accel_qps": 30000.0},
    })
    _write_round(d, 7, {
        "scan": {"accel_qps": 12000.0, "ops": 5000},
        "write_put_batch": {"batched_qps": 7900.0, "solo_qps": 2800.0},
    })
    _write_round(d, 8, {
        # headline RENAMED: the ratio chain restarts instead of
        # comparing a filtered number against an unfiltered one
        "point_get": {"filtered_qps": 18000.0, "unfiltered_qps": 8000.0},
        "scan": {"accel_qps": 6000.0},
    })
    return d


def test_headline_preference_order():
    assert headline({"ops": 5, "accel_qps": 123.0}) == ("accel_qps",
                                                        123.0)
    assert headline({"filtered_qps": 2.0, "accel_qps": 1.0})[0] == \
        "filtered_qps"
    assert headline({"meets_2x": True}) is None  # bools never qualify
    assert headline({"records": 10})[0] == "records"  # fallback


def test_trajectory_rounds_ratios_and_rename(tmp_path):
    d = _fixture(tmp_path)
    rep = trajectory(d)
    assert rep["rounds"] == [5, 7, 8]
    scan = rep["phases"]["scan"]
    assert [r["round"] for r in scan] == [5, 7, 8]
    assert scan[0]["ratio"] is None
    assert scan[1]["ratio"] == 1.2
    assert scan[2]["ratio"] == 0.5
    pg = rep["phases"]["point_get"]
    assert pg[0]["metric"] == "accel_qps" and pg[0]["ratio"] is None
    # renamed headline: no cross-metric ratio
    assert pg[1]["metric"] == "filtered_qps" and pg[1]["ratio"] is None
    # single-round phase still appears
    assert rep["phases"]["write_put_batch"][0]["value"] == 7900.0


def test_torn_artifact_is_skipped_not_fatal(tmp_path):
    d = _fixture(tmp_path)
    with open(os.path.join(d, "BENCH_r09.json"), "w") as f:
        f.write("{torn")
    rounds = load_rounds(d)
    assert [r for r, _p in rounds] == [5, 7, 8]


def test_render_and_main(tmp_path, capsys):
    d = _fixture(tmp_path)
    text = render(trajectory(d))
    assert "scan:" in text and "(1.200x)" in text
    assert main(["--dir", d]) == 0
    assert "perf trajectory" in capsys.readouterr().out
    assert main([str(tmp_path / "empty")]) == 1 \
        if os.path.isdir(str(tmp_path / "empty")) else True
    # real repo artifacts parse too (the tool's actual deployment)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rep = trajectory(repo)
    assert rep["phases"], "repo BENCH_r*.json artifacts unreadable"
