"""Shell command breadth (parity: src/shell/main.cpp's 87-command
surface): extended data verbs, offline forensics, codec tools, the
interactive REPL, and admin verbs over a real multi-process onebox.
"""

import json
import os
import shutil
import time

import pytest

from pegasus_tpu.tools.shell import main as shell_main


def run(capsys, *argv):
    code = shell_main(list(argv))
    return code, capsys.readouterr().out


@pytest.fixture
def root(tmp_path, capsys):
    root = str(tmp_path / "box")
    assert shell_main(["--root", root, "create_app", "demo",
                       "-p", "4"]) == 0
    capsys.readouterr()
    return root


def test_check_and_set_and_mutate(root, capsys):
    code, out = run(capsys, "--root", root, "check_and_set", "demo",
                    "h", "ck", "not_exist", "", "sk", "v1")
    assert code == 0 and "set" in out
    # check now fails: ck still absent is false? ck was never written —
    # set it, then not_exist fails
    assert run(capsys, "--root", root, "set", "demo", "h", "ck",
               "present")[0] == 0
    code, out = run(capsys, "--root", root, "check_and_set", "demo",
                    "h", "ck", "not_exist", "", "sk", "v2")
    assert "not set" in out
    code, out = run(capsys, "--root", root, "check_and_set", "demo",
                    "h", "ck", "match_prefix", "pre", "sk", "v3")
    assert "set" in out and "not set" not in out
    # check_and_mutate: put two, delete one
    code, out = run(capsys, "--root", root, "check_and_mutate", "demo",
                    "h", "ck", "exist", "", "m1=a", "m2=b", "del:sk")
    assert code == 0 and "mutated" in out
    code, out = run(capsys, "--root", root, "get", "demo", "h", "m1")
    assert out.strip() == "a"
    code, out = run(capsys, "--root", root, "exist", "demo", "h", "sk")
    assert out.strip() == "false"


def test_multi_range_verbs(root, capsys):
    kvs = ["s%02d=v%d" % (i, i) for i in range(10)]
    assert run(capsys, "--root", root, "multi_set", "demo", "h",
               *kvs)[0] == 0
    code, out = run(capsys, "--root", root, "multi_get_range", "demo",
                    "h", "--start", "s02", "--stop", "s05")
    assert code == 0 and "s02" in out and "s05" not in out
    code, out = run(capsys, "--root", root, "multi_get_sortkeys",
                    "demo", "h")
    assert "10 sort key(s)" in out
    code, out = run(capsys, "--root", root, "hash_scan", "demo", "h",
                    "--start", "s03", "--stop", "s07")
    assert "s03" in out and "4 record(s)" in out
    code, out = run(capsys, "--root", root, "multi_del", "demo", "h",
                    "s00", "s01")
    assert "deleted 2" in out
    code, out = run(capsys, "--root", root, "multi_del_range", "demo",
                    "h", "--start", "s02", "--stop", "s04")
    assert "deleted 2" in out
    code, out = run(capsys, "--root", root, "count", "demo", "h")
    assert out.strip() == "6"


def test_multi_del_range_paginates_past_read_limit(root, capsys):
    """Ranges larger than the server's one-shot read budget (the
    INCOMPLETE cap) must delete everything via pagination."""
    kvs = ["s%04d=v" % i for i in range(1500)]
    # multi_set in chunks (arg list size)
    for off in range(0, 1500, 500):
        assert run(capsys, "--root", root, "multi_set", "demo", "big",
                   *kvs[off:off + 500])[0] == 0
    code, out = run(capsys, "--root", root, "multi_del_range", "demo",
                    "big")
    assert code == 0 and "deleted 1500" in out
    code, out = run(capsys, "--root", root, "count", "demo", "big")
    assert out.strip() == "0"


def test_sortkeys_resume_across_all_expired_run(root, capsys):
    """An expired-but-uncompacted run longer than the one-shot read
    budget must not truncate multi_get_sortkeys: the server's
    resume_sort_key lets the client page THROUGH a fully-filtered page."""
    import time as _time

    from pegasus_tpu.tools.onebox import Onebox

    box = Onebox(root)
    try:
        c = box.client("demo")
        # >1000 (the read budget) doomed records, then live ones AFTER
        # them in sort order
        for i in range(1100):
            assert c.set(b"exp", b"a%04d" % i, b"v",
                         ttl_seconds=1) == 0
        for i in range(30):
            assert c.set(b"exp", b"z%02d" % i, b"v") == 0
        _time.sleep(1.2)  # the run expires in place (no compaction)
        err, sks = c.multi_get_sortkeys(b"exp")
        assert err == 0
        assert sks == [b"z%02d" % i for i in range(30)]
    finally:
        box.close()


def test_check_and_mutate_rejects_ambiguous_token(root, capsys):
    assert run(capsys, "--root", root, "set", "demo", "h", "ck",
               "x")[0] == 0
    # no '=' and no del: prefix -> error, nothing executed
    code, out = run(capsys, "--root", root, "check_and_mutate", "demo",
                    "h", "ck", "exist", "", "forgot_equals")
    assert code == 1
    # del: prefix deletes; sk= puts an empty value
    assert run(capsys, "--root", root, "set", "demo", "h", "gone",
               "x")[0] == 0
    code, out = run(capsys, "--root", root, "check_and_mutate", "demo",
                    "h", "ck", "exist", "", "del:gone", "empty=")
    assert code == 0 and "mutated" in out
    code, out = run(capsys, "--root", root, "exist", "demo", "h",
                    "gone")
    assert out.strip() == "false"
    code, out = run(capsys, "--root", root, "exist", "demo", "h",
                    "empty")
    assert out.strip() == "true"


def test_full_scan_copy_clear_count(root, capsys):
    for i in range(12):
        assert run(capsys, "--root", root, "set", "demo",
                   "hk%d" % i, "s", "v%d" % i)[0] == 0
    code, out = run(capsys, "--root", root, "count_data", "demo")
    assert out.strip() == "12"
    code, out = run(capsys, "--root", root, "full_scan", "demo",
                    "--max", "5")
    assert "5 record(s)" in out
    assert run(capsys, "--root", root, "create_app", "copy",
               "-p", "2")[0] == 0
    code, out = run(capsys, "--root", root, "copy_data", "demo", "copy")
    assert "copied 12" in out
    code, out = run(capsys, "--root", root, "count_data", "copy")
    assert out.strip() == "12"
    code, out = run(capsys, "--root", root, "clear_data", "copy")
    assert code == 1 and "force" in out
    code, out = run(capsys, "--root", root, "clear_data", "copy",
                    "--force")
    assert "deleted 12" in out
    code, out = run(capsys, "--root", root, "count_data", "copy")
    assert out.strip() == "0"


def test_hash_and_codec_tools(root, capsys):
    code, out = run(capsys, "--root", root, "hash", "demo", "hk", "sk")
    assert code == 0 and "partition:" in out and "key_hash" in out
    code, hex_out = run(capsys, "rdb_key_str2hex", "hk", "sk")
    assert code == 0
    code, out = run(capsys, "rdb_key_hex2str", hex_out.strip())
    assert "hash_key: hk" in out and "sort_key: sk" in out
    # value: [u32 expire_ts][data] v1 layout via a real stored value
    from pegasus_tpu.base.value_schema import generate_value

    raw = generate_value(1, b"payload", 0)
    code, out = run(capsys, "rdb_value_hex2str", raw.hex())
    assert "payload" in out


def test_local_get_offline(root, capsys, tmp_path):
    assert run(capsys, "--root", root, "set", "demo", "off", "s",
               "offline-value")[0] == 0
    assert run(capsys, "--root", root, "flush", "demo")[0] == 0
    # find the partition dir holding the key
    from pegasus_tpu.base.key_schema import key_hash_parts

    pidx = key_hash_parts(b"off", b"s") % 4
    sst_dir = None
    for dirpath, dirnames, filenames in os.walk(root):
        # partition dirs are "<app_id>.<pidx>/sst"
        if (os.path.basename(dirpath) == "sst"
                and os.path.dirname(dirpath).endswith(f".{pidx}")
                and filenames):
            sst_dir = dirpath
    assert sst_dir, f"no sst dir for p{pidx} under {root}"
    code, out = run(capsys, "local_get", sst_dir, "off", "s")
    assert code == 0 and "offline-value" in out
    code, out = run(capsys, "local_get", sst_dir, "nope", "s")
    assert code == 1


def test_repl(root, capsys, monkeypatch):
    # find an sst file to prove offline verbs work inside the REPL
    assert run(capsys, "--root", root, "set", "demo", "rk", "s",
               "rv")[0] == 0
    assert run(capsys, "--root", root, "flush", "demo")[0] == 0
    sst = None
    for dirpath, _dn, filenames in os.walk(root):
        for f in filenames:
            if f.endswith(".sst"):
                sst = os.path.join(dirpath, f)
    assert sst
    lines = iter(["use demo", "set hk sk repl-value", "get hk sk",
                  "hash hk sk", "version", "help", "bogus_verb",
                  f"sst_dump {sst}", "exit"])
    monkeypatch.setattr("builtins.input",
                        lambda prompt="": next(lines))
    assert shell_main(["--root", root, "-i"]) == 0
    # without -i on a non-tty stdin, the missing verb fails loudly
    # instead of dropping into an accidental REPL
    out = capsys.readouterr().out
    assert "using demo" in out
    assert "repl-value" in out
    assert "key_hash" in out
    assert "full_scan" in out  # help listing
    assert "records" in out  # sst_dump ran offline inside the REPL


def test_admin_verbs_over_wire(tmp_path, capsys):
    """Admin breadth against a real 1-meta + 2-replica process cluster."""
    from pegasus_tpu.tools import onebox_cluster as ob
    from pegasus_tpu.utils.errors import PegasusError

    d = str(tmp_path / "onebox")
    shutil.rmtree(d, ignore_errors=True)
    ob.start(d, n_replica=2)
    try:
        admin = ob.OneboxAdmin(d)
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                if len(admin.call("list_nodes", timeout=6)) == 2:
                    break
            except PegasusError:
                pass
            time.sleep(0.5)
        admin.create_table("wt", partition_count=2, replica_count=2)
        admin.close()
        c = ob.connect("wt", d)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                if c.set(b"k", b"s", b"v") == 0:
                    break
            except PegasusError:
                time.sleep(1)
        c.net.close()

        code, out = run(capsys, "--cluster", d, "cluster_info")
        info = json.loads(out)
        assert info["app_count"] == 1 and len(info["alive_nodes"]) == 2
        code, out = run(capsys, "--cluster", d, "server_info")
        assert code == 0 and "replica_count" in out
        code, out = run(capsys, "--cluster", d, "get_meta_level")
        assert out.strip() == "steady"
        code, out = run(capsys, "--cluster", d, "set_meta_level",
                        "lively")
        assert out.strip() == "lively"
        code, out = run(capsys, "--cluster", d, "get_replica_count",
                        "wt")
        assert out.strip() == "2"
        code, out = run(capsys, "--cluster", d, "app_stat", "wt")
        assert code == 0 and '"gpid"' in out
        code, out = run(capsys, "--cluster", d, "app_disk", "wt")
        assert "total:" in out
        code, out = run(capsys, "--cluster", d, "ddd_diagnose")
        assert code == 0
        code, out = run(capsys, "--cluster", d, "hot_partitions", "wt")
        assert code == 0 and '"cu_rate"' in out and "node_load" in out
        code, out = run(capsys, "--cluster", d, "rename", "wt", "wt2")
        assert "OK" in out
        code, out = run(capsys, "--cluster", d, "ls")
        assert "wt2" in out
        code, out = run(capsys, "--cluster", d, "flush_log", "node0")
        assert "flushed" in out
        code, out = run(capsys, "--cluster", d, "set", "wt2", "a",
                        "s", "x")
        assert code == 0
        code, out = run(capsys, "--cluster", d, "full_scan", "wt2")
        assert code == 0 and "record(s)" in out
    finally:
        ob.stop(d)


def test_reference_verb_aliases(root, capsys):
    """The reference's verb spellings work: create/drop/recall/balance/
    local_partition_split/query_bulk_load_status (commands.h names)."""
    assert run(capsys, "--root", root, "create", "ali", "-p", "2")[0] == 0
    assert run(capsys, "--root", root, "set", "ali", "h", "s", "v")[0] == 0
    code, out = run(capsys, "--root", root, "local_partition_split", "ali")
    assert code == 0
    assert run(capsys, "--root", root, "drop", "ali")[0] == 0


def test_atomic_idempotent_verbs(root, capsys):
    code, out = run(capsys, "--root", root, "get_atomic_idempotent",
                    "demo")
    assert code == 0 and "false" in out
    assert run(capsys, "--root", root, "enable_atomic_idempotent",
               "demo")[0] == 0
    code, out = run(capsys, "--root", root, "get_atomic_idempotent",
                    "demo")
    assert code == 0 and "true" in out
    assert run(capsys, "--root", root, "disable_atomic_idempotent",
               "demo")[0] == 0


def test_repl_settings_and_cc(root, capsys, monkeypatch, tmp_path):
    other = str(tmp_path / "box2")
    assert shell_main(["--root", other, "create_app", "t2",
                       "-p", "2"]) == 0
    capsys.readouterr()
    lines = iter(["mycluster", "timeout 30", "timeout",
                  "escape_all true", "use demo",
                  "set ek s ÿ-bin", "get ek s",
                  "escape_all false",
                  f"cc {other}", "mycluster", "use t2",
                  "set a b c", "get a b", "exit"])
    monkeypatch.setattr("builtins.input", lambda prompt="": next(lines))
    assert shell_main(["--root", root, "-i"]) == 0
    out = capsys.readouterr().out
    assert "30.0s" in out
    assert "escape_all: true" in out
    assert "\\xc3\\xbf-bin" in out   # escaped utf-8 bytes of ÿ
    assert other in out              # cc switched, mycluster shows it
    assert "c" in out
