"""Columnar response pages + multi-flavor batched scan evaluation.

Covers the round-3 serving-path redesign:
- native batched gather/serialize (server/page.py over
  native/packer.cpp pegasus_gather_page) vs the pure-Python twin
- ScanPage sequence protocol + O(1) wire codec round-trip
- scan_multi batches mixing filter FLAVORS: one multi-flavor device
  program (ops/predicates.multi_static_block_predicate), responses
  equal to solo serving
"""

import numpy as np
import pytest

from pegasus_tpu.base.key_schema import generate_key, restore_key
from pegasus_tpu.base.value_schema import epoch_now
from pegasus_tpu.client import PegasusClient, Table
from pegasus_tpu.ops.predicates import (
    FT_MATCH_ANYWHERE,
    FT_MATCH_PREFIX,
    FilterSpec,
    multi_static_block_predicate,
    static_block_predicate,
)
from pegasus_tpu.server import scan_coordinator as sc
from pegasus_tpu.server.page import build_page, _gather_python
from pegasus_tpu.server.types import GetScannerRequest, KeyValue, ScanPage
from pegasus_tpu.storage.sstable import Block


def _make_block(n=32, w=32, hdr=4):
    keys = np.zeros((n, w), dtype=np.uint8)
    key_len = np.zeros(n, dtype=np.int32)
    offs = np.zeros(n + 1, dtype=np.uint32)
    heap = bytearray()
    for i in range(n):
        k = generate_key(b"hk%02d" % (i % 4), b"s%03d" % i)
        keys[i, :len(k)] = np.frombuffer(k, dtype=np.uint8)
        key_len[i] = len(k)
        offs[i] = len(heap)
        heap += b"\x00" * hdr + b"value-%04d" % i
    offs[n] = len(heap)
    ets = (np.arange(n) * 7).astype(np.uint32)
    return Block(keys, key_len, ets, None, np.zeros(n, np.uint8), offs,
                 bytes(heap))


def test_build_page_matches_python_gather():
    blk = _make_block()
    take = np.array([1, 4, 9, 30], dtype=np.int64)
    page, size, last = build_page([(blk, take)], hdr=4, want_ets=True)
    assert len(page) == 4
    for j, row in enumerate(take):
        assert page.key_at(j) == blk.key_at(int(row))
        assert page.value_at(j) == b"value-%04d" % row
        assert page.ets_at(j) == int(blk.expire_ts[row])
    assert last == blk.key_at(30)
    assert size == sum(len(blk.key_at(int(r))) + 10 for r in take)

    # python twin produces identical blobs
    n = len(take)
    ko = np.zeros(n + 1, np.uint32)
    vo = np.zeros(n + 1, np.uint32)
    kb = np.zeros(sum(int(blk.key_len[r]) for r in take), np.uint8)
    vb = np.zeros(10 * n, np.uint8)
    _gather_python(blk, take, 4, False, kb, ko, vb, vo, 0)
    assert kb.tobytes() == page.key_blob
    assert vb.tobytes() == page.val_blob
    assert ko.tobytes() == page.key_offs
    assert vo.tobytes() == page.val_offs


def test_build_page_multi_chunk_and_no_value():
    blk1, blk2 = _make_block(), _make_block(n=16)
    page, size, last = build_page(
        [(blk1, np.array([0, 5], np.int64)),
         (blk2, np.array([2], np.int64))], hdr=4, no_value=True)
    assert len(page) == 3
    assert [kv.value for kv in page] == [b"", b"", b""]
    assert page.key_at(2) == blk2.key_at(2) == last
    assert size == sum(len(k) for k in
                       (blk1.key_at(0), blk1.key_at(5), blk2.key_at(2)))


def test_empty_page():
    page, size, last = build_page([], hdr=4)
    assert len(page) == 0 and not page and size == 0 and last is None
    assert list(page) == []


def test_scan_page_sequence_protocol_and_codec():
    blk = _make_block()
    page, _s, _l = build_page([(blk, np.arange(6, dtype=np.int64))],
                              hdr=4, want_ets=True)
    assert page[2] == KeyValue(blk.key_at(2), b"value-0002", 14)
    assert page[-1].key == blk.key_at(5)
    with pytest.raises(IndexError):
        page[6]

    from pegasus_tpu.rpc.message import decode_message, encode_message
    from pegasus_tpu.server.types import ScanResponse

    resp = ScanResponse(error=0, kvs=page, context_id=-1)
    blob = encode_message("a", "b", "scan_resp", resp)
    _src, _dst, _mt, decoded = decode_message(blob[12:])  # skip header
    assert isinstance(decoded.kvs, ScanPage)
    assert [kv.key for kv in decoded.kvs] == [kv.key for kv in page]
    assert [kv.value for kv in decoded.kvs] == [kv.value for kv in page]
    assert decoded.kvs.ets_at(3) == page.ets_at(3)


def test_multi_flavor_predicate_matches_single():
    from pegasus_tpu.ops.record_block import block_from_columns

    blk = _make_block(n=64)
    dev = block_from_columns(blk.keys, blk.key_len, blk.expire_ts,
                             hash_lo=None)
    flavors = [
        (FilterSpec.none(), FilterSpec.make(FT_MATCH_ANYWHERE, b"s00")),
        (FilterSpec.none(), FilterSpec.make(FT_MATCH_ANYWHERE, b"s01")),
        (FilterSpec.none(), FilterSpec.make(FT_MATCH_ANYWHERE, b"s06")),
    ]
    multi = multi_static_block_predicate(dev, flavors, False, 0, 0)
    for k, (hf, sf) in enumerate(flavors):
        single = np.asarray(static_block_predicate(
            dev, hash_filter=hf, sort_filter=sf, validate_hash=False,
            pidx=0, partition_version=0))
        assert np.array_equal(multi[k][:len(single)], single), k


def test_packed_single_predicate_matches_unpacked():
    from pegasus_tpu.ops.predicates import unpack_masks
    from pegasus_tpu.ops.record_block import block_from_columns

    blk = _make_block(n=64)
    dev = block_from_columns(blk.keys, blk.key_len, blk.expire_ts,
                             hash_lo=None)
    sf = FilterSpec.make(FT_MATCH_PREFIX, b"s0")
    plain = np.asarray(static_block_predicate(
        dev, sort_filter=sf, validate_hash=False))
    packed = static_block_predicate(dev, sort_filter=sf,
                                    validate_hash=False, pack=True)
    assert np.array_equal(unpack_masks(packed, len(plain)), plain)


@pytest.fixture()
def table(tmp_path):
    t = Table(str(tmp_path / "t"), app_id=3, partition_count=2)
    c = PegasusClient(t)
    for h in range(40):
        for s in range(10):
            c.set(b"hk%03d" % h, b"s%02d" % s, b"v%03d-%02d" % (h, s))
    t.flush_all()
    for srv in t.all_partitions():
        srv.manual_compact()
    yield t
    t.close()


def test_scan_multi_mixed_flavors_equals_solo(table):
    srv = table.all_partitions()[0]
    pats = (b"s01", b"s05", b"", b"s09")
    reqs = [GetScannerRequest(
        start_key=b"", batch_size=1000, validate_partition_hash=True,
        sort_key_filter_type=FT_MATCH_ANYWHERE if p else 0,
        sort_key_filter_pattern=p) for p in pats]
    out = sc.scan_multi([(srv, reqs)], epoch_now())

    def drain(resp):
        keys = [kv.key for kv in resp.kvs]
        ctx = resp.context_id
        while ctx >= 0:
            r2 = srv.on_scan(ctx)
            keys += [kv.key for kv in r2.kvs]
            ctx = r2.context_id
        return keys

    for p, resp, req in zip(pats, out[0], reqs):
        batched = drain(resp)
        solo = drain(srv.on_get_scanner(req))
        assert batched == solo, p
        assert batched, p  # every flavor matches something here
        for k in batched:
            _hk, sk = restore_key(k)
            assert (not p) or p in sk


def test_scan_multi_mixed_flavors_warms_sibling_masks(table):
    """A multi-flavor wave caches (flavor, block) masks beyond each
    flavor's own miss set — the next scan with the sibling pattern must
    plan with zero misses."""
    srv = table.all_partitions()[0]
    pats = (b"s02", b"s03")
    reqs = [GetScannerRequest(
        start_key=b"", batch_size=1000, validate_partition_hash=True,
        sort_key_filter_type=FT_MATCH_ANYWHERE,
        sort_key_filter_pattern=p) for p in pats]
    sc.scan_multi([(srv, reqs)], epoch_now())
    for p in pats:
        req = GetScannerRequest(
            start_key=b"", batch_size=1000,
            validate_partition_hash=True,
            sort_key_filter_type=FT_MATCH_ANYWHERE,
            sort_key_filter_pattern=p)
        state = srv.plan_scan_batch([req], now=epoch_now())
        assert state is not None and "precomputed" not in state
        assert not srv.planned_misses(state), p
