"""Batched point-read path tests: the read coordinator and its
supporting pieces (vectorized block probes, per-generation location
cache, native co-located gathers, transport flush-window dispatch) —
plus the compaction-narrowing / cache-eviction satellites that keep the
batched caches honest across publishes.

The load-bearing regression: every batched result must be
BYTE-IDENTICAL to the corresponding single-request handler.
"""

import threading
import time

import pytest

from pegasus_tpu.base.key_schema import generate_key, key_hash_parts
from pegasus_tpu.server import (
    BatchGetRequest,
    FullKey,
    MultiGetRequest,
    PartitionServer,
)
from pegasus_tpu.utils.errors import StorageStatus

OK = int(StorageStatus.OK)
NOT_FOUND = int(StorageStatus.NOT_FOUND)


@pytest.fixture
def server(tmp_path):
    s = PartitionServer(str(tmp_path / "p0"))
    yield s
    s.close()


def norm(result):
    """Canonical comparable form of any point-read result."""
    if isinstance(result, tuple):
        return result
    if hasattr(result, "kvs"):
        return (result.error,
                [(kv.key, kv.value, kv.expire_ts_seconds)
                 for kv in result.kvs])
    return (result.error,
            [(d.hash_key, d.sort_key, d.value) for d in result.data])


def solo(server, op, args, ph=None):
    if op == "get":
        return server.on_get(args, partition_hash=ph)
    if op == "ttl":
        return server.on_ttl(args, partition_hash=ph)
    if op == "multi_get":
        return server.on_multi_get(args)
    return server.on_batch_get(args)


def load_mixed(server, n_hk=40, ttl_every=7):
    """n_hk hash keys x 5 sort keys; every ttl_every-th record carries a
     1-second TTL (expired by the time tests read), plus overlay rows
    and a tombstone on top of the compacted base."""
    for h in range(n_hk):
        hk = b"user%04d" % h
        for sk in range(5):
            ttl = 1 if (h * 5 + sk) % ttl_every == 0 else 0
            server.on_put(generate_key(hk, b"s%02d" % sk),
                          b"val-%d-%d" % (h, sk), ttl_seconds=ttl)
    server.flush()
    server.manual_compact()
    server.on_put(generate_key(b"user0001", b"s00"), b"overlaid")
    server.on_put(generate_key(b"user9999", b"s00"), b"overlay-only")
    server.on_remove(generate_key(b"user0002", b"s01"))
    time.sleep(1.1)  # the 1s TTLs expire


def mixed_ops(server):
    ops = []
    for h in (0, 1, 2, 3, 7, 500):
        hk = b"user%04d" % h
        key = generate_key(hk, b"s00")
        ops.append(("get", key, None))
        ops.append(("ttl", key, None))
        ops.append(("multi_get",
                    MultiGetRequest(hk, sort_keys=[b"s00", b"s01",
                                                   b"s04", b"szz"]),
                    None))
        ops.append(("multi_get",
                    MultiGetRequest(hk, sort_keys=[b"s02"],
                                    no_value=True), None))
        ops.append(("batch_get",
                    BatchGetRequest([FullKey(hk, b"s%02d" % i)
                                     for i in range(5)]), None))
    ops.append(("get", generate_key(b"user9999", b"s00"), None))
    ops.append(("get", generate_key(b"user0002", b"s01"), None))
    return ops


def test_batched_byte_identical_mixed(server):
    load_mixed(server)
    ops = mixed_ops(server)
    expect = [norm(solo(server, op, args, ph)) for op, args, ph in ops]
    got = [norm(r) for r in server.on_point_read_batch(ops)]
    assert got == expect


def test_batched_expired_ttl_and_abnormal_counting(server):
    server.on_put(generate_key(b"hk", b"dead"), b"x", ttl_seconds=1)
    server.on_put(generate_key(b"hk", b"live"), b"y")
    server.flush()
    server.manual_compact()
    time.sleep(1.1)
    before = server._abnormal_reads.value()
    res = server.on_point_read_batch([
        ("get", generate_key(b"hk", b"dead"), None),
        ("get", generate_key(b"hk", b"live"), None),
        ("ttl", generate_key(b"hk", b"dead"), None),
    ])
    assert [r[0] for r in res] == [NOT_FOUND, OK, NOT_FOUND]
    assert res[1][1] == b"y"
    assert server._abnormal_reads.value() == before + 2


def test_hot_key_overlap_resolves_once(server):
    load_mixed(server)
    key = generate_key(b"user0003", b"s00")
    ops = [("get", key, None)] * 10 + [("ttl", key, None)] * 5
    want_get = server.on_get(key)
    want_ttl = server.on_ttl(key)
    got = server.on_point_read_batch(ops)
    assert all(g == want_get for g in got[:10])
    assert all(g == want_ttl for g in got[10:])


def test_point_cache_invalidates_on_generation_change(server):
    key = generate_key(b"gen", b"s0")
    server.on_put(key, b"v1")
    server.flush()
    server.manual_compact()
    assert server.on_point_read_batch([("get", key, None)])[0] == (OK,
                                                                   b"v1")
    # overwrite + republish: the cached (block, row) location is dead
    server.on_put(key, b"v2")
    server.flush()
    server.manual_compact()
    assert server.on_point_read_batch([("get", key, None)])[0] == (OK,
                                                                   b"v2")


def test_wide_multi_get_rides_native_gather(server):
    """>= POINT_GATHER_MIN co-located sort keys: the build_page path."""
    n = PartitionServer.POINT_GATHER_MIN * 4
    for j in range(n):
        server.on_put(generate_key(b"wide", b"s%04d" % j),
                      b"v%0100d" % j)
    server.flush()
    server.manual_compact()
    req = MultiGetRequest(b"wide",
                          sort_keys=[b"s%04d" % j for j in range(n)])
    state = server.plan_get_batch([("multi_get", req, None)])
    chunks = server.point_chunks(state)
    assert chunks and sum(len(r) for _b, r in chunks) == n
    got = norm(server.on_point_read_batch([("multi_get", req, None)])[0])
    assert got == norm(server.on_multi_get(req))


def test_probe_handles_trailing_zero_keys(server):
    """Zero-padded key-matrix probes must not confuse keys differing
    only in trailing NUL bytes."""
    twins = [b"k", b"k\x00", b"k\x00\x00", b"k\x00a"]
    for i, sk in enumerate(twins):
        server.on_put(generate_key(b"z", sk), b"tw%d" % i)
    server.flush()
    server.manual_compact()
    ops = [("get", generate_key(b"z", sk), None) for sk in twins]
    ops.append(("get", generate_key(b"z", b"k\x00\x00\x00"), None))
    got = server.on_point_read_batch(ops)
    assert got[:4] == [(OK, b"tw%d" % i) for i in range(4)]
    assert got[4] == (NOT_FOUND, b"")


def test_batched_vs_solo_during_overlay_and_l0(server):
    """Unflushed memtable + L0 overlay served identically (newest
    wins, tombstones hide)."""
    for h in range(10):
        server.on_put(generate_key(b"ov%d" % h, b"s"), b"base%d" % h)
    server.flush()
    server.manual_compact()
    server.on_put(generate_key(b"ov1", b"s"), b"l0-new")
    server.flush()  # L0, no compact
    server.on_put(generate_key(b"ov2", b"s"), b"mem-new")
    server.on_remove(generate_key(b"ov3", b"s"))
    ops = [("get", generate_key(b"ov%d" % h, b"s"), None)
           for h in range(10)]
    expect = [solo(server, *op) for op in ops]
    assert server.on_point_read_batch(ops) == expect


def test_in_process_client_point_read_multi(tmp_path):
    from pegasus_tpu.client import PegasusClient, Table

    table = Table(str(tmp_path / "t"), app_id=1, partition_count=8)
    client = PegasusClient(table)
    try:
        for i in range(400):
            client.set(b"hk%04d" % (i // 4), b"s%d" % (i % 4),
                       b"v%05d" % i)
        table.flush_all()
        table.manual_compact_all()
        groups, expect = {}, {}
        for i in range(0, 100, 3):
            hk, sk = b"hk%04d" % (i // 4), b"s%d" % (i % 4)
            ph = key_hash_parts(hk, sk)
            pidx = ph % 8
            groups.setdefault(pidx, []).append(
                ("get", generate_key(hk, sk), ph))
            expect.setdefault(pidx, []).append(client.get(hk, sk))
        res = client.point_read_multi(groups)
        assert res == expect
    finally:
        table.close()


def test_transport_flush_window_batches_point_reads():
    """TcpTransport.register_batch: consecutive same-type messages from
    one connection deliver as a single batch; other types keep solo
    dispatch and ordering."""
    from pegasus_tpu.rpc.transport import TcpTransport

    srv = TcpTransport(("127.0.0.1", 0), {})
    name = "batched-node"
    got = []                 # interleaved delivery order
    release = threading.Event()
    done = threading.Event()

    def batch_handler(items):
        got.append([p["i"] for _s, p in items])

    def solo_handler(src, msg_type, payload):
        if msg_type == "block":
            release.wait(10)  # hold the dispatcher: the burst queues up
            return
        got.append((msg_type, payload["i"]))
        if msg_type == "finish":
            done.set()

    srv.register(name, solo_handler)
    srv.register_batch(name, "pread", batch_handler)
    book = {name: srv.listen_addr}
    cli = TcpTransport(None, book)
    try:
        cli.send("c", name, "block", {"i": -1})
        for i in range(6):
            cli.send("c", name, "pread", {"i": i})
        cli.send("c", name, "other", {"i": 100})
        cli.send("c", name, "pread", {"i": 6})
        cli.send("c", name, "finish", {"i": -1})
        deadline = time.monotonic() + 10
        while srv._inbox.qsize() < 9 and time.monotonic() < deadline:
            time.sleep(0.01)  # everything queued behind the block
        release.set()
        assert done.wait(10)
        # the consecutive pread run coalesced into ONE batch; the
        # non-batch message cut the window, and ordering held exactly
        assert got == [[0, 1, 2, 3, 4, 5], ("other", 100), [6],
                       ("finish", -1)]
    finally:
        cli.close()
        srv.close()


def test_cluster_client_point_read_multi(tmp_path):
    from pegasus_tpu.tools.cluster import SimCluster

    c = SimCluster(str(tmp_path), n_nodes=1)
    try:
        c.create_table("t", partition_count=4, replica_count=1)
        cl = c.client("t")
        cl.refresh_config()
        for i in range(200):
            cl.set(b"hk%03d" % (i // 2), b"s%d" % (i % 2), b"v%04d" % i)
        c.loop.run_until_idle()
        groups, expect = {}, {}
        for i in range(0, 60, 5):
            hk, sk = b"hk%03d" % (i // 2), b"s%d" % (i % 2)
            ph = key_hash_parts(hk, sk)
            pidx = ph % cl.partition_count
            key = generate_key(hk, sk)
            groups.setdefault(pidx, []).append(("get", key, ph))
            expect.setdefault(pidx, []).append(cl.get(hk, sk))
            groups[pidx].append(("ttl", key, ph))
            expect[pidx].append(cl.ttl(hk, sk))
        res = cl.point_read_multi(groups)
        assert {p: [norm(r) for r in rs] for p, rs in res.items()} == \
            {p: [norm(r) for r in rs] for p, rs in expect.items()}
    finally:
        c.close()


def test_batched_split_staleness_gates(tmp_path):
    """Every batched op applies the split-staleness gate the solo wire
    path applies: a stale partition_hash (or stale-grouped batch_get)
    must surface ERR_PARENT_PARTITION_MISUSED, never silent misses."""
    from pegasus_tpu.utils.errors import ErrorCode

    s = PartitionServer(str(tmp_path / "p0"), pidx=0, partition_count=4)
    s.on_put(generate_key(b"hk", b"s"), b"v")
    wrong_ph = s.pidx + 1  # (ph & 3) != 0
    key = generate_key(b"hk", b"s")
    req = MultiGetRequest(b"hk", sort_keys=[b"s"])
    bad = int(ErrorCode.ERR_PARENT_PARTITION_MISUSED)
    got = s.on_point_read_batch([
        ("get", key, wrong_ph),
        ("ttl", key, wrong_ph),
        ("multi_get", req, wrong_ph),
        ("batch_get", BatchGetRequest([FullKey(b"hk", b"s")]), None),
    ])
    assert got[0] == (bad, b"") and got[1] == (bad, 0)
    assert got[2].error == bad
    # batch_get's per-key vectorized gate: 'hk' only belongs to pidx 0
    # if its crc says so — compare against the solo handler
    assert got[3].error == s.on_batch_get(
        BatchGetRequest([FullKey(b"hk", b"s")])).error
    s.close()


def test_rpc_batch_malformed_op_gets_definite_reply(tmp_path):
    """A malformed op in client_read_batch must fail its own slot with
    INVALID_PARAMETERS, not leave the whole node batch unreplied."""
    from pegasus_tpu.tools.cluster import SimCluster
    from pegasus_tpu.utils.errors import PegasusError

    c = SimCluster(str(tmp_path), n_nodes=1)
    try:
        c.create_table("t", partition_count=2, replica_count=1)
        cl = c.client("t")
        cl.refresh_config()
        cl.set(b"hk", b"s", b"v")
        c.loop.run_until_idle()
        ph = key_hash_parts(b"hk", b"s")
        good_pidx = ph % 2
        # a bogus op name in one partition's group
        with pytest.raises(PegasusError):
            cl.point_read_multi({good_pidx: [("frobnicate", b"x", None)]})
        # and a well-formed batch afterwards still works
        res = cl.point_read_multi(
            {good_pidx: [("get", generate_key(b"hk", b"s"), ph)]})
        assert res[good_pidx][0] == (OK, b"v")
    finally:
        c.close()


# ---- satellite regressions ------------------------------------------


def test_compact_finish_time_set_at_publish_not_merge_start(tmp_path):
    from pegasus_tpu.storage.lsm import LSMStore

    store = LSMStore(str(tmp_path / "sst"))
    for i in range(10):
        store.put(b"k%02d" % i, b"v")
    store.flush(meta={})

    def exploding_filter(keys, ets):
        raise RuntimeError("mid-merge failure")

    with pytest.raises(RuntimeError):
        store.compact(record_filter=exploding_filter,
                      meta={"manual_compact_finish_time": 12345})
    assert store.compact_finish_time == 0, \
        "a failed compaction must not satisfy its env trigger"
    store.compact(meta={"manual_compact_finish_time": 12345})
    assert store.compact_finish_time == 12345
    store.close()


def test_publish_evicts_dead_run_cache_entries(server):
    for i in range(2000):
        server.on_put(generate_key(b"hk%04d" % i, b"s"), b"v%d" % i)
    server.flush()
    server.manual_compact()
    # populate mask/device/plan caches through a scan batch
    from pegasus_tpu.server.types import GetScannerRequest

    req = GetScannerRequest(start_key=b"", stop_key=b"",
                            batch_size=50, one_page=True)
    server.on_get_scanner_batch([req])
    server.on_point_read_batch(
        [("get", generate_key(b"hk0001", b"s"), None)])
    assert server._mask_cache or server._device_block_cache
    old_paths = {k[0][0] for k in server._mask_cache}
    old_paths |= {k[0] for k in server._device_block_cache}
    # rewrite the store: the old runs' cache entries must all go
    server.on_put(generate_key(b"hk0001", b"s"), b"new")
    server.flush()
    server.manual_compact()
    live = {t.path for t in server.engine.lsm.l1_runs}
    assert all(k[0][0] in live for k in server._mask_cache)
    assert all(k[0] in live for k in server._device_block_cache)
    assert server._point_cache is None
    assert not (old_paths & live)


def test_writes_survive_concurrent_manual_compact(tmp_path):
    """The narrow-critical-section satellite: writes flowing DURING a
    manual compaction are acked, survive the publish, and stay
    readable — and the compaction itself completes."""
    s = PartitionServer(str(tmp_path / "p0"))
    try:
        for i in range(20000):
            s.on_put(generate_key(b"base%06d" % i, b"s"), b"v%d" % i)
        s.flush()
        acked = {}
        stop = threading.Event()
        errors = []

        def writer():
            i = 0
            try:
                while not stop.is_set():
                    hk = b"during%05d" % i
                    if s.on_put(generate_key(hk, b"s"),
                                b"w%d" % i) == OK:
                        acked[hk] = b"w%d" % i
                    i += 1
            except Exception as exc:  # noqa: BLE001 - surfaced below
                errors.append(repr(exc))

        t = threading.Thread(target=writer)
        t.start()
        try:
            s.manual_compact()
            s.manual_compact()
        finally:
            stop.set()
            t.join(timeout=30)
        assert not errors, errors
        assert acked, "writer never got a write through"
        lost = [hk for hk, v in acked.items()
                if s.on_get(generate_key(hk, b"s")) != (OK, v)]
        assert not lost, f"{len(lost)} acked writes lost"
        # base data survived both compactions too
        assert s.on_get(generate_key(b"base000123", b"s")) == (OK,
                                                               b"v123")
    finally:
        s.close()


def test_blob_server_traversal_returns_400(tmp_path):
    import http.client

    from pegasus_tpu.storage.blob_server import BlobServer

    srv = BlobServer(str(tmp_path / "root"))
    try:
        conn = http.client.HTTPConnection(srv.host, srv.port, timeout=5)
        for verb, path in (("GET", "/blob/../../etc/passwd"),
                           ("HEAD", "/blob/../../etc/passwd"),
                           ("GET", "/list/../..")):
            conn.request(verb, path)
            resp = conn.getresponse()
            resp.read()
            assert resp.status == 400, (verb, path, resp.status)
        # the connection survived (no traceback kill) and normal ops work
        conn.request("PUT", "/blob/a/b", body=b"data")
        assert conn.getresponse().status == 200
        conn.request("GET", "/blob/a/b")
        r = conn.getresponse()
        assert r.status == 200 and r.read() == b"data"
    finally:
        srv.close()


def test_geo_rejects_magic_prefixed_legacy_value():
    """A legacy (headerless) index value that happens to start with the
    packed-header magic must not inject garbage coordinates."""
    from pegasus_tpu.geo.geo_client import (
        _MAGIC,
        _page_coords,
        LatLngCodec,
    )

    codec = LatLngCodec()
    # 16 bytes of 0xFF decode as huge/inf doubles -> out of range
    legacy = _MAGIC + b"\xff" * 16 + b"|40.1|-74.2|payload"
    values = [legacy]
    coords, rows, packed = _page_coords(
        values, codec, lambda i: values[i], 1)
    assert coords is None or not packed[0], \
        "magic-prefixed legacy value misparsed as packed header"
