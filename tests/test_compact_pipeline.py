"""PR 8: accelerator-pipelined compaction + the background-IO scheduler.

Covers the four acceptance surfaces:
- byte-identity of pipelined vs serial compaction over MIXED
  legacy(none)+dcz+dcz2 stores (both compaction shapes);
- crash mid-pipeline: a write fault aborts the compaction, nothing of
  the half-built output is adopted at reopen (manifest-then-unlink
  ordering holds) and the data still serves;
- the dcz2 column codecs (FOR expire_ts, dict-indexed hash_lo):
  round-trip equivalence with v1, native-subset parity, and the
  down-transcode guard that keeps v2 blocks out of 'dcz' files;
- the schedulers: seeded governor AIMD backoff under growing
  shed/deadline counters (and recovery), the meta coordinator's
  stagger invariants, and the env-trigger defer/grant path.
"""

from __future__ import annotations

import hashlib
import os
import shutil

import numpy as np
import pytest

from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.base.value_schema import epoch_now
from pegasus_tpu.storage.compact_governor import CompactionGovernor
from pegasus_tpu.storage.engine import StorageEngine, WriteBatchItem
from pegasus_tpu.storage.wal import OP_PUT
from pegasus_tpu.utils.flags import FLAGS


def _set_flag(section, name, value):
    old = FLAGS.get(section, name)
    FLAGS.set(section, name, value)
    return old


@pytest.fixture
def pipeline_flags():
    """Snapshot + restore the storage flags the tests flip."""
    saved = [(s, n, FLAGS.get(s, n)) for s, n in (
        ("pegasus.storage", "compact_pipeline"),
        ("pegasus.storage", "block_codec"),
        ("pegasus.storage", "compact_pipeline_window"),
    )]
    yield
    for s, n, v in saved:
        FLAGS.set(s, n, v)


def _build_mixed_store(d: str, block_capacity: int = 64) -> None:
    """A store whose L0s span all three codecs (a rolling-upgrade
    shape: legacy files keep serving beside both dcz generations)."""
    eng = StorageEngine(d, block_capacity=block_capacity)
    now = epoch_now()
    rng = np.random.default_rng(11)
    dec = 0
    for codec in ("none", "dcz", "dcz2"):
        FLAGS.set("pegasus.storage", "block_codec", codec)
        for b in range(4):
            items = []
            for j in range(300):
                i = dec * 300 + j
                k = generate_key(b"hk%05d" % (i // 25),
                                 b"s%03d" % (i % 25))
                ets = int(now) - 40 if rng.random() < 0.25 else 0
                items.append(WriteBatchItem(
                    OP_PUT, k, b"value-%06d|" % i * 3, ets))
            dec += 1
            eng.write_batch(items, dec)
            eng.flush()
    eng.close()


def _digest(eng: StorageEngine) -> str:
    h = hashlib.sha256()
    for k, v, e in eng.iterate():
        h.update(k)
        h.update(v)
        h.update(b"%d" % e)
    sst = os.path.join(eng.data_dir, "sst")
    for name in sorted(os.listdir(sst)):
        if name.endswith(".sst"):
            with open(os.path.join(sst, name), "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def test_pipelined_identical_to_serial_mixed_codecs(tmp_path,
                                                    pipeline_flags,
                                                    monkeypatch):
    """The tentpole gate: the pipelined stages must produce the exact
    bytes the serial path produces, over a store mixing legacy raw,
    dcz, and dcz2 runs — through BOTH compaction shapes (merge over
    L0s, then bulk over pure L1)."""
    import pegasus_tpu.storage.engine as engine_mod

    # the compaction meta stamps manual_compact_finish_time =
    # epoch_now() into the SST index, and the TTL drop masks read the
    # clock too — freeze it so the two runs can't straddle a second
    # boundary and diverge on bytes that have nothing to do with the
    # pipeline
    monkeypatch.setattr(engine_mod, "epoch_now", lambda: 334_000_000)
    src = str(tmp_path / "src")
    _build_mixed_store(src)
    FLAGS.set("pegasus.storage", "block_codec", "dcz2")
    FLAGS.set("pegasus.storage", "compact_pipeline_window", 8)
    digs = {}
    for mode in (False, True):
        d = str(tmp_path / f"m{mode}")
        shutil.copytree(src, d)
        FLAGS.set("pegasus.storage", "compact_pipeline", mode)
        eng = StorageEngine(d, block_capacity=64)
        eng.manual_compact()          # merge path: L0s -> L1
        assert eng.lsm.bulk_compact_eligible()
        eng.manual_compact()          # bulk path over pure L1
        digs[mode] = _digest(eng)
        eng.close()
    assert digs[True] == digs[False]


def test_crash_mid_pipeline_keeps_old_store(tmp_path, pipeline_flags):
    """A disk fault mid-compaction must abort the pipeline cleanly:
    the error propagates, stage threads stop, no half-built l1 output
    is adopted at reopen (the manifest still names the old runs), and
    every record still serves."""
    from pegasus_tpu.utils.fail_point import FAIL_POINTS

    d = str(tmp_path / "s")
    _build_mixed_store(d)
    FLAGS.set("pegasus.storage", "block_codec", "dcz2")
    FLAGS.set("pegasus.storage", "compact_pipeline", True)
    FLAGS.set("pegasus.storage", "compact_pipeline_window", 8)
    eng = StorageEngine(d, block_capacity=64)
    eng.manual_compact()  # pure L1 now
    before = _digest(eng)
    runs_before = [os.path.basename(t.path) for t in eng.lsm.l1_runs]
    gen = eng.lsm.generation
    FAIL_POINTS.teardown()
    FAIL_POINTS.setup()
    FAIL_POINTS.seed(3)
    FAIL_POINTS.cfg("vfs::write", "return(eio)")
    try:
        with pytest.raises(OSError):
            eng.manual_compact()
    finally:
        FAIL_POINTS.teardown()
    # publish never happened: same run set, same generation
    assert eng.lsm.generation == gen
    assert [os.path.basename(t.path)
            for t in eng.lsm.l1_runs] == runs_before
    eng.close()
    # reopen: boot must clean any orphan outputs and serve identically
    eng2 = StorageEngine(d, block_capacity=64)
    assert [os.path.basename(t.path)
            for t in eng2.lsm.l1_runs] == runs_before
    assert _digest(eng2) == before
    # and a clean retry completes
    eng2.manual_compact()
    eng2.close()


# ---- dcz2 column codecs ------------------------------------------------


def _raw_block(n=120, seed=3, wide_ttl=False):
    rng = np.random.default_rng(seed)
    keys_list = []
    for h in range(n // 6):
        for s in range(6):
            hk = b"user%04d" % h
            sk = b"s%02d" % s
            keys_list.append(bytes([0, len(hk)]) + hk + sk)
    keys_list = sorted(keys_list)[:n]
    keys_list[0] = bytes([0, 0]) + b"aaa-sortonly"  # empty hashkey
    keys_list.sort()
    n = len(keys_list)
    width = 32
    keys = np.zeros((n, width), dtype=np.uint8)
    key_len = np.zeros(n, dtype=np.int32)
    for i, k in enumerate(keys_list):
        keys[i, :len(k)] = np.frombuffer(k, dtype=np.uint8)
        key_len[i] = len(k)
    ets = np.where(rng.random(n) < 0.5, 0,
                   1_700_000_000
                   + rng.integers(0, 900, n)).astype(np.uint32)
    if wide_ttl:
        ets[1] = 17
        ets[2] = 0xE0000000
    flags = np.zeros(n, dtype=np.uint8)
    vals = [b"v%04d|" % i
            + bytes(rng.integers(32, 127, 18, dtype=np.uint8))
            for i in range(n)]
    offs = np.zeros(n + 1, dtype=np.uint32)
    offs[1:] = np.cumsum([len(v) for v in vals])
    heap = b"".join(vals)
    from pegasus_tpu.base.crc import crc64_batch

    hkl = (keys[:, 0].astype(np.int64) << 8) \
        | keys[:, 1].astype(np.int64)
    region = np.where(hkl > 0, hkl, key_len.astype(np.int64) - 2)
    hash_lo = (crc64_batch(keys, region, start=2)
               & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return keys, key_len, ets, hash_lo, flags, offs, heap


@pytest.mark.parametrize("wide_ttl", [False, True])
def test_dcz2_roundtrip_equals_v1(wide_ttl):
    """FOR expire_ts + dict-indexed hash_lo must reproduce exactly the
    columns the v1 layout stores raw — including the empty-hashkey
    rows whose hash is NOT group-constant (they ride the overflow
    array) and the wide-TTL spread that falls back to raw u32."""
    from pegasus_tpu.storage.block_codec import (
        EncodedBlock,
        block_version,
        encode_block,
    )

    cols = _raw_block(wide_ttl=wide_ttl)
    b1 = encode_block(*cols, version=1)
    b2 = encode_block(*cols, version=2)
    assert block_version(b1) == 1 and block_version(b2) == 2
    keys, key_len, ets, hash_lo, flags, offs, heap = cols
    for b in (b1, b2):
        enc = EncodedBlock.parse(b)
        assert np.array_equal(enc.expire_ts, ets)
        assert np.array_equal(enc.hash_lo, hash_lo)
        blk = enc.decode()
        assert np.array_equal(blk.keys, keys)
        assert np.array_equal(blk.value_offs, offs)
        assert bytes(np.asarray(blk.value_heap)) == heap
    if not wide_ttl:
        # the whole point: v2 stores the predicate columns smaller
        assert len(b2) < len(b1)


def test_dcz2_native_subset_parity():
    """The native kernel must subset a v2 block to the same logical
    content as the same v1 block — keys, rewritten TTLs, hashes,
    bloom hashes, fences — and keep the block's format version."""
    from pegasus_tpu import native
    from pegasus_tpu.storage.block_codec import (
        EncodedBlock,
        block_version,
        encode_block,
    )

    sub = native.cblock_subset_fn()
    if sub is None:
        pytest.skip("native library unavailable")
    cols = _raw_block(seed=9)
    n = cols[0].shape[0]
    rng = np.random.default_rng(4)
    keep = rng.random(n) > 0.35
    ets = cols[2]
    new_ets = np.where(ets == 0, 0, ets + 9).astype(np.uint32)
    got = {}
    for ver in (1, 2):
        b = encode_block(*cols, version=ver)
        enc = EncodedBlock.parse(b)
        r = sub(bytes(enc.raw) if not isinstance(enc.raw, bytes)
                else enc.raw, enc.raw_heap_len, enc.key_width, keep,
                new_ets, True, want_hashes=True)
        assert r is not None
        buf, hashes, m, vsub, fk, lk = r
        assert block_version(buf) == ver
        assert m == int(keep.sum())
        got[ver] = (EncodedBlock.parse(buf), hashes, fk, lk)
    e1, h1, fk1, lk1 = got[1]
    e2, h2, fk2, lk2 = got[2]
    assert np.array_equal(h1, h2)
    assert (fk1, lk1) == (fk2, lk2)
    assert np.array_equal(e1.hash_lo, e2.hash_lo)
    d1, d2 = e1.decode(), e2.decode()
    assert np.array_equal(d1.keys, d2.keys)
    assert np.array_equal(d1.expire_ts, d2.expire_ts)
    assert np.array_equal(d1.expire_ts, new_ets[keep])
    assert bytes(np.asarray(d1.value_heap)) == \
        bytes(np.asarray(d2.value_heap))


def test_dcz_writer_never_embeds_v2(tmp_path, pipeline_flags):
    """Format-version containment: compacting a dcz2 store under a
    'dcz' writer must down-transcode every block — the output file's
    blocks are all v1, so a build that knows only dcz can serve it."""
    from pegasus_tpu.storage.block_codec import block_version

    d = str(tmp_path / "s")
    FLAGS.set("pegasus.storage", "block_codec", "dcz2")
    eng = StorageEngine(d, block_capacity=64)
    now = epoch_now()
    items = [WriteBatchItem(
        OP_PUT, generate_key(b"hk%03d" % (i // 10), b"s%02d" % (i % 10)),
        b"payload-%04d|" % i * 3,
        int(now) - 30 if i % 4 == 0 else 0) for i in range(600)]
    eng.write_batch(items, 1)
    eng.flush()
    eng.manual_compact()
    eng.manual_compact()  # bulk: pure-L1 dcz2 store now
    before = {k: (v, e) for k, v, e in eng.iterate()}
    assert all(t.codec == "dcz2" for t in eng.lsm.l1_runs)
    FLAGS.set("pegasus.storage", "block_codec", "dcz")
    eng.manual_compact()  # rewrites under the dcz writer
    for t in eng.lsm.l1_runs:
        assert t.codec == "dcz"
        for i in range(len(t.blocks)):
            raw, _bm = t._read_raw_block(i)
            assert block_version(bytes(raw[:48])) == 1
    after = {k: (v, e) for k, v, e in eng.iterate()}
    assert after == before
    eng.close()


# ---- the governor (node scheduler) -------------------------------------


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _governor(clock, pressure):
    sleeps = []

    def sleep(s):
        sleeps.append(s)
        clock.t += s

    g = CompactionGovernor(clock=clock, sleep=sleep,
                           pressure_source=lambda: pressure[0])
    return g, sleeps


def test_governor_backs_off_under_pressure_and_recovers():
    """Seeded feedback loop: growing shed/deadline counters must
    engage a cap and halve it per interval (never below the floor);
    quiet intervals recover multiplicatively until the cap disengages.
    Background progress never stops: acquire() always returns."""
    clock = _Clock()
    pressure = [0]
    g, sleeps = _governor(clock, pressure)
    step = 1 << 20  # 1 MiB per acquire
    # establish a measured rate with no pressure: never throttled
    for _ in range(40):
        g.acquire(step)
        clock.t += 0.05  # ~20 MB/s offered
    assert g.status()["throttle_mbps"] == 0
    assert not sleeps
    # pressure grows across two feedback intervals: cap engages, halves
    pressure[0] = 10
    clock.t += 1.1
    g.acquire(step)
    t1 = g.status()["throttle_mbps"]
    assert t1 > 0
    pressure[0] = 25
    clock.t += 1.1
    g.acquire(step)
    t2 = g.status()["throttle_mbps"]
    assert t2 == pytest.approx(max(t1 / 2,
                                   FLAGS.get("pegasus.storage",
                                             "compact_min_mbps")))
    assert g._c_backoff.value() >= 2
    # throttled acquires now sleep (bytes/s bounded) but still return
    n_sleeps = len(sleeps)
    for _ in range(30):
        g.acquire(step)
    assert len(sleeps) > n_sleeps
    # pressure stops growing: recovery climbs and eventually uncaps
    for _ in range(30):
        clock.t += 1.1
        g.acquire(step)
        if g.status()["throttle_mbps"] == 0:
            break
    assert g.status()["throttle_mbps"] == 0


def test_governor_floor_guarantees_progress():
    """However long the pressure persists, the throttle never drops
    below compact_min_mbps — compaction keeps moving."""
    clock = _Clock()
    pressure = [0]
    g, _sleeps = _governor(clock, pressure)
    g.acquire(1 << 20)
    for i in range(12):
        pressure[0] += 5
        clock.t += 1.1
        g.acquire(1 << 20)
    floor = float(FLAGS.get("pegasus.storage", "compact_min_mbps"))
    assert g.status()["throttle_mbps"] == pytest.approx(floor)


def test_governor_grant_lease():
    clock = _Clock()
    g, _ = _governor(clock, [0])
    assert g.heavy_allowed()  # no coordinator ever answered: open
    g.set_cluster_grant(False)
    assert not g.heavy_allowed()
    g.set_cluster_grant(True)
    assert g.heavy_allowed()
    g.set_cluster_grant(False)
    lease = float(FLAGS.get("pegasus.storage", "compact_grant_lease_s"))
    clock.t += lease + 1
    # an EXPIRED denial fails open: a dead meta must not wedge
    # compaction cluster-wide
    assert g.heavy_allowed()


# ---- the coordinator (meta scheduler) ----------------------------------


class _FakeMeta:
    def __init__(self):
        self.t = 0.0
        self.name = "meta1"

    def clock(self):
        return self.t


def test_coordinator_staggers_and_rotates():
    """At most K nodes hold the grant; a holder that finishes releases
    its slot the same round; waiters admit in first-seen order; a
    holder that goes silent ages out after the lease."""
    from pegasus_tpu.meta.compaction_scheduler import (
        CompactionCoordinator,
    )

    meta = _FakeMeta()
    c = CompactionCoordinator(meta)
    old = FLAGS.get("pegasus.meta", "compaction_concurrent_nodes")
    FLAGS.set("pegasus.meta", "compaction_concurrent_nodes", 1)
    try:
        def report(node, running=0, waiting=False):
            return c.on_report(node, {"compaction": {
                "running": running, "waiting": waiting,
                "bytes_per_s": 0}})

        lease = float(FLAGS.get("pegasus.meta",
                                "compaction_grant_lease_s"))
        grace = lease / 3
        # three nodes want to compact: exactly one granted
        got = {n: report(n, waiting=True) for n in ("n1", "n2", "n3")}
        assert sum(got.values()) == 1
        winner = next(n for n, g in got.items() if g)
        # within the delivery grace a not-yet-running holder KEEPS its
        # slot (the grant rides the NEXT reply; a graceless release
        # would pass it around the ring with no reply ever saying yes)
        meta.t += 1
        assert report(winner, running=0, waiting=True) is True
        # winner runs; others keep asking — still only the winner,
        # well past the grace (running holders are never released)
        for _ in range(3):
            meta.t += grace
            assert report(winner, running=1) is True
            for n in ("n1", "n2", "n3"):
                if n != winner:
                    assert report(n, waiting=True) is False
        # winner finishes: once past the grace the slot releases and
        # the FIRST waiter gets it
        meta.t += grace + 1
        assert report(winner, running=0, waiting=False) is False
        waiters = [n for n in ("n1", "n2", "n3") if n != winner]
        got2 = {n: report(n, waiting=True) for n in waiters}
        assert sum(got2.values()) == 1
        second = next(n for n, g in got2.items() if g)
        # a holder that only ever reports waiting (never running) also
        # rotates out after the grace — camping would livelock every
        # other node (sim nodes even share the governor waiting flag)
        meta.t += grace + 1
        assert report(second, running=0, waiting=True) is False
        got3 = {n: report(n, waiting=True) for n in waiters
                if n != second}
        assert sum(got3.values()) == 1
        second = next(n for n, g in got3.items() if g)
        # the new holder dies silently: its grant ages out and the
        # remaining waiter is admitted
        last = next(n for n in waiters if n != second)
        lease = float(FLAGS.get("pegasus.meta",
                                "compaction_grant_lease_s"))
        meta.t += lease + 1
        assert report(last, waiting=True) is True
        # stagger off (k=0): everyone granted
        FLAGS.set("pegasus.meta", "compaction_concurrent_nodes", 0)
        assert report(second, waiting=True) is True
        assert report(last, waiting=True) is True
        # nodes with no compaction block are never gated
        assert c.on_report("old-node", {}) is None
    finally:
        FLAGS.set("pegasus.meta", "compaction_concurrent_nodes", old)


@pytest.fixture
def server(tmp_path):
    from pegasus_tpu.server.partition_server import PartitionServer

    s = PartitionServer(str(tmp_path / "p0"))
    yield s
    s.close()


def test_env_trigger_defers_until_granted(server):
    """The heavy-compaction gate on the env trigger: denied -> the
    trigger defers (demand recorded, trigger_seen NOT consumed);
    granted -> the SAME re-delivered env starts the compaction."""
    import time

    from pegasus_tpu.storage.compact_governor import GOVERNOR

    for i in range(40):
        server.engine.write_batch(
            [WriteBatchItem(OP_PUT,
                            generate_key(b"gk%02d" % i, b"s"),
                            b"v%d" % i, 0)],
            server.engine.last_committed_decree + 1)
    lsm = server.engine.lsm
    assert not lsm.l1_runs
    trigger = {"manual_compact.once.trigger_time":
               str(int(time.time()))}
    GOVERNOR.set_cluster_grant(False)
    d0 = GOVERNOR.status()["defer_count"]
    server.update_app_envs(trigger)
    assert not server._mc_running
    assert GOVERNOR.status()["defer_count"] == d0 + 1
    assert GOVERNOR.report()["waiting"] is True
    assert not lsm.l1_runs
    # the grant arrives (next config-sync reply): the re-delivered env
    # now starts the run
    GOVERNOR.set_cluster_grant(True)
    server.update_app_envs(trigger)
    deadline = time.monotonic() + 30
    while server._mc_running and time.monotonic() < deadline:
        time.sleep(0.02)
    assert not server._mc_running
    assert lsm.l1_runs and not len(lsm.memtable)


# ---- scrub restart-once under pipelined publishes ----------------------


def test_scrub_restarts_once_per_publish(tmp_path, pipeline_flags):
    """One pipelined manual compaction bumps the store generation
    more than once (freeze-flush + publish cut-over); the scrubber
    must restart its pass exactly ONCE for it — and pause (not
    restart) while the compaction holds the lock."""
    from pegasus_tpu.storage.scrub import ReplicaScrubber
    from pegasus_tpu.utils.metrics import METRICS

    FLAGS.set("pegasus.storage", "compact_pipeline", True)
    d = str(tmp_path / "s")
    _build_mixed_store(d)
    eng = StorageEngine(d, block_capacity=64)

    class _Rep:
        class server:
            engine = eng

    reps = {(1, 0): _Rep()}
    scrubber = ReplicaScrubber(lambda: reps, lambda g, e: None,
                               blocks_per_tick=2)
    scrubber.pass_interval = 0.0
    restart = METRICS.entity("storage", "node").counter(
        "scrub_restart_count")
    scrubber.tick()  # opens a cursor mid-pass (2 blocks of many)
    assert (1, 0) in scrubber._cursor
    r0 = restart.value()
    # freeze-flush + compact + publish: >= 2 generation bumps
    gen0 = eng.lsm.generation
    with eng.compact_lock:
        # while the lock is held (mid-compaction), ticks PAUSE the
        # cursor rather than restarting it
        scrubber.tick()
        assert restart.value() == r0
        assert (1, 0) in scrubber._cursor
    eng.write_batch(
        [WriteBatchItem(OP_PUT, generate_key(b"fresh", b"s"),
                        b"v", 0)],
        eng.last_committed_decree + 1)
    eng.flush()          # the freeze-flush half of the publish
    eng.manual_compact()  # the cut-over half
    assert eng.lsm.generation >= gen0 + 2
    # however many ticks observe the new generation, the restart fires
    # exactly once
    scrubber.tick()
    scrubber.tick()
    scrubber.tick()
    assert restart.value() == r0 + 1
    eng.close()


def test_pipeline_stall_counters_populate(tmp_path, pipeline_flags):
    """Observability satellite: a pipelined compaction must leave
    per-stage evidence behind (bytes/s gauge; stall counters may or
    may not tick depending on which stage bottlenecks, but the gauges
    exist on the storage entity and the run must not zero them out)."""
    from pegasus_tpu.utils.metrics import METRICS

    FLAGS.set("pegasus.storage", "compact_pipeline", True)
    FLAGS.set("pegasus.storage", "compact_pipeline_window", 4)
    d = str(tmp_path / "s")
    _build_mixed_store(d)
    eng = StorageEngine(d, block_capacity=64)
    eng.manual_compact()
    eng.manual_compact()
    eng.close()
    snap = [s["metrics"] for s in METRICS.snapshot("storage")][0]
    for name in ("compaction_bytes_per_s", "compact_read_stall_ms",
                 "compact_filter_stall_ms", "compact_write_stall_ms",
                 "compact_readq_depth", "compact_filtq_depth"):
        assert name in snap, name
