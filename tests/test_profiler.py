"""Per-task-code profiler toollet (parity: runtime/profiler.cpp:90-198
— per-code queue/exec latency + throughput, opt-in, dumped via the
remote-command surface)."""

import pytest

from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.utils.profiler import PROFILER


@pytest.fixture
def cluster(tmp_path):
    PROFILER.disable()
    PROFILER.clear()
    c = SimCluster(str(tmp_path / "cl"), n_nodes=2)
    yield c
    c.close()
    PROFILER.disable()
    PROFILER.clear()


def test_profiler_collects_per_code_stats(cluster):
    cluster.create_table("pf", partition_count=2)
    client = cluster.client("pf")
    # off by default: traffic leaves no rows
    assert client.set(b"a", b"s", b"v") == 0
    assert PROFILER.dump() == []
    PROFILER.enable()
    for i in range(30):
        assert client.set(b"k%d" % i, b"s", b"v") == 0
        assert client.get(b"k%d" % i, b"s") == (0, b"v")
    rows = {r["code"]: r for r in PROFILER.dump()}
    assert "client_write" in rows and "client_read" in rows
    w = rows["client_write"]
    assert w["count"] >= 30
    assert w["exec_ms_p99"] >= w["exec_ms_p50"] >= 0
    assert w["queue_ms_p50"] >= 0 and "qps" in w
    # disable stops collection; clear empties it
    PROFILER.disable()
    before = rows["client_write"]["count"]
    client.set(b"z", b"s", b"v")
    after = {r["code"]: r for r in PROFILER.dump()}["client_write"]
    assert after["count"] == before
    PROFILER.clear()
    assert PROFILER.dump() == []


def test_profiler_remote_command_surface(cluster):
    """Operators drive it through the stub's command registry (shell
    remote_command <node> task-profiler ...)."""
    cluster.create_table("pc", partition_count=1)
    stub = next(iter(cluster.stubs.values()))
    assert "task-profiler" in stub.commands.verbs()
    assert "enabled" in stub.commands.call("task-profiler", ["enable"])
    client = cluster.client("pc")
    for i in range(10):
        client.set(b"x%d" % i, b"s", b"v")
    rows = stub.commands.call("task-profiler", [])
    assert any(r["code"] == "client_write" for r in rows)
    assert "cleared" in stub.commands.call("task-profiler", ["clear"])
    assert stub.commands.call("task-profiler", ["dump"]) == []
    assert "disabled" in stub.commands.call("task-profiler",
                                            ["disable"])


def test_profiler_publishes_task_entities_to_metrics_spine(cluster):
    """Enabled-profiler stats surface on "task" metric entities (count /
    qps / queue-p99 / exec-p99), so Prometheus exposition and the
    flight recorder see them — not just the text dump verb."""
    from pegasus_tpu.utils.metrics import METRICS, to_prometheus

    cluster.create_table("pm", partition_count=2)
    client = cluster.client("pm")
    PROFILER.enable()
    for i in range(20):
        assert client.set(b"m%d" % i, b"s", b"v") == 0
        assert client.get(b"m%d" % i, b"s") == (0, b"v")
    n = PROFILER.publish()
    assert n > 0
    snap = {e["id"]: e["metrics"] for e in METRICS.snapshot("task")}
    assert "client_write" in snap
    w = snap["client_write"]
    assert w["task_dispatch_count"]["value"] >= 20
    assert w["task_exec_ms_p99"]["value"] >= w["task_exec_ms_p50"]["value"]
    assert "task_queue_ms_p99" in w and "task_qps" in w
    # publish is idempotent on the cumulative count (no double counting)
    before = w["task_dispatch_count"]["value"]
    PROFILER.publish()
    snap2 = {e["id"]: e["metrics"] for e in METRICS.snapshot("task")}
    assert snap2["client_write"]["task_dispatch_count"]["value"] == before
    # and the rows render through the Prometheus exposition
    prom = to_prometheus(METRICS.snapshot("task"))
    assert "pegasus_task_dispatch_count" in prom
    assert 'code="client_write"' in prom
    # the flight recorder records them: a stub's health tick owns the
    # task entities (process == node once deployed)
    stub = next(iter(cluster.stubs.values()))
    stub.recorder.tick(force=True)
    for _ in range(2):
        for i in range(20):
            client.set(b"m%d" % i, b"s", b"w")
        cluster.step()
        stub.recorder.tick(force=True)
    assert stub.recorder.match("task"), \
        "task entities must land in the flight recorder rings"
