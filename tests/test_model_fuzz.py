"""Model-based consistency fuzzing: random ops against the replicated
cluster, checked against an in-memory reference model, with random
failovers injected — the deterministic-simulator complement to the
kill-test harness (same spirit as the reference's seeded schedule
exploration, env.sim.h:36).

Every acked mutation updates the model; reads must match the model
exactly (linearizable single-client view). Unacked mutations may or may
not have applied — the model forks on ambiguity and reads collapse it.
"""

import random

import pytest

from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.utils.errors import PegasusError, StorageStatus

OK = int(StorageStatus.OK)
NOT_FOUND = int(StorageStatus.NOT_FOUND)


@pytest.mark.parametrize("seed", [11, 23, 47])
def test_randomized_ops_match_model(tmp_path, seed):
    rng = random.Random(seed)
    cluster = SimCluster(str(tmp_path / f"c{seed}"), n_nodes=4,
                         seed=seed)
    try:
        cluster.create_table("fuzz", partition_count=4)
        c = cluster.client("fuzz")
        model = {}          # (hk, sk) -> value
        ambiguous = {}      # (hk, sk) -> set of possible values
        hks = [b"h%02d" % i for i in range(8)]
        sks = [b"s%02d" % i for i in range(6)]
        killed = []

        for step in range(400):
            op = rng.random()
            hk, sk = rng.choice(hks), rng.choice(sks)
            key = (hk, sk)
            if op < 0.40:  # write
                value = b"v%d" % step
                try:
                    if c.set(hk, sk, value) == OK:
                        model[key] = value
                        ambiguous.pop(key, None)
                    else:
                        ambiguous.setdefault(key, set()).add(value)
                except PegasusError:
                    ambiguous.setdefault(key, set()).add(value)
            elif op < 0.50:  # delete
                try:
                    if c.delete(hk, sk) == OK:
                        model.pop(key, None)
                        ambiguous.pop(key, None)
                    else:
                        ambiguous.setdefault(key, set()).add(None)
                except PegasusError:
                    ambiguous.setdefault(key, set()).add(None)
            elif op < 0.90:  # read, checked against the model
                try:
                    err, got = c.get(hk, sk)
                except PegasusError:
                    continue  # unavailable; no consistency claim
                if key in ambiguous:
                    # collapse the ambiguity to what the cluster holds
                    allowed = set(ambiguous.pop(key))
                    allowed.add(model.get(key))
                    observed = got if err == OK else None
                    assert observed in allowed, (
                        step, key, observed, allowed)
                    if observed is None:
                        model.pop(key, None)
                    else:
                        model[key] = observed
                elif key in model:
                    assert (err, got) == (OK, model[key]), (step, key)
                else:
                    assert err == NOT_FOUND, (step, key, got)
            elif op < 0.95 and len(killed) < 2:  # chaos: kill a node
                alive = [n for n in cluster.stubs
                         if n not in cluster._dead]
                if len(alive) > 2:
                    victim = rng.choice(alive)
                    cluster.kill(victim)
                    killed.append(victim)
            else:  # let the cluster breathe / cure
                cluster.step()

        # final sweep: every unambiguous model entry must read back
        cluster.step(rounds=4)
        for (hk, sk), value in sorted(model.items()):
            if (hk, sk) in ambiguous:
                continue
            assert c.get(hk, sk) == (OK, value), (hk, sk)
    finally:
        cluster.close()
