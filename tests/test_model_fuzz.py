"""Model-based consistency fuzzing: random ops against the replicated
cluster, checked against an in-memory reference model, with random
failovers injected — the deterministic-simulator complement to the
kill-test harness (same spirit as the reference's seeded schedule
exploration, env.sim.h:36).

Every acked mutation updates the model; reads must match the model
exactly (linearizable single-client view). Unacked mutations may or may
not have applied — the model forks on ambiguity and reads collapse it.
"""

import random

import pytest

from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.utils.errors import PegasusError, StorageStatus

OK = int(StorageStatus.OK)
NOT_FOUND = int(StorageStatus.NOT_FOUND)


@pytest.mark.parametrize("seed", [11, 23, 47])
def test_randomized_ops_match_model(tmp_path, seed):
    rng = random.Random(seed)
    cluster = SimCluster(str(tmp_path / f"c{seed}"), n_nodes=4,
                         seed=seed)
    try:
        cluster.create_table("fuzz", partition_count=4)
        c = cluster.client("fuzz")
        model = {}          # (hk, sk) -> value
        ambiguous = {}      # (hk, sk) -> set of possible values
        hks = [b"h%02d" % i for i in range(8)]
        sks = [b"s%02d" % i for i in range(6)]
        killed = []

        for step in range(400):
            op = rng.random()
            hk, sk = rng.choice(hks), rng.choice(sks)
            key = (hk, sk)
            if op < 0.40:  # write
                value = b"v%d" % step
                try:
                    if c.set(hk, sk, value) == OK:
                        model[key] = value
                        ambiguous.pop(key, None)
                    else:
                        ambiguous.setdefault(key, set()).add(value)
                except PegasusError:
                    ambiguous.setdefault(key, set()).add(value)
            elif op < 0.50:  # delete
                try:
                    if c.delete(hk, sk) == OK:
                        model.pop(key, None)
                        ambiguous.pop(key, None)
                    else:
                        ambiguous.setdefault(key, set()).add(None)
                except PegasusError:
                    ambiguous.setdefault(key, set()).add(None)
            elif op < 0.90:  # read, checked against the model
                try:
                    err, got = c.get(hk, sk)
                except PegasusError:
                    continue  # unavailable; no consistency claim
                if key in ambiguous:
                    # collapse the ambiguity to what the cluster holds
                    allowed = set(ambiguous.pop(key))
                    allowed.add(model.get(key))
                    observed = got if err == OK else None
                    assert observed in allowed, (
                        step, key, observed, allowed)
                    if observed is None:
                        model.pop(key, None)
                    else:
                        model[key] = observed
                elif key in model:
                    assert (err, got) == (OK, model[key]), (step, key)
                else:
                    assert err == NOT_FOUND, (step, key, got)
            elif op < 0.95 and len(killed) < 2:  # chaos: kill a node
                alive = [n for n in cluster.stubs
                         if n not in cluster._dead]
                if len(alive) > 2:
                    victim = rng.choice(alive)
                    cluster.kill(victim)
                    killed.append(victim)
            else:  # let the cluster breathe / cure
                cluster.step()

        # final sweep: every unambiguous model entry must read back
        cluster.step(rounds=4)
        for (hk, sk), value in sorted(model.items()):
            if (hk, sk) in ambiguous:
                continue
            assert c.get(hk, sk) == (OK, value), (hk, sk)
    finally:
        cluster.close()


@pytest.mark.parametrize("seed", [5, 19])
def test_partition_heal_fuzz(tmp_path, seed):
    """Network-partition chaos (vs kill-based above): nodes get isolated
    and healed mid-traffic; acked writes must survive and reads stay
    linearizable per the fork-on-ambiguity model. Exercises lease
    expiry, reconfiguration around isolated primaries, and catch-up on
    heal — the message-loss classes the .act cases script explicitly,
    here explored randomly (env.sim.h:36 spirit)."""
    rng = random.Random(seed)
    cluster = SimCluster(str(tmp_path / f"p{seed}"), n_nodes=4,
                         seed=seed)
    try:
        cluster.create_table("pz", partition_count=4)
        c = cluster.client("pz")
        # current = last KNOWN value (None = absent); pending = values of
        # timed-out writes that may STILL commit later: unlike the
        # kill-based fuzz above, a partitioned-then-healed primary can
        # drain a stuck write queue long after the client gave up, so a
        # pending value stays possible until an ACKED write supersedes
        # it (FIFO per-replica queues commit earlier-issued first) or it
        # is observed (committed => the older value is gone)
        current = {}
        pending = {}
        hks = [b"h%02d" % i for i in range(6)]
        isolated = None

        for step in range(300):
            op = rng.random()
            hk = rng.choice(hks)
            key = (hk, b"s")
            if op < 0.35:  # write
                value = b"v%d" % step
                try:
                    if c.set(hk, b"s", value) == OK:
                        current[key] = value
                        pending.pop(key, None)
                    else:
                        pending.setdefault(key, set()).add(value)
                except PegasusError:
                    pending.setdefault(key, set()).add(value)
            elif op < 0.75:  # read
                try:
                    err, got = c.get(hk, b"s")
                except PegasusError:
                    continue
                observed = got if err == OK else None
                allowed = set(pending.get(key, ()))
                allowed.add(current.get(key))
                assert observed in allowed, (step, key, observed,
                                             allowed)
                if observed != current.get(key):
                    # a pending write is now committed: the prior value
                    # can never be read again, other pending may remain
                    pending[key].discard(observed)
                    if observed is None:
                        current.pop(key, None)
                    else:
                        current[key] = observed
            elif op < 0.85:  # chaos: isolate ONE replica node at a time
                if isolated is None:
                    victim = rng.choice(list(cluster.stubs))
                    cluster.net.partition(victim)
                    isolated = victim
                else:
                    cluster.net.heal(isolated)
                    isolated = None
            else:
                cluster.step()

        if isolated is not None:
            cluster.net.heal(isolated)
        cluster.step(rounds=6)
        for (hk, sk), value in sorted(current.items()):
            if pending.get((hk, sk)):
                continue
            deadline_ok = False
            for _ in range(6):
                try:
                    if c.get(hk, sk) == (OK, value):
                        deadline_ok = True
                        break
                except PegasusError:
                    pass
                cluster.step()
            assert deadline_ok, (hk, sk, value)
    finally:
        cluster.close()
