"""Compressed SST blocks (codec dcz) + direct compute on encoded data.

Gates for the round-11 format change: byte-identity of every decoded
column vs the raw layout, legacy/mixed stores serving unmodified,
format-version refusal on unknown codecs, encoded-probe equivalence
with the device predicate kernels, the byte-capped block cache, and
compaction identity (including the verbatim compressed-copy path).
"""

import json
import os
import struct

import numpy as np
import pytest

from pegasus_tpu.base.crc import crc32
from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.base.value_schema import epoch_now
from pegasus_tpu.storage.block_codec import EncodedBlock, encode_block
from pegasus_tpu.storage.lsm import LSMStore
from pegasus_tpu.storage.sstable import (
    FOOTER,
    MAGIC,
    SSTable,
    SSTableWriter,
)
from pegasus_tpu.utils.errors import StorageCorruptionError
from pegasus_tpu.utils.flags import FLAGS
from pegasus_tpu.utils.metrics import METRICS


@pytest.fixture
def codec_flag():
    """Save/restore the codec flag around tests that flip it."""
    old = FLAGS.get("pegasus.storage", "block_codec")
    yield
    FLAGS.set("pegasus.storage", "block_codec", old)


def _write(path, codec, n_hash=40, n_sort=5, block_capacity=64,
           ttl_every=0):
    old = FLAGS.get("pegasus.storage", "block_codec")
    FLAGS.set("pegasus.storage", "block_codec", codec)
    try:
        w = SSTableWriter(path, block_capacity=block_capacity)
        now = epoch_now()
        i = 0
        for h in range(n_hash):
            for s in range(n_sort):
                ets = (now - 30 if ttl_every and i % ttl_every == 0
                       else 0)
                w.add(generate_key(b"hash%05d" % h, b"sk%03d" % s),
                      b"value|%05d|%03d|" % (h, s) * 2, ets)
                i += 1
        w.finish()
    finally:
        FLAGS.set("pegasus.storage", "block_codec", old)
    return SSTable(path)


def _assert_blocks_identical(ta, tb):
    assert len(ta.blocks) == len(tb.blocks)
    for i in range(len(ta.blocks)):
        a, b = ta.read_block(i), tb.read_block(i)
        for col in ("keys", "key_len", "expire_ts", "hash_lo", "flags",
                    "value_offs"):
            ca, cb = getattr(a, col), getattr(b, col)
            assert np.array_equal(ca, cb), (i, col)
            assert ca.dtype == cb.dtype, (i, col)
        assert np.array_equal(np.asarray(a.value_heap),
                              np.asarray(b.value_heap)), i


# ---- round trip + identity --------------------------------------------


def test_dcz_roundtrip_byte_identical_to_raw(tmp_path):
    ta = _write(str(tmp_path / "raw.sst"), "none", ttl_every=7)
    tb = _write(str(tmp_path / "dcz.sst"), "dcz", ttl_every=7)
    assert ta.codec is None and tb.codec == "dcz"
    _assert_blocks_identical(ta, tb)
    assert list(ta.iterate()) == list(tb.iterate())
    for h in (0, 13, 39):
        key = generate_key(b"hash%05d" % h, b"sk%03d" % 2)
        assert ta.get(key) == tb.get(key)
    # the codec genuinely shrinks the file, and the stats record it
    st = tb.codec_stats
    assert st and st["stored_bytes"] < st["raw_bytes"]
    assert os.path.getsize(tb.path) < os.path.getsize(ta.path)
    # block CRCs cover the ON-DISK (encoded) bytes: scrub verify works
    for i in range(len(tb.blocks)):
        assert tb.verify_block(i) is True
    tb.verify_index_consistency()
    ta.close()
    tb.close()


def test_malformed_keys_roundtrip_raw_rows(tmp_path, codec_flag):
    """Keys the pegasus codec would never produce (short / lying
    header) take the sentinel raw-row path and still round-trip
    byte-for-byte."""
    for codec in ("none", "dcz"):
        FLAGS.set("pegasus.storage", "block_codec", codec)
        w = SSTableWriter(str(tmp_path / f"{codec}.sst"),
                          block_capacity=8)
        w.add(generate_key(b"aa", b"s"), b"v1")  # normal
        w.add(b"\x00\x50ab", b"v2")      # header beyond the body
        w.add(b"\x01", b"v3")            # shorter than the header
        w.finish()
    ta = SSTable(str(tmp_path / "none.sst"))
    tb = SSTable(str(tmp_path / "dcz.sst"))
    _assert_blocks_identical(ta, tb)
    enc = tb.read_block_encoded(0)
    assert enc.has_malformed
    assert enc.key_at(1) == b"\x00\x50ab"
    # the encoded probe refuses malformed blocks (device-kernel
    # semantics differ there) — the caller falls back to the device
    from pegasus_tpu.ops.predicates import (
        FT_NO_FILTER,
        encoded_static_keep,
    )

    assert encoded_static_keep(enc, False, 0, -1,
                               (FT_NO_FILTER, b"", FT_NO_FILTER, b"")) \
        is None
    ta.close()
    tb.close()


# ---- legacy + mixed stores --------------------------------------------


def test_codec_none_is_bitwise_legacy_format(tmp_path):
    """block_codec=none must emit the pre-codec layout exactly: no
    codec keys in the index, and the first block parses as the raw
    columnar struct."""
    t = _write(str(tmp_path / "t.sst"), "none")
    with open(t.path, "rb") as f:
        data = f.read()
    index_offset, index_size, _crc, magic = FOOTER.unpack(
        data[-FOOTER.size:])
    assert magic == MAGIC
    index = json.loads(data[index_offset:index_offset + index_size])
    assert "codec" not in index and "codec_stats" not in index
    bm = t.blocks[0]
    n, width, heap = struct.unpack_from("<IIQ", data, bm.offset)
    assert n == bm.count and width == bm.key_width
    t.close()


def test_mixed_legacy_and_compressed_store_serves(tmp_path, codec_flag):
    """Runs written before the codec existed keep serving beside
    compressed runs in ONE store — no rewrite required — and the next
    compaction converges the store onto the configured codec."""
    store = LSMStore(str(tmp_path / "s"), block_capacity=32)
    FLAGS.set("pegasus.storage", "block_codec", "none")
    for h in range(30):
        store.put(generate_key(b"old%04d" % h, b"s"), b"legacy-%04d" % h)
    store.flush()
    FLAGS.set("pegasus.storage", "block_codec", "dcz")
    for h in range(30):
        store.put(generate_key(b"new%04d" % h, b"s"), b"fresh-%04d" % h)
    store.flush()
    codecs = {t.codec for t in store.l0}
    assert codecs == {None, "dcz"}
    for h in range(30):
        assert store.get(generate_key(b"old%04d" % h, b"s")) == \
            (b"legacy-%04d" % h, 0)
        assert store.get(generate_key(b"new%04d" % h, b"s")) == \
            (b"fresh-%04d" % h, 0)
    before = list(store.iterate())
    store.compact()
    assert list(store.iterate()) == before
    assert all(t.codec == "dcz" for t in store.l1_runs)
    store.close()


def test_unknown_codec_refused_at_open(tmp_path):
    t = _write(str(tmp_path / "t.sst"), "dcz")
    t.close()
    with open(str(tmp_path / "t.sst"), "rb") as f:
        data = f.read()
    index_offset, index_size, _crc, magic = FOOTER.unpack(
        data[-FOOTER.size:])
    index = json.loads(data[index_offset:index_offset + index_size])
    index["codec"] = "zstd-99"
    blob = json.dumps(index).encode()
    with open(str(tmp_path / "t.sst"), "wb") as f:
        f.write(data[:index_offset])
        f.write(blob)
        f.write(FOOTER.pack(index_offset, len(blob), crc32(blob), magic))
    with pytest.raises(StorageCorruptionError, match="unsupported"):
        SSTable(str(tmp_path / "t.sst"))


# ---- direct compute ----------------------------------------------------


def test_encoded_probe_matches_device_masks(tmp_path):
    from pegasus_tpu.ops.predicates import (
        FT_MATCH_ANYWHERE,
        FT_MATCH_POSTFIX,
        FT_MATCH_PREFIX,
        FT_NO_FILTER,
        FilterSpec,
        encoded_static_keep,
        static_block_predicate,
    )
    from pegasus_tpu.ops.record_block import block_from_columns

    t = _write(str(tmp_path / "t.sst"), "dcz", n_hash=50, n_sort=6,
               block_capacity=128)
    flavors = [
        (FT_NO_FILTER, b"", FT_NO_FILTER, b""),
        (FT_MATCH_PREFIX, b"hash0001", FT_NO_FILTER, b""),
        (FT_NO_FILTER, b"", FT_MATCH_PREFIX, b"sk00"),
        (FT_MATCH_ANYWHERE, b"sh000", FT_MATCH_POSTFIX, b"3"),
        (FT_MATCH_POSTFIX, b"21", FT_MATCH_ANYWHERE, b"k0"),
        (FT_MATCH_PREFIX, b"hash00013zzzz", FT_MATCH_PREFIX,
         b"sk00333"),
    ]
    probe0 = METRICS.entity("storage", "node").counter(
        "encoded_probe_count").value()
    for i in range(len(t.blocks)):
        blk = t.read_block(i)
        enc = t.read_block_encoded(i)
        dev = block_from_columns(blk.keys, blk.key_len, blk.expire_ts,
                                 hash_lo=blk.hash_lo)
        for validate, pidx, pv in ((False, 0, -1), (True, 1, 3),
                                   (True, 5, 3)):
            for fk in flavors:
                want = np.asarray(static_block_predicate(
                    dev, hash_filter=FilterSpec.make(fk[0], fk[1]),
                    sort_filter=FilterSpec.make(fk[2], fk[3]),
                    validate_hash=validate, pidx=pidx,
                    partition_version=pv))
                got = encoded_static_keep(enc, validate, pidx, pv, fk)
                assert got is not None
                assert np.array_equal(want, got), (i, validate, fk)
    assert METRICS.entity("storage", "node").counter(
        "encoded_probe_count").value() > probe0
    t.close()


def test_encoded_probe_python_fallback(tmp_path, monkeypatch):
    """Without the native library the probe + key-matrix rebuild fall
    back to numpy/scalar paths with identical results."""
    import pegasus_tpu.native as native
    from pegasus_tpu.ops.predicates import (
        FT_MATCH_POSTFIX,
        FT_MATCH_PREFIX,
        encoded_static_keep,
    )

    t = _write(str(tmp_path / "t.sst"), "dcz", n_hash=12, n_sort=4,
               block_capacity=32)
    enc = t.read_block_encoded(0)
    fk = (FT_MATCH_PREFIX, b"hash0000", FT_MATCH_POSTFIX, b"2")
    with_native = encoded_static_keep(enc, True, 1, 3, fk)
    km_native = enc.key_matrix()
    monkeypatch.setattr(native, "region_filter_fn", lambda: None)
    monkeypatch.setattr(native, "cblock_decode_keys_fn", lambda: None)
    assert np.array_equal(with_native,
                          encoded_static_keep(enc, True, 1, 3, fk))
    assert np.array_equal(km_native, enc.key_matrix())
    t.close()


def test_lazy_heap_defers_inflate_to_value_access(tmp_path):
    t = _write(str(tmp_path / "t.sst"), "dcz")
    blk = t.read_block(0)
    # key-side work happens without inflating the value heap
    assert blk.key_at(0) == generate_key(b"hash00000", b"sk000")
    assert blk.alive_mask(epoch_now()).all()
    assert callable(blk._vh), "heap inflated before any value access"
    v = blk.value_at(0)
    assert v == b"value|%05d|%03d|" % (0, 0) * 2
    assert not callable(blk._vh)
    t.close()


# ---- byte-capped block cache ------------------------------------------


def test_block_cache_byte_cap_and_evict_counter(tmp_path):
    t = _write(str(tmp_path / "t.sst"), "dcz", n_hash=64, n_sort=4,
               block_capacity=16)
    assert len(t.blocks) >= 8
    ent = METRICS.entity("storage", "node")
    d0 = ent.counter("compressed_block_decode_count").value()
    e0 = ent.counter("block_cache_evict_bytes").value()
    t.close()
    # budget for ~2 decoded blocks: each charges n*W + 13n + heap + 512
    one = (16 * 32 + 13 * 16 + 16 * 2 * len(b"value|00000|000|")
           + 512)
    t = SSTable(str(tmp_path / "t.sst"), cache_bytes=2 * one + 64)
    for i in range(len(t.blocks)):
        t.read_block(i)
    assert len(t._cache) <= 2
    assert t._cache_bytes <= 2 * one + 64
    ent2 = METRICS.entity("storage", "node")
    assert ent2.counter(
        "compressed_block_decode_count").value() >= d0 + len(t.blocks)
    assert ent2.counter("block_cache_evict_bytes").value() > e0
    # a re-read of an evicted block decodes again (counted)
    d1 = ent2.counter("compressed_block_decode_count").value()
    t.read_block(0)
    assert ent2.counter(
        "compressed_block_decode_count").value() == d1 + 1
    t.close()


# ---- compaction --------------------------------------------------------


def _build_engine(data_dir, codec, expired_every=4):
    from pegasus_tpu.storage.engine import StorageEngine, WriteBatchItem
    from pegasus_tpu.storage.wal import OP_PUT

    old = FLAGS.get("pegasus.storage", "block_codec")
    FLAGS.set("pegasus.storage", "block_codec", codec)
    try:
        eng = StorageEngine(data_dir, block_capacity=64)
        now = epoch_now()
        d = 0
        for h in range(60):
            items = []
            for s in range(8):
                i = h * 8 + s
                ets = int(now - 40) if i % expired_every == 0 else 0
                items.append(WriteBatchItem(
                    OP_PUT, generate_key(b"user%05d" % h, b"s%02d" % s),
                    b"payload|%05d|%02d|" % (h, s) * 3, ets))
            d += 1
            eng.write_batch(items, d)
        eng.flush()
        eng.manual_compact()        # merge path -> compressed L1
        assert eng.lsm.bulk_compact_eligible()
        eng.manual_compact()        # bulk path (encoded drop masks)
    finally:
        FLAGS.set("pegasus.storage", "block_codec", old)
    return eng


def test_bulk_compact_identity_and_verbatim_copy(tmp_path, codec_flag):
    ea = _build_engine(str(tmp_path / "raw"), "none")
    eb = _build_engine(str(tmp_path / "dcz"), "dcz")
    assert list(ea.iterate()) == list(eb.iterate())
    assert all(t.codec == "dcz" for t in eb.lsm.l1_runs)
    # with the expired rows already dropped, a second bulk compaction
    # copies every compressed block VERBATIM (no decode): the decode
    # counter must not move while the output stays identical
    FLAGS.set("pegasus.storage", "block_codec", "dcz")
    before = list(eb.iterate())
    for t in eb.lsm.l1_runs:
        t.clear_block_cache()
    d0 = METRICS.entity("storage", "node").counter(
        "compressed_block_decode_count").value()
    eb.manual_compact()
    assert METRICS.entity("storage", "node").counter(
        "compressed_block_decode_count").value() == d0
    assert list(eb.iterate()) == before
    # iterate() above re-decoded blocks; that's expected — only the
    # compaction itself must stay decode-free
    ea.close()
    eb.close()


def test_bulk_compact_ttl_drop_on_compressed_matches_raw(tmp_path,
                                                         codec_flag):
    """default_ttl rewrite + expiry on the encoded fast path produces
    the same surviving records as the raw/device path."""
    ea = _build_engine(str(tmp_path / "raw"), "none", expired_every=3)
    eb = _build_engine(str(tmp_path / "dcz"), "dcz", expired_every=3)
    ra, rb = list(ea.iterate()), list(eb.iterate())
    assert ra == rb
    assert len(ra) == 60 * 8 - (60 * 8 + 2) // 3
    ea.close()
    eb.close()


def test_writer_finish_sites_all_stamp_codec(tmp_path, codec_flag):
    """flush, merge-compact, bulk-compact, ingest: every site produces
    codec-stamped files with working bloom filters."""
    from pegasus_tpu.storage.engine import StorageEngine, WriteBatchItem
    from pegasus_tpu.storage.wal import OP_PUT

    FLAGS.set("pegasus.storage", "block_codec", "dcz")
    eng = StorageEngine(str(tmp_path / "e"), block_capacity=32)
    for d in range(1, 41):
        eng.write_batch([WriteBatchItem(
            OP_PUT, generate_key(b"fk%04d" % d, b"s"),
            b"v%04d" % d)], d)
    eng.flush()
    assert eng.lsm.l0[0].codec == "dcz"           # flush site
    assert eng.lsm.l0[0].bloom is not None
    eng.manual_compact()
    assert all(t.codec == "dcz" for t in eng.lsm.l1_runs)  # merge site
    eng.manual_compact()                           # bulk site
    assert all(t.codec == "dcz" and t.bloom is not None
               for t in eng.lsm.l1_runs)

    ext = str(tmp_path / "ext.sst")
    w = SSTableWriter(ext, block_capacity=16)
    for i in range(20):
        w.add(generate_key(b"ing%04d" % i, b"s"), b"iv%04d" % i)
    w.finish()
    eng.ingest_sst_file(ext, decree=100)           # ingest site
    assert eng.lsm.l0[0].codec == "dcz"
    assert eng.get(generate_key(b"ing0007", b"s")) == (b"iv0007", 0)
    assert eng.get(generate_key(b"fk0011", b"s")) == (b"v0011", 0)
    eng.close()


def test_subset_single_survivor_fence_keys(tmp_path, codec_flag):
    """A subset keeping exactly ONE row must report that key as BOTH
    first and last key — a zeroed last-key slot corrupts the published
    block fence and point reads silently skip the block."""
    from pegasus_tpu import native

    fn = native.cblock_subset_fn()
    if fn is None:
        pytest.skip("native library unavailable")

    n = 16
    keys = np.zeros((n, 32), dtype=np.uint8)
    recs = [generate_key(b"hk%02d" % i, b"s") for i in range(n)]
    kl = np.array([len(r) for r in recs], np.int32)
    for i, r in enumerate(recs):
        keys[i, :len(r)] = np.frombuffer(r, np.uint8)
    offs = np.zeros(n + 1, np.uint32)
    offs[1:] = np.cumsum([8] * n)
    raw = encode_block(keys, kl, np.zeros(n, np.uint32),
                       np.arange(n, dtype=np.uint32),
                       np.zeros(n, np.uint8), offs, b"v" * (8 * n))
    src = EncodedBlock.parse(raw)
    keep = np.zeros(n, np.uint8)
    keep[7] = 1
    res = fn(raw, src.raw_heap_len, 32, keep, None, False, False)
    assert res is not None
    _buf, _h, m, _vsub, fk, lk = res
    assert m == 1
    assert fk == recs[7] and lk == recs[7]

    # end-to-end: TTL-expire all but one record per store, bulk
    # compact, and the lone survivor must still be point-readable
    from pegasus_tpu.storage.engine import StorageEngine, WriteBatchItem
    from pegasus_tpu.storage.wal import OP_PUT

    FLAGS.set("pegasus.storage", "block_codec", "dcz")
    eng = StorageEngine(str(tmp_path / "e"), block_capacity=64)
    now = epoch_now()
    for d in range(1, 41):
        ets = 0 if d == 17 else int(now - 40)
        eng.write_batch([WriteBatchItem(
            OP_PUT, generate_key(b"sk%04d" % d, b"s"),
            b"val%04d" % d, ets)], d)
    eng.flush()
    eng.manual_compact()
    assert eng.lsm.bulk_compact_eligible()
    eng.manual_compact()
    assert eng.get(generate_key(b"sk0017", b"s")) == (b"val0017", 0)
    assert list(eng.iterate()) != []
    eng.close()


def test_bulk_compact_all_dropped_publishes_no_runs(tmp_path,
                                                    codec_flag):
    """Every record expired -> bulk compaction must publish ZERO L1
    runs (the encoded subset path must not instantiate a writer for
    fully-dropped blocks)."""
    from pegasus_tpu.storage.engine import StorageEngine, WriteBatchItem
    from pegasus_tpu.storage.wal import OP_PUT

    FLAGS.set("pegasus.storage", "block_codec", "dcz")
    eng = StorageEngine(str(tmp_path / "e"), block_capacity=32)
    now = epoch_now()
    for d in range(1, 41):
        eng.write_batch([WriteBatchItem(
            OP_PUT, generate_key(b"gone%04d" % d, b"s"),
            b"v%04d" % d, int(now - 40))], d)
    eng.flush()
    eng.manual_compact()
    if eng.lsm.bulk_compact_eligible():
        eng.manual_compact()
    assert list(eng.iterate()) == []
    empties = [t for t in eng.lsm.l1_runs if t.record_count == 0] \
        if eng.lsm.l1_runs and hasattr(eng.lsm.l1_runs[0],
                                       "record_count") else []
    assert not empties
    assert not eng.lsm.l1_runs, [t.path for t in eng.lsm.l1_runs]
    eng.close()


def test_encode_block_rejects_nonzero_offset_base():
    with pytest.raises(ValueError):
        encode_block(np.zeros((1, 32), np.uint8),
                     np.array([4], np.int32), np.zeros(1, np.uint32),
                     np.zeros(1, np.uint32), np.zeros(1, np.uint8),
                     np.array([2, 3], np.uint32), b"abc")


def test_zlib_heap_blocks_still_decode_and_compact(tmp_path,
                                                   monkeypatch):
    """The heap compressor moved zlib -> zstd; blocks whose value heap
    was deflated with zlib (heap_mode=1) must keep decoding byte-for-
    byte, and the native subset kernel must take them (re-compressing
    the surviving heap forward to zstd when libzstd resolves)."""
    from pegasus_tpu.storage import block_codec as bc

    # force the encoder onto the zlib fallback for one file
    monkeypatch.setattr(bc._Zstd, "_lib", None)
    monkeypatch.setattr(bc._Zstd, "_tried", True)
    ta = _write(str(tmp_path / "zlib.sst"), "dcz", n_hash=30, n_sort=4)
    monkeypatch.undo()
    assert bc._Zstd.lib() is not None, "container lost libzstd"

    tb = _write(str(tmp_path / "zstd.sst"), "dcz", n_hash=30, n_sort=4)
    _assert_blocks_identical(ta, tb)
    assert list(ta.iterate()) == list(tb.iterate())

    # at least one heap must actually be compressed in each file, and
    # with different compressors (mode 1 vs mode 2)
    def modes(t):
        return {t.read_block_encoded(i).heap_mode
                for i in range(len(t.blocks))}
    ma, mb = modes(ta), modes(tb)
    assert bc._HEAP_ZLIB in ma and bc._HEAP_ZSTD not in ma
    assert bc._HEAP_ZSTD in mb and bc._HEAP_ZLIB not in mb
    ta.close()
    tb.close()


def test_native_subset_takes_both_heap_modes(monkeypatch):
    """pegasus_cblock_subset inflates zlib AND zstd heaps; the subset
    of a zlib-heap block re-compresses forward to zstd."""
    from pegasus_tpu import native
    from pegasus_tpu.storage import block_codec as bc

    fn = native.cblock_subset_fn()
    if fn is None:
        pytest.skip("native library unavailable")

    n = 64
    keys = np.zeros((n, 32), dtype=np.uint8)
    recs = [generate_key(b"hk%02d" % (i // 8), b"s%02d" % (i % 8))
            for i in range(n)]
    kl = np.array([len(r) for r in recs], np.int32)
    for i, r in enumerate(recs):
        keys[i, :len(r)] = np.frombuffer(r, np.uint8)
    vals = b"".join(b"compressible-value-%04d|" % i for i in range(n))
    offs = np.zeros(n + 1, np.uint32)
    offs[1:] = np.cumsum([24] * n)
    ets = np.zeros(n, np.uint32)
    hlo = np.arange(n, dtype=np.uint32)
    flags = np.zeros(n, np.uint8)

    for forced_zlib in (True, False):
        if forced_zlib:
            monkeypatch.setattr(bc._Zstd, "_lib", None)
            monkeypatch.setattr(bc._Zstd, "_tried", True)
        raw = encode_block(keys, kl, ets, hlo, flags, offs, vals)
        if forced_zlib:
            monkeypatch.undo()
        src = EncodedBlock.parse(raw)
        want = bc._HEAP_ZLIB if forced_zlib else bc._HEAP_ZSTD
        assert src.heap_mode == want
        keep = np.zeros(n, np.uint8)
        keep[::2] = 1
        res = fn(raw, src.raw_heap_len, 32, keep, None, False, True)
        assert res is not None
        sub = EncodedBlock.parse(res[0])
        assert sub.n == n // 2
        # surviving heap re-compresses with zstd regardless of source
        assert sub.heap_mode == bc._HEAP_ZSTD
        blk = sub.decode()
        got = [blk.key_at(j) for j in range(sub.n)]
        assert got == recs[::2]
        assert np.asarray(blk.value_heap).tobytes() == b"".join(
            b"compressible-value-%04d|" % i for i in range(0, n, 2))


def test_encoded_block_parse_roundtrip_fields():
    keys = np.zeros((3, 32), dtype=np.uint8)
    recs = [generate_key(b"hk", b"a"), generate_key(b"hk", b"b"),
            generate_key(b"zz", b"a")]
    kl = np.array([len(r) for r in recs], np.int32)
    for i, r in enumerate(recs):
        keys[i, :len(r)] = np.frombuffer(r, np.uint8)
    ets = np.array([0, 5, 0], np.uint32)
    hlo = np.array([1, 1, 2], np.uint32)
    flags = np.array([0, 1, 0], np.uint8)
    offs = np.array([0, 2, 2, 5], np.uint32)
    raw = encode_block(keys, kl, ets, hlo, flags, offs, b"abcde")
    enc = EncodedBlock.parse(raw)
    assert enc.n == 3 and not enc.has_malformed
    assert [enc.key_at(i) for i in range(3)] == recs
    assert enc.dict_entries() == [b"hk", b"zz"]    # 2 unique hashkeys
    blk = enc.decode()
    assert np.array_equal(blk.keys, keys)
    assert np.array_equal(blk.flags, flags)
    assert np.array_equal(blk.value_offs, offs)
    assert bytes(np.asarray(blk.value_heap)) == b"abcde"
