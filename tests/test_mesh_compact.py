"""Mesh-resident compaction filtering acceptance: ONE whole-table SPMD
dispatch must hand every sibling partition's bulk compaction its drop
masks (and rewritten-TTL column) BYTE-IDENTICALLY to the host-serial
and host-pipelined filter stages over every store shape — mixed
none/dcz/dcz2 histories, empty-hashkey overflow rows, verbatim-carry
blocks, default-TTL rewrites and user rulesets — degrade through the
tunnel watchdog to host filtering with identical published files, and
close the publish loop by survivor-gathering residency (reuse counter)
instead of restaging every block (rebuild counter)."""

import hashlib
import os
import shutil

# idempotent with conftest: the virtual 8-device CPU mesh must exist
# before jax initializes (standalone runs of this module included)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

import pytest

from pegasus_tpu.base.value_schema import epoch_now
from pegasus_tpu.client.client import PegasusClient
from pegasus_tpu.client.table import Table
from pegasus_tpu.ops.compaction_rules import compile_rules
from pegasus_tpu.parallel.mesh_resident import MESH_SERVING
from pegasus_tpu.utils.flags import FLAGS

N_PARTS = 8
FROZEN_FINISH = 400_000_000  # finish-time stamp lands in the SST index

RULES = ('[{"op":"delete_key","rules":[{"type":"hashkey_pattern",'
         '"match":"prefix","pattern":"hk01"}]},'
         '{"op":"update_ttl","update_ttl_type":"from_now","value":1234,'
         '"rules":[{"type":"sortkey_pattern","match":"anywhere",'
         '"pattern":"s001"}]}]')


@pytest.fixture
def mesh_guard(monkeypatch):
    """Flag + singleton isolation, plus a frozen compaction finish-time
    stamp: manual_compact_finish_time = epoch_now() is written into the
    SST index, so two arms straddling a wall-clock second boundary
    would diverge on bytes that have nothing to do with the filter."""
    import pegasus_tpu.storage.engine as engine_mod

    saved = [(sec, name, FLAGS.get(sec, name)) for sec, name in (
        ("pegasus.storage", "block_codec"),
        ("pegasus.storage", "compact_pipeline"),
        ("pegasus.mesh", "serving_enabled"),
        ("pegasus.mesh", "dispatch_deadline_s"),
    )]
    monkeypatch.setattr(engine_mod, "epoch_now", lambda: FROZEN_FINISH)
    MESH_SERVING.reset()
    yield
    MESH_SERVING.reset()
    for sec, name, val in saved:
        FLAGS.set(sec, name, val)


def force_compact_pays(monkeypatch):
    """Tiny fixtures never amortize a dispatch; identity tests pin the
    gate open so every compaction exercises the mesh path (the honest
    gate has its own unit test + the bench's 8-partition phase)."""
    from pegasus_tpu.ops import placement
    monkeypatch.setattr(placement, "mesh_compact_pays",
                        lambda *_a, **_k: True)


def build_store(tmp_path, final_codec="none"):
    """8 partitions crossing every storage shape: rows written under
    three codec generations, TTL'd rows that will expire at the arms'
    fixed filter timestamp, empty-hashkey overflow rows — then
    compacted to the pure L1 the bulk path requires (under
    `final_codec`, so dcz/dcz2 arms exercise the encoded-domain
    verbatim/subset write paths)."""
    base = str(tmp_path / "base")
    table = Table(base, partition_count=N_PARTS)
    c = PegasusClient(table)
    i = 0
    for codec in ("none", "dcz", "dcz2"):
        FLAGS.set("pegasus.storage", "block_codec", codec)
        for _ in range(200):
            rc = c.set(b"hk%03d" % (i % 40), b"s%05d" % i, b"v%05d" % i,
                       ttl_seconds=7 if i % 3 == 0 else 0)
            assert rc == 0
            i += 1
        assert c.set(b"", b"osk%02d" % (i % 7), b"ovf-%d" % i) == 0
        i += 1
        table.flush_all()
    FLAGS.set("pegasus.storage", "block_codec", final_codec)
    for s in table.partitions.values():
        s.engine.flush()
        s.engine.manual_compact()
    for s in table.partitions.values():
        assert s.engine.lsm.bulk_compact_eligible()
    table.close()
    return base


def digest(d):
    """(relpath, sha256) of every published SST under the table dir."""
    out = []
    for root, _dirs, files in os.walk(d):
        for f in sorted(files):
            if f.endswith(".sst"):
                p = os.path.join(root, f)
                with open(p, "rb") as fh:
                    out.append((os.path.relpath(p, d),
                                hashlib.sha256(fh.read()).hexdigest()))
    return sorted(out)


def compact_arm(base, name, now, *, mesh=False, wedge=False,
                pipelined=True, default_ttl=0, rules=None):
    """Copy the base store, compact every partition at the shared
    fixed `now`, return (sst digests, iterated rows, serving status)."""
    d = base + "_" + name
    shutil.rmtree(d, ignore_errors=True)
    shutil.copytree(base, d)
    MESH_SERVING.reset()
    FLAGS.set("pegasus.storage", "compact_pipeline", pipelined)
    t = Table(d, partition_count=N_PARTS)
    try:
        if mesh:
            for s in t.partitions.values():
                MESH_SERVING.attach(s)
        if wedge:
            MESH_SERVING.watchdog.deadline_s = 1e-9
        for s in t.partitions.values():
            s.manual_compact(default_ttl=default_ttl, rules_filter=rules,
                             now=now)
        st = MESH_SERVING.status()
        rows = {p: list(s.engine.lsm.iterate())
                for p, s in sorted(t.partitions.items())}
        return digest(d), rows, st
    finally:
        t.close()
        MESH_SERVING.reset()


@pytest.mark.parametrize("codec", ["none", "dcz", "dcz2"])
def test_identity_host_serial_pipelined_mesh(tmp_path, mesh_guard,
                                             monkeypatch, codec):
    """The tentpole gate: host-serial, host-pipelined, and mesh-filter
    modes publish the exact same bytes, and the mesh mode really serves
    the whole table from ONE dispatch (7 sibling cache hits)."""
    base = build_store(tmp_path, final_codec=codec)
    now = epoch_now() + 3600  # every ttl_seconds=7 row is expired
    serial, s_rows, _ = compact_arm(base, "serial", now, pipelined=False)
    piped, p_rows, _ = compact_arm(base, "piped", now)
    force_compact_pays(monkeypatch)
    meshed, m_rows, st = compact_arm(base, "mesh", now, mesh=True)
    assert serial == piped == meshed
    assert s_rows == p_rows == m_rows
    assert any(s_rows.values()), "degenerate fixture: nothing survived"
    assert st["compact_dispatches"] == 1
    assert st["compact_mask_serves"] == N_PARTS
    assert st["compact_mesh_fallback_count"] == 0


def test_identity_default_ttl_and_rules(tmp_path, mesh_guard,
                                        monkeypatch):
    """want_ets leg: a default-TTL rewrite plus a user ruleset
    (delete_key + update_ttl) must patch TTL headers identically
    whether the new-ets column came off the mesh or the host."""
    base = build_store(tmp_path, final_codec="dcz2")
    now = epoch_now() + 3600
    host, h_rows, _ = compact_arm(base, "host", now, default_ttl=500,
                                  rules=compile_rules(RULES))
    force_compact_pays(monkeypatch)
    meshed, m_rows, st = compact_arm(base, "mesh", now, mesh=True,
                                     default_ttl=500,
                                     rules=compile_rules(RULES))
    assert host == meshed
    assert h_rows == m_rows
    assert st["compact_dispatches"] == 1
    assert st["compact_mask_serves"] == N_PARTS


def test_wedged_watchdog_publishes_identical_files(tmp_path, mesh_guard,
                                                   monkeypatch):
    """A tripped mesh mid-compaction degrades to host filtering and
    still publishes byte-identical files — zero masks served off the
    mesh, the fallback counter proves the degradation was exercised."""
    base = build_store(tmp_path)
    now = epoch_now() + 3600
    host, h_rows, _ = compact_arm(base, "host", now)
    force_compact_pays(monkeypatch)
    wedged, w_rows, st = compact_arm(base, "wedged", now, mesh=True,
                                     wedge=True)
    assert host == wedged
    assert h_rows == w_rows
    assert st["compact_dispatches"] == 0
    assert st["compact_mesh_fallback_count"] >= 1
    assert st["watchdog"]["trips"] >= 1


def test_publish_refresh_reuses_survivor_masks(tmp_path, mesh_guard,
                                               monkeypatch):
    """Satellite pin: a compaction publish on a mesh-filtered table
    must refresh residency by survivor-gather (reuse counter, no slab
    build), while a publish the mesh did NOT filter takes the rebuild
    path — the counter split proves which happened."""
    base = build_store(tmp_path)
    now = epoch_now() + 3600
    force_compact_pays(monkeypatch)
    d = base + "_refresh"
    shutil.copytree(base, d)
    MESH_SERVING.reset()
    t = Table(d, partition_count=N_PARTS)
    try:
        for s in t.partitions.values():
            MESH_SERVING.attach(s)
        assert MESH_SERVING.ensure_current()
        builds0 = MESH_SERVING.slab_builds
        for s in t.partitions.values():
            s.manual_compact(now=now)
        assert MESH_SERVING.ensure_current()
        st = MESH_SERVING.status()
        assert st["compact_dispatches"] == 1
        # instance split (zeroed by reset); the _count twins are the
        # process-global metrics-node counters lint covers
        assert st["refresh_reuses"] == N_PARTS
        assert st["refresh_rebuilds"] == 0
        assert MESH_SERVING.slab_builds == builds0, \
            "survivor reuse must not restage a single slab"
        # the refreshed image matches the store it claims to mirror
        for pidx, s in t.partitions.items():
            tres = MESH_SERVING._tables[s.app_id]
            slab = tres.slabs[pidx]
            assert slab.generation == s.engine.lsm.generation
            assert slab.n_rows == sum(
                int(bm.count) for run in s.engine.lsm.l1_runs
                for bm in run.blocks)
        # control: a publish the mesh did not filter rebuilds
        c = PegasusClient(t)
        assert c.set(b"hk000", b"snew", b"fresh") == 0
        for s in t.partitions.values():
            s.engine.flush()
            s.engine.manual_compact()  # merge path, no mesh masks
        assert MESH_SERVING.ensure_current()
        st2 = MESH_SERVING.status()
        assert st2["refresh_rebuilds"] >= 1
    finally:
        t.close()
        MESH_SERVING.reset()


def test_compact_gate_honest_and_breakdown():
    """mesh_compact_pays: a solo one-window compaction stays on the
    host; a many-window whole-table batch pays. offload_breakdown grows
    the compaction block `shell placement` renders."""
    from pegasus_tpu.ops import placement

    assert not placement.mesh_compact_pays(1, 64 * 1024)
    assert placement.mesh_compact_pays(64, 512 * 1024 * 1024)
    bd = placement.offload_breakdown("rules", 1 << 20)
    c = bd["compact"]
    assert c["workload"] == "mesh_compact"
    assert {"n_windows", "mask_bytes", "mesh_pays",
            "mesh_batch_s_est", "host_batch_s_est"} <= set(c)
    # explicit window-count override (shell placement --windows)
    c64 = placement.compact_breakdown(1 << 28, n_windows=64)
    assert c64["n_windows"] == 64
    assert c64["host_batch_s_est"] > c["host_batch_s_est"]


def test_compact_counters_lint_and_status(mesh_guard):
    """The new dispatch-site counters register through the metrics
    node (metrics_lint coverage) and surface in MESH_SERVING.status()
    for the shell placement/mesh blocks."""
    from pegasus_tpu.tools.metrics_lint import _PKG_ROOT, lint, scan_tree

    regs = scan_tree(_PKG_ROOT)
    for name in ("compact_mesh_dispatch_count",
                 "compact_mesh_fallback_count",
                 "mesh_refresh_reuse_count",
                 "mesh_refresh_rebuild_count"):
        assert name in regs, name
    assert not [c for c in lint() if "compact_mesh" in c
                or "mesh_refresh" in c]
    st = MESH_SERVING.status()
    for key in ("compact_mesh_dispatch_count",
                "compact_mesh_fallback_count",
                "mesh_refresh_reuse_count",
                "mesh_refresh_rebuild_count",
                "compact_dispatches", "compact_mask_serves",
                "refresh_reuses", "refresh_rebuilds"):
        assert key in st, key
