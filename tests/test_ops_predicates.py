"""Device predicate kernels vs host reference semantics.

Cross-checks the jnp kernels against a straightforward scalar Python port of
the reference's validate_filter / validate_key_value_for_scan logic
(src/server/pegasus_server_impl.cpp:2350,2382).
"""

import numpy as np
import pytest

from pegasus_tpu.base.crc import crc64
from pegasus_tpu.base.key_schema import generate_key, key_hash
from pegasus_tpu.ops import (
    FT_MATCH_ANYWHERE,
    FT_MATCH_PREFIX,
    FT_MATCH_POSTFIX,
    FT_NO_FILTER,
    FilterSpec,
    RecordBlock,
    build_record_block,
    scan_block_predicate,
)
from pegasus_tpu.ops.device_crc import crc64_device, key_hash_device


def scalar_match(filter_type: int, pattern: bytes, value: bytes) -> bool:
    """Scalar port of validate_filter for cross-checking."""
    if filter_type == FT_NO_FILTER:
        return True
    if len(pattern) == 0:
        return True
    if len(value) < len(pattern):
        return False
    if filter_type == FT_MATCH_ANYWHERE:
        return pattern in value
    if filter_type == FT_MATCH_PREFIX:
        return value.startswith(pattern)
    return value.endswith(pattern)


def _random_keys(rng, n, with_pattern=b""):
    keys = []
    for _ in range(n):
        hk = bytes(rng.integers(97, 123, size=rng.integers(1, 12), dtype=np.uint8))
        sk = bytes(rng.integers(97, 123, size=rng.integers(0, 20), dtype=np.uint8))
        if with_pattern and rng.random() < 0.5:
            pos = rng.integers(0, len(sk) + 1)
            sk = sk[:pos] + with_pattern + sk[pos:]
        keys.append(generate_key(hk, sk))
    return keys


def test_device_crc64_matches_host():
    rng = np.random.default_rng(2)
    keys = _random_keys(rng, 33)
    block = build_record_block(keys, [0] * len(keys), capacity=64)
    hi, lo = crc64_device(np.asarray(block.keys), block.key_len - 2, start=2)
    for i, k in enumerate(keys):
        hk, _ = k[2:2 + block.hashkey_len[i]], None
        full = crc64(k[2:len(k)])
        got = (int(hi[i]) << 32) | int(lo[i])
        assert got == full


def test_key_hash_device_matches_host():
    rng = np.random.default_rng(3)
    keys = _random_keys(rng, 20) + [generate_key(b"", b"sortonly")]
    block = build_record_block(keys, [0] * len(keys), capacity=32)
    hi, lo = key_hash_device(np.asarray(block.keys), block.key_len,
                             block.hashkey_len)
    for i, k in enumerate(keys):
        got = (int(hi[i]) << 32) | int(lo[i])
        assert got == key_hash(k), f"record {i}"


@pytest.mark.parametrize("ftype", [FT_NO_FILTER, FT_MATCH_ANYWHERE,
                                   FT_MATCH_PREFIX, FT_MATCH_POSTFIX])
@pytest.mark.parametrize("target", ["hash", "sort"])
def test_filter_matches_scalar_semantics(ftype, target):
    rng = np.random.default_rng(4 + ftype)
    pattern = b"abc"
    keys = _random_keys(rng, 100, with_pattern=pattern)
    block = build_record_block(keys, [0] * len(keys), capacity=128)
    spec = FilterSpec.make(ftype, pattern)
    kwargs = {"hash_filter": spec} if target == "hash" else {"sort_filter": spec}
    masks = scan_block_predicate(block, now=0, **kwargs)
    keep = np.asarray(masks.keep)
    for i, k in enumerate(keys):
        hk_len = int(block.hashkey_len[i])
        hk, sk = k[2:2 + hk_len], k[2 + hk_len:]
        region = hk if target == "hash" else sk
        assert keep[i] == scalar_match(ftype, pattern, region), (
            f"record {i}: hk={hk!r} sk={sk!r}")
    # padding never kept
    assert not keep[len(keys):].any()


def test_empty_pattern_matches_everything():
    keys = [generate_key(b"h", b"s")]
    block = build_record_block(keys, [0])
    for ftype in (FT_MATCH_ANYWHERE, FT_MATCH_PREFIX, FT_MATCH_POSTFIX):
        masks = scan_block_predicate(block, 0,
                                     sort_filter=FilterSpec.make(ftype, b""))
        assert bool(masks.keep[0])


def test_pattern_longer_than_region_never_matches():
    keys = [generate_key(b"h", b"ab")]
    block = build_record_block(keys, [0])
    for ftype in (FT_MATCH_ANYWHERE, FT_MATCH_PREFIX, FT_MATCH_POSTFIX):
        masks = scan_block_predicate(block, 0,
                                     sort_filter=FilterSpec.make(ftype, b"abc"))
        assert not bool(masks.keep[0])


def test_ttl_and_precedence():
    now = 1000
    keys = [generate_key(b"h%d" % i, b"s") for i in range(4)]
    # record 0: live; record 1: expired; record 2: expired AND filtered
    # (expired wins); record 3: filtered only
    ets = [0, 500, 500, 0]
    block = build_record_block(keys, ets)
    masks = scan_block_predicate(
        block, now, sort_filter=FilterSpec.make(FT_MATCH_PREFIX, b"zzz"))
    assert list(np.asarray(masks.keep)) == [False, False, False, False]
    assert list(np.asarray(masks.expired)) == [False, True, True, False]
    assert list(np.asarray(masks.filtered)) == [True, False, False, True]
    # boundary: expire_ts == now is expired
    block2 = build_record_block(keys[:1], [now])
    assert bool(scan_block_predicate(block2, now).expired[0])
    # future is live
    block3 = build_record_block(keys[:1], [now + 1])
    assert bool(scan_block_predicate(block3, now).keep[0])


def test_partition_hash_validation():
    pc = 8
    keys, ets = [], []
    for i in range(50):
        hk = b"user_%d" % i
        keys.append(generate_key(hk, b"s"))
        ets.append(0)
    block = build_record_block(keys, ets, capacity=64)
    pidx = 3
    masks = scan_block_predicate(block, 0, validate_hash=True, pidx=pidx,
                                 partition_version=pc - 1)
    keep = np.asarray(masks.keep)
    inval = np.asarray(masks.hash_invalid)
    for i, k in enumerate(keys):
        serves = (key_hash(k) & (pc - 1)) == pidx
        assert keep[i] == serves
        assert inval[i] == (not serves)


def test_partition_version_negative_rejects_all():
    keys = [generate_key(b"h", b"s"), generate_key(b"h2", b"s")]
    # second record is expired: expiry precedence holds even on the
    # invalid-partition-state path (reference checks expiry first,
    # pegasus_server_impl.cpp:2392)
    block = build_record_block(keys, [0, 5])
    masks = scan_block_predicate(block, 100, validate_hash=True, pidx=0,
                                 partition_version=-1)
    assert not bool(masks.keep[0]) and not bool(masks.keep[1])
    assert bool(masks.hash_invalid[0]) and not bool(masks.hash_invalid[1])
    assert bool(masks.expired[1])
