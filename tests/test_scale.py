"""Scale harness: the million-user elasticity proof artifact + the
two-cluster geo-replication soak.

The @slow soaks are the ROADMAP deliverables — a multi-process onebox
with ≥128 partitions under chaos through a split and a rebalance, and
the WAN topology: two oneboxes, A geo-replicating to B across a faulted
link with kill chaos on both sides, ending in the controlled failover
drill with the DataVerifier invariant (zero acked-write loss) replayed
against B. The fast tests pin seeded determinism so tier-1 exercises
the workload shape — and a full seeded-sim twin of the WAN drill — on
every run (the sim twin of the elasticity loop itself lives in
tests/test_elasticity.py).
"""

import random

import pytest

from pegasus_tpu.tools.scale_test import zipf_keys


def test_zipf_workload_is_seeded_and_skewed():
    a = zipf_keys(random.Random(7), 1000, 1.2, 5000)
    b = zipf_keys(random.Random(7), 1000, 1.2, 5000)
    assert a == b  # replayable from the seed
    from collections import Counter

    counts = Counter(a)
    top = counts.most_common(10)
    # zipfian shape: the head dominates, the tail is long
    assert top[0][1] > 5 * top[9][1]
    assert len(counts) > 100


def test_zipf_tenants_draw_distinct_streams():
    a = zipf_keys(random.Random(1000), 1000, 1.2, 200)
    b = zipf_keys(random.Random(2000), 1000, 1.2, 200)
    assert a != b


@pytest.mark.slow
def test_scale_soak_split_and_rebalance_under_chaos(tmp_path):
    """≥128 partitions across 4 tenant tables on a 3-process onebox:
    zipfian multi-tenant load + kill chaos driven through one online
    split and one rebalance — no verifier violations, no lost acks."""
    from pegasus_tpu.tools.scale_test import run_scale_test

    report = run_scale_test(
        str(tmp_path / "soak"), n_tenants=4, partitions=32,
        duration_s=45, n_replica=3, seed=3, chaos_mode="kill",
        kill_every_s=18)
    assert report["violations"] == [], report["violations"]
    assert report["split_started"] and report["split_done"], report
    assert report["rebalance_proposals"] is not None
    # 4x32 created, tenant0 doubled by the online split
    assert report["partition_total"] >= 4 * 32 + 32
    assert report["kills"] >= 1
    total_acked = sum(t["writes_acked"]
                      for t in report["tenants"].values())
    assert total_acked > 40
    # the controller's signal surface was live during the run
    hp = report["hot_partitions"]
    assert hp and len(hp["partitions"]) >= 128


def test_wan_sim_twin_chaos_and_failover_drill(tmp_path):
    """Fast seeded-sim twin of the WAN soak (tier-1): two SimClusters
    on one wire with delay+loss on the inter-cluster links, a kill on
    EACH side mid-stream, then the controlled failover drill — fence
    (typed ERR_DUP_FENCED to clients), drain confirmed==last_committed,
    flip — and every write A ever acked reads back on B."""
    from pegasus_tpu.runtime.sim import SimLoop, SimNetwork
    from pegasus_tpu.tools.cluster import SimCluster
    from pegasus_tpu.utils.errors import ErrorCode, PegasusError

    loop = SimLoop(seed=21)
    net = SimNetwork(loop)
    a = SimCluster(str(tmp_path / "A"), n_nodes=3, name_prefix="a-",
                   loop=loop, net=net, cluster_id=1)
    b = SimCluster(str(tmp_path / "B"), n_nodes=3, name_prefix="b-",
                   loop=loop, net=net, cluster_id=2)

    def step_both(rounds=1):
        for _ in range(rounds):
            a.step()
            b.step(advance=False)

    try:
        step_both(2)
        a.create_table("t", partition_count=2, replica_count=3)
        b.create_table("t", partition_count=2, replica_count=3)
        a.meta.duplication.add_duplication("t", "b-meta", "t")
        # WAN shape on every inter-cluster link, both directions
        for s in list(a.stubs) + [m.name for m in a.metas]:
            for d in list(b.stubs) + [m.name for m in b.metas]:
                net.set_delay(0.08, src=s, dst=d)
                net.set_delay(0.08, src=d, dst=s)
                net.set_drop(0.1, src=s, dst=d)
                net.set_drop(0.1, src=d, dst=s)
        ca = a.client("t")
        acked = {}
        seq = 0
        for burst in range(4):
            for _ in range(10):
                seq += 1
                hk = b"w%04d" % seq
                if ca.set(hk, b"s", b"v%d" % seq) == 0:
                    acked[hk] = b"v%d" % seq
            if burst == 1:
                # kill one node on each side mid-stream; guardians cure
                a.kill(sorted(a.stubs)[1])
                b.kill(sorted(b.stubs)[1])
            if burst == 2:
                a.revive(sorted(a.stubs)[1])
                b.revive(sorted(b.stubs)[1])
            step_both(3)
        assert len(acked) >= 30
        # ---- the drill ----------------------------------------------
        a.meta.duplication.start_failover("t")
        step_both(1)
        # fenced: a client write surfaces the typed retryable error
        c2 = a.client("t", name="a-fence-probe")
        c2.max_retries = 1
        with pytest.raises(PegasusError) as ei:
            if c2.set(b"fenced", b"s", b"x") != 0:
                raise PegasusError(ErrorCode.ERR_DUP_FENCED, "gated")
        assert "DUP_FENCED" in str(ei.value)
        done = False
        for _ in range(25):
            step_both(1)
            st = a.meta.duplication.failover_status("t")
            if st["phase"] == "done":
                done = True
                break
        assert done, st
        assert st["drained"] or st["phase"] == "done"
        # ---- the invariant: zero acked-write loss on B --------------
        cb = b.client("t")
        lost = [hk for hk, v in acked.items()
                if cb.get(hk, b"s") != (0, v)]
        assert lost == [], f"{len(lost)} acked writes missing on B"
        # fence rejections were actually observed by A's nodes
        from pegasus_tpu.utils.metrics import METRICS

        fence = sum(ent["metrics"].get("dup_fence_reject_count",
                                       {}).get("value", 0)
                    for ent in METRICS.snapshot("storage"))
        assert fence >= 1
    finally:
        a.close()
        b.close()


@pytest.mark.slow
def test_wan_soak_two_oneboxes_failover_drill(tmp_path):
    """The WAN topology soak (multi-process, real TCP): A duplicates 2
    tenant tables to B across a delayed+lossy link with a mid-run full
    blackout, kill chaos alternating across BOTH clusters, ending in
    the failover drill — fence, drain, flip — after which the
    DataVerifier ledger replays every acked write against B. Zero
    violations = zero acked-write loss."""
    from pegasus_tpu.tools.scale_test import run_wan_test

    report = run_wan_test(
        str(tmp_path / "wan"), n_tenants=2, partitions=4,
        duration_s=40, n_replica=2, seed=5, kill_every_s=14)
    assert report["violations"] == [], report["violations"]
    assert report["drill_done"], report.get("drill")
    assert report["kills_a"] >= 1 and report["kills_b"] >= 1
    assert report["blackout_done"]
    total_acked = sum(t["writes_acked"]
                      for t in report["tenants"].values())
    assert total_acked > 40
    stats = report.get("dup_stats") or []
    assert stats and sum(s["shipped_bytes"] for s in stats) > 0
