"""Scale harness: the million-user elasticity proof artifact.

The @slow soak is the ROADMAP deliverable — a multi-process onebox with
≥128 partitions, multi-tenant zipfian load with per-tenant CU QoS,
chaos kills, one online split, and one rebalance, all while the
DataVerifier invariant (zero acked-write loss) holds. The fast tests
pin the harness's seeded determinism so tier-1 exercises the workload
shape on every run (the sim twin of the closed loop itself lives in
tests/test_elasticity.py).
"""

import random

import pytest

from pegasus_tpu.tools.scale_test import zipf_keys


def test_zipf_workload_is_seeded_and_skewed():
    a = zipf_keys(random.Random(7), 1000, 1.2, 5000)
    b = zipf_keys(random.Random(7), 1000, 1.2, 5000)
    assert a == b  # replayable from the seed
    from collections import Counter

    counts = Counter(a)
    top = counts.most_common(10)
    # zipfian shape: the head dominates, the tail is long
    assert top[0][1] > 5 * top[9][1]
    assert len(counts) > 100


def test_zipf_tenants_draw_distinct_streams():
    a = zipf_keys(random.Random(1000), 1000, 1.2, 200)
    b = zipf_keys(random.Random(2000), 1000, 1.2, 200)
    assert a != b


@pytest.mark.slow
def test_scale_soak_split_and_rebalance_under_chaos(tmp_path):
    """≥128 partitions across 4 tenant tables on a 3-process onebox:
    zipfian multi-tenant load + kill chaos driven through one online
    split and one rebalance — no verifier violations, no lost acks."""
    from pegasus_tpu.tools.scale_test import run_scale_test

    report = run_scale_test(
        str(tmp_path / "soak"), n_tenants=4, partitions=32,
        duration_s=45, n_replica=3, seed=3, chaos_mode="kill",
        kill_every_s=18)
    assert report["violations"] == [], report["violations"]
    assert report["split_started"] and report["split_done"], report
    assert report["rebalance_proposals"] is not None
    # 4x32 created, tenant0 doubled by the online split
    assert report["partition_total"] >= 4 * 32 + 32
    assert report["kills"] >= 1
    total_acked = sum(t["writes_acked"]
                      for t in report["tenants"].values())
    assert total_acked > 40
    # the controller's signal surface was live during the run
    hp = report["hot_partitions"]
    assert hp and len(hp["partitions"]) >= 128
