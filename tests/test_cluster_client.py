"""Unified-stack tests: client → meta resolution → replica gates.

Parity targets: partition_resolver_simple.h:56 (hash → cached config →
primary, refresh on error), replica_stub.cpp:1100 (read dispatch through
the replica gate), and the kill-test harness's acked-write durability
invariant (src/test/kill_test/data_verifier.cpp).
"""

import pytest

from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.utils.errors import StorageStatus

OK = int(StorageStatus.OK)
NOT_FOUND = int(StorageStatus.NOT_FOUND)


@pytest.fixture
def cluster(tmp_path):
    c = SimCluster(str(tmp_path / "cluster"), n_nodes=4)
    yield c
    c.close()


def test_client_resolves_through_meta(cluster):
    cluster.create_table("t", partition_count=8)
    client = cluster.client("t")
    assert client.set(b"hk", b"sk", b"v") == OK
    assert client.app_id is not None and client.partition_count == 8
    assert client.get(b"hk", b"sk") == (OK, b"v")
    assert client.get(b"hk", b"nope") == (NOT_FOUND, b"")


def test_full_api_over_cluster(cluster):
    cluster.create_table("api", partition_count=4)
    c = cluster.client("api")
    # spread across partitions
    for i in range(40):
        assert c.set(b"u%03d" % i, b"s", b"v%d" % i) == OK
    for i in range(40):
        assert c.get(b"u%03d" % i, b"s") == (OK, b"v%d" % i)
    # multi ops
    assert c.multi_set(b"mh", {b"a": b"1", b"b": b"2"}) == OK
    err, kvs = c.multi_get(b"mh")
    assert err == OK and kvs == {b"a": b"1", b"b": b"2"}
    err, n = c.multi_del(b"mh", [b"a"])
    assert (err, n) == (OK, 1)
    assert c.sortkey_count(b"mh") == (OK, 1)
    # ttl
    assert c.set(b"th", b"ts", b"tv", ttl_seconds=5000) == OK
    err, ttl = c.ttl(b"th", b"ts")
    assert err == OK and 4000 < ttl <= 5000
    # incr
    resp = c.incr(b"ih", b"is", 5)
    assert resp.error == OK and resp.new_value == 5
    # batch_get across partitions
    err, rows = c.batch_get([(b"u%03d" % i, b"s") for i in range(10)])
    assert err == OK and len(rows) == 10
    # delete
    assert c.delete(b"u000", b"s") == OK
    assert not c.exist(b"u000", b"s")


def test_scanners_over_cluster(cluster):
    cluster.create_table("scan", partition_count=4)
    c = cluster.client("scan")
    for i in range(30):
        c.set(b"sc%02d" % (i % 3), b"k%03d" % i, b"v%d" % i)
    # hashkey-scoped ordered scan
    got = [(sk, v) for _hk, sk, v in c.get_scanner(b"sc00")]
    assert got == [(b"k%03d" % i, b"v%d" % i) for i in range(0, 30, 3)]
    # full-table fan-out
    seen = set()
    for sc in c.get_unordered_scanners(3):
        for hk, sk, v in sc:
            seen.add((hk, sk))
    assert len(seen) == 30


def test_writes_replicate_through_2pc(cluster):
    """The served path is the REPLICATED path: an acked write is on every
    member, not just the primary."""
    app_id = cluster.create_table("rep", partition_count=2)
    c = cluster.client("rep")
    assert c.set(b"rk", b"rs", b"rv") == OK
    cluster.step()
    from pegasus_tpu.base.key_schema import generate_key, key_hash_parts

    pidx = key_hash_parts(b"rk", b"rs") % 2
    pc = cluster.meta.state.get_partition(app_id, pidx)
    assert len(pc.members()) == 3
    for node in pc.members():
        r = cluster.stubs[node].get_replica((app_id, pidx))
        assert r.server.on_get(generate_key(b"rk", b"rs")) == (OK, b"rv")


def test_failover_mid_workload_keeps_acked_writes(cluster):
    """Kill a primary mid-stream: every OK-acked write must remain
    readable after the guardian cures the partitions (VERDICT item 3
    done-condition; parity: kill_test data_verifier)."""
    app_id = cluster.create_table("fo", partition_count=4)
    c = cluster.client("fo")
    acked = []
    for i in range(40):
        if c.set(b"f%03d" % i, b"s", b"v%d" % i) == OK:
            acked.append(i)
    assert len(acked) == 40
    victim = cluster.meta.state.get_partition(app_id, 0).primary
    cluster.kill(victim)
    # clients keep working THROUGH the failover: retries pump sim time,
    # FD declares the node dead, guardian promotes secondaries
    for i in range(40, 60):
        if c.set(b"f%03d" % i, b"s", b"v%d" % i) == OK:
            acked.append(i)
    for i in acked:
        assert c.get(b"f%03d" % i, b"s") == (OK, b"v%d" % i), i
    # the cured configs exclude the dead node
    for pidx in range(4):
        pc = cluster.meta.state.get_partition(app_id, pidx)
        assert victim not in pc.members()
        assert pc.primary


def test_config_refresh_after_primary_move(cluster):
    """A client holding a stale config must transparently re-resolve
    (parity: partition_resolver refresh on ERR_INVALID_STATE)."""
    app_id = cluster.create_table("mv", partition_count=2)
    c = cluster.client("mv")
    assert c.set(b"a", b"b", b"c") == OK
    # force new primaries via rebalance-style config churn: kill current
    # primary of partition 0
    old = cluster.meta.state.get_partition(app_id, 0).primary
    cluster.kill(old)
    cluster.step(rounds=8)
    # stale cache in c still names `old`; ops must succeed anyway
    assert c.set(b"a2", b"b2", b"c2") == OK
    assert c.get(b"a", b"b") == (OK, b"c")


def test_read_your_writes_after_failover(cluster):
    cluster.create_table("ryw", partition_count=2, replica_count=3)
    c = cluster.client("ryw")
    for i in range(10):
        assert c.set(b"h", b"s%02d" % i, b"val%d" % i) == OK
    # kill ALL the primaries of both partitions one at a time
    killed = set()
    for pidx in range(2):
        p = cluster.meta.state.get_partition(c.app_id, pidx).primary
        if p and p not in killed:
            cluster.kill(p)
            killed.add(p)
    cluster.step(rounds=8)
    err, kvs = c.multi_get(b"h")
    assert err == OK
    assert kvs == {b"s%02d" % i: b"val%d" % i for i in range(10)}


def test_scan_multi_matches_per_partition(cluster):
    """Cross-partition batched scans (one stacked device evaluation per
    node) must return exactly what per-partition serving returns."""
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.server.types import GetScannerRequest

    cluster.create_table("sm", partition_count=8)
    c = cluster.client("sm")
    for i in range(160):
        assert c.set(b"m%04d" % i, b"s", b"v%d" % i) == OK
    # compact most partitions; leave write overlays on some
    node_servers = {}
    for name, stub in cluster.stubs.items():
        for gpid, r in stub.replicas.items():
            node_servers.setdefault(gpid[1], []).append(r.server)
    for pidx, servers in node_servers.items():
        if pidx % 2 == 0:
            for srv in servers:
                srv.engine.flush()
                srv.manual_compact()
    groups = {pidx: [GetScannerRequest(
        start_key=generate_key(b"m%04d" % (pidx * 3), b""),
        batch_size=30, validate_partition_hash=True)]
        for pidx in range(8)}
    results = c.scan_multi({p: list(r) for p, r in groups.items()})
    assert set(results) == set(range(8))
    for pidx, reqs in groups.items():
        solo = c._read("get_scanner", reqs[0], pidx)
        got = results[pidx][0]
        assert [(kv.key, kv.value) for kv in got.kvs] == \
            [(kv.key, kv.value) for kv in solo.kvs], pidx


def test_throttle_and_deny_envs_over_cluster(cluster):
    """function_test/throttle parity: per-table deny and reject-mode
    write throttling propagate through meta envs and gate the replicated
    write path."""
    cluster.create_table("th", partition_count=2)
    c = cluster.client("th")
    assert c.set(b"a", b"s", b"v") == OK
    # deny all client requests
    cluster.meta.update_app_envs(
        "th", {"replica.deny_client_request": "reject*all"})
    cluster.step()
    from pegasus_tpu.utils.errors import PegasusError, StorageStatus

    try:
        err = c.set(b"b", b"s", b"v")
        assert err == int(StorageStatus.TRY_AGAIN)
    except PegasusError:
        pass  # retries exhausted is equally a rejection
    # lift the deny; writes flow again
    cluster.meta.update_app_envs("th",
                                 {"replica.deny_client_request": ""})
    cluster.step()
    assert c.set(b"b", b"s", b"v") == OK
    # reject-mode throttling: 1 request burst then TryAgain
    cluster.meta.update_app_envs(
        "th", {"replica.write_throttling": "1*reject*0"})
    cluster.step()
    results = []
    for i in range(6):
        try:
            results.append(c.set(b"t%d" % i, b"s", b"v"))
        except PegasusError:
            results.append(-1)
    assert any(r != OK for r in results), results
