"""Meta layer tests: FD, create/drop/recall, guardian cures, learner
upgrades — a whole cluster in the deterministic simulator (the onebox
analogue of the reference's function tests)."""

import pytest

from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.meta import MetaService
from pegasus_tpu.meta.failure_detector import worker_lease_valid
from pegasus_tpu.replica.mutation import WriteOp
from pegasus_tpu.replica.replica import PartitionStatus
from pegasus_tpu.replica.stub import ReplicaStub
from pegasus_tpu.rpc.codec import OP_PUT
from pegasus_tpu.runtime import SimLoop, SimNetwork
from pegasus_tpu.utils.errors import PegasusError


class ClusterHarness:
    def __init__(self, tmp_path, n_nodes=4, seed=0):
        self.loop = SimLoop(seed=seed)
        self.net = SimNetwork(self.loop)
        clock = lambda: self.loop.now
        self.meta = MetaService("meta", str(tmp_path / "meta"), self.net,
                                clock)
        self.stubs = {}
        for i in range(n_nodes):
            name = f"node{i}"
            stub = ReplicaStub(name, str(tmp_path / name), self.net,
                               clock=lambda: 1_700_000_000 + self.loop.now)
            stub.meta_addr = "meta"
            self.stubs[name] = stub
        self.run_beacons()

    def run_beacons(self, rounds=2, interval=3.0):
        """Advance virtual time with everyone beaconing."""
        for _ in range(rounds):
            for stub in self.stubs.values():
                stub.send_beacon()
            self.loop.run_for(interval)
            self.meta.tick()
        self.loop.run_until_idle()

    def silence(self, node, rounds=5, interval=3.0):
        """Advance time with `node` NOT beaconing (crash simulation)."""
        for _ in range(rounds):
            for name, stub in self.stubs.items():
                if name != node:
                    stub.send_beacon()
            self.loop.run_for(interval)
            self.meta.tick()
        self.loop.run_until_idle()

    def primary_replica(self, app_id, pidx):
        pc = self.meta.state.get_partition(app_id, pidx)
        return self.stubs[pc.primary].get_replica((app_id, pidx))

    def write(self, app_id, pidx, hk, sk, value):
        r = self.primary_replica(app_id, pidx)
        r.client_write([WriteOp(OP_PUT, (generate_key(hk, sk), value, 0))])
        self.loop.run_until_idle()

    def read_everywhere(self, app_id, pidx, hk, sk):
        pc = self.meta.state.get_partition(app_id, pidx)
        self.primary_replica(app_id, pidx).broadcast_group_check()
        self.loop.run_until_idle()
        out = {}
        for node in pc.members():
            r = self.stubs[node].get_replica((app_id, pidx))
            out[node] = r.server.on_get(generate_key(hk, sk))
        return out

    def close(self):
        for s in self.stubs.values():
            s.close()


@pytest.fixture
def cluster(tmp_path):
    c = ClusterHarness(tmp_path)
    yield c
    c.close()


def test_fd_tracks_liveness(cluster):
    assert sorted(cluster.meta.fd.alive_workers()) == [
        "node0", "node1", "node2", "node3"]
    cluster.silence("node2")
    assert not cluster.meta.fd.is_alive("node2")
    assert cluster.meta.fd.is_alive("node0")
    # lease < grace: the worker self-fences before meta declares death
    assert not worker_lease_valid(last_ack=0.0, now=9.5)
    assert worker_lease_valid(last_ack=0.0, now=8.0)


def test_create_app_places_replicas(cluster):
    app_id = cluster.meta.create_app("temp", partition_count=4,
                                     replica_count=3)
    cluster.loop.run_until_idle()
    for pidx in range(4):
        pc = cluster.meta.state.get_partition(app_id, pidx)
        assert pc.primary and len(pc.secondaries) == 2
        prim = cluster.stubs[pc.primary].get_replica((app_id, pidx))
        assert prim.status == PartitionStatus.PRIMARY
        for s in pc.secondaries:
            assert cluster.stubs[s].get_replica(
                (app_id, pidx)).status == PartitionStatus.SECONDARY
    # duplicate name rejected
    with pytest.raises(PegasusError):
        cluster.meta.create_app("temp", 4)
    # end-to-end write through the placed group
    cluster.write(app_id, 0, b"hk", b"sk", b"v1")
    reads = cluster.read_everywhere(app_id, 0, b"hk", b"sk")
    assert all(v == (0, b"v1") for v in reads.values())


def test_primary_failover_cure(cluster):
    app_id = cluster.meta.create_app("t", partition_count=2,
                                     replica_count=3)
    cluster.loop.run_until_idle()
    cluster.write(app_id, 0, b"hk", b"sk", b"before")
    pc0 = cluster.meta.state.get_partition(app_id, 0)
    dead = pc0.primary
    cluster.net.partition(dead)
    cluster.silence(dead)
    pc1 = cluster.meta.state.get_partition(app_id, 0)
    assert pc1.primary != dead and pc1.ballot > pc0.ballot
    assert dead not in pc1.members()
    # new primary serves reads and writes
    cluster.write(app_id, 0, b"hk", b"sk2", b"after")
    reads = cluster.read_everywhere(app_id, 0, b"hk", b"sk2")
    assert all(v == (0, b"after") for v in reads.values())
    assert cluster.primary_replica(app_id, 0).server.on_get(
        generate_key(b"hk", b"sk")) == (0, b"before")


def test_guardian_restores_replication_level(cluster):
    app_id = cluster.meta.create_app("t", partition_count=1,
                                     replica_count=3)
    cluster.loop.run_until_idle()
    for i in range(5):
        cluster.write(app_id, 0, b"hk", b"s%d" % i, b"v%d" % i)
    pc = cluster.meta.state.get_partition(app_id, 0)
    dead = pc.secondaries[0]
    cluster.net.partition(dead)
    cluster.silence(dead)
    pc2 = cluster.meta.state.get_partition(app_id, 0)
    assert dead not in pc2.members()
    # guardian pass adds the spare node as learner; learn completes and
    # the partition is back at 3 replicas
    cluster.run_beacons(rounds=3)
    pc3 = cluster.meta.state.get_partition(app_id, 0)
    assert len(pc3.members()) == 3
    newcomer = [n for n in pc3.members() if n not in pc.members()][0]
    r = cluster.stubs[newcomer].get_replica((app_id, 0))
    assert r.status == PartitionStatus.SECONDARY
    cluster.primary_replica(app_id, 0).broadcast_group_check()
    cluster.loop.run_until_idle()
    assert r.server.on_get(generate_key(b"hk", b"s3")) == (0, b"v3")


def test_drop_and_recall(cluster):
    app_id = cluster.meta.create_app("t", partition_count=2,
                                     replica_count=2)
    cluster.loop.run_until_idle()
    cluster.write(app_id, 0, b"hk", b"sk", b"keepme")
    cluster.meta.drop_app("t")
    cluster.loop.run_until_idle()
    assert cluster.meta.state.find_app("t") is None
    with pytest.raises(PegasusError):
        cluster.meta.query_config("t")
    # replicas deactivated
    pc = cluster.meta.state.get_partition(app_id, 0)
    assert pc.primary == ""
    # recall resurrects with data intact
    rid = cluster.meta.recall_app("t")
    cluster.loop.run_until_idle()
    assert rid == app_id
    reads = cluster.read_everywhere(app_id, 0, b"hk", b"sk")
    assert any(v == (0, b"keepme") for v in reads.values())


def test_query_config_and_envs(cluster):
    cluster.meta.create_app("t", partition_count=4, replica_count=2,
                            envs={"default_ttl": "500"})
    cluster.loop.run_until_idle()
    app_id, pc_count, configs = cluster.meta.query_config("t")
    assert pc_count == 4 and len(configs) == 4
    assert all(c.primary for c in configs)
    # envs propagated to the hosting replicas
    pc = configs[0]
    r = cluster.stubs[pc.primary].get_replica((app_id, 0))
    assert r.server.app_envs.get("default_ttl") == "500"
    # update propagates too
    cluster.meta.update_app_envs(
        "t", {"replica.deny_client_request": "reject*write"})
    cluster.loop.run_until_idle()
    assert r.server._deny_client == "write"


def test_lease_fencing_blocks_stale_primary_reads(cluster):
    # regression: a partitioned old primary must self-fence (lease < grace)
    # instead of serving stale reads through the client path
    from pegasus_tpu.rpc.codec import OP_PUT as OPP
    app_id = cluster.meta.create_app("t", partition_count=1,
                                     replica_count=3)
    cluster.loop.run_until_idle()
    cluster.write(app_id, 0, b"hk", b"sk", b"v")
    pc = cluster.meta.state.get_partition(app_id, 0)
    old_primary = pc.primary
    replies = []
    cluster.net.register("client", lambda src, mt, p: replies.append(p))

    # healthy primary serves the read
    cluster.net.send("client", old_primary, "client_read",
                     {"gpid": (app_id, 0), "rid": 1, "op": "get",
                      "args": generate_key(b"hk", b"sk")})
    cluster.loop.run_until_idle()
    assert replies[-1]["err"] == 0 and replies[-1]["result"] == (0, b"v")

    # partition the primary; its lease lapses while meta cures
    cluster.net.partition(old_primary)
    cluster.silence(old_primary)
    cluster.net.heal(old_primary)  # network back, but lease expired
    cluster.net.send("client", old_primary, "client_read",
                     {"gpid": (app_id, 0), "rid": 2, "op": "get",
                      "args": generate_key(b"hk", b"sk")})
    cluster.loop.run_until_idle()
    assert replies[-1]["rid"] == 2 and replies[-1]["err"] != 0

    # the cured primary serves through the same path
    pc2 = cluster.meta.state.get_partition(app_id, 0)
    assert pc2.primary != old_primary
    cluster.net.send("client", pc2.primary, "client_read",
                     {"gpid": (app_id, 0), "rid": 3, "op": "get",
                      "args": generate_key(b"hk", b"sk")})
    cluster.loop.run_until_idle()
    assert replies[-1]["err"] == 0 and replies[-1]["result"] == (0, b"v")


def test_client_write_path_over_network(cluster):
    from pegasus_tpu.rpc.codec import OP_PUT as OPP
    app_id = cluster.meta.create_app("t", partition_count=1,
                                     replica_count=2)
    cluster.loop.run_until_idle()
    pc = cluster.meta.state.get_partition(app_id, 0)
    replies = []
    cluster.net.register("client", lambda src, mt, p: replies.append(p))
    cluster.net.send("client", pc.primary, "client_write", {
        "gpid": (app_id, 0), "rid": 7,
        "ops": [(OPP, (generate_key(b"hk", b"sk"), b"netv", 0))]})
    cluster.loop.run_until_idle()
    assert replies and replies[-1]["rid"] == 7 and replies[-1]["err"] == 0
    # a secondary refuses client writes
    cluster.net.send("client", pc.secondaries[0], "client_write", {
        "gpid": (app_id, 0), "rid": 8,
        "ops": [(OPP, (generate_key(b"hk", b"x"), b"y", 0))]})
    cluster.loop.run_until_idle()
    assert replies[-1]["rid"] == 8 and replies[-1]["err"] != 0


def test_stub_restart_recovers_partition_count(tmp_path):
    c = ClusterHarness(tmp_path)
    try:
        app_id = c.meta.create_app("t", partition_count=8, replica_count=2)
        c.loop.run_until_idle()
        pc = c.meta.state.get_partition(app_id, 3)
        node = pc.primary
        r = c.stubs[node].get_replica((app_id, 3))
        assert r.server.partition_count == 8
        c.stubs[node].close()
        # reboot the node: the boot scan must restore the real count
        from pegasus_tpu.replica.stub import ReplicaStub
        stub2 = ReplicaStub(node, str(tmp_path / node), c.net,
                            clock=lambda: 1_700_000_000 + c.loop.now)
        c.stubs[node] = stub2
        r2 = stub2.get_replica((app_id, 3))
        assert r2.server.partition_count == 8
        assert r2.server.validate_partition_hash
    finally:
        c.close()


def test_recall_rejected_when_name_reused(cluster):
    cluster.meta.create_app("t", partition_count=1, replica_count=2)
    cluster.loop.run_until_idle()
    cluster.meta.drop_app("t")
    cluster.meta.create_app("t", partition_count=1, replica_count=2)
    cluster.loop.run_until_idle()
    with pytest.raises(PegasusError):
        cluster.meta.recall_app("t")


def test_desired_replica_count_survives_small_cluster(tmp_path):
    # create with only 2 nodes alive; when more join, the guardian tops up
    c = ClusterHarness(tmp_path, n_nodes=2)
    try:
        app_id = c.meta.create_app("t", partition_count=1, replica_count=3)
        c.loop.run_until_idle()
        assert len(c.meta.state.get_partition(app_id, 0).members()) == 2
        assert c.meta.state.apps[app_id].max_replica_count == 3
        # a third node joins
        from pegasus_tpu.replica.stub import ReplicaStub
        s = ReplicaStub("node9", str(tmp_path / "node9"), c.net,
                        clock=lambda: 1_700_000_000 + c.loop.now)
        s.meta_addr = "meta"
        c.stubs["node9"] = s
        c.run_beacons(rounds=4)
        pc = c.meta.state.get_partition(app_id, 0)
        assert len(pc.members()) == 3 and "node9" in pc.members()
    finally:
        c.close()


def test_meta_state_persists_across_restart(tmp_path):
    c = ClusterHarness(tmp_path)
    try:
        app_id = c.meta.create_app("t", partition_count=2, replica_count=2)
        c.loop.run_until_idle()
        pc_before = c.meta.state.get_partition(app_id, 0)
        # meta restarts from its storage file
        meta2 = MetaService("meta2", str(tmp_path / "meta"), c.net,
                            lambda: c.loop.now)
        assert meta2.state.apps[app_id].app_name == "t"
        pc_after = meta2.state.get_partition(app_id, 0)
        assert pc_after.to_json() == pc_before.to_json()
    finally:
        c.close()
