"""Cold backup/restore over the block service + cross-cluster duplication."""

import os

import pytest

from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.client import PegasusClient, Table
from pegasus_tpu.replica.mutation import WriteOp
from pegasus_tpu.replica.replica import Replica, ReplicaConfig
from pegasus_tpu.rpc.codec import OP_INCR, OP_MULTI_PUT, OP_PUT, OP_REMOVE
from pegasus_tpu.runtime import SimLoop, SimNetwork
from pegasus_tpu.server.backup import (
    BackupEngine,
    BackupPolicy,
    BackupScheduler,
)
from pegasus_tpu.server.duplication import ReplicaDuplicator, TableShipper
from pegasus_tpu.server.types import IncrRequest, KeyValue, MultiPutRequest
from pegasus_tpu.storage.block_service import LocalBlockService
from pegasus_tpu.storage.engine import StorageEngine, WriteBatchItem
from pegasus_tpu.storage.wal import OP_PUT as WAL_PUT


def k(h, s):
    return generate_key(h, s)


# ---- block service ----------------------------------------------------


def test_block_service_roundtrip(tmp_path):
    bs = LocalBlockService(str(tmp_path / "bs"))
    bs.write_file("a/b/file.bin", b"hello")
    assert bs.exists("a/b/file.bin")
    assert bs.read_file("a/b/file.bin") == b"hello"
    assert bs.list_dir("a/b") == ["file.bin"]
    # md5 integrity check
    with open(bs._abs("a/b/file.bin"), "wb") as f:
        f.write(b"corrupted")
    with pytest.raises(IOError):
        bs.read_file("a/b/file.bin")
    bs.remove_path("a")
    assert not bs.exists("a/b/file.bin")


def test_block_service_rejects_escape(tmp_path):
    bs = LocalBlockService(str(tmp_path / "bs"))
    with pytest.raises(ValueError):
        bs.write_file("../outside", b"x")


# ---- backup / restore -------------------------------------------------


def test_backup_restore_roundtrip(tmp_path):
    from pegasus_tpu.base.value_schema import generate_value
    eng = StorageEngine(str(tmp_path / "src"))
    items = [WriteBatchItem(WAL_PUT, k(b"h%02d" % i, b"s"),
                            generate_value(1, b"v%d" % i, 0), 0)
             for i in range(50)]
    eng.write_batch(items, decree=1)

    bs = LocalBlockService(str(tmp_path / "bs"))
    be = BackupEngine(bs, "daily")
    decree = be.backup_partition(backup_id=100, app_id=2, pidx=0,
                                 engine=eng)
    assert decree == 1
    be.finish_backup(100, 2, "mytable", 1)
    assert be.list_backups() == [100]
    meta = be.read_backup_metadata(100)
    assert meta["app_name"] == "mytable" and meta["complete"]

    # restore into a fresh dir
    eng2 = be.restore_partition(100, 2, 0, str(tmp_path / "restored"))
    for i in range(50):
        hit = eng2.get(k(b"h%02d" % i, b"s"))
        assert hit is not None
    assert eng2.last_committed_decree == 1
    # writes continue after the restored watermark
    eng2.write_batch([WriteBatchItem(WAL_PUT, k(b"new", b"s"), b"\0\0\0\0x",
                                     0)], decree=2)
    eng.close()
    eng2.close()


def test_backup_gc(tmp_path):
    bs = LocalBlockService(str(tmp_path / "bs"))
    be = BackupEngine(bs, "daily")
    eng = StorageEngine(str(tmp_path / "src"))
    eng.write_batch([WriteBatchItem(WAL_PUT, k(b"h", b"s"), b"\0\0\0\0v", 0)],
                    decree=1)
    for backup_id in (1, 2, 3, 4):
        be.backup_partition(backup_id, 1, 0, eng)
        be.finish_backup(backup_id, 1, "t", 1)
    assert be.gc_old_backups(keep=2) == [1, 2]
    assert be.list_backups() == [3, 4]
    eng.close()


def test_backup_scheduler(tmp_path):
    ran = []
    clock_now = [1000.0]
    sched = BackupScheduler(
        backup_table=lambda policy, backup_id, app_id: ran.append(
            (policy.name, app_id)),
        clock=lambda: clock_now[0])
    sched.add_policy(BackupPolicy("daily", app_ids=[1, 2],
                                  interval_seconds=3600))
    assert len(sched.tick()) == 1           # due immediately
    assert ran == [("daily", 1), ("daily", 2)]
    assert sched.tick() == []               # not due again yet
    clock_now[0] += 3601
    assert len(sched.tick()) == 1
    with pytest.raises(ValueError):
        sched.add_policy(BackupPolicy("daily", app_ids=[1]))


# ---- duplication ------------------------------------------------------


def _make_master_replica(tmp_path, loop, net):
    # wall clock: duplication timetags must be comparable with the
    # follower's locally-written timetags
    import time
    r = Replica("m1", str(tmp_path / "m1"), net, clock=time.time)
    net.register("m1", r.on_message)
    r.assign_config(ReplicaConfig(1, "m1", []))
    return r


def test_duplication_ships_and_confirms(tmp_path):
    loop = SimLoop()
    net = SimNetwork(loop)
    master = _make_master_replica(tmp_path, loop, net)
    follower = Table(str(tmp_path / "follower"), partition_count=4)
    progress = []
    dup = ReplicaDuplicator(master, TableShipper(follower),
                            on_progress=lambda d, c: progress.append(c))
    try:
        for i in range(10):
            master.client_write([WriteOp(
                OP_PUT, (k(b"user_%d" % i, b"s"), b"v%d" % i, 0))])
        loop.run_until_idle()
        shipped = dup.sync_round()
        assert shipped == 10
        assert dup.confirmed_decree == 10
        assert progress == [10]
        fc = PegasusClient(follower)
        for i in range(10):
            assert fc.get(b"user_%d" % i, b"s") == (0, b"v%d" % i)
        # idle round ships nothing
        assert dup.sync_round() == 0
        # multi_put + remove flow through too
        master.client_write([WriteOp(OP_MULTI_PUT, MultiPutRequest(
            b"cart", [KeyValue(b"a", b"1"), KeyValue(b"b", b"2")]))])
        master.client_write([WriteOp(OP_REMOVE, (k(b"user_3", b"s"),))])
        loop.run_until_idle()
        assert dup.sync_round() == 2
        assert fc.multi_get(b"cart")[1] == {b"a": b"1", b"b": b"2"}
        assert fc.get(b"user_3", b"s")[0] == 1  # removed on follower
    finally:
        master.close()
        follower.close()


def test_duplication_timetag_conflict_resolution(tmp_path):
    loop = SimLoop()
    net = SimNetwork(loop)
    master = _make_master_replica(tmp_path, loop, net)
    follower = Table(str(tmp_path / "f"), partition_count=2)
    dup = ReplicaDuplicator(master, TableShipper(follower))
    try:
        # master writes an OLD value (its mutation timestamp is in the past
        # relative to the follower's local write)
        master.client_write([WriteOp(OP_PUT, (k(b"hk", b"s"), b"stale", 0))])
        loop.run_until_idle()
        # follower's own LOCAL write happens later -> larger timetag
        import time
        time.sleep(0.001)
        fc = PegasusClient(follower)
        fc.set(b"hk", b"s", b"local-newer")
        # the master's mutation timestamp predates the local write, so the
        # shipped update must LOSE
        dup.sync_round()
        assert fc.get(b"hk", b"s") == (0, b"local-newer")
        # but a later master write wins
        time.sleep(0.001)
        master.client_write([WriteOp(OP_PUT, (k(b"hk", b"s"), b"m2", 0))])
        loop.run_until_idle()
        dup.sync_round()
        assert fc.get(b"hk", b"s") == (0, b"m2")
    finally:
        master.close()
        follower.close()


def test_duplication_rejects_atomic_mutations(tmp_path):
    loop = SimLoop()
    net = SimNetwork(loop)
    master = _make_master_replica(tmp_path, loop, net)
    follower = Table(str(tmp_path / "f"), partition_count=2)
    dup = ReplicaDuplicator(master, TableShipper(follower))
    try:
        master.client_write([WriteOp(OP_INCR,
                                     IncrRequest(k(b"h", b"c"), 1))])
        loop.run_until_idle()
        with pytest.raises(ValueError):
            dup.sync_round()
    finally:
        master.close()
        follower.close()


def test_duplication_does_not_skip_uncommitted_frames(tmp_path):
    # regression: a sync_round that sees prepared-but-uncommitted frames
    # must not advance its offset past them
    loop = SimLoop()
    net = SimNetwork(loop)
    r = Replica("m1", str(tmp_path / "m1"), net,
                clock=__import__("time").time)
    net.register("m1", r.on_message)
    # a secondary that never acks -> prepare stays uncommitted
    r.assign_config(ReplicaConfig(1, "m1", ["ghost"]))
    follower = Table(str(tmp_path / "f"), partition_count=2)
    dup = ReplicaDuplicator(r, TableShipper(follower))
    try:
        r.client_write([WriteOp(OP_PUT, (k(b"h", b"s"), b"v", 0))])
        assert r.last_committed_decree == 0  # stuck uncommitted
        assert dup.sync_round() == 0
        # now the ghost is removed and the decree commits
        r.assign_config(ReplicaConfig(2, "m1", []))
        loop.run_until_idle()
        assert r.last_committed_decree == 1
        assert dup.sync_round() == 1  # the frame was NOT skipped
        fc = PegasusClient(follower)
        assert fc.get(b"h", b"s")[0] == 0
    finally:
        r.close()
        follower.close()


def test_log_gc_respects_duplication_progress(tmp_path):
    # regression: flushing + GC'ing the log must not delete mutations the
    # duplicator hasn't shipped yet
    loop = SimLoop()
    net = SimNetwork(loop)
    master = _make_master_replica(tmp_path, loop, net)
    follower = Table(str(tmp_path / "f"), partition_count=2)
    dup = ReplicaDuplicator(master, TableShipper(follower))
    try:
        for i in range(5):
            master.client_write([WriteOp(OP_PUT,
                                         (k(b"u%d" % i, b"s"), b"v", 0))])
        master.flush_and_gc_log()  # dup confirmed=0 -> nothing may drop
        assert dup.sync_round() == 5
        fc = PegasusClient(follower)
        assert all(fc.get(b"u%d" % i, b"s")[0] == 0 for i in range(5))
        # now everything shipped: GC may proceed
        master.flush_and_gc_log()
        assert master.log.read_range(1) == []
    finally:
        master.close()
        follower.close()


def test_restarted_primary_timestamps_stay_monotonic(tmp_path):
    loop = SimLoop()
    net = SimNetwork(loop)
    # frozen clock: without the boot floor, a restart would reuse old
    # timestamps
    frozen = [1_700_000_000.0]
    r = Replica("m1", str(tmp_path / "m1"), net, clock=lambda: frozen[0])
    net.register("m1", r.on_message)
    r.assign_config(ReplicaConfig(1, "m1", []))
    r.client_write([WriteOp(OP_PUT, (k(b"h", b"a"), b"1", 0))])
    r.client_write([WriteOp(OP_PUT, (k(b"h", b"b"), b"2", 0))])
    ts_before = r._last_timestamp_us
    r.close()
    r2 = Replica("m1", str(tmp_path / "m1"), net, clock=lambda: frozen[0])
    assert r2._last_timestamp_us >= ts_before
    r2.assign_config(ReplicaConfig(1, "m1", []))
    r2.client_write([WriteOp(OP_PUT, (k(b"h", b"c"), b"3", 0))])
    mus = r2.log.read_range(3)
    assert mus[-1].timestamp_us > ts_before
    r2.close()


def test_duplication_resumes_from_confirmed(tmp_path):
    loop = SimLoop()
    net = SimNetwork(loop)
    master = _make_master_replica(tmp_path, loop, net)
    follower = Table(str(tmp_path / "f"), partition_count=2)
    try:
        for i in range(6):
            master.client_write([WriteOp(
                OP_PUT, (k(b"u%d" % i, b"s"), b"v", 0))])
        loop.run_until_idle()
        dup = ReplicaDuplicator(master, TableShipper(follower))
        dup.sync_round()
        confirmed = dup.confirmed_decree
        # a new duplicator resuming from the synced progress re-ships
        # nothing old
        dup2 = ReplicaDuplicator(master, TableShipper(follower),
                                 confirmed_decree=confirmed)
        assert dup2.sync_round() == 0
    finally:
        master.close()
        follower.close()
