"""MaskPrefresher: background static-mask warming.

Parity intent: SURVEY §7's 'host iteration ∥ device eval' hard part —
steady-state scans must not synchronously wait on the accelerator.
Static masks (filters + partition-hash) are `now`-independent, so the
warmer's job is re-evaluating NEW blocks after a flush/compaction for
the scan flavors serving has been using; TTL expiry is applied
host-side at assembly and needs no warming at all.
"""

import pytest

from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.base.value_schema import epoch_now
from pegasus_tpu.client import PegasusClient, Table
from pegasus_tpu.server.scan_coordinator import MaskPrefresher
from pegasus_tpu.server.types import GetScannerRequest


@pytest.fixture
def table(tmp_path):
    t = Table(str(tmp_path / "t"), app_id=1, partition_count=4)
    c = PegasusClient(t)
    for i in range(200):
        ttl = 0 if i % 5 else 2  # some records expire soon
        assert c.set(b"pk%04d" % i, b"s", b"v%d" % i,
                     ttl_seconds=ttl) == 0
    t.flush_all()
    for srv in t.all_partitions():
        srv.manual_compact()
    yield t, c
    t.close()


def _scan_batch(srv, now):
    reqs = [GetScannerRequest(start_key=generate_key(b"pk", b""),
                              batch_size=50,
                              validate_partition_hash=True)]
    state = srv.plan_scan_batch(reqs, now=now)
    assert state is not None and "precomputed" not in state
    keep = srv.eval_planned_masks(state)
    return srv.finish_scan_batch(state, keep)


def test_prefresher_warms_new_blocks_after_compaction(table):
    t, _c = table
    now = epoch_now()
    # a served scan registers its flavor and caches its static masks
    for srv in t.all_partitions():
        _scan_batch(srv, now)
        # masks cached -> nothing to warm
        assert srv.hot_block_entries(0.0, 60.0) == []
    # compaction replaces the SSTs: masks are gone, flavor remains
    for srv in t.all_partitions():
        srv.manual_compact()
        assert srv.hot_block_entries(0.0, 60.0)
    pre = MaskPrefresher(t.all_partitions())
    warmed = pre.refresh_once()
    assert warmed > 0
    # new blocks' masks are in cache: planning now has NO misses
    for srv in t.all_partitions():
        reqs = [GetScannerRequest(start_key=generate_key(b"pk", b""),
                                  batch_size=50,
                                  validate_partition_hash=True)]
        state = srv.plan_scan_batch(reqs, now=now)
        assert srv.planned_misses(state) == {}
    # and a second pass has nothing left to warm
    assert pre.refresh_once() == 0


def test_prefreshed_masks_match_synchronous_eval(table):
    """The warmed mask must be BIT-IDENTICAL to what synchronous serving
    would compute — the prefresher moves when, not what."""
    t, _c = table
    now = epoch_now()
    target = now + 3  # beyond the records' 2s TTL: expiry flips results
    for srv in t.all_partitions():
        _scan_batch(srv, now)
        srv.manual_compact()
    MaskPrefresher(t.all_partitions()).refresh_once()
    for srv in t.all_partitions():
        warmed = _scan_batch(srv, target)
        with srv._mask_lock:
            srv._mask_cache.clear()  # force cold recompute
        cold = _scan_batch(srv, target)
        assert [(kv.key, kv.value) for kv in warmed[0].kvs] == \
            [(kv.key, kv.value) for kv in cold[0].kvs]


def test_ttl_expiry_needs_no_rewarm(table):
    """The static mask computed at second T serves second T+k correctly:
    expiry is host-applied, so results differ while masks are shared."""
    t, _c = table
    now = epoch_now()
    srv = t.all_partitions()[0]
    early = _scan_batch(srv, now)[0]
    state = srv.plan_scan_batch(
        [GetScannerRequest(start_key=generate_key(b"pk", b""),
                           batch_size=50, validate_partition_hash=True)],
        now=now + 10)
    assert srv.planned_misses(state) == {}  # no new device work
    late = _scan_batch(srv, now + 10)[0]
    early_keys = {kv.key for kv in early.kvs}
    late_keys = {kv.key for kv in late.kvs}
    assert late_keys < early_keys  # TTL=2 records dropped, nothing new


def test_filtered_scans_ride_the_batched_path(table):
    """A batch sharing one filter qualifies for the stacked/cached-mask
    path (filter is part of the mask key) and returns exactly what
    per-request serving returns."""
    from pegasus_tpu.ops.predicates import FT_MATCH_PREFIX

    t, _c = table
    srv = t.all_partitions()[0]
    now = epoch_now()
    reqs = [GetScannerRequest(start_key=generate_key(b"pk", b""),
                              batch_size=60,
                              hash_key_filter_type=FT_MATCH_PREFIX,
                              hash_key_filter_pattern=b"pk00",
                              validate_partition_hash=True)
            for _ in range(3)]
    state = srv.plan_scan_batch(reqs, now=now)
    assert state is not None and "precomputed" not in state
    keep = srv.eval_planned_masks(state)
    batched = srv.finish_scan_batch(state, keep)
    solo = [srv.on_get_scanner(r) for r in reqs]
    for b, s in zip(batched, solo):
        assert [(kv.key, kv.value) for kv in b.kvs] == \
            [(kv.key, kv.value) for kv in s.kvs]
        assert len(b.kvs) > 0
    # same filter again: all masks cached (no misses)
    state2 = srv.plan_scan_batch(reqs, now=now)
    assert srv.planned_misses(state2) == {}
    # a DIFFERENT filter gets its own masks (no false sharing).
    # Compressed blocks resolve first-touch masks HOST-side via the
    # encoded probe (planned_misses may come back empty with the masks
    # already cached), so assert the contract itself: pk01 is served
    # from its own mask, identically to per-request serving
    reqs2 = [GetScannerRequest(start_key=generate_key(b"pk", b""),
                               batch_size=60,
                               hash_key_filter_type=FT_MATCH_PREFIX,
                               hash_key_filter_pattern=b"pk01",
                               validate_partition_hash=True)]
    state3 = srv.plan_scan_batch(reqs2, now=now)
    keep3 = srv.eval_planned_masks(state3)
    b3 = srv.finish_scan_batch(state3, keep3)[0]
    s3 = srv.on_get_scanner(reqs2[0])
    assert [(kv.key, kv.value) for kv in b3.kvs] == \
        [(kv.key, kv.value) for kv in s3.kvs]
    assert any(mk[3][1] == b"pk01" for mk in srv._mask_cache)
    # the recurring filtered flavor is warmed on new blocks too
    srv.manual_compact()
    pre = MaskPrefresher(t.all_partitions())
    assert pre.refresh_once() > 0
    state4 = srv.plan_scan_batch(reqs, now=now)
    assert srv.planned_misses(state4) == {}


def test_filtered_batch_respects_overlay(table):
    """Overlay rows (unflushed writes) obey the batch's shared filter:
    matching rows surface, non-matching rows neither appear nor shadow."""
    from pegasus_tpu.ops.predicates import FT_MATCH_PREFIX

    from pegasus_tpu.base.key_schema import partition_index

    t, c = table
    # unflushed overlay writes on the SAME partition: one matches the
    # filter, one doesn't — the miss must exercise the exclusion branch
    target = partition_index(b"pk0001", 4)
    miss_hk = next(b"other%02d" % i for i in range(100)
                   if partition_index(b"other%02d" % i, 4) == target)
    assert c.set(b"pk0001", b"zz-new", b"overlay-hit") == 0
    assert c.set(miss_hk, b"s", b"overlay-miss") == 0
    srv = t.resolve(b"pk0001")
    req = GetScannerRequest(start_key=b"", batch_size=500,
                            hash_key_filter_type=FT_MATCH_PREFIX,
                            hash_key_filter_pattern=b"pk",
                            validate_partition_hash=True)
    state = srv.plan_scan_batch([req])
    assert state is not None and "precomputed" not in state
    keep = srv.eval_planned_masks(state)
    resp = srv.finish_scan_batch(state, keep)[0]
    keys = {kv.key for kv in resp.kvs}
    from pegasus_tpu.base.key_schema import generate_key as gk
    from pegasus_tpu.base.key_schema import restore_key
    assert gk(b"pk0001", b"zz-new") in keys
    assert all(restore_key(k)[0].startswith(b"pk") for k in keys)


def test_warm_flavors_age_out(table):
    t, _c = table
    now = epoch_now()
    srv = t.all_partitions()[0]
    _scan_batch(srv, now)
    srv.manual_compact()
    assert srv.hot_block_entries(0.0, 60.0)
    # far-future wall clock: every flavor idle past the horizon
    assert srv.hot_block_entries(1e9, 15.0) == []
    assert not srv._warm_flavors


def test_prefresher_thread_smoke(table):
    """Thread start/stop + warming through the background loop."""
    import time

    t, _c = table
    now = epoch_now()
    for srv in t.all_partitions():
        _scan_batch(srv, now)
        srv.manual_compact()
    pre = MaskPrefresher(t.all_partitions(), poll_s=0.05).start()
    try:
        deadline = time.monotonic() + 10
        while pre.refreshed == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert pre.refreshed > 0
    finally:
        pre.stop()
