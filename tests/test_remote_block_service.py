"""Remote blob-store backend (parity: block_service/hdfs/
hdfs_service.h:47 — the NETWORK backend behind block_service.h:273):
the blob daemon + RemoteBlockService client, and a full backup/restore
cycle whose root is a remote:// URL."""

import pytest

from pegasus_tpu.storage.blob_server import BlobServer
from pegasus_tpu.storage.block_service import (
    RemoteBlockService,
    block_service_for,
)
from pegasus_tpu.tools.cluster import SimCluster


@pytest.fixture
def blob(tmp_path):
    srv = BlobServer(str(tmp_path / "blobroot"))
    yield srv
    srv.close()


def test_remote_interface_roundtrip(blob, tmp_path):
    bs = block_service_for(blob.url + "/bucket1")
    assert isinstance(bs, RemoteBlockService)
    assert not bs.exists("a/b.txt")
    bs.write_file("a/b.txt", b"hello-blob")
    assert bs.exists("a/b.txt")
    assert bs.read_file("a/b.txt") == b"hello-blob"
    bs.write_file("a/c.txt", b"two")
    assert bs.list_dir("a") == ["b.txt", "c.txt"]
    # upload/download ride the same verbs
    p = tmp_path / "local.bin"
    p.write_bytes(b"\x00\x01\xffpayload")
    bs.upload(str(p), "up/l.bin")
    q = tmp_path / "out" / "l.bin"
    bs.download("up/l.bin", str(q))
    assert q.read_bytes() == b"\x00\x01\xffpayload"
    # buckets isolate
    other = block_service_for(blob.url + "/bucket2")
    assert not other.exists("a/b.txt")
    bs.remove_path("a")
    assert bs.list_dir("a") == []
    with pytest.raises(FileNotFoundError):
        bs.read_file("a/b.txt")


def test_backup_restore_over_remote_backend(blob, tmp_path):
    """The same cold-backup -> restore flow the local backend serves,
    with the policy root pointed at the network store — proving the
    abstraction the way the reference's HDFS backend does."""
    c = SimCluster(str(tmp_path / "cl"), n_nodes=3)
    try:
        c.create_table("rb", partition_count=2)
        cl = c.client("rb")
        for i in range(30):
            assert cl.set(b"k%03d" % i, b"s", b"v%d" % i) == 0
        root = blob.url + "/backups"
        c.meta.backup.add_policy("net", ["rb"], root,
                                 interval_seconds=5)
        c.step(rounds=10)
        from pegasus_tpu.server.backup import BackupEngine

        be = BackupEngine(block_service_for(root), "net")
        backups = be.list_backups()
        assert backups, "no backup landed on the remote store"
        # restore into a NEW table from the remote artifacts
        c.meta.backup.create_app_from_backup(
            "rb_restored", root, "net", backups[-1], replica_count=3)
        c.step(rounds=12)
        rc = c.client("rb_restored")
        for i in range(30):
            assert rc.get(b"k%03d" % i, b"s") == (0, b"v%d" % i)
    finally:
        c.close()
