"""Meta-orchestrated operations: backup, restore, duplication, split,
bulk load — each driven end-to-end through meta on a replicated
SimCluster, surviving failovers mid-operation (VERDICT r1 item 5).

Parity: meta_backup_service.h:360, server_state_restore.cpp,
meta_duplication_service.h, meta_split_service.h:34,
meta_bulk_load_service.h:143.
"""

import pytest

from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.utils.errors import StorageStatus

OK = int(StorageStatus.OK)


@pytest.fixture
def cluster(tmp_path):
    c = SimCluster(str(tmp_path / "cluster"), n_nodes=4)
    yield c
    c.close()


def _fill(client, n=40, prefix=b"bk"):
    for i in range(n):
        assert client.set(b"%s%03d" % (prefix, i), b"s",
                          b"v%d" % i) == OK


def test_meta_backup_completes_across_partitions(cluster, tmp_path):
    cluster.create_table("bt", partition_count=4)
    c = cluster.client("bt")
    _fill(c)
    backup_id = cluster.meta.backup.start_backup("bt",
                                                 str(tmp_path / "bucket"))
    cluster.step(rounds=2)
    st = cluster.meta.backup.backup_status(backup_id)
    assert st["complete"], st
    # metadata written and listed
    from pegasus_tpu.server.backup import BackupEngine
    from pegasus_tpu.storage.block_service import LocalBlockService

    be = BackupEngine(LocalBlockService(str(tmp_path / "bucket")),
                      "manual")
    assert backup_id in be.list_backups()


def test_meta_backup_survives_primary_failover(cluster, tmp_path):
    app_id = cluster.create_table("bt2", partition_count=4)
    c = cluster.client("bt2")
    _fill(c)
    # kill the primary of partition 0 BEFORE starting: the start pass
    # cannot reach it; meta's tick must re-drive against the cured primary
    victim = cluster.meta.state.get_partition(app_id, 0).primary
    cluster.kill(victim)
    backup_id = cluster.meta.backup.start_backup("bt2",
                                                 str(tmp_path / "b2"))
    cluster.step(rounds=8)  # FD grace + cure + retry ticks
    st = cluster.meta.backup.backup_status(backup_id)
    assert st["complete"], st


def test_restore_into_new_table(cluster, tmp_path):
    cluster.create_table("src", partition_count=4)
    c = cluster.client("src")
    _fill(c, 50)
    backup_id = cluster.meta.backup.start_backup("src",
                                                 str(tmp_path / "b3"))
    cluster.step(rounds=2)
    assert cluster.meta.backup.backup_status(backup_id)["complete"]

    cluster.meta.backup.create_app_from_backup(
        "dst", str(tmp_path / "b3"), "manual", backup_id)
    cluster.step(rounds=3)
    assert not cluster.meta.pending_restores
    c2 = cluster.client("dst")
    for i in range(50):
        assert c2.get(b"bk%03d" % i, b"s") == (OK, b"v%d" % i), i
    # the guardian re-replicates the restored table back to 3 members,
    # and the learners carry the RESTORED data
    for _ in range(10):
        cluster.step(rounds=2)
        pcs = [cluster.meta.state.get_partition(c2.app_id, p)
               for p in range(4)]
        if all(len(pc.members()) == 3 for pc in pcs):
            break
    pcs = [cluster.meta.state.get_partition(c2.app_id, p)
           for p in range(4)]
    assert all(len(pc.members()) == 3 for pc in pcs)
    from pegasus_tpu.base.key_schema import generate_key, key_hash_parts

    key = generate_key(b"bk001", b"s")
    pidx = key_hash_parts(b"bk001", b"s") % 4
    for node in pcs[pidx].members():
        r = cluster.stubs[node].get_replica((c2.app_id, pidx))
        assert r.server.on_get(key) == (OK, b"v1"), node


def test_meta_bulk_load_rolling_ingest(cluster, tmp_path):
    """Offline SSTs -> meta-driven rolling ingestion through 2PC: every
    member of every partition holds the loaded records."""
    from pegasus_tpu.server.bulk_load import SSTGenerator
    from pegasus_tpu.storage.block_service import LocalBlockService

    app_id = cluster.create_table("blt", partition_count=4)
    root = str(tmp_path / "staged")
    gen = SSTGenerator(LocalBlockService(root), "blt", partition_count=4)
    records = [(b"bl%04d" % i, b"s", b"val%d" % i, 0) for i in range(80)]
    gen.generate(records)

    cluster.meta.bulk_load.start_bulk_load("blt", root)
    for _ in range(12):
        cluster.step()
        if cluster.meta.bulk_load.bulk_load_status("blt")["complete"]:
            break
    assert cluster.meta.bulk_load.bulk_load_status("blt")["complete"]
    # group checks piggy-back the commit point so secondaries apply the
    # (single, deduplicated) ingest mutation
    cluster.step(rounds=2)
    c = cluster.client("blt")
    for i in range(80):
        assert c.get(b"bl%04d" % i, b"s") == (OK, b"val%d" % i), i
    # replicated: every member ingested at the same decree
    from pegasus_tpu.base.key_schema import generate_key, key_hash_parts

    key = generate_key(b"bl0001", b"s")
    pidx = key_hash_parts(b"bl0001", b"s") % 4
    pc = cluster.meta.state.get_partition(app_id, pidx)
    assert len(pc.members()) == 3
    for node in pc.members():
        r = cluster.stubs[node].get_replica((app_id, pidx))
        assert r.server.on_get(key) == (OK, b"val1"), node


def test_bulk_load_survives_failover_midway(cluster, tmp_path):
    from pegasus_tpu.server.bulk_load import SSTGenerator
    from pegasus_tpu.storage.block_service import LocalBlockService

    app_id = cluster.create_table("blf", partition_count=4)
    root = str(tmp_path / "staged2")
    gen = SSTGenerator(LocalBlockService(root), "blf", partition_count=4)
    gen.generate([(b"f%04d" % i, b"s", b"v%d" % i, 0) for i in range(60)])

    victim = cluster.meta.state.get_partition(app_id, 0).primary
    cluster.meta.bulk_load.start_bulk_load("blf", root)
    cluster.kill(victim)  # mid-operation crash
    for _ in range(20):
        cluster.step()
        if cluster.meta.bulk_load.bulk_load_status("blf")["complete"]:
            break
    assert cluster.meta.bulk_load.bulk_load_status("blf")["complete"]
    c = cluster.client("blf")
    for i in range(60):
        assert c.get(b"f%04d" % i, b"s") == (OK, b"v%d" % i), i


def test_meta_duplication_ships_to_follower(cluster):
    """Master table -> follower table through the wire: shipped writes ride
    the follower's own 2PC, conflicts resolve by source timetag."""
    cluster.create_table("master", partition_count=2)
    cluster.create_table("follower", partition_count=4)  # different count
    c = cluster.client("master")
    for i in range(20):
        assert c.set(b"d%03d" % i, b"s", b"v%d" % i) == OK
    dupid = cluster.meta.duplication.add_duplication(
        "master", "meta", "follower")
    for _ in range(10):
        cluster.step()
    fc = cluster.client("follower")
    for i in range(20):
        assert fc.get(b"d%03d" % i, b"s") == (OK, b"v%d" % i), i
    # progress synced to meta and persisted
    dups = cluster.meta.duplication.query_duplication("master")
    assert dups and dups[0]["dupid"] == dupid
    assert all(v > 0 for v in dups[0]["progress"].values())
    # writes made AFTER dup-add flow through too (tailing, not snapshot)
    assert c.set(b"late", b"s", b"latev") == OK
    for _ in range(6):
        cluster.step()
    assert fc.get(b"late", b"s") == (OK, b"latev")
    # multi ops and deletes ship as well
    assert c.multi_set(b"mh", {b"a": b"1", b"b": b"2"}) == OK
    assert c.delete(b"d000", b"s") == OK
    for _ in range(6):
        cluster.step()
    assert fc.multi_get(b"mh") == (OK, {b"a": b"1", b"b": b"2"})
    assert fc.get(b"d000", b"s")[0] != OK


def test_duplication_resumes_after_primary_failover(cluster):
    app_id = cluster.create_table("m2", partition_count=2)
    cluster.create_table("f2", partition_count=2)
    c = cluster.client("m2")
    for i in range(10):
        assert c.set(b"x%03d" % i, b"s", b"v%d" % i) == OK
    cluster.meta.duplication.add_duplication("m2", "meta", "f2")
    for _ in range(6):
        cluster.step()
    # kill the primary of partition 0; new primary must resume shipping
    # from the persisted confirmed decree
    victim = cluster.meta.state.get_partition(app_id, 0).primary
    cluster.kill(victim)
    for _ in range(8):
        cluster.step()
    for i in range(10, 25):
        assert c.set(b"x%03d" % i, b"s", b"v%d" % i) == OK
    for _ in range(10):
        cluster.step()
    fc = cluster.client("f2")
    for i in range(25):
        assert fc.get(b"x%03d" % i, b"s") == (OK, b"v%d" % i), i


def test_duplication_bootstrap_syncs_preexisting_data(cluster, tmp_path):
    """DS_PREPARE parity: pre-existing data reaches the follower via a
    checkpoint restore; incremental shipping resumes from the checkpoint
    decrees (no replay of already-synced mutations, no gaps)."""
    cluster.create_table("bm", partition_count=2)
    c = cluster.client("bm")
    for i in range(30):
        assert c.set(b"p%03d" % i, b"s", b"v%d" % i) == OK
    cluster.meta.duplication.add_duplication(
        "bm", "meta", "bf", bootstrap_root=str(tmp_path / "boot"))
    for _ in range(12):
        cluster.step()
    fc = cluster.client("bf")
    for i in range(30):
        assert fc.get(b"p%03d" % i, b"s") == (OK, b"v%d" % i), i
    # incremental keeps flowing after bootstrap
    assert c.set(b"after", b"s", b"av") == OK
    for _ in range(6):
        cluster.step()
    assert fc.get(b"after", b"s") == (OK, b"av")


def test_replica_protocol_split_doubles_partitions(cluster):
    """Meta-driven online split: children copy parent state + log tail,
    register, count flips, stale halves filter out — no data loss and no
    table-wide rewrite."""
    app_id = cluster.create_table("sp", partition_count=2)
    c = cluster.client("sp")
    for i in range(60):
        assert c.set(b"s%03d" % i, b"s", b"v%d" % i) == OK
    assert cluster.meta.split.start_partition_split("sp") == 4
    for _ in range(12):
        cluster.step()
        if not cluster.meta.split.split_status("sp")["splitting"]:
            break
    assert not cluster.meta.split.split_status("sp")["splitting"]
    assert cluster.meta.state.apps[app_id].partition_count == 4
    # every record readable through the NEW routing
    c.refresh_config()
    assert c.partition_count == 4
    for i in range(60):
        assert c.get(b"s%03d" % i, b"s") == (OK, b"v%d" % i), i
    # scans see exactly the records (stale halves masked)
    seen = set()
    for sc in c.get_unordered_scanners(4):
        for hk, sk, v in sc:
            seen.add(hk)
    assert len(seen) == 60
    # new writes land on children when routed there
    for i in range(60, 80):
        assert c.set(b"s%03d" % i, b"s", b"v%d" % i) == OK
    for i in range(60, 80):
        assert c.get(b"s%03d" % i, b"s") == (OK, b"v%d" % i)


def test_split_under_concurrent_writes_no_loss(cluster):
    """Writes racing the split either land pre-checkpoint (copied), get
    fenced+retried (ERR_SPLITTING -> client retry), or land post-flip
    (new routing) — every ack survives."""
    cluster.create_table("spw", partition_count=2)
    c = cluster.client("spw")
    acked = []
    for i in range(20):
        if c.set(b"w%03d" % i, b"s", b"v%d" % i) == OK:
            acked.append(i)
    cluster.meta.split.start_partition_split("spw")
    # interleave writes with split progress
    for i in range(20, 50):
        if c.set(b"w%03d" % i, b"s", b"v%d" % i) == OK:
            acked.append(i)
        cluster.step()
    for _ in range(10):
        cluster.step()
        if not cluster.meta.split.split_status("spw")["splitting"]:
            break
    assert not cluster.meta.split.split_status("spw")["splitting"]
    assert len(acked) == 50
    for i in acked:
        assert c.get(b"w%03d" % i, b"s") == (OK, b"v%d" % i), i


def test_split_survives_parent_primary_failover(cluster):
    app_id = cluster.create_table("spf", partition_count=2)
    c = cluster.client("spf")
    for i in range(30):
        assert c.set(b"f%03d" % i, b"s", b"v%d" % i) == OK
    victim = cluster.meta.state.get_partition(app_id, 0).primary
    cluster.meta.split.start_partition_split("spf")
    cluster.kill(victim)  # mid-split crash of a parent primary
    for _ in range(25):
        cluster.step()
        if not cluster.meta.split.split_status("spf")["splitting"]:
            break
    assert not cluster.meta.split.split_status("spf")["splitting"]
    for i in range(30):
        assert c.get(b"f%03d" % i, b"s") == (OK, b"v%d" % i), i


def test_split_fence_survives_parent_failover_after_registration(cluster):
    """A parent primary failing over AFTER its child registered must leave
    the NEW primary write-fenced until the flip — otherwise writes acked
    in that window that hash to the child half vanish at the flip."""
    app_id = cluster.create_table("spz", partition_count=2)
    c = cluster.client("spz")
    for i in range(20):
        assert c.set(b"z%03d" % i, b"s", b"v%d" % i) == OK
    cluster.meta.split.start_partition_split("spz")
    # drive until at least one child registers but the split is unfinished
    for _ in range(20):
        cluster.step()
        st = cluster.meta.split.split_status("spz")
        if not st.get("splitting"):
            break
        if st["registered"]:
            break
    st = cluster.meta.split.split_status("spz")
    if st.get("splitting") and st["registered"]:
        child_pidx = st["registered"][0]
        parent_pidx = child_pidx - 2
        old_primary = cluster.meta.state.get_partition(
            app_id, parent_pidx).primary
        cluster.kill(old_primary)
        cluster.step(rounds=8)  # cure + fence re-proposal
        new_primary = cluster.meta.state.get_partition(
            app_id, parent_pidx).primary
        if new_primary:
            r = cluster.stubs[new_primary].get_replica(
                (app_id, parent_pidx))
            assert getattr(r, "splitting", False), (
                "new primary of a registered parent must be fenced")
    # drive to completion; every acked write must survive
    for _ in range(30):
        cluster.step()
        if not cluster.meta.split.split_status("spz")["splitting"]:
            break
    assert not cluster.meta.split.split_status("spz")["splitting"]
    for i in range(20):
        assert c.get(b"z%03d" % i, b"s") == (OK, b"v%d" % i), i


def test_duplicated_atomic_ops_ship_idempotently(cluster):
    """Idempotent-writer parity: on a duplicated table, incr/cas log as
    the concrete puts they resolve to, so the follower converges without
    re-executing the atomic op."""
    cluster.create_table("am", partition_count=2)
    cluster.create_table("af", partition_count=2)
    c = cluster.client("am")
    cluster.meta.duplication.add_duplication("am", "meta", "af")
    cluster.step(rounds=3)
    r = c.incr(b"cnt", b"x", 5)
    assert r.error == OK and r.new_value == 5
    r = c.incr(b"cnt", b"x", 37)
    assert r.error == OK and r.new_value == 42
    from pegasus_tpu.server.types import CasCheckType

    resp = c.check_and_set(b"cas", b"k", int(CasCheckType.CT_VALUE_NOT_EXIST),
                           b"", b"k", b"first")
    assert resp.error == OK
    # a FAILED check resolves to no writes and must not disturb anything
    resp = c.check_and_set(b"cas", b"k", int(CasCheckType.CT_VALUE_NOT_EXIST),
                           b"", b"k", b"second")
    assert resp.error != OK
    for _ in range(8):
        cluster.step()
    fc = cluster.client("af")
    assert fc.get(b"cnt", b"x") == (OK, b"42")
    assert fc.get(b"cas", b"k") == (OK, b"first")
    # and the master itself reads its own atomic results
    assert c.get(b"cnt", b"x") == (OK, b"42")


def test_recover_rebuilds_lost_apps_and_freezed_blocks_gc(cluster):
    """Parity: shell `recover` from replica list (commands.h:209) +
    meta_function_level gating. A meta that lost its state must not GC
    the orphan replicas while freezed, and `recover` readopts them from
    the nodes' config-sync reports."""
    cluster.create_table("rt", partition_count=2, replica_count=2)
    c = cluster.client("rt")
    _fill(c, prefix=b"rc")
    meta = cluster.meta
    app = meta.state.find_app("rt")
    app_id = app.app_id

    # simulate total meta-state loss for this app
    meta.set_meta_level("freezed")
    del meta.state.apps[app_id]
    meta.state.configs.pop(app_id, None)

    # nodes report their stored replicas; freezed meta must NOT list
    # them for garbage collection
    def hosted():
        return sum(1 for stub in cluster.stubs.values()
                   for gpid in stub.replicas if gpid[0] == app_id)

    before = hosted()
    assert before > 0
    for stub in cluster.stubs.values():
        stub.config_sync()
    cluster.loop.run_until_idle()
    assert hosted() == before, \
        "freezed meta must not GC unknown replicas"

    res = meta.recover_from_reports()
    assert [r["app_id"] for r in res["created"]] == [app_id]
    meta.rename_app(f"recovered_{app_id}", "rt")
    meta.set_meta_level("steady")
    cluster.step(rounds=2)

    c2 = cluster.client("rt", name="post-recover")
    c2.refresh_config()
    err, v = c2.get(b"rc001", b"s")
    assert err == OK and v == b"v1"

    # steady meta DOES gc replicas of apps that are truly gone
    assert meta.function_level == "steady"


def test_dups_lists_cluster_wide(cluster, tmp_path):
    cluster.create_table("d1", partition_count=2)
    cluster.create_table("d2", partition_count=2)
    meta = cluster.meta
    id1 = meta.duplication.add_duplication("d1", "meta-x", "f1")
    id2 = meta.duplication.add_duplication("d2", "meta-x", "f2")
    rows = meta.duplication.list_all()
    assert {r["dupid"] for r in rows} == {id1, id2}
    assert {r["follower_app"] for r in rows} == {"f1", "f2"}
