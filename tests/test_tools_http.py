"""Onebox + shell CLI + HTTP endpoints."""

import json
import urllib.request

import pytest

from pegasus_tpu.http import MetricsHttpServer
from pegasus_tpu.tools.onebox import Onebox
from pegasus_tpu.tools.shell import main as shell_main


def run_shell(capsys, *argv):
    code = shell_main(list(argv))
    return code, capsys.readouterr().out


def test_onebox_lifecycle(tmp_path):
    box = Onebox(str(tmp_path / "box"))
    box.create_table("t1", partition_count=4)
    with pytest.raises(ValueError):
        box.create_table("t1")
    c = box.client("t1")
    c.set(b"h", b"s", b"v")
    box.close()
    # reopen from catalog
    box2 = Onebox(str(tmp_path / "box"))
    assert [t["name"] for t in box2.list_tables()] == ["t1"]
    assert box2.client("t1").get(b"h", b"s") == (0, b"v")
    box2.drop_table("t1")
    assert box2.list_tables() == []
    box2.close()


def test_shell_data_flow(tmp_path, capsys):
    root = str(tmp_path / "box")
    assert run_shell(capsys, "--root", root, "create_app", "demo",
                     "-p", "4")[0] == 0
    code, out = run_shell(capsys, "--root", root, "ls")
    assert "demo" in out and "partitions=4" in out
    assert run_shell(capsys, "--root", root, "set", "demo", "hk", "sk",
                     "hello")[0] == 0
    code, out = run_shell(capsys, "--root", root, "get", "demo", "hk", "sk")
    assert code == 0 and out.strip() == "hello"
    code, out = run_shell(capsys, "--root", root, "incr", "demo", "hk",
                          "cnt", "5")
    assert out.strip() == "5"
    run_shell(capsys, "--root", root, "multi_set", "demo", "cart",
              "a=1", "b=2")
    code, out = run_shell(capsys, "--root", root, "multi_get", "demo",
                          "cart")
    assert "a : 1" in out and "2 record(s)" in out
    code, out = run_shell(capsys, "--root", root, "count", "demo", "cart")
    assert out.strip() == "2"
    code, out = run_shell(capsys, "--root", root, "scan", "demo",
                          "--hash_prefix", "hk")
    assert "hk : sk => hello" in out
    # del + not-found exit code
    run_shell(capsys, "--root", root, "del", "demo", "hk", "sk")
    code, out = run_shell(capsys, "--root", root, "get", "demo", "hk", "sk")
    assert code == 1 and "not found" in out


def test_shell_admin_flow(tmp_path, capsys):
    root = str(tmp_path / "box")
    run_shell(capsys, "--root", root, "create_app", "t", "-p", "2")
    run_shell(capsys, "--root", root, "set", "t", "logs_1", "s", "v")
    run_shell(capsys, "--root", root, "set", "t", "keep_1", "s", "v")
    code, _ = run_shell(
        capsys, "--root", root, "set_app_envs", "t",
        'user_specified_compaction=[{"op": "delete_key", "rules": '
        '[{"type": "hashkey_pattern", "match": "prefix", '
        '"pattern": "logs_"}]}]')
    assert code == 0
    code, out = run_shell(capsys, "--root", root, "get_app_envs", "t")
    assert "user_specified_compaction" in out
    run_shell(capsys, "--root", root, "manual_compact", "t")
    code, out = run_shell(capsys, "--root", root, "count", "t", "logs_1")
    assert out.strip() == "0"
    code, out = run_shell(capsys, "--root", root, "count", "t", "keep_1")
    assert out.strip() == "1"


def test_shell_backup_restore(tmp_path, capsys):
    root = str(tmp_path / "box")
    bucket = str(tmp_path / "bucket")
    run_shell(capsys, "--root", root, "create_app", "t", "-p", "2")
    run_shell(capsys, "--root", root, "set", "t", "h", "s", "precious")
    code, out = run_shell(capsys, "--root", root, "backup", "t",
                          "--bucket", bucket, "--backup_id", "42")
    assert code == 0 and "backup 42" in out
    code, out = run_shell(capsys, "--root", root, "restore", "t",
                          "--bucket", bucket, "--backup_id", "42")
    assert code == 0
    code, out = run_shell(capsys, "--root", root, "get", "t_restored",
                          "h", "s")
    assert out.strip() == "precious"


def test_http_endpoints(tmp_path):
    from pegasus_tpu.utils.metrics import METRICS
    METRICS.entity("server", "http-test").counter("probe").increment(3)
    srv = MetricsHttpServer().start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        version = json.load(urllib.request.urlopen(f"{base}/version"))
        assert version["framework"] == "pegasus_tpu"
        config = json.load(urllib.request.urlopen(f"{base}/config"))
        assert "pegasus.server" in config
        metrics = json.load(urllib.request.urlopen(
            f"{base}/metrics?entity_type=server"))
        ours = [e for e in metrics if e["id"] == "http-test"]
        assert ours and ours[0]["metrics"]["probe"]["value"] == 3
        # unknown path -> 404
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f"{base}/nope")
        assert err.value.code == 404
    finally:
        srv.stop()


def test_pprof_endpoints():
    import threading
    import time

    srv = MetricsHttpServer().start()
    try:
        # a busy worker thread gives the profiler something to sample
        stop = threading.Event()

        def spin():
            while not stop.is_set():
                sum(i * i for i in range(1000))

        t = threading.Thread(target=spin, daemon=True)
        t.start()
        base = f"http://127.0.0.1:{srv.port}"
        heap = json.loads(urllib.request.urlopen(
            base + "/pprof/heap", timeout=10).read())
        assert heap["max_rss_kb"] > 0 and "tracing" in heap
        prof = json.loads(urllib.request.urlopen(
            base + "/pprof/profile?seconds=0.4", timeout=15).read())
        assert prof["samples"] > 0
        assert any("spin" in s["stack"] for s in prof["stacks"])
    finally:
        stop.set()
        srv.stop()
