"""Elasticity closed loop: detect→decide→act under chaos.

The PR 6 surface: per-partition load signals (capacity units + hotkey
results) flow node→meta on the config-sync report channel, the meta
elasticity controller decides split-vs-rebalance with guards (pressure
backoff, split/balancer mutual exclusion, health checks), the split
path survives mid-flight chaos (parent primary kill, quarantine), and
the batched client paths retry exactly the misrouted subset of a flush
that spans the count flip.
"""

import random

import pytest

import pegasus_tpu.meta.elasticity  # noqa: F401 - registers the flags

from pegasus_tpu.base.key_schema import generate_key, key_hash_parts
from pegasus_tpu.rpc.codec import OP_PUT
from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.tools.kill_test import DataVerifier
from pegasus_tpu.utils.errors import ErrorCode, PegasusError, StorageStatus
from pegasus_tpu.utils.flags import FLAGS

OK = int(StorageStatus.OK)


@pytest.fixture
def fast_flags():
    """Aggressive controller thresholds so sim twins converge in a few
    beacon rounds."""
    saved = [(s, n, FLAGS.get(s, n)) for s, n in (
        ("pegasus.meta", "elasticity_act_interval_s"),
        ("pegasus.meta", "elasticity_split_cu_rate"),
        ("pegasus.meta", "elasticity_detect_grace_s"))]
    FLAGS.set("pegasus.meta", "elasticity_act_interval_s", 1.0)
    FLAGS.set("pegasus.meta", "elasticity_split_cu_rate", 3.0)
    FLAGS.set("pegasus.meta", "elasticity_detect_grace_s", 4.0)
    yield
    for s, n, v in saved:
        FLAGS.set(s, n, v)


# ---- the tier-1 closed-loop twin (<5s): detect an overloaded table
# from real config-sync signals, split it online under live writes ----


def test_closed_loop_detects_and_splits_oversized_table(tmp_path,
                                                        fast_flags):
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=17)
    try:
        app_id = cluster.create_table("elastic", partition_count=2)
        cluster.meta.set_meta_level("lively")
        c = cluster.client("elastic")
        acked = {}
        split_seen = False
        for round_ in range(24):
            for i in range(30):
                hk = b"u%04d" % (round_ * 30 + i)
                if c.set(hk, b"s", b"v%d" % i) == OK:
                    acked[hk] = b"v%d" % i
            cluster.step()
            if cluster.meta.state.apps[app_id].partition_count == 4:
                split_seen = True
                break
        assert split_seen, "controller never split the overloaded table"
        # freeze further elasticity actions; drive to completion + settle
        cluster.meta.set_meta_level("steady")
        for _ in range(6):
            cluster.step()
        st = cluster.meta.split.split_status("elastic")
        assert not st["splitting"]
        ctl = cluster.meta.elasticity
        assert ctl.last_action and ctl.last_action["kind"] == "split"
        # the invariant: every acked write byte-identical via new routing
        c.refresh_config()
        assert c.partition_count == 4
        for hk, want in acked.items():
            assert c.get(hk, b"s") == (OK, want), hk
    finally:
        cluster.close()


def test_closed_loop_split_survives_primary_kill_and_chaos(tmp_path,
                                                           fast_flags):
    """The acceptance scenario: live writes, controller splits, a parent
    primary is killed MID-SPLIT (register channel cut first so the
    session is provably in flight), and the DataVerifier invariant
    holds end-to-end — zero acked-write loss, byte-identical reads."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=4, seed=23)
    try:
        app_id = cluster.create_table("fire", partition_count=2)
        c = cluster.client("fire")
        c.op_timeout_ms = 600_000
        verifier = DataVerifier(c, random.Random(23))
        for _ in range(12):
            verifier.step()
        # cut the parent primary's meta uplink: its split session will
        # wedge at the register phase — a provable mid-split window
        victim = cluster.primaries(app_id)[0]
        cluster.net.set_drop(1.0, src=victim, dst="meta")
        assert cluster.meta.split.start_partition_split("fire") == 4
        cluster.step()
        assert (app_id, 0) in cluster.stubs[victim]._split_sessions
        # kill -9 the parent primary mid-split
        cluster.kill(victim)
        for _ in range(8):
            verifier.step()
        # FD grace + cure + meta re-drives the split at the new primary
        for _ in range(30):
            cluster.step()
            if not cluster.meta.split.split_status("fire")["splitting"]:
                break
        assert not cluster.meta.split.split_status("fire")["splitting"]
        assert cluster.meta.state.apps[app_id].partition_count == 4
        for _ in range(6):
            verifier.step()
        cluster.step(rounds=2)
        assert verifier.violations == [], verifier.violations
        assert verifier.write_ok > 15
        # zero acked-write loss, byte-identical from the children
        for hk, want in verifier.acked.items():
            assert c.get(hk, b"s") == (OK, want), hk
    finally:
        cluster.close()


# ---- decide paths: dominant hotkey → move, pressure → backoff -------


def _feed(cluster, samples, at, pressure=None):
    """Push synthetic load reports into the controller as if config_sync
    delivered them: samples = {gpid: (node, cu_total, hot_key)}."""
    by_node = {}
    for gpid, (node, cu, hot) in samples.items():
        by_node.setdefault(node, []).append({
            "gpid": gpid,
            "load": {"read_cu": cu, "write_cu": 0, "hot_key": hot,
                     "at": at}})
    for node, stored in by_node.items():
        payload = {"stored": stored}
        if pressure is not None:
            payload["pressure"] = pressure.get(node, {})
        cluster.meta.elasticity.on_report(node, payload)


def test_dominant_hotkey_moves_primary_instead_of_splitting(tmp_path,
                                                            fast_flags):
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=4, seed=3)
    try:
        app_id = cluster.create_table("whale", partition_count=16,
                                      replica_count=3)
        cluster.loop.run_until_idle()
        meta = cluster.meta
        ctl = meta.elasticity
        hot_pidx = 5
        hot_pc = meta.state.get_partition(app_id, hot_pidx)

        def samples(scale):
            # the hot node carries CO-LOCATED load beyond the whale
            # partition, so moving the whale to an idle secondary is a
            # real win (the ping-pong guard refuses pointless moves)
            out = {}
            for p in range(16):
                pc = meta.state.get_partition(app_id, p)
                if p == hot_pidx:
                    cu = 10_000 * scale
                elif pc.primary == hot_pc.primary:
                    cu = 2_000 * scale
                else:
                    cu = 10 * scale
                hot = b"whale" if p == hot_pidx else None
                out[(app_id, p)] = (pc.primary, cu, hot)
            return out

        _feed(cluster, samples(1), at=0.0)
        ctl.tick()  # first sample: no rates yet, no action
        assert ctl.last_action is None
        _feed(cluster, samples(2), at=10.0)
        ctl.tick()
        assert ctl.last_action and ctl.last_action["kind"] == "move", \
            ctl.last_action
        assert ctl.last_action["gpid"] == (app_id, hot_pidx)
        # zero-copy move: leadership went to the coolest alive secondary
        new_pc = meta.state.get_partition(app_id, hot_pidx)
        assert new_pc.primary != hot_pc.primary
        assert new_pc.primary in hot_pc.secondaries
        assert new_pc.ballot == hot_pc.ballot + 1
        # and no split was started for a single-key hotspot
        assert app_id not in meta.split._splits
        # the consumed verdict re-arms detection: a stale FINISHED
        # result must not pin this partition to "move" forever
        assert (app_id, hot_pidx) in ctl._detect_started
    finally:
        cluster.close()


def test_move_refused_when_it_would_only_ping_pong(tmp_path,
                                                   fast_flags):
    """A whale partition dominating an otherwise idle node gains
    nothing from a primary move (the whale saturates whichever node
    hosts it) — the controller must refuse instead of oscillating
    leadership every act interval."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=4, seed=4)
    try:
        app_id = cluster.create_table("pong", partition_count=16,
                                      replica_count=3)
        cluster.loop.run_until_idle()
        meta = cluster.meta
        ctl = meta.elasticity
        hot_pidx = 5
        hot_pc = meta.state.get_partition(app_id, hot_pidx)

        def samples(scale):
            return {(app_id, p): (
                meta.state.get_partition(app_id, p).primary,
                (10_000 if p == hot_pidx else 10) * scale,
                b"whale" if p == hot_pidx else None)
                for p in range(16)}

        _feed(cluster, samples(1), at=0.0)
        ctl.tick()
        _feed(cluster, samples(2), at=10.0)
        ctl.tick()
        assert ctl.last_action and ctl.last_action["kind"] == "move"
        assert ctl.last_action["moved_to"] is None  # refused: no win
        assert meta.state.get_partition(app_id, hot_pidx).primary \
            == hot_pc.primary
    finally:
        cluster.close()


def test_cooled_partition_clears_detection_window(tmp_path, fast_flags):
    """A detection window belongs to one flag episode: if the partition
    cools before the grace elapses, a re-flag much later must run a
    FRESH detection instead of instantly concluding diffuse heat from
    the stale stamp (and splitting unprovoked)."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=19)
    try:
        app_id = cluster.create_table("cool", partition_count=16,
                                      replica_count=2)
        cluster.loop.run_until_idle()
        meta = cluster.meta
        ctl = meta.elasticity
        hot_pidx = 3

        def samples(scale, hot_cu):
            return {(app_id, p): (
                meta.state.get_partition(app_id, p).primary,
                (hot_cu if p == hot_pidx else 5) * scale, None)
                for p in range(16)}

        _feed(cluster, samples(1, 9_000), at=0.0)
        ctl.tick()
        _feed(cluster, samples(2, 9_000), at=5.0)
        ctl.tick()
        assert (app_id, hot_pidx) in ctl._detect_started
        # the heat subsides before the grace window elapses
        _feed(cluster, samples(3, 6), at=10.0)
        ctl.tick()
        assert (app_id, hot_pidx) not in ctl._detect_started
        # much later the partition re-heats: detection restarts — no
        # instant split from the stale episode's stamp
        cluster.loop.run_for(100.0)
        _feed(cluster, samples(40, 9_000), at=110.0)
        ctl.tick()
        cluster.loop.run_for(2.0)
        _feed(cluster, samples(80, 9_000), at=115.0)
        ctl.tick()
        assert app_id not in meta.split._splits
        assert (app_id, hot_pidx) in ctl._detect_started
    finally:
        cluster.close()


def test_diffuse_hotspot_starts_detection_then_splits(tmp_path,
                                                      fast_flags):
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=7)
    try:
        app_id = cluster.create_table("diffuse", partition_count=16,
                                      replica_count=2)
        cluster.loop.run_until_idle()
        meta = cluster.meta
        ctl = meta.elasticity
        hot_pidx = 11
        primary = meta.state.get_partition(app_id, hot_pidx).primary

        def samples(scale):
            return {(app_id, p): (
                meta.state.get_partition(app_id, p).primary,
                (9_000 if p == hot_pidx else 5) * scale, None)
                for p in range(16)}

        _feed(cluster, samples(1), at=0.0)
        ctl.tick()
        _feed(cluster, samples(2), at=5.0)
        ctl.tick()  # hot but no dominant key: detection commanded
        assert (app_id, hot_pidx) in ctl._detect_started
        cluster.loop.run_until_idle()  # deliver detect_hotkey
        stub = cluster.stubs[primary]
        hc = stub.replicas[(app_id, hot_pidx)].server.hotkey_collectors
        assert hc["read"].state.value == "coarse"  # detection running
        # detection window passes with NO dominant key -> diffuse -> split
        cluster.loop.run_for(10.0)  # past detect_grace_s
        _feed(cluster, samples(3), at=15.0)
        ctl.tick()
        assert ctl.last_action and ctl.last_action["kind"] == "split", \
            ctl.last_action
        assert app_id in meta.split._splits
    finally:
        cluster.close()


def test_foreground_pressure_backs_off_actions(tmp_path, fast_flags):
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=9)
    try:
        app_id = cluster.create_table("busy", partition_count=2)
        cluster.loop.run_until_idle()
        meta = cluster.meta
        ctl = meta.elasticity
        before = ctl._backoff_count.value()

        def samples(scale):
            return {(app_id, p): (
                meta.state.get_partition(app_id, p).primary,
                50_000 * scale, None) for p in range(2)}

        # oversized on rate alone — but the shed/deadline counters grew,
        # so the controller must defer instead of splitting
        _feed(cluster, samples(1), at=0.0)
        ctl.tick()
        _feed(cluster, samples(2), at=5.0,
              pressure={n: {"read_shed": 10, "deadline_expired": 3}
                        for n in cluster.stubs})
        ctl.tick()
        assert app_id not in meta.split._splits
        assert ctl._backoff > 1
        assert ctl._backoff_count.value() == before + 1
        # pressure stable (no growth) long enough: the deferred split
        # eventually runs once the backoff window expires
        for i in range(3, 40):
            _feed(cluster, samples(i), at=5.0 * i,
                  pressure={n: {"read_shed": 10, "deadline_expired": 3}
                            for n in cluster.stubs})
            cluster.loop.run_for(60.0)
            ctl.tick()
            if app_id in meta.split._splits:
                break
        assert app_id in meta.split._splits
    finally:
        cluster.close()


def test_detection_requires_evidence_before_diffuse_split(tmp_path,
                                                          fast_flags):
    """Grace expiry alone must not conclude diffuse heat: when the
    primary's report shows the collectors never sampled (the one-shot
    detect command was lost, or the primary died and its successor
    reports fresh stopped collectors), the controller restarts the
    window instead of splitting on zero evidence."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=29)
    try:
        app_id = cluster.create_table("ev", partition_count=16,
                                      replica_count=2)
        cluster.loop.run_until_idle()
        meta = cluster.meta
        ctl = meta.elasticity
        hot_pidx = 7

        def feed(scale, at, hot_state):
            for p in range(16):
                pc = meta.state.get_partition(app_id, p)
                cu = (9_000 if p == hot_pidx else 5) * scale
                ctl.on_report(pc.primary, {"stored": [{
                    "gpid": (app_id, p),
                    "load": {"read_cu": cu, "write_cu": 0,
                             "hot_key": None, "hot_state": hot_state,
                             "at": at}}]})

        stopped = {"read": "stopped", "write": "stopped"}
        feed(1, 0.0, stopped)
        ctl.tick()
        feed(2, 5.0, stopped)
        ctl.tick()
        assert (app_id, hot_pidx) in ctl._detect_started
        first_window = ctl._detect_started[(app_id, hot_pidx)]
        # grace passes, but the report says no collector ever sampled:
        # the window restarts — no split on zero evidence
        cluster.loop.run_for(10.0)
        feed(3, 15.0, stopped)
        ctl.tick()
        assert app_id not in meta.split._splits
        assert ctl._detect_started[(app_id, hot_pidx)] > first_window
        # once the report proves a detector ran the window with no
        # dominant key, diffuse heat is a sound conclusion
        cluster.loop.run_for(10.0)
        feed(4, 25.0, {"read": "coarse", "write": "coarse"})
        ctl.tick()
        assert app_id in meta.split._splits
    finally:
        cluster.close()


def test_rate_rebases_when_leadership_moves(tmp_path):
    """A failover hands the partition to a node whose cumulative CU
    counter is unrelated to the old primary's — diffing across the
    handoff would clamp a real rate to zero or, on the way back,
    manufacture an enormous phantom rate that could split a near-idle
    table."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=2, seed=31)
    try:
        app_id = cluster.create_table("rb", partition_count=2)
        cluster.loop.run_until_idle()
        ctl = cluster.meta.elasticity
        gpid = (app_id, 0)

        def feed(node, cu, at):
            ctl.on_report(node, {"stored": [{
                "gpid": gpid,
                "load": {"read_cu": cu, "write_cu": 0, "hot_key": None,
                         "at": at}}]})

        feed("node0", 1_000_000, 0.0)
        ctl.tick()
        feed("node0", 1_000_050, 5.0)
        ctl.tick()
        assert ctl.rates[gpid] == pytest.approx(10.0)
        # failover: node1's counter starts near zero — re-base, the
        # smoothed rate survives untouched
        feed("node1", 10, 10.0)
        ctl.tick()
        assert ctl.rates[gpid] == pytest.approx(10.0)
        feed("node1", 20, 15.0)
        ctl.tick()
        assert ctl.rates[gpid] == pytest.approx(6.0)  # 0.5*10 + 0.5*2
        # leadership returns to node0: again a re-base, not a
        # (1_000_100 - 20)/dt phantom spike
        feed("node0", 1_000_100, 20.0)
        ctl.tick()
        assert ctl.rates[gpid] == pytest.approx(6.0)
    finally:
        cluster.close()


def test_signals_for_dead_gpids_are_pruned(tmp_path):
    """Rates for gpids that no longer exist (dropped table, admin
    split flip) must not haunt node_load() forever."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=2, seed=37)
    try:
        app_id = cluster.create_table("pr", partition_count=2)
        cluster.loop.run_until_idle()
        ctl = cluster.meta.elasticity
        for at, cu in ((0.0, 1_000), (5.0, 2_000)):
            ctl.on_report("node0", {"stored": [
                {"gpid": (999, 0),
                 "load": {"read_cu": cu, "write_cu": 0,
                          "hot_key": None, "at": at}},
                {"gpid": (app_id, 0),
                 "load": {"read_cu": cu, "write_cu": 0,
                          "hot_key": None, "at": at}}]})
            ctl.tick()
        assert (app_id, 0) in ctl.rates
        assert (999, 0) not in ctl.rates
        assert (999, 0) not in ctl._reports
    finally:
        cluster.close()


def test_refused_app_does_not_starve_other_apps(tmp_path, fast_flags):
    """A split refusal is not an action: the tick must keep scanning so
    one perpetually-guarded app cannot starve every other app's
    elasticity forever."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=41)
    try:
        a_id = cluster.create_table("starver", partition_count=2)
        b_id = cluster.create_table("starved", partition_count=2)
        cluster.loop.run_until_idle()
        meta = cluster.meta
        ctl = meta.elasticity
        # app A: oversized but permanently refused (pending balancer
        # copy-secondary move holds the split guard)
        meta._pending_moves[(a_id, 0)] = ("node2", "node0")
        meta._pending_learns[(a_id, 0)] = ("node2", 0.0)

        def feed(scale, at):
            stored = []
            for app in (a_id, b_id):
                for p in range(2):
                    pc = meta.state.get_partition(app, p)
                    stored.append({
                        "gpid": (app, p),
                        "load": {"read_cu": 50_000 * scale,
                                 "write_cu": 0, "hot_key": None,
                                 "at": at}})
                    ctl.on_report(pc.primary, {"stored": stored})

        feed(1, 0.0)
        ctl.tick()
        feed(2, 5.0)
        ctl.tick()
        # A (first in list order) was refused; B still got its split
        assert a_id not in meta.split._splits
        assert b_id in meta.split._splits
        assert ctl.last_action["app"] == "starved"
    finally:
        cluster.close()


# ---- guards: split/balancer mutual exclusion + health ----------------


def test_rebalance_skips_apps_with_inflight_split(tmp_path):
    from pegasus_tpu.meta.server_state import PartitionConfig

    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=1)
    try:
        app_id = cluster.create_table("sk", partition_count=6)
        cluster.loop.run_until_idle()
        meta = cluster.meta
        # force every primary onto node0 so a rebalance WOULD propose
        for pidx in range(6):
            pc = meta.state.get_partition(app_id, pidx)
            forced = PartitionConfig(pc.ballot + 1, "node0",
                                     [n for n in pc.members()
                                      if n != "node0"])
            meta.state.update_partition(app_id, pidx, forced)
            meta._propose(app_id, pidx, forced)
        cluster.loop.run_until_idle()
        # with a split in flight the balancer must not touch the app
        meta.split._splits[app_id] = {"old_count": 6, "new_count": 12,
                                      "registered": []}
        assert meta.rebalance() == []
        del meta.split._splits[app_id]
        assert meta.rebalance()  # now it proposes
    finally:
        cluster.close()


def test_split_refuses_pending_moves_and_unhealthy_partitions(tmp_path):
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=4, seed=2)
    try:
        app_id = cluster.create_table("gd", partition_count=2,
                                      replica_count=2)
        cluster.loop.run_until_idle()
        meta = cluster.meta
        # pending balancer copy-secondary move on the app: refused
        meta._pending_moves[(app_id, 0)] = ("node3", "node1")
        meta._pending_learns[(app_id, 0)] = ("node3", 0.0)
        with pytest.raises(PegasusError) as ei:
            meta.split.start_partition_split("gd")
        assert ei.value.code == ErrorCode.ERR_INVALID_STATE
        del meta._pending_moves[(app_id, 0)]
        del meta._pending_learns[(app_id, 0)]
        # unhealthy partition (both members dead, primary un-curable):
        # refused until repaired
        pc = meta.state.get_partition(app_id, 0)
        for node in pc.members():
            cluster.kill(node)
        cluster.step(rounds=4)  # FD declares them dead; no cure possible
        with pytest.raises(PegasusError) as ei:
            meta.split.start_partition_split("gd")
        assert ei.value.code == ErrorCode.ERR_INVALID_STATE
        # repair: revive the members; once the table is healthy again
        # (primary back, no guardian learns in flight) the split runs
        for node in pc.members():
            cluster.revive(node)
        for _ in range(25):
            cluster.step()
            healthy = not meta._pending_learns and all(
                meta.fd.is_alive(
                    meta.state.get_partition(app_id, p).primary)
                for p in range(2))
            if healthy:
                break
        assert meta.split.start_partition_split("gd") == 4
    finally:
        cluster.close()


# ---- quarantine firing mid-split (PR 5 x split) ----------------------


def test_child_quarantine_mid_split_rebuilds_from_checkpoint(tmp_path):
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=2, seed=5)
    try:
        app_id = cluster.create_table("qc", partition_count=1,
                                      replica_count=1)
        c = cluster.client("qc")
        for i in range(30):
            assert c.set(b"q%03d" % i, b"s", b"v%d" % i) == OK
        pc = cluster.meta.state.get_partition(app_id, 0)
        stub = cluster.stubs[pc.primary]
        # wedge the register phase so the session is provably mid-split
        stub.meta_addr = None
        cluster.meta.split.start_partition_split("qc")
        cluster.loop.run_until_idle()
        sess = stub._split_sessions[(app_id, 0)]
        assert sess["phase"] == "register"
        # PR 5 quarantine hits the HALF-BUILT CHILD: its store is
        # trashed; the session must restart from a fresh checkpoint
        stub._quarantine_replica((app_id, 1), "planted corruption")
        assert stub._split_sessions[(app_id, 0)]["phase"] == "ckpt"
        assert (app_id, 1) not in stub.replicas
        stub.meta_addr = cluster.metas[0].name
        for _ in range(12):
            cluster.step()
            if not cluster.meta.split.split_status("qc")["splitting"]:
                break
        assert cluster.meta.state.apps[app_id].partition_count == 2
        c.refresh_config()
        for i in range(30):
            assert c.get(b"q%03d" % i, b"s") == (OK, b"v%d" % i), i
    finally:
        cluster.close()


def test_parent_quarantine_mid_split_aborts_session(tmp_path):
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=6)
    try:
        app_id = cluster.create_table("qp", partition_count=1,
                                      replica_count=2)
        c = cluster.client("qp")
        for i in range(20):
            assert c.set(b"p%03d" % i, b"s", b"v%d" % i) == OK
        pc = cluster.meta.state.get_partition(app_id, 0)
        stub = cluster.stubs[pc.primary]
        stub.meta_addr = None  # wedge at register
        cluster.meta.split.start_partition_split("qp")
        cluster.loop.run_until_idle()
        assert (app_id, 0) in stub._split_sessions
        stub.meta_addr = cluster.metas[0].name
        # PR 5 quarantine hits the PARENT mid-split: session + half-built
        # child die with it; meta demotes and re-drives at the promoted
        # secondary, which re-spawns the child from its own state
        stub._quarantine_replica((app_id, 0), "planted corruption")
        assert (app_id, 0) not in stub._split_sessions
        assert (app_id, 1) not in stub.replicas
        for _ in range(20):
            cluster.step()
            if not cluster.meta.split.split_status("qp")["splitting"]:
                break
        assert cluster.meta.state.apps[app_id].partition_count == 2
        c.refresh_config()
        for i in range(20):
            assert c.get(b"p%03d" % i, b"s") == (OK, b"v%d" % i), i
    finally:
        cluster.close()


def test_meta_unregisters_corrupt_registered_child(tmp_path):
    """A REGISTERED (pre-flip, single-replica) child that reports
    corruption cannot be repaired by remove-and-relearn — meta must
    unregister it and re-drive the parent."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=2, seed=8)
    try:
        app_id = cluster.create_table("uc", partition_count=2,
                                      replica_count=1)
        cluster.loop.run_until_idle()
        meta = cluster.meta
        from pegasus_tpu.meta.server_state import PartitionConfig

        node = meta.state.get_partition(app_id, 0).primary
        meta.split._splits[app_id] = {"old_count": 2, "new_count": 4,
                                      "registered": [2]}
        meta.state.set_partition_raw(app_id, 2,
                                     PartitionConfig(1, node, []))
        parent_ballot = meta.state.get_partition(app_id, 0).ballot
        meta._on_replica_corrupted((app_id, 2), node)
        info = meta.split._splits[app_id]
        assert 2 not in info["registered"]
        assert meta.state.get_partition(app_id, 2).primary == ""
        # parent re-proposed (unfence + re-drive)
        assert meta.state.get_partition(app_id, 0).ballot \
            == parent_ballot + 1
    finally:
        cluster.close()


# ---- batched-path misroute: retry ONLY the stale-routed subset -------


def _count_batch_ops(cluster, msg_type, log):
    orig = cluster.net.send

    def send(src, dst, mt, payload):
        if mt == msg_type:
            log.append(sum(len(ops) for _g, ops in payload["groups"]))
        return orig(src, dst, mt, payload)

    cluster.net.send = send


def _split_to_four(cluster, app_id, table):
    cluster.meta.split.start_partition_split(table)
    for _ in range(15):
        cluster.step()
        if not cluster.meta.split.split_status(table)["splitting"]:
            break
    assert cluster.meta.state.apps[app_id].partition_count == 4


def test_point_read_batch_retries_only_misrouted_subset(tmp_path):
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=11)
    try:
        app_id = cluster.create_table("mr", partition_count=2)
        c = cluster.client("mr")
        keys = [b"k%03d" % i for i in range(24)]
        for hk in keys:
            assert c.set(hk, b"s", b"val-" + hk) == OK
        stale = cluster.client("mr", name="stale-reader")
        stale._ensure_config()
        assert stale.partition_count == 2
        _split_to_four(cluster, app_id, "mr")
        # the stale client flushes a batch grouped under count=2; the
        # keys whose new pidx moved to a child bounce per-op with
        # ERR_PARENT_PARTITION_MISUSED and ONLY they are retried
        misrouted = sum(1 for hk in keys
                        if key_hash_parts(hk, b"s") % 4 >= 2)
        assert 0 < misrouted < len(keys)
        sent = []
        _count_batch_ops(cluster, "client_read_batch", sent)
        groups = {}
        for hk in keys:
            ph = key_hash_parts(hk, b"s")
            groups.setdefault(ph % 2, []).append(
                ("get", generate_key(hk, b"s"), ph))
        out = stale.point_read_multi(groups)
        flat = [r for results in out.values() for r in results]
        assert len(flat) == len(keys)
        assert {r[1] for r in flat} == {b"val-" + hk for hk in keys}
        assert sum(sent) == len(keys) + misrouted, sent
    finally:
        cluster.close()


def test_write_batch_retries_only_misrouted_subset(tmp_path):
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=12)
    try:
        app_id = cluster.create_table("mw", partition_count=2)
        c = cluster.client("mw")
        assert c.set(b"seed", b"s", b"v") == OK
        stale = cluster.client("mw", name="stale-writer")
        stale._ensure_config()
        assert stale.partition_count == 2
        _split_to_four(cluster, app_id, "mw")
        keys = [b"w%03d" % i for i in range(24)]
        misrouted = sum(1 for hk in keys
                        if key_hash_parts(hk, b"s") % 4 >= 2)
        assert 0 < misrouted < len(keys)
        sent = []
        _count_batch_ops(cluster, "client_write_batch", sent)
        groups = {}
        for hk in keys:
            ph = key_hash_parts(hk, b"s")
            groups.setdefault(ph % 2, []).append(
                (OP_PUT, (generate_key(hk, b"s"), b"wv-" + hk, 0), ph))
        out = stale.write_multi(groups)
        assert all(r == OK for results in out.values() for r in results)
        assert sum(sent) == len(keys) + misrouted, sent
        # every write landed exactly once, readable through new routing
        for hk in keys:
            assert c.get(hk, b"s") == (OK, b"wv-" + hk), hk
    finally:
        cluster.close()


def test_batch_get_keeps_answered_groups_across_split_retry(tmp_path):
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=13)
    try:
        app_id = cluster.create_table("bg", partition_count=2)
        c = cluster.client("bg")
        # craft: group pidx0 (stale) mixes clean+moved keys -> bounces;
        # group pidx1 only holds keys that stay put -> answered once
        pool = [b"g%04d" % i for i in range(400)]
        moved = [hk for hk in pool if key_hash_parts(hk, b"s") % 4 == 2]
        steady0 = [hk for hk in pool
                   if key_hash_parts(hk, b"s") % 4 == 0]
        steady1 = [hk for hk in pool
                   if key_hash_parts(hk, b"s") % 4 == 1]
        keys = moved[:4] + steady0[:4] + steady1[:4]
        assert len(keys) == 12
        for hk in keys:
            assert c.set(hk, b"s", b"bv-" + hk) == OK
        stale = cluster.client("bg", name="stale-bg")
        stale._ensure_config()
        _split_to_four(cluster, app_id, "bg")
        sent = []  # (pidx, n_keys) per batch_get request
        orig = cluster.net.send

        def send(src, dst, mt, payload):
            if (mt == "client_read" and isinstance(payload, dict)
                    and payload.get("op") == "batch_get"):
                sent.append((payload["gpid"][1],
                             len(payload["args"].keys)))
            return orig(src, dst, mt, payload)

        cluster.net.send = send
        err, rows = stale.batch_get([(hk, b"s") for hk in keys])
        assert err == OK
        assert {(hk, v) for hk, _sk, v in rows} \
            == {(hk, b"bv-" + hk) for hk in keys}
        # attempt 1: pidx0 carries 8 keys (bounces), pidx1 carries 4
        # (answered). Attempt 2 re-sends ONLY pidx0's 8 keys, now split
        # across their true owners — the answered group never replays.
        total = sum(n for _p, n in sent)
        assert total == 12 + 8, sent
    finally:
        cluster.close()


# ---- observability: hot_partitions verb + metrics --------------------


def test_hot_partitions_verb_reports_signals(tmp_path):
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=14)
    try:
        cluster.create_table("hp", partition_count=4)
        c = cluster.client("hp")
        for i in range(80):
            assert c.set(b"h%03d" % i, b"s", b"v") == OK
        cluster.step(rounds=3)  # config_sync reports + controller rates
        replies = []
        cluster.net.register("hpcx",
                             lambda src, mt, p: replies.append(p))
        cluster.net.send("hpcx", cluster.metas[0].name, "admin", {
            "rid": 1, "cmd": "hot_partitions", "args": {}})
        cluster.loop.run_until_idle()
        assert replies and replies[0]["err"] == 0
        status = replies[0]["result"]
        rows = status["partitions"]
        assert len(rows) == 4
        assert sorted(r["gpid"][1] for r in rows) == [0, 1, 2, 3]
        assert all("cu_rate" in r and "hot_key" in r for r in rows)
        assert sum(r["read_cu"] + r["write_cu"] for r in rows) > 0
        assert status["splits_inflight"] == []
        assert "node_load" in status and "pressure" in status
    finally:
        cluster.close()


def test_split_fence_reject_metric_counts_fenced_writes(tmp_path):
    from pegasus_tpu.utils.metrics import METRICS

    cluster = SimCluster(str(tmp_path / "c"), n_nodes=2, seed=15)
    try:
        app_id = cluster.create_table("fm", partition_count=1,
                                      replica_count=1)
        c = cluster.client("fm")
        assert c.set(b"a", b"s", b"v") == OK
        pc = cluster.meta.state.get_partition(app_id, 0)
        stub = cluster.stubs[pc.primary]
        counter = METRICS.entity("storage", "node").counter(
            "split_fence_reject_count")
        before = counter.value()
        # fence the replica directly and fire one write at it
        stub.replicas[(app_id, 0)].splitting = True
        rid = c._send_request(pc.primary, "client_write", {
            "gpid": (app_id, 0), "ops": [], "auth": None,
            "partition_hash": None})
        reply = c._await(rid)
        assert reply["err"] == int(ErrorCode.ERR_SPLITTING)
        assert counter.value() == before + 1
        stub.replicas[(app_id, 0)].splitting = False
    finally:
        cluster.close()
