"""Partition split: 2x in-place split with lazy stale-half GC.

Parity: src/replica/split/replica_split_manager.h:58 (child copies parent
state, group flips partition count) + key_ttl_compaction_filter.h:114-121
(stale-half physical removal at compaction).
"""

import pytest

from pegasus_tpu.base.key_schema import generate_key, partition_index
from pegasus_tpu.client import PegasusClient, ScanOptions, Table


@pytest.fixture
def loaded(tmp_path):
    t = Table(str(tmp_path / "t"), partition_count=4)
    c = PegasusClient(t)
    data = {}
    for i in range(120):
        hk, sk, v = b"user_%03d" % i, b"s%d" % (i % 3), b"v%d" % i
        c.multi_set(hk, {sk: v})
        data.setdefault(hk, {})[sk] = v
    yield t, c, data
    t.close()


def test_split_preserves_all_data(loaded):
    t, c, data = loaded
    t.split()
    assert t.partition_count == 8
    for hk, kvs in data.items():
        for sk, v in kvs.items():
            assert c.get(hk, sk) == (0, v), (hk, sk)
    # new routing actually spreads across the new partitions
    owners = {partition_index(hk, 8) for hk in data}
    assert len(owners) > 4


def test_split_scans_exclude_stale_halves(loaded):
    t, c, data = loaded
    total_before = sum(len(kvs) for kvs in data.values())
    t.split()
    rows = [r for sc in c.get_unordered_scanners(1, ScanOptions(
        batch_size=1000)) for r in sc]
    # every record exactly once despite two physical copies existing
    assert len(rows) == total_before
    seen = {}
    for hk, sk, v in rows:
        assert seen.setdefault((hk, sk), v) == v
    assert len(seen) == total_before


def test_split_compaction_drops_stale_halves(loaded):
    t, c, data = loaded
    t.split()
    # physical copies before compaction: every record exists twice
    physical = sum(
        sum(tbl.total_count for tbl in p.engine.lsm.l0)
        + sum(t.total_count for t in p.engine.lsm.l1_runs)
        + len(p.engine.lsm.memtable)
        for p in t.all_partitions())
    total = sum(len(kvs) for kvs in data.values())
    assert physical >= total  # duplicated state present
    t.manual_compact_all()
    physical_after = sum(
        sum(t.total_count for t in p.engine.lsm.l1_runs)
        for p in t.all_partitions())
    assert physical_after == total  # stale halves physically gone
    for hk, kvs in data.items():
        for sk, v in kvs.items():
            assert c.get(hk, sk) == (0, v)


def test_split_table_reopens_from_disk(tmp_path):
    t = Table(str(tmp_path / "t"), partition_count=2)
    c = PegasusClient(t)
    c.set(b"hk", b"s", b"v")
    t.split()
    t.flush_all()
    t.close()
    t2 = Table(str(tmp_path / "t"), partition_count=4)
    assert PegasusClient(t2).get(b"hk", b"s") == (0, b"v")
    t2.close()


def test_onebox_split_persists_catalog(tmp_path, capsys):
    from pegasus_tpu.tools.shell import main as shell_main
    root = str(tmp_path / "box")
    shell_main(["--root", root, "create_app", "t", "-p", "2"])
    shell_main(["--root", root, "set", "t", "hk", "s", "v"])
    assert shell_main(["--root", root, "partition_split", "t"]) == 0
    out = capsys.readouterr().out
    assert "partition count now 4" in out
    shell_main(["--root", root, "ls"])
    assert "partitions=4" in capsys.readouterr().out
    assert shell_main(["--root", root, "get", "t", "hk", "s"]) == 0
    assert capsys.readouterr().out.strip() == "v"


def test_split_requires_power_of_two(tmp_path):
    t = Table(str(tmp_path / "t"), partition_count=3)
    try:
        with pytest.raises(ValueError):
            t.split()
    finally:
        t.close()


def test_split_children_inherit_envs_and_data_version(tmp_path):
    t = Table(str(tmp_path / "t"), partition_count=2, data_version=0)
    try:
        c = PegasusClient(t)
        t.update_app_envs({"default_ttl": "500"})
        c.set(b"hk", b"s", b"v0value")
        t.split()
        for p in t.all_partitions():
            assert p.app_envs.get("default_ttl") == "500"
            assert p.data_version == 0
        # v0 values still decode correctly everywhere after the split
        assert c.get(b"hk", b"s") == (0, b"v0value")
    finally:
        t.close()


def test_writes_after_split_land_in_new_partitions(loaded):
    t, c, _ = loaded
    t.split()
    c.set(b"newbie_42", b"s", b"fresh")
    pidx = partition_index(b"newbie_42", 8)
    server = t.partitions[pidx]
    assert server.on_get(generate_key(b"newbie_42", b"s")) == (0, b"fresh")


def test_flip_drops_row_and_plan_caches_no_stale_parent_row(tmp_path):
    """Epoch-guard across the count flip (PR 6): rows admitted into the
    node row cache and the per-generation plan/point caches under the
    PARENT's pre-split routing must never serve after the flip — an
    acked pre-split write read through a child (or a post-split
    overwrite) must always see the latest bytes."""
    from pegasus_tpu.server.row_cache import ROW_CACHE

    t = Table(str(tmp_path / "t"), partition_count=2)
    try:
        c = PegasusClient(t)
        keys = [b"rc%03d" % i for i in range(40)]
        for hk in keys:
            c.set(hk, b"s", b"v1-" + hk)
        t.flush_all()  # rows must be base-resolved to enter the cache
        # two batched flushes: the repeat gate admits on the 2nd touch
        for parent in t.all_partitions():
            ops = [("get", generate_key(hk, b"s"), None) for hk in keys
                   if partition_index(hk, 2) == parent.pidx]
            for _ in range(2):
                results = parent.on_point_read_batch(ops)
                assert all(r[0] == 0 for r in results)
        app_id = t.app_id
        stats = ROW_CACHE.stats()["per_gid"]
        parent_gids = {str((app_id, p)) for p in range(2)}
        assert parent_gids & set(stats), stats  # parent rows resident
        assert any(p._point_cache is not None
                   for p in t.all_partitions())
        t.split()
        # the flip dropped every parent row and plan/point cache
        stats = ROW_CACHE.stats()["per_gid"]
        assert not (parent_gids & set(stats)), stats
        for p in t.all_partitions():
            assert p._point_cache is None
            assert p._plan_cache is None
            assert p._live_cache == {}
        # overwrite post-split (owned by whichever partition routes it
        # now), then read through the batched path: never the v1 bytes
        for hk in keys:
            c.set(hk, b"s", b"v2-" + hk)
        for hk in keys:
            pidx = partition_index(hk, 4)
            server = t.partitions[pidx]
            res = server.on_point_read_batch(
                [("get", generate_key(hk, b"s"), None)] * 2)
            assert res == [(0, b"v2-" + hk)] * 2, hk
            assert c.get(hk, b"s") == (0, b"v2-" + hk), hk
    finally:
        t.close()


def test_split_concurrent_writes_not_lost(tmp_path):
    """ADVICE r1 (medium): a write acked by a parent after its child's
    checkpoint but before the count flip must not vanish. split() fences
    writes table-wide, so every acked write is either pre-checkpoint (in
    the child copy) or post-flip (routed by the new count)."""
    import threading

    from pegasus_tpu.client import PegasusClient, Table
    from pegasus_tpu.utils.errors import StorageStatus

    t = Table(str(tmp_path / "t"), partition_count=4)
    c = PegasusClient(t)
    acked = []
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            hk = b"w_%05d" % i
            if c.set(hk, b"sk", b"v%d" % i) == int(StorageStatus.OK):
                acked.append((hk, b"v%d" % i))
            i += 1

    th = threading.Thread(target=writer)
    th.start()
    try:
        t.split()
        t.split()  # 4 -> 8 -> 16 under fire
    finally:
        stop.set()
        th.join()
    assert t.partition_count == 16
    t.flush_all()
    t.manual_compact_all()  # drops stale-half copies; acked must survive
    for hk, v in acked:
        assert c.get(hk, b"sk") == (int(StorageStatus.OK), v), hk
    assert len(acked) > 0
    t.close()
