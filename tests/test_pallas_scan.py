"""Fused Pallas scan kernel vs the reference jnp predicate path.

Runs in interpret mode on CPU; the same program compiles for TPU.
"""

import numpy as np
import pytest

from pegasus_tpu.base.crc import crc64
from pegasus_tpu.base.key_schema import generate_key, key_hash
from pegasus_tpu.ops.pallas_scan import fused_scan_block, prepare_transposed
from pegasus_tpu.ops.predicates import (
    FT_MATCH_ANYWHERE,
    FT_MATCH_POSTFIX,
    FT_MATCH_PREFIX,
    FT_NO_FILTER,
    FilterSpec,
    scan_block_predicate,
)
from pegasus_tpu.ops.record_block import build_record_block


def _block_with_hash(keys, ets, capacity=None):
    block = build_record_block(keys, ets, capacity=capacity)
    n = block.capacity
    hash_lo = np.zeros(n, dtype=np.uint32)
    for i, k in enumerate(keys):
        hash_lo[i] = key_hash(k) & 0xFFFFFFFF
    return block._replace(hash_lo=hash_lo)


def _random_keys(rng, n, pattern=b""):
    keys = []
    for _ in range(n):
        hk = bytes(rng.integers(97, 123, size=rng.integers(1, 10),
                                dtype=np.uint8))
        sk = bytes(rng.integers(97, 123, size=rng.integers(0, 16),
                                dtype=np.uint8))
        if pattern and rng.random() < 0.5:
            pos = rng.integers(0, len(sk) + 1)
            sk = sk[:pos] + pattern + sk[pos:]
        keys.append(generate_key(hk, sk))
    return keys


@pytest.mark.parametrize("ftype", [FT_NO_FILTER, FT_MATCH_ANYWHERE,
                                   FT_MATCH_PREFIX, FT_MATCH_POSTFIX])
def test_fused_matches_jnp_path(ftype):
    rng = np.random.default_rng(ftype)
    keys = _random_keys(rng, 100, pattern=b"abc")
    ets = [0 if i % 4 else 500 for i in range(100)]
    block = _block_with_hash(keys, ets, capacity=128)
    spec = FilterSpec.make(ftype, b"abc")
    now = 1000
    keep_f, expired_f = fused_scan_block(
        block, now, sort_filter=spec, validate_hash=True, pidx=3,
        partition_version=7, interpret=True)
    masks = scan_block_predicate(block, now, sort_filter=spec,
                                 validate_hash=True, pidx=3,
                                 partition_version=7)
    np.testing.assert_array_equal(keep_f, np.asarray(masks.keep))
    np.testing.assert_array_equal(expired_f, np.asarray(masks.expired))


def test_fused_no_validate_hash():
    keys = [generate_key(b"h%d" % i, b"s%d" % i) for i in range(10)]
    block = _block_with_hash(keys, [0] * 10, capacity=16)
    keep, expired = fused_scan_block(block, 100, interpret=True)
    assert keep[:10].all() and not keep[10:].any()
    assert not expired.any()


def test_fused_requires_hash_column():
    keys = [generate_key(b"h", b"s")]
    # strip the hash column (the native packer now provides it by default)
    block = build_record_block(keys, [0])._replace(hash_lo=None)
    with pytest.raises(ValueError):
        fused_scan_block(block, 0, validate_hash=True, partition_version=1)


def test_fused_with_prepared_cache():
    keys = [generate_key(b"hk", b"s%02d" % i) for i in range(20)]
    block = _block_with_hash(keys, [0] * 20, capacity=32)
    prepared = prepare_transposed(block)
    spec = FilterSpec.make(FT_MATCH_PREFIX, b"s0")
    keep1, _ = fused_scan_block(block, 0, sort_filter=spec, interpret=True,
                                prepared=prepared)
    keep2, _ = fused_scan_block(block, 0, sort_filter=spec, interpret=True)
    np.testing.assert_array_equal(keep1, keep2)
    assert keep1[:10].all() and not keep1[10:20].any()


def test_fused_long_pattern_rejected():
    keys = [generate_key(b"h", b"s")]
    block = _block_with_hash(keys, [0])
    with pytest.raises(ValueError):
        fused_scan_block(block, 0,
                         sort_filter=FilterSpec.make(FT_MATCH_PREFIX,
                                                     b"x" * 40),
                         interpret=True)
