"""Storage integrity end to end: block CRCs, injectable disk faults,
the background scrubber, and corruption repair via re-learn.

Parity: the reference trusts rocksdb's per-block CRC and repairs
corrupt replicas through the learner flow; the chaos shape mirrors
kill_test with --mode corrupt (seeded bit-flips in live SST files).
Everything here is seeded and deterministic — the e2e sim case replays
the full detect -> quarantine -> guardian-removal -> re-learn ->
byte-identical-reads loop in-process.
"""

import errno
import json
import os
import random

import pytest

from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.storage.sstable import FOOTER, SSTable, SSTableWriter
from pegasus_tpu.utils.errors import (
    ErrorCode,
    PegasusError,
    StorageCorruptionError,
)
from pegasus_tpu.utils.fail_point import FAIL_POINTS
from pegasus_tpu.utils.flags import FLAGS
from pegasus_tpu.utils.metrics import METRICS

OK = 0


def k(h, s=""):
    return generate_key(h if isinstance(h, bytes) else h.encode(),
                        s if isinstance(s, bytes) else s.encode())


def _write_sst(path, n=40, block_capacity=8, meta=None):
    w = SSTableWriter(path, block_capacity=block_capacity, meta=meta)
    for i in range(n):
        w.add(k("h%04d" % i, "s"), b"value-%04d" % i)
    w.finish()
    return path


def _flip_block_byte(path, block_idx=0, offset_in_block=7, bit=3):
    """Deterministically flip one bit inside a data block."""
    with open(path, "r+b") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(size - FOOTER.size)
        index_offset, index_size, _crc, _magic = FOOTER.unpack(
            f.read(FOOTER.size))
        f.seek(index_offset)
        index = json.loads(f.read(index_size))
        b = index["blocks"][block_idx]
        pos = b["off"] + (offset_in_block % b["size"])
        f.seek(pos)
        byte = f.read(1)
        f.seek(pos)
        f.write(bytes([byte[0] ^ (1 << bit)]))


# ---- block crc: round trip, detection, legacy fallback ----------------


def test_block_crc_roundtrip_and_detection(tmp_path):
    path = _write_sst(str(tmp_path / "t.sst"))
    t = SSTable(path)
    assert all(bm.crc is not None for bm in t.blocks)
    assert t.get(k("h0003", "s")) == (b"value-0003", 0)
    # every block passes the scrub-side raw verify too
    for i in range(len(t.blocks)):
        assert t.verify_block(i) is True
    t.verify_index_consistency()
    t.close()

    _flip_block_byte(path, block_idx=1)
    t2 = SSTable(path)  # index itself is intact — open succeeds
    # a key in the clean block still serves
    assert t2.get(k("h0001", "s")) == (b"value-0001", 0)
    # the corrupt block is refused at decode time, typed
    with pytest.raises(StorageCorruptionError):
        t2.read_block(1)
    with pytest.raises(StorageCorruptionError):
        t2.verify_block(1)
    t2.close()


def test_block_crc_cached_hit_not_reverified(tmp_path):
    """Verify-on-read sits BEHIND the block cache: a resident block is
    never re-checked (the <3% overhead contract), so a flip landing
    after the block was cached is served from memory until eviction —
    the scrubber exists precisely for that window."""
    path = _write_sst(str(tmp_path / "t.sst"))
    t = SSTable(path)
    blk = t.read_block(0)  # verified + cached
    _flip_block_byte(path, block_idx=0)
    # cache hit: no re-read, no raise, same decoded block object
    assert t.read_block(0) is blk
    t.close()


def test_legacy_file_without_block_crc_serves_unverified(tmp_path):
    FLAGS.set("pegasus.storage", "block_crc", False)
    try:
        path = _write_sst(str(tmp_path / "legacy.sst"))
    finally:
        FLAGS.set("pegasus.storage", "block_crc", True)
    t = SSTable(path)
    assert all(bm.crc is None for bm in t.blocks)
    assert t.get(k("h0002", "s")) == (b"value-0002", 0)
    # nothing to verify: the scrub raw pass skips legacy blocks
    assert t.verify_block(0) is False
    t.verify_index_consistency()
    t.close()


def test_index_corruption_detected_at_open(tmp_path):
    path = _write_sst(str(tmp_path / "t.sst"))
    with open(path, "r+b") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        f.seek(size - FOOTER.size)
        index_offset, _sz, _crc, _magic = FOOTER.unpack(
            f.read(FOOTER.size))
        f.seek(index_offset + 3)
        byte = f.read(1)
        f.seek(index_offset + 3)
        f.write(bytes([byte[0] ^ 0x10]))
    with pytest.raises(StorageCorruptionError):
        SSTable(path)


# ---- vfs fault actions ------------------------------------------------


def _armed(points, seed=42):
    FAIL_POINTS.teardown()
    FAIL_POINTS.setup()
    FAIL_POINTS.seed(seed)
    for name, action in points.items():
        FAIL_POINTS.cfg(name, action)


def test_vfs_bit_flip_read_is_deterministic(tmp_path):
    from pegasus_tpu.storage import vfs

    p = str(tmp_path / "f.bin")
    with open(p, "wb") as f:
        f.write(bytes(range(256)) * 4)

    def read_once(seed):
        _armed({"vfs::read": "return(bit_flip)"}, seed=seed)
        try:
            with vfs.open_data_file(p, "rb") as f:
                return f.read()
        finally:
            FAIL_POINTS.teardown()

    a = read_once(7)
    b = read_once(7)
    c = read_once(8)
    clean = open(p, "rb").read()
    assert a == b, "same seed must corrupt the same bit"
    assert a != clean, "the flip must actually corrupt"
    assert c != a, "a different seed draws a different bit"
    # exactly one bit differs
    diff = [(x, y) for x, y in zip(a, clean) if x != y]
    assert len(diff) == 1
    assert bin(diff[0][0] ^ diff[0][1]).count("1") == 1


def test_vfs_eio_and_enospc_typed_oserrors(tmp_path):
    from pegasus_tpu.storage import vfs

    p = str(tmp_path / "f.bin")
    with open(p, "wb") as f:
        f.write(b"x" * 64)
    _armed({"vfs::read": "return(eio)"})
    try:
        with pytest.raises(OSError) as ei:
            vfs.open_data_file(p, "rb").read()
        assert ei.value.errno == errno.EIO
    finally:
        FAIL_POINTS.teardown()
    _armed({"vfs::write": "return(enospc)"})
    try:
        with pytest.raises(OSError) as ei:
            vfs.open_data_file(str(tmp_path / "g.bin"), "wb").write(b"y")
        assert ei.value.errno == errno.ENOSPC
    finally:
        FAIL_POINTS.teardown()
    _armed({"vfs::fsync": "return(eio)"})
    try:
        f = vfs.open_data_file(str(tmp_path / "h.bin"), "wb")
        f.write(b"z")
        with pytest.raises(OSError) as ei:
            vfs.fsync_file(f)
        assert ei.value.errno == errno.EIO
    finally:
        FAIL_POINTS.teardown()


def test_vfs_torn_write_persists_strict_prefix(tmp_path):
    from pegasus_tpu.storage import vfs

    p = str(tmp_path / "t.bin")
    payload = bytes(range(200))
    _armed({"vfs::write": "return(torn_write)"}, seed=3)
    try:
        f = vfs.open_data_file(p, "wb")
        with pytest.raises(OSError) as ei:
            f.write(payload)
        assert ei.value.errno == errno.EIO
        f.close()
    finally:
        FAIL_POINTS.teardown()
    on_disk = open(p, "rb").read()
    assert len(on_disk) < len(payload)
    assert on_disk == payload[:len(on_disk)]


def test_mutation_log_torn_tail_recovery_under_injected_faults(tmp_path):
    """The satellite contract: a partial (torn) append + a failed fsync
    must leave the log recoverable — the valid prefix replays, the torn
    tail truncates at reopen, and later appends land cleanly."""
    from pegasus_tpu.replica.mutation import Mutation
    from pegasus_tpu.replica.mutation_log import MutationLog

    path = str(tmp_path / "plog" / "mlog.bin")
    log = MutationLog(path)
    for d in (1, 2, 3):
        log.append(Mutation(1, d, d - 1, 1000 + d, []))
    log.close()

    _armed({"vfs::write": "return(torn_write)",
            "vfs::fsync": "return(eio)"}, seed=5)
    try:
        log2 = MutationLog(path)  # reopen THROUGH the armed vfs
        with pytest.raises(OSError):
            log2.append(Mutation(1, 4, 3, 1004, []), sync=True)
        log2.close()
    finally:
        FAIL_POINTS.teardown()

    # the file now carries a torn frame after 3 valid ones; recovery
    # truncates it and the next appends are reachable
    log3 = MutationLog(path)
    assert [mu.decree for mu in log3.replay(path)] == [1, 2, 3]
    log3.append(Mutation(1, 5, 3, 1005, []))
    assert [mu.decree for mu in log3.replay(path)] == [1, 2, 3, 5]
    log3.close()


# ---- scrubber ---------------------------------------------------------


def _mini_engine(tmp_path, n=64):
    from types import SimpleNamespace

    from pegasus_tpu.storage.engine import StorageEngine, WriteBatchItem
    from pegasus_tpu.storage.wal import OP_PUT

    eng = StorageEngine(str(tmp_path / "app"))
    eng.write_batch([WriteBatchItem(OP_PUT, k("h%03d" % i, "s"),
                                    b"v%03d" % i) for i in range(n)],
                    decree=1)
    eng.flush()
    fake_replica = SimpleNamespace(server=SimpleNamespace(engine=eng))
    return eng, fake_replica


def test_scrubber_clean_pass_then_finds_planted_flip(tmp_path):
    from pegasus_tpu.storage.scrub import ReplicaScrubber

    eng, rep = _mini_engine(tmp_path)
    hits = []
    sc = ReplicaScrubber(lambda: {(1, 0): rep},
                         lambda gpid, exc: hits.append((gpid, exc)))
    res = sc.scrub_now((1, 0), rep)
    assert res["state"] == "clean" and res["blocks_scanned"] > 0
    assert hits == []

    sst = [os.path.join(eng.lsm.data_dir, f)
           for f in os.listdir(eng.lsm.data_dir) if f.endswith(".sst")]
    assert sst
    _flip_block_byte(sst[0])
    before = METRICS.entity("storage", "node").counter(
        "scrub_corrupt_blocks").value()
    res = sc.scrub_now((1, 0), rep)
    assert res["state"] == "corrupt"
    assert hits and hits[0][0] == (1, 0)
    assert isinstance(hits[0][1], StorageCorruptionError)
    assert METRICS.entity("storage", "node").counter(
        "scrub_corrupt_blocks").value() == before + 1
    eng.close()


def test_scrubber_paced_tick_restarts_on_generation_change(tmp_path):
    from pegasus_tpu.storage.engine import WriteBatchItem
    from pegasus_tpu.storage.scrub import ReplicaScrubber
    from pegasus_tpu.storage.wal import OP_PUT

    eng, rep = _mini_engine(tmp_path, n=64)
    sc = ReplicaScrubber(lambda: {(1, 0): rep}, lambda *_: None,
                         blocks_per_tick=1)
    sc.tick()  # starts a pass, one block in
    assert (1, 0) in sc._cursor
    # a flush bumps the generation: the cursor restarts next tick
    eng.write_batch([WriteBatchItem(OP_PUT, k("zzz", "s"), b"v")],
                    decree=2)
    eng.flush()
    sc.tick()
    cur = sc._cursor[(1, 0)]
    assert cur["gen"] == eng.lsm.generation
    eng.close()


# ---- dir health -------------------------------------------------------


def test_fs_manager_dir_health_and_placement(tmp_path):
    from pegasus_tpu.replica.fs_manager import (
        DIR_IO_ERROR,
        DIR_NORMAL,
        DIR_SPACE_INSUFFICIENT,
        FsManager,
    )

    d1, d2 = str(tmp_path / "d1"), str(tmp_path / "d2")
    fs = FsManager([d1, d2])
    assert fs.dir_status(d1) == DIR_NORMAL
    fs.note_io_error(os.path.join(d1, "1.0", "app", "x.sst"),
                     OSError(errno.ENOSPC, "no space"))
    assert fs.dir_status(d1) == DIR_SPACE_INSUFFICIENT
    # new replicas avoid the sick dir
    assert fs.replica_dir((9, 9)).startswith(os.path.abspath(d2))
    fs.note_io_error(os.path.join(d1, "wal"), OSError(errno.EIO, "io"))
    assert fs.dir_status(d1) == DIR_IO_ERROR
    # IO_ERROR is sticky over a later ENOSPC
    fs.note_io_error(d1, OSError(errno.ENOSPC, "no space"))
    assert fs.dir_status(d1) == DIR_IO_ERROR
    health = {h["dir"]: h for h in fs.health()}
    assert health[os.path.abspath(d1)]["io_errors"] == 3
    assert health[os.path.abspath(d2)]["status"] == DIR_NORMAL
    # every dir sick: placement degrades to least-loaded instead of
    # refusing (cures must not wedge)
    fs.note_io_error(d2, OSError(errno.EIO, "io"))
    assert fs.replica_dir((9, 8))
    fs.mark_dir_normal(d2)
    assert fs.dir_status(d2) == DIR_NORMAL


def test_integrity_codes_are_client_retryable():
    from pegasus_tpu.client.cluster_client import _RETRYABLE

    assert int(ErrorCode.ERR_CHECKSUM_FAILED) in _RETRYABLE
    assert int(ErrorCode.ERR_DISK_IO_ERROR) in _RETRYABLE


# ---- end-to-end: detect -> quarantine -> re-learn ---------------------


def _flush_all(cluster):
    for stub in cluster.stubs.values():
        for r in stub.replicas.values():
            r.server.flush()


def _sst_files_of(cluster, node, gpid):
    stub = cluster.stubs[node]
    r = stub.replicas[gpid]
    d = os.path.join(r.server.engine.data_dir, "sst")
    return sorted(os.path.join(d, f) for f in os.listdir(d)
                  if f.endswith(".sst"))


def _storage_counter(name):
    return METRICS.entity("storage", "node").counter(name).value()


def test_corrupt_secondary_scrub_detects_guardian_relearns(tmp_path):
    """The acceptance loop, seeded: flip a bit in a SECONDARY's SST
    (secondaries serve no reads — only the scrub can see it), assert
    the replica quarantines, the guardian removes it, a learner
    catches back up, and every read is byte-identical to
    pre-corruption — with the counters observing each stage."""
    from pegasus_tpu.replica.replica import PartitionStatus
    from pegasus_tpu.server.row_cache import ROW_CACHE
    from pegasus_tpu.tools.cluster import SimCluster

    # 3 nodes, 3 replicas: the quarantined node is the ONLY spare, so
    # the guardian MUST repair by re-learning onto it — proving the
    # fresh store rebuilds from a healthy peer, not the trashed bytes
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=17)
    try:
        app_id = cluster.create_table("it", partition_count=1,
                                      replica_count=3)
        client = cluster.client("it")
        expected = {}
        for i in range(120):
            hk = b"ik%04d" % i
            val = b"payload-%04d" % i
            assert client.set(hk, b"s", val) == OK
            expected[hk] = val
        _flush_all(cluster)
        gpid = (app_id, 0)
        pc = cluster.meta.state.get_partition(*gpid)
        victim = pc.secondaries[0]
        ssts = _sst_files_of(cluster, victim, gpid)
        assert ssts, "flush must have produced SSTs on the secondary"
        # plant a stale row for this gid in the node row cache: the
        # quarantine must drop it (regression: no pre-repair bytes may
        # survive the re-learn)
        vstub = cluster.stubs[victim]
        lsm = vstub.replicas[gpid].server.engine.lsm
        ROW_CACHE.admit(gpid, lsm.store_uid, lsm.generation,
                        b"stale-key", b"stale-value", 0)
        assert str(gpid) in ROW_CACHE.stats()["per_gid"]

        q0 = _storage_counter("replica_quarantine_count")
        s0 = _storage_counter("scrub_corrupt_blocks")
        ballot0 = pc.ballot
        old_replica = vstub.replicas[gpid]
        rng = random.Random(99)
        from pegasus_tpu.tools.kill_test import corrupt_sst_file

        assert corrupt_sst_file(ssts[0], rng)
        # force the scrub past its pass-interval pacing
        vstub.scrubber.pass_interval = 0.0

        # detection + quarantine + guardian removal + re-learn all ride
        # the cluster timers (a full cycle can resolve inside one step)
        for _ in range(12):
            cluster.step()
            pc = cluster.meta.state.get_partition(*gpid)
            r = cluster.stubs[victim].replicas.get(gpid)
            if (victim in pc.members() and r is not None
                    and r is not old_replica
                    and r.status == PartitionStatus.SECONDARY):
                break
        # each stage observed
        assert _storage_counter("scrub_corrupt_blocks") == s0 + 1
        assert _storage_counter("replica_quarantine_count") == q0 + 1
        # the guardian's removal really happened: the cure bumped the
        # ballot (removal + learner upgrade are distinct config steps)
        pc = cluster.meta.state.get_partition(*gpid)
        assert pc.ballot >= ballot0 + 2
        # the corrupt store was retired to trash, and the victim serves
        # from a FRESH replica (re-learned), not the old object
        node_dir = cluster.stubs[victim].data_dir
        assert any(e.endswith(".gar") for e in os.listdir(node_dir)), \
            "corrupt store was not trashed"
        assert cluster.stubs[victim].replicas[gpid] is not old_replica
        # the stale pre-repair row is gone from the node cache
        assert str(gpid) not in ROW_CACHE.stats()["per_gid"]
        # the victim was removed and re-learned back to SECONDARY
        pc = cluster.meta.state.get_partition(*gpid)
        assert victim in pc.members()
        assert cluster.stubs[victim].replicas[gpid].status == \
            PartitionStatus.SECONDARY
        # the re-learned store matches the primary byte for byte
        primary_engine = \
            cluster.stubs[pc.primary].replicas[gpid].server.engine
        victim_engine = \
            cluster.stubs[victim].replicas[gpid].server.engine
        for hk in expected:
            key = k(hk, "s")
            assert victim_engine.get(key) == primary_engine.get(key), hk
        # and reads are byte-identical to pre-corruption
        for hk, val in expected.items():
            assert client.get(hk, b"s") == (OK, val)
    finally:
        cluster.close()


def test_corrupt_compressed_block_scrub_quarantine_relearn(tmp_path):
    """Round-11 coverage: the bit-flip lands inside a COMPRESSED (dcz)
    block. The per-block CRC is computed over the on-disk encoded
    bytes, so the scrubber's raw re-read detects the flip without any
    decode; quarantine -> guardian removal -> re-learn repairs, and
    reads come back byte-identical."""
    from pegasus_tpu.replica.replica import PartitionStatus
    from pegasus_tpu.tools.cluster import SimCluster

    assert FLAGS.get("pegasus.storage",
                 "block_codec").startswith("dcz")
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=31)
    try:
        app_id = cluster.create_table("cz", partition_count=1,
                                      replica_count=3)
        client = cluster.client("cz")
        expected = {}
        for i in range(150):
            hk = b"ck%04d" % i
            val = b"zpayload-%04d|" % i * 3
            assert client.set(hk, b"s", val) == OK
            expected[hk] = val
        _flush_all(cluster)
        # compact every replica so the victim serves from L1 runs that
        # are PROVABLY compressed (flush already stamps the codec, but
        # the compacted run is the steady-state shape)
        for stub in cluster.stubs.values():
            for r in stub.replicas.values():
                r.server.manual_compact()
        gpid = (app_id, 0)
        pc = cluster.meta.state.get_partition(*gpid)
        victim = pc.secondaries[0]
        vstub = cluster.stubs[victim]
        lsm = vstub.replicas[gpid].server.engine.lsm
        runs = list(lsm.l0) + list(lsm.l1_runs)
        assert runs and all(t.codec.startswith("dcz")
                    for t in runs)
        assert all(bm.crc is not None
                   for t in runs for bm in t.blocks)
        old_replica = vstub.replicas[gpid]

        s0 = _storage_counter("scrub_corrupt_blocks")
        q0 = _storage_counter("replica_quarantine_count")
        _flip_block_byte(runs[0].path, block_idx=0, offset_in_block=60)
        vstub.scrubber.pass_interval = 0.0
        for _ in range(12):
            cluster.step()
            pc = cluster.meta.state.get_partition(*gpid)
            r = cluster.stubs[victim].replicas.get(gpid)
            if (victim in pc.members() and r is not None
                    and r is not old_replica
                    and r.status == PartitionStatus.SECONDARY):
                break
        assert _storage_counter("scrub_corrupt_blocks") == s0 + 1
        assert _storage_counter("replica_quarantine_count") == q0 + 1
        # re-learned store: compressed runs again, byte-identical reads
        new_lsm = cluster.stubs[victim].replicas[gpid] \
            .server.engine.lsm
        assert all(t.codec.startswith("dcz")
                   for t in list(new_lsm.l0) + list(new_lsm.l1_runs))
        pc = cluster.meta.state.get_partition(*gpid)
        primary_engine = \
            cluster.stubs[pc.primary].replicas[gpid].server.engine
        victim_engine = \
            cluster.stubs[victim].replicas[gpid].server.engine
        for hk, val in expected.items():
            key = k(hk, "s")
            assert victim_engine.get(key) == primary_engine.get(key)
            assert client.get(hk, b"s") == (OK, val)
    finally:
        cluster.close()


def test_corrupt_primary_read_detects_demotes_and_serves(tmp_path):
    """A corrupt PRIMARY is detected on the READ path: the client sees
    typed retryable ERR_CHECKSUM_FAILED, the replica quarantines, the
    guardian promotes a healthy secondary, and the retried read serves
    the correct bytes from it."""
    from pegasus_tpu.tools.cluster import SimCluster

    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=23)
    try:
        app_id = cluster.create_table("cp", partition_count=1,
                                      replica_count=3)
        client = cluster.client("cp")
        expected = {}
        for i in range(80):
            hk = b"pk%04d" % i
            val = b"pv-%04d" % i
            assert client.set(hk, b"s", val) == OK
            expected[hk] = val
        _flush_all(cluster)
        gpid = (app_id, 0)
        pc = cluster.meta.state.get_partition(*gpid)
        old_primary = pc.primary
        ssts = _sst_files_of(cluster, old_primary, gpid)
        assert ssts
        # corrupt EVERY block of the primary's SSTs so the very next
        # uncached read trips the crc (the block cache may hold some)
        for sst in ssts:
            t = SSTable(sst)
            nblocks = len(t.blocks)
            t.close()
            for bi in range(nblocks):
                _flip_block_byte(sst, block_idx=bi)
        # drop the primary's decoded-block caches so reads re-decode
        stub = cluster.stubs[old_primary]
        for table in (list(stub.replicas[gpid].server.engine.lsm.l0)
                      + list(stub.replicas[gpid].server.engine.lsm
                             .l1_runs)):
            table.clear_block_cache()
        q0 = _storage_counter("replica_quarantine_count")
        # reads retry through the refresh path onto the new primary
        for hk, val in expected.items():
            assert client.get(hk, b"s") == (OK, val)
        assert _storage_counter("replica_quarantine_count") == q0 + 1
        pc = cluster.meta.state.get_partition(*gpid)
        assert pc.primary and pc.primary != old_primary
        assert old_primary not in pc.members() or \
            old_primary != pc.primary
    finally:
        cluster.close()


def test_stub_write_path_reports_disk_health(tmp_path):
    """An OSError surfacing through a client write marks the owning
    data dir sick and quarantines the replica with the typed
    ERR_DISK_IO_ERROR reply (counted on the storage entity)."""
    from pegasus_tpu.replica.fs_manager import DIR_IO_ERROR
    from pegasus_tpu.tools.cluster import SimCluster

    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=31)
    try:
        app_id = cluster.create_table("dh", partition_count=1,
                                      replica_count=3)
        client = cluster.client("dh")
        assert client.set(b"k", b"s", b"v") == OK
        gpid = (app_id, 0)
        pc = cluster.meta.state.get_partition(*gpid)
        stub = cluster.stubs[pc.primary]
        r = stub.replicas[gpid]
        d0 = _storage_counter("disk_io_error_count")

        def exploding_write(*a, **kw):
            raise OSError(errno.EIO, "dying disk",
                          os.path.join(r.data_dir, "plog", "mlog.bin"))

        r.client_write = exploding_write
        # the write fails over: quarantine -> promote -> retry lands on
        # the new primary and succeeds
        assert client.set(b"k2", b"s", b"v2") == OK
        assert _storage_counter("disk_io_error_count") == d0 + 1
        assert stub.fs.dir_status(stub.data_dir) == DIR_IO_ERROR
        assert gpid not in stub.replicas  # quarantined
    finally:
        cluster.close()


@pytest.mark.slow
def test_kill_test_corrupt_mode_onebox(tmp_path):
    """Real processes, real disk: seeded bit-flips in live SST files;
    the DataVerifier invariant must hold through detection ->
    quarantine -> re-learn, and the integrity counters must have
    observed at least one full loop."""
    from pegasus_tpu.tools import onebox_cluster as ob
    from pegasus_tpu.tools.kill_test import run_kill_test

    d = str(tmp_path / "corruptbox")
    ob.start(d, n_replica=3)
    try:
        report = run_kill_test(d, duration_s=30, kill_every_s=10,
                               seed=4, mode="corrupt",
                               op_timeout_ms=30_000)
        assert report["violations"] == [], report
        assert report["kills"] >= 1, report
        assert report["quarantines"] >= 1, report
    finally:
        ob.stop(d)
