"""At-rest encryption: KMS envelope keys + transparent file cipher
(parity: security/kms_client.h, replica/kms_key_provider.h, and the
encrypted-Env file path under FLAGS_encrypt_data_at_rest)."""

import os

import pytest

from pegasus_tpu.security.kms import (
    KeyProvider,
    KmsError,
    LocalKmsClient,
    keystream,
    xor_crypt,
)
from pegasus_tpu.storage import efile
from pegasus_tpu.storage.efile import open_data_file
from pegasus_tpu.storage.sstable import SSTable, SSTableWriter


@pytest.fixture
def zone(tmp_path):
    """An encryption zone over tmp_path/data, torn down after the test."""
    root = str(tmp_path / "data")
    kms = LocalKmsClient(b"test-root-key-0123456789")
    efile.enable_encryption(root, KeyProvider(root, kms))
    try:
        yield root
    finally:
        efile.disable_encryption(root)


def test_keystream_is_seekable():
    key, nonce = b"k" * 32, b"n" * 16
    full = keystream(key, nonce, 0, 20_000)
    for off, ln in ((0, 10), (4090, 20), (8192, 4096), (13_333, 777)):
        assert keystream(key, nonce, off, ln) == full[off:off + ln]
    data = os.urandom(9000)
    ct = xor_crypt(key, nonce, 0, data)
    assert xor_crypt(key, nonce, 0, ct) == data
    # decrypting an interior slice needs only its offset
    assert xor_crypt(key, nonce, 5000, ct[5000:6000]) == data[5000:6000]


def test_kms_wrap_unwrap_and_tamper():
    kms = LocalKmsClient(b"root-key-material-xyz")
    key, wrapped = kms.generate_data_key()
    assert kms.unwrap(wrapped) == key
    bad = bytearray(wrapped)
    bad[20] ^= 0xFF
    with pytest.raises(KmsError):
        kms.unwrap(bytes(bad))
    with pytest.raises(KmsError):
        LocalKmsClient(b"a-different-root-key").unwrap(wrapped)


def test_key_provider_persists_key(tmp_path):
    kms = LocalKmsClient(b"root-key-material-xyz")
    p1 = KeyProvider(str(tmp_path), kms)
    p2 = KeyProvider(str(tmp_path), kms)
    assert p1.data_key == p2.data_key
    with pytest.raises(KmsError):
        KeyProvider(str(tmp_path), LocalKmsClient(b"wrong-root-key-..."))


def test_cipher_file_random_access(zone):
    path = os.path.join(zone, "blob")
    os.makedirs(zone, exist_ok=True)
    payload = os.urandom(50_000)
    with open_data_file(path, "wb") as f:
        f.write(payload)
        f.flush()
    with open(path, "rb") as f:
        raw = f.read()
    assert raw[:8] == efile.MAGIC and payload[:64] not in raw
    with open_data_file(path, "rb") as f:
        assert f.read() == payload
        f.seek(40_000)
        assert f.read(100) == payload[40_000:40_100]
        f.seek(-500, os.SEEK_END)
        assert f.read() == payload[-500:]
    # append continues the stream where it left off
    with open_data_file(path, "ab") as f:
        assert f.tell() == len(payload)
        f.write(b"tail-bytes")
    with open_data_file(path, "rb") as f:
        assert f.read() == payload + b"tail-bytes"
    # truncate through r+b (the mutation-log repair path)
    with open_data_file(path, "r+b") as f:
        f.truncate(1000)
    with open_data_file(path, "rb") as f:
        assert f.read() == payload[:1000]


def test_sstable_encrypted_round_trip(zone):
    os.makedirs(zone, exist_ok=True)
    path = os.path.join(zone, "t.sst")
    w = SSTableWriter(path, block_capacity=8)
    rows = [(b"\x00\x04hk%02d" % i + b"sortkey%02d" % i,
             b"SECRETVALUE-%04d" % i) for i in range(40)]
    for k, v in rows:
        w.add(k, v)
    w.finish()
    assert efile.is_encrypted(path)
    with open(path, "rb") as f:
        raw = f.read()
    assert b"SECRETVALUE" not in raw and b"sortkey" not in raw
    t = SSTable(path)
    got = []
    for bi in range(len(t.blocks)):
        blk = t.read_block(bi)
        for i in range(blk.count):
            got.append((blk.key_at(i), blk.value_at(i)))
    assert got == rows
    t.close()


def test_legacy_plaintext_readable_inside_zone(tmp_path):
    root = str(tmp_path / "data")
    os.makedirs(root)
    path = os.path.join(root, "old.sst")
    w = SSTableWriter(path, block_capacity=8)  # plaintext: no zone yet
    w.add(b"\x00\x02hharold", b"plain-old-value")
    w.finish()
    kms = LocalKmsClient(b"test-root-key-0123456789")
    efile.enable_encryption(root, KeyProvider(root, kms))
    try:
        t = SSTable(path)  # sniffed as plaintext, still served
        assert t.read_block(0).value_at(0) == b"plain-old-value"
        t.close()
        new = os.path.join(root, "new.sst")
        w = SSTableWriter(new, block_capacity=8)
        w.add(b"\x00\x02hhnew", b"fresh")
        w.finish()
        assert efile.is_encrypted(new) and not efile.is_encrypted(path)
    finally:
        efile.disable_encryption(root)


def test_mutation_log_encrypted_restart(zone):
    from pegasus_tpu.replica.mutation import Mutation, WriteOp
    from pegasus_tpu.replica.mutation_log import MutationLog
    from pegasus_tpu.rpc.codec import OP_PUT

    os.makedirs(zone, exist_ok=True)
    path = os.path.join(zone, "plog")
    log = MutationLog(path)
    for d in range(1, 8):
        log.append(Mutation(ballot=1, decree=d, last_committed=d - 1,
                            timestamp_us=d * 1000, ops=[
                WriteOp(OP_PUT, (b"k%d" % d, b"v%d" % d, 0))]),
            sync=True)
    log.close()
    assert efile.is_encrypted(path)
    log2 = MutationLog(path)  # exercises scan + truncate-repair open
    assert log2.max_decree == 7
    replayed = [mu.decree for mu in MutationLog.replay(path)]
    assert replayed == list(range(1, 8))
    log2.gc(durable_decree=5)
    assert [mu.decree for mu in MutationLog.replay(path)] == [6, 7]
    assert efile.is_encrypted(path)
    log2.close()


def test_cluster_end_to_end_encrypted(tmp_path, monkeypatch):
    from pegasus_tpu.tools.cluster import SimCluster

    monkeypatch.setenv("PEGASUS_ENCRYPT_AT_REST", "1")
    monkeypatch.setenv("PEGASUS_KMS_ROOT_KEY", b"cluster-root-secret!".hex())
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3)
    try:
        cluster.create_table("enc", partition_count=4)
        c = cluster.client("enc")
        for i in range(30):
            assert c.set(b"user%03d" % i, b"s", b"topsecret-%d" % i) == 0
        for node in cluster.stubs.values():
            for rep in list(node.replicas.values()):
                rep.server.engine.flush()
        assert c.get(b"user007", b"s") == (0, b"topsecret-7")
        # NOTHING on disk leaks plaintext — every file under every
        # node (SSTs, storage WAL, replica mutation log, metadata)
        n_files = 0
        for base, _dirs, files in os.walk(str(tmp_path / "c")):
            for name in files:
                n_files += 1
                with open(os.path.join(base, name), "rb") as f:
                    raw = f.read()
                assert b"topsecret" not in raw, os.path.join(base, name)
                assert b"user00" not in raw, os.path.join(base, name)
        assert n_files > 0
    finally:
        cluster.close()
        for z in list(efile._zones):
            efile.disable_encryption(z)


def test_learning_transfer_reencrypts_per_node(tmp_path, monkeypatch):
    """LT_APP learning across nodes with encryption on: checkpoint files
    travel as plaintext chunks (the nfs-analogue reads through the
    cipher) and land re-encrypted under the LEARNER's own data key."""
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.tools.cluster import SimCluster
    from pegasus_tpu.utils.errors import StorageStatus

    OK = int(StorageStatus.OK)
    monkeypatch.setenv("PEGASUS_ENCRYPT_AT_REST", "1")
    monkeypatch.setenv("PEGASUS_KMS_ROOT_KEY", b"cluster-root-secret!".hex())
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=2)
    try:
        app_id = cluster.create_table("tx", partition_count=1,
                                      replica_count=1)
        c = cluster.client("tx")
        for i in range(200):
            assert c.set(b"t%04d" % i, b"s", b"v%d" % i) == OK
        pc = cluster.meta.state.get_partition(app_id, 0)
        primary = cluster.stubs[pc.primary]
        primary.get_replica((app_id, 0)).flush_and_gc_log()
        for stub in cluster.stubs.values():
            stub.shared_fs = False
            for r in stub.replicas.values():
                r.shared_fs = False
        cluster.meta.state.apps[app_id].max_replica_count = 2
        for _ in range(12):
            cluster.step()
            pc = cluster.meta.state.get_partition(app_id, 0)
            if len(pc.members()) == 2:
                break
        assert len(pc.members()) == 2, pc
        other = [n for n in pc.members() if n != primary.name][0]
        learner = cluster.stubs[other].get_replica((app_id, 0))
        for i in (0, 100, 199):
            assert learner.server.on_get(
                generate_key(b"t%04d" % i, b"s")) == (OK, b"v%d" % i)
        # the learned SSTs are ciphertext under the learner's key
        n = 0
        sst_dir = os.path.join(learner.server.engine.data_dir, "sst")
        for name in os.listdir(sst_dir):
            if name.endswith(".sst"):
                n += 1
                assert efile.is_encrypted(os.path.join(sst_dir, name))
        assert n > 0
        k1 = cluster.stubs[pc.primary].data_dir
        k2 = cluster.stubs[other].data_dir
        from pegasus_tpu.storage.efile import zone_for
        assert zone_for(k1).data_key != zone_for(k2).data_key
    finally:
        cluster.close()
        for z in list(efile._zones):
            efile.disable_encryption(z)


def test_boot_fails_loudly_without_root_key(tmp_path, monkeypatch):
    from pegasus_tpu.tools.cluster import SimCluster

    monkeypatch.setenv("PEGASUS_ENCRYPT_AT_REST", "1")
    monkeypatch.delenv("PEGASUS_KMS_ROOT_KEY", raising=False)
    monkeypatch.delenv("PEGASUS_KMS_ROOT_KEY_FILE", raising=False)
    with pytest.raises(RuntimeError, match="PEGASUS_KMS_ROOT_KEY"):
        SimCluster(str(tmp_path / "c"), n_nodes=1)


def test_repair_truncate_uses_fresh_nonce(zone):
    """Torn-tail repair must not re-emit keystream at reused offsets."""
    os.makedirs(zone, exist_ok=True)
    path = os.path.join(zone, "log")
    with open_data_file(path, "wb") as f:
        f.write(b"A" * 1000)
    nonce_before = efile._sniff(path)
    efile.repair_truncate(path, 400)
    nonce_after = efile._sniff(path)
    assert nonce_before != nonce_after
    with open_data_file(path, "rb") as f:
        assert f.read() == b"A" * 400
    with open_data_file(path, "ab") as f:
        f.write(b"B" * 100)
    with open_data_file(path, "rb") as f:
        assert f.read() == b"A" * 400 + b"B" * 100


def test_offline_dump_reads_encrypted_files(tmp_path, monkeypatch, capsys):
    """sst_dump / mlog_dump on an encrypted cluster work when the
    operator exports the KMS root key, and fail loudly without it."""
    from pegasus_tpu.tools.shell import main as shell_main
    from pegasus_tpu.tools.cluster import SimCluster

    monkeypatch.setenv("PEGASUS_ENCRYPT_AT_REST", "1")
    monkeypatch.setenv("PEGASUS_KMS_ROOT_KEY", b"forensics-root-key!!".hex())
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=1)
    try:
        cluster.create_table("t", partition_count=1)
        c = cluster.client("t")
        for i in range(10):
            assert c.set(b"hk%d" % i, b"s", b"val-%d" % i) == 0
        for node in cluster.stubs.values():
            for rep in list(node.replicas.values()):
                rep.server.engine.flush()
    finally:
        cluster.close()
        for z in list(efile._zones):
            efile.disable_encryption(z)
    sst = None
    for base, _d, files in os.walk(str(tmp_path / "c")):
        for name in files:
            if name.endswith(".sst"):
                sst = os.path.join(base, name)
    assert sst and efile.is_encrypted(sst)
    assert shell_main(["sst_dump", sst]) == 0
    out = capsys.readouterr().out
    assert "val-" in out
    monkeypatch.delenv("PEGASUS_KMS_ROOT_KEY")
    with pytest.raises(SystemExit, match="PEGASUS_KMS_ROOT_KEY"):
        shell_main(["sst_dump", sst])


def test_key_provider_for_dirs_survives_disk0_loss(tmp_path):
    """Multi-disk server: the wrapped key is replicated to every dir and
    found in ANY of them, so replacing disk 0 cannot orphan the rest."""
    import shutil

    kms = LocalKmsClient(b"root-key-material-xyz")
    dirs = [str(tmp_path / d) for d in ("d0", "d1", "d2")]
    p1 = KeyProvider.for_dirs(dirs, kms)
    from pegasus_tpu.security.kms import KEY_FILE
    assert all(os.path.exists(os.path.join(d, KEY_FILE)) for d in dirs)
    # disk 0 replaced with a blank one
    shutil.rmtree(dirs[0])
    os.makedirs(dirs[0])
    p2 = KeyProvider.for_dirs(dirs, kms)
    assert p2.data_key == p1.data_key  # found on d1, re-replicated
    assert os.path.exists(os.path.join(dirs[0], KEY_FILE))


def test_shared_fs_learn_reencrypts(tmp_path, monkeypatch):
    """Default shared_fs=True learn copies the primary's checkpoint by
    path; with per-server keys the copy must decrypt/re-encrypt, not
    raw-copy bytes."""
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.tools.cluster import SimCluster
    from pegasus_tpu.utils.errors import StorageStatus

    OK = int(StorageStatus.OK)
    monkeypatch.setenv("PEGASUS_ENCRYPT_AT_REST", "1")
    monkeypatch.setenv("PEGASUS_KMS_ROOT_KEY", b"cluster-root-secret!".hex())
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=2)
    try:
        app_id = cluster.create_table("tx", partition_count=1,
                                      replica_count=1)
        c = cluster.client("tx")
        for i in range(200):
            assert c.set(b"t%04d" % i, b"s", b"v%d" % i) == OK
        pc = cluster.meta.state.get_partition(app_id, 0)
        primary = cluster.stubs[pc.primary]
        primary.get_replica((app_id, 0)).flush_and_gc_log()
        cluster.meta.state.apps[app_id].max_replica_count = 2
        for _ in range(12):
            cluster.step()
            pc = cluster.meta.state.get_partition(app_id, 0)
            if len(pc.members()) == 2:
                break
        assert len(pc.members()) == 2, pc
        other = [n for n in pc.members() if n != primary.name][0]
        learner = cluster.stubs[other].get_replica((app_id, 0))
        for i in (0, 100, 199):
            assert learner.server.on_get(
                generate_key(b"t%04d" % i, b"s")) == (OK, b"v%d" % i)
    finally:
        cluster.close()
        for z in list(efile._zones):
            efile.disable_encryption(z)
