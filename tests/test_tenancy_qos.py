"""Multi-tenant QoS tier-1 gates: the retryability matrix (sync and
async clients must agree code-for-code), the bounded tenant registry +
CU-budget governor, the transport's deficit-weighted round-robin, the
aggressor-only brownout rule, the read-limiter's virtual-clock
threading, and the seeded-sim isolation proof — a compliant tenant
riding next to a zipfian abuser (through a node kill) sees ZERO
over-budget rejections while the abuser is the only one gated."""

import random

import pytest

from pegasus_tpu.client import aio
from pegasus_tpu.client import cluster_client as cc
from pegasus_tpu.server.read_limiter import RangeReadLimiter
from pegasus_tpu.server.tenancy import (
    DEFAULT_TENANT,
    MAX_TENANTS,
    TENANTS,
    sanitize_tenant,
)
from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.utils.errors import ErrorCode, PegasusError, StorageStatus
from pegasus_tpu.utils.flags import FLAGS

OK = int(StorageStatus.OK)


@pytest.fixture(autouse=True)
def _qos_flags():
    """Restore the mutable QoS flags tests flip (the TENANTS registry
    itself is reset by the conftest autouse fixture)."""
    yield
    from pegasus_tpu.utils import health as health_mod

    health_mod.reset_capture()
    FLAGS.set("pegasus.qos", "tenant_enforce", True)
    FLAGS.set("pegasus.qos", "tenant_borrow_when_idle", True)
    FLAGS.set("pegasus.qos", "tenant_idle_borrow_s", 2.0)


class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


# ---- satellite: the retryability matrix ----------------------------------


# the full matrix, spelled out: a code joining either set must be added
# HERE too, so retry semantics change by explicit decision, not drift
_EXPECT_RETRYABLE = {
    ErrorCode.ERR_INVALID_STATE,
    ErrorCode.ERR_INACTIVE_STATE,
    ErrorCode.ERR_PARENT_PARTITION_MISUSED,
    ErrorCode.ERR_OBJECT_NOT_FOUND,
    ErrorCode.ERR_TIMEOUT,
    ErrorCode.ERR_SPLITTING,
    ErrorCode.ERR_BUSY,
    ErrorCode.ERR_CHECKSUM_FAILED,
    ErrorCode.ERR_DISK_IO_ERROR,
    ErrorCode.ERR_DUP_FENCED,
    ErrorCode.ERR_STALE_REPLICA,
    ErrorCode.ERR_CU_OVERBUDGET,
}
_EXPECT_NO_REFRESH = {
    ErrorCode.ERR_BUSY,
    ErrorCode.ERR_STALE_REPLICA,
    ErrorCode.ERR_CU_OVERBUDGET,
}


def test_retryability_matrix_is_explicit_and_total():
    assert cc.RETRYABLE_CODES == {int(c) for c in _EXPECT_RETRYABLE}
    assert cc.NO_REFRESH_CODES == {int(c) for c in _EXPECT_NO_REFRESH}
    # no-refresh is a refinement of retryable, never a separate policy
    assert cc.NO_REFRESH_CODES < cc.RETRYABLE_CODES
    # hard non-retryables stay out (an app-level error must surface,
    # not spin the backoff loop)
    for code in (ErrorCode.ERR_OK, ErrorCode.ERR_APP_NOT_EXIST,
                 ErrorCode.ERR_ACL_DENY, ErrorCode.ERR_INVALID_PARAMETERS):
        assert int(code) not in cc.RETRYABLE_CODES


def test_sync_and_async_clients_share_one_retry_surface():
    """aio re-exports the SAME frozensets (identity, not copies): the
    async client can never drift a code from the sync client."""
    assert aio.RETRYABLE_CODES is cc.RETRYABLE_CODES
    assert aio.NO_REFRESH_CODES is cc.NO_REFRESH_CODES
    assert isinstance(cc.RETRYABLE_CODES, frozenset)
    assert isinstance(cc.NO_REFRESH_CODES, frozenset)


# ---- registry: bounded cardinality + sanitation --------------------------


def test_wire_tags_fold_into_bounded_registry():
    assert sanitize_tenant("gold-7") == "gold-7"
    for raw in (None, "", "UPPER", "a" * 33, "sneaky;drop", 42,
                "-leading-dash"):
        assert sanitize_tenant(raw) == DEFAULT_TENANT
    # resolve() never mints: an unknown (but well-formed) tag answers
    # as the default tenant until an env/operator registers it
    assert TENANTS.resolve("unregistered").name == DEFAULT_TENANT
    TENANTS.ensure("gold", 4.0, 0.0)
    assert TENANTS.resolve("gold").name == "gold"


def test_registry_cap_folds_overflow_to_default():
    for i in range(MAX_TENANTS + 10):
        TENANTS.ensure(f"t{i:03d}", 1.0, 0.0)
    assert len(TENANTS.names()) <= MAX_TENANTS
    # the overflow registration answered as default, not a fresh entity
    assert TENANTS.ensure("one-more", 1.0, 0.0).name == DEFAULT_TENANT


def test_env_config_parses_weights_and_budgets():
    TENANTS.configure_from_envs(
        {"qos.tenants": "gold:4:10000, free:1:500, bare, junk:x:y"})
    snap = TENANTS.snapshot()
    assert snap["gold"]["weight"] == 4.0
    assert snap["gold"]["cu_budget"] == 10000.0
    assert snap["free"]["cu_budget"] == 500.0
    assert snap["bare"]["weight"] == 1.0 and snap["bare"]["cu_budget"] == 0
    assert "junk" not in snap  # malformed fields skip, never crash


# ---- CU budgets: post-debit admission + borrow-when-idle -----------------


def test_cu_budget_post_debit_gate_and_refill():
    clk = _Clock()
    TENANTS.set_clock(clk)
    TENANTS.ensure("payg", 1.0, 100.0)  # 100 CU/s, 2s burst = 200 CU
    FLAGS.set("pegasus.qos", "tenant_borrow_when_idle", False)
    assert TENANTS.admit("payg") == 0  # bucket starts at burst
    TENANTS.charge("payg", 500)  # post-debit: bill ACTUAL usage
    err = TENANTS.admit("payg")
    assert err == int(ErrorCode.ERR_CU_OVERBUDGET)
    assert TENANTS.snapshot()["payg"]["overbudget"] >= 1
    # refill pays the debt down; admission resumes without any reset
    clk.t += 10.0
    assert TENANTS.admit("payg") == 0
    # the kill switch bypasses the gate entirely
    TENANTS.charge("payg", 10_000)
    FLAGS.set("pegasus.qos", "tenant_enforce", False)
    assert TENANTS.admit("payg") == 0


def test_borrow_when_idle_admits_without_contention():
    clk = _Clock()
    TENANTS.set_clock(clk)
    TENANTS.ensure("payg", 1.0, 100.0)
    TENANTS.ensure("noisy", 1.0, 0.0)
    TENANTS.charge("payg", 10_000)  # deep over budget
    # every OTHER tenant quiet -> soft mode lets it run (budgets cap
    # contention, not idle throughput)
    assert TENANTS.admit("payg") == 0
    # a recent charge by anyone else ends the borrow
    TENANTS.charge("noisy", 1)
    assert TENANTS.admit("payg") == int(ErrorCode.ERR_CU_OVERBUDGET)
    # ... and the borrow returns once they go quiet past the horizon
    clk.t += FLAGS.get("pegasus.qos", "tenant_idle_borrow_s") + 0.1
    assert TENANTS.admit("payg") == 0


# ---- transport: deficit-weighted round-robin -----------------------------


class _NoThreadTransport:
    """TcpTransport with its IO threads suppressed: the fair-queue
    structure (_classify/_drr_pick/_sched_get) is dispatch-thread-only
    state, so with no dispatcher running the test IS the dispatcher."""

    def __new__(cls):
        from pegasus_tpu.rpc.transport import TcpTransport

        class _T(TcpTransport):
            def _spawn(self, fn, *args):
                pass

        return _T(None, {})


def _read_item(tenant):
    return (0.0, "cli", "node0", "client_read",
            {"tenant": tenant, "rid": 1}, "s1")


def test_drr_drains_tenants_by_weight_ratio():
    TENANTS.ensure("gold", 4.0, 0.0)
    TENANTS.ensure("free", 1.0, 0.0)
    tr = _NoThreadTransport()
    for _ in range(8):
        tr._classify(_read_item("gold"))
        tr._classify(_read_item("free"))
    drained = [tr._drr_pick()[4]["tenant"] for _ in range(10)]
    # weight 4:1 -> each rotation serves 4 gold then 1 free; over the
    # first 10 picks the hot-but-heavy tenant gets exactly its share
    # while the light tenant still makes progress every rotation
    assert drained.count("gold") == 8
    assert drained.count("free") == 2
    assert drained[:5] == ["gold"] * 4 + ["free"]


def test_writes_and_system_traffic_bypass_the_fair_queue():
    TENANTS.ensure("gold", 4.0, 0.0)
    tr = _NoThreadTransport()
    tr._classify(_read_item("gold"))
    tr._classify((0.0, "cli", "node0", "client_write",
                  {"tenant": "gold", "rid": 2}, "s1"))
    tr._classify((0.0, "peer", "node0", "prepare_batch", [], "s2"))
    # mutation + replication drain first, strict priority — the
    # fair queue arbitrates only shed-eligible reads
    assert tr._sched_get()[3] == "client_write"
    assert tr._sched_get()[3] == "prepare_batch"
    assert tr._sched_get()[3] == "client_read"
    # forged/unknown tags fold into the default queue, never mint one
    tr._classify(_read_item("NOT A SLUG ~~~"))
    assert set(tr._tenant_queues) <= {"gold", DEFAULT_TENANT}


# ---- brownout: the aggressor-only rule drives the registry ---------------


def test_brownout_rule_fires_on_aggressor_only_and_gates_registry():
    from pegasus_tpu.utils.health import HealthEngine, default_rules
    from pegasus_tpu.utils.metrics import MetricRegistry
    from pegasus_tpu.utils.timeseries import FlightRecorder, SeriesRing

    clock = _Clock(1000.0)
    reg = MetricRegistry()
    rec = FlightRecorder("n0", clock=clock, registry=reg)
    rule = next(r for r in default_rules() if r.name == "tenant_brownout")
    assert rule.entity_type == "tenant"
    abuser = SeriesRing("value")
    victim = SeriesRing("value")
    rec._series[("tenant", "abuser", "tenant_cu_ratio")] = abuser
    rec._series[("tenant", "victim", "tenant_cu_ratio")] = victim
    rec._total_points = 1
    eng = HealthEngine("n0", rec, rules=[rule], clock=clock)
    TENANTS.ensure("abuser", 1.0, 100.0)
    TENANTS.ensure("victim", 4.0, 0.0)

    def drive():
        for ev in eng.evaluate():
            if ev.rule == "tenant_brownout":
                TENANTS.set_brownout(ev.entity[1], ev.firing)

    # sustained 3x-over-budget consumption on the abuser, calm victim
    for i in range(4):
        abuser.append(clock.t, 3.0)
        victim.append(clock.t, 0.2)
        drive()
        clock.t += 10.0
    assert TENANTS.browned("abuser")
    assert not TENANTS.browned("victim")  # per-tenant series: a
    # compliant tenant can NEVER trip the aggressor's rule
    assert TENANTS.snapshot()["abuser"]["browned"] is True
    # shedding pulls the ratio back under budget -> clear_hold releases
    for i in range(4):
        abuser.append(clock.t, 0.1)
        victim.append(clock.t, 0.2)
        drive()
        clock.t += 10.0
    assert not TENANTS.browned("abuser")


# ---- satellite: read-limiter virtual-clock regression --------------------


def test_range_read_limiter_burns_the_injected_clock():
    """Regression: the iteration time budget must follow the clock the
    host threads in — a compressed sim schedule burns thousands of
    virtual seconds in milliseconds of wall time (and a wall-stalled
    host must not trip a budget with zero virtual time spent)."""
    ns = _Clock(t=0)
    lim = RangeReadLimiter(max_iteration_count=0, threshold_time_ms=10,
                           clock_ns=lambda: int(ns.t))
    assert lim.valid()
    ns.t = 9 * 1_000_000
    assert not lim.time_exceeded() and lim.valid()
    ns.t = 11 * 1_000_000
    assert lim.time_exceeded() and not lim.valid()
    # count budget is independent of the clock
    lim2 = RangeReadLimiter(max_iteration_count=3, threshold_time_ms=0,
                            clock_ns=lambda: int(ns.t))
    lim2.add_count(3)
    assert lim2.count_exceeded() and not lim2.time_exceeded()


def test_sim_hosted_partitions_thread_the_virtual_clock(tmp_path):
    """A SimCluster replica's partition server must hold a clock_ns on
    the VIRTUAL timebase (stub wiring), not wall perf_counter."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3)
    try:
        cluster.create_table("t", partition_count=2)
        cluster.step(rounds=2)
        r = next(iter(next(iter(cluster.stubs.values())).replicas.values()))
        assert r.server.clock_ns is not None
        before = r.server.clock_ns()
        cluster.loop.run_for(5.0)  # 5 virtual seconds, ~0 wall
        assert r.server.clock_ns() - before >= int(5.0 * 1e9)
    finally:
        cluster.close()


# ---- satellite: seeded-sim isolation proof -------------------------------


_QOS_ENVS = {
    # abuser: weight 1, 60 CU/s budget (120 CU burst). compliant:
    # weight 8 and an effectively-unmetered budget.
    "qos.tenants": "abuser:1:60,compliant:8:1000000",
    "qos.default_tenant": "compliant",
}


def test_sim_qos_isolation_compliant_never_gated_through_node_kill(tmp_path):
    """Two tenants on one table: a zipfian abuser hammering well past
    its CU budget and a compliant tenant doing steady light work, with
    a node kill mid-run. The gate: the abuser is the ONLY tenant that
    ever goes over budget — the compliant tenant finishes every op,
    is never shed, and never sees ERR_CU_OVERBUDGET."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=3)
    try:
        app_id = cluster.create_table("t", partition_count=4,
                                      envs=_QOS_ENVS)
        cluster.step(rounds=2)
        abuser = cluster.client("t", name="cli-abuser",
                                tenant="abuser")
        compliant = cluster.client("t", name="cli-compliant",
                                   tenant="compliant")
        rng = random.Random(11)
        from pegasus_tpu.tools.scale_test import zipf_keys

        compliant_ok = 0
        # 32KB values: ~8 CU per abuser write, so each iteration's 5
        # writes (~40 CU) far outrun the 60 CU/s refill — the budget
        # gates on consumption, not on the op count
        for i in range(30):
            for hk in zipf_keys(rng, 200, 1.2, 5):
                abuser.set(hk, b"s", b"x" * 32768)
            # compliant: steady light traffic, interleaved so the
            # borrow-when-idle soft mode never applies to the abuser
            assert compliant.set(b"ck%d" % i, b"s", b"v%d" % i) == OK
            assert compliant.get(b"ck%d" % i, b"s") == (OK, b"v%d" % i)
            compliant_ok += 2
            if i == 15:
                victim_node = cluster.primaries(app_id)[0]
                cluster.kill(victim_node)
                cluster.step(rounds=4)  # FD expiry + cures
        snap = TENANTS.snapshot()
        assert compliant_ok == 60
        # the abuser was gated (typed retryable rejections it rode out
        # with jittered backoff — its ops still completed eventually)
        assert snap["abuser"]["overbudget"] > 0
        # the compliant tenant NEVER was: zero over-budget rejections,
        # zero brownout sheds, despite sharing every funnel
        assert snap["compliant"]["overbudget"] == 0
        assert snap["compliant"]["shed"] == 0
        # both tenants' CU consumption was actually metered (the proof
        # is vacuous if attribution silently broke)
        assert snap["abuser"]["cu_total"] > 0
        assert snap["compliant"]["cu_total"] > 0
    finally:
        cluster.close()


def test_overbudget_retry_skips_config_refresh(tmp_path):
    """ERR_CU_OVERBUDGET means "the tenant is hot", not "the routing
    table is stale": the client's backoff retry must NOT burn a config
    refresh (re-resolving would convert a CU storm into a meta query
    storm — same discipline as ERR_BUSY)."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3)
    try:
        cluster.create_table(
            "t", partition_count=2,
            envs={"qos.tenants": "abuser:1:20,compliant:8:1000000"})
        cluster.step(rounds=2)
        FLAGS.set("pegasus.qos", "tenant_borrow_when_idle", False)
        client = cluster.client("t", tenant="abuser")
        assert client.set(b"warm", b"s", b"v") == OK  # config cached
        refreshes = []
        real_refresh = client.refresh_config
        client.refresh_config = lambda *a, **k: (
            refreshes.append(1), real_refresh(*a, **k))
        # 16KB values = ~5 CU each against a 20 CU/s budget: the tail
        # of these ops hits the admit gate and retries through the
        # jittered backoff (virtual sleep refills the bucket, so every
        # op still completes — the deficit is bounded by one op's CU)
        for i in range(60):
            assert client.set(b"k%d" % i, b"s", b"x" * 16384) == OK
        assert TENANTS.snapshot()["abuser"]["overbudget"] > 0
        assert refreshes == []  # no-refresh subset held behaviorally
    finally:
        cluster.close()


def test_brownout_gate_sheds_only_the_browned_tenant(tmp_path):
    """The stub's read gate honors the registry's brownout verdict:
    ONLY the browned tenant's reads shed (ERR_BUSY), writes and every
    other tenant keep flowing; release reopens the tap."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3)
    try:
        cluster.create_table("t", partition_count=2, envs=_QOS_ENVS)
        cluster.step(rounds=2)
        abuser = cluster.client("t", name="cli-abuser",
                                tenant="abuser")
        compliant = cluster.client("t", name="cli-compliant",
                                   tenant="compliant")
        assert abuser.set(b"a", b"s", b"v1") == OK
        assert compliant.set(b"c", b"s", b"v2") == OK
        TENANTS.set_brownout("abuser", True)
        with pytest.raises(PegasusError):
            abuser.get(b"a", b"s")  # retries exhaust against the gate
        # writes are NEVER brownout-shed (mutation path degrades last)
        assert abuser.set(b"a2", b"s", b"v3") == OK
        assert compliant.get(b"c", b"s") == (OK, b"v2")  # untouched
        assert TENANTS.snapshot()["abuser"]["shed"] > 0
        assert TENANTS.snapshot()["compliant"]["shed"] == 0
        TENANTS.set_brownout("abuser", False)
        assert abuser.get(b"a", b"s") == (OK, b"v1")
    finally:
        cluster.close()
