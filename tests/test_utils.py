"""Utility-layer tests: flags, metrics, fail points, token bucket."""

import time

import pytest

from pegasus_tpu.utils.errors import ErrorCode, PegasusError, StorageStatus
from pegasus_tpu.utils.fail_point import FAIL_POINTS, fail_point
from pegasus_tpu.utils.flags import FlagRegistry
from pegasus_tpu.utils.metrics import MetricRegistry
from pegasus_tpu.utils.token_bucket import TokenBucket, parse_throttle_env


def test_flags_define_get_set(tmp_path):
    reg = FlagRegistry()
    reg.define("pegasus.server", "rocksdb_block_cache_capacity", 1024,
               mutable=True)
    reg.define("replication", "staleness_for_commit", 20, mutable=False,
               validator=lambda v: v > 0)
    assert reg.get("pegasus.server", "rocksdb_block_cache_capacity") == 1024
    reg.set("pegasus.server", "rocksdb_block_cache_capacity", 2048)
    assert reg.get("pegasus.server", "rocksdb_block_cache_capacity") == 2048
    with pytest.raises(ValueError):
        reg.set("replication", "staleness_for_commit", 30)  # immutable

    ini = tmp_path / "config.ini"
    ini.write_text("[replication]\nstaleness_for_commit = 40\n")
    reg.load_ini(str(ini))
    assert reg.get("replication", "staleness_for_commit") == 40

    ini.write_text("[replication]\nstaleness_for_commit = -1\n")
    with pytest.raises(ValueError):
        reg.load_ini(str(ini))


def test_metrics_entities_and_percentile():
    reg = MetricRegistry()
    ent = reg.entity("replica", "1.2", {"table": "temp"})
    ent.counter("get_requests").increment(5)
    ent.gauge("sst_count").set(3)
    p = ent.percentile("get_latency_ns")
    for v in range(100):
        p.set(float(v))
    snap = reg.snapshot(entity_type="replica")
    assert len(snap) == 1
    m = snap[0]["metrics"]
    assert m["get_requests"]["value"] == 5
    assert m["sst_count"]["value"] == 3
    assert m["get_latency_ns"]["p50"] == pytest.approx(50.0, abs=2)
    assert reg.snapshot(entity_type="table") == []


def test_volatile_counter_resets():
    reg = MetricRegistry()
    c = reg.entity("server", "s1").volatile_counter("qps")
    c.increment(10)
    assert c.fetch_and_reset() == 10
    assert c.value() == 0


def test_fail_point_lifecycle():
    assert fail_point("replica::on_write") is None  # disabled: zero effect
    FAIL_POINTS.setup()
    try:
        FAIL_POINTS.cfg("replica::on_write", "return(ERR_TIMEOUT)")
        assert fail_point("replica::on_write") == "ERR_TIMEOUT"
        FAIL_POINTS.cfg("replica::on_write", "off")
        assert fail_point("replica::on_write") is None
        FAIL_POINTS.cfg("boom", "raise(injected)")
        with pytest.raises(RuntimeError):
            fail_point("boom")
    finally:
        FAIL_POINTS.teardown()
    assert fail_point("boom") is None


def test_token_bucket():
    tb = TokenBucket(rate=1000, burst=10)
    assert all(tb.try_consume() for _ in range(10))
    # bucket drained; refill is 1 token/ms
    ok = tb.try_consume(10)
    assert not ok
    delay = tb.consume_or_delay(5)
    assert delay > 0


def test_parse_throttle_env():
    d, r = parse_throttle_env("2000*delay*100")
    assert d is not None and d.rate == 2000 and r is None
    d, r = parse_throttle_env("1000*delay*50,2000*reject*10")
    assert d.rate == 1000 and r.rate == 2000
    d, r = parse_throttle_env("100K")
    assert d.rate == 100_000
    assert parse_throttle_env("") == (None, None)


def test_error_codes():
    err = PegasusError(ErrorCode.ERR_TIMEOUT, "rpc timed out")
    assert err.code == ErrorCode.ERR_TIMEOUT
    assert "ERR_TIMEOUT" in str(err)
    assert StorageStatus.OK == 0 and StorageStatus.NOT_FOUND == 1
