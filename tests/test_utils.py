"""Utility-layer tests: flags, metrics, fail points, token bucket."""

import time

import pytest

from pegasus_tpu.utils.errors import ErrorCode, PegasusError, StorageStatus
from pegasus_tpu.utils.fail_point import FAIL_POINTS, fail_point
from pegasus_tpu.utils.flags import FlagRegistry
from pegasus_tpu.utils.metrics import MetricRegistry
from pegasus_tpu.utils.token_bucket import TokenBucket, parse_throttle_env


def test_flags_define_get_set(tmp_path):
    reg = FlagRegistry()
    reg.define("pegasus.server", "rocksdb_block_cache_capacity", 1024,
               mutable=True)
    reg.define("replication", "staleness_for_commit", 20, mutable=False,
               validator=lambda v: v > 0)
    assert reg.get("pegasus.server", "rocksdb_block_cache_capacity") == 1024
    reg.set("pegasus.server", "rocksdb_block_cache_capacity", 2048)
    assert reg.get("pegasus.server", "rocksdb_block_cache_capacity") == 2048
    with pytest.raises(ValueError):
        reg.set("replication", "staleness_for_commit", 30)  # immutable

    ini = tmp_path / "config.ini"
    ini.write_text("[replication]\nstaleness_for_commit = 40\n")
    reg.load_ini(str(ini))
    assert reg.get("replication", "staleness_for_commit") == 40

    ini.write_text("[replication]\nstaleness_for_commit = -1\n")
    with pytest.raises(ValueError):
        reg.load_ini(str(ini))


def test_metrics_entities_and_percentile():
    reg = MetricRegistry()
    ent = reg.entity("replica", "1.2", {"table": "temp"})
    ent.counter("get_requests").increment(5)
    ent.gauge("sst_count").set(3)
    p = ent.percentile("get_latency_ns")
    for v in range(100):
        p.set(float(v))
    snap = reg.snapshot(entity_type="replica")
    assert len(snap) == 1
    m = snap[0]["metrics"]
    assert m["get_requests"]["value"] == 5
    assert m["sst_count"]["value"] == 3
    assert m["get_latency_ns"]["p50"] == pytest.approx(50.0, abs=2)
    assert reg.snapshot(entity_type="table") == []


def test_volatile_counter_legacy_shim_still_reads_deltas():
    reg = MetricRegistry()
    c = reg.entity("server", "s1").volatile_counter("qps")
    c.increment(10)
    # the deprecated reset-on-read surface keeps its delta semantics
    # through one implicit shared cursor...
    assert c.fetch_and_reset() == 10
    assert c.fetch_and_reset() == 0
    c.increment(3)
    assert c.fetch_and_reset() == 3
    # ...but the stored value is now CUMULATIVE: nothing resets under
    # other readers, and snapshots report the sum
    assert c.value() == 13
    assert c.snapshot() == {"type": "volatile_counter", "value": 13}


def test_volatile_counter_concurrent_readers_each_see_full_sum():
    """The multi-reader race regression: the recorder, the collector,
    and /metrics used to steal each other's deltas through
    reset-on-read. With per-reader cursors, two interleaved readers
    each observe the complete sum."""
    import threading

    reg = MetricRegistry()
    c = reg.entity("server", "s1").volatile_counter("ops")
    totals = {"a": 0, "b": 0}
    stop = threading.Event()

    def reader(rid):
        while not stop.is_set():
            totals[rid] += c.delta_since(rid)
        totals[rid] += c.delta_since(rid)

    threads = [threading.Thread(target=reader, args=(rid,))
               for rid in totals]
    for t in threads:
        t.start()
    n = 20_000
    for _ in range(n):
        c.increment()
    stop.set()
    for t in threads:
        t.join()
    assert totals["a"] == n
    assert totals["b"] == n
    assert c.value() == n


def test_fail_point_lifecycle():
    assert fail_point("replica::on_write") is None  # disabled: zero effect
    FAIL_POINTS.setup()
    try:
        FAIL_POINTS.cfg("replica::on_write", "return(ERR_TIMEOUT)")
        assert fail_point("replica::on_write") == "ERR_TIMEOUT"
        FAIL_POINTS.cfg("replica::on_write", "off")
        assert fail_point("replica::on_write") is None
        FAIL_POINTS.cfg("boom", "raise(injected)")
        with pytest.raises(RuntimeError):
            fail_point("boom")
    finally:
        FAIL_POINTS.teardown()
    assert fail_point("boom") is None


def test_fail_point_probabilistic_actions():
    """The reference's '<N>%action(...)' frequency syntax, backed by the
    registry's seeded RNG (fail_point.h's probabilistic fail points)."""
    FAIL_POINTS.setup()
    try:
        FAIL_POINTS.seed(42)
        FAIL_POINTS.cfg("p::ret", "30%return(shed)")
        hits = sum(1 for _ in range(2000)
                   if fail_point("p::ret") is not None)
        assert 480 < hits < 720  # ~30% of 2000, generous bounds
        # reproducible: the same seed replays the same decision stream
        FAIL_POINTS.seed(42)
        first = [fail_point("p::ret") for _ in range(50)]
        FAIL_POINTS.seed(42)
        assert [fail_point("p::ret") for _ in range(50)] == first
        # probabilistic raise: fires sometimes, not always
        FAIL_POINTS.cfg("p::raise", "50%raise(boom)")
        raised = 0
        for _ in range(200):
            try:
                fail_point("p::raise")
            except RuntimeError:
                raised += 1
        assert 50 < raised < 150
        # 100%-equivalent prefix behaves like the plain action
        FAIL_POINTS.cfg("p::always", "100%return(x)")
        assert all(fail_point("p::always") == "x" for _ in range(10))
        # probabilistic delay: a miss is a no-op, a hit sleeps; either
        # way the injected value stays None (delay never returns one)
        FAIL_POINTS.cfg("p::delay", "50%delay(1)")
        assert all(fail_point("p::delay") is None for _ in range(20))
    finally:
        FAIL_POINTS.teardown()


def test_backoff_jitter_bounds_and_determinism():
    from pegasus_tpu.utils.backoff import Backoff

    slept = []
    b = Backoff(base_ms=20, max_ms=1000, seed=7,
                sleep=lambda s: slept.append(s))
    for attempt in range(1, 12):
        d = b.sleep(attempt)
        ceiling = min(1.0, 0.020 * 2 ** (attempt - 1))
        # full-jitter window: [ceiling/2, ceiling] — never zero (a zero
        # sleep is the busy-spin this exists to kill), never past cap
        assert ceiling / 2 <= d <= ceiling, (attempt, d)
    assert slept == b.slept and len(slept) == 11
    # deterministic from the seed
    b2 = Backoff(base_ms=20, max_ms=1000, seed=7, sleep=lambda s: None)
    assert [b2.delay(a) for a in range(1, 12)] != \
        [b2.delay(a) for a in range(1, 12)]  # jitter varies per draw
    b3 = Backoff(base_ms=20, max_ms=1000, seed=7, sleep=lambda s: None)
    b4 = Backoff(base_ms=20, max_ms=1000, seed=7, sleep=lambda s: None)
    assert [b3.delay(a) for a in range(1, 12)] == \
        [b4.delay(a) for a in range(1, 12)]


def test_token_bucket():
    tb = TokenBucket(rate=1000, burst=10)
    assert all(tb.try_consume() for _ in range(10))
    # bucket drained; refill is 1 token/ms
    ok = tb.try_consume(10)
    assert not ok
    delay = tb.consume_or_delay(5)
    assert delay > 0


def test_parse_throttle_env():
    d, r = parse_throttle_env("2000*delay*100")
    assert d is not None and d.rate == 2000 and r is None
    d, r = parse_throttle_env("1000*delay*50,2000*reject*10")
    assert d.rate == 1000 and r.rate == 2000
    d, r = parse_throttle_env("100K")
    assert d.rate == 100_000
    assert parse_throttle_env("") == (None, None)


def test_error_codes():
    err = PegasusError(ErrorCode.ERR_TIMEOUT, "rpc timed out")
    assert err.code == ErrorCode.ERR_TIMEOUT
    assert "ERR_TIMEOUT" in str(err)
    assert StorageStatus.OK == 0 and StorageStatus.NOT_FOUND == 1


def test_latency_tracer_stage_chain():
    from pegasus_tpu.utils.latency_tracer import LatencyTracer, SlowQueryLog

    clock_v = [0.0]
    tr = LatencyTracer("write.1.0.d7", clock=lambda: clock_v[0])
    clock_v[0] = 0.002
    tr.add_point("prepare_local")
    clock_v[0] = 0.010
    tr.add_point("committed")
    rep = tr.report()
    assert rep["total_ms"] == 10.0
    assert [s["stage"] for s in rep["stages"]] == ["prepare_local",
                                                   "committed"]
    assert rep["stages"][1]["delta_ms"] == 8.0

    log = SlowQueryLog(threshold_ms=5.0, capacity=2)
    assert log.observe(tr)
    fast = LatencyTracer("fast", clock=lambda: clock_v[0])
    assert not log.observe(fast)
    # capacity bounds the ring
    log.observe_simple("a", 50)
    log.observe_simple("b", 60)
    dump = log.dump()
    assert len(dump) == 2 and dump[-1]["name"] == "b"


def test_command_manager_verbs():
    import pytest

    from pegasus_tpu.utils.command_manager import CommandManager

    mgr = CommandManager()
    mgr.register("echo", lambda args: list(args), "echo args")
    assert mgr.call("echo", ["a", "b"]) == ["a", "b"]
    assert "echo" in mgr.call("help", [])
    with pytest.raises(KeyError):
        mgr.call("nope", [])
    with pytest.raises(ValueError):
        mgr.register("echo", lambda a: a)


def test_slow_write_traces_recorded(tmp_path):
    """The replicated write path records stage chains for slow mutations
    and the node's remote command dumps them."""
    from pegasus_tpu.tools.cluster import SimCluster

    cluster = SimCluster(str(tmp_path / "c"), n_nodes=2)
    try:
        cluster.create_table("tr", partition_count=2, replica_count=2)
        c = cluster.client("tr")
        # force every write to be "slow" by lowering the threshold
        for stub in cluster.stubs.values():
            for r in stub.replicas.values():
                r.slow_log.threshold_ms = 0.0
        assert c.set(b"k", b"s", b"v") == 0
        cluster.step()
        dumps = []
        for stub in cluster.stubs.values():
            dumps += stub.commands.call("slow-query-dump", [])
        assert dumps, "no slow-write trace recorded"
        stages = [st["stage"] for st in dumps[0]["stages"]]
        assert "append_plog" in stages and "replied" in stages
    finally:
        cluster.close()
