"""Native C++ packer: bit-equivalence with the Python/numpy paths."""

import numpy as np
import pytest

from pegasus_tpu import native
from pegasus_tpu.base.crc import crc64
from pegasus_tpu.base.key_schema import generate_key, key_hash

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def test_native_crc64_matches_reference_golden():
    assert native.crc64_native(b"hashkey_123") == 0x345456810DAFB9C5
    assert native.crc64_native(b"") == 0
    assert native.crc64_native(b"pegasus") == crc64(b"pegasus")


def test_pack_records_matches_python_packer():
    rng = np.random.default_rng(5)
    keys = []
    for i in range(200):
        hk = bytes(rng.integers(97, 123, size=rng.integers(1, 12),
                                dtype=np.uint8))
        sk = bytes(rng.integers(97, 123, size=rng.integers(0, 20),
                                dtype=np.uint8))
        keys.append(generate_key(hk, sk))
    keys.append(generate_key(b"", b"sortonly"))  # empty-hashkey fallback
    packed = native.pack_records(keys, 64)
    assert packed is not None
    arr, key_len, hkl, hash_lo, valid = packed
    for i, k in enumerate(keys):
        assert arr[i, :len(k)].tobytes() == k
        assert not arr[i, len(k):].any()
        assert key_len[i] == len(k)
        assert hkl[i] == int.from_bytes(k[:2], "big")
        assert int(hash_lo[i]) == (key_hash(k) & 0xFFFFFFFF), i
        assert valid[i]


def test_pack_rejects_overwide_key():
    assert native.pack_records([b"\x00\x01" + b"x" * 100], 32) is None


def test_pack_malformed_header_marked_invalid():
    # header claims 255 hashkey bytes but the body has none: the packer
    # must mark the row invalid without reading past the key
    packed = native.pack_records([b"\x00\xff", b"\x01"], 32)
    arr, key_len, hkl, hash_lo, valid = packed
    assert not valid[0] and hkl[0] == 0 and hash_lo[0] == 0
    assert not valid[1]  # 1-byte key: too short
    # the Python fallback gives the same contract
    from pegasus_tpu.ops.record_block import build_record_block
    from pegasus_tpu import native as nat
    orig = nat.available
    nat.available = lambda: False
    try:
        block = build_record_block([b"\x00\xff", b"\x01"], [0, 0])
        assert not block.valid[0] and not block.valid[1]
        assert block.hashkey_len[0] == 0
    finally:
        nat.available = orig


def test_build_record_block_uses_native_hash():
    from pegasus_tpu.ops.record_block import build_record_block
    keys = [generate_key(b"user_%d" % i, b"s") for i in range(10)]
    block = build_record_block(keys, [0] * 10, capacity=16)
    assert block.hash_lo is not None
    for i, k in enumerate(keys):
        assert int(block.hash_lo[i]) == (key_hash(k) & 0xFFFFFFFF)
    assert not block.valid[10:].any()
