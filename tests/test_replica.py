"""PacificA replication tests under the deterministic simulator.

Modeled on the reference's simple_kv .act harness (SURVEY §4.2): a whole
replica group runs in one process over SimLoop/SimNetwork with seeded
delays, so every schedule replays exactly from its seed.
"""

import os

import pytest

from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.replica import (
    Mutation,
    MutationLog,
    PartitionStatus,
    PrepareList,
    Replica,
    ReplicaConfig,
    WriteOp,
)
from pegasus_tpu.replica.prepare_list import (
    COMMIT_ALL_READY,
    COMMIT_TO_DECREE_HARD,
)
from pegasus_tpu.rpc.codec import OP_INCR, OP_PUT, OP_REMOVE
from pegasus_tpu.runtime import SimLoop, SimNetwork
from pegasus_tpu.server.types import IncrRequest
from pegasus_tpu.utils.errors import StorageStatus


def k(h, s=""):
    return generate_key(h if isinstance(h, bytes) else h.encode(),
                        s if isinstance(s, bytes) else s.encode())


def put_op(hk, sk, value, ets=0):
    return WriteOp(OP_PUT, (k(hk, sk), value, ets))


class Cluster:
    """Test control plane: wires N replicas over a SimNetwork and plays
    the meta role (config assignment, learner upgrades)."""

    def __init__(self, tmp_path, names=("r1", "r2", "r3"), seed=0):
        self.loop = SimLoop(seed=seed)
        self.net = SimNetwork(self.loop)
        self.replicas = {}
        for name in names:
            r = Replica(name, str(tmp_path / name), self.net,
                        clock=lambda: 1_700_000_000 + self.loop.now)
            self.net.register(name, r.on_message)
            self.replicas[name] = r
        self.ballot = 1
        self.config = ReplicaConfig(self.ballot, names[0],
                                    list(names[1:]))
        for r in self.replicas.values():
            r.assign_config(self.config)

    @property
    def primary(self):
        return self.replicas[self.config.primary]

    def reconfigure(self, primary, secondaries):
        self.ballot += 1
        self.config = ReplicaConfig(self.ballot, primary, list(secondaries))
        for r in self.replicas.values():
            r.assign_config(self.config)

    def write(self, ops, callback=None):
        decree = self.primary.client_write(ops, callback)
        self.loop.run_until_idle()
        return decree

    def close(self):
        for r in self.replicas.values():
            r.close()


# ---- unit: prepare list / mutation codec ------------------------------


def test_mutation_codec_roundtrip():
    mu = Mutation(ballot=3, decree=17, last_committed=16,
                  timestamp_us=123456789,
                  ops=[put_op("h", "s", b"v", 99),
                       WriteOp(OP_REMOVE, (k("h", "x"),)),
                       WriteOp(OP_INCR, IncrRequest(k("h", "c"), 5, -1))])
    mu2 = Mutation.decode(mu.encode())
    assert mu2.ballot == 3 and mu2.decree == 17 and mu2.last_committed == 16
    assert len(mu2.ops) == 3
    assert mu2.ops[0].request == (k("h", "s"), b"v", 99)
    assert mu2.ops[2].request.increment == 5
    assert mu2.ops[2].request.expire_ts_seconds == -1


def test_prepare_list_commit_modes():
    committed = []
    pl = PrepareList(0, 16, committed.append)
    mus = [Mutation(1, d, d - 1, 0, []) for d in range(1, 5)]
    for mu in mus:
        pl.prepare(mu)
    # ALL_READY commits only the contiguous acked prefix
    pl.mark_ready(2)
    assert pl.commit(2, COMMIT_ALL_READY) == 0  # decree 1 not ready
    pl.mark_ready(1)
    assert pl.commit(1, COMMIT_ALL_READY) == 2  # 1 then 2
    assert pl.last_committed_decree == 2
    # HARD commit advances through prepared decrees
    assert pl.commit(4, COMMIT_TO_DECREE_HARD) == 2
    # gap -> fatal
    pl.prepare(Mutation(1, 7, 4, 0, []))
    with pytest.raises(RuntimeError):
        pl.commit(7, COMMIT_TO_DECREE_HARD)


def test_prepare_list_higher_ballot_wins():
    pl = PrepareList(0, 16, lambda mu: None)
    pl.prepare(Mutation(2, 1, 0, 0, [put_op("h", "a", b"new")]))
    pl.prepare(Mutation(1, 1, 0, 0, [put_op("h", "a", b"old")]))
    assert pl.get_mutation_by_decree(1).ballot == 2


def test_mutation_log_replay_and_gc(tmp_path):
    path = str(tmp_path / "plog" / "m.bin")
    log = MutationLog(path)
    for d in range(1, 6):
        log.append(Mutation(1, d, d - 1, 0, [put_op("h", "s%d" % d, b"v")]))
    log.close()
    log2 = MutationLog(path)
    assert log2.max_decree == 5
    assert [mu.decree for mu in log2.read_range(3)] == [3, 4, 5]
    log2.gc(3)
    assert [mu.decree for mu in log2.read_range(1)] == [4, 5]
    log2.close()


# ---- group: 2PC over the simulator ------------------------------------


def test_three_replica_commit_flow(tmp_path):
    c = Cluster(tmp_path)
    try:
        results = []
        c.write([put_op("u", "s1", b"v1")], results.append)
        assert results and results[0] == [0]
        # primary committed
        assert c.primary.last_committed_decree == 1
        # secondaries committed via piggy-back on the NEXT prepare
        c.write([put_op("u", "s2", b"v2")])
        for name in ("r2", "r3"):
            assert c.replicas[name].last_committed_decree >= 1
        # group check pushes the final commit point everywhere
        c.primary.broadcast_group_check()
        c.loop.run_until_idle()
        for r in c.replicas.values():
            assert r.last_committed_decree == 2
            assert r.server.on_get(k("u", "s1")) == (0, b"v1")
            assert r.server.on_get(k("u", "s2")) == (0, b"v2")
    finally:
        c.close()


def test_batched_and_atomic_mutations(tmp_path):
    c = Cluster(tmp_path)
    try:
        c.write([put_op("u", "a", b"1"), put_op("u", "b", b"2"),
                 WriteOp(OP_REMOVE, (k("u", "a"),))])
        results = []
        c.write([WriteOp(OP_INCR, IncrRequest(k("u", "cnt"), 42))],
                results.append)
        assert results[0][0].new_value == 42
        c.primary.broadcast_group_check()
        c.loop.run_until_idle()
        for r in c.replicas.values():
            assert r.server.on_get(k("u", "a"))[0] == 1  # removed
            assert r.server.on_get(k("u", "b")) == (0, b"2")
            assert r.server.on_get(k("u", "cnt")) == (0, b"42")
        # atomic ops may not batch
        with pytest.raises(ValueError):
            c.primary.client_write([
                WriteOp(OP_INCR, IncrRequest(k("u", "c"), 1)),
                put_op("u", "d", b"x")])
    finally:
        c.close()


def test_value_bytes_identical_across_replicas(tmp_path):
    # timetag determinism: every replica must store identical value bytes
    c = Cluster(tmp_path)
    try:
        c.write([put_op("u", "s", b"payload")])
        c.primary.broadcast_group_check()
        c.loop.run_until_idle()
        raws = [r.server.engine.get(k("u", "s"))[0]
                for r in c.replicas.values()]
        assert raws[0] == raws[1] == raws[2]
    finally:
        c.close()


def test_failover_promote_secondary(tmp_path):
    c = Cluster(tmp_path)
    try:
        for i in range(5):
            c.write([put_op("u", "s%d" % i, b"v%d" % i)])
        c.primary.broadcast_group_check()
        c.loop.run_until_idle()
        # primary dies; meta promotes r2 with ballot+1
        c.net.partition("r1")
        c.reconfigure("r2", ["r3"])
        c.loop.run_until_idle()
        assert c.replicas["r2"].status == PartitionStatus.PRIMARY
        assert c.replicas["r2"].ballot == 2
        # writes continue through the new primary
        c.write([put_op("u", "after", b"failover")])
        c.replicas["r2"].broadcast_group_check()
        c.loop.run_until_idle()
        assert c.replicas["r3"].server.on_get(k("u", "after")) == (
            0, b"failover")
        # old data intact
        assert c.replicas["r2"].server.on_get(k("u", "s3")) == (0, b"v3")
    finally:
        c.close()


def test_new_primary_repropose_uncommitted_window(tmp_path):
    c = Cluster(tmp_path)
    try:
        # drop all acks from secondaries -> primary can't commit
        c.net.set_drop(1.0, src="r2", dst="r1")
        c.net.set_drop(1.0, src="r3", dst="r1")
        c.write([put_op("u", "s", b"v")])
        assert c.primary.last_committed_decree == 0  # stuck
        assert c.replicas["r2"].last_prepared_decree() == 1
        # old primary dies; r2 promoted; its re-propose commits the window
        c.net.partition("r1")
        c.reconfigure("r2", ["r3"])
        c.loop.run_until_idle()
        assert c.replicas["r2"].last_committed_decree == 1
        assert c.replicas["r2"].server.on_get(k("u", "s")) == (0, b"v")
    finally:
        c.close()


def test_learner_catchup_via_log(tmp_path):
    c = Cluster(tmp_path, names=("r1", "r2"))
    try:
        c.reconfigure("r1", ["r2"])
        for i in range(8):
            c.write([put_op("u", "s%d" % i, b"v%d" % i)])
        # r4 joins empty
        r4 = Replica("r4", str(tmp_path / "r4"), c.net,
                     clock=lambda: 1_700_000_000 + c.loop.now)
        c.net.register("r4", r4.on_message)
        c.replicas["r4"] = r4
        upgraded = []
        c.primary.on_learn_completed = upgraded.append
        c.primary.add_learner("r4")
        c.loop.run_until_idle()
        assert upgraded == ["r4"]
        # meta upgrades to secondary
        c.reconfigure("r1", ["r2", "r4"])
        c.write([put_op("u", "after", b"learn")])
        c.primary.broadcast_group_check()
        c.loop.run_until_idle()
        assert r4.status == PartitionStatus.SECONDARY
        assert r4.server.on_get(k("u", "s5")) == (0, b"v5")
        assert r4.server.on_get(k("u", "after")) == (0, b"learn")
    finally:
        c.close()


def test_learner_catchup_via_checkpoint(tmp_path):
    c = Cluster(tmp_path, names=("r1", "r2"))
    try:
        c.reconfigure("r1", ["r2"])
        for i in range(10):
            c.write([put_op("u", "s%02d" % i, b"v%d" % i)])
        # flush + GC the primary's log: the early decrees now live only in
        # storage -> learner must take the LT_APP path
        c.primary.flush_and_gc_log()
        assert c.primary.log.read_range(1) == []
        for i in range(10, 14):
            c.write([put_op("u", "s%02d" % i, b"v%d" % i)])
        r4 = Replica("r4", str(tmp_path / "r4"), c.net,
                     clock=lambda: 1_700_000_000 + c.loop.now)
        c.net.register("r4", r4.on_message)
        c.replicas["r4"] = r4
        c.primary.add_learner("r4")
        c.loop.run_until_idle()
        c.reconfigure("r1", ["r2", "r4"])
        c.write([put_op("u", "after", b"ckpt")])
        c.primary.broadcast_group_check()
        c.loop.run_until_idle()
        for i in range(14):
            assert r4.server.on_get(k("u", "s%02d" % i)) == (
                0, b"v%d" % i), i
        assert r4.server.on_get(k("u", "after")) == (0, b"ckpt")
    finally:
        c.close()


def test_secondary_gap_detected_and_reported(tmp_path):
    c = Cluster(tmp_path)
    try:
        errors = []
        c.primary.on_replication_error = lambda src, d: errors.append(src)
        # r3 misses decree 1 (dropped prepare)
        c.net.set_drop(1.0, src="r1", dst="r3")
        c.write([put_op("u", "s1", b"v1")])
        c.net.set_drop(0.0, src="r1", dst="r3")
        # decree 2 arrives at r3 -> gap detected -> error ack
        c.write([put_op("u", "s2", b"v2")])
        assert errors == ["r3"]
        # meta removes r3; the stuck decrees commit with the smaller group
        c.reconfigure("r1", ["r2"])
        c.loop.run_until_idle()
        assert c.primary.last_committed_decree == 2
    finally:
        c.close()


def test_replica_restart_recovers_from_log(tmp_path):
    c = Cluster(tmp_path, names=("r1", "r2"))
    try:
        c.reconfigure("r1", ["r2"])
        for i in range(6):
            c.write([put_op("u", "s%d" % i, b"v%d" % i)])
        c.primary.broadcast_group_check()
        c.loop.run_until_idle()
        lc = c.replicas["r2"].last_committed_decree
        c.replicas["r2"].close()
        # restart r2 from disk
        r2 = Replica("r2", str(tmp_path / "r2"), c.net,
                     clock=lambda: 1_700_000_000 + c.loop.now)
        c.net.register("r2", r2.on_message)
        c.replicas["r2"] = r2
        assert r2.last_committed_decree == lc
        r2.assign_config(c.config)
        c.write([put_op("u", "post", b"restart")])
        c.primary.broadcast_group_check()
        c.loop.run_until_idle()
        assert r2.server.on_get(k("u", "post")) == (0, b"restart")
        assert r2.server.on_get(k("u", "s2")) == (0, b"v2")
    finally:
        c.close()


def test_deposed_primary_cannot_commit_divergent_content(tmp_path):
    # regression (safety): a ballot-1 prepare arriving where a ballot-2
    # mutation for the same decree is stored must NOT get an OK ack
    c = Cluster(tmp_path)
    try:
        c.write([put_op("u", "s0", b"v0")])
        # r2 promoted with ballot 2 but r1 doesn't know (no config update
        # delivered to r1) and keeps writing
        c.replicas["r2"].assign_config(ReplicaConfig(2, "r2", ["r3"]))
        c.replicas["r3"].assign_config(ReplicaConfig(2, "r2", ["r3"]))
        c.loop.run_until_idle()
        c.replicas["r2"].client_write([put_op("u", "key", b"NEW")])
        c.loop.run_until_idle()
        # old primary r1 (ballot 1) tries the same decree with other content
        r1 = c.replicas["r1"]
        before = r1.last_committed_decree
        r1.client_write([put_op("u", "key", b"OLD")])
        c.loop.run_until_idle()
        # r1 must not have committed its divergent decree
        assert r1.last_committed_decree == before
        c.replicas["r2"].broadcast_group_check()
        c.loop.run_until_idle()
        assert c.replicas["r3"].server.on_get(k("u", "key")) == (0, b"NEW")
    finally:
        c.close()


def test_lost_ack_recovered_by_group_check(tmp_path):
    # regression (liveness): a dropped prepare_ack must not stall commits
    # forever — the group-check resend path recovers it
    c = Cluster(tmp_path)
    try:
        c.net.set_drop(1.0, src="r2", dst="r1")  # r2's acks vanish
        c.write([put_op("u", "s", b"v")])
        assert c.primary.last_committed_decree == 0  # stuck
        c.net.set_drop(0.0, src="r2", dst="r1")
        c.primary.broadcast_group_check()  # re-sends pending prepares
        c.loop.run_until_idle()
        assert c.primary.last_committed_decree == 1
    finally:
        c.close()


def test_learner_tolerates_prepare_before_learn_completes(tmp_path):
    # regression: a prepare racing ahead of the learn_response must not
    # trigger a false gap error on the mid-learn learner
    c = Cluster(tmp_path, names=("r1", "r2"))
    try:
        c.reconfigure("r1", ["r2"])
        for i in range(4):
            c.write([put_op("u", "s%d" % i, b"v%d" % i)])
        r4 = Replica("r4", str(tmp_path / "r4"), c.net,
                     clock=lambda: 1_700_000_000 + c.loop.now)
        c.net.register("r4", r4.on_message)
        c.replicas["r4"] = r4
        errors = []
        c.primary.on_replication_error = lambda s, d: errors.append(s)
        c.primary.add_learner("r4")
        # write immediately — the prepare for decree 5 races the learn
        c.primary.client_write([put_op("u", "race", b"x")])
        c.loop.run_until_idle()
        assert errors == []
        c.reconfigure("r1", ["r2", "r4"])
        c.write([put_op("u", "final", b"y")])
        c.primary.broadcast_group_check()
        c.loop.run_until_idle()
        assert r4.server.on_get(k("u", "race")) == (0, b"x")
        assert r4.server.on_get(k("u", "s2")) == (0, b"v2")
    finally:
        c.close()


def test_deterministic_schedules_replay_identically(tmp_path):
    # same seed -> identical delivery counts and commit points; different
    # seed -> (almost surely) different schedule but same final state
    import shutil

    def run(seed, path):
        c = Cluster(path, seed=seed)
        try:
            for i in range(5):
                c.write([put_op("u", "s%d" % i, b"v%d" % i)])
            c.primary.broadcast_group_check()
            c.loop.run_until_idle()
            return (c.net.delivered, c.loop.now,
                    [r.last_committed_decree
                     for r in c.replicas.values()])
        finally:
            c.close()

    a = run(42, tmp_path / "a")
    b = run(42, tmp_path / "b")
    assert a == b
    d = run(43, tmp_path / "c")
    assert d[2] == a[2]  # same outcome
    assert d[1] != a[1]  # different schedule timing


def test_write_queue_batches_behind_inflight_window(tmp_path):
    """Mutation-queue parity: once the 2PC window is at pipelining depth,
    further batchable writes coalesce into ONE following mutation, each
    caller still receiving its own response."""
    c = Cluster(tmp_path)
    try:
        # freeze acks so the window fills: r3 never answers
        c.net.set_drop(1.0, src="r3", dst="r1")
        results = []
        for i in range(6):
            c.primary.client_write(
                [put_op("u", "s%d" % i, b"v%d" % i)],
                lambda r, i=i: results.append((i, r)))
        c.loop.run_until_idle()
        # depth-2 window in flight, the rest queued as one pending batch
        assert len(c.primary._pending_acks) == 2
        assert sum(n for n, _cb in c.primary._write_queue) == 4
        assert results == []  # nothing acked yet
        # heal: acks flow, the window drains, the batch ships and commits
        c.net.set_drop(0.0, src="r3", dst="r1")
        c.primary.broadcast_group_check()
        c.loop.run_until_idle()
        c.primary.broadcast_group_check()
        c.loop.run_until_idle()
        assert sorted(i for i, _r in results) == list(range(6))
        for i in range(6):
            err, v = c.primary.server.on_get(
                generate_key(b"u", b"s%d" % i))
            assert (err, v) == (0, b"v%d" % i)
    finally:
        c.close()
