"""User-specified compaction rules + app-env plumbing.

Parity targets: src/server/compaction_filter_rule.{h,cpp},
compaction_operation.{h,cpp}, and the replica_envs dynamic-settings
surface (deny client, throttling, default_ttl).
"""

import numpy as np
import pytest

from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.base.value_schema import PEGASUS_EPOCH_BEGIN
from pegasus_tpu.ops.compaction_rules import compile_rules, parse_rules
from pegasus_tpu.server import PartitionServer
from pegasus_tpu.utils.errors import StorageStatus

OK = int(StorageStatus.OK)
TRY_AGAIN = int(StorageStatus.TRY_AGAIN)


def k(h, s):
    return generate_key(h, s)


def test_delete_by_hashkey_prefix():
    f = compile_rules(
        '[{"op": "delete_key", "rules": '
        '[{"type": "hashkey_pattern", "match": "prefix", "pattern": "tmp_"}]}]')
    keys = [k(b"tmp_1", b"s"), k(b"keep", b"s"), k(b"tmp_2", b"x")]
    drop, ets = f(keys, [0, 0, 0], now=1000)
    assert list(drop) == [True, False, True]


def test_delete_requires_all_rules_match():
    # AND semantics: hashkey prefix AND sortkey postfix
    f = compile_rules([
        {"op": "delete_key", "rules": [
            {"type": "hashkey_pattern", "match": "prefix", "pattern": "u_"},
            {"type": "sortkey_pattern", "match": "postfix", "pattern": "_old"},
        ]}])
    keys = [k(b"u_1", b"a_old"), k(b"u_1", b"a_new"), k(b"x", b"a_old")]
    drop, _ = f(keys, [0, 0, 0], now=1000)
    assert list(drop) == [True, False, False]


def test_ttl_range_rule():
    now = 5000
    f = compile_rules([
        {"op": "delete_key", "rules": [
            {"type": "ttl_range", "start_ttl": 100, "stop_ttl": 200}]}])
    keys = [k(b"h", b"s%d" % i) for i in range(4)]
    # remaining TTLs: none, 150 (in range), 50 (below), 300 (above)
    ets = [0, now + 150, now + 50, now + 300]
    drop, _ = f(keys, ets, now=now)
    assert list(drop) == [False, True, False, False]
    # start=stop=0 matches exactly the no-TTL records
    f0 = compile_rules([
        {"op": "delete_key", "rules": [
            {"type": "ttl_range", "start_ttl": 0, "stop_ttl": 0}]}])
    drop0, _ = f0(keys, ets, now=now)
    assert list(drop0) == [True, False, False, False]


def test_update_ttl_ops():
    now = 10_000
    keys = [k(b"h", b"a"), k(b"h", b"b"), k(b"h", b"c")]
    # from_now
    f = compile_rules([
        {"op": "update_ttl", "update_ttl_type": "from_now", "value": 500,
         "rules": [{"type": "sortkey_pattern", "match": "prefix",
                    "pattern": "a"}]}])
    _, ets = f(keys, [7, 7, 7], now=now)
    assert list(ets) == [now + 500, 7, 7]
    # from_current: no-op on no-TTL records
    f2 = compile_rules([
        {"op": "update_ttl", "update_ttl_type": "from_current", "value": 100,
         "rules": [{"type": "hashkey_pattern", "match": "anywhere",
                    "pattern": "h"}]}])
    _, ets2 = f2(keys, [50, 0, 60], now=now)
    assert list(ets2) == [150, 0, 160]
    # timestamp: expire at unix ts value
    unix_target = PEGASUS_EPOCH_BEGIN + 999
    f3 = compile_rules([
        {"op": "update_ttl", "update_ttl_type": "timestamp",
         "value": unix_target,
         "rules": [{"type": "sortkey_pattern", "match": "prefix",
                    "pattern": "c"}]}])
    _, ets3 = f3(keys, [0, 0, 0], now=now)
    assert list(ets3) == [0, 0, 999]


def test_operation_order_delete_wins():
    f = compile_rules([
        {"op": "delete_key", "rules": [
            {"type": "sortkey_pattern", "match": "prefix", "pattern": "x"}]},
        {"op": "update_ttl", "update_ttl_type": "from_now", "value": 1,
         "rules": [{"type": "sortkey_pattern", "match": "prefix",
                    "pattern": "x"}]},
    ])
    drop, ets = f([k(b"h", b"x1")], [0], now=100)
    assert bool(drop[0]) and ets[0] == 0  # deleted, not re-stamped


def test_empty_pattern_matches_nothing():
    # regression (parity): reference string_pattern_match returns false
    # for empty patterns — an empty-pattern delete rule must not wipe data
    f = compile_rules([
        {"op": "delete_key", "rules": [
            {"type": "hashkey_pattern", "match": "anywhere", "pattern": ""}]}])
    drop, _ = f([k(b"h", b"s")], [0], now=100)
    assert not bool(drop[0])


def test_ops_evaluate_against_original_ttl():
    # regression (parity): op2's ttl_range must see the ORIGINAL expire_ts,
    # not op1's rewrite
    now = 1000
    f = compile_rules([
        {"op": "update_ttl", "update_ttl_type": "from_now", "value": 100,
         "rules": [{"type": "hashkey_pattern", "match": "prefix",
                    "pattern": "h"}]},
        {"op": "delete_key", "rules": [
            {"type": "ttl_range", "start_ttl": 50, "stop_ttl": 200}]},
    ])
    drop, ets = f([k(b"h", b"s")], [0], now=now)
    assert not bool(drop[0])          # original ets=0 never in ttl_range
    assert int(ets[0]) == now + 100   # but the update still applied


def test_bad_rule_specs_rejected():
    with pytest.raises(ValueError):
        parse_rules('[{"op": "delete_key", "rules": []}]')
    with pytest.raises(ValueError):
        parse_rules('[{"op": "explode", "rules": [{"type": "ttl_range", '
                    '"start_ttl": 0, "stop_ttl": 0}]}]')
    with pytest.raises(ValueError):
        parse_rules('[{"op": "delete_key", "rules": [{"type": "nope"}]}]')


def test_server_compaction_with_env_rules(tmp_path):
    s = PartitionServer(str(tmp_path / "p"))
    try:
        for i in range(10):
            s.on_put(k(b"logs", b"day%02d" % i), b"v")
            s.on_put(k(b"data", b"day%02d" % i), b"v")
        s.update_app_envs({"user_specified_compaction":
                           '[{"op": "delete_key", "rules": '
                           '[{"type": "hashkey_pattern", "match": "prefix", '
                           '"pattern": "logs"}]}]'})
        s.manual_compact()
        assert s.on_sortkey_count(b"logs") == (OK, 0)
        assert s.on_sortkey_count(b"data") == (OK, 10)
    finally:
        s.close()


def test_server_default_ttl_env(tmp_path):
    s = PartitionServer(str(tmp_path / "p"))
    try:
        s.on_put(k(b"h", b"s"), b"v")  # no TTL
        s.update_app_envs({"default_ttl": "100"})
        s.manual_compact()
        err, ttl = s.on_ttl(k(b"h", b"s"))
        assert err == OK and 0 < ttl <= 100
    finally:
        s.close()


def test_deny_client_and_throttle_envs(tmp_path):
    s = PartitionServer(str(tmp_path / "p"))
    try:
        s.on_put(k(b"h", b"s"), b"v")
        s.update_app_envs({"replica.deny_client_request": "reject*write"})
        assert s.on_put(k(b"h", b"s2"), b"v") == TRY_AGAIN
        assert s.on_get(k(b"h", b"s")) == (OK, b"v")  # reads still fine
        s.update_app_envs({"replica.deny_client_request": "reject*all"})
        assert s.on_get(k(b"h", b"s"))[0] == TRY_AGAIN
        s.update_app_envs({"replica.deny_client_request": ""})
        assert s.on_get(k(b"h", b"s")) == (OK, b"v")
        # tiny write-QPS budget: the burst runs out
        s.update_app_envs({"replica.write_throttling": "2*reject*0"})
        results = [s.on_put(k(b"h", b"t%d" % i), b"v") for i in range(10)]
        assert TRY_AGAIN in results and OK in results
    finally:
        s.close()
