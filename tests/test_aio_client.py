"""Async client facade (parity: client.h async_* API family)."""

import asyncio

from pegasus_tpu.client import PegasusClient, Table
from pegasus_tpu.client.aio import AsyncPegasusClient


def test_async_client_round_trip(tmp_path):
    t = Table(str(tmp_path / "t"), partition_count=4)
    ac = AsyncPegasusClient(PegasusClient(t))

    async def drive():
        errs = await ac.gather_set(
            [(b"hk%02d" % i, b"s", b"v%d" % i) for i in range(20)])
        assert errs == [0] * 20
        res = await ac.gather_get(
            [(b"hk%02d" % i, b"s") for i in range(20)])
        assert res == [(0, b"v%d" % i) for i in range(20)]
        err, val = await ac.get(b"hk07", b"s")
        assert (err, val) == (0, b"v7")
        assert await ac.exist(b"hk07", b"s")
        resp = await ac.incr(b"cnt", b"c", 5)
        assert (resp.error, resp.new_value) == (0, 5)
        await ac.multi_set(b"cart", {b"a": b"1", b"b": b"2"})
        err, kvs = await ac.multi_get(b"cart")
        assert err == 0 and dict(kvs) == {b"a": b"1", b"b": b"2"}
        rows = await ac.scan_all(b"cart")
        assert len(rows) == 2
        # concurrency really happens: many gets in flight at once
        many = await ac.gather_get(
            [(b"hk%02d" % (i % 20), b"s") for i in range(200)])
        assert len(many) == 200

    try:
        asyncio.run(drive())
    finally:
        ac.close()
        t.close()


def test_async_client_cluster_backend(tmp_path):
    from pegasus_tpu.tools.cluster import SimCluster

    cluster = SimCluster(str(tmp_path / "c"), n_nodes=2)
    try:
        cluster.create_table("t", partition_count=4)
        ac = AsyncPegasusClient(cluster.client("t"), max_workers=1)

        async def drive():
            assert await ac.set(b"h", b"s", b"v") == 0
            assert await ac.get(b"h", b"s") == (0, b"v")
        asyncio.run(drive())
        ac.close()
    finally:
        cluster.close()
