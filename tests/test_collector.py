"""Info-collector + availability-detector tests (parity:
src/server/info_collector.h:48, available_detector.h:49)."""

import pytest

from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.tools.collector import DETECT_TABLE, STAT_TABLE, InfoCollector


@pytest.fixture
def cluster(tmp_path):
    c = SimCluster(str(tmp_path / "c"), n_nodes=3)
    yield c
    c.close()


def make_collector(cluster):
    cluster.create_table(STAT_TABLE, partition_count=2)
    cluster.create_table(DETECT_TABLE, partition_count=2)
    return InfoCollector(cluster.net, "collector",
                         list(cluster.stubs), cluster.client, cluster.pump)


def test_collect_round_aggregates_and_persists(cluster):
    cluster.create_table("traffic", partition_count=4)
    c = cluster.client("traffic")
    for i in range(30):
        assert c.set(b"t%02d" % i, b"s", b"v" * 100) == 0
    for i in range(30):
        assert c.get(b"t%02d" % i, b"s")[0] == 0
    col = make_collector(cluster)
    per_table = col.collect_round()
    app_id = str(c.app_id)
    assert app_id in per_table
    assert per_table[app_id]["write_cu"] > 0
    assert per_table[app_id]["read_cu"] > 0
    assert per_table[app_id]["partitions"] >= 4
    # the row landed in the stat table (result_writer parity)
    history = col.table_history(app_id)
    assert history and history[-1]["write_cu"] == \
        per_table[app_id]["write_cu"]


def test_availability_probe_tracks_failures(cluster):
    col = make_collector(cluster)
    assert col.probe_round(probes=5) == 1.0
    # cut every node off: probes fail, availability drops below 1
    for name in list(cluster.stubs):
        cluster.kill(name)
    col._detect_client._max_retries = 1
    col._detect_client._pump_rounds = 3
    av = col.probe_round(probes=3)
    assert av < 1.0
    assert col.probe_total == 8 and col.probe_failed >= 3


def test_collect_dups_aggregates_per_table_lag_rows(cluster):
    """The collector's geo-replication surface: every node's dup.stats
    verb rolls up into one per-table row (worst lag, shipped/error
    totals) persisted as the `_dups` stat row."""
    import json

    from pegasus_tpu.utils.metrics import METRICS

    cluster.create_table("gm", partition_count=2)
    cluster.create_table("gf", partition_count=2)
    c = cluster.client("gm")
    for i in range(15):
        assert c.set(b"g%02d" % i, b"s", b"v%d" % i) == 0
    # duplication entity ids are node.app.pidx.dupid — other sim tests
    # in this process may have used colliding ids, so counter
    # assertions are DELTAS against this snapshot, never absolutes
    pre_skips = sum(ent["metrics"].get("dup_skip_count",
                                       {}).get("value", 0)
                    for ent in METRICS.snapshot("duplication"))
    cluster.meta.duplication.add_duplication("gm", "meta", "gf")
    cluster.step(rounds=6)
    col = make_collector(cluster)
    rows = col.collect_dups()
    app_id = str(c.app_id)
    assert app_id in rows, rows
    assert rows[app_id]["sessions"] >= 2  # one per partition
    assert rows[app_id]["shipped_bytes"] > 0
    assert rows[app_id]["max_lag_decrees"] == 0  # fully drained
    post_skips = sum(ent["metrics"].get("dup_skip_count",
                                        {}).get("value", 0)
                     for ent in METRICS.snapshot("duplication"))
    assert post_skips == pre_skips  # this dup abandoned nothing
    # the row rides collect_round into the stat table
    col.collect_round()
    err, kvs = col._stat_client.multi_get(b"_dups")
    assert err == 0 and kvs
    persisted = json.loads(sorted(kvs.items())[-1][1])
    assert persisted[app_id]["shipped_bytes"] > 0


def test_probe_round_healthy_then_partitioned_node_degrades(cluster):
    """The availability detector under SimCluster: a healthy cluster
    probes at 1.0; partitioning ONE node (not killing it — its
    partitions stay assigned until the FD cures them) degrades the
    fraction below 1.0; healing and re-probing raises it again."""
    col = make_collector(cluster)
    assert col.probe_round(probes=6) == 1.0
    assert col.probe_total == 6 and col.probe_failed == 0
    victim = next(iter(cluster.stubs))
    cluster.net.partition(victim)
    col._detect_client._max_retries = 1
    col._detect_client._pump_rounds = 3
    av = col.probe_round(probes=6)
    assert av < 1.0
    assert col.probe_failed >= 1
    cluster.net.heal(victim)
    cluster.step(rounds=2)
    col._detect_client._max_retries = 3
    col._detect_client._pump_rounds = 100
    assert col.probe_round(probes=6) > av


def test_collect_workload_row_aggregates_shape_stats(cluster):
    """The workload-profiler surface (PR 15): per-table op mix /
    batch-size / selectivity / hot-share roll up from the nodes'
    `workload` metric entities into one `_workload` stat row, with the
    node cost-model drift ratio alongside."""
    import json as _json

    cluster.create_table("wl", partition_count=4)
    c = cluster.client("wl")
    col = make_collector(cluster)
    # workload entity ids are app.pidx and the registry is process-
    # global, so (like the dup test above) counter assertions are
    # DELTAS against this snapshot, never absolutes (c.app_id resolves
    # lazily — read it after the first op)
    pre_rows = col.collect_workload().get("tables", {})
    for i in range(25):
        assert c.set(b"w%02d" % i, b"s", b"v" * 80) == 0
    for i in range(25):
        assert c.get(b"w%02d" % i, b"s")[0] == 0
    err, kvs = c.multi_get(b"w03")  # ranged leg feeds selectivity
    assert err == 0 and kvs
    app_id = str(c.app_id)
    pre = pre_rows.get(app_id, {})
    out = col.collect_workload()
    rows = out["tables"]
    assert app_id in rows, rows
    agg = rows[app_id]
    # entities dedupe by id across the scraped nodes: PARTITIONS, not
    # replicas (a per-node sum reported 12 partitions and ~3x ops for
    # this exact scenario — the read delta below would be 75), and the
    # 25 primary-served reads count exactly once. >= : another test in
    # this process may have registered same-app-id workload entities.
    assert 4 <= agg["partitions"] < 12
    assert agg["read_ops"] - pre.get("read_ops", 0) == 25
    # writes apply on secondaries too and the in-process sim shares
    # one registry (the known storage/rpc-singleton artifact), so the
    # floor — never an exact count — is what's assertable here
    assert agg["write_ops"] - pre.get("write_ops", 0) >= 25
    assert agg["scan_ops"] - pre.get("scan_ops", 0) >= 1
    assert agg["scan_selectivity_p50"] > 0.0
    assert agg["value_bytes_p99"] >= 80
    assert "drift_ratio" in out  # beside the tables, never among them
    # every tables value is a row dict (the sentinel-key regression)
    assert all(isinstance(v, dict) for v in rows.values())
    # the row rides collect_round into the stat table
    col.collect_round()
    err, kvs = col._stat_client.multi_get(b"_workload")
    assert err == 0 and kvs
    persisted = _json.loads(sorted(kvs.items())[-1][1])
    assert persisted["tables"][app_id]["read_ops"] > 0


def test_collect_round_persists_health_and_alert_rows(cluster):
    """The flight-recorder rows: `_health` lands per-node watchdog
    status in table history each round; `_alerts` appears once a node
    journals a typed event."""
    import json as _json

    from pegasus_tpu.utils.fail_point import FAIL_POINTS
    from pegasus_tpu.utils.flags import FLAGS

    cluster.create_table("traffic2", partition_count=2)
    c = cluster.client("traffic2")
    for i in range(10):
        assert c.set(b"h%02d" % i, b"s", b"v") == 0
    cluster.step(rounds=3)
    col = make_collector(cluster)
    col.collect_round()
    err, kvs = col._stat_client.multi_get(b"_health")
    assert err == 0 and kvs
    rows = _json.loads(sorted(kvs.items())[-1][1])
    assert set(rows) == set(cluster.stubs)
    for node, row in rows.items():
        assert row["status"] == "ok" and row["firing"] == []
        assert row["ring_bytes"] > 0
    # fire an incident on one node -> its `_alerts` row appears
    victim = "node0"
    FLAGS.set("pegasus.health", "recorder_interval_s", 1.0)
    FAIL_POINTS.setup()
    FAIL_POINTS.cfg(f"stub_read_shed:{victim}", "return(busy)")
    try:
        for _ in range(4):
            for i in range(10):
                try:
                    c.get(b"h%02d" % i, b"s")
                except Exception:  # noqa: BLE001 - shed IS the scenario
                    pass
            cluster.step()
        col.collect_round()
    finally:
        FAIL_POINTS.teardown()
        from pegasus_tpu.utils import health as health_mod

        health_mod.reset_capture()
        FLAGS.set("pegasus.health", "recorder_interval_s", 10.0)
        FLAGS.set("pegasus.tracing", "sample_ratio", 0.0)
    err, kvs = col._stat_client.multi_get(b"_health")
    assert err == 0
    rows = _json.loads(sorted(kvs.items())[-1][1])
    assert rows[victim]["status"] == "degraded"
    assert "read_shed_growth" in rows[victim]["firing"]
    err, kvs = col._stat_client.multi_get(b"_alerts")
    assert err == 0 and kvs
    alerts = _json.loads(sorted(kvs.items())[-1][1])
    assert any(ev["rule"] == "read_shed_growth" and ev["firing"]
               for ev in alerts.get(victim, []))
