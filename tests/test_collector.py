"""Info-collector + availability-detector tests (parity:
src/server/info_collector.h:48, available_detector.h:49)."""

import pytest

from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.tools.collector import DETECT_TABLE, STAT_TABLE, InfoCollector


@pytest.fixture
def cluster(tmp_path):
    c = SimCluster(str(tmp_path / "c"), n_nodes=3)
    yield c
    c.close()


def make_collector(cluster):
    cluster.create_table(STAT_TABLE, partition_count=2)
    cluster.create_table(DETECT_TABLE, partition_count=2)
    return InfoCollector(cluster.net, "collector",
                         list(cluster.stubs), cluster.client, cluster.pump)


def test_collect_round_aggregates_and_persists(cluster):
    cluster.create_table("traffic", partition_count=4)
    c = cluster.client("traffic")
    for i in range(30):
        assert c.set(b"t%02d" % i, b"s", b"v" * 100) == 0
    for i in range(30):
        assert c.get(b"t%02d" % i, b"s")[0] == 0
    col = make_collector(cluster)
    per_table = col.collect_round()
    app_id = str(c.app_id)
    assert app_id in per_table
    assert per_table[app_id]["write_cu"] > 0
    assert per_table[app_id]["read_cu"] > 0
    assert per_table[app_id]["partitions"] >= 4
    # the row landed in the stat table (result_writer parity)
    history = col.table_history(app_id)
    assert history and history[-1]["write_cu"] == \
        per_table[app_id]["write_cu"]


def test_availability_probe_tracks_failures(cluster):
    col = make_collector(cluster)
    assert col.probe_round(probes=5) == 1.0
    # cut every node off: probes fail, availability drops below 1
    for name in list(cluster.stubs):
        cluster.kill(name)
    col._detect_client._max_retries = 1
    col._detect_client._pump_rounds = 3
    av = col.probe_round(probes=3)
    assert av < 1.0
    assert col.probe_total == 8 and col.probe_failed >= 3
