"""Key schema tests (parity: src/base/pegasus_key_schema.h semantics)."""

import pytest

from pegasus_tpu.base.key_schema import (
    check_key_hash,
    generate_key,
    generate_next_bytes,
    hash_key_hash,
    key_hash,
    partition_index,
    restore_key,
)
from pegasus_tpu.base.crc import crc64


def test_roundtrip():
    for hk, sk in [(b"h", b"s"), (b"", b"sort"), (b"hash", b""), (b"", b""),
                   (b"x" * 300, b"y" * 500)]:
        key = generate_key(hk, sk)
        assert key[:2] == len(hk).to_bytes(2, "big")
        assert restore_key(key) == (hk, sk)


def test_too_long_hashkey_rejected():
    with pytest.raises(ValueError):
        generate_key(b"x" * 0xFFFF, b"")


def test_next_bytes_ordering():
    # next(hash_key) must be > every key with that hashkey, and
    # <= the encoding of any later hashkey.
    hk = b"user1"
    upper = generate_next_bytes(hk)
    for sk in [b"", b"a", b"\xff\xff\xff", b"zzzz"]:
        assert generate_key(hk, sk) < upper
    assert upper <= generate_key(b"user2", b"")


def test_next_bytes_strips_trailing_ff():
    hk = b"ab\xff"
    upper = generate_next_bytes(hk)
    # trailing 0xFF must be stripped and the previous byte incremented
    assert not upper.endswith(b"\xff")
    assert generate_key(hk, b"\xff" * 5) < upper


def test_next_bytes_with_sortkey():
    hk, sk = b"h", b"s1"
    upper = generate_next_bytes(hk, sk)
    assert generate_key(hk, sk) < upper
    assert generate_key(hk, sk + b"suffix") < upper
    assert upper <= generate_key(hk, b"s2")


def test_key_hash_uses_hashkey():
    key = generate_key(b"hashkey_123", b"sortkey")
    assert key_hash(key) == crc64(b"hashkey_123") == hash_key_hash(b"hashkey_123")


def test_key_hash_empty_hashkey_falls_back_to_sortkey():
    # parity: pegasus_key_schema.h:161-164
    key = generate_key(b"", b"sortonly")
    assert key_hash(key) == crc64(b"sortonly")


def test_partition_index_and_check():
    pc = 8
    hk = b"some_user"
    idx = partition_index(hk, pc)
    assert 0 <= idx < pc
    key = generate_key(hk, b"sk")
    # partition_version = partition_count - 1 for power-of-two counts
    assert check_key_hash(key, idx, pc - 1)
    assert not check_key_hash(key, (idx + 1) % pc, pc - 1)
