"""GEO client tests (parity: src/geo/test + the radius-search semantics
of geo_client.h:295-335), on the dual-table design over in-process
tables and over the replicated cluster."""

import math

import pytest

from pegasus_tpu.client import PegasusClient, Table
from pegasus_tpu.geo import GeoClient, cell_id, covering_cells, haversine_m
from pegasus_tpu.utils.errors import StorageStatus

OK = int(StorageStatus.OK)


def make_geo(tmp_path, partitions=4):
    raw = Table(str(tmp_path / "raw"), app_id=1, partition_count=partitions)
    idx = Table(str(tmp_path / "idx"), app_id=2, partition_count=partitions)
    return (GeoClient(PegasusClient(raw), PegasusClient(idx)), raw, idx)


def test_cell_ids_hierarchical():
    deep = cell_id(40.0, -74.0, 16)
    assert cell_id(40.0, -74.0, 12) == deep[:12]
    assert len(deep) == 16
    # neighbors at the same level share the ancestor prefix
    assert cell_id(40.0, -74.0, 4) == cell_id(40.01, -74.01, 4)


def test_covering_cells_cover_the_circle():
    cells = covering_cells(40.0, -74.0, 500.0, 12)
    assert cell_id(40.0, -74.0, 12) in cells
    # points near the radius edge fall inside SOME covering cell
    for brg in range(0, 360, 45):
        dlat = 0.004 * math.cos(math.radians(brg))
        dlng = 0.004 * math.sin(math.radians(brg))
        assert cell_id(40.0 + dlat, -74.0 + dlng, 12) in cells


def test_haversine_known_distance():
    # JFK -> LGA is ~17.5 km
    d = haversine_m(40.6413, -73.7781, 40.7769, -73.8740)
    assert 16000 < d < 19000


def test_geo_set_get_search(tmp_path):
    geo, raw, idx = make_geo(tmp_path)
    try:
        # a small constellation around (40, -74)
        points = {
            b"p_center": (40.0000, -74.0000),
            b"p_200m_n": (40.0018, -74.0000),
            b"p_400m_e": (40.0000, -73.9953),
            b"p_2km_s": (39.9820, -74.0000),
            b"p_far": (41.0, -75.0),
        }
        for name, (la, ln) in points.items():
            value = b"%f|%f|payload-%s" % (la, ln, name)
            assert geo.set(name, b"s", value) == OK
        assert geo.get(b"p_center", b"s")[0] == OK

        got = {r.hash_key for r in geo.search_radial(40.0, -74.0, 500)}
        assert got == {b"p_center", b"p_200m_n", b"p_400m_e"}
        # sorted by distance; count caps results
        top = geo.search_radial(40.0, -74.0, 5000, count=2)
        assert [r.hash_key for r in top] == [b"p_center", b"p_200m_n"]
        assert top[0].distance_m < 1.0
        # search by existing key
        got = {r.hash_key
               for r in geo.search_radial_by_key(b"p_center", b"s", 500)}
        assert b"p_400m_e" in got
        # distance between two stored records
        d = geo.distance(b"p_center", b"s", b"p_2km_s", b"s")
        assert 1800 < d < 2200
    finally:
        raw.close()
        idx.close()


def test_geo_update_moves_index_entry(tmp_path):
    geo, raw, idx = make_geo(tmp_path)
    try:
        assert geo.set(b"mover", b"s", b"40.0|-74.0|v1") == OK
        assert len(geo.search_radial(40.0, -74.0, 200)) == 1
        # move far away: old index entry must disappear
        assert geo.set(b"mover", b"s", b"41.0|-75.0|v2") == OK
        assert geo.search_radial(40.0, -74.0, 200) == []
        hits = geo.search_radial(41.0, -75.0, 200)
        assert len(hits) == 1 and hits[0].value == b"41.0|-75.0|v2"
        # delete removes both tables' entries
        assert geo.delete(b"mover", b"s") == OK
        assert geo.search_radial(41.0, -75.0, 200) == []
    finally:
        raw.close()
        idx.close()


def test_geo_rejects_uncodable_value(tmp_path):
    geo, raw, idx = make_geo(tmp_path)
    try:
        assert geo.set(b"bad", b"s", b"no-coords-here") == int(
            StorageStatus.INVALID_ARGUMENT)
    finally:
        raw.close()
        idx.close()


def test_geo_over_replicated_cluster(tmp_path):
    from pegasus_tpu.tools.cluster import SimCluster

    cluster = SimCluster(str(tmp_path / "cl"), n_nodes=3)
    try:
        cluster.create_table("georaw", partition_count=4)
        cluster.create_table("geoidx", partition_count=4)
        geo = GeoClient(cluster.client("georaw"), cluster.client("geoidx"))
        for i in range(12):
            la = 40.0 + i * 0.0009  # ~100m apart going north
            assert geo.set(b"pt%02d" % i, b"s",
                           b"%f|%f|v%d" % (la, -74.0, i)) == OK
        hits = geo.search_radial(40.0, -74.0, 520)
        assert {r.hash_key for r in hits} == {b"pt%02d" % i
                                              for i in range(6)}
    finally:
        cluster.close()


def test_geo_overflowing_cell_pages_through_context(tmp_path):
    """A covering cell with more points than one page must surface ALL
    of them, resuming the server-held scan context (review regression:
    the tail used to re-scan positionally and could skip/duplicate)."""
    geo, raw, idx = make_geo(tmp_path, partitions=2)
    try:
        # ~1500 points in a tight 30m blob -> one covering cell, >1 page
        import random

        rng = random.Random(3)
        for i in range(1500):
            la = 40.0 + rng.uniform(-0.00013, 0.00013)
            ln = -74.0 + rng.uniform(-0.00013, 0.00013)
            assert geo.set(b"blob%05d" % i, b"s",
                           b"%f|%f|x" % (la, ln)) == 0
        hits = geo.search_radial(40.0, -74.0, 100)
        assert len(hits) == 1500
        assert len({h.hash_key for h in hits}) == 1500  # no duplicates
    finally:
        raw.close()
        idx.close()


def test_adaptive_covering_matches_brute_force(tmp_path):
    """Radius search with adaptive finer-level covering cells (sortkey-
    range scans inside coarse hashkey cells) returns EXACTLY the
    brute-force haversine ground truth — no candidates lost at cell
    boundaries, none invented."""
    import numpy as np

    from pegasus_tpu.geo.cells import haversine_m

    geo, raw, idx = make_geo(tmp_path, partitions=4)
    try:
        rng = np.random.default_rng(5)
        n = 3000
        lats = 40.0 + (rng.random(n) - 0.5) * 0.18
        lngs = -74.0 + (rng.random(n) - 0.5) * 0.24
        for i in range(n):
            assert geo.set(b"poi%05d" % i, b"s",
                           b"%f|%f|p" % (lats[i], lngs[i])) == 0
        raw.flush_all()
        idx.flush_all()
        for radius in (120, 500, 2500):
            for ci in (0, 11, 42):
                got = {r.hash_key for r in geo.search_radial(
                    float(lats[ci]), float(lngs[ci]), radius)}
                want = {b"poi%05d" % i for i in range(n)
                        if haversine_m(float(lats[ci]), float(lngs[ci]),
                                       float(lats[i]),
                                       float(lngs[i])) <= radius}
                assert got == want, (radius, ci)
        # the adaptive level actually narrows for small radii
        assert geo._cover_level(100) > geo._cover_level(50_000)
        assert geo._cover_level(1e9) == geo.index_level
        assert geo._cover_level(0.1) == geo.max_level
    finally:
        raw.close()
        idx.close()


def test_polar_search_coarsens_instead_of_crashing(tmp_path):
    """Near the poles the longitude span blows up the fine covering —
    the search must coarsen its level, not raise (review regression)."""
    geo, raw, idx = make_geo(tmp_path, partitions=2)
    try:
        assert geo.set(b"polar", b"s", b"89.900000|10.000000|x") == 0
        hits = geo.search_radial(89.9, 10.0, 500)
        assert [h.hash_key for h in hits] == [b"polar"]
    finally:
        raw.close()
        idx.close()


def test_legacy_headerless_index_rows_still_searchable(tmp_path):
    """Index rows written by builds that stored the raw value directly
    (no packed coordinate header) must keep appearing in radius
    searches via the per-record text-codec fallback, alongside
    headered rows — and their values must come back unstripped."""
    geo, _raw, _idx = make_geo(tmp_path)
    # a headered row through the normal path
    assert geo.set(b"new", b"s", b"40.0001|-74.0001|new-point") == OK
    # a LEGACY row: planted directly in the index table, raw value only
    ih, isk = geo._index_keys(b"old", b"s", 40.0002, -74.0002)
    legacy_value = b"40.0002|-74.0002|old-point"
    assert geo.index.set(ih, isk, legacy_value) == OK
    assert geo.raw.set(b"old", b"s", legacy_value) == OK

    got = geo.search_radial(40.0, -74.0, 300)
    by_hk = {g.hash_key: g for g in got}
    assert set(by_hk) == {b"new", b"old"}
    assert by_hk[b"new"].value == b"40.0001|-74.0001|new-point"
    assert by_hk[b"old"].value == legacy_value
    assert abs(by_hk[b"old"].distance_m
               - haversine_m(40.0, -74.0, 40.0002, -74.0002)) < 1.0
