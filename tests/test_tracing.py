"""Distributed-tracing tests: context propagation across SimCluster
hops (incl. batch fan-out/fan-in parenting), tail keep, ring bounds,
cross-node stitching with clock alignment, the zero-overhead off state,
plus the transport error counters and the Prometheus exposition."""

import json
import time
import urllib.request

import pytest

from pegasus_tpu.base.key_schema import generate_key, key_hash_parts
from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.utils import tracing
from pegasus_tpu.utils.flags import FLAGS
from pegasus_tpu.utils.metrics import METRICS, MetricEntity, to_prometheus


@pytest.fixture(autouse=True)
def _trace_isolation():
    """Every test starts with empty rings, deterministic ids, and
    sampling OFF; nothing leaks into later tests."""
    tracing.reset()
    tracing.seed(7)
    FLAGS.set("pegasus.tracing", "sample_ratio", 0.0)
    yield
    FLAGS.set("pegasus.tracing", "sample_ratio", 0.0)
    FLAGS.set("pegasus.tracing", "ring_capacity", 2048)
    FLAGS.set("pegasus.tracing", "slow_trace_ms", 20.0)
    tracing.reset()


@pytest.fixture
def cluster(tmp_path):
    c = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=5)
    yield c
    c.close()


def _partition_of(cluster, hk, sk, partition_count):
    pidx = key_hash_parts(hk, sk) % partition_count
    return pidx, cluster.meta.state.get_partition(1, pidx)


def _cluster_spans(cluster, client, tid):
    """The `shell trace <id>` machinery: local (client) ring + the
    trace-dump remote verb fanned to every node."""
    spans = list(tracing.ring_for(client.name).dump(tid))
    for stub in cluster.stubs.values():
        spans += stub.commands.call("trace-dump", [tid])
    return spans


# ---- sampling off: nothing happens ---------------------------------------


def test_sampled_zero_adds_no_spans(cluster):
    cluster.create_table("t", partition_count=2)
    c = cluster.client("t")
    assert c.set(b"hk", b"s", b"v") == 0
    assert c.get(b"hk", b"s")[0] == 0
    assert tracing.dump_all() == []
    # and no payload grew a context: the rings never even saw a trace
    assert tracing.ring_for(c.name).dump() == []


# ---- propagation + stitching ---------------------------------------------


def test_write_trace_crosses_every_hop(cluster):
    cluster.create_table("t", partition_count=2, replica_count=3)
    c = cluster.client("t")
    pidx, pc = _partition_of(cluster, b"hk", b"s", 2)
    FLAGS.set("pegasus.tracing", "sample_ratio", 1.0)
    assert c.set(b"hk", b"s", b"v") == 0
    FLAGS.set("pegasus.tracing", "sample_ratio", 0.0)
    client_spans = tracing.ring_for(c.name).dump()
    roots = [s for s in client_spans if s["parent"] is None]
    assert len(roots) == 1 and roots[0]["name"] == "client.write"
    tid = roots[0]["trace"]
    spans = _cluster_spans(cluster, c, tid)
    nodes = {s["node"] for s in spans}
    # client, primary, and both secondaries all contributed spans
    assert c.name in nodes and pc.primary in nodes
    for sec in pc.secondaries:
        assert sec in nodes
    by_id = {s["span"]: s for s in spans}
    # every non-root span's parent resolves inside the same trace
    for s in spans:
        assert s["trace"] == tid
        if s["parent"] is not None:
            assert s["parent"] in by_id
    # the 2PC span carries the LatencyTracer stage chain as annotations
    tpc = [s for s in spans if s["name"].startswith("2pc.")]
    assert len(tpc) == 1
    stages = [a[0] for a in tpc[0]["ann"]]
    for want in ("prepare_local", "append_plog", "plog_durable",
                 "prepares_sent", "committed_applied", "replied"):
        assert want in stages


def test_stitch_one_rooted_tree_monotonic(cluster):
    cluster.create_table("t", partition_count=2, replica_count=3)
    c = cluster.client("t")
    FLAGS.set("pegasus.tracing", "sample_ratio", 1.0)
    assert c.set(b"hk", b"s", b"v") == 0
    FLAGS.set("pegasus.tracing", "sample_ratio", 0.0)
    tid = tracing.ring_for(c.name).dump()[-1]["trace"]
    tree = tracing.stitch(_cluster_spans(cluster, c, tid))
    assert tree is not None and tree["name"] == "client.write"

    seen = []

    def check(n):
        seen.append(n)
        for ch in n["children"]:
            # per-hop alignment is monotonic: a child never starts
            # before its parent on the stitched timeline
            assert ch["rel_ms"] >= n["rel_ms"] - 1e-6
            check(ch)

    check(tree)
    assert len(seen) >= 4  # client -> dispatch -> 2pc -> prepare hops
    # rendering never throws and names every hop
    text = tracing.render(tree)
    assert "client.write" in text and "2pc." in text


# ---- the acceptance scenario: injected slow secondary --------------------


def test_slow_secondary_trace_and_tail_keep(cluster):
    """FaultPlan-style delay on the prepare link: `trace <id>` stitches
    one cross-node tree whose longest (self-time) span is the delayed
    prepare hop, and tail keep pins the trace at every hop the keep
    decision reaches."""
    cluster.create_table("t", partition_count=2, replica_count=3)
    c = cluster.client("t")
    pidx, pc = _partition_of(cluster, b"hk", b"s", 2)
    slow_peer = pc.secondaries[0]
    cluster.net.set_delay(0.5, src=pc.primary, dst=slow_peer)
    FLAGS.set("pegasus.tracing", "sample_ratio", 1.0)
    assert c.set(b"hk", b"s", b"v") == 0
    FLAGS.set("pegasus.tracing", "sample_ratio", 0.0)
    # tail keep: the client's op crossed the slow threshold -> pinned
    kept = tracing.ring_for(c.name).slow_roots()
    assert kept and kept[-1]["name"] == "client.write"
    assert kept[-1]["total_ms"] >= 500.0
    tid = kept[-1]["trace"]
    # ... and the primary pinned too (local slow prepare hop + the keep
    # bit riding the reply pinned the client; spans exist on all hops)
    assert tracing.ring_for(pc.primary).is_kept(tid)
    spans = _cluster_spans(cluster, c, tid)
    assert {s["node"] for s in spans} >= {c.name, pc.primary, slow_peer}
    tree = tracing.stitch(spans)
    nodes = [n for n in tracing.walk(tree) if n is not tree]
    slowest = max(nodes, key=lambda n: n["self_ms"])
    assert slowest["name"] == f"prepare.{slow_peer}"
    assert slowest["node"] == pc.primary
    assert slowest["self_ms"] >= 450.0
    # the meta heard about it on config-sync (one-call `traces --slow`)
    cluster.step()
    rep = cluster.meta._trace_reports.get(pc.primary)
    assert rep and rep["kept"] >= 1
    assert any(r["trace"] == tid for r in rep["roots"])


# ---- batch fan-out / fan-in ----------------------------------------------


def test_read_batch_carrier_fans_out_per_op(cluster):
    cluster.create_table("t", partition_count=2, replica_count=3)
    c = cluster.client("t")
    for i in range(4):
        assert c.set(b"hk%d" % i, b"s", b"v%d" % i) == 0
    # group N=4 gets by their partitions (ops carry partition_hash)
    groups = {}
    for i in range(4):
        ph = key_hash_parts(b"hk%d" % i, b"s")
        pidx = ph % 2
        groups.setdefault(pidx, []).append(
            ("get", generate_key(b"hk%d" % i, b"s"), ph))
    FLAGS.set("pegasus.tracing", "sample_ratio", 1.0)
    res = c.point_read_multi(groups)
    FLAGS.set("pegasus.tracing", "sample_ratio", 0.0)
    assert all(r[0] == 0 for rs in res.values() for r in rs)
    tid = tracing.ring_for(c.name).dump()[-1]["trace"]
    spans = _cluster_spans(cluster, c, tid)
    carriers = [s for s in spans if s["name"] == "client_read_batch"]
    op_spans = [s for s in spans if s["name"].startswith("op.get.")]
    # N ops in the carriers fan out to N child spans — never N carriers
    # (one carrier per NODE, not per op; 3-replica spread over 3 nodes
    # means at most 2 distinct primaries for 2 partitions)
    assert 1 <= len(carriers) <= 2
    assert len(op_spans) == 4
    carrier_ids = {s["span"] for s in carriers}
    assert all(s["parent"] in carrier_ids for s in op_spans)


def test_write_batch_carrier_fans_out_per_op(cluster):
    cluster.create_table("t", partition_count=2, replica_count=3)
    c = cluster.client("t")
    from pegasus_tpu.base.value_schema import expire_ts_from_ttl
    from pegasus_tpu.rpc.codec import OP_PUT

    groups = {}
    for i in range(4):
        hk = b"wk%d" % i
        ph = key_hash_parts(hk, b"s")
        groups.setdefault(ph % 2, []).append(
            (OP_PUT, (generate_key(hk, b"s"), b"v",
                      expire_ts_from_ttl(0)), ph))
    FLAGS.set("pegasus.tracing", "sample_ratio", 1.0)
    res = c.write_multi(groups)
    FLAGS.set("pegasus.tracing", "sample_ratio", 0.0)
    assert all(r == 0 for rs in res.values() for r in rs)
    tid = tracing.ring_for(c.name).dump()[-1]["trace"]
    spans = _cluster_spans(cluster, c, tid)
    carriers = [s for s in spans if s["name"] == "client_write_batch"]
    op_spans = [s for s in spans if s["name"].startswith("op.write.")]
    assert 1 <= len(carriers) <= 2
    assert len(op_spans) == 4
    carrier_ids = {s["span"] for s in carriers}
    assert all(s["parent"] in carrier_ids for s in op_spans)
    # fan-in: the coalesced 2PC rounds also hang off the carriers, one
    # per combined run per partition — not one per op
    tpc = [s for s in spans if s["name"].startswith("2pc.")]
    assert 1 <= len(tpc) <= 2
    assert all(s["parent"] in carrier_ids for s in tpc)


# ---- ring bounds ----------------------------------------------------------


def test_ring_bounds_under_churn():
    FLAGS.set("pegasus.tracing", "ring_capacity", 64)
    clock = [0.0]
    ring = tracing.ring_for("churn", clock=lambda: clock[0])
    for i in range(500):
        sp = ring.start(f"op{i}")
        sp.finish()  # zero elapsed: never slow, never pinned
    assert len(ring.dump()) == 64
    assert ring.drop_count.value() == 436
    # a pinned trace SURVIVES churn
    slow = ring.start("slow-op")
    clock[0] += 1.0  # one virtual second: way past slow_trace_ms
    slow.finish()
    tid = slow.trace_id
    assert ring.is_kept(tid)
    for i in range(200):
        ring.start(f"more{i}").finish()
    assert [s["name"] for s in ring.dump(tid)] == ["slow-op"]
    # kept-trace store is bounded too
    FLAGS.set("pegasus.tracing", "kept_traces", 4)
    for i in range(8):
        sp = ring.start(f"slow{i}")
        clock[0] += 1.0
        sp.finish()
    assert len(ring.slow_roots(limit=100)) == 4


# ---- clock alignment ------------------------------------------------------


def test_stitch_aligns_skewed_clocks():
    """Two processes with clocks 5 s apart: the per-hop alignment lands
    the child inside its parent with a bounded skew estimate."""
    t = [1000.0]
    a = tracing.ring_for("A", clock=lambda: t[0])
    skew = 5.0
    b = tracing.ring_for("B", clock=lambda: t[0] + skew)
    parent = a.start("client.op")
    t[0] += 0.010  # request travels 10ms
    child = b.start("serve", parent_ctx=parent.ctx())
    t[0] += 0.050  # server works 50ms
    child.finish()
    t[0] += 0.010  # reply travels 10ms
    parent.finish()
    tree = tracing.stitch(a.dump() + b.dump())
    assert tree["name"] == "client.op"
    (ch,) = tree["children"]
    # aligned: child starts after parent, ends before it, despite the
    # raw clocks being 5s apart; skew bound covers the 10ms asymmetry
    assert 0.0 <= ch["rel_ms"] <= 20.0
    assert ch["skew_ms"] <= 11.0
    assert ch["rel_ms"] + ch["dur_ms"] <= tree["dur_ms"] + 1e-6


# ---- transport error counters --------------------------------------------


def test_transport_error_counters():
    from pegasus_tpu.rpc.transport import TcpTransport

    ent = METRICS.entity("rpc", "dispatch", {})
    d0 = ent.counter("dispatch_error_count").value()
    s0 = ent.counter("sender_error_count").value()
    server = TcpTransport(("127.0.0.1", 0), {})
    host, port = server.listen_addr

    def bad_handler(src, msg_type, payload):
        raise RuntimeError("boom")

    server.register("srv", bad_handler)
    client = TcpTransport(None, {"srv": (host, port),
                                 "ghost": ("127.0.0.1", 1)})
    try:
        client.send("cli", "srv", "poke", {"x": 1})
        deadline = time.monotonic() + 5.0
        while (ent.counter("dispatch_error_count").value() == d0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        # the dispatcher survived AND counted the handler failure
        assert ent.counter("dispatch_error_count").value() > d0
        # a dead peer counts sender errors instead of spamming stdout
        client.send("cli", "ghost", "poke", {"x": 2})
        deadline = time.monotonic() + 5.0
        while (ent.counter("sender_error_count").value() == s0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert ent.counter("sender_error_count").value() > s0
    finally:
        client.close()
        server.close()


# ---- prometheus exposition ------------------------------------------------


def test_prometheus_text_format():
    ent = MetricEntity("replica", "1.0", {"table": "1", "partition": "0"})
    ent.counter("read_cu").increment(42)
    ent.gauge("depth").set(3.5)
    p = ent.percentile("lat_ms")
    for v in range(100):
        p.set(float(v))
    text = to_prometheus([ent.snapshot()])
    lines = text.splitlines()
    assert "# TYPE pegasus_read_cu counter" in lines
    assert ('pegasus_read_cu{entity="replica",id="1.0",table="1",'
            'partition="0"} 42') in lines
    assert "# TYPE pegasus_depth gauge" in lines
    assert any(line.startswith("pegasus_lat_ms{") and
               'quantile="0.99"' in line for line in lines)
    # label escaping: quotes/newlines/backslashes never break the format
    weird = MetricEntity("x", 'a"b\nc\\d', {})
    weird.counter("c").increment()
    text2 = to_prometheus([weird.snapshot()])
    assert 'id="a\\"b\\nc\\\\d"' in text2


def test_prometheus_over_http():
    from pegasus_tpu.http.http_server import MetricsHttpServer

    METRICS.entity("tracing", "prom-node").counter(
        "kept_trace_count").increment(2)
    srv = MetricsHttpServer().start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics?format=prom"
                "&entity_type=tracing") as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            body = r.read().decode()
        assert "# TYPE pegasus_kept_trace_count counter" in body
        assert 'id="prom-node"' in body
        # JSON stays the default
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics"
                "?entity_type=tracing") as r:
            assert r.headers["Content-Type"] == "application/json"
            json.loads(r.read().decode())
    finally:
        srv.stop()


# ---- read-path slow-query stage chain ------------------------------------


def test_point_read_slow_log_stage_chain(tmp_path):
    from pegasus_tpu.server.partition_server import PartitionServer

    s = PartitionServer(str(tmp_path / "p0"))
    try:
        for i in range(20):
            s.on_put(generate_key(b"hk%02d" % i, b"s"), b"v%02d" % i)
        s.flush()
        s.slow_log.threshold_ms = 0.0  # everything is "slow"
        ops = [("get", generate_key(b"hk%02d" % i, b"s"), None)
               for i in range(8)]
        res = s.on_point_read_batch(ops)
        assert all(r[0] == 0 for r in res)
        dump = s.slow_log.dump()
        rep = dump[-1]
        assert rep["name"].startswith("point_get_batch.")
        stages = [st["stage"] for st in rep["stages"]]
        # the real chain: WHERE the read stalled, not just that it did
        for want in ("plan", "bloom", "block_probe", "decode", "finish"):
            assert want in stages, (want, stages)
        assert rep["ops"] == 8
    finally:
        s.close()


def test_scan_page_slow_log_stage_chain(tmp_path):
    from pegasus_tpu.server.partition_server import PartitionServer
    from pegasus_tpu.server.types import GetScannerRequest

    s = PartitionServer(str(tmp_path / "p0"))
    try:
        for i in range(50):
            s.on_put(generate_key(b"hk", b"s%03d" % i), b"v")
        s.flush()
        s.slow_log.threshold_ms = 0.0
        resp = s.on_get_scanner(GetScannerRequest(
            start_key=generate_key(b"hk", b""), stop_key=b"",
            batch_size=10))
        assert resp.error == 0 and resp.kvs
        rep = s.slow_log.dump()[-1]
        assert rep["name"].startswith("scan")
        stages = [st["stage"] for st in rep["stages"]]
        assert "plan" in stages and "finish" in stages or \
            "block_scan" in stages
    finally:
        s.close()


# ---- collector integration ------------------------------------------------


def test_collector_scrapes_latency_and_kept_traces(cluster):
    from pegasus_tpu.tools.collector import (
        DETECT_TABLE,
        STAT_TABLE,
        InfoCollector,
    )

    cluster.create_table(STAT_TABLE, partition_count=2)
    cluster.create_table(DETECT_TABLE, partition_count=2)
    cluster.create_table("traffic", partition_count=2)
    c = cluster.client("traffic")
    for i in range(10):
        assert c.set(b"k%d" % i, b"s", b"v" * 50) == 0
    groups = {}
    for i in range(10):
        ph = key_hash_parts(b"k%d" % i, b"s")
        groups.setdefault(ph % 2, []).append(
            ("get", generate_key(b"k%d" % i, b"s"), ph))
    res = c.point_read_multi(groups)
    assert all(r[0] == 0 for rs in res.values() for r in rs)
    # pin one slow trace on a node ring
    stub_name = next(iter(cluster.stubs))
    ring = tracing.ring_for(stub_name)
    sp = ring.start("slowread")
    sp.end = sp.start + 10.0
    ring.record(sp)
    assert ring.is_kept(sp.trace_id)
    col = InfoCollector(cluster.net, "collector", list(cluster.stubs),
                        cluster.client, cluster.pump)
    per_table = col.collect_round()
    app = per_table[str(c.app_id)]
    assert app["write_p99_ms"] > 0.0
    assert app["read_p99_ms"] > 0.0
    traces = col.collect_traces()
    assert traces.get(stub_name, 0) >= 1
