"""Cross-cluster duplication: WAN-shaped batched shipping.

Two layers of coverage:

- seeded SIM tests over TWO SimClusters sharing one loop+network
  (distinct name prefixes + cluster ids — the real geo topology, with
  the inter-cluster links faulted like a WAN): batched envelope
  decree-order apply + idempotent re-ship under loss, the
  origin-cluster echo filter under master-master, lost config-reply
  re-ask, late-ack convergence under sustained link delay, fail_mode=
  skip abandon-and-advance, the ship-abort state regression, governor
  backpressure, and the dup trace crossing clusters as one tree;
- the original multi-process onebox test: cluster A duplicating to
  cluster B through real TCP transports (A's address book carries B's
  nodes as external peers), now riding the compressed envelope path.
"""

import json
import os
import time

import pytest

from pegasus_tpu.utils.errors import PegasusError
from pegasus_tpu.utils.flags import FLAGS


# ---- sim harness: two clusters, one wire --------------------------------


def make_two_clusters(tmp_path, seed=0, n_nodes=2):
    from pegasus_tpu.runtime.sim import SimLoop, SimNetwork
    from pegasus_tpu.tools.cluster import SimCluster

    loop = SimLoop(seed=seed)
    net = SimNetwork(loop)
    a = SimCluster(str(tmp_path / "A"), n_nodes=n_nodes,
                   name_prefix="a-", loop=loop, net=net, cluster_id=1)
    b = SimCluster(str(tmp_path / "B"), n_nodes=n_nodes,
                   name_prefix="b-", loop=loop, net=net, cluster_id=2)
    return a, b


def step_both(a, b, rounds=1):
    """Paired step: shared virtual time advances ONCE per round while
    both clusters run their timers (beacons, dup/config-sync ticks)."""
    for _ in range(rounds):
        a.step()
        b.step(advance=False)


def inter_links(a, b):
    an = list(a.stubs) + [m.name for m in a.metas]
    bn = list(b.stubs) + [m.name for m in b.metas]
    return ([(x, y) for x in an for y in bn]
            + [(y, x) for x in an for y in bn])


def dup_session(cluster):
    """Every live dup session across the cluster's stubs."""
    out = []
    for stub in cluster.stubs.values():
        out.extend(stub._dup_sessions.values())
    return out


@pytest.fixture
def dup_flags():
    """Snapshot/restore the [pegasus.dup] knobs tests fiddle."""
    import pegasus_tpu.replica.dup_governor  # noqa: F401 - defines flags
    import pegasus_tpu.replica.duplication_cluster  # noqa: F401

    keys = ["ship_batch_mutations", "ship_batch_bytes", "ship_governor",
            "ship_max_mbps", "ship_min_mbps"]
    saved = {k: FLAGS.get("pegasus.dup", k) for k in keys}
    yield
    for k, v in saved.items():
        FLAGS.set("pegasus.dup", k, v)


def test_batched_envelopes_converge_in_decree_order_under_loss(
        tmp_path, dup_flags):
    """A window of mutations (overwrites included) ships as compressed
    dup_apply_batch envelopes; seeded loss forces idempotent re-ships;
    the follower converges to exactly the master's final content."""
    from pegasus_tpu.utils.metrics import METRICS

    a, b = make_two_clusters(tmp_path, seed=3)
    try:
        step_both(a, b, 2)
        a.create_table("t", partition_count=2, replica_count=2)
        b.create_table("t", partition_count=2, replica_count=2)
        ca = a.client("t")
        # overwrites across mutations: only decree-order apply (within
        # and across envelopes) lands the final values
        for rnd in range(3):
            for i in range(20):
                assert ca.set(b"k%03d" % i, b"s",
                              b"r%d-%d" % (rnd, i)) == 0
        assert ca.multi_set(b"mh", {b"a": b"1", b"b": b"2"}) == 0
        assert ca.delete(b"k000", b"s") == 0
        a.meta.duplication.add_duplication("t", "b-meta", "t")
        # WAN: seeded loss both ways on every inter-cluster link
        for s, d in inter_links(a, b):
            a.net.set_drop(0.3, src=s, dst=d)
        step_both(a, b, 14)
        for s, d in inter_links(a, b):
            a.net.set_drop(0.0, src=s, dst=d)
        step_both(a, b, 4)
        cb = b.client("t")
        for i in range(1, 20):
            assert cb.get(b"k%03d" % i, b"s") == (0, b"r2-%d" % i), i
        assert cb.get(b"k000", b"s")[0] == 1  # delete shipped last
        assert cb.multi_get(b"mh") == (0, {b"a": b"1", b"b": b"2"})
        # the batched path actually ran: compressed envelope bytes and
        # confirmed mutations on the "duplication" entity
        shipped = confirmed = 0
        for ent in METRICS.snapshot("duplication"):
            m = ent.get("metrics", {})
            shipped += m.get("dup_shipped_bytes", {}).get("value", 0)
            confirmed += m.get("dup_confirmed_mutations",
                               {}).get("value", 0)
        assert shipped > 0 and confirmed > 0
        # lag drained to zero and reported up config-sync to meta
        stats = a.meta.duplication.dup_stats("t")
        assert stats and stats[0]["max_lag_decrees"] == 0
        assert stats[0]["shipped_bytes"] > 0
    finally:
        a.close()
        b.close()


def test_master_master_echo_filter(tmp_path, dup_flags):
    """Both clusters duplicate the same table at each other. Writes
    received FROM the peer (timetag cluster != own id) must never be
    re-shipped back — the origin-cluster filter — while each side's own
    writes reach the other."""
    a, b = make_two_clusters(tmp_path, seed=5)
    try:
        step_both(a, b, 2)
        a.create_table("t", partition_count=2, replica_count=2)
        b.create_table("t", partition_count=2, replica_count=2)
        a.meta.duplication.add_duplication("t", "b-meta", "t")
        b.meta.duplication.add_duplication("t", "a-meta", "t")
        step_both(a, b, 3)
        ca, cb = a.client("t"), b.client("t")
        assert ca.set(b"from_a", b"s", b"av") == 0
        assert cb.set(b"from_b", b"s", b"bv") == 0
        step_both(a, b, 8)
        assert cb.get(b"from_a", b"s") == (0, b"av")
        assert ca.get(b"from_b", b"s") == (0, b"bv")
        # B's sessions saw A's dup writes in their logs and CONFIRMED
        # past them without shipping them back (echo filtered): after
        # convergence, more A-writes advance B's confirmed decrees with
        # ZERO new shipped bytes from B
        b_sessions = dup_session(b)
        assert b_sessions
        b_shipped0 = sum(s.stats()["shipped_bytes"] for s in b_sessions)
        for i in range(10):
            assert ca.set(b"more%02d" % i, b"s", b"v%d" % i) == 0
        step_both(a, b, 8)
        assert cb.get(b"more09", b"s") == (0, b"v9")
        b_sessions = dup_session(b)
        b_shipped1 = sum(s.stats()["shipped_bytes"] for s in b_sessions)
        assert b_shipped1 == b_shipped0, "echoed dup writes re-shipped"
        # and B confirmed past the received-dup decrees (no wedge)
        assert all(s.stats()["lag_decrees"] == 0 for s in b_sessions)
    finally:
        a.close()
        b.close()


def test_lost_config_reply_is_reasked(tmp_path, dup_flags):
    """Every follower-config reply is dropped for a while: the session
    must keep re-asking with fresh rids (not wedge on the lost one) and
    converge after the link heals."""
    a, b = make_two_clusters(tmp_path, seed=7)
    try:
        step_both(a, b, 2)
        a.create_table("t", partition_count=2, replica_count=2)
        b.create_table("t", partition_count=2, replica_count=2)
        ca = a.client("t")
        for i in range(10):
            assert ca.set(b"c%02d" % i, b"s", b"v%d" % i) == 0
        # silence the follower meta's replies BEFORE the dup starts
        for an in list(a.stubs):
            a.net.set_drop(1.0, src="b-meta", dst=an)
        a.meta.duplication.add_duplication("t", "b-meta", "t")
        step_both(a, b, 6)
        sessions = dup_session(a)
        assert sessions
        assert all(s.confirmed_decree == 0 for s in sessions)
        for an in list(a.stubs):
            a.net.set_drop(0.0, src="b-meta", dst=an)
        step_both(a, b, 8)
        cb = b.client("t")
        for i in range(10):
            assert cb.get(b"c%02d" % i, b"s") == (0, b"v%d" % i), i
    finally:
        a.close()
        b.close()


def test_late_ack_convergence_under_sustained_link_delay(
        tmp_path, dup_flags):
    """Inter-cluster RTT sustained past the re-drive cadence: retained
    rids must let LATE acks complete windows (no livelock on the same
    window), and re-shipped envelopes stay idempotent."""
    a, b = make_two_clusters(tmp_path, seed=11)
    try:
        step_both(a, b, 2)
        a.create_table("t", partition_count=2, replica_count=2)
        b.create_table("t", partition_count=2, replica_count=2)
        a.meta.duplication.add_duplication("t", "b-meta", "t")
        step_both(a, b, 3)
        # one-way delay > the 3-tick base re-drive limit (3s beacons)
        for s, d in inter_links(a, b):
            a.net.set_delay(5.0, src=s, dst=d)
        ca = a.client("t")
        for i in range(12):
            assert ca.set(b"d%02d" % i, b"s", b"v%d" % i) == 0
        step_both(a, b, 20)
        cb = b.client("t")
        for i in range(12):
            assert cb.get(b"d%02d" % i, b"s") == (0, b"v%d" % i), i
        assert all(s.stats()["lag_decrees"] == 0 for s in dup_session(a))
    finally:
        a.close()
        b.close()


def test_fail_mode_skip_abandons_and_advances(tmp_path, dup_flags):
    """fail_mode=skip: a poison decree (follower rejects every apply)
    is retried a bounded number of times, then LOUDLY abandoned —
    dup_skip_count ticks, confirmed advances, later mutations flow."""
    from pegasus_tpu.tools.cluster import SimCluster
    from pegasus_tpu.utils.fail_point import FAIL_POINTS
    from pegasus_tpu.utils.metrics import METRICS

    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=9)
    try:
        cluster.create_table("m", partition_count=1, replica_count=2)
        cluster.create_table("f", partition_count=1, replica_count=2)
        c = cluster.client("m")
        assert c.set(b"poison", b"s", b"p") == 0
        dupid = cluster.meta.duplication.add_duplication("m", "meta", "f")
        cluster.meta.duplication.set_fail_mode(dupid, "skip")
        FAIL_POINTS.setup()
        FAIL_POINTS.cfg("dup::apply_batch", "return(13)")
        try:
            cluster.step(rounds=6)
        finally:
            FAIL_POINTS.cfg("dup::apply_batch", "off")
            FAIL_POINTS.teardown()
        skips = rejects = 0
        for ent in METRICS.snapshot("duplication"):
            m = ent.get("metrics", {})
            skips += m.get("dup_skip_count", {}).get("value", 0)
            rejects += m.get("dup_reject_count", {}).get("value", 0)
        assert skips >= 1, "abandon was not counted"
        assert rejects >= 3, "bounded retries did not run"
        # the poison decree was confirmed past (pipeline un-wedged)...
        sessions = dup_session(cluster)
        assert sessions and all(s.confirmed_decree >= 1
                                for s in sessions)
        # ...and LATER writes reach the follower while the abandoned
        # one is (operator-sanctioned) lost
        assert c.set(b"after", b"s", b"av") == 0
        cluster.step(rounds=6)
        fc = cluster.client("f")
        assert fc.get(b"after", b"s") == (0, b"av")
        assert fc.get(b"poison", b"s")[0] == 1
    finally:
        cluster.close()


def _unit_dup(tmp_path, fail_mode="slow"):
    """Fake-stub harness: a real MutationLog + ClusterDuplicator with
    every send recorded — deterministic white-box ship scenarios."""
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.replica.duplication_cluster import ClusterDuplicator
    from pegasus_tpu.replica.mutation import Mutation, WriteOp
    from pegasus_tpu.replica.mutation_log import MutationLog
    from pegasus_tpu.replica.replica import PartitionStatus
    from pegasus_tpu.rpc.codec import OP_PUT

    class _Net:
        def __init__(self):
            self.sent = []

        def send(self, src, dst, typ, payload):
            self.sent.append((dst, typ, payload))

    class _Replica:
        def __init__(self, log):
            self.log = log
            self.status = PartitionStatus.PRIMARY
            self.last_committed_decree = 0
            self.duplicators = []

    class _Stub:
        name = "src-node"
        auth_secret = None
        clock = None

        def __init__(self, replica):
            self.net = _Net()
            self._replica = replica

        def get_replica(self, _gpid):
            return self._replica

    log = MutationLog(os.path.join(str(tmp_path), "mlog.bin"))
    replica = _Replica(log)
    stub = _Stub(replica)
    # keys spreading over BOTH follower partitions (count=2), two
    # mutations so the window spans decrees
    for d in (1, 2):
        ops = [WriteOp(OP_PUT, (generate_key(b"hk%02d" % i, b"s"),
                                b"v", 0xFFFFFFFF))
               for i in range(d * 4, d * 4 + 4)]
        log.append(Mutation(ballot=1, decree=d, last_committed=d - 1,
                            timestamp_us=d * 1_000_000, ops=ops),
                   sync=True)
    replica.last_committed_decree = 2
    dup = ClusterDuplicator(stub, (9, 0), 1, "b-meta", "t",
                            fail_mode=fail_mode)
    return dup, stub, log


def test_ship_abort_clears_outstanding_state(tmp_path, dup_flags):
    """Regression: the mid-loop 'follower partition unowned' abort left
    `_outstanding`/`_pending_pidx` populated with rids from the aborted
    attempt — a late ack for one of them reset the re-drive clock for a
    window no longer in flight. Both must clear on abort."""
    dup, stub, log = _unit_dup(tmp_path)
    # follower config: partition 1 unowned — the ship must abort
    # mid-loop AFTER (possibly) sending partition 0's envelope
    dup._fconfig = {"app_id": 7, "partition_count": 2,
                    "configs": [{"primary": "b-node0"},
                                {"primary": ""}]}
    dup.tick()
    sent = [p for _d, t, p in stub.net.sent if t == "dup_apply_batch"]
    assert dup._outstanding == {}, "aborted rids left registered"
    assert dup._pending_pidx == set(), "aborted pidxs left pending"
    assert dup._inflight_decree is None
    assert dup._fconfig is None
    if sent:  # an envelope left before the abort: its late ack must be
        # a no-op (unknown rid), not a state reset
        dup._inflight_ticks = 2
        assert dup.on_write_reply({"rid": sent[0]["rid"],
                                   "err": 0}) is False
        assert dup._inflight_ticks == 2
    log.close()


def test_transient_rejection_does_not_pin_solo_windows(tmp_path,
                                                       dup_flags):
    """Regression: in fail_mode=skip, ONE transient follower rejection
    set `_fail_count` and nothing cleared it on the subsequent
    successful ack — every later tick shipped solo (cap_n=1) windows,
    silently giving up the whole batched-shipping win for the session's
    lifetime."""
    dup, stub, log = _unit_dup(tmp_path, fail_mode="skip")
    fconfig = {"app_id": 7, "partition_count": 2,
               "configs": [{"primary": "b-node0"},
                           {"primary": "b-node1"}]}
    dup._fconfig = dict(fconfig, configs=[dict(c) for c
                                          in fconfig["configs"]])
    dup.tick()
    sent = [p for _d, t, p in stub.net.sent if t == "dup_apply_batch"]
    assert sent and sent[0]["max_decree"] == 2  # batched window of 2
    # transient rejection (follower mid-failover)
    assert dup.on_write_reply({"rid": sent[0]["rid"], "err": 13})
    assert dup._fail_count == 1
    # re-resolve + re-ship (cooldown consumes one tick first)
    dup._fconfig = dict(fconfig, configs=[dict(c) for c
                                          in fconfig["configs"]])
    stub.net.sent.clear()
    dup.tick()  # consumes the rejection cooldown
    dup.tick()  # solo retry window while rejections are being counted
    retry = [p for _d, t, p in stub.net.sent
             if t == "dup_apply_batch"]
    assert retry and retry[0]["max_decree"] == 1  # isolated to solo
    for p in retry:
        assert dup.on_write_reply({"rid": p["rid"], "err": 0})
    assert dup._fail_count == 0  # the success CLEARED the skip state
    # the next window is batched again, not pinned solo forever
    stub.net.sent.clear()
    dup.tick()
    nxt = [p for _d, t, p in stub.net.sent if t == "dup_apply_batch"]
    assert nxt and nxt[0]["max_decree"] == 2
    log.close()


def test_governor_backoff_recovery_and_floor(dup_flags):
    """Seeded DupGovernor unit: follower pressure growth halves the
    budget (engaging from uncapped), quiet acks recover it back to
    uncapped, and the floor is never undercut."""
    from pegasus_tpu.replica.dup_governor import DupGovernor

    FLAGS.set("pegasus.dup", "ship_min_mbps", 0.5)
    now = [0.0]
    gov = DupGovernor("test-node", clock=lambda: now[0])
    assert gov.window_budget() is None  # uncapped at rest
    gov._rate_bps = 8e6  # pretend catch-up measured 8 MB/s
    gov.on_follower_pressure("f1", {"deadline_expired": 0,
                                    "read_shed": 0})
    now[0] += 1.0
    gov.on_follower_pressure("f1", {"deadline_expired": 5,
                                    "read_shed": 0})
    assert gov._throttle_mbps == pytest.approx(4.0)  # engage at half
    for k in range(6):
        now[0] += 1.0
        gov.on_follower_pressure("f1", {"deadline_expired": 6 + k,
                                        "read_shed": 5 + k})
    assert gov._throttle_mbps == pytest.approx(0.5)  # halved to floor
    assert gov.status()["backoff_count"] >= 2
    # budget is finite and refills with time while capped
    b0 = gov.window_budget()
    assert b0 is not None
    gov.note_shipped(b0 + 100_000)
    assert gov.window_budget() < b0
    # quiet acks: multiplicative recovery until fully uncapped
    for _ in range(30):
        now[0] += 2.0
        gov.on_follower_pressure("f1", {"deadline_expired": 5,
                                        "read_shed": 5})
        if gov._throttle_mbps == 0.0:
            break
    assert gov.window_budget() is None  # recovered to uncapped


def test_governor_floor_still_ships_one_mutation(tmp_path, dup_flags):
    """Forward-progress floor end-to-end: with the budget squeezed to
    zero bytes, every tick still loads (and ships) one mutation — the
    catch-up can be slowed, never stalled."""
    a, b = make_two_clusters(tmp_path, seed=13)
    try:
        step_both(a, b, 2)
        a.create_table("t", partition_count=1, replica_count=2)
        b.create_table("t", partition_count=1, replica_count=2)
        ca = a.client("t")
        for i in range(6):
            assert ca.set(b"f%02d" % i, b"s", b"v%d" % i) == 0
        # engage a throttle so tiny the token bucket is always empty
        FLAGS.set("pegasus.dup", "ship_max_mbps", 1e-9)
        a.meta.duplication.add_duplication("t", "b-meta", "t")
        step_both(a, b, 12)
        cb = b.client("t")
        for i in range(6):
            assert cb.get(b"f%02d" % i, b"s") == (0, b"v%d" % i), i
    finally:
        a.close()
        b.close()


def test_dup_trace_crosses_clusters_as_one_tree(tmp_path, dup_flags):
    """A sampled write's trace context rides the dup envelope: the
    stitched tree contains the client op, the source 2PC span, the
    dup.ship hop, and the follower's dup_apply_batch dispatch span —
    one write visible crossing clusters."""
    from pegasus_tpu.utils import tracing

    tracing.reset()
    tracing.seed(4)
    FLAGS.set("pegasus.tracing", "sample_ratio", 1.0)
    try:
        a, b = make_two_clusters(tmp_path, seed=15)
        try:
            step_both(a, b, 2)
            a.create_table("t", partition_count=1, replica_count=2)
            b.create_table("t", partition_count=1, replica_count=2)
            a.meta.duplication.add_duplication("t", "b-meta", "t")
            step_both(a, b, 3)
            ca = a.client("t")
            assert ca.set(b"traced", b"s", b"tv") == 0
            step_both(a, b, 6)
            cb = b.client("t")
            assert cb.get(b"traced", b"s") == (0, b"tv")
            spans = tracing.dump_all()
            ship = [s for s in spans if s["name"].startswith("dup.ship")]
            assert ship, "no dup.ship span recorded"
            trace_id = ship[0]["trace"]
            tree_spans = [s for s in spans if s["trace"] == trace_id]
            names = {s["name"] for s in tree_spans}
            assert any(n.startswith("2pc.") for n in names)
            assert any(n == "dup_apply_batch" for n in names)
            tree = tracing.stitch(tree_spans)
            nodes = list(tracing.walk(tree))
            # the follower's dispatch span is a DESCENDANT in one tree
            assert any(n["name"] == "dup_apply_batch" for n in nodes)
        finally:
            a.close()
            b.close()
    finally:
        FLAGS.set("pegasus.tracing", "sample_ratio", 0.0)
        tracing.reset()


def test_solo_wire_flag_degrades_to_legacy_shipping(tmp_path, dup_flags):
    """ship_batch_mutations<=1 keeps the original one-mutation
    client_write shipping alive (the bench baseline + a rollback
    lever); content still converges."""
    from pegasus_tpu.utils.metrics import METRICS

    FLAGS.set("pegasus.dup", "ship_batch_mutations", 1)
    a, b = make_two_clusters(tmp_path, seed=17)
    try:
        step_both(a, b, 2)
        a.create_table("t", partition_count=1, replica_count=2)
        b.create_table("t", partition_count=1, replica_count=2)
        ca = a.client("t")
        for i in range(8):
            assert ca.set(b"s%02d" % i, b"s", b"v%d" % i) == 0
        before = {ent["id"]: ent["metrics"].get(
            "dup_shipped_bytes", {}).get("value", 0)
            for ent in METRICS.snapshot("duplication")}
        a.meta.duplication.add_duplication("t", "b-meta", "t")
        step_both(a, b, 12)
        cb = b.client("t")
        for i in range(8):
            assert cb.get(b"s%02d" % i, b"s") == (0, b"v%d" % i), i
        # solo wire still accounts shipped bytes on the dup entity
        after = sum(ent["metrics"].get("dup_shipped_bytes",
                                       {}).get("value", 0)
                    - before.get(ent["id"], 0)
                    for ent in METRICS.snapshot("duplication"))
        assert after > 0
    finally:
        a.close()
        b.close()


# ---- the original wire test: two real oneboxes over TCP -----------------


def _wait_nodes(admin, n, deadline_s=90):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            if len(admin.call("list_nodes", timeout=6)) == n:
                return
        except PegasusError:
            pass
        time.sleep(0.5)
    pytest.fail("cluster never came up")


def test_wire_duplication_between_two_oneboxes(tmp_path):
    from pegasus_tpu.tools import onebox_cluster as ob

    db = str(tmp_path / "B")
    da = str(tmp_path / "A")
    ob.start(db, n_replica=1, name_prefix="b", cluster_id=2)
    try:
        admin_b = ob.OneboxAdmin(db)
        _wait_nodes(admin_b, 1)
        admin_b.create_table("dapp", partition_count=2, replica_count=1)
        with open(os.path.join(db, "cluster.json")) as f:
            bnodes = {n: (c["host"], c["port"])
                      for n, c in json.load(f)["nodes"].items()}

        ob.start(da, n_replica=1, name_prefix="a", extra_peers=bnodes,
                 cluster_id=1)
        try:
            admin_a = ob.OneboxAdmin(da)
            _wait_nodes(admin_a, 1)
            admin_a.create_table("dapp", partition_count=2,
                                 replica_count=1)
            pa = ob.connect("dapp", da)
            for i in range(10):
                assert pa.set(b"dk%02d" % i, b"s", b"v%d" % i) == 0
            admin_a.call("add_dup", app_name="dapp",
                         follower_meta="bmeta", follower_app="dapp",
                         timeout=30)
            pb = ob.connect("dapp", db)
            deadline = time.monotonic() + 90
            missing = -1
            while time.monotonic() < deadline:
                missing = sum(pb.get(b"dk%02d" % i, b"s") !=
                              (0, b"v%d" % i) for i in range(10))
                if missing == 0:
                    break
                time.sleep(0.5)
            assert missing == 0, f"{missing} rows never converged on B"
            # live write + delete keep flowing
            assert pa.set(b"live", b"s", b"lv") == 0
            assert pa.delete(b"dk00", b"s") == 0
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if (pb.get(b"live", b"s") == (0, b"lv")
                        and pb.get(b"dk00", b"s")[0] == 1):
                    break
                time.sleep(0.5)
            assert pb.get(b"live", b"s") == (0, b"lv")
            assert pb.get(b"dk00", b"s")[0] == 1
            # the wire path shipped envelopes and reports dup health
            stats = admin_a.call("dup_stats", timeout=15)
            assert stats and stats[0]["shipped_bytes"] > 0
            node_stats = admin_a.remote_command("anode0", "dup.stats",
                                                [])
            assert node_stats["sessions"]
        finally:
            ob.stop(da)
    finally:
        ob.stop(db)
