"""Cross-cluster duplication over the WIRE: two multi-process oneboxes,
cluster A duplicating to cluster B through real TCP transports — A's
address book carries B's nodes as external (book-only) peers.
Parity: the reference's cross-cluster duplication between real
clusters (duplication_sync_timer + dup shipping), which the `.act`
cases exercise only in the simulator."""

import json
import os
import time

import pytest

from pegasus_tpu.utils.errors import PegasusError


def _wait_nodes(admin, n, deadline_s=90):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            if len(admin.call("list_nodes", timeout=6)) == n:
                return
        except PegasusError:
            pass
        time.sleep(0.5)
    pytest.fail("cluster never came up")


def test_wire_duplication_between_two_oneboxes(tmp_path):
    from pegasus_tpu.tools import onebox_cluster as ob

    db = str(tmp_path / "B")
    da = str(tmp_path / "A")
    ob.start(db, n_replica=1, name_prefix="b")
    try:
        admin_b = ob.OneboxAdmin(db)
        _wait_nodes(admin_b, 1)
        admin_b.create_table("dapp", partition_count=2, replica_count=1)
        with open(os.path.join(db, "cluster.json")) as f:
            bnodes = {n: (c["host"], c["port"])
                      for n, c in json.load(f)["nodes"].items()}

        ob.start(da, n_replica=1, name_prefix="a", extra_peers=bnodes)
        try:
            admin_a = ob.OneboxAdmin(da)
            _wait_nodes(admin_a, 1)
            admin_a.create_table("dapp", partition_count=2,
                                 replica_count=1)
            pa = ob.connect("dapp", da)
            for i in range(10):
                assert pa.set(b"dk%02d" % i, b"s", b"v%d" % i) == 0
            admin_a.call("add_dup", app_name="dapp",
                         follower_meta="bmeta", follower_app="dapp",
                         timeout=30)
            pb = ob.connect("dapp", db)
            deadline = time.monotonic() + 90
            missing = -1
            while time.monotonic() < deadline:
                missing = sum(pb.get(b"dk%02d" % i, b"s") !=
                              (0, b"v%d" % i) for i in range(10))
                if missing == 0:
                    break
                time.sleep(0.5)
            assert missing == 0, f"{missing} rows never converged on B"
            # live write + delete keep flowing
            assert pa.set(b"live", b"s", b"lv") == 0
            assert pa.delete(b"dk00", b"s") == 0
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if (pb.get(b"live", b"s") == (0, b"lv")
                        and pb.get(b"dk00", b"s")[0] == 1):
                    break
                time.sleep(0.5)
            assert pb.get(b"live", b"s") == (0, b"lv")
            assert pb.get(b"dk00", b"s")[0] == 1
        finally:
            ob.stop(da)
    finally:
        ob.stop(db)
