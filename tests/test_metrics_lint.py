"""metrics_lint tier-1 gate: the tree must stay free of metric-name
drift (conflicting kinds under one name, sanitizer-breaking names) —
caught at PR time, not at the dashboard."""

import os

from pegasus_tpu.tools.metrics_lint import (
    _PKG_ROOT,
    lint,
    main,
    scan_tenant_entities,
    scan_tree,
)


def test_package_tree_is_clean():
    """THE gate: every counter(/gauge(/percentile( registration in the
    package agrees on kind per name and survives the Prometheus
    sanitizer unchanged."""
    problems = lint()
    assert problems == [], "\n".join(problems)


def test_scan_finds_known_registrations():
    found = scan_tree(_PKG_ROOT)
    # cross-file kind agreement is only meaningful if the scan actually
    # sees the registrations: spot-check knowns from several layers
    assert "read_shed_count" in found
    assert set(found["read_shed_count"]) == {"counter"}
    assert "index_bloom_bytes" in found
    assert set(found["index_bloom_bytes"]) == {"gauge"}
    assert "read_latency_ms" in found
    assert set(found["read_latency_ms"]) == {"percentile"}
    assert len(found) > 50  # the spine is large; a tiny count means
    # the regex rotted and the gate is vacuous


def test_scan_sees_perf_context_fields():
    """PerfContext field registrations (utils/perf_context.perf_field)
    ride the same drift gate: the scan finds them with their kinds, so
    a context field named like a metric of another kind fails lint."""
    found = scan_tree(_PKG_ROOT)
    assert "bloom_pruned" in found
    assert set(found["bloom_pruned"]) == {"counter"}
    assert "queue_wait_ms" in found
    assert set(found["queue_wait_ms"]) == {"gauge"}
    # shared names must agree in kind across BOTH registration styles
    # (block_cache_hit is a storage counter AND a perf field)
    assert set(found["block_cache_hit"]) == {"counter"}
    assert len(found["block_cache_hit"]["counter"]) >= 2


def test_lint_catches_perf_field_kind_conflict(tmp_path):
    bad = tmp_path / "pkg"
    os.makedirs(bad)
    (bad / "a.py").write_text('ent.counter("drifted_name")\n')
    (bad / "b.py").write_text(
        'perf_field("drifted_name", "gauge")\n'
        'perf_field("plain_default")\n'
        'perf_field("kw_form", kind="gauge")\n')
    problems = lint(str(bad))
    text = "\n".join(problems)
    assert "drifted_name" in text and "conflicting kinds" in text
    # the kind-less form defaults to counter and is seen
    found = scan_tree(str(bad))
    assert set(found["plain_default"]) == {"counter"}
    # the keyword form carries its kind (not silently a counter)
    assert set(found["kw_form"]) == {"gauge"}


def test_lint_catches_conflicts_and_bad_names(tmp_path):
    bad = tmp_path / "pkg"
    os.makedirs(bad)
    (bad / "a.py").write_text(
        'ent.counter("worker_load")\n'
        'ent.gauge("bad-name")\n')
    (bad / "b.py").write_text(
        'other.gauge("worker_load")\n'
        'other.counter(\n    "multi_line_name")\n')
    problems = lint(str(bad))
    text = "\n".join(problems)
    assert "worker_load" in text and "conflicting kinds" in text
    assert "bad-name" in text and "sanitizer" in text
    # the multi-line registration is seen (not a silent scan gap)
    assert "multi_line_name" in scan_tree(str(bad))
    assert main([str(bad)]) == 1
    (bad / "a.py").write_text('ent.counter("worker_load")\n')
    (bad / "b.py").write_text('other.counter("worker_load")\n')
    assert main([str(bad)]) == 0


def test_tenant_entity_rule_fails_sites_outside_the_registry(tmp_path):
    """Per-tenant metric entities may ONLY be minted by the bounded
    registry (server/tenancy.py): anywhere else, a request-supplied
    tag becomes unbounded metric cardinality — the linter fails it."""
    bad = tmp_path / "pkg"
    os.makedirs(bad / "server")
    (bad / "rogue.py").write_text(
        'ent = METRICS.entity("tenant", raw_wire_tag)\n'
        'ok = METRICS.entity("table", name)\n')
    (bad / "server" / "tenancy.py").write_text(
        'ent = METRICS.entity("tenant", name, {"tenant": name})\n')
    sites = scan_tenant_entities(str(bad))
    assert sites == ["rogue.py:1"]  # the home file is exempt; other
    # entity types don't trip the rule
    problems = lint(str(bad))
    assert any("unbounded metric cardinality" in p for p in problems)
    assert main([str(bad)]) == 1
    (bad / "rogue.py").write_text('ok = METRICS.entity("table", name)\n')
    assert main([str(bad)]) == 0
    # the multi-line form is seen too (not a silent scan gap)
    (bad / "rogue.py").write_text(
        'ent = METRICS.entity(\n    "tenant", raw)\n')
    assert scan_tenant_entities(str(bad)) == ["rogue.py:1"]


def test_package_tree_mints_tenant_entities_only_in_tenancy():
    """THE gate: across the whole package, the bounded registry is the
    single place a tenant-labeled entity comes from."""
    assert scan_tenant_entities(_PKG_ROOT) == []
