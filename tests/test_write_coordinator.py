"""Batched write hot path tests: cross-partition write coalescing
(client_write_batch / write_multi), node-level plog group commit,
prepare fan-out aggregation, the vectorized apply translate, and the
shared framed-log codec.

The load-bearing regressions: batched writes must leave state (and
per-op results) identical to the solo handlers, and the group-commit
window must never release an ack before its mutations are durable —
a crash mid-window loses only writes nobody was acked for.
"""

import os

import pytest

from pegasus_tpu.base.key_schema import generate_key, key_hash_parts
from pegasus_tpu.replica import (
    Mutation,
    MutationLog,
    Replica,
    ReplicaBusyError,
    ReplicaConfig,
    WriteFlushWindow,
    WriteOp,
)
from pegasus_tpu.rpc.codec import (
    OP_INCR,
    OP_MULTI_PUT,
    OP_MULTI_REMOVE,
    OP_PUT,
    OP_REMOVE,
)
from pegasus_tpu.runtime import SimLoop, SimNetwork
from pegasus_tpu.server.types import (
    IncrRequest,
    KeyValue,
    MultiPutRequest,
    MultiRemoveRequest,
)
from pegasus_tpu.storage.framed_log import (
    iter_frames,
    pack_frame,
    scan_valid_end,
)
from pegasus_tpu.utils.errors import ErrorCode
from pegasus_tpu.utils.flags import FLAGS
from pegasus_tpu.utils.metrics import METRICS

OK = int(ErrorCode.ERR_OK)


def k(h, s=b""):
    return generate_key(h, s)


def mk_mu(decree, ballot=1, ts=None):
    return Mutation(ballot=ballot, decree=decree,
                    last_committed=decree - 1,
                    timestamp_us=ts or (1_000_000 + decree),
                    ops=[WriteOp(OP_PUT,
                                 (k(b"h%d" % decree, b"s"),
                                  b"v%d" % decree, 0))])


# ---- shared framed-log codec -----------------------------------------


def test_framed_log_roundtrip_and_torn_tail():
    payloads = [b"alpha", b"", b"x" * 1000]
    data = b"".join(pack_frame(p) for p in payloads)
    assert [p for p, _e in iter_frames(data)] == payloads
    assert scan_valid_end(data) is None  # fully valid
    # torn tail: a partial frame stops iteration at the boundary
    torn = data + pack_frame(b"tail")[:-3]
    assert [p for p, _e in iter_frames(torn)] == payloads
    assert scan_valid_end(torn) == len(data)
    # corrupt crc: frames past it are unreachable by contract
    corrupt = bytearray(data)
    corrupt[10] ^= 0xFF
    assert [p for p, _e in iter_frames(bytes(corrupt))] == []
    assert scan_valid_end(bytes(corrupt)) == 0


def test_mutation_log_append_batch_matches_solo(tmp_path):
    solo = MutationLog(str(tmp_path / "solo" / "m.bin"))
    batch = MutationLog(str(tmp_path / "batch" / "m.bin"))
    mus = [mk_mu(d) for d in (1, 2, 3)]
    for mu in mus:
        solo.append(mu)
    batch.append_batch(mus)
    solo.close()
    batch.close()
    with open(solo.path, "rb") as f:
        a = f.read()
    with open(batch.path, "rb") as f:
        b = f.read()
    assert a == b
    assert batch.max_decree == 3
    assert [m.decree for m in MutationLog.replay(batch.path)] == [1, 2, 3]


def test_buffered_append_visible_to_readers(tmp_path):
    """read_range/read_tail flush the append buffer first — duplication
    tailing must never miss a window's staged frames."""
    log = MutationLog(str(tmp_path / "m.bin"))
    log.append(mk_mu(1), flush=False)
    assert [m.decree for m in log.read_range(1)] == [1]
    log.append(mk_mu(2), flush=False)
    tail = log.read_tail(0)
    assert [m.decree for m, _off in tail] == [1, 2]
    log.close()


# ---- group commit: durability contract --------------------------------


def test_crash_mid_group_commit_window_loses_only_unacked(tmp_path):
    """Acked (post-commit_window) mutations survive a crash; mutations
    staged in an uncommitted window — whose acks were still deferred —
    may be lost, and recovery still sees a clean prefix."""
    path = str(tmp_path / "m.bin")
    log = MutationLog(path)
    for d in (1, 2, 3):
        log.append(mk_mu(d), flush=False)
    log.commit_window(sync=True)  # window 1 hardened: acks released
    for d in (4, 5):
        log.append(mk_mu(d), flush=False)  # window 2 never commits
    # crash: the on-disk bytes are all a dead process leaves (the
    # buffered tail lived in its userspace buffer)
    with open(path, "rb") as f:
        disk = f.read()
    crash = str(tmp_path / "crash.bin")
    # plus half a frame: a torn tail from a kill mid-write
    with open(crash, "wb") as f:
        f.write(disk + pack_frame(mk_mu(6).encode())[:-4])
    recovered = MutationLog(crash)
    assert [m.decree for m in recovered.replay(crash)] == [1, 2, 3]
    # the torn tail was truncated: appends after recovery are reachable
    recovered.append(mk_mu(7))
    assert [m.decree for m in recovered.replay(crash)] == [1, 2, 3, 7]
    recovered.close()
    log._f = open(os.devnull, "ab")  # drop the dead buffer for teardown


def test_ack_released_only_after_window_commit(tmp_path):
    """The appended-before-acked contract under group commit: the
    client callback (and the decree-ready path behind it) runs only
    after commit_window hardened the plog."""
    loop = SimLoop(seed=0)
    net = SimNetwork(loop)
    r = Replica("r1", str(tmp_path / "r1"), net,
                clock=lambda: 1_700_000_000 + loop.now)
    net.register("r1", r.on_message)
    r.assign_config(ReplicaConfig(1, "r1", []))
    window = WriteFlushWindow(net, "r1",
                              METRICS.entity("write", "test-ack"))
    r.plog_sink = window

    events = []
    orig_commit = r.log.commit_window
    r.log.commit_window = lambda sync=False: (
        events.append("commit"), orig_commit(sync))[1]
    with window:
        r.client_write([WriteOp(OP_PUT, (k(b"h", b"s"), b"v", 0))],
                       lambda res: events.append("ack"))
        events.append("staged")
    assert events == ["staged", "commit", "ack"]
    # and outside a window the legacy immediate path still acks inline
    events.clear()
    r.client_write([WriteOp(OP_PUT, (k(b"h", b"s2"), b"v2", 0))],
                   lambda res: events.append("ack"))
    assert events == ["ack"]
    r.close()


def test_group_commit_fsync_amortized(tmp_path):
    """fsync mode: one shared fsync per dirty log per window — a
    64-op write_multi costs ~#partitions fsyncs, while the same ops
    solo cost one window (and one fsync) each."""
    from pegasus_tpu.tools.cluster import SimCluster

    FLAGS.set("pegasus.replica", "plog_sync_mode", "fsync")
    try:
        c = SimCluster(str(tmp_path), n_nodes=1)
        c.create_table("t", partition_count=4, replica_count=1)
        cl = c.client("t")
        cl.refresh_config()
        fsyncs = METRICS.entity("write", "node0").counter(
            "plog_fsync_count")
        groups = {}
        for i in range(64):
            hk, sk = b"hk%03d" % i, b"s"
            ph = key_hash_parts(hk, sk)
            groups.setdefault(ph % 4, []).append(
                (OP_PUT, (k(hk, sk), b"v%d" % i, 0), ph))
        before = fsyncs.value()
        res = cl.write_multi(groups)
        batched_cost = fsyncs.value() - before
        assert all(r == 0 for rs in res.values() for r in rs)
        # one batch message -> one window -> at most one fsync per
        # partition log touched (4), plus one follow-up pass if a
        # queued run drained — nowhere near one per op
        assert batched_cost <= 8, batched_cost
        before = fsyncs.value()
        for i in range(16):
            cl.set(b"solo%03d" % i, b"s", b"v")
        solo_cost = fsyncs.value() - before
        assert solo_cost >= 16  # one window (>= one fsync) per solo op
        c.close()
    finally:
        FLAGS.set("pegasus.replica", "plog_sync_mode", "flush")


def test_restart_recovers_acked_writes_with_stale_engine_wal(tmp_path):
    """Under a window the engine-WAL frame may ride the IO buffer
    (never flushed); an acked write must STILL survive a crash because
    the plog hardened before the ack and boot replay + the reprepare
    path recommit it."""
    import shutil

    from pegasus_tpu.server.types import MultiGetRequest  # noqa: F401

    loop = SimLoop(seed=0)
    net = SimNetwork(loop)
    rdir = tmp_path / "r1"
    r = Replica("r1", str(rdir), net,
                clock=lambda: 1_700_000_000 + loop.now)
    net.register("r1", r.on_message)
    r.assign_config(ReplicaConfig(1, "r1", []))
    window = WriteFlushWindow(net, "r1",
                              METRICS.entity("write", "test-crash"))
    r.plog_sink = window
    acked = []
    with window:
        for i in range(8):
            r.client_write(
                [WriteOp(OP_PUT, (k(b"h%d" % i, b"s"), b"v%d" % i, 0))],
                lambda res, i=i: acked.append(i))
    assert acked == list(range(8))
    # crash: only on-disk bytes survive (the engine WAL's frames are
    # still in the dead process's buffer; the plog was flushed by the
    # window before the acks)
    crash_dir = tmp_path / "crash"
    shutil.copytree(rdir, crash_dir)
    r2 = Replica("r1", str(crash_dir), net,
                 clock=lambda: 1_700_000_000 + loop.now)
    # the engine alone is BEHIND (stale WAL)...
    assert r2.server.engine.last_committed_decree < 8
    # ...but the plog replay re-prepared the tail, and the promotion
    # reprepare recommits it before the replica may serve reads
    r2.assign_config(ReplicaConfig(2, "r1", []))
    assert r2.ready_to_serve()
    assert r2.last_committed_decree == 8
    for i in range(8):
        err, v = r2.server.on_get(k(b"h%d" % i, b"s"))
        assert (err, v) == (0, b"v%d" % i)
    r2.close()
    r.close()


# ---- typed overload ---------------------------------------------------


def test_write_queue_overload_raises_typed_busy(tmp_path):
    """Queue-full and non-batchable-behind-in-flight both raise
    ReplicaBusyError (stub maps it to ERR_BUSY — retryable)."""
    loop = SimLoop(seed=0)
    net = SimNetwork(loop)
    r = Replica("r1", str(tmp_path / "r1"), net,
                clock=lambda: 1_700_000_000 + loop.now)
    net.register("r1", r.on_message)
    # ghost secondaries: prepares go nowhere, acks never arrive, the
    # pipeline stays in flight
    r.assign_config(ReplicaConfig(1, "r1", ["ghost1", "ghost2"]))
    for i in range(r.PIPELINE_DEPTH):
        assert r.client_write(
            [WriteOp(OP_PUT, (k(b"h%d" % i, b"s"), b"v", 0))]) > 0
    # an atomic op cannot batch behind the in-flight round
    with pytest.raises(ReplicaBusyError):
        r.client_write([WriteOp(OP_INCR,
                                IncrRequest(k(b"c", b"s"), 1, 0))])
    # batchable ops coalesce until the queue cap, then typed busy
    batch = [WriteOp(OP_PUT, (k(b"q", b"s%03d" % i), b"v", 0))
             for i in range(r.MAX_BATCH_OPS)]
    assert r.client_write(batch) == -1
    with pytest.raises(ReplicaBusyError):
        r.client_write([WriteOp(OP_PUT, (k(b"q2", b"s"), b"v", 0))])
    r.close()


def test_stub_maps_busy_to_err_busy(tmp_path):
    from pegasus_tpu.tools.cluster import SimCluster

    c = SimCluster(str(tmp_path), n_nodes=1)
    app_id = c.create_table("t", partition_count=1, replica_count=1)
    stub = c.stubs["node0"]
    r = stub.get_replica((app_id, 0))
    orig = r.client_write
    r.client_write = lambda *a, **kw: (_ for _ in ()).throw(
        ReplicaBusyError("full"))
    replies = []
    c.net.register("probe", lambda s, mt, p: replies.append(p))
    c.net.send("probe", "node0", "client_write", {
        "gpid": (app_id, 0), "rid": 1, "auth": None,
        "ops": [(OP_PUT, (k(b"h", b"s"), b"v", 0))],
        "partition_hash": None})
    c.loop.run_until_idle()
    assert replies and replies[0]["err"] == int(ErrorCode.ERR_BUSY)
    r.client_write = orig
    c.close()


# ---- client_write_batch RPC ------------------------------------------


def _batch_write_reply(cluster, payload):
    replies = []
    cluster.net.register("probe",
                         lambda s, mt, p: replies.append((mt, p)))
    cluster.net.send("probe", "node0", "client_write_batch", payload)
    cluster.loop.run_until_idle()
    assert replies, "no reply to client_write_batch"
    return replies[-1][1]


def test_per_op_deadline_inside_write_batch(tmp_path):
    """An expired per-op deadline fast-fails THAT op with a typed
    ERR_TIMEOUT before its 2PC starts; its window neighbors commit."""
    from pegasus_tpu.tools.cluster import SimCluster

    c = SimCluster(str(tmp_path), n_nodes=1)
    app_id = c.create_table("t", partition_count=1, replica_count=1)
    cl = c.client("t")
    cl.refresh_config()
    stub = c.stubs["node0"]
    now = stub.clock()
    key_dead, key_live = k(b"dead", b"s"), k(b"live", b"s")
    reply = _batch_write_reply(c, {
        "rid": 7, "auth": None, "groups": [((app_id, 0), [
            ([(OP_PUT, (key_dead, b"x", 0))], None, now - 5.0),
            ([(OP_PUT, (key_live, b"y", 0))], None, now + 60.0),
        ])]})
    assert reply["err"] == OK
    (pidx, err, items) = reply["result"][0]
    assert (pidx, err) == (0, OK)
    assert items[0] == (int(ErrorCode.ERR_TIMEOUT), [])
    assert items[1] == (OK, [0])
    err, _v = cl.get(b"dead", b"s")
    assert err != 0  # the expired op never ran
    err, v = cl.get(b"live", b"s")
    assert (err, v) == (0, b"y")
    c.close()


def test_write_batch_partition_gate_failures_in_slot(tmp_path):
    """A stale/unhosted partition fails in ITS slot; hosted slots in
    the same message still serve."""
    from pegasus_tpu.tools.cluster import SimCluster

    c = SimCluster(str(tmp_path), n_nodes=1)
    app_id = c.create_table("t", partition_count=1, replica_count=1)
    reply = _batch_write_reply(c, {
        "rid": 9, "auth": None, "groups": [
            ((app_id, 0), [([(OP_PUT, (k(b"a", b"s"), b"v", 0))],
                            None, None)]),
            ((app_id + 7, 0), [([(OP_PUT, (k(b"b", b"s"), b"v", 0))],
                                None, None)]),
        ]})
    assert reply["err"] == OK
    slots = reply["result"]
    assert slots[0][1] == OK and slots[0][2][0] == (OK, [0])
    assert slots[1][1] == int(ErrorCode.ERR_INVALID_STATE)
    assert slots[1][2] is None
    c.close()


# ---- batched vs solo identity ----------------------------------------


def _mixed_workload(n=24):
    """(tag, args) ops covering the full batchable mix + an atomic
    interleaved mid-stream."""
    ops = []
    for i in range(n):
        hk = b"user%04d" % (i // 3)
        ops.append(("set", (hk, b"s%02d" % i, b"val-%d" % i)))
        if i % 5 == 0:
            ops.append(("multi_set",
                        (hk, [(b"m0-%d" % i, b"mv0"),
                              (b"m1-%d" % i, b"mv1")])))
        if i % 7 == 3:
            ops.append(("del", (hk, b"s%02d" % (i - 1),)))
        if i == n // 2:
            ops.append(("incr", (b"counter", b"c", 11)))
        if i % 9 == 4:
            ops.append(("multi_del", (hk, [b"m0-%d" % (i - 4)])))
    return ops


def _run_solo(cl, ops):
    results = []
    for tag, args in ops:
        if tag == "set":
            results.append(cl.set(*args))
        elif tag == "multi_set":
            results.append(cl.multi_set(args[0], args[1]))
        elif tag == "del":
            results.append(cl.delete(*args))
        elif tag == "incr":
            resp = cl.incr(*args)
            results.append((resp.error, resp.new_value))
        elif tag == "multi_del":
            results.append(tuple(cl.multi_del(args[0], args[1])))
    return results


def _run_batched(cl, ops, batch=16):
    """The same logical ops through write_multi, `batch` per flush,
    preserving submission order inside each partition."""
    results = []
    pending = {}
    pending_order = []
    pending_n = 0

    def flush():
        nonlocal pending_n
        if not pending:
            return
        got = cl.write_multi({p: [op for op, _tag in lst]
                              for p, lst in pending.items()})
        for p, i in pending_order:
            res = got[p][i]
            tag = pending[p][i][1]
            if tag == "incr":
                results.append((res.error, res.new_value))
            elif tag == "multi_del":
                results.append(tuple(res))
            else:
                results.append(res)
        pending.clear()
        pending_order.clear()
        pending_n = 0

    for tag, args in ops:
        if tag == "set":
            hk, sk, v = args
            ph = key_hash_parts(hk, sk)
            op = (OP_PUT, (generate_key(hk, sk), v, 0), ph)
        elif tag == "multi_set":
            hk, kvs = args
            ph = key_hash_parts(hk)
            op = (OP_MULTI_PUT,
                  MultiPutRequest(hk, [KeyValue(a, b) for a, b in kvs],
                                  0), ph)
        elif tag == "del":
            hk, sk = args
            ph = key_hash_parts(hk, sk)
            op = (OP_REMOVE, (generate_key(hk, sk),), ph)
        elif tag == "incr":
            hk, sk, by = args
            ph = key_hash_parts(hk, sk)
            op = (OP_INCR, IncrRequest(generate_key(hk, sk), by, 0), ph)
        elif tag == "multi_del":
            hk, sks = args
            ph = key_hash_parts(hk)
            op = (OP_MULTI_REMOVE, MultiRemoveRequest(hk, list(sks)), ph)
        pidx = ph % cl.partition_count
        lst = pending.setdefault(pidx, [])
        pending_order.append((pidx, len(lst)))
        lst.append((op, tag))
        pending_n += 1
        if pending_n >= batch:
            flush()
    flush()
    return results


def _state_of(cl, ops):
    """Read back every key either path touched: (err, value) pairs."""
    keys = set()
    for tag, args in ops:
        if tag in ("set", "del"):
            keys.add((args[0], args[1]))
        elif tag == "incr":
            keys.add((args[0], args[1]))
        elif tag == "multi_set":
            keys.update((args[0], sk) for sk, _v in args[1])
        elif tag == "multi_del":
            keys.update((args[0], sk) for sk in args[1])
    return {hk + b"|" + sk: cl.get(hk, sk) for hk, sk in sorted(keys)}


def test_write_multi_identity_with_solo_across_op_mix(tmp_path):
    """Full-mix identity: per-op results AND resulting user-visible
    state of the batched path match the solo handlers exactly (two
    tables on one cluster, same logical workload)."""
    from pegasus_tpu.tools.cluster import SimCluster

    c = SimCluster(str(tmp_path), n_nodes=2)
    c.create_table("solo", partition_count=4, replica_count=2)
    c.create_table("batch", partition_count=4, replica_count=2)
    cl_solo = c.client("solo", name="cs")
    cl_batch = c.client("batch", name="cb")
    cl_solo.refresh_config()
    cl_batch.refresh_config()
    ops = _mixed_workload()
    res_solo = _run_solo(cl_solo, ops)
    res_batch = _run_batched(cl_batch, ops)
    assert res_batch == res_solo
    assert _state_of(cl_batch, ops) == _state_of(cl_solo, ops)
    c.close()


def test_translate_put_run_byte_identical(tmp_path):
    """The vectorized apply's run translate emits byte-identical
    engine items to translate_put/translate_remove called per op."""
    from pegasus_tpu.server.partition_server import PartitionServer

    s = PartitionServer(str(tmp_path / "p0"))
    ws = s.write_service
    ts = 1_234_567_890_123_456
    reqs = [(k(b"h%d" % i, b"s"), b"v%d" % i, i % 3) for i in range(40)]
    run = ws.translate_put_run(reqs, ts)
    solo = [it for key, ud, ets in reqs
            for it in ws.translate_put(key, ud, ets, ts)]
    assert [(it.op, it.key, it.value, it.expire_ts) for it in run] == \
        [(it.op, it.key, it.value, it.expire_ts) for it in solo]
    keys = [key for key, _ud, _ets in reqs]
    run_rm = ws.translate_remove_run(keys)
    solo_rm = [it for key in keys for it in ws.translate_remove(key)]
    assert [(it.op, it.key, it.value, it.expire_ts) for it in run_rm] \
        == [(it.op, it.key, it.value, it.expire_ts) for it in solo_rm]
    s.close()


# ---- prepare fan-out aggregation -------------------------------------


def test_prepare_batch_aggregation_on_secondary_path(tmp_path):
    """A multi-partition write flush to a replicated table collapses
    its per-partition prepares into prepare_batch messages (and the
    acks into prepare_batch_ack) — and every write still commits."""
    from pegasus_tpu.tools.cluster import SimCluster

    c = SimCluster(str(tmp_path), n_nodes=3)
    c.create_table("t", partition_count=8, replica_count=3)
    cl = c.client("t")
    cl.refresh_config()
    seen = []
    orig_send = c.net.send

    def spy(src, dst, msg_type, payload):
        if msg_type in ("prepare_batch", "prepare_batch_ack"):
            seen.append((msg_type, len(payload["items"])))
        return orig_send(src, dst, msg_type, payload)

    c.net.send = spy
    groups = {}
    for i in range(96):
        hk, sk = b"hk%04d" % i, b"s"
        ph = key_hash_parts(hk, sk)
        groups.setdefault(ph % 8, []).append(
            (OP_PUT, (k(hk, sk), b"v%d" % i, 0), ph))
    res = cl.write_multi(groups)
    c.net.send = orig_send
    assert all(r == 0 for rs in res.values() for r in rs)
    batched = [n for mt, n in seen if mt == "prepare_batch"]
    assert batched and max(batched) > 1, seen
    acks = [n for mt, n in seen if mt == "prepare_batch_ack"]
    assert acks and max(acks) > 1, seen
    for i in range(0, 96, 7):
        err, v = cl.get(b"hk%04d" % i, b"s")
        assert (err, v) == (0, b"v%d" % i)
    # the observability surface recorded the aggregation
    snap = {s["id"]: s["metrics"]
            for s in METRICS.snapshot("write")}
    sizes = [m.get("prepare_batch_size") for m in snap.values()
             if m.get("prepare_batch_size")]
    assert sizes
    c.close()


def test_pipeline_queue_depth_metric_sampled(tmp_path):
    from pegasus_tpu.tools.cluster import SimCluster

    c = SimCluster(str(tmp_path), n_nodes=1)
    c.create_table("t", partition_count=1, replica_count=1)
    cl = c.client("t")
    cl.refresh_config()
    cl.set(b"hk", b"s", b"v")
    snap = {s["id"]: s["metrics"] for s in METRICS.snapshot("write")}
    assert "pipeline_queue_depth" in snap["node0"]
    c.close()
