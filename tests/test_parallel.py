"""Multi-device sharded scan over the 8-device virtual CPU mesh."""

import jax
import numpy as np
import pytest

from pegasus_tpu.base.key_schema import generate_key, key_hash
from pegasus_tpu.ops.predicates import FT_MATCH_PREFIX, FilterSpec
from pegasus_tpu.ops.record_block import build_record_block
from pegasus_tpu.parallel import make_mesh, sharded_scan_step
from pegasus_tpu.parallel.partition_mesh import stack_blocks


def _make_partition_blocks(pc, per_part, expired_every=4):
    blocks, pidx = [], []
    expect_keep = 0
    expect_expired = 0
    for p in range(pc):
        keys, ets = [], []
        n = 0
        i = 0
        while n < per_part:
            hk = b"user_%d" % i
            i += 1
            if key_hash(generate_key(hk, b"")) % pc != p:
                continue
            keys.append(generate_key(hk, b"sk_%03d" % n))
            if n % expired_every == 0:
                ets.append(1)  # long expired
                expect_expired += 1
            else:
                ets.append(0)
                expect_keep += 1
            n += 1
        blocks.append(build_record_block(keys, ets, capacity=per_part,
                                         key_width=32))
        pidx.append(p)
    return blocks, pidx, expect_keep, expect_expired


def test_mesh_shapes():
    assert len(jax.devices()) == 8  # conftest forced 8 virtual devices
    pm = make_mesh()
    assert pm.dp == 8 and pm.sp == 1
    pm = make_mesh(dp=4)
    assert pm.dp == 4 and pm.sp == 2
    with pytest.raises(ValueError):
        make_mesh(dp=3)


def test_sharded_scan_step_counts():
    pc, per_part = 8, 64
    blocks, pidx, want_keep, want_expired = _make_partition_blocks(pc, per_part)
    stacked = stack_blocks(blocks, pidx)
    pm = make_mesh(dp=4)  # dp=4, sp=2: partitions AND batch both sharded
    keep, total_kept, total_expired, per_part_kept = sharded_scan_step(
        pm, stacked, now=100)
    assert int(total_kept) == want_keep
    assert int(total_expired) == want_expired
    assert int(per_part_kept.sum()) == want_keep
    assert keep.shape == (pc, per_part)


def test_sharded_scan_with_filter_matches_unsharded():
    pc, per_part = 4, 32
    blocks, pidx, _, _ = _make_partition_blocks(pc, per_part)
    stacked = stack_blocks(blocks, pidx)
    spec = FilterSpec.make(FT_MATCH_PREFIX, b"sk_00")
    pm = make_mesh(dp=2)
    keep, total, _, _ = sharded_scan_step(pm, stacked, now=100,
                                          sort_filter=spec)
    # compare against the single-device predicate per partition
    from pegasus_tpu.ops.predicates import scan_block_predicate
    want = 0
    for b in blocks:
        masks = scan_block_predicate(b, 100, sort_filter=spec)
        want += int(np.asarray(masks.keep).sum())
    assert int(total) == want


def test_sharded_scan_validates_partition_ownership():
    pc, per_part = 8, 32
    blocks, pidx, want_keep, _ = _make_partition_blocks(
        pc, per_part, expired_every=10**9)  # nothing expired
    # swap two partitions' blocks: their records become foreign
    blocks[0], blocks[1] = blocks[1], blocks[0]
    stacked = stack_blocks(blocks, pidx)
    pm = make_mesh()
    _, total, _, per_part_kept = sharded_scan_step(
        pm, stacked, now=100, validate_hash=True, partition_version=pc - 1)
    counts = np.asarray(per_part_kept)
    assert counts[0] == 0 and counts[1] == 0  # foreign data rejected
    assert int(total) == int(counts[2:].sum())
