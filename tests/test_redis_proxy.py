"""Redis proxy tests: RESP protocol + command semantics + a live socket
session against the replicated cluster (parity: src/redis_protocol/
proxy_lib/redis_parser.cpp:60-74 command surface)."""

import socket

import pytest

from pegasus_tpu.client import PegasusClient, Table
from pegasus_tpu.redis_proxy import RedisHandler, RedisProxy
from pegasus_tpu.redis_proxy.resp import RespParser, array, bulk, integer


def test_resp_parser_multibulk_and_inline():
    p = RespParser()
    cmds = p.feed(b"*3\r\n$3\r\nSET\r\n$1\r\nk\r\n$1\r\nv\r\n")
    assert cmds == [[b"SET", b"k", b"v"]]
    # split across feeds
    assert p.feed(b"*2\r\n$3\r\nGET\r\n$") == []
    assert p.feed(b"1\r\nk\r\n") == [[b"GET", b"k"]]
    # inline form
    assert p.feed(b"PING\r\n") == [[b"PING"]]
    # pipelined
    assert p.feed(b"*1\r\n$4\r\nPING\r\n*1\r\n$4\r\nPING\r\n") == [
        [b"PING"], [b"PING"]]


def test_resp_serializers():
    assert bulk(None) == b"$-1\r\n"
    assert bulk(b"ab") == b"$2\r\nab\r\n"
    assert integer(-2) == b":-2\r\n"
    assert array([b"a", 1, [b"b"]]) == (
        b"*3\r\n$1\r\na\r\n:1\r\n*1\r\n$1\r\nb\r\n")


@pytest.fixture
def handler(tmp_path):
    t = Table(str(tmp_path / "t"), partition_count=4)
    yield RedisHandler(PegasusClient(t))
    t.close()


def test_command_semantics(handler):
    h = handler.handle
    assert h([b"PING"]) == b"+PONG\r\n"
    assert h([b"SET", b"k", b"hello"]) == b"+OK\r\n"
    assert h([b"GET", b"k"]) == b"$5\r\nhello\r\n"
    assert h([b"GET", b"missing"]) == b"$-1\r\n"
    assert h([b"EXISTS", b"k", b"missing"]) == b":1\r\n"
    assert h([b"DEL", b"k", b"missing"]) == b":1\r\n"
    assert h([b"GET", b"k"]) == b"$-1\r\n"
    # TTL family
    assert h([b"SETEX", b"tk", b"100", b"v"]) == b"+OK\r\n"
    ttl = int(h([b"TTL", b"tk"])[1:-2])
    assert 90 <= ttl <= 100
    assert h([b"TTL", b"nope"]) == b":-2\r\n"
    assert h([b"SET", b"nt", b"v"]) == b"+OK\r\n"
    assert h([b"TTL", b"nt"]) == b":-1\r\n"
    # counters
    assert h([b"INCR", b"c"]) == b":1\r\n"
    assert h([b"INCRBY", b"c", b"41"]) == b":42\r\n"
    assert h([b"DECR", b"c"]) == b":41\r\n"
    assert h([b"DECRBY", b"c", b"40"]) == b":1\r\n"
    # errors
    assert h([b"NOPE"]).startswith(b"-ERR")
    assert h([b"SET", b"only-key"]).startswith(b"-ERR")


def test_geo_commands(tmp_path):
    from pegasus_tpu.geo import GeoClient

    raw = Table(str(tmp_path / "raw"), app_id=1, partition_count=4)
    idx = Table(str(tmp_path / "idx"), app_id=2, partition_count=4)
    geo = GeoClient(PegasusClient(raw), PegasusClient(idx))
    h = RedisHandler(PegasusClient(raw), geo=geo).handle
    assert h([b"GEOADD", b"places", b"-74.0", b"40.0", b"center",
              b"-74.0", b"40.0018", b"north200m"]) == b":2\r\n"
    out = h([b"GEORADIUS", b"places", b"-74.0", b"40.0", b"300", b"m"])
    assert b"center" in out and b"north200m" in out
    out = h([b"GEORADIUS", b"places", b"-74.0", b"40.0", b"300", b"m",
             b"COUNT", b"1"])
    assert b"center" in out and b"north200m" not in out
    dist = h([b"GEODIST", b"places", b"center", b"north200m"])
    assert 150 < float(dist.split(b"\r\n")[1]) < 250
    # GEOPOS: (lng, lat) per member, nil for absent (g_geo_pos parity)
    pos = h([b"GEOPOS", b"places", b"center", b"missing"])
    assert pos.startswith(b"*2\r\n*2\r\n")
    lng = float(pos.split(b"\r\n")[3])
    assert abs(lng - (-74.0)) < 1e-6
    assert pos.endswith(b"*-1\r\n")  # absent member = NIL ARRAY
    # GEORADIUSBYMEMBER: centered on an existing member
    out = h([b"GEORADIUSBYMEMBER", b"places", b"north200m", b"300",
             b"m"])
    assert b"center" in out and b"north200m" in out
    out = h([b"GEORADIUSBYMEMBER", b"places", b"north200m", b"50",
             b"m"])
    assert b"north200m" in out and b"center" not in out
    # a missing CENTER is an error, never an empty result
    assert h([b"GEORADIUSBYMEMBER", b"places", b"missing", b"300",
              b"m"]).startswith(b"-ERR")
    raw.close()
    idx.close()


def test_proxy_over_socket_against_cluster(tmp_path):
    """A raw RESP session over TCP against the replicated SimCluster-backed
    proxy (redis-cli equivalent; the binary itself isn't in this image)."""
    from pegasus_tpu.tools.cluster import SimCluster

    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3)
    try:
        cluster.create_table("redis", partition_count=4)
        proxy = RedisProxy(cluster.client("redis")).start()
        s = socket.create_connection(("127.0.0.1", proxy.port), timeout=5)
        s.sendall(b"*3\r\n$3\r\nSET\r\n$2\r\nrk\r\n$3\r\nval\r\n")
        assert s.recv(100) == b"+OK\r\n"
        s.sendall(b"*2\r\n$3\r\nGET\r\n$2\r\nrk\r\n")
        assert s.recv(100) == b"$3\r\nval\r\n"
        s.sendall(b"*2\r\n$4\r\nINCR\r\n$1\r\nc\r\n"
                  b"*2\r\n$4\r\nINCR\r\n$1\r\nc\r\n")
        got = b""
        while got.count(b"\r\n") < 2:
            got += s.recv(100)
        assert got == b":1\r\n:2\r\n"
        s.close()
        proxy.stop()
    finally:
        cluster.close()


def test_resp_negative_bulk_rejected():
    p = RespParser()
    with pytest.raises(ValueError):
        p.feed(b"*1\r\n$-1\r\n*1\r\n$4\r\nPING\r\n")


def test_cluster_error_becomes_err_reply(handler):
    from pegasus_tpu.utils.errors import ErrorCode, PegasusError

    class Boom:
        def set(self, *a, **k):
            raise PegasusError(ErrorCode.ERR_TIMEOUT, "retries exhausted")

    h = RedisHandler(Boom())
    out = h.handle([b"SET", b"k", b"v"])
    assert out.startswith(b"-ERR cluster error")
