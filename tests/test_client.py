"""End-to-end client tests: client -> routing -> partitions -> device path.

Modeled on the reference's function tests (src/test/function_test/
base_api: basic/scan/ttl/check_and_set/check_and_mutate) against an
in-process multi-partition table.
"""

import pytest

from pegasus_tpu.client import PegasusClient, ScanOptions, Table
from pegasus_tpu.ops.predicates import FT_MATCH_PREFIX
from pegasus_tpu.server.types import CasCheckType, Mutate, MutateOperation
from pegasus_tpu.utils.errors import StorageStatus

OK = int(StorageStatus.OK)
NOT_FOUND = int(StorageStatus.NOT_FOUND)


@pytest.fixture
def table(tmp_path):
    t = Table(str(tmp_path / "t"), partition_count=8)
    yield t
    t.close()


@pytest.fixture
def client(table):
    return PegasusClient(table)


def test_set_get_del_across_partitions(client, table):
    # keys spread over all 8 partitions
    for i in range(64):
        assert client.set(b"user_%d" % i, b"sk", b"v%d" % i) == OK
    touched = {p.pidx for p in table.all_partitions()
               if p.engine.last_committed_decree > 0}
    assert len(touched) >= 6  # crc64 spreads well
    for i in range(64):
        assert client.get(b"user_%d" % i, b"sk") == (OK, b"v%d" % i)
    assert client.delete(b"user_3", b"sk") == OK
    assert not client.exist(b"user_3", b"sk")
    assert client.exist(b"user_4", b"sk")


def test_multi_ops(client):
    assert client.multi_set(b"hk", {b"a": b"1", b"b": b"2", b"c": b"3"}) == OK
    err, kvs = client.multi_get(b"hk")
    assert err == OK and kvs == {b"a": b"1", b"b": b"2", b"c": b"3"}
    err, sks = client.multi_get_sortkeys(b"hk")
    assert sks == [b"a", b"b", b"c"]
    err, n = client.multi_del(b"hk", [b"a", b"c"])
    assert (err, n) == (OK, 2)
    assert client.sortkey_count(b"hk") == (OK, 1)


def test_ttl_roundtrip(client):
    client.set(b"hk", b"s", b"v", ttl_seconds=5000)
    err, ttl = client.ttl(b"hk", b"s")
    assert err == OK and 4000 < ttl <= 5000


def test_incr_and_cas(client):
    assert client.incr(b"hk", b"cnt", 7).new_value == 7
    resp = client.check_and_set(b"hk", b"cnt",
                                CasCheckType.CT_VALUE_INT_EQUAL, b"7",
                                b"flag", b"set!", return_check_value=True)
    assert resp.error == OK and resp.check_value == b"7"
    assert client.get(b"hk", b"flag") == (OK, b"set!")
    resp = client.check_and_mutate(
        b"hk", b"flag", CasCheckType.CT_VALUE_EXIST, b"",
        [Mutate(MutateOperation.MO_PUT, b"m1", b"x"),
         Mutate(MutateOperation.MO_DELETE, b"cnt")])
    assert resp.error == OK
    assert client.get(b"hk", b"m1") == (OK, b"x")
    assert not client.exist(b"hk", b"cnt")


def test_batch_get_cross_partition(client):
    keys = [(b"user_%d" % i, b"s") for i in range(20)]
    for hk, sk in keys:
        client.set(hk, sk, b"v_" + hk)
    err, rows = client.batch_get(keys + [(b"missing", b"s")])
    assert err == OK and len(rows) == 20
    assert all(v == b"v_" + hk for hk, _, v in rows)


def test_hashkey_scanner(client):
    for i in range(30):
        client.set(b"scanme", b"s%02d" % i, b"v%d" % i)
    client.set(b"other", b"s", b"x")
    got = list(PegasusClient.get_scanner(client, b"scanme",
                                         options=ScanOptions(batch_size=7)))
    assert len(got) == 30
    assert [sk for _, sk, _ in got] == [b"s%02d" % i for i in range(30)]
    assert all(hk == b"scanme" for hk, _, _ in got)
    # range-bounded
    got = list(client.get_scanner(b"scanme", b"s10", b"s15"))
    assert [sk for _, sk, _ in got] == [b"s%02d" % i for i in range(10, 15)]


def test_unordered_scanners_cover_table(client, table):
    expect = {}
    for i in range(100):
        hk, sk, v = b"u%03d" % i, b"s", b"v%d" % i
        client.set(hk, sk, v)
        expect[(hk, sk)] = v
    scanners = client.get_unordered_scanners(
        3, ScanOptions(batch_size=16))
    assert len(scanners) == 3
    got = {}
    for sc in scanners:
        for hk, sk, v in sc:
            got[(hk, sk)] = v
    assert got == expect


def test_scanner_filter_and_count(client):
    for i in range(50):
        client.set(b"apple_%d" % i, b"s", b"v")
        client.set(b"pear_%d" % i, b"s", b"v")
    scanners = client.get_unordered_scanners(
        1, ScanOptions(hash_key_filter_type=FT_MATCH_PREFIX,
                       hash_key_filter_pattern=b"apple_", batch_size=1000))
    rows = [hk for sc in scanners for hk, _, _ in sc]
    assert len(rows) == 50 and all(hk.startswith(b"apple_") for hk in rows)
    # count-only scan
    scanners = client.get_unordered_scanners(
        2, ScanOptions(only_return_count=True))
    total = 0
    for sc in scanners:
        for _ in sc:
            pass
        total += sc.kv_count
    assert total == 100


def test_scan_survives_flush_compact(client, table):
    for i in range(40):
        client.set(b"hk%d" % i, b"s", b"v%d" % i)
    table.flush_all()
    table.manual_compact_all()
    scanners = client.get_unordered_scanners(1, ScanOptions(batch_size=8))
    assert sum(1 for sc in scanners for _ in sc) == 40
    assert client.get(b"hk7", b"s") == (OK, b"v7")


def test_non_power_of_two_partition_count_scans_complete(tmp_path):
    # regression: routing is crc64 % count but hash validation is an
    # &-mask — on non-pow2 counts validation must be disabled or scans
    # silently lose records
    t = Table(str(tmp_path / "t6"), partition_count=6)
    try:
        c = PegasusClient(t)
        for i in range(60):
            c.set(b"user_%d" % i, b"s", b"v")
        scanners = c.get_unordered_scanners(2, ScanOptions(batch_size=50))
        assert sum(1 for sc in scanners for _ in sc) == 60
    finally:
        t.close()


def test_scanner_restarts_after_context_loss(client, table):
    for i in range(30):
        client.set(b"scanctx", b"s%02d" % i, b"v%d" % i)
    sc = client.get_scanner(b"scanctx", options=ScanOptions(batch_size=10))
    got = [next(sc) for _ in range(10)]
    # server GCs every context (simulates the 5-minute expiry)
    server = table.resolve(b"scanctx")
    server._scan_cache._contexts.clear()
    got += list(sc)
    assert [sk for _, sk, _ in got] == [b"s%02d" % i for i in range(30)]


def test_expired_records_filtered_everywhere(client, table):
    from pegasus_tpu.base.value_schema import epoch_now
    client.set(b"hk", b"live", b"v", ttl_seconds=5000)
    # write an already-expired record directly through the write service
    server = table.resolve(b"hk")
    from pegasus_tpu.base.key_schema import generate_key
    server.write_service.put(generate_key(b"hk", b"dead"), b"v",
                             epoch_now() - 10, server._next_decree())
    assert client.get(b"hk", b"dead") == (NOT_FOUND, b"")
    err, kvs = client.multi_get(b"hk")
    assert set(kvs) == {b"live"}
    assert client.sortkey_count(b"hk") == (OK, 1)
    got = list(client.get_scanner(b"hk"))
    assert [sk for _, sk, _ in got] == [b"live"]


def test_empty_hashkey_routing_consistent_with_validation(client, table):
    """ADVICE r1 (high): empty-hashkey records must route by the same hash
    the scan/compaction validation predicates use (pegasus_key_hash ==
    crc64 of the sortkey when the hashkey is empty), or they are hidden
    from validated scans and deleted by the next manual compaction."""
    n = 32
    for i in range(n):
        assert client.set(b"", b"esk_%04d" % i, b"v%d" % i) == OK
    # point reads see them
    for i in range(n):
        assert client.get(b"", b"esk_%04d" % i) == (OK, b"v%d" % i)
    # they scatter across partitions (crc64 of the sortkey), not all on p0
    touched = {p.pidx for p in table.all_partitions()
               if p.engine.last_committed_decree > 0}
    assert len(touched) > 1
    # validated full scan sees all of them
    scanners = client.get_unordered_scanners(8)
    got = set()
    for sc in scanners:
        for hk, sk, _v in sc:
            if hk == b"":
                got.add(sk)
    assert got == {b"esk_%04d" % i for i in range(n)}
    # manual compaction (partition-hash validation active for pow-2
    # counts) must NOT drop them
    table.flush_all()
    table.manual_compact_all()
    for i in range(n):
        assert client.get(b"", b"esk_%04d" % i) == (OK, b"v%d" % i)
