"""Meta group tests: leader election, state replication, leader-kill
recovery (VERDICT r1 item 8 done-condition: kill the meta leader under
load, the cluster re-elects, and DDL still works).

Parity: meta_service.cpp:384-401 (elect via distributed lock),
meta_service.h:304 (followers forward to leader),
meta_state_service_zookeeper.h:50 (replicated meta state).
"""

import pytest

from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.utils.errors import StorageStatus

OK = int(StorageStatus.OK)


@pytest.fixture
def cluster(tmp_path):
    c = SimCluster(str(tmp_path / "c"), n_nodes=3, n_meta=3)
    yield c
    c.close()


def leaders(cluster):
    return [m.name for m in cluster.metas
            if m.election.is_leader and m.name not in cluster._dead]


def test_single_leader_elected(cluster):
    cluster.step(rounds=2)
    assert len(leaders(cluster)) == 1


def test_state_replicates_to_followers(cluster):
    cluster.create_table("rt", partition_count=4)
    cluster.step(rounds=2)
    for m in cluster.metas:
        assert m.storage.get("/apps/1") is not None, m.name
        app = [a for a in m.state.apps.values() if a.app_name == "rt"]
        assert app and app[0].partition_count == 4, m.name


def test_followers_forward_to_leader(cluster):
    cluster.create_table("fw", partition_count=2)
    c = cluster.client("fw")
    assert c.set(b"k", b"s", b"v") == OK
    # point the client at a FOLLOWER meta only; resolution still works
    follower = next(m.name for m in cluster.metas
                    if not m.election.is_leader)
    c2 = cluster.client("fw", name="via-follower")
    c2.meta_addrs = [follower]
    c2._meta_i = 0
    c2.refresh_config()
    assert c2.partition_count == 2
    assert c2.get(b"k", b"s") == (OK, b"v")


def test_leader_kill_under_load_reelects_and_serves(cluster):
    cluster.create_table("lk", partition_count=4)
    c = cluster.client("lk")
    acked = []
    for i in range(30):
        if c.set(b"k%03d" % i, b"s", b"v%d" % i) == OK:
            acked.append(i)
    old_leader = leaders(cluster)[0]
    cluster.kill(old_leader)
    # clients keep working while the group re-elects (lease ~8s of sim
    # time; pump advances it)
    for i in range(30, 45):
        if c.set(b"k%03d" % i, b"s", b"v%d" % i) == OK:
            acked.append(i)
    cluster.step(rounds=5)
    new = leaders(cluster)
    assert len(new) == 1 and new[0] != old_leader
    # DDL works on the new leader
    cluster.create_table("post_failover", partition_count=2)
    c2 = cluster.client("post_failover")
    assert c2.set(b"x", b"y", b"z") == OK
    # every acked write survived the meta failover
    for i in acked:
        assert c.get(b"k%03d" % i, b"s") == (OK, b"v%d" % i), i
    # replica failover still cured by the NEW leader
    victim = cluster.meta.state.get_partition(c.app_id, 0).primary
    cluster.kill(victim)
    for i in range(45, 55):
        if c.set(b"k%03d" % i, b"s", b"v%d" % i) == OK:
            acked.append(i)
    for i in acked:
        assert c.get(b"k%03d" % i, b"s") == (OK, b"v%d" % i), i


def test_revived_old_leader_steps_down(cluster):
    cluster.create_table("sd", partition_count=2)
    old_leader = leaders(cluster)[0]
    cluster.kill(old_leader)
    cluster.step(rounds=5)
    assert leaders(cluster) and leaders(cluster)[0] != old_leader
    new_leader = leaders(cluster)[0]
    # old leader comes back: sees the higher term, steps down
    cluster.revive(old_leader)
    cluster.step(rounds=4)
    assert leaders(cluster) == [new_leader]
    # and it catches up on state it missed
    cluster.create_table("while_you_were_out", partition_count=2)
    cluster.step(rounds=4)
    old = next(m for m in cluster.metas if m.name == old_leader)
    assert any(a.app_name == "while_you_were_out"
               for a in old.state.apps.values())


def test_partitioned_leader_self_demotes(tmp_path):
    """A leader that loses contact with a majority must drop is_leader
    within the lease window (no split-brain leader-only reads)."""
    from pegasus_tpu.tools.cluster import SimCluster

    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, n_meta=3)
    try:
        cluster.create_table("t", partition_count=2)
        leader = next(m for m in cluster.metas if m.election.is_leader)
        cluster.net.partition(leader.name)
        # let sim time pass beyond the lease; the isolated leader keeps
        # ticking (partition drops messages, not timers)
        for _ in range(12):
            cluster.step()
        assert not leader.election.is_leader
        # and a new leader exists among the connected majority
        alive_leaders = [m for m in cluster.metas
                         if m.name != leader.name
                         and m.election.is_leader]
        assert len(alive_leaders) == 1
        # heal: old leader rejoins as follower of the higher term
        cluster.net.heal(leader.name)
        for _ in range(8):
            cluster.step()
        leaders = [m for m in cluster.metas if m.election.is_leader]
        assert len(leaders) == 1
    finally:
        cluster.close()


def test_one_way_link_loss_no_split_brain(tmp_path):
    """Asymmetric failure: one meta stops RECEIVING the leader's
    heartbeats while the leader still reaches everyone else. The
    isolated member campaigns, but lease-sticky voting denies it a
    majority — at no observed point do two live metas claim leadership."""
    from pegasus_tpu.tools.cluster import SimCluster

    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, n_meta=3)
    try:
        cluster.create_table("t", partition_count=2)
        leader = next(m for m in cluster.metas if m.election.is_leader)
        victim = next(m for m in cluster.metas
                      if not m.election.is_leader)
        cluster.net.set_drop(1.0, src=leader.name, dst=victim.name)
        for _ in range(25):
            cluster.step()
            leaders = [m.name for m in cluster.metas
                       if m.election.is_leader]
            assert len(leaders) <= 1, leaders
            # pre-vote: the victim cannot assemble a majority, so the
            # healthy leader is never dethroned (availability holds,
            # not just safety) and terms do not inflate
            assert leaders == [leader.name], leaders
        assert victim.election.term <= leader.election.term + 1
        # the healthy majority still has a working leader and the
        # cluster still serves writes
        c = cluster.client("t")
        assert c.set(b"k", b"s", b"v") == 0
        assert c.get(b"k", b"s") == (0, b"v")
    finally:
        cluster.close()


def test_flaky_link_does_not_dethrone_leader(tmp_path):
    """Check-quorum: a LOSSY (not fully dead) leader->victim link lets
    the victim's pre-vote reach the leader — a seated leader with fresh
    majority contact must refuse to help depose itself."""
    from pegasus_tpu.tools.cluster import SimCluster

    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, n_meta=3)
    try:
        cluster.create_table("t", partition_count=2)
        leader = next(m for m in cluster.metas if m.election.is_leader)
        victim = next(m for m in cluster.metas
                      if not m.election.is_leader)
        cluster.net.set_drop(0.7, src=leader.name, dst=victim.name)
        for _ in range(40):
            cluster.step()
            leaders = [m.name for m in cluster.metas
                       if m.election.is_leader]
            assert leaders == [leader.name], leaders
    finally:
        cluster.close()
