"""SerialAccessChecker / ThreadAccessChecker (SURVEY §5.2 parity:
utils/thread_access_checker.h — races surface as loud failures)."""

import threading
import time

import pytest

from pegasus_tpu.utils.thread_check import (
    SerialAccessChecker,
    ThreadAccessChecker,
)


def test_serial_checker_allows_reentrancy():
    c = SerialAccessChecker("x")
    with c:
        with c:  # guarded method calling another guarded method
            pass
    with c:  # and a fresh entry after full exit
        pass


def test_serial_checker_detects_concurrency():
    c = SerialAccessChecker("replica 1.0@node0")
    inside = threading.Event()
    release = threading.Event()
    errors = []

    def holder():
        with c:
            inside.set()
            release.wait(5)

    t = threading.Thread(target=holder)
    t.start()
    assert inside.wait(5)
    with pytest.raises(RuntimeError, match="concurrent access"):
        with c:
            pass
    release.set()
    t.join()
    with c:  # usable again after the offender is gone
        pass


def test_thread_checker_pins_first_thread():
    c = ThreadAccessChecker("parser")
    c.check()
    c.check()
    err = []

    def other():
        try:
            c.check()
        except RuntimeError as e:
            err.append(e)

    t = threading.Thread(target=other)
    t.start()
    t.join()
    assert err and "owned by" in str(err[0])


def test_replica_guard_is_wired():
    """A replica's write path really is guarded: entering from a second
    thread while one is inside raises instead of racing."""
    import tempfile

    from pegasus_tpu.replica.replica import Replica

    class _NullTransport:
        def register(self, *a):
            pass

        def send(self, *a, **kw):
            pass

    with tempfile.TemporaryDirectory() as td:
        r = Replica("n0", td, _NullTransport())
        with r._access:
            errs = []

            def intruder():
                try:
                    r.client_write([])
                except RuntimeError as e:
                    errs.append(str(e))

            t = threading.Thread(target=intruder)
            t.start()
            t.join()
        assert errs and "concurrent access" in errs[0]
        r.close()
