"""Test configuration: force CPU with an 8-device virtual mesh.

Two things must happen before any test imports jax functionality:

1. JAX_PLATFORMS=cpu + xla_force_host_platform_device_count=8 so the
   multi-chip sharding paths run on virtual CPU devices.
2. De-register the `axon` TPU-tunnel PJRT plugin, which this image's
   sitecustomize installs at interpreter start. Its get_backend hook
   initializes the tunnel client even when JAX_PLATFORMS=cpu, and that
   dials the (single-tenant) TPU pool — tests must never touch the real
   chip. Removing its backend factory before first backend init keeps the
   whole test session CPU-only.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax
import jax._src.xla_bridge as _xb

# sitecustomize already imported jax with jax_platforms=axon; both the
# config value and the plugin factory must go. This must FAIL LOUDLY if the
# private API moves — silently keeping the axon factory would make the whole
# test session dial the single-tenant TPU pool (observed: >120s hangs).
# (Self-contained copy of pegasus_tpu/utils/cpu_isolation.force_cpu:
# conftest must run before anything imports the package.)
jax.config.update("jax_platforms", "cpu")
# pop ONLY the axon tunnel plugin: popping "tpu" as well would remove it
# from xb.known_platforms() and break importing pallas' TPU lowerings
_xb._backend_factories.pop("axon", None)
# prove the isolation actually holds: backend init must yield cpu devices
# only (this would hang/fail loudly if the tunnel were still reachable)
_devs = {d.platform for d in jax.devices()}
if _devs != {"cpu"}:
    raise RuntimeError(
        f"conftest failed to isolate tests from the TPU tunnel: {_devs}")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_compaction_governor():
    """The compaction governor is a process singleton (one per node in
    real deployments); in-process sim clusters share it, so a cluster
    stagger grant issued in one test must not gate env-triggered
    compactions in the next."""
    yield
    try:
        from pegasus_tpu.storage.compact_governor import GOVERNOR
    except Exception:  # noqa: BLE001 - package not imported by this test
        return
    GOVERNOR._grant = None
    GOVERNOR._heavy_waiting = False
    GOVERNOR.heavy_running = 0
    GOVERNOR._throttle_mbps = 0.0
    GOVERNOR._engaged_at_mbps = 0.0
    GOVERNOR._pressure_last = None


@pytest.fixture(autouse=True)
def _reset_tenant_registry():
    """The tenant QoS registry is a process singleton too; a SimCluster
    pins its governor clock to the (dead, frozen) sim loop and a test's
    tenant budgets / brownout verdicts would leak into the next test."""
    yield
    try:
        from pegasus_tpu.server.tenancy import TENANTS
    except Exception:  # noqa: BLE001 - package not imported by this test
        return
    TENANTS.reset()
