"""Filtered-read layer tests: per-SSTable bloom filters (build /
persist / probe / legacy degrade), batched probe pruning on the
point-read path, the node row cache (admission, write-through and
publish invalidation, byte cap), and the block-cache LRU fix.

The load-bearing regressions: a bloom may never produce a FALSE
NEGATIVE (results must stay byte-identical to the unfiltered path),
and the row cache may never serve a value a completed write replaced.
"""

import threading

import numpy as np
import pytest

from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.server import PartitionServer
from pegasus_tpu.server.row_cache import ROW_CACHE, RowCache
from pegasus_tpu.storage.bloom import BloomFilter
from pegasus_tpu.storage.lsm import LSMStore
from pegasus_tpu.storage.sstable import SSTable, SSTableWriter
from pegasus_tpu.utils.errors import StorageStatus
from pegasus_tpu.utils.flags import FLAGS

OK = int(StorageStatus.OK)
NOT_FOUND = int(StorageStatus.NOT_FOUND)


@pytest.fixture
def server(tmp_path):
    s = PartitionServer(str(tmp_path / "p0"))
    yield s
    s.close()


@pytest.fixture
def no_row_cache():
    old = FLAGS.get("pegasus.server", "row_cache_bytes")
    FLAGS.set("pegasus.server", "row_cache_bytes", 0)
    yield
    FLAGS.set("pegasus.server", "row_cache_bytes", old)


def _write_sst(path, n, tag=b"k"):
    w = SSTableWriter(str(path))
    for i in range(n):
        w.add(tag + b"%08d" % i, b"v%d" % i, 0)
    w.finish()
    return SSTable(str(path))


# ---- bloom filter core ------------------------------------------------


def test_bloom_roundtrip_and_fp_rate(tmp_path):
    """Persisted filter reloads with the run; every present key passes
    (no false negatives, ever); absent-key FP rate under a bound that
    ~10 bits/key comfortably meets (theory ~0.8%)."""
    t = _write_sst(tmp_path / "a.sst", 5000)
    assert t.bloom is not None
    for i in range(0, 5000, 17):
        assert t.bloom.may_contain(b"k%08d" % i)
    absent = [b"x%08d" % i for i in range(4000)]
    fps = sum(t.bloom.may_contain(k) for k in absent)
    assert fps / len(absent) < 0.03
    # the vectorized batch probe agrees with the scalar probe
    from pegasus_tpu.ops.predicates import bloom_key_hashes, bloom_probe_rows

    sample = [b"k%08d" % i for i in range(0, 200, 7)] + absent[:200]
    hs = bloom_key_hashes(sample)
    batch = bloom_probe_rows(t.bloom, hs)
    scalar = np.array([t.bloom.may_contain(k) for k in sample])
    assert (batch == scalar).all()
    t.close()


def test_multi_probe_matches_scalar():
    """The one-call (keys x filters) matrix — native when built, scalar
    fallback otherwise — must agree cell-for-cell with per-filter
    scalar probes."""
    from pegasus_tpu.storage.bloom import MultiProbe

    rng = np.random.default_rng(3)
    filters = []
    for t in range(5):
        hs = rng.integers(1, 2**63, size=200 + 37 * t).astype(np.uint64)
        filters.append(BloomFilter.build(hs, 10))
    mp = MultiProbe(filters)
    probes = rng.integers(1, 2**63, size=64).astype(np.uint64)
    mat = mp.probe(probes)
    assert len(mat) == 64 * 5
    for i, h in enumerate(probes):
        for t, f in enumerate(filters):
            assert mat[i * 5 + t] == f.may_contain_hash(int(h))
    # fallback path agrees with whatever path mp took
    mp2 = MultiProbe(filters)
    mp2._native = None
    assert mp2.probe(probes) == mat


def test_bloom_bytes_roundtrip():
    hashes = np.arange(1, 1001, dtype=np.uint64) * np.uint64(0x9E3779B9)
    bf = BloomFilter.build(hashes, 10)
    bf2 = BloomFilter.from_bytes(bf.to_bytes(), bf.m, bf.k)
    assert (bf2.may_contain_hashes(hashes)).all()
    assert BloomFilter.from_bytes(bf.to_bytes()[:-1], bf.m, bf.k) is None


def test_legacy_sst_without_filter_still_readable(tmp_path):
    """Files written with filters off (pre-existing data) load with
    bloom=None and serve exactly as before; a store mixing filtered and
    filterless runs answers correctly for both."""
    FLAGS.set("pegasus.server", "bloom_bits_per_key", 0)
    try:
        legacy = _write_sst(tmp_path / "legacy.sst", 100)
    finally:
        FLAGS.set("pegasus.server", "bloom_bits_per_key", 10)
    assert legacy.bloom is None
    assert legacy.may_contain(b"anything")  # filterless: always maybe
    assert legacy.get(b"k%08d" % 3) == (b"v3", 0)
    legacy.close()

    store = LSMStore(str(tmp_path / "mixed"))
    FLAGS.set("pegasus.server", "bloom_bits_per_key", 0)
    try:
        for i in range(50):
            store.put(b"old%04d" % i, b"ov%d" % i)
        store.flush()
    finally:
        FLAGS.set("pegasus.server", "bloom_bits_per_key", 10)
    for i in range(50):
        store.put(b"new%04d" % i, b"nv%d" % i)
    store.flush()
    assert store.l0[0].bloom is not None and store.l0[1].bloom is None
    for i in range(50):
        assert store.get(b"old%04d" % i) == (b"ov%d" % i, 0)
        assert store.get(b"new%04d" % i) == (b"nv%d" % i, 0)
        assert store.get(b"abs%04d" % i) is None
    store.close()


def test_bloom_built_for_flush_compact_and_bulk_outputs(server):
    """Acceptance: flush, merge-compaction, and bulk block-level
    compaction outputs all carry filters."""
    for i in range(600):
        server.on_put(generate_key(b"hk%04d" % i, b"s"), b"v%d" % i)
    server.flush()
    lsm = server.engine.lsm
    assert all(t.bloom is not None for t in lsm.l0)
    server.manual_compact()  # merge path (overlay present at snapshot)
    assert lsm.l1_runs and all(r.bloom is not None for r in lsm.l1_runs)
    assert lsm.bulk_compact_eligible()
    server.manual_compact()  # bulk block-level rewrite path
    assert lsm.l1_runs and all(r.bloom is not None for r in lsm.l1_runs)
    # filters answer for the compacted keys
    assert all(r.get(b"absent") is None for r in lsm.l1_runs)
    err, v = server.on_get(generate_key(b"hk0007", b"s"))
    assert (err, v) == (OK, b"v7")


def test_batched_identity_filtered_vs_unfiltered(server, no_row_cache):
    """The whole point of a bloom layer: byte-identical results, fewer
    block probes. Compare the batched path's answers with probing on
    vs off over hits, misses, and deep-L0 state."""
    for i in range(200):
        server.on_put(generate_key(b"hk%04d" % i, b"s"), b"base-%d" % i)
    server.flush()
    server.manual_compact()
    # deep L0: three overlay flushes interleaved across the keyspace
    for gen in range(3):
        for i in range(gen, 200, 50):
            server.on_put(generate_key(b"hk%04d" % i, b"x%d" % gen),
                          b"l0-%d-%d" % (gen, i))
        server.flush()
    ops = []
    for i in range(0, 300, 3):  # past 200: misses
        ops.append(("get", generate_key(b"hk%04d" % i, b"s"), None))
        ops.append(("get", generate_key(b"hk%04d" % i, b"x1"), None))
    # indexed runs answer through the perfect-hash index (which prunes
    # AND locates); filter-only runs keep the bloom — either way the
    # sidecar layer must have pruned probes
    useful0 = server._bloom_useful.value() + server._phash_useful.value()
    on = server.on_point_read_batch(list(ops))
    assert server._bloom_useful.value() \
        + server._phash_useful.value() > useful0  # sidecars did work
    FLAGS.set("pegasus.server", "bloom_probe", False)
    try:
        server._point_cache = None  # drop locations learned with filters
        off = server.on_point_read_batch(list(ops))
    finally:
        FLAGS.set("pegasus.server", "bloom_probe", True)
    assert on == off
    # solo path agrees too
    for (op, key, _ph), r in zip(ops, on):
        assert server.on_get(key) == r


def test_l0_fence_short_circuit(tmp_path, no_row_cache):
    """Out-of-range L0 tables cost a compare, not a block lookup."""
    store = LSMStore(str(tmp_path / "s"))
    for i in range(50):
        store.put(b"aa%04d" % i, b"v")
    store.flush()
    calls = []
    orig = store.l0[0].get
    store.l0[0].get = lambda k, **kw: calls.append(k) or orig(k, **kw)
    assert store.get(b"zz0001") is None  # above the fence
    assert store.get(b"a") is None       # below the fence
    assert not calls
    assert store.get(b"aa0001") == (b"v", 0)
    assert calls == [b"aa0001"]
    store.close()


# ---- block cache LRU --------------------------------------------------


def test_block_cache_true_lru(tmp_path):
    """A hit refreshes recency: the old FIFO popped insertion order, so
    a hot block died to any cold streak."""
    old_codec = FLAGS.get("pegasus.storage", "block_codec")
    FLAGS.set("pegasus.storage", "block_codec", "none")
    try:
        w = SSTableWriter(str(tmp_path / "t.sst"), block_capacity=4)
        for i in range(16):  # 4 blocks of 4
            w.add(b"k%04d" % i, b"v", 0)
        w.finish()
    finally:
        FLAGS.set("pegasus.storage", "block_codec", old_codec)
    # learn one block's cache charge, then budget exactly two blocks
    t = SSTable(str(tmp_path / "t.sst"))
    t.read_block(0)
    one = t._cache[0][1]
    t.close()
    t = SSTable(str(tmp_path / "t.sst"), cache_bytes=2 * one + 16)
    t.read_block(0)
    t.read_block(1)
    t.read_block(0)   # refresh block 0
    t.read_block(2)   # must evict block 1, NOT block 0
    assert set(t._cache) == {0, 2}
    t.close()


# ---- row cache --------------------------------------------------------


def test_row_cache_serves_identical_and_counts(server):
    key = generate_key(b"hot", b"s")
    server.on_put(key, b"payload")
    server.flush()
    server.manual_compact()
    solo = server.on_get(key)
    h0 = server._row_cache_hits.value()
    for _ in range(4):  # touch 1 counts, touch 2 admits, then hits
        assert server.on_point_read_batch([("get", key, None)]) == [solo]
    assert server._row_cache_hits.value() > h0
    assert ROW_CACHE.stats()["entries"] >= 1


def test_row_cache_write_invalidation(server):
    key = generate_key(b"w", b"s")
    server.on_put(key, b"v1")
    server.flush()
    server.manual_compact()
    for _ in range(3):
        server.on_point_read_batch([("get", key, None)])
    assert server.on_point_read_batch([("get", key, None)]) == [(OK, b"v1")]
    server.on_put(key, b"v2")  # write-through invalidation
    assert server.on_point_read_batch([("get", key, None)]) == [(OK, b"v2")]
    assert server.on_get(key) == (OK, b"v2")
    server.on_remove(key)
    assert server.on_point_read_batch([("get", key, None)]) == \
        [(NOT_FOUND, b"")]


def test_row_cache_publish_and_flush_invalidation(server):
    key = generate_key(b"p", b"s")
    server.on_put(key, b"v1")
    server.flush()
    server.manual_compact()
    for _ in range(3):
        server.on_point_read_batch([("get", key, None)])
    server.on_put(key, b"v2")
    server.flush()            # generation bump orphans the old entry
    server.manual_compact()   # publish drops this gid wholesale
    assert server.on_point_read_batch([("get", key, None)]) == [(OK, b"v2")]


def test_row_cache_no_stale_under_concurrent_writes(server):
    """Monotonic-read check: a writer advances a counter value while a
    reader hammers the batched path; an answer may lag the in-flight
    write but may NEVER go backwards (a backwards value = a stale cache
    serve after an acked overwrite)."""
    key = generate_key(b"race", b"s")
    server.on_put(key, b"%08d" % 0)
    server.flush()
    server.manual_compact()
    stop = threading.Event()
    errors = []

    def writer():
        i = 0
        while not stop.is_set():
            i += 1
            server.on_put(key, b"%08d" % i)

    def reader():
        last = 0
        while not stop.is_set():
            err, v = server.on_point_read_batch([("get", key, None)])[0]
            if err != OK:
                errors.append(("err", err))
                return
            cur = int(v)
            if cur < last:
                errors.append(("stale", cur, last))
                return
            last = cur

    th_w = threading.Thread(target=writer)
    th_r = threading.Thread(target=reader)
    th_w.start()
    th_r.start()
    import time as _t

    _t.sleep(1.0)
    stop.set()
    th_w.join()
    th_r.join()
    assert not errors


def test_row_cache_byte_cap_and_eviction():
    rc = RowCache()
    old = FLAGS.get("pegasus.server", "row_cache_bytes")
    FLAGS.set("pegasus.server", "row_cache_bytes", 2048)
    try:
        gid = (9, 0)
        for i in range(50):
            k = b"k%04d" % i
            assert rc.note_and_check(gid, k) is False  # first touch
            assert rc.note_and_check(gid, k) is True   # second admits
            rc.admit(gid, 1, 1, k, b"v" * 100, 0)
        st = rc.stats()
        assert st["bytes"] <= 2048
        assert 0 < st["entries"] < 50  # evictions happened
    finally:
        FLAGS.set("pegasus.server", "row_cache_bytes", old)


def test_row_cache_disable_frees_resident_bytes():
    """Turning the mutable knob to 0 must free already-admitted rows
    (the knob caps memory, not just serving)."""
    rc = RowCache()
    old = FLAGS.get("pegasus.server", "row_cache_bytes")
    FLAGS.set("pegasus.server", "row_cache_bytes", 1 << 20)
    try:
        gid = (9, 7)
        for i in range(20):
            k = b"d%04d" % i
            rc.note_and_check(gid, k)
            rc.note_and_check(gid, k)
            rc.admit(gid, 1, 1, k, b"v" * 50, 0)
        assert rc.stats()["bytes"] > 0
        FLAGS.set("pegasus.server", "row_cache_bytes", 0)
        assert rc.enabled is False  # the disable path clears
        assert rc.stats()["bytes"] == 0 and rc.stats()["entries"] == 0
    finally:
        FLAGS.set("pegasus.server", "row_cache_bytes", old)


def test_row_cache_gid_index_consistent_after_churn():
    """Per-gid wholesale invalidation drops exactly that partition's
    rows (and survives interleaved admits/evictions/invalidations)."""
    rc = RowCache()
    old = FLAGS.get("pegasus.server", "row_cache_bytes")
    FLAGS.set("pegasus.server", "row_cache_bytes", 4096)
    try:
        for gid in ((1, 0), (1, 1)):
            for i in range(30):
                k = b"g%04d" % i
                rc.note_and_check(gid, k)
                rc.note_and_check(gid, k)
                rc.admit(gid, 1, 1, k, b"v" * 30, 0)
        rc.invalidate((1, 0), 1, 1, [b"g0029"])
        rc.invalidate_gid((1, 0))
        st = rc.stats()
        assert "(1, 0)" not in st["per_gid"]
        assert st["entries"] == sum(
            g["entries"] for g in st["per_gid"].values())
        rc.invalidate_gid((1, 1))
        assert rc.stats()["entries"] == 0
        assert rc.stats()["bytes"] == 0
    finally:
        FLAGS.set("pegasus.server", "row_cache_bytes", old)


def test_row_cache_admission_epoch_guard():
    """An invalidation between the observed epoch and the admit voids
    the admission — the populate race can never cache a stale row."""
    rc = RowCache()
    gid = (9, 1)
    epoch = rc.epoch(gid)
    rc.invalidate(gid, 1, 1, [b"k"])  # concurrent write lands
    rc.admit(gid, 1, 1, b"k", b"stale", 0, epoch=epoch)
    assert rc.get(gid, 1, 1, b"k") is None


def test_row_cache_disabled_window_write_voids_admission():
    """A write landing while the knob is toggled OFF must still void a
    plan that observed the enabled cache — even for a gid that was
    never invalidated before (implicit epoch 0)."""
    rc = RowCache()
    old = FLAGS.get("pegasus.server", "row_cache_bytes")
    FLAGS.set("pegasus.server", "row_cache_bytes", 1 << 20)
    try:
        gid = (9, 3)
        epoch = rc.epoch(gid)  # plan starts against the enabled cache
        FLAGS.set("pegasus.server", "row_cache_bytes", 0)
        rc.invalidate(gid, 1, 1, [b"k"])  # write in the disabled window
        FLAGS.set("pegasus.server", "row_cache_bytes", 1 << 20)
        rc.admit(gid, 1, 1, b"k", b"stale", 0, epoch=epoch)
        assert rc.get(gid, 1, 1, b"k") is None
    finally:
        FLAGS.set("pegasus.server", "row_cache_bytes", old)


def test_row_cache_hotkey_fast_admit(server):
    """A FINISHED hotkey detection fast-admits its hashkey on first
    touch (no repeat gate)."""
    from pegasus_tpu.server.hotkey import HotkeyState

    key = generate_key(b"viral", b"s")
    server.on_put(key, b"v")
    server.flush()
    server.manual_compact()
    hc = server.hotkey_collectors["read"]
    hc.state = HotkeyState.FINISHED
    hc.result = b"viral"
    try:
        server.on_point_read_batch([("get", key, None)])  # single touch
        assert ROW_CACHE.get((server.app_id, server.pidx),
                             server.engine.lsm.store_uid,
                             server.engine.lsm.generation, key) is not None
    finally:
        hc.state = HotkeyState.STOPPED
        hc.result = None


# ---- shell observability ----------------------------------------------


def test_shell_storage_stats(tmp_path, capsys):
    import json

    from pegasus_tpu.tools.shell import main as shell_main

    root = str(tmp_path / "box")
    assert shell_main(["--root", root, "create_app", "demo",
                       "-p", "2"]) == 0
    for i in range(20):
        assert shell_main(["--root", root, "set", "demo",
                           "hk%d" % i, "sk", "v%d" % i]) == 0
    assert shell_main(["--root", root, "flush", "demo"]) == 0
    capsys.readouterr()
    assert shell_main(["--root", root, "storage_stats", "demo"]) == 0
    stats = json.loads(capsys.readouterr().out)
    assert len(stats["partitions"]) == 2
    assert all(p["runs_with_bloom"] >= 1 for p in stats["partitions"]
               if p["l0_tables"] + p["l1_runs"] > 0)
    assert "bloom_useful_count" in stats["storage"] or stats["storage"]
    assert "capacity" in stats["row_cache"]


# ---- crc64_rows (the probe hash kernel) -------------------------------


def test_crc64_rows_matches_scalar():
    from pegasus_tpu.base.crc import crc64, crc64_batch, crc64_rows

    keys = [b"\x00\x04hashsort%03d" % i for i in range(40)]
    w = max(len(k) for k in keys)
    mat = np.zeros((len(keys), w), dtype=np.uint8)
    lens = np.zeros(len(keys), dtype=np.int64)
    for i, k in enumerate(keys):
        mat[i, :len(k)] = np.frombuffer(k, dtype=np.uint8)
        lens[i] = len(k)
    rows = crc64_rows(mat, lens)
    assert (rows == crc64_batch(mat, lens)).all()
    assert (rows == np.array([crc64(k) for k in keys],
                             dtype=np.uint64)).all()
