"""Edge paths of the native batch scan assembly (page.serve_batch):
byte-budget truncation (state 2) and arena-capacity overflow (state 3
-> per-request Python re-serve). These are the fallback seams the
serving fast path relies on under pathological values."""

import numpy as np
import pytest

from pegasus_tpu import native
from pegasus_tpu.server.page import plan_geometry, serve_batch
from pegasus_tpu.storage.sstable import SSTable, SSTableWriter


@pytest.fixture
def table(tmp_path):
    w = SSTableWriter(str(tmp_path / "t.sst"))
    for i in range(100):
        # 2-byte length prefix + hashkey + sortkey, 50-byte values
        key = b"\x00\x02hk" + b"s%03d" % i
        w.add(key, b"v" * 50, 0)
    w.finish()
    t = SSTable(str(tmp_path / "t.sst"))
    yield t
    t.close()


def _window(t):
    blk = t.read_block(0)
    ckey = (t.path, t.blocks[0].offset)
    plan = [(ckey, blk, 0, blk.count)]
    masks = {ckey: np.ones(blk.count, dtype=bool)}
    return plan, masks, {ckey: (t, t.blocks[0], blk)}


def test_serve_batch_byte_budget_truncates(table):
    if native.scan_serve_fn() is None:
        pytest.skip("no native toolchain")
    plan, masks, unique = _window(table)
    win = (plan, 100, False, False, masks, plan_geometry(plan))
    # each row is ~9 key bytes + 50 value bytes; a 200-byte budget fits
    # ~3 rows (the first row always lands: forward progress)
    (res,) = serve_batch([win], unique, 200, 0)
    page, size, last_key, truncated = res
    assert truncated
    assert 1 <= len(page) <= 4
    assert size <= 200 + 59  # budget + at most one overshoot row
    assert last_key == page.key_at(len(page) - 1)


def test_serve_batch_row_count_and_exhaustion(table):
    if native.scan_serve_fn() is None:
        pytest.skip("no native toolchain")
    plan, masks, unique = _window(table)
    win = (plan, 7, False, False, masks, plan_geometry(plan))
    (res,) = serve_batch([win], unique, 1 << 20, 0)
    page, _size, last_key, truncated = res
    assert len(page) == 7 and not truncated
    assert page.key_at(0) == b"\x00\x02hks000"
    # want beyond the table: exhausted, not truncated
    win = (plan, 1000, False, False, masks, plan_geometry(plan))
    (res,) = serve_batch([win], unique, 1 << 20, 0)
    page, _s, _lk, truncated = res
    assert len(page) == 100 and not truncated


def test_serve_batch_arena_overflow_returns_none(table):
    """A row that cannot fit the arena (forged tiny geometry) must
    surface as None (state 3) so the caller re-serves in Python — not
    as a silently truncated page."""
    if native.scan_serve_fn() is None:
        pytest.skip("no native toolchain")
    plan, masks, unique = _window(table)
    # lie about the span so the value arena is far too small for row 1
    geom = (100, 10, 32)
    win = (plan, 100, False, False, masks, geom)
    (res,) = serve_batch([win], unique, 1 << 20, 0)
    assert res is None


def test_serve_batch_no_value_and_ets(table):
    if native.scan_serve_fn() is None:
        pytest.skip("no native toolchain")
    plan, masks, unique = _window(table)
    win = (plan, 5, True, True, masks, plan_geometry(plan))
    (res,) = serve_batch([win], unique, 1 << 20, 0)
    page, size, _lk, _tr = res
    assert len(page) == 5
    assert all(page.value_at(i) == b"" for i in range(5))
    assert page.ets_at(0) == 0
    assert size == sum(len(page.key_at(i)) for i in range(5))


def test_serve_batch_cached_path_matches_legacy(table):
    """The serving path passes 8-tuple windows (cached plan_nat +
    live_ptrs) through the fully vectorized bookkeeping; ad-hoc callers
    pass 6-tuples through the per-window loop. Both must produce
    byte-identical pages under mixed wants / no_value / ets flavors."""
    if native.scan_serve_fn() is None:
        pytest.skip("no native toolchain")
    from pegasus_tpu.server.page import plan_nat

    plan, masks, unique = _window(table)
    # a second window with a PARTIAL mask and different want
    masks2 = {k: v.copy() for k, v in masks.items()}
    next(iter(masks2.values()))[::3] = False
    nat = plan_nat(plan)
    live_ptrs = {k: v.ctypes.data for k, v in masks.items()}
    live_ptrs2 = {k: v.ctypes.data for k, v in masks2.items()}
    geom = plan_geometry(plan)

    legacy = serve_batch([
        (plan, 7, False, False, masks, geom),
        (plan, 50, False, True, masks2, geom),
        (plan, 100, True, False, masks, geom),
    ], unique, 1 << 20, 0)
    cached = serve_batch([
        (plan, 7, False, False, masks, geom, nat, live_ptrs),
        (plan, 50, False, True, masks2, geom, nat, live_ptrs2),
        (plan, 100, True, False, masks, geom, nat, live_ptrs),
    ], unique, 1 << 20, 0)
    assert legacy is not None and cached is not None
    for lg, ca in zip(legacy, cached):
        pl, sl, kl, tl = lg
        pc, sc, kc, tc = ca
        assert (sl, kl, tl) == (sc, kc, tc)
        assert (pl.key_offs, pl.key_blob, pl.val_offs, pl.val_blob,
                pl.ets) == (pc.key_offs, pc.key_blob, pc.val_offs,
                            pc.val_blob, pc.ets)
    assert len(legacy[0][0]) == 7
    assert all(legacy[2][0].value_at(i) == b""
               for i in range(len(legacy[2][0])))
