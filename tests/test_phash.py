"""Perfect-hash two-level SST index tests (storage/phash.py).

The load-bearing regressions: the index may never produce a WRONG
location (a fingerprint collision must read as "absent", never as
another row's value), probing through it must stay byte-identical to
the bisect path across every block codec and every store mix, a miss
on an indexed run must touch ZERO blocks, construction failure must
degrade (deterministically) to bloom+bisect rather than error, and a
corrupt or version-unknown index must be refused/flagged loudly.
"""

import json
import os
import struct

import numpy as np
import pytest

from pegasus_tpu.base.crc import crc32, crc64
from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.server import PartitionServer
from pegasus_tpu.storage.lsm import LSMStore
from pegasus_tpu.storage.phash import (
    ABSENT,
    PHASH_BUILD_FAIL,
    PHASH_USEFUL,
    PHashIndex,
    PHashMultiProbe,
    _build_once_py,
    _geometry,
)
from pegasus_tpu.storage.sstable import (
    _BLOCK_CACHE_HIT,
    _BLOCK_CACHE_MISS,
    FOOTER,
    SSTable,
    SSTableWriter,
)
from pegasus_tpu.utils.errors import StorageCorruptionError, StorageStatus
from pegasus_tpu.utils.flags import FLAGS

OK = int(StorageStatus.OK)
NOT_FOUND = int(StorageStatus.NOT_FOUND)


@pytest.fixture
def no_row_cache():
    old = FLAGS.get("pegasus.server", "row_cache_bytes")
    FLAGS.set("pegasus.server", "row_cache_bytes", 0)
    yield
    FLAGS.set("pegasus.server", "row_cache_bytes", old)


@pytest.fixture
def codec_flag():
    old = FLAGS.get("pegasus.storage", "block_codec")
    yield
    FLAGS.set("pegasus.storage", "block_codec", old)


def _write_sst(path, keys_vals, block_capacity=64):
    w = SSTableWriter(str(path), block_capacity=block_capacity)
    for k, v, ets, tomb in keys_vals:
        w.add(k, v, ets, tombstone=tomb)
    w.finish()
    return SSTable(str(path))


def _key_set(n, with_odd_rows=True):
    """Sorted key set spanning the interesting shapes: normal
    hashkey+sortkey rows, empty-hashkey rows (dcz2 hash overflow), and
    malformed short/bad-header rows (codec sentinel rows) — slot
    numbering must survive all of them."""
    keys = set()
    for i in range(n):
        keys.add(b"\x00\x04hk%02d" % (i % 23) + b"s%06d" % i)
    if with_odd_rows:
        keys.add(b"\x00")                       # malformed: 1 byte
        keys.add(b"\x00\x00nosortkeyhash")      # empty hashkey
        keys.add(b"\x7f\xffclaims-huge-hashkey")  # header > body
    out = sorted(keys)
    return [(k, b"v-%d" % i, 0, i % 89 == 0) for i, k in enumerate(out)]


# ---- index core -------------------------------------------------------


def test_phash_build_probe_roundtrip():
    """Every present hash probes to its EXACT packed loc (scalar and
    vectorized agree); absent hashes answer ABSENT at ~the 10-bit
    fingerprint rate — and an fp collision can only ever point at a
    real row (the caller's key-verify rejects it)."""
    rng = np.random.default_rng(11)
    n = 30_000
    hashes = rng.integers(1, 2**63, size=n, dtype=np.uint64)
    counts = [1024] * (n // 1024) + [n % 1024]
    ix = PHashIndex.build(hashes.astype(np.uint64), counts)
    assert ix is not None
    # ~5.2 resident bytes/key at the default geometry
    assert ix.mem_bytes() / n < 6.0
    out = ix.probe_hashes(hashes.astype(np.uint64))
    starts = np.zeros(len(counts) + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    bids = np.repeat(np.arange(len(counts)), counts)
    slots = np.arange(n) - np.repeat(starts[:-1], counts)
    locs = ((bids << ix.slot_bits) | slots).astype(np.uint32)
    assert (out == locs).all()
    for i in range(0, n, 2999):
        assert ix.lookup_hash(int(hashes[i])) == int(locs[i])
    absent = rng.integers(1, 2**63, size=10_000, dtype=np.uint64)
    aout = ix.probe_hashes(absent.astype(np.uint64))
    assert float((aout != ABSENT).mean()) < 0.01
    for i in range(0, 10_000, 997):
        assert ix.lookup_hash(int(absent[i])) == \
            (int(aout[i]) if aout[i] != ABSENT else -1)


def test_phash_native_and_python_builds_identical():
    """The Python CHD fallback and the native kernel are the same
    on-disk format: identical slots/disp for identical inputs (the
    mixer/geometry/bucket-order are format, not implementation)."""
    from pegasus_tpu import native

    if native.phash_build_fn() is None:
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(5)
    n = 5000
    hashes = rng.integers(1, 2**63, size=n, dtype=np.uint64).astype(
        np.uint64)
    ix = PHashIndex.build(hashes, [512] * 9 + [n - 9 * 512])
    assert ix is not None
    ts, nb = _geometry(n)
    bids = np.repeat(np.arange(10, dtype=np.int64),
                     [512] * 9 + [n - 9 * 512])
    starts = np.zeros(11, dtype=np.int64)
    np.cumsum([512] * 9 + [n - 9 * 512], out=starts[1:])
    slots = np.arange(n, dtype=np.int64) - np.repeat(starts[:-1],
                                                     [512] * 9
                                                     + [n - 9 * 512])
    locs = ((bids << ix.slot_bits) | slots).astype(np.uint32)
    res = _build_once_py(hashes, locs, ix.seed, ts, nb)
    assert res is not None
    slots_py, disp_py = res
    assert (slots_py == ix.slots).all()
    assert (disp_py == ix.disp).all()


def test_multi_probe_matches_scalar_and_fallback():
    rng = np.random.default_rng(3)
    ixs = []
    for t in range(4):
        hs = rng.integers(1, 2**63, size=700 + 131 * t,
                          dtype=np.uint64).astype(np.uint64)
        ix = PHashIndex.build(hs, [256] * (len(hs) // 256)
                              + [len(hs) % 256])
        assert ix is not None
        ixs.append((ix, hs))
    mp = PHashMultiProbe([ix for ix, _ in ixs])
    probes = np.concatenate(
        [hs[:16] for _ix, hs in ixs]
        + [rng.integers(1, 2**63, size=64,
                        dtype=np.uint64).astype(np.uint64)])
    mat, mask = mp.probe(probes)
    mp2 = PHashMultiProbe([ix for ix, _ in ixs])
    mp2._native = None
    mat2, mask2 = mp2.probe(probes)
    assert bytes(mat2) == bytes(mat) and mask2 == mask
    for i, h in enumerate(probes):
        for t, (ix, _hs) in enumerate(ixs):
            loc = ix.lookup_hash(int(h))
            cell = i * 4 + t
            assert bool(mask[cell]) == (loc >= 0)
            assert int(mat[cell]) == (loc if loc >= 0 else ABSENT)


# ---- SST integration: byte-identity across codecs ---------------------


@pytest.mark.parametrize("codec", ["none", "dcz", "dcz2"])
def test_probe_identical_to_bisect_across_codecs(tmp_path, codec,
                                                 codec_flag):
    """Batched probe == scalar probe == bisect, byte-identical, over
    randomized keys spanning all three block codecs — including
    malformed and empty-hashkey rows (dcz2's hash overflow slots), so
    (block, slot) provably means the same row under every layout."""
    FLAGS.set("pegasus.storage", "block_codec", codec)
    recs = _key_set(1500)
    t = _write_sst(tmp_path / f"{codec}.sst", recs, block_capacity=128)
    assert t.phash is not None and t.bloom is not None
    t.verify_index_consistency()
    present = [k for k, *_ in recs]
    absent = [b"\x00\x04zz%02d" % (i % 9) + b"a%06d" % i
              for i in range(400)]
    sample = present[::7] + absent
    hashes = np.array([crc64(k) for k in sample], dtype=np.uint64)
    # batched locate == scalar locate
    mp = PHashMultiProbe([t.phash])
    mat, mask = mp.probe(hashes)
    for i, k in enumerate(sample):
        loc = t.phash.lookup_hash(int(hashes[i]))
        assert bool(mask[i]) == (loc >= 0)
        assert int(mat[i]) == (loc if loc >= 0 else ABSENT)
    # phash get == bisect get, byte for byte
    for k in sample:
        FLAGS.set("pegasus.server", "phash_probe", True)
        a = t.get(k)
        FLAGS.set("pegasus.server", "phash_probe", False)
        b = t.get(k)
        FLAGS.set("pegasus.server", "phash_probe", True)
        assert a == b, (codec, k)
    # every present key locates to a row holding exactly that key
    for k in present[::13]:
        loc = t.phash.lookup_hash(crc64(k))
        assert loc >= 0
        bi, slot = t.phash.unpack(loc)
        assert t.read_block(bi).key_at(slot) == k
    t.close()


def test_slot_stability_through_compaction_paths(tmp_path, codec_flag,
                                                 no_row_cache):
    """The verbatim-copy and native-subset compaction paths must not
    invalidate stamped indexes: after a bulk rewrite that drops rows
    from a dcz2 store, every output run's fresh phash locates every
    survivor (scrub-verified), and gets stay identical to bisect."""
    FLAGS.set("pegasus.storage", "block_codec", "dcz2")
    store = LSMStore(str(tmp_path / "s"), block_capacity=64,
                     l1_run_capacity=400)
    vals = {}
    for i in range(1200):
        k = b"\x00\x04hk%02d" % (i % 17) + b"s%05d" % i
        store.put(k, b"val-%06d" % i, 0)
        vals[k] = b"val-%06d" % i
    store.flush()
    store.compact()
    assert store.bulk_compact_eligible()
    # drop every 5th row through the encoded-domain subset kernel
    per_block = []
    drop_keys = set()
    for run, idx, _bm in store.bulk_compact_entries():
        enc = run.read_block_encoded(idx)
        blk = enc if enc is not None else run.read_block(idx)
        n = blk.count
        drop = np.zeros(n, dtype=bool)
        drop[::5] = True
        for j in np.flatnonzero(drop):
            drop_keys.add(blk.key_at(int(j)))
        per_block.append((run, idx, blk, drop,
                          np.asarray(blk.expire_ts)))
    store.bulk_compact_rewrite(per_block, meta=None,
                               ttl_may_change=False)
    for run in store.l1_runs:
        assert run.phash is not None
        run.verify_index_consistency()
    for k, v in vals.items():
        expect = None if k in drop_keys else (v, 0)
        FLAGS.set("pegasus.server", "phash_probe", True)
        a = store.get(k)
        FLAGS.set("pegasus.server", "phash_probe", False)
        b = store.get(k)
        FLAGS.set("pegasus.server", "phash_probe", True)
        assert a == b == expect, (k, a, b, expect)
    store.close()


# ---- mixed stores, fallback, format versioning ------------------------


def test_mixed_store_serving(tmp_path, no_row_cache):
    """One LSM mixing a pre-index file (phash build off), a bloom-only
    run (forced build failure), and indexed runs serves byte-identical
    results through the batched plan path and the solo path."""
    server = PartitionServer(str(tmp_path / "p0"))
    vals = {}

    def put_group(tag, n):
        for i in range(n):
            hk, sk = b"%s%02d" % (tag, i % 5), b"s%04d" % i
            server.on_put(generate_key(hk, sk), b"%s-%d" % (tag, i))
            vals[(hk, sk)] = b"%s-%d" % (tag, i)

    put_group(b"pre", 300)
    FLAGS.set("pegasus.server", "phash_index", False)
    server.flush()          # pre-index file: no phash entry at all
    FLAGS.set("pegasus.server", "phash_index", True)
    put_group(b"blm", 300)
    FLAGS.set("pegasus.server", "phash_force_fail", True)
    fails0 = PHASH_BUILD_FAIL.value()
    server.flush()          # bloom-only run (deterministic build fail)
    FLAGS.set("pegasus.server", "phash_force_fail", False)
    assert PHASH_BUILD_FAIL.value() == fails0 + 1
    put_group(b"idx", 300)
    server.flush()          # indexed run
    lsm = server.engine.lsm
    kinds = {(t.bloom is not None, t.phash is not None)
             for t in lsm.l0}
    assert kinds == {(True, False), (True, True)}
    assert sum(1 for t in lsm.l0 if t.phash is None) == 2

    keys = list(vals)
    absent = [(b"pre%02d" % (i % 5), b"s%04dx" % i) for i in range(64)]
    ops = [("get", generate_key(hk, sk), 0) for hk, sk in keys + absent]
    FLAGS.set("pegasus.server", "phash_probe", True)
    server._point_cache = None
    on = server.on_point_read_batch(ops)
    FLAGS.set("pegasus.server", "phash_probe", False)
    server._point_cache = None
    off = server.on_point_read_batch(ops)
    FLAGS.set("pegasus.server", "phash_probe", True)
    assert on == off
    for (hk, sk), r in zip(keys, on):
        assert r == (OK, vals[(hk, sk)])
    for r in on[len(keys):]:
        assert r == (NOT_FOUND, b"")
    # solo path agrees too (engine values carry the encoded header)
    for hk, sk in keys[::31]:
        hit = lsm.get(generate_key(hk, sk))
        assert hit is not None and hit[0].endswith(vals[(hk, sk)])
    server.close()


def test_build_failure_fallback_deterministic(tmp_path):
    """Under the seeded fail point every build fails the same way: the
    run is stamped "no phash" (a counter tick, never an exception),
    keeps its bloom, and serves correctly; the next finish builds."""
    FLAGS.set("pegasus.server", "phash_force_fail", True)
    try:
        fails0 = PHASH_BUILD_FAIL.value()
        t1 = _write_sst(tmp_path / "f1.sst", _key_set(200, False))
        t2 = _write_sst(tmp_path / "f2.sst", _key_set(200, False))
        assert PHASH_BUILD_FAIL.value() == fails0 + 2
        assert t1.phash is None and t2.phash is None
        assert t1.bloom is not None
        k = _key_set(200, False)[3][0]
        assert t1.get(k) == t2.get(k) != None  # noqa: E711
        t1.close(), t2.close()
    finally:
        FLAGS.set("pegasus.server", "phash_force_fail", False)
    t3 = _write_sst(tmp_path / "f3.sst", _key_set(200, False))
    assert t3.phash is not None
    t3.close()


def test_unknown_phash_version_refused_at_open(tmp_path):
    """A file stamping a phash version this build does not know is
    refused at open (never misparsed), exactly like an unknown codec;
    pre-index files (no entry) keep serving."""
    t = _write_sst(tmp_path / "v.sst", _key_set(100, False))
    t.close()
    path = str(tmp_path / "v.sst")
    with open(path, "rb") as f:
        raw = f.read()
    index_offset, index_size, _crc, magic = FOOTER.unpack(
        raw[-FOOTER.size:])
    index = json.loads(raw[index_offset:index_offset + index_size])
    assert index["phash"]["version"] == 1
    index["phash"]["version"] = 99
    blob = json.dumps(index).encode()
    with open(path, "wb") as f:
        f.write(raw[:index_offset] + blob
                + FOOTER.pack(index_offset, len(blob), crc32(blob),
                              magic))
    with pytest.raises(StorageCorruptionError, match="phash"):
        SSTable(path)


def test_scrub_catches_phash_corruption(tmp_path):
    """Planted corruption in the index blob: the structural pass
    (phash-locates-resident-keys) must flag the file — feeding the
    quarantine/re-learn loop — because a silently wrong index is
    NotFound-shaped data loss."""
    t = _write_sst(tmp_path / "c.sst", _key_set(400, False))
    t.verify_index_consistency()  # clean file passes
    t.close()
    path = str(tmp_path / "c.sst")
    with open(path, "rb") as f:
        raw = f.read()
    index_offset, index_size, _crc, _magic = FOOTER.unpack(
        raw[-FOOTER.size:])
    ph = json.loads(raw[index_offset:index_offset + index_size])["phash"]
    with open(path, "r+b") as f:
        f.seek(ph["off"])
        f.write(b"\xab" * ph["size"])  # trash disp + slots wholesale
    t2 = SSTable(path)
    with pytest.raises(StorageCorruptionError, match="phash"):
        t2.verify_index_consistency()
    t2.close()


# ---- the acceptance property: misses touch zero blocks ----------------


def test_miss_on_indexed_run_reads_zero_blocks(tmp_path, no_row_cache):
    """A miss flush against indexed runs (bloom probing OFF, so only
    the phash answers) performs ZERO block reads — asserted on the
    block-cache hit/miss counters, not the bench."""
    server = PartitionServer(str(tmp_path / "p0"))
    for i in range(2000):
        hk, sk = b"hk%03d" % (i % 31), b"s%05d" % i
        server.on_put(generate_key(hk, sk), b"v%d" % i)
    server.flush()
    for i in range(400):  # deep-ish overlay: a second indexed L0 table
        hk, sk = b"hk%03d" % (i % 31), b"t%05d" % i
        server.on_put(generate_key(hk, sk), b"w%d" % i)
    server.flush()
    assert all(t.phash is not None for t in server.engine.lsm.l0)
    FLAGS.set("pegasus.server", "bloom_probe", False)
    try:
        server._point_cache = None
        absent = [("hk%03d" % (i % 31)).encode() for i in range(256)]
        ops = [("get", generate_key(hk, b"zz%05d" % i), 0)
               for i, hk in enumerate(absent)]
        h0, m0 = _BLOCK_CACHE_HIT.value(), _BLOCK_CACHE_MISS.value()
        u0 = PHASH_USEFUL.value()
        res = server.on_point_read_batch(ops)
        assert all(r == (NOT_FOUND, b"") for r in res)
        assert _BLOCK_CACHE_HIT.value() == h0
        assert _BLOCK_CACHE_MISS.value() == m0
        assert PHASH_USEFUL.value() > u0
        assert server._phash_useful.value() > 0
    finally:
        FLAGS.set("pegasus.server", "bloom_probe", True)
    server.close()


def test_solo_path_structure_selection(tmp_path, no_row_cache):
    """The solo path selects sidecars exactly like the batched planner:
    an indexed table answers through the phash ALONE (its bloom is
    never consulted — no double per-pair work), and bloom_probe=False
    really kills the bloom (a suspect filter must not keep pruning
    just because the phash hash was computed)."""
    store = LSMStore(str(tmp_path / "s"), block_capacity=32)
    for i in range(300):
        store.put(b"k%05d" % i, b"v%d" % i)
    store.flush()
    t = store.l0[0]
    assert t.phash is not None and t.bloom is not None

    class _Boom:
        def may_contain_hash(self, h):
            raise AssertionError("bloom consulted")

        def may_contain(self, k):
            raise AssertionError("bloom consulted")

    t.bloom = _Boom()
    # phash on: the bloom must never be touched on an indexed table
    assert store.get(b"k%05d" % 7) == (b"v7", 0)
    assert store.get(b"zz") is None
    # bloom kill switch with phash off: neither structure consulted,
    # the get serves through the bisect
    FLAGS.set("pegasus.server", "phash_probe", False)
    FLAGS.set("pegasus.server", "bloom_probe", False)
    try:
        assert store.get(b"k%05d" % 7) == (b"v7", 0)
        assert store.get(b"zz") is None
    finally:
        FLAGS.set("pegasus.server", "bloom_probe", True)
        FLAGS.set("pegasus.server", "phash_probe", True)
    store.close()


# ---- writer-finish dedupe: every site builds both sidecars ------------


def test_all_writer_finish_sites_build_sidecars(tmp_path, no_row_cache):
    """Flush, merge-compact, and ingest all route through the shared
    sidecar helper: every produced file carries bloom AND phash (the
    bulk-compact site is covered by
    test_slot_stability_through_compaction_paths)."""
    store = LSMStore(str(tmp_path / "s"), block_capacity=32,
                     l1_run_capacity=300)
    for i in range(500):
        store.put(b"k%05d" % i, b"v%d" % i)
    store.flush()                      # site 1: flush
    assert store.l0[0].phash is not None
    assert store.l0[0].bloom is not None
    store.compact()                    # site 2: merge-compact
    assert store.l1_runs and all(
        r.phash is not None and r.bloom is not None
        for r in store.l1_runs)

    def build(dest, meta):             # site 3: ingest
        w = SSTableWriter(dest, meta=meta)
        for i in range(200):
            w.add(b"z%05d" % i, b"in%d" % i)
        w.finish()

    t = store.ingest(build)
    assert t.phash is not None and t.bloom is not None
    # index memory split is visible per table
    im = t.index_memory()
    assert im["phash"] > 0 and im["bloom"] > 0
    assert store.get(b"z%05d" % 7) == (b"in7", 0)
    store.close()
