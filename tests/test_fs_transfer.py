"""Disk management (fs_manager) + remote file transfer tests.

Parity: common/fs_manager.h:115, replica/disk_cleaner.*,
replica_disk_migrator.h, and src/nfs (copy_remote_files feeding LT_APP
learning across hosts).
"""

import os

import pytest

from pegasus_tpu.replica.fs_manager import FsManager
from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.utils.errors import StorageStatus

OK = int(StorageStatus.OK)


def test_fs_manager_placement_and_stats(tmp_path):
    dirs = [str(tmp_path / f"disk{i}") for i in range(3)]
    fs = FsManager(dirs)
    # placement spreads by replica count (created one by one — placement
    # reflects the dirs that exist at decision time)
    homes = []
    for i in range(6):
        h = fs.replica_dir((1, i))
        os.makedirs(h)
        homes.append(h)
    by_disk = {}
    for h in homes:
        by_disk.setdefault(os.path.dirname(h), []).append(h)
    assert all(len(v) == 2 for v in by_disk.values())
    # rescan finds them all
    assert len(fs.scan_replicas()) == 6
    st = fs.stats()
    assert sum(len(d["replicas"]) for d in st) == 6
    assert all(d["disk_total"] > 0 for d in st)


def test_fs_manager_trash_and_clean(tmp_path):
    fs = FsManager([str(tmp_path / "d")])
    rdir = fs.replica_dir((2, 0))
    os.makedirs(rdir)
    open(os.path.join(rdir, "x"), "w").write("data")
    trashed = fs.trash_replica((2, 0))
    assert trashed.endswith(".gar") and os.path.isdir(trashed)
    assert fs.dir_of((2, 0)) is None
    # young trash survives; aged trash is removed
    assert fs.clean_trash(max_age_seconds=3600) == []
    removed = fs.clean_trash(max_age_seconds=0)
    assert len(removed) == 1 and not os.path.exists(trashed)


def test_fs_manager_migration(tmp_path):
    dirs = [str(tmp_path / "a"), str(tmp_path / "b")]
    fs = FsManager(dirs)
    rdir = fs.replica_dir((3, 1))
    os.makedirs(rdir)
    open(os.path.join(rdir, "payload"), "w").write("blob")
    dest = fs.migrate((3, 1), dirs[1])
    assert dest.startswith(dirs[1])
    assert open(os.path.join(dest, "payload")).read() == "blob"
    assert fs.dir_of((3, 1)) == dest
    with pytest.raises(ValueError):
        fs.migrate((3, 1), str(tmp_path / "unmanaged"))


def test_multi_dir_stub_places_and_reboots(tmp_path):
    from pegasus_tpu.replica.stub import ReplicaStub
    from pegasus_tpu.runtime.sim import SimLoop, SimNetwork

    loop = SimLoop()
    net = SimNetwork(loop)
    dirs = [str(tmp_path / "d0"), str(tmp_path / "d1")]
    stub = ReplicaStub("n", dirs, net, clock=lambda: 0.0)
    for pidx in range(4):
        stub._open_replica((1, pidx), 4)
    by_dir = {d: 0 for d in stub.fs.data_dirs}
    for gpid, path in stub.fs.scan_replicas().items():
        by_dir[os.path.dirname(path)] += 1
    assert sorted(by_dir.values()) == [2, 2]
    stub.close()
    # reboot finds replicas on BOTH disks
    net2 = SimNetwork(SimLoop())
    stub2 = ReplicaStub("n", dirs, net2, clock=lambda: 0.0)
    assert len(stub2.replicas) == 4
    stub2.close()


def test_learning_over_file_transfer_no_shared_fs(tmp_path):
    """Force the nfs-analogue path: the learner pretends the primary's
    checkpoint path is on another host, so the LT_APP state travels via
    chunked transfer messages instead of a local copy."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=2)
    try:
        app_id = cluster.create_table("tx", partition_count=1,
                                      replica_count=1)
        c = cluster.client("tx")
        for i in range(200):
            assert c.set(b"t%04d" % i, b"s", b"v%d" % i) == OK
        # flush + GC the primary's log so a fresh learner MUST take the
        # LT_APP (checkpoint) route, then mark every node non-shared-fs
        pc = cluster.meta.state.get_partition(app_id, 0)
        primary = cluster.stubs[pc.primary]
        rep = primary.get_replica((app_id, 0))
        rep.flush_and_gc_log()
        for stub in cluster.stubs.values():
            stub.shared_fs = False
            for r in stub.replicas.values():
                r.shared_fs = False
        # raise the replication level: the guardian adds a learner on the
        # other node, whose catch-up is checkpoint-based
        cluster.meta.state.apps[app_id].max_replica_count = 2
        for _ in range(12):
            cluster.step()
            pc = cluster.meta.state.get_partition(app_id, 0)
            if len(pc.members()) == 2:
                break
        assert len(pc.members()) == 2, pc
        other = [n for n in pc.members() if n != primary.name][0]
        learner = cluster.stubs[other].get_replica((app_id, 0))
        from pegasus_tpu.base.key_schema import generate_key

        for i in (0, 100, 199):
            assert learner.server.on_get(
                generate_key(b"t%04d" % i, b"s")) == (OK, b"v%d" % i)
    finally:
        cluster.close()
