"""Scan pushdown acceptance: server-side value filters + aggregates
must be byte-identical to client-side evaluation over every store shape
(mixed codecs, overlay rows, empty-hashkey overflow rows, TTL expiry
mid-scan), ship O(partitions) aggregate bytes on the wire, survive
context loss without double counting, and reconcile EXPLAIN's cost
vector against the workload profiler's metric deltas."""

import time

import pytest

from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.client.client import PegasusClient, ScanOptions
from pegasus_tpu.client.table import Table
from pegasus_tpu.ops.predicates import (
    FT_MATCH_ANYWHERE,
    FT_MATCH_POSTFIX,
    FT_MATCH_PREFIX,
    host_match_filter,
)
from pegasus_tpu.ops.pushdown import PushdownSpec, value_as_u64
from pegasus_tpu.server.partition_server import PartitionServer
from pegasus_tpu.server.types import (
    GetScannerRequest,
    SCAN_CONTEXT_ID_COMPLETED,
)
from pegasus_tpu.utils.errors import StorageStatus
from pegasus_tpu.utils.flags import FLAGS

OK = int(StorageStatus.OK)


@pytest.fixture
def flags_guard():
    saved = [(sec, name, FLAGS.get(sec, name)) for sec, name in (
        ("pegasus.storage", "block_codec"),
        ("pegasus.server", "scan_pushdown_enabled"),
        ("pegasus.server", "rocksdb_max_iteration_count"),
    )]
    yield
    for sec, name, val in saved:
        FLAGS.set(sec, name, val)


def put(s, hk, sk, v, ttl=0):
    assert s.on_put(generate_key(hk, sk), v, ttl) == OK


def drain(s, req):
    """Page a scan to exhaustion; returns (rows, shipped_bytes, agg)."""
    rows, shipped = [], 0
    resp = s.on_get_scanner(req)
    while True:
        assert resp.error == OK
        shipped += resp.wire_bytes()
        rows.extend((kv.key, kv.value) for kv in resp.kvs)
        if resp.context_id == SCAN_CONTEXT_ID_COMPLETED:
            return rows, shipped, resp.agg
        resp = s.on_scan(resp.context_id)


def vf_req(pat, ft=FT_MATCH_ANYWHERE, agg="", k=0, seed=0, **kw):
    pd = PushdownSpec(value_filter_type=ft, value_filter_pattern=pat,
                      aggregate=agg, k=k, seed=seed)
    return GetScannerRequest(pushdown=pd, **kw)


def build_mixed_store(tmp_path, flags_guard):
    """One partition whose range crosses every storage shape: three SST
    codec generations (none/dcz/dcz2), empty-hashkey overflow rows, and
    an unflushed overlay generation that SHADOWS some base rows."""
    s = PartitionServer(str(tmp_path / "p0"))
    i = 0
    for codec in ("none", "dcz", "dcz2"):
        FLAGS.set("pegasus.storage", "block_codec", codec)
        for _ in range(80):
            v = b"blue-%04d" % i if i % 5 == 0 else b"red-%04d" % i
            put(s, b"hk%02d" % (i % 4), b"s%05d" % i, v)
            i += 1
        # dcz2 groups rows by hashkey hash; empty hashkeys ride its
        # overflow slots — the shape that breaks group-constant paths
        put(s, b"", b"osk%02d" % (i % 7), b"blue-ovf-%d" % i)
        i += 1
        s.engine.flush()
    # overlay generation: newest-wins shadows over flushed base copies,
    # including a value-REJECTED overwrite of a previously-matching row
    put(s, b"hk00", b"s%05d" % 0, b"red-shadowed")     # was blue-0000
    put(s, b"hk01", b"s%05d" % 77, b"blue-promoted")   # was red-0077
    put(s, b"hknew", b"s0", b"blue-overlay-only")
    return s


def client_filtered(s, pat, ft=FT_MATCH_ANYWHERE, **kw):
    rows, shipped, _ = drain(s, GetScannerRequest(**kw))
    return [(k, v) for k, v in rows if host_match_filter(v, ft, pat)], \
        shipped


def test_filter_identity_mixed_codecs(tmp_path, flags_guard):
    s = build_mixed_store(tmp_path, flags_guard)
    try:
        for pat, ft in ((b"blue", FT_MATCH_ANYWHERE),
                        (b"red-", FT_MATCH_PREFIX),
                        (b"77", FT_MATCH_POSTFIX)):
            want, plain_bytes = client_filtered(s, pat, ft, batch_size=17)
            got, push_bytes, _ = drain(
                s, vf_req(pat, ft, batch_size=17))
            assert got == want, (pat, ft)
            assert want, "degenerate fixture: filter matched nothing"
            # the win the pushdown exists for: fewer bytes on the wire
            assert push_bytes < plain_bytes
        # compacted (pure columnar sorted-runs) state must agree too
        s.engine.flush()
        s.engine.manual_compact()
        for pat in (b"blue", b"red"):
            want, _ = client_filtered(s, pat, batch_size=23)
            got, _, _ = drain(s, vf_req(pat, batch_size=23))
            assert got == want
    finally:
        s.close()


def test_aggregates_identity_and_wire_o_partitions(tmp_path, flags_guard):
    s = build_mixed_store(tmp_path, flags_guard)
    try:
        s.engine.flush()
        s.engine.manual_compact()
        want, plain_bytes = client_filtered(s, b"blue")
        rows, agg_bytes, agg = drain(s, vf_req(b"blue", agg="count"))
        assert rows == [], "aggregate replies must carry no rows"
        assert agg["count"] == len(want)
        # O(partitions) wire cost: one tiny partial, not pages of rows
        assert agg_bytes <= 256 < plain_bytes
        _, _, agg = drain(s, vf_req(b"blue", agg="sum"))
        assert agg["total"] == sum(value_as_u64(v)
                                   for _k, v in want) % (1 << 64)
        _, _, agg = drain(s, vf_req(b"blue", agg="top_k", k=3))
        assert agg["items"] == sorted(want)[-3:]
        _, _, s1 = drain(s, vf_req(b"blue", agg="sample", k=5, seed=9))
        _, _, s2 = drain(s, vf_req(b"blue", agg="sample", k=5, seed=9))
        assert s1["items"] == s2["items"] and len(s1["items"]) == 5
        assert set((k, v) for _p, k, v in s1["items"]) <= set(want)
    finally:
        s.close()


def test_paged_aggregate_ships_partial_once(tmp_path, flags_guard):
    s = build_mixed_store(tmp_path, flags_guard)
    try:
        s.engine.flush()
        s.engine.manual_compact()
        want, _ = client_filtered(s, b"blue")
        # a tiny iteration budget forces the aggregate to page; the
        # partial must ride server-side and ship ONLY on the final page
        FLAGS.set("pegasus.server", "rocksdb_max_iteration_count", 40)
        resp = s.on_get_scanner(vf_req(b"blue", agg="count"))
        pages, partials = 0, 0
        while True:
            assert resp.error == OK and resp.pushdown_applied
            assert resp.kvs == []
            pages += 1
            if resp.agg is not None:
                partials += 1
            if resp.context_id == SCAN_CONTEXT_ID_COMPLETED:
                break
            assert resp.agg is None, "partial leaked on a non-final page"
            resp = s.on_scan(resp.context_id)
        assert pages > 1 and partials == 1
        assert resp.agg["count"] == len(want)
    finally:
        s.close()


def test_ttl_expiry_mid_aggregate_and_identity(tmp_path, flags_guard):
    s = PartitionServer(str(tmp_path / "p0"))
    try:
        for i in range(60):
            put(s, b"hk", b"e%03d" % i, b"blue-%d" % i, ttl=3)
        for i in range(60):
            put(s, b"hk", b"k%03d" % i, b"blue-%d" % i)
        s.engine.flush()
        s.engine.manual_compact()
        _, _, agg = drain(s, vf_req(b"blue", agg="count"))
        assert agg["count"] == 120
        FLAGS.set("pegasus.server", "rocksdb_max_iteration_count", 40)
        # page 1 folds while the e* rows are alive; they expire before
        # the remaining pages — the straddling aggregate must count
        # every row at most once and never resurrect an expired row
        resp = s.on_get_scanner(vf_req(b"blue", agg="count"))
        assert resp.error == OK and resp.agg is None
        time.sleep(3.2)
        while resp.context_id != SCAN_CONTEXT_ID_COMPLETED:
            resp = s.on_scan(resp.context_id)
            assert resp.error == OK
        assert 60 <= resp.agg["count"] <= 120
        # steady state after expiry: exact, on both eval arms
        FLAGS.set("pegasus.server", "rocksdb_max_iteration_count", 0)
        want, _ = client_filtered(s, b"blue")
        assert len(want) == 60
        got, _, _ = drain(s, vf_req(b"blue"))
        assert got == want
        _, _, agg = drain(s, vf_req(b"blue", agg="count"))
        assert agg["count"] == 60
    finally:
        s.close()


def test_context_loss_mid_aggregate_never_double_counts(tmp_path,
                                                        flags_guard):
    """The split-fence / failover bounce: a scan context vanishes
    between aggregate pages. The partial is lost WITH the pages it
    folded, and the scanner restarts the partition from its original
    start — the merged count must stay exact, never inflated."""
    table = Table(str(tmp_path), partition_count=1)
    try:
        c = PegasusClient(table)
        for i in range(120):
            v = b"blue-%03d" % i if i % 3 == 0 else b"red-%03d" % i
            assert c.set(b"hk", b"s%04d" % i, v) == 0
        srv = table.partitions[0]
        srv.engine.flush()
        srv.engine.manual_compact()
        FLAGS.set("pegasus.server", "rocksdb_max_iteration_count", 25)
        orig_on_scan = srv.on_scan
        dropped = {"n": 0}

        def bouncing_on_scan(cid):
            if dropped["n"] == 0:
                dropped["n"] += 1
                srv.on_clear_scanner(cid)  # the bounce: context gone
            return orig_on_scan(cid)

        srv.on_scan = bouncing_on_scan
        try:
            sc = c.get_scanner(b"hk", options=ScanOptions(
                value_filter_type=FT_MATCH_ANYWHERE,
                value_filter_pattern=b"blue"))
            assert sc.count() == 40
            assert dropped["n"] == 1, "fixture never exercised the bounce"
        finally:
            srv.on_scan = orig_on_scan
    finally:
        table.close()


def test_soft_fallback_when_pushdown_disabled(tmp_path, flags_guard):
    """scan_pushdown_enabled=False simulates a pre-pushdown server: the
    spec is ignored, pushdown_applied stays False, and clients must
    produce the SAME rows and aggregates by evaluating locally."""
    table = Table(str(tmp_path), partition_count=2)
    try:
        c = PegasusClient(table)
        for i in range(150):
            hk = b"hk%d" % (i % 3)
            v = b"blue-%03d" % i if i % 5 == 0 else b"red-%03d" % i
            assert c.set(hk, b"s%04d" % i, v) == 0
        opts = ScanOptions(value_filter_type=FT_MATCH_ANYWHERE,
                           value_filter_pattern=b"blue")
        with_push = sorted(c.get_scanner(b"hk0", options=opts))
        n_push = c.get_scanner(b"hk0", options=opts).count()
        sum_push = c.get_scanner(b"hk0", options=opts).aggregate("sum")
        FLAGS.set("pegasus.server", "scan_pushdown_enabled", False)
        resp = table.partitions[0].on_get_scanner(
            vf_req(b"blue", one_page=True))
        assert not resp.pushdown_applied and resp.agg is None
        assert sorted(c.get_scanner(b"hk0", options=opts)) == with_push
        assert c.get_scanner(b"hk0", options=opts).count() == n_push
        assert c.get_scanner(b"hk0",
                             options=opts).aggregate("sum") == sum_push
    finally:
        table.close()


def test_batched_scan_multi_mixed_specs(tmp_path, flags_guard):
    """scan_multi with a plain request, a value-filtered request and an
    aggregate request in ONE flush: the coordinator groups by pushdown
    identity, aggregates route to the solo aggregate path, and every
    response matches its own spec."""
    table = Table(str(tmp_path), partition_count=1)
    try:
        c = PegasusClient(table)
        for i in range(90):
            v = b"blue-%03d" % i if i % 3 == 0 else b"red-%03d" % i
            assert c.set(b"hk", b"s%04d" % i, v) == 0
        s = table.partitions[0]
        s.engine.flush()
        s.engine.manual_compact()
        plain = GetScannerRequest(one_page=True, batch_size=1000)
        filt = vf_req(b"blue", one_page=True, batch_size=1000)
        agg = vf_req(b"blue", agg="count", one_page=True)
        resps = c.scan_multi({0: [plain, filt, agg]})[0]
        assert [r.error for r in resps] == [OK] * 3
        assert len(resps[0].kvs) == 90 and not resps[0].pushdown_applied
        assert len(resps[1].kvs) == 30 and resps[1].pushdown_applied
        assert all(b"blue" in kv.value for kv in resps[1].kvs)
        assert resps[2].kvs == [] and resps[2].agg["count"] == 30
        assert resps[2].wire_bytes() <= 256
    finally:
        table.close()


def test_explain_reconciles_with_workload_metrics(tmp_path, flags_guard):
    """EXPLAIN's pushdown stage + cost vector must reconcile with the
    same run's workload-profiler metric deltas (the counters are the
    PerfContext fields' metric twins)."""
    from pegasus_tpu.server import explain as explain_mod

    s = PartitionServer(str(tmp_path / "p0"))
    try:
        for i in range(100):
            v = b"blue-%03d" % i if i % 4 == 0 else b"red-%03d" % i
            put(s, b"hk", b"s%04d" % i, v)
        s.engine.flush()
        s.engine.manual_compact()
        spec = explain_mod.spec_from_words(["scan", "hk", "filter=blue"])
        op, args, ph = explain_mod.op_from_spec(spec)
        pruned0 = s.workload._pushdown_pruned.value()
        ops0 = s.workload._pushdown_ops.value()
        report = explain_mod.explain_op(s, op, args, partition_hash=ph)
        pruned = report["perf"]["pushdown_rows_pruned"]
        assert pruned == 75
        assert report["result"]["rows"] == 25
        assert report["result"]["pushdown_applied"] is True
        assert s.workload._pushdown_pruned.value() - pruned0 == pruned
        assert s.workload._pushdown_ops.value() - ops0 == 1
        stages = [st["stage"] for st in report["stages"]]
        assert "pushdown" in stages
        rendered = explain_mod.render_report(report)
        assert "pushdown" in rendered and "pushdown_rows_pruned" in rendered
        # aggregate explain: agg lands in the result summary
        spec = explain_mod.spec_from_words(
            ["scan", "hk", "filter=blue", "agg=count"])
        op, args, ph = explain_mod.op_from_spec(spec)
        report = explain_mod.explain_op(s, op, args, partition_hash=ph)
        assert report["result"]["agg"]["count"] == 25
        assert report["perf"]["rows_aggregated"] == 25
    finally:
        s.close()


def test_workload_summary_labels_pushdown_mix(tmp_path, flags_guard):
    from pegasus_tpu.server.workload import fold_summaries

    s = PartitionServer(str(tmp_path / "p0"))
    try:
        for i in range(40):
            put(s, b"hk", b"s%03d" % i, b"blue-%d" % i)
        # metric entities are process-global (shared by every 1.0
        # partition this process opened): assert DELTAS, not absolutes
        scan0 = s.workload._scan_ops.value()
        push0 = s.workload._pushdown_ops.value()
        drain(s, GetScannerRequest(batch_size=1000))
        drain(s, vf_req(b"blue", batch_size=1000))
        drain(s, vf_req(b"blue", agg="count"))
        summ = s.workload.summary()
        assert summ["scan_ops"] - scan0 == 3
        assert summ["pushdown_ops"] - push0 == 2
        fold = fold_summaries([summ, summ])
        assert fold["pushdown_ops"] == 2 * summ["pushdown_ops"]
    finally:
        s.close()


def test_metrics_lint_stays_clean():
    from pegasus_tpu.tools.metrics_lint import lint

    assert not [c for c in lint() if "pushdown" in c
                or "rows_aggregated" in c]


def test_spec_check_rejects_malformed(tmp_path, flags_guard):
    with pytest.raises(ValueError):
        PushdownSpec(aggregate="median").check()
    with pytest.raises(ValueError):
        PushdownSpec(aggregate="top_k").check()  # k missing
    with pytest.raises(ValueError):
        PushdownSpec(value_filter_type=99,
                     value_filter_pattern=b"x").check()
    s = PartitionServer(str(tmp_path / "p0"))
    try:
        put(s, b"hk", b"s", b"v")
        with pytest.raises(ValueError):
            s.on_get_scanner(GetScannerRequest(
                pushdown=PushdownSpec(aggregate="median")))
    finally:
        s.close()


def test_aio_scan_all_filter_and_scan_count(tmp_path, flags_guard):
    import asyncio

    from pegasus_tpu.client.aio import AsyncPegasusClient

    table = Table(str(tmp_path), partition_count=1)
    try:
        c = PegasusClient(table)
        for i in range(60):
            v = b"blue-%02d" % i if i % 6 == 0 else b"red-%02d" % i
            assert c.set(b"hk", b"s%03d" % i, v) == 0

        async def run():
            ac = AsyncPegasusClient(c)
            try:
                rows = await ac.scan_all(b"hk", value_filter=b"blue")
                n = await ac.scan_count(b"hk", value_filter=b"blue")
                n_all = await ac.scan_count(b"hk")
                return rows, n, n_all
            finally:
                ac.close()

        rows, n, n_all = asyncio.run(run())
        assert len(rows) == n == 10 and n_all == 60
        assert all(b"blue" in v for _hk, _sk, v in rows)
    finally:
        table.close()


def test_wire_codec_carries_spec_and_tolerates_old_peers():
    """PGT1 regression pin: a PushdownSpec-bearing request and an
    agg-bearing response round-trip the REAL wire codec, and a peer
    built before the trailing fields were added (the compiled native
    client sends the 15-field GetScannerRequest layout) still decodes
    — omitted trailing defaulted fields fill in, anything else raises."""
    import dataclasses
    import struct

    from pegasus_tpu.rpc import message as msg
    from pegasus_tpu.server.types import (
        GetScannerRequest, KeyValue, ScanResponse)

    spec = PushdownSpec(value_filter_type=FT_MATCH_ANYWHERE,
                        value_filter_pattern=b"red", aggregate="count")
    req = GetScannerRequest(start_key=b"a", stop_key=b"z",
                            batch_size=10, pushdown=spec)
    frame = msg.encode_message("c", "s", "read", req)
    _src, _dst, _mt, out = msg.decode_message(frame[12:])
    assert out == req and out.pushdown == spec

    resp = ScanResponse(error=0, kvs=[KeyValue(b"k", b"v")],
                        context_id=-1,
                        agg={"kind": "count", "count": 5},
                        pushdown_applied=True)
    frame = msg.encode_message("s", "c", "read_resp", resp)
    _src, _dst, _mt, out = msg.decode_message(frame[12:])
    assert out == resp

    # hand-roll the pre-pushdown (shorter) field layout
    def old_layout(n_drop):
        body = []
        for s in ("c", "s", "read"):
            msg._enc_value(body, s)
        fields = dataclasses.fields(GetScannerRequest)
        old = GetScannerRequest(start_key=b"a", stop_key=b"z",
                                batch_size=10)
        name = b"GetScannerRequest"
        body.append(b"D" + struct.pack("<I", len(name)))
        body.append(name)
        body.append(struct.pack("<I", len(fields) - n_drop))
        for f in fields[:len(fields) - n_drop]:
            msg._enc_value(body, getattr(old, f.name))
        return b"".join(body), old

    body, old = old_layout(1)
    _src, _dst, _mt, out = msg.decode_message(body)
    assert out == old and out.pushdown is None

    # dropping into the non-defaulted head must still fail loudly —
    # only ADDED-with-default skew is legal (KeyValue.key: no default)
    body = []
    for s in ("c", "s", "read"):
        msg._enc_value(body, s)
    body.append(b"D" + struct.pack("<I", len(b"KeyValue")))
    body.append(b"KeyValue")
    body.append(struct.pack("<I", 0))  # zero of KeyValue's fields
    with pytest.raises(ValueError, match="field count"):
        msg.decode_message(b"".join(body))

    # and a LONGER-than-registry layout (a newer peer) stays loud
    fields = dataclasses.fields(GetScannerRequest)
    body, _old = old_layout(0)
    body = body.replace(struct.pack("<I", len(fields)),
                        struct.pack("<I", len(fields) + 1), 1)
    with pytest.raises(ValueError, match="field count"):
        msg.decode_message(body)
