"""PartitionServer tests: the full rrdb handler surface.

Modeled on the reference's server-layer unit tests
(src/server/test/pegasus_server_impl_test.cpp) — a real PartitionServer
against a scratch storage dir.
"""

import pytest

from pegasus_tpu.base.key_schema import generate_key, restore_key
from pegasus_tpu.base.value_schema import epoch_now
from pegasus_tpu.ops.predicates import FT_MATCH_PREFIX
from pegasus_tpu.server import (
    BatchGetRequest,
    CasCheckType,
    CheckAndMutateRequest,
    CheckAndSetRequest,
    FullKey,
    GetScannerRequest,
    IncrRequest,
    KeyValue,
    MultiGetRequest,
    MultiPutRequest,
    MultiRemoveRequest,
    Mutate,
    MutateOperation,
    PartitionServer,
    SCAN_CONTEXT_ID_COMPLETED,
    SCAN_CONTEXT_ID_NOT_EXIST,
)
from pegasus_tpu.utils.errors import StorageStatus

OK = int(StorageStatus.OK)
NOT_FOUND = int(StorageStatus.NOT_FOUND)
INCOMPLETE = int(StorageStatus.INCOMPLETE)
INVALID = int(StorageStatus.INVALID_ARGUMENT)
TRY_AGAIN = int(StorageStatus.TRY_AGAIN)


@pytest.fixture
def server(tmp_path):
    s = PartitionServer(str(tmp_path / "p0"))
    yield s
    s.close()


def put(s, hk, sk, v, ttl=0):
    return s.on_put(generate_key(hk, sk), v, ttl)


def test_put_get_remove(server):
    key = generate_key(b"u", b"s")
    assert server.on_put(key, b"hello") == OK
    assert server.on_get(key) == (OK, b"hello")
    assert server.on_remove(key) == OK
    assert server.on_get(key) == (NOT_FOUND, b"")


def test_ttl_visibility(server):
    key = generate_key(b"u", b"s")
    server.on_put(key, b"v", ttl_seconds=10_000)
    err, ttl = server.on_ttl(key)
    assert err == OK and 9_000 < ttl <= 10_000
    # eternal record: ttl == -1
    key2 = generate_key(b"u", b"s2")
    server.on_put(key2, b"v")
    assert server.on_ttl(key2) == (OK, -1)
    # expired record invisible to get
    key3 = generate_key(b"u", b"s3")
    server.write_service.put(key3, b"v", epoch_now() - 5,
                             server._next_decree())
    assert server.on_get(key3) == (NOT_FOUND, b"")
    assert server.metrics.counter("abnormal_read_count").value() >= 1


def test_multi_put_multi_get_point(server):
    req = MultiPutRequest(b"hk", [KeyValue(b"s%d" % i, b"v%d" % i)
                                  for i in range(5)])
    assert server.on_multi_put(req) == OK
    resp = server.on_multi_get(MultiGetRequest(
        b"hk", sort_keys=[b"s1", b"s3", b"nope"]))
    assert resp.error == OK
    assert [(kv.key, kv.value) for kv in resp.kvs] == [
        (b"s1", b"v1"), (b"s3", b"v3")]


def test_multi_get_range_and_filters(server):
    for i in range(20):
        put(server, b"hk", b"a%02d" % i, b"v%d" % i)
    for i in range(5):
        put(server, b"hk", b"b%02d" % i, b"w%d" % i)
    # range [a05, a10)
    resp = server.on_multi_get(MultiGetRequest(
        b"hk", start_sortkey=b"a05", stop_sortkey=b"a10"))
    assert resp.error == OK
    assert [kv.key for kv in resp.kvs] == [b"a%02d" % i for i in range(5, 10)]
    # inclusive stop
    resp = server.on_multi_get(MultiGetRequest(
        b"hk", start_sortkey=b"a05", stop_sortkey=b"a10",
        stop_inclusive=True))
    assert resp.kvs[-1].key == b"a10"
    # exclusive start
    resp = server.on_multi_get(MultiGetRequest(
        b"hk", start_sortkey=b"a05", stop_sortkey=b"a10",
        start_inclusive=False))
    assert resp.kvs[0].key == b"a06"
    # prefix filter on sortkey
    resp = server.on_multi_get(MultiGetRequest(
        b"hk", sort_key_filter_type=FT_MATCH_PREFIX,
        sort_key_filter_pattern=b"b"))
    assert [kv.key for kv in resp.kvs] == [b"b%02d" % i for i in range(5)]
    # reverse returns ascending order of the LAST n
    resp = server.on_multi_get(MultiGetRequest(b"hk", max_kv_count=3,
                                               reverse=True))
    assert [kv.key for kv in resp.kvs] == [b"b02", b"b03", b"b04"]


def test_multi_get_incomplete_on_count_limit(server):
    for i in range(10):
        put(server, b"hk", b"s%02d" % i, b"v")
    resp = server.on_multi_get(MultiGetRequest(b"hk", max_kv_count=4))
    assert resp.error == INCOMPLETE
    assert len(resp.kvs) == 4


def test_multi_get_no_value(server):
    put(server, b"hk", b"s", b"payload")
    resp = server.on_multi_get(MultiGetRequest(b"hk", no_value=True))
    assert resp.kvs[0].value == b""


def test_multi_remove(server):
    for i in range(4):
        put(server, b"hk", b"s%d" % i, b"v")
    err, count = server.on_multi_remove(
        MultiRemoveRequest(b"hk", [b"s0", b"s2"]))
    assert err == OK and count == 2
    assert server.on_multi_remove(MultiRemoveRequest(b"hk", []))[0] == INVALID
    err, n = server.on_sortkey_count(b"hk")
    assert (err, n) == (OK, 2)


def test_batch_get(server):
    put(server, b"h1", b"s1", b"v1")
    put(server, b"h2", b"s2", b"v2")
    resp = server.on_batch_get(BatchGetRequest(
        [FullKey(b"h1", b"s1"), FullKey(b"h2", b"s2"),
         FullKey(b"h3", b"nope")]))
    assert resp.error == OK
    assert [(d.hash_key, d.value) for d in resp.data] == [
        (b"h1", b"v1"), (b"h2", b"v2")]


def test_incr(server):
    key = generate_key(b"h", b"cnt")
    resp = server.on_incr(IncrRequest(key, 5))
    assert (resp.error, resp.new_value) == (OK, 5)
    resp = server.on_incr(IncrRequest(key, -2))
    assert resp.new_value == 3
    assert server.on_get(key) == (OK, b"3")
    # non-numeric value -> invalid
    key2 = generate_key(b"h", b"str")
    server.on_put(key2, b"abc")
    assert server.on_incr(IncrRequest(key2, 1)).error == INVALID
    # overflow -> invalid, value unchanged
    resp = server.on_incr(IncrRequest(key, (1 << 62)))
    assert resp.error == OK
    resp = server.on_incr(IncrRequest(key, (1 << 62)))
    assert resp.error == INVALID
    # ttl: reset then clear
    resp = server.on_incr(IncrRequest(key, 0, expire_ts_seconds=500))
    assert server.on_ttl(key)[1] > 0
    server.on_incr(IncrRequest(key, 0, expire_ts_seconds=-1))
    assert server.on_ttl(key)[1] == -1


def test_check_and_set(server):
    req = CheckAndSetRequest(
        b"h", b"k1", CasCheckType.CT_VALUE_NOT_EXIST, b"",
        set_value=b"first")
    assert server.on_check_and_set(req).error == OK
    assert server.on_get(generate_key(b"h", b"k1")) == (OK, b"first")
    # second attempt: NOT_EXIST now fails with TryAgain
    resp = server.on_check_and_set(req)
    assert resp.error == TRY_AGAIN
    # int compare + diff sort key + return check value
    server.on_put(generate_key(b"h", b"num"), b"42")
    req2 = CheckAndSetRequest(
        b"h", b"num", CasCheckType.CT_VALUE_INT_GREATER_OR_EQUAL, b"40",
        set_diff_sort_key=True, set_sort_key=b"winner", set_value=b"yes",
        return_check_value=True)
    resp = server.on_check_and_set(req2)
    assert resp.error == OK and resp.check_value == b"42"
    assert server.on_get(generate_key(b"h", b"winner")) == (OK, b"yes")
    # malformed int operand -> invalid
    req3 = CheckAndSetRequest(
        b"h", b"num", CasCheckType.CT_VALUE_INT_LESS, b"xx",
        set_value=b"no")
    assert server.on_check_and_set(req3).error == INVALID


def test_check_and_mutate(server):
    server.on_put(generate_key(b"h", b"guard"), b"ready")
    req = CheckAndMutateRequest(
        b"h", b"guard", CasCheckType.CT_VALUE_BYTES_EQUAL, b"ready",
        mutate_list=[
            Mutate(MutateOperation.MO_PUT, b"a", b"va"),
            Mutate(MutateOperation.MO_PUT, b"b", b"vb"),
            Mutate(MutateOperation.MO_DELETE, b"guard"),
        ])
    assert server.on_check_and_mutate(req).error == OK
    assert server.on_get(generate_key(b"h", b"a")) == (OK, b"va")
    assert server.on_get(generate_key(b"h", b"guard")) == (NOT_FOUND, b"")
    # failed check mutates nothing
    req2 = CheckAndMutateRequest(
        b"h", b"a", CasCheckType.CT_VALUE_BYTES_EQUAL, b"wrong",
        mutate_list=[Mutate(MutateOperation.MO_DELETE, b"a")])
    assert server.on_check_and_mutate(req2).error == TRY_AGAIN
    assert server.on_get(generate_key(b"h", b"a")) == (OK, b"va")
    # empty mutate list -> invalid
    req3 = CheckAndMutateRequest(
        b"h", b"a", CasCheckType.CT_NO_CHECK, b"", mutate_list=[])
    assert server.on_check_and_mutate(req3).error == INVALID


def test_scanner_paging(server):
    for i in range(25):
        put(server, b"hk%02d" % (i % 5), b"s%02d" % i, b"v%d" % i)
    seen = []
    resp = server.on_get_scanner(GetScannerRequest(batch_size=10))
    while True:
        seen.extend(kv.key for kv in resp.kvs)
        if resp.context_id == SCAN_CONTEXT_ID_COMPLETED:
            break
        resp = server.on_scan(resp.context_id)
        assert resp.error == OK
    assert len(seen) == 25
    assert seen == sorted(seen)  # total order over encoded keys
    # expired/unknown context
    resp = server.on_scan(99999)
    assert resp.context_id == SCAN_CONTEXT_ID_NOT_EXIST


def test_scanner_filters_and_count(server):
    for i in range(10):
        put(server, b"alpha", b"s%d" % i, b"v")
        put(server, b"beta", b"s%d" % i, b"v")
    resp = server.on_get_scanner(GetScannerRequest(
        hash_key_filter_type=FT_MATCH_PREFIX, hash_key_filter_pattern=b"al",
        batch_size=100))
    assert len(resp.kvs) == 10
    assert all(restore_key(kv.key)[0] == b"alpha" for kv in resp.kvs)
    # count-only scan
    resp = server.on_get_scanner(GetScannerRequest(only_return_count=True))
    assert resp.kv_count == 20 and resp.kvs == []


def test_scanner_range_bounds(server):
    for i in range(10):
        put(server, b"hk", b"s%02d" % i, b"v")
    start = generate_key(b"hk", b"s03")
    stop = generate_key(b"hk", b"s07")
    resp = server.on_get_scanner(GetScannerRequest(
        start_key=start, stop_key=stop, start_inclusive=False,
        stop_inclusive=True, batch_size=100))
    got = [restore_key(kv.key)[1] for kv in resp.kvs]
    assert got == [b"s04", b"s05", b"s06", b"s07"]


def test_scanner_return_expire_ts(server):
    put(server, b"hk", b"s", b"v", ttl=5000)
    resp = server.on_get_scanner(GetScannerRequest(return_expire_ts=True,
                                                   batch_size=10))
    assert resp.kvs[0].expire_ts_seconds > 0


def test_scan_validates_partition_hash(tmp_path):
    # two partitions of an 8-partition table; each scan only returns
    # records its partition owns
    from pegasus_tpu.base.key_schema import partition_index
    pc = 8
    servers = {i: PartitionServer(str(tmp_path / f"p{i}"), pidx=i,
                                  partition_count=pc) for i in range(2)}
    try:
        written = {0: 0, 1: 0}
        for i in range(60):
            hk = b"user_%d" % i
            pidx = partition_index(hk, pc)
            if pidx in servers:
                servers[pidx].on_put(generate_key(hk, b"s"), b"v")
                written[pidx] += 1
        from pegasus_tpu.storage.engine import WriteBatchItem
        for pidx, s in servers.items():
            # pretend some stale post-split data: write a foreign key
            s.engine.write_batch(
                [WriteBatchItem(0, generate_key(b"foreign_%d" % pidx, b"s"),
                                b"\x00\x00\x00\x00stale", 0)],
                s.engine.last_committed_decree + 1)
            resp = s.on_get_scanner(GetScannerRequest(
                batch_size=1000, validate_partition_hash=True))
            assert resp.error == OK
            keys = [restore_key(kv.key)[0] for kv in resp.kvs]
            from pegasus_tpu.base.key_schema import partition_index as pi
            assert all(pi(hk, pc) == pidx for hk in keys)
    finally:
        for s in servers.values():
            s.close()


def test_scan_after_flush_and_compact(server):
    for i in range(30):
        put(server, b"hk", b"s%02d" % i, b"v%d" % i)
    server.flush()
    for i in range(30, 40):
        put(server, b"hk", b"s%02d" % i, b"v%d" % i)
    server.manual_compact()
    err, n = server.on_sortkey_count(b"hk")
    assert (err, n) == (OK, 40)
    resp = server.on_multi_get(MultiGetRequest(b"hk"))
    assert len(resp.kvs) == 40


def test_capacity_units_accumulate(server):
    put(server, b"hk", b"s", b"v" * 5000)  # 2 write CUs
    assert server.cu.write_cu >= 2
    server.on_get(generate_key(b"hk", b"s"))
    assert server.cu.read_cu >= 2


def test_batched_multi_scan_matches_individual(tmp_path):
    """on_get_scanner_batch: shared-block dedup must return exactly what
    per-request serving returns (pagination included)."""
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.base.value_schema import epoch_now, generate_value
    from pegasus_tpu.server.partition_server import PartitionServer
    from pegasus_tpu.server.types import (
        GetScannerRequest,
        SCAN_CONTEXT_ID_COMPLETED,
    )
    from pegasus_tpu.storage.engine import WriteBatchItem
    from pegasus_tpu.storage.wal import OP_PUT

    srv = PartitionServer(str(tmp_path / "p"), partition_count=1)
    now = epoch_now()
    items = []
    for i in range(900):
        ets = 0 if i % 7 else now - 50  # some expired records
        items.append(WriteBatchItem(
            OP_PUT, generate_key(b"h%03d" % (i % 30), b"s%04d" % i),
            generate_value(1, b"v%d" % i, ets), ets))
    srv.engine.write_batch(items, 1)
    srv.manual_compact()  # the columnar fast path qualifies

    reqs = [
        GetScannerRequest(start_key=generate_key(b"h00%d" % d, b""),
                          batch_size=25)
        for d in range(5)
    ] + [GetScannerRequest(start_key=b"", batch_size=40)] * 3
    batch = srv.on_get_scanner_batch(list(reqs))
    for req, got in zip(reqs, batch):
        solo = srv.on_get_scanner(req)
        assert got.error == solo.error
        assert [(kv.key, kv.value) for kv in got.kvs] == \
            [(kv.key, kv.value) for kv in solo.kvs], req
        assert (got.context_id == SCAN_CONTEXT_ID_COMPLETED) == \
            (solo.context_id == SCAN_CONTEXT_ID_COMPLETED)
        # paging continues correctly from the batch-created context
        if got.context_id >= 0:
            page2 = srv.on_scan(got.context_id)
            solo2 = srv.on_scan(solo.context_id)
            assert [(kv.key, kv.value) for kv in page2.kvs] == \
                [(kv.key, kv.value) for kv in solo2.kvs]
    srv.close()


def test_batched_scan_falls_back_off_fast_path(tmp_path):
    """An overlay (memtable) or filtered request serves per-request."""
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.ops.predicates import FT_MATCH_PREFIX
    from pegasus_tpu.server.partition_server import PartitionServer
    from pegasus_tpu.server.types import GetScannerRequest

    srv = PartitionServer(str(tmp_path / "p"), partition_count=1)
    for i in range(50):
        srv.on_put(generate_key(b"hk", b"s%02d" % i), b"v%d" % i)
    # memtable overlay -> fallback path must still answer correctly
    reqs = [GetScannerRequest(start_key=generate_key(b"hk", b""),
                              batch_size=100),
            GetScannerRequest(start_key=b"",
                              sort_key_filter_type=FT_MATCH_PREFIX,
                              sort_key_filter_pattern=b"s0",
                              batch_size=100)]
    out = srv.on_get_scanner_batch(reqs)
    assert len(out[0].kvs) == 50
    assert len(out[1].kvs) == 10
    srv.close()


def test_batched_scan_overlay_merge_matches_individual(tmp_path):
    """A small write overlay merges host-side onto the device-filtered
    base: batched results must equal per-request serving, including
    shadowing (updates + tombstones) and pagination."""
    from pegasus_tpu.base.key_schema import generate_key
    from pegasus_tpu.base.value_schema import generate_value
    from pegasus_tpu.server.partition_server import PartitionServer
    from pegasus_tpu.server.types import GetScannerRequest
    from pegasus_tpu.storage.engine import WriteBatchItem
    from pegasus_tpu.storage.wal import OP_PUT

    srv = PartitionServer(str(tmp_path / "p"), partition_count=1)
    items = [WriteBatchItem(
        OP_PUT, generate_key(b"h%02d" % (i % 10), b"s%04d" % i),
        generate_value(1, b"base%d" % i, 0), 0) for i in range(400)]
    srv.engine.write_batch(items, 1)
    srv.manual_compact()
    # overlay: updates shadowing base rows, fresh inserts, tombstones
    srv.on_put(generate_key(b"h00", b"s0000"), b"UPDATED")
    srv.on_put(generate_key(b"h00", b"s0000x"), b"INSERTED")
    srv.on_remove(generate_key(b"h01", b"s0011"))
    srv.engine.flush()  # some overlay in L0...
    srv.on_put(generate_key(b"h02", b"s0002"), b"NEWEST")  # ...some in mem

    reqs = [GetScannerRequest(start_key=generate_key(b"h0%d" % d, b""),
                              batch_size=17) for d in range(4)] \
        + [GetScannerRequest(start_key=b"", batch_size=33)]
    batch = srv.on_get_scanner_batch(list(reqs))
    for req, got in zip(reqs, batch):
        solo = srv.on_get_scanner(req)
        assert [(kv.key, kv.value) for kv in got.kvs] == \
            [(kv.key, kv.value) for kv in solo.kvs], req
        # paging equivalence
        g, s_ = got, solo
        while g.context_id >= 0 and s_.context_id >= 0:
            g = srv.on_scan(g.context_id)
            s_ = srv.on_scan(s_.context_id)
            assert [(kv.key, kv.value) for kv in g.kvs] == \
                [(kv.key, kv.value) for kv in s_.kvs]
        assert (g.context_id >= 0) == (s_.context_id >= 0)
    # the shadowed values surfaced
    all_rows = dict((kv.key, kv.value)
                    for kv in srv.on_get_scanner(
                        GetScannerRequest(start_key=b"",
                                          batch_size=1000)).kvs)
    assert all_rows[generate_key(b"h00", b"s0000")] == b"UPDATED"
    assert all_rows[generate_key(b"h02", b"s0002")] == b"NEWEST"
    assert generate_key(b"h01", b"s0011") not in all_rows
    srv.close()


def test_env_triggered_manual_compact(server):
    """Remote manual compaction rides the `manual_compact.once.
    trigger_time` app env (parity: pegasus_manual_compact_service.cpp
    MANUAL_COMPACT_ONCE_TRIGGER_TIME_KEY, written by the shell and
    delivered to replicas via config-sync): a fresh trigger compacts
    once (asynchronously), re-deliveries are idempotent, and a stale
    trigger older than the store's recorded finish time never
    re-compacts."""
    import time

    for i in range(50):
        put(server, b"mc%02d" % i, b"s", b"v%d" % i)
    lsm = server.engine.lsm
    assert len(lsm.memtable) == 50 and not lsm.l1_runs

    # unix-seconds trigger (the reference's `date +%s` convention)
    server.update_app_envs(
        {"manual_compact.once.trigger_time": str(int(time.time()))})
    deadline = time.monotonic() + 30
    while server._mc_running and time.monotonic() < deadline:
        time.sleep(0.02)
    assert not server._mc_running
    assert lsm.l1_runs and not len(lsm.memtable)
    gen = lsm.generation
    # the data survived, TTL semantics intact
    assert server.on_get(generate_key(b"mc07", b"s")) == (OK, b"v7")

    # config-sync re-delivery of the SAME env value: no second run
    server.update_app_envs(
        {"manual_compact.once.trigger_time":
         str(server._mc_trigger_seen)})
    time.sleep(0.1)
    assert lsm.generation == gen

    # restart-shaped staleness: a brand-new server over the same store
    # re-syncing the old trigger must see it already satisfied (the
    # finish time persists in the manifest, independent of the run set)
    assert lsm.compact_finish_time > 0
    server._mc_trigger_seen = 0
    server.update_app_envs(
        {"manual_compact.once.trigger_time":
         str(lsm.compact_finish_time)})
    time.sleep(0.1)
    assert lsm.generation == gen


def test_scans_stay_consistent_during_env_compaction(server):
    """The env-triggered compaction runs on its own thread while the
    node keeps serving: every concurrent scan must return the complete,
    correct row set before AND after the atomic generation publish —
    no torn reads, no errors from swapped-out runs."""
    import threading
    import time

    for i in range(3000):
        put(server, b"cc%04d" % (i % 300), b"s%02d" % (i // 300),
            b"val-%d" % (i % 300))
    server.engine.flush()
    for i in range(40):  # an overlay too
        put(server, b"ov%02d" % i, b"s", b"o")

    errors = []
    gens_seen = set()
    stop = threading.Event()
    lsm = server.engine.lsm
    gen_before = lsm.generation

    def scan_loop():
        try:
            while not stop.is_set():
                g = lsm.generation
                total = 0
                resp = server.on_get_scanner(
                    GetScannerRequest(start_key=b"", batch_size=5000))
                while True:
                    assert resp.error == OK, resp.error
                    total += len(resp.kvs)
                    if resp.context_id < 0:
                        break
                    resp = server.on_scan(resp.context_id)
                assert total == 3040, total
                gens_seen.add(g)
        except Exception as exc:  # noqa: BLE001 - surfaced below
            errors.append(repr(exc))

    t = threading.Thread(target=scan_loop)
    t.start()
    # warm one scan round before triggering so the overlap window isn't
    # eaten by first-touch compiles
    deadline = time.monotonic() + 60
    while not gens_seen and time.monotonic() < deadline:
        time.sleep(0.01)
    server.update_app_envs(
        {"manual_compact.once.trigger_time": str(int(time.time()))})
    while server._mc_running and time.monotonic() < deadline:
        time.sleep(0.01)
    # keep scanning until a post-publish round completes
    while lsm.generation not in gens_seen and \
            time.monotonic() < deadline and not errors:
        time.sleep(0.01)
    stop.set()
    t.join(timeout=10)
    assert not errors, errors
    assert not server._mc_running
    # rounds completed at BOTH the pre- and post-publish generation
    assert gen_before in gens_seen, (gen_before, gens_seen)
    assert lsm.generation > gen_before
    assert lsm.generation in gens_seen, (lsm.generation, gens_seen)
    assert lsm.l1_runs
