"""Cluster flight recorder tests: time-series rings, the health-rules
watchdog (threshold / burn-rate / z-score, flap damping, typed events,
auto-pinned capture), the meta ClusterHealth fold, and the seeded-sim
incident scenario behind `shell health` / `shell timeline`."""

import argparse
import io
import itertools
import json

import pytest

from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.utils import health as health_mod
from pegasus_tpu.utils import tracing
from pegasus_tpu.utils.fail_point import FAIL_POINTS
from pegasus_tpu.utils.flags import FLAGS
from pegasus_tpu.utils.health import (
    HealthEngine,
    HealthRule,
    default_rules,
    parse_window,
    render_timeline,
)
from pegasus_tpu.utils.metrics import MetricRegistry
from pegasus_tpu.utils.profiler import PROFILER
from pegasus_tpu.utils.timeseries import FlightRecorder


@pytest.fixture(autouse=True)
def _isolation():
    """Every test: clean rings/flags/fail-points/capture pins."""
    tracing.reset()
    tracing.seed(7)
    FLAGS.set("pegasus.tracing", "sample_ratio", 0.0)
    FLAGS.set("pegasus.health", "recorder_enabled", True)
    yield
    FAIL_POINTS.teardown()
    health_mod.reset_capture()
    PROFILER.disable()
    PROFILER.clear()
    FLAGS.set("pegasus.tracing", "sample_ratio", 0.0)
    FLAGS.set("pegasus.health", "recorder_enabled", True)
    FLAGS.set("pegasus.health", "recorder_interval_s", 10.0)
    FLAGS.set("pegasus.health", "recorder_window_s", 600.0)
    FLAGS.set("pegasus.health", "recorder_byte_cap", 262144)
    tracing.reset()


# ---- recorder unit tests -------------------------------------------------


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _recorder(reg, clock):
    return FlightRecorder("n0", clock=clock, registry=reg)


def test_recorder_counters_become_rates_gauges_sampled():
    reg = MetricRegistry()
    clock = _Clock()
    ent = reg.entity("rpc", "n0")
    c = ent.counter("read_shed_count")
    g = ent.gauge("queue_depth")
    p = ent.percentile("lat_ms")
    rec = _recorder(reg, clock)
    c.increment(10)
    g.set(3.0)
    for v in range(100):
        p.set(float(v))
    rec.tick()  # first sight: cursors only, no rate points yet
    assert rec.series("rpc", "n0", "read_shed_count") is None
    clock.t += 10.0
    c.increment(50)
    rec.tick()
    ring = rec.series("rpc", "n0", "read_shed_count")
    assert ring.kind == "rate"
    assert ring.latest()[1] == pytest.approx(5.0)  # 50 over 10s
    assert rec.series("rpc", "n0", "queue_depth").latest()[1] == 3.0
    p50 = rec.series("rpc", "n0", "lat_ms.p50")
    assert p50 is not None and p50.kind == "value"
    # volatile counters drain through the per-reader cursor: the
    # recorder's reads never steal another reader's delta
    v = ent.volatile_counter("qps")
    v.increment(30)
    clock.t += 10.0
    rec.tick()
    assert rec.series("rpc", "n0", "qps").latest()[1] == pytest.approx(3.0)
    assert v.delta_since("other_reader") == 30  # full sum still there


def test_recorder_coalesces_below_interval_and_respects_master_switch():
    reg = MetricRegistry()
    clock = _Clock()
    ent = reg.entity("rpc", "n0")
    ent.gauge("g").set(1.0)
    rec = _recorder(reg, clock)
    assert rec.tick() is not None
    clock.t += 1.0  # below the 5s cadence: coalesced
    assert rec.tick() is None
    clock.t += 10.0
    FLAGS.set("pegasus.health", "recorder_enabled", False)
    assert rec.tick() is None
    FLAGS.set("pegasus.health", "recorder_enabled", True)
    assert rec.tick() is not None


def test_recorder_window_trim_and_byte_cap():
    reg = MetricRegistry()
    clock = _Clock()
    ent = reg.entity("rpc", "n0")
    g = ent.gauge("g")
    rec = _recorder(reg, clock)
    FLAGS.set("pegasus.health", "recorder_window_s", 100.0)
    for i in range(30):
        g.set(float(i + 1))
        rec.tick(force=True)
        clock.t += 10.0
    ring = rec.series("rpc", "n0", "g")
    # 100s window over 10s spacing: ~10 newest points retained
    assert len(ring.points) <= 11
    assert ring.points[0][0] >= clock.t - 110.0
    # hard byte cap: overflow evicts oldest points, never grows
    FLAGS.set("pegasus.health", "recorder_window_s", 1e9)
    FLAGS.set("pegasus.health", "recorder_byte_cap", 600)
    for i in range(200):
        g.set(float(i))
        rec.tick(force=True)
        clock.t += 10.0
    assert rec.nbytes() <= 600 + 200  # one series' overhead slack
    assert rec.evicted_points > 0


def test_recorder_ownership_predicate():
    reg = MetricRegistry()
    clock = _Clock()
    reg.entity("rpc", "n0").gauge("g").set(1.0)
    reg.entity("rpc", "n1").gauge("g").set(2.0)
    rec = FlightRecorder("n0", clock=clock, registry=reg,
                         owns=lambda e: e.entity_id == "n0")
    rec.tick()
    assert rec.series("rpc", "n0", "g") is not None
    assert rec.series("rpc", "n1", "g") is None


# ---- rules engine unit tests ---------------------------------------------


def _engine_with_series(rule, points, clock, kind="rate"):
    """Engine over a hand-built ring (no registry round trip)."""
    from pegasus_tpu.utils.timeseries import SeriesRing

    reg = MetricRegistry()
    rec = FlightRecorder("n0", clock=clock, registry=reg)
    ring = SeriesRing(kind)
    for ts, v in points:
        ring.append(ts, v)
        rec._total_points += 1
    rec._series[(rule.entity_type, "n0", rule.metric)] = ring
    eng = HealthEngine("n0", rec, rules=[rule], clock=clock)
    return eng, ring


def test_threshold_rule_fires_and_clears_with_hysteresis():
    clock = _Clock()
    rule = HealthRule("hot", "rpc", "m", kind="threshold", threshold=5.0,
                      clear_hold=2)
    eng, ring = _engine_with_series(rule, [(999.0, 9.0)], clock)
    evs = eng.evaluate()
    assert len(evs) == 1 and evs[0].firing and evs[0].rule == "hot"
    assert evs[0].severity == "degraded" and evs[0].evidence
    assert eng.status()["status"] == "degraded"
    # one calm eval is NOT enough to clear (clear_hold=2)
    ring.append(1001.0, 0.0)
    assert eng.evaluate() == []
    ring.append(1002.0, 0.0)
    evs = eng.evaluate()
    assert len(evs) == 1 and not evs[0].firing
    assert eng.status()["status"] == "ok"
    # journal holds the full fired/cleared ledger
    assert [d["firing"] for d in eng.journal] == [True, False]


def test_burn_rate_needs_sustained_violation_not_one_blip():
    clock = _Clock()
    rule = HealthRule("burn", "rpc", "m", kind="burn_rate",
                      threshold=1.0, window_s=30.0, min_points=2)
    # a single blip: huge spike then silence — the windowed mean stays
    # high but the LATEST sample is calm, so it must never fire
    eng, ring = _engine_with_series(
        rule, [(980.0, 50.0), (990.0, 0.0)], clock)
    assert eng.evaluate() == []
    # blip AFTER a quiet stretch (run-length compression leaves one
    # trailing zero): huge latest sample, but the previous sample is
    # calm — "burn" means consecutive hot ticks, so still no fire
    ring.append(992.0, 30.0)
    assert eng.evaluate() == []
    # sustained: consecutive hot samples -> fires
    ring.append(995.0, 4.0)
    ring.append(999.0, 4.0)
    evs = eng.evaluate()
    assert len(evs) == 1 and evs[0].firing


def test_zscore_rule_detects_spike_over_history():
    clock = _Clock()
    pts = [(900.0 + i * 10, 10.0 + (i % 2)) for i in range(9)]
    pts.append((995.0, 60.0))  # the spike
    rule = HealthRule("spike", "rpc", "m", kind="zscore", threshold=4.0,
                      window_s=120.0, min_points=5)
    eng, _ring = _engine_with_series(rule, pts, clock)
    evs = eng.evaluate()
    assert len(evs) == 1 and evs[0].firing
    assert "σ" in evs[0].reason


def test_hold_delays_firing_until_consecutive_violations():
    clock = _Clock()
    rule = HealthRule("flappy", "rpc", "m", kind="threshold",
                      threshold=1.0, hold=3)
    eng, ring = _engine_with_series(rule, [(999.0, 5.0)], clock)
    assert eng.evaluate() == []  # 1st violation
    assert eng.evaluate() == []  # 2nd
    evs = eng.evaluate()  # 3rd consecutive -> fire
    assert len(evs) == 1 and evs[0].firing


def test_firing_pins_capture_and_clear_restores_it():
    clock = _Clock()
    FLAGS.set("pegasus.tracing", "sample_ratio", 0.01)
    rule = HealthRule("hot", "rpc", "m", kind="threshold", threshold=1.0,
                      clear_hold=1)
    eng, ring = _engine_with_series(rule, [(999.0, 9.0)], clock)
    assert not PROFILER.enabled
    eng.evaluate()
    assert FLAGS.get("pegasus.tracing", "sample_ratio") == \
        FLAGS.get("pegasus.health", "pin_sample_ratio")
    assert PROFILER.enabled  # incident-window profiling is on
    ring.append(1001.0, 0.0)
    evs = eng.evaluate()
    assert evs and not evs[0].firing
    assert FLAGS.get("pegasus.tracing", "sample_ratio") == 0.01
    assert not PROFILER.enabled


def test_unpin_preserves_operator_ratio_change():
    """An operator who re-tunes the sample ratio mid-incident keeps
    their value: unpin restores only if the ratio is still the boost
    it set."""
    clock = _Clock()
    rule = HealthRule("hot", "rpc", "m", kind="threshold", threshold=1.0,
                      clear_hold=1)
    eng, ring = _engine_with_series(rule, [(999.0, 9.0)], clock)
    eng.evaluate()  # fires -> pinned to pin_sample_ratio
    FLAGS.set("pegasus.tracing", "sample_ratio", 0.9)  # operator tune
    ring.append(1001.0, 0.0)
    evs = eng.evaluate()  # clears -> unpin
    assert evs and not evs[0].firing
    assert FLAGS.get("pegasus.tracing", "sample_ratio") == 0.9


def test_cluster_health_stale_node_stops_asserting_tables():
    """A node that stops reporting goes stale and its frozen firing
    list must stop escalating table/cluster status — the meta refuses
    to claim health it can no longer see."""
    from pegasus_tpu.meta.cluster_health import STALE_S, ClusterHealth

    class _Meta:
        t = 0.0

        def clock(self):
            return self.t

    meta = _Meta()
    ch = ClusterHealth(meta)
    ch.on_report("n0", {"health": {
        "status": "critical",
        "firing": [{"rule": "replica_quarantine",
                    "entity": ["replica", "3.1"],
                    "metric": "replica_quarantine_count",
                    "severity": "critical", "since": 0.0}],
        "events": []}})
    st = ch.status()
    assert st["tables"]["3"]["status"] == "critical"
    assert st["cluster"] == "critical"
    meta.t = STALE_S + 1.0  # the node never reports again
    st = ch.status()
    assert st["nodes"]["n0"]["status"] == "stale"
    assert "3" not in st["tables"]
    assert st["cluster"] == "ok"


def test_engine_close_releases_outstanding_pins():
    clock = _Clock()
    base = FLAGS.get("pegasus.tracing", "sample_ratio")
    rule = HealthRule("hot", "rpc", "m", kind="threshold", threshold=1.0)
    eng, _ring = _engine_with_series(rule, [(999.0, 9.0)], clock)
    eng.evaluate()
    assert FLAGS.get("pegasus.tracing", "sample_ratio") != base
    eng.close()
    assert FLAGS.get("pegasus.tracing", "sample_ratio") == base


def test_drain_report_is_bounded_and_counts_drops():
    clock = _Clock()
    FLAGS_cap = FLAGS.get("pegasus.health", "report_max_events")
    rule = HealthRule("hot", "rpc", "m", kind="threshold", threshold=1.0,
                      clear_hold=1)
    eng, ring = _engine_with_series(rule, [(999.0, 9.0)], clock)
    # flip fire/clear far past the report cap
    for i in range(FLAGS_cap + 10):
        ring.append(1000.0 + i, 9.0 if i % 2 == 0 else 0.0)
        eng.evaluate()
    rep = eng.drain_report()
    assert len(rep["events"]) == FLAGS_cap
    assert rep["dropped"] > 0
    assert rep["events_total"] == len(eng.journal)
    # unacked events RE-SHIP (a report lost on a broken meta link —
    # the incident itself — must lose nothing) ...
    rep2 = eng.drain_report()
    assert [e["seq"] for e in rep2["events"]] == \
        [e["seq"] for e in rep["events"]]
    # ... until the config_sync_reply ack covers their seq
    eng.ack_report(max(e["seq"] for e in rep2["events"]))
    assert eng.drain_report()["events"] == []


def test_meta_journal_dedupes_reshipped_events():
    """Re-shipped (reply-lost) events must not duplicate in the meta
    journal: dedupe by per-node seq, acked via on_report's return."""
    from pegasus_tpu.meta.cluster_health import ClusterHealth

    class _Meta:
        t = 0.0

        def clock(self):
            return self.t

    ch = ClusterHealth(_Meta())
    block = {"health": {"status": "degraded", "firing": [], "events": [
        {"rule": "r", "entity": ["rpc", "n0"], "metric": "m",
         "severity": "degraded", "firing": True, "ts": 1.0,
         "reason": "x", "evidence": [], "seq": 1},
        {"rule": "r", "entity": ["rpc", "n0"], "metric": "m",
         "severity": "degraded", "firing": False, "ts": 2.0,
         "reason": "y", "evidence": [], "seq": 2}]}}
    block["health"]["seq_hw"] = 2
    assert ch.on_report("n0", block) == 2  # the ack high-water mark
    assert ch.on_report("n0", block) == 2  # re-shipped: deduped
    assert len(ch.journal) == 2
    # node restart: a fresh engine's seq starts over — the backward
    # seq_hw resets the dedupe cursor so post-restart events are NOT
    # silently skipped-and-acked
    restarted = {"health": {"status": "degraded", "firing": [],
                            "seq_hw": 1, "events": [
        {"rule": "r2", "entity": ["rpc", "n0"], "metric": "m",
         "severity": "degraded", "firing": True, "ts": 9.0,
         "reason": "z", "evidence": [], "seq": 1}]}}
    assert ch.on_report("n0", restarted) == 1
    assert len(ch.journal) == 3


# ---- seeded-sim incident scenario (the acceptance gate) ------------------


class _SimAdmin:
    """OneboxAdmin's wire protocol over the sim network: the shell's
    admin surface against a SimCluster, exercising meta _on_admin."""

    def __init__(self, cluster):
        self.c = cluster
        self._rids = itertools.count(77_000_000)
        self._replies = {}
        cluster.net.register("shelladmin", self._on_msg)

    def _on_msg(self, _src, msg_type, payload):
        if msg_type == "admin_reply":
            self._replies[payload["rid"]] = payload

    def call(self, cmd, **args):
        rid = next(self._rids)
        self.c.net.send("shelladmin", self.c.meta.name, "admin",
                        {"rid": rid, "cmd": cmd, "args": args})
        for _ in range(50):
            self.c.loop.run_until_idle()
            if rid in self._replies:
                rep = self._replies.pop(rid)
                assert rep["err"] == 0, rep
                return rep["result"]
        raise RuntimeError(f"no admin reply for {cmd}")


class _SimBox:
    """The minimum shell-box surface `health`/`timeline` dispatch on."""

    def __init__(self, cluster):
        self.c = cluster
        self.admin = _SimAdmin(cluster)

    def remote_command(self, node, verb, args):
        return self.c.stubs[node].commands.call(verb, args)


@pytest.fixture
def cluster(tmp_path):
    # every step records: maps "recorder tick" 1:1 onto SimCluster.step
    FLAGS.set("pegasus.health", "recorder_interval_s", 1.0)
    c = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=11)
    yield c
    c.close()


def _load(client, n=24, write=True, read=True):
    ok = 0
    for i in range(n):
        try:
            if write:
                client.set(b"k%03d" % i, b"s", b"v%d" % i)
            if read:
                client.get(b"k%03d" % i, b"s")
            ok += 1
        except Exception:  # noqa: BLE001 - shed errors ARE the scenario
            pass
    return ok


def test_incident_shed_fires_pins_capture_and_renders_timeline(cluster):
    """The flight-recorder acceptance scenario: sustained read shedding
    injected on one node -> its shed-rate rule fires within 3 recorder
    ticks; meta health shows THAT node degraded (others ok); the trace
    sample ratio is provably raised while firing and restored after
    clear; `shell timeline` renders ring slice + events + >=1 kept
    slow trace in one report."""
    cluster.create_table("t", partition_count=4)
    client = cluster.client("t")
    _load(client)
    cluster.step(rounds=2)
    victim = "node0"
    stub = cluster.stubs[victim]
    base_ratio = FLAGS.get("pegasus.tracing", "sample_ratio")
    # keep-threshold low so retry-stretched ops tail-keep in sim time
    FLAGS.set("pegasus.tracing", "slow_trace_ms", 5.0)
    FAIL_POINTS.setup()
    FAIL_POINTS.cfg(f"stub_read_shed:{victim}", "return(busy)")
    fired_at = None
    for tick in range(1, 4):
        _load(client, write=False)
        cluster.step()
        if stub.health.status()["status"] != "ok":
            fired_at = tick
            break
    assert fired_at is not None and fired_at <= 3, \
        "shed rule must fire within 3 recorder ticks"
    firing = stub.health.firing()
    assert any(f["rule"] == "read_shed_growth" for f in firing)
    # auto-pin: sample ratio provably raised while firing
    assert FLAGS.get("pegasus.tracing", "sample_ratio") == \
        FLAGS.get("pegasus.health", "pin_sample_ratio") > base_ratio
    assert PROFILER.enabled
    # pinned capture + shed retries -> tail-kept slow traces exist
    _load(client, write=False)
    cluster.step(rounds=2)  # config-sync carries digest + traces up
    box = _SimBox(cluster)
    status = box.admin.call("cluster_health")
    assert status["cluster"] == "degraded"
    assert status["nodes"][victim]["status"] == "degraded"
    assert any(f["rule"] == "read_shed_growth"
               for f in status["nodes"][victim]["firing"])
    for other in ("node1", "node2"):
        assert status["nodes"][other]["status"] == "ok"
    # the shell surfaces: `health` ...
    from pegasus_tpu.tools.shell import _dispatch

    out = io.StringIO()
    _dispatch(argparse.Namespace(cmd="health", json=False), box, out)
    text = out.getvalue()
    assert "cluster: degraded" in text
    assert "read_shed_growth" in text
    # ... then clear: stop the injection, rule clears, ratio restores
    FAIL_POINTS.teardown()
    for _ in range(6):
        cluster.step()
        if not stub.health.firing():
            break
    assert not stub.health.firing()
    assert FLAGS.get("pegasus.tracing", "sample_ratio") == base_ratio
    assert not PROFILER.enabled
    kinds = [d["firing"] for d in stub.health.journal
             if d["rule"] == "read_shed_growth"]
    assert kinds == [True, False]
    # cleared event carries the incident-window profiler snapshot
    cleared = [d for d in stub.health.journal if not d["firing"]][-1]
    assert cleared.get("profile"), \
        "auto-pinned TaskProfiler dump must ride the cleared event"
    cluster.step(rounds=3)  # ship the cleared event + damped recovery
    assert box.admin.call("cluster_health")["nodes"][victim][
        "status"] == "ok"
    # ONE rendered incident report: ring slice + events + kept trace
    out = io.StringIO()
    _dispatch(argparse.Namespace(cmd="timeline", target=victim,
                                 window="5m", json=False), box, out)
    report = out.getvalue()
    assert "FIRING" in report and "CLEARED" in report
    assert "read_shed_growth" in report
    assert "read_shed_count" in report and "|" in report  # sparkline
    assert "trace " in report, "timeline must include a kept slow trace"
    # and the bundle is JSON-able for tooling
    out = io.StringIO()
    _dispatch(argparse.Namespace(cmd="timeline", target=victim,
                                 window="5m", json=True), box, out)
    bundle = json.loads(out.getvalue())
    assert bundle["events"] and bundle["series"] and bundle["traces"]


def test_healthy_soak_fires_zero_events_and_blips_are_damped(cluster):
    """Steady healthy load over a full soak: zero events anywhere.
    Then a sub-sustained one-tick shed blip: flap damping (burn-rate's
    latest-sample gate) keeps the watchdog quiet through it too."""
    cluster.create_table("s", partition_count=4)
    client = cluster.client("s")
    for _ in range(10):
        assert _load(client) > 0
        cluster.step()
    for name, stub in cluster.stubs.items():
        assert stub.health.events_total == 0, \
            f"{name} fired during a healthy soak"
        assert stub.health.status()["status"] == "ok"
    status = cluster.meta.health.status()
    assert status["cluster"] == "ok"
    # one-tick blip: a burst of shed inside a single recorder tick
    FAIL_POINTS.setup()
    FAIL_POINTS.cfg("stub_read_shed:node1", "return(busy)")
    _load(client, write=False)
    FAIL_POINTS.teardown()  # gone before the next tick
    for _ in range(4):
        _load(client)
        cluster.step()
    assert cluster.stubs["node1"].health.events_total == 0, \
        "a one-tick blip must be flap-damped, not fired"


def test_table_timeline_folds_replica_entities(cluster):
    """A rule firing on a table's replica entity shows on the TABLE
    timeline: per-table status + filtered events."""
    cluster.create_table("tt", partition_count=2)
    client = cluster.client("tt")
    _load(client)
    # synthetic per-table rule so the fold is deterministic
    for stub in cluster.stubs.values():
        stub.health.rules.append(HealthRule(
            "table_write_p99", "replica", "write_latency_ms.p99",
            kind="threshold", threshold=-1.0))  # always fires once seen
    for _ in range(3):
        _load(client)
        cluster.step()
    box = _SimBox(cluster)
    status = box.admin.call("cluster_health")
    app_id = str(client.app_id)
    assert status["tables"].get(app_id, {}).get("status") == "degraded"
    events = box.admin.call("health_events", table=app_id)
    assert events and all(e["entity"][0] == "replica" for e in events)
    out = io.StringIO()
    from pegasus_tpu.tools.shell import _dispatch

    _dispatch(argparse.Namespace(cmd="timeline", target="tt",
                                 window="10m", json=False), box, out)
    assert "table_write_p99" in out.getvalue()


def test_timeseries_dump_verb_and_health_status_verb(cluster):
    cluster.create_table("d", partition_count=2)
    client = cluster.client("d")
    for _ in range(3):
        _load(client)
        cluster.step()
    stub = cluster.stubs["node0"]
    rows = stub.commands.call("timeseries-dump", ["write", "node0"])
    assert rows and all(r["entity"] == "write" for r in rows)
    assert all(r["points"] for r in rows)
    # wildcarded positions + window arg
    rows = stub.commands.call("timeseries-dump", ["", "", "", "60"])
    assert rows
    st = stub.commands.call("health.status", [])
    assert st["status"] == "ok" and st["ring_bytes"] > 0
    assert stub.commands.call("health.events", []) == []


def test_parse_window_and_render_smoke():
    assert parse_window("90s") == 90.0
    assert parse_window("5m") == 300.0
    assert parse_window("2h") == 7200.0
    assert parse_window("42") == 42.0
    text = render_timeline({
        "target": "node0", "window": [0.0, 60.0], "status": "degraded",
        "events": [{"ts": 30.0, "firing": True, "severity": "degraded",
                    "rule": "r", "entity": ["rpc", "node0"],
                    "metric": "m", "reason": "m=2 > 1"}],
        "series": [{"entity": "rpc", "id": "node0", "metric": "m",
                    "kind": "rate",
                    "points": [[10.0, 0.0], [30.0, 2.0], [50.0, 1.0]]}],
        "traces": [{"trace": "ab", "name": "client_read",
                    "node": "node0", "total_ms": 42.0}]})
    assert "FIRING" in text and "client_read" in text and "|" in text
