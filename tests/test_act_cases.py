"""The .act scripted-case tier (parity: simple_kv .act harness — fault
classes by case number, deterministic seeded runs)."""

import glob
import os

import pytest

from pegasus_tpu.runtime.act import ActRunner

CASES = sorted(glob.glob(os.path.join(os.path.dirname(__file__),
                                      "cases", "*.act")))


@pytest.mark.parametrize("case", CASES, ids=[os.path.basename(c)
                                             for c in CASES])
def test_act_case(case, tmp_path):
    runner = ActRunner(str(tmp_path / "c"), n_nodes=4, seed=7)
    try:
        runner.run_file(case)
    finally:
        runner.close()


def test_act_cases_deterministic(tmp_path):
    """Same seed -> byte-identical outcome; a failing schedule replays."""
    for trial in range(2):
        runner = ActRunner(str(tmp_path / f"d{trial}"), n_nodes=4, seed=3)
        try:
            runner.run_file(CASES[0])
            after = runner.cluster.loop.now
        finally:
            runner.close()
        if trial == 0:
            first = after
        else:
            assert after == first


def test_act_assertion_failures_surface(tmp_path):
    from pegasus_tpu.runtime.act import ActError

    runner = ActRunner(str(tmp_path / "c"), n_nodes=3, seed=1)
    try:
        with pytest.raises(ActError, match="wanted 'nope'"):
            runner.run_text(
                "create: t partitions=2 replicas=2\n"
                "set: hk sk actual\n"
                "expect_read: hk sk nope\n", "inline")
    finally:
        runner.close()


_FAULT600 = [c for c in CASES
             if os.path.basename(c).startswith("case-6")]
# an empty glob would silently skip the whole seed-diversity suite
assert _FAULT600, "no case-6xx act files found"


@pytest.mark.parametrize("seed", [1, 13, 42])
@pytest.mark.parametrize("case", _FAULT600,
                         ids=[os.path.basename(c) for c in _FAULT600])
def test_act_fault600_seed_diversity(seed, case, tmp_path):
    """The duplication/backup/recovery cases must hold under DIFFERENT
    simulator schedules, not just the canonical seed — a round-5 sweep
    found a real livelock (a dropped follower-config ask wedging
    duplication forever) that the canonical schedule never exercised."""
    runner = ActRunner(str(tmp_path / "c"), n_nodes=4, seed=seed)
    try:
        runner.run_file(case)
    finally:
        runner.close()
