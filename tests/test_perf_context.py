"""Query-level observability (PR 15): PerfContext cost vectors,
one-command EXPLAIN, the workload profiler, and the cost-model drift
watchdog.

The acceptance pins: (1) an isolated op's explain counters RECONCILE
with the same-run storage-entity metric deltas (blocks_decoded vs
block_cache_miss, bloom/phash-pruned vs their node counters); (2) a
planted mis-prediction (fail-point-scaled kernel time) drives the
cost-model drift gauge across threshold and fires its health rule;
(3) solo and batched slow-log entries carry the SAME perf field set.
"""

import pytest

from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.client.table import Table
from pegasus_tpu.server.explain import (
    explain_op,
    from_trace,
    op_from_spec,
    render_report,
    render_trace_report,
    spec_from_words,
)
from pegasus_tpu.server.workload import DRIFT, WorkloadStats, fold_summaries
from pegasus_tpu.utils import perf_context as perf
from pegasus_tpu.utils.flags import FLAGS
from pegasus_tpu.utils.metrics import METRICS

_STORAGE = METRICS.entity("storage", "node")


@pytest.fixture
def table(tmp_path):
    t = Table(str(tmp_path / "t"), app_id=9, app_name="perft",
              partition_count=1)
    srv = t.partitions[0]
    for i in range(300):
        srv.on_put(generate_key(b"hk%04d" % i, b"s"), b"v%06d" % i)
    srv.flush()
    srv.engine.manual_compact()
    yield t
    t.close()


def _counter(name: str) -> int:
    return _STORAGE.relaxed_counter(name).value()


def test_perf_context_vector_and_ambient():
    pc = perf.PerfContext("unit")
    d = pc.to_dict()
    # the FULL fixed vector, zeros included — field-set comparability
    # between solo and batched entries is structural
    for f in perf.FIELDS:
        assert f in d
    assert d["op"] == "unit" and d["placement"] == ""
    assert perf.current() is None
    with perf.activate(pc):
        assert perf.current() is pc
        pc.blocks_decoded += 2
    assert perf.current() is None
    assert pc.to_dict()["blocks_decoded"] == 2
    # kill switch: start() hands out nothing when off
    FLAGS.set("pegasus.perfctx", "enabled", False)
    try:
        assert perf.start("x") is None
    finally:
        FLAGS.set("pegasus.perfctx", "enabled", True)
    assert perf.start("x") is not None
    # every registered field is a declared (name, kind) pair the
    # metrics linter can check
    kinds = dict(perf.FIELD_DEFS)
    assert kinds["blocks_decoded"] == "counter"
    assert kinds["queue_wait_ms"] == "gauge"


def test_explain_counters_reconcile_with_storage_metrics(table):
    """Acceptance pin 1: for an isolated op, the explain report's
    blocks_decoded and phash-pruned counts equal the same-run storage-
    entity counter deltas."""
    srv = table.partitions[0]
    # cold present key: the phash-located block decode is the op's one
    # block touch
    op, args, _ph = op_from_spec(
        {"op": "get", "hash_key": "hk0007", "sort_key": "s"})
    pre_miss = _counter("block_cache_miss")
    pre_hit = _counter("block_cache_hit")
    rep = explain_op(srv, op, args)
    assert rep["result"]["status"] == 0
    pcd = rep["perf"]
    assert pcd["blocks_decoded"] == _counter("block_cache_miss") - pre_miss
    assert pcd["block_cache_hit"] == _counter("block_cache_hit") - pre_hit
    assert pcd["blocks_decoded"] + pcd["block_cache_hit"] >= 1
    assert pcd["rows_survived"] == 1 and pcd["bytes_returned"] > 0
    # absent key INSIDE the run fences: pruned by the perfect hash with
    # zero block touches, and the counts reconcile
    op, args, _ph = op_from_spec(
        {"op": "get", "hash_key": "hk0100", "sort_key": "zz"})
    pre_ph = _counter("phash_useful_count")
    pre_miss = _counter("block_cache_miss")
    rep = explain_op(srv, op, args)
    assert rep["result"]["status"] != 0
    pcd = rep["perf"]
    assert pcd["phash_pruned"] == \
        _counter("phash_useful_count") - pre_ph == 1
    assert pcd["blocks_decoded"] == \
        _counter("block_cache_miss") - pre_miss == 0
    # bloom path (phash probing off): bloom_pruned reconciles too
    # (fresh absent key — the per-generation point cache already
    # remembers the one above, which is itself the layer working)
    FLAGS.set("pegasus.server", "phash_probe", False)
    try:
        pre_bl = _counter("bloom_useful_count")
        rep = explain_op(srv, "get",
                         generate_key(b"hk0101", b"zz"))
        assert rep["perf"]["bloom_pruned"] == \
            _counter("bloom_useful_count") - pre_bl == 1
    finally:
        FLAGS.set("pegasus.server", "phash_probe", True)
    # rendering: tree + rollup lines
    text = render_report(rep)
    assert "EXPLAIN get" in text and "bloom_pruned=1" in text


def test_solo_and_batched_slow_entries_field_parity(table):
    """Acceptance pin 3 / satellite: the solo on_get fallback populates
    the SAME PerfContext field set as the batched path."""
    srv = table.partitions[0]
    srv.slow_log.threshold_ms = -1.0
    try:
        key = generate_key(b"hk0005", b"s")
        st, _v = srv.on_get(key)
        assert st == 0
        solo = srv.slow_log.dump()[-1]
        out = srv.on_point_read_batch([("get", key, None)])
        assert out[0][0] == 0
        batched = srv.slow_log.dump()[-1]
    finally:
        srv.slow_log.threshold_ms = 20.0
    assert solo["name"].startswith("point_get.")
    assert batched["name"].startswith("point_get_batch.")
    assert "perf" in solo and "perf" in batched
    # THE regression pin: identical field sets, so dashboards and the
    # explain renderer read both shapes with one schema
    assert set(solo["perf"]) == set(batched["perf"])
    # and the load-bearing fields moved identically for the same op
    for f in ("ops", "keys_resolved", "rows_evaluated",
              "rows_survived", "runs_considered"):
        assert solo["perf"][f] > 0, f
        assert batched["perf"][f] > 0, f
    assert solo["perf"]["placement"] == \
        batched["perf"]["placement"] == "native"


def test_explain_scan_reports_selectivity_shape(table):
    srv = table.partitions[0]
    op, args, _ph = op_from_spec({"op": "scan", "hash_key": "hk0002"})
    rep = explain_op(srv, op, args)
    pcd = rep["perf"]
    assert rep["result"]["rows"] == 1
    assert pcd["rows_evaluated"] >= pcd["rows_survived"] >= 1
    assert pcd["blocks_planned"] >= 1
    assert [s["stage"] for s in rep["stages"]][0] == "plan"
    assert "EXPLAIN scan" in render_report(rep)
    # the workload profiler saw the scan's selectivity
    summary = srv.workload.summary()
    assert summary["scan_ops"] >= 1
    assert 0.0 < summary["scan_selectivity_p50"] <= 100.0


def test_explain_from_trace_rebuilds_report(table):
    """A span that served an instrumented op carries the cost vector in
    its perf tag; explain --from-trace rebuilds the report from the
    dump alone."""
    from pegasus_tpu.utils import tracing

    srv = table.partitions[0]
    ring = tracing.ring_for("perfnode")
    span = ring.start("client_read")
    with tracing.activate(span):
        out = srv.on_point_read_batch(
            [("get", generate_key(b"hk0009", b"s"), None)])
    span.finish()
    assert out[0][0] == 0
    spans = ring.dump(span.trace_id)
    rep = from_trace(spans, span.trace_id)
    assert len(rep["ops"]) == 1
    op = rep["ops"][0]
    assert op["perf"]["rows_survived"] == 1
    assert any(s["stage"] == "plan" for s in op["stages"])
    text = render_trace_report(rep)
    assert span.trace_id in text and "rows:" in text


def test_carrier_span_merges_per_partition_vectors(tmp_path):
    """A batched RPC serving MANY partitions under ONE carrier span:
    each partition's flush context MERGES into the span's perf tag
    (counters sum) — assignment would keep only the last partition."""
    from pegasus_tpu.server.read_coordinator import point_read_multi
    from pegasus_tpu.utils import tracing

    t = Table(str(tmp_path / "mt"), app_id=13, app_name="merget",
              partition_count=2)
    # one key per partition so the flush really spans both
    from pegasus_tpu.base.key_schema import key_hash_parts

    keys = {}
    i = 0
    while len(keys) < 2:
        hk = b"mk%04d" % i
        keys.setdefault(key_hash_parts(hk, b"s") % 2,
                        generate_key(hk, b"s"))
        i += 1
    for pidx, key in keys.items():
        t.partitions[pidx].on_put(key, b"v%d" % pidx)
    ring = tracing.ring_for("mergenode")
    span = ring.start("client_read_batch")
    with tracing.activate(span):
        out = point_read_multi(
            [(t.partitions[p], [("get", k, None)])
             for p, k in sorted(keys.items())])
    span.finish()
    assert [r[0][0] for r in out] == [0, 0]
    pcd = span.tags.get("perf")
    assert pcd is not None
    assert pcd["ops"] == 2  # both partitions' flushes, summed
    assert pcd["rows_survived"] == 2
    t.close()


def test_write_slow_entry_carries_queue_wait(tmp_path):
    """The write apply path's context: rows + the group-commit window
    wait (append_plog -> plog_durable), attached to the slow entry."""
    from pegasus_tpu.tools.cluster import SimCluster

    cluster = SimCluster(str(tmp_path / "wc"), n_nodes=3)
    try:
        cluster.create_table("wt", partition_count=2, replica_count=3)
        for stub in cluster.stubs.values():
            for r in stub.replicas.values():
                r.server.slow_log.threshold_ms = -1.0
        c = cluster.client("wt")
        assert c.set(b"wk", b"s", b"v" * 100) == 0
        entries = []
        for stub in cluster.stubs.values():
            for r in stub.replicas.values():
                entries += [e for e in r.server.slow_log.dump()
                            if e.get("name", "").startswith("write.")]
        assert entries, "no write slow entries captured"
        with_perf = [e for e in entries if "perf" in e]
        assert with_perf, "write entries carry no perf vector"
        pcd = with_perf[-1]["perf"]
        assert pcd["op"] == "write"
        assert pcd["rows_evaluated"] >= 1
        assert pcd["queue_wait_ms"] >= 0.0
        assert set(pcd) == set(
            perf.PerfContext("x").to_dict())  # same schema as reads
    finally:
        cluster.close()


def test_workload_profiler_shapes_and_fold(table):
    srv = table.partitions[0]
    base = srv.workload.summary()
    srv.on_point_read_batch(
        [("get", generate_key(b"hk%04d" % i, b"s"), None)
         for i in range(16)])
    srv.on_put(generate_key(b"hkw", b"s"), b"x" * 500)
    s = srv.workload.summary()
    assert s["read_ops"] >= base["read_ops"] + 16
    assert s["write_ops"] >= base["write_ops"] + 1
    assert s["read_batch_p99"] >= 16
    assert s["value_bytes_p99"] >= 7  # b"v%06d" values
    fold = fold_summaries([s, dict(s, read_ops=5, hot_share=0.9)])
    assert fold["partitions"] == 2
    assert fold["read_ops"] == s["read_ops"] + 5
    assert fold["hot_share"] == 0.9


def test_drift_gauge_crosses_on_planted_misprediction(tmp_path):
    """Acceptance pin 2a: fail-point-scaled kernel time drives the
    cost-model drift gauge (EWMA, warmup discarded) over the health
    rule's threshold."""
    from pegasus_tpu.server.types import GetScannerRequest
    from pegasus_tpu.utils.fail_point import FAIL_POINTS

    # raw blocks: the compressed encoded-probe path answers static
    # masks host-side with no kernel dispatch — the drift audit lives
    # on the stacked device-eval path, so build an uncompressed store
    old_codec = FLAGS.get("pegasus.storage", "block_codec")
    FLAGS.set("pegasus.storage", "block_codec", "none")
    try:
        t = Table(str(tmp_path / "dt"), app_id=11, app_name="driftt",
                  partition_count=1)
        srv = t.partitions[0]
        for i in range(200):
            srv.on_put(generate_key(b"dk%04d" % i, b"s"), b"v%d" % i)
        srv.flush()
        srv.engine.manual_compact()
    finally:
        FLAGS.set("pegasus.storage", "block_codec", old_codec)
    DRIFT.reset()
    gauge = METRICS.entity("workload", "node").gauge(
        "cost_model_drift_ratio")
    FAIL_POINTS.setup()
    FAIL_POINTS.cfg("perf::kernel_time_scale", "return(5000)")
    try:
        from pegasus_tpu.ops.predicates import FT_MATCH_PREFIX

        # each DISTINCT filter pattern is a fresh mask flavor -> a real
        # stacked kernel eval (cached masks never re-dispatch); the
        # first DRIFT_WARMUP samples are discarded as compile warmup
        for i in range(8):
            resp = srv.on_get_scanner_batch([GetScannerRequest(
                batch_size=50, one_page=True,
                hash_key_filter_type=FT_MATCH_PREFIX,
                hash_key_filter_pattern=b"dk%02d" % i)])[0]
            assert resp.error == 0
        assert gauge.value() > 16.0, DRIFT.status()
        assert DRIFT.status()["classes"]["rules"]["samples"] >= 4
        # ...and the whole chain end-to-end: a recorder ringing the
        # gauge feeds the shipped rule, which FIRES on the second hot
        # tick — the mis-calibration became a typed HealthEvent
        from pegasus_tpu.utils import health as health_mod
        from pegasus_tpu.utils.health import HealthEngine
        from pegasus_tpu.utils.timeseries import FlightRecorder

        clock = [5000.0]
        rec = FlightRecorder(
            "driftnode", clock=lambda: clock[0],
            owns=lambda e: (e.entity_type,
                            e.entity_id) == ("workload", "node"))
        eng = HealthEngine("driftnode", rec)
        try:
            rec.tick(force=True)
            eng.evaluate()  # arms (hold=2)
            clock[0] += 10.0
            rec.tick(force=True)
            fired = [e for e in eng.evaluate()
                     if e.rule == "cost_model_drift" and e.firing]
            assert fired, "planted mis-prediction did not fire"
            assert fired[0].metric == "cost_model_drift_ratio"
        finally:
            eng.close()
            health_mod.reset_capture()
    finally:
        FAIL_POINTS.teardown()
        DRIFT.reset()
    t.close()


def test_drift_health_rule_fires_and_clears(tmp_path):
    """Acceptance pin 2b: the shipped cost_model_drift rule turns a
    sustained over-threshold gauge into a typed HealthEvent (hold=2:
    one hot tick alone must not fire)."""
    from pegasus_tpu.utils import health as health_mod
    from pegasus_tpu.utils.health import HealthEngine
    from pegasus_tpu.utils.timeseries import FlightRecorder

    clock = [1000.0]
    rec = FlightRecorder(
        "dnode", clock=lambda: clock[0],
        owns=lambda e: (e.entity_type, e.entity_id) == ("workload",
                                                        "node"))
    eng = HealthEngine("dnode", rec)
    assert any(r.name == "cost_model_drift" for r in eng.rules)
    gauge = METRICS.entity("workload", "node").gauge(
        "cost_model_drift_ratio")
    try:
        gauge.set(40.0)
        rec.tick(force=True)
        events = eng.evaluate()
        assert events == []  # hold=2: first hot tick arms, not fires
        clock[0] += 10.0
        rec.tick(force=True)
        events = eng.evaluate()
        fired = [e for e in events if e.rule == "cost_model_drift"]
        assert fired and fired[0].firing
        assert fired[0].entity == ("workload", "node")
        # recovery: calm gauge clears it after clear_hold ticks
        gauge.set(1.0)
        cleared = []
        for _ in range(4):
            clock[0] += 10.0
            rec.tick(force=True)
            cleared += [e for e in eng.evaluate()
                        if e.rule == "cost_model_drift"
                        and not e.firing]
        assert cleared
    finally:
        gauge.set(0.0)
        eng.close()
        health_mod.reset_capture()


def test_shell_explain_and_workload_root_mode(tmp_path, capsys):
    """The operator surface end-to-end in --root mode: explain renders
    a plan tree; workload prints the table profile; placement prints
    the offload verdict."""
    import json as _json

    from pegasus_tpu.tools.onebox import Onebox
    from pegasus_tpu.tools.shell import main as shell_main

    root = str(tmp_path / "box")
    box = Onebox(root)
    t = box.create_table("st", partition_count=2)
    c = box.client("st")
    for i in range(150):
        assert c.set(b"sk%03d" % i, b"s", b"val%d" % i) == 0
    for p_ in t.all_partitions():
        p_.flush()
        p_.engine.manual_compact()
    box.close()
    rc = shell_main(["--root", root, "explain", "st",
                     "get", "sk010", "s"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "EXPLAIN get" in out and "finish" in out
    rc = shell_main(["--root", root, "explain", "st", "scan", "sk011"])
    out = capsys.readouterr().out
    assert rc == 0 and "EXPLAIN scan" in out
    rc = shell_main(["--root", root, "workload", "st", "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    data = _json.loads(out)
    assert "st" in data and data["st"]["table"]["partitions"] == 2
    rc = shell_main(["--root", root, "placement", "probe"])
    out = capsys.readouterr().out
    assert rc == 0
    data = _json.loads(out)
    assert "breakdown" in data and "drift" in data
    assert data["breakdown"]["workload"] == "probe"


def test_stub_verbs_and_workload_config_sync(tmp_path):
    """Wire surfaces: the node's placement / workload.stats /
    perf.explain verbs answer, and the workload digest rides
    config-sync into the meta `workload` admin fold."""
    import json as _json

    from pegasus_tpu.tools.cluster import SimCluster

    cluster = SimCluster(str(tmp_path / "vc"), n_nodes=3)
    try:
        cluster.create_table("vt", partition_count=2, replica_count=3)
        c = cluster.client("vt")
        for i in range(40):
            assert c.set(b"vk%03d" % i, b"s", b"v%d" % i) == 0
        for i in range(40):
            assert c.get(b"vk%03d" % i, b"s")[0] == 0
        cluster.step(rounds=3)
        node = next(iter(cluster.stubs))
        res = cluster.stubs[node].commands.call(
            "placement", ["probe", "4096"])
        assert res["breakdown"]["workload"] == "probe"
        res = cluster.stubs[node].commands.call("workload.stats", [])
        assert res["node"] == node
        # perf.explain on whichever node hosts the key's primary
        spec = _json.dumps({"app_id": c.app_id, "op": "get",
                            "hash_key": "vk001", "sort_key": "s"})
        rep = None
        for n in cluster.stubs:
            try:
                rep = cluster.stubs[n].commands.call("perf.explain",
                                                     [spec])
                break
            except Exception:  # noqa: BLE001 - not the primary host
                continue
        assert rep is not None and rep["result"]["status"] == 0
        assert rep["perf"]["ops"] == 1
        # meta-side fold off the config-sync digests
        status = cluster.meta.workload_status("vt")
        assert "vt" in status
        fold = status["vt"]["table"]
        assert fold["partitions"] >= 2
        assert fold["read_ops"] > 0 and fold["write_ops"] > 0
    finally:
        cluster.close()
