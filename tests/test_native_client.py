"""Native C++ wire client tests: builds with the image toolchain, speaks
the PGT1 wire format, and round-trips against a LIVE multi-process
onebox (the second-language-client parity check)."""

import json
import os
import shutil
import time

import pytest

from pegasus_tpu.native import wire_client


def test_native_crc64_matches_python():
    lib = wire_client.load()
    if lib is None:
        pytest.skip("no native toolchain")
    from pegasus_tpu.base.crc import crc64

    for data in (b"", b"a", b"hello world", bytes(range(256)) * 3):
        assert lib.pegc_crc64(data, len(data)) == crc64(data), data


def test_native_client_against_onebox(tmp_path):
    lib = wire_client.load()
    if lib is None:
        pytest.skip("no native toolchain")
    from pegasus_tpu.tools import onebox_cluster as ob

    d = str(tmp_path / "onebox")
    shutil.rmtree(d, ignore_errors=True)
    cfg = ob.start(d, n_replica=2)
    nc = None
    try:
        from pegasus_tpu.utils.errors import PegasusError

        admin = ob.OneboxAdmin(d)
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                if len(admin.call("list_nodes", timeout=6)) == 2:
                    break
            except PegasusError:
                pass
            time.sleep(0.5)
        admin.create_table("native", partition_count=4, replica_count=2)
        admin.close()

        book = {n: (c["host"], c["port"])
                for n, c in cfg["nodes"].items()}
        metas = [n for n, c in cfg["nodes"].items()
                 if c["role"] == "meta"]
        nc = wire_client.NativeClient("cpp-client", book, metas, "native")
        assert nc.refresh(), nc.last_error()
        assert nc.partition_count == 4

        # writes from C++ land on the right partitions (crc64 routing)
        for i in range(20):
            assert nc.set(b"ck%02d" % i, b"s", b"cv%d" % i) == 0
        for i in range(20):
            assert nc.get(b"ck%02d" % i, b"s") == (0, b"cv%d" % i)
        assert nc.get(b"missing", b"s")[0] == 1  # NotFound
        assert nc.delete(b"ck00", b"s") == 0
        assert nc.get(b"ck00", b"s")[0] == 1

        # interop: the PYTHON client reads what C++ wrote
        pc = ob.connect("native", d)
        assert pc.get(b"ck01", b"s") == (0, b"cv1")
        assert pc.set(b"from-python", b"s", b"pv") == 0
        assert nc.get(b"from-python", b"s") == (0, b"pv")
    finally:
        if nc is not None:
            nc.close()
        ob.stop(d)
