"""Native C++ wire client tests: builds with the image toolchain, speaks
the PGT1 wire format, and round-trips against a LIVE multi-process
onebox (the second-language-client parity check)."""

import json
import os
import shutil
import time

import pytest

from pegasus_tpu.native import wire_client


def test_native_crc64_matches_python():
    lib = wire_client.load()
    if lib is None:
        pytest.skip("no native toolchain")
    from pegasus_tpu.base.crc import crc64

    for data in (b"", b"a", b"hello world", bytes(range(256)) * 3):
        assert lib.pegc_crc64(data, len(data)) == crc64(data), data


def test_native_client_against_onebox(tmp_path):
    lib = wire_client.load()
    if lib is None:
        pytest.skip("no native toolchain")
    from pegasus_tpu.tools import onebox_cluster as ob

    d = str(tmp_path / "onebox")
    shutil.rmtree(d, ignore_errors=True)
    cfg = ob.start(d, n_replica=2)
    nc = None
    try:
        from pegasus_tpu.utils.errors import PegasusError

        admin = ob.OneboxAdmin(d)
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                if len(admin.call("list_nodes", timeout=6)) == 2:
                    break
            except PegasusError:
                pass
            time.sleep(0.5)
        admin.create_table("native", partition_count=4, replica_count=2)
        admin.close()

        book = {n: (c["host"], c["port"])
                for n, c in cfg["nodes"].items()}
        metas = [n for n, c in cfg["nodes"].items()
                 if c["role"] == "meta"]
        nc = wire_client.NativeClient("cpp-client", book, metas, "native")
        assert nc.refresh(), nc.last_error()
        assert nc.partition_count == 4

        # writes from C++ land on the right partitions (crc64 routing)
        for i in range(20):
            assert nc.set(b"ck%02d" % i, b"s", b"cv%d" % i) == 0
        for i in range(20):
            assert nc.get(b"ck%02d" % i, b"s") == (0, b"cv%d" % i)
        assert nc.get(b"missing", b"s")[0] == 1  # NotFound
        assert nc.delete(b"ck00", b"s") == 0
        assert nc.get(b"ck00", b"s")[0] == 1

        # interop: the PYTHON client reads what C++ wrote
        pc = ob.connect("native", d)
        assert pc.get(b"ck01", b"s") == (0, b"cv1")
        assert pc.set(b"from-python", b"s", b"pv") == 0
        assert nc.get(b"from-python", b"s") == (0, b"pv")

        # multi_get: every sort key of one hash key in one native call
        for i in range(12):
            assert nc.set(b"mgk", b"s%02d" % i, b"mv%d" % i) == 0
        st, kvs = nc.multi_get(b"mgk")
        assert st == 0
        assert kvs == {b"s%02d" % i: b"mv%d" % i for i in range(12)}

        # scanner: PAGED native scan round-trip (batch_size forces
        # multiple get_scanner/scan pages over the wire)
        for i in range(57):
            assert nc.set(b"scanhk", b"r%03d" % i, b"sv%d" % i) == 0
        rows = list(nc.scan(b"scanhk", batch_size=10))
        assert [sk for sk, _v in rows] == [b"r%03d" % i
                                           for i in range(57)]
        assert rows[13] == (b"r013", b"sv13")

        # check_and_set: value-exist check gates the write (parity:
        # pegasus client.h check_and_set, CT_VALUE_EXIST=3)
        CT_EXIST = 3  # CasCheckType.CT_VALUE_EXIST
        st, exist = nc.check_and_set(b"cask", b"guard", CT_EXIST, b"",
                                     b"dest", b"won't-win")
        assert st != 0 and not exist  # guard missing: rejected
        assert nc.set(b"cask", b"guard", b"here") == 0
        st, exist = nc.check_and_set(b"cask", b"guard", CT_EXIST, b"",
                                     b"dest", b"wins")
        assert st == 0 and exist
        assert nc.get(b"cask", b"dest") == (0, b"wins")

        # check_and_mutate: guarded single-mutate (SET)
        st, exist = nc.check_and_mutate(b"cask", b"guard", CT_EXIST,
                                        b"", 0, b"dest2", b"mutated")
        assert st == 0 and exist
        assert nc.get(b"cask", b"dest2") == (0, b"mutated")
        st, _ = nc.check_and_mutate(b"cask", b"nope", CT_EXIST, b"",
                                    0, b"dest3", b"never")
        assert st != 0
        assert nc.get(b"cask", b"dest3")[0] == 1

        # python client sees the C++ CAS results (wire interop both ways)
        assert pc.get(b"cask", b"dest") == (0, b"wins")
    finally:
        if nc is not None:
            nc.close()
        ob.stop(d)
