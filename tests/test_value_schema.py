"""Value schema tests (parity: src/base/pegasus_value_schema.h)."""

import struct

from pegasus_tpu.base.value_schema import (
    check_if_record_expired,
    check_if_ts_expired,
    epoch_now,
    expire_ts_from_ttl,
    extract_expire_ts,
    extract_timestamp_from_timetag,
    extract_timetag,
    extract_user_data,
    generate_timetag,
    generate_value,
    header_length,
    update_expire_ts,
)


def test_v0_layout():
    v = generate_value(0, b"payload", expire_ts=12345)
    assert v[:4] == struct.pack(">I", 12345)
    assert extract_expire_ts(0, v) == 12345
    assert extract_user_data(0, v) == b"payload"
    assert header_length(0) == 4


def test_v1_layout():
    tag = generate_timetag(timestamp_us=1_700_000_000_000_000, cluster_id=5,
                           deleted=False)
    v = generate_value(1, b"data", expire_ts=99, timetag=tag)
    assert extract_expire_ts(1, v) == 99
    assert extract_timetag(1, v) == tag
    assert extract_user_data(1, v) == b"data"
    assert header_length(1) == 12


def test_timetag_fields():
    ts, cid = 123456789012345, 42
    tag = generate_timetag(ts, cid, True)
    assert extract_timestamp_from_timetag(tag) == ts
    assert tag & 1 == 1
    assert (tag >> 1) & 0x7F == cid


def test_expiry_predicate():
    # parity: expired iff expire_ts > 0 and expire_ts <= now
    assert not check_if_ts_expired(100, 0)      # no TTL
    assert not check_if_ts_expired(100, 101)    # future
    assert check_if_ts_expired(100, 100)        # boundary: expired
    assert check_if_ts_expired(100, 99)


def test_record_expiry_roundtrip():
    now = epoch_now()
    live = generate_value(0, b"x", expire_ts=now + 1000)
    dead = generate_value(0, b"x", expire_ts=max(1, now - 1000))
    eternal = generate_value(0, b"x", expire_ts=0)
    assert not check_if_record_expired(0, now, live)
    assert check_if_record_expired(0, now, dead)
    assert not check_if_record_expired(0, now, eternal)


def test_update_expire_ts():
    v = generate_value(1, b"abc", expire_ts=5, timetag=77)
    v2 = update_expire_ts(1, v, 500)
    assert extract_expire_ts(1, v2) == 500
    assert extract_timetag(1, v2) == 77
    assert extract_user_data(1, v2) == b"abc"


def test_expire_ts_from_ttl():
    assert expire_ts_from_ttl(0) == 0
    assert expire_ts_from_ttl(-5) == 0
    assert expire_ts_from_ttl(10, now=100) == 110
