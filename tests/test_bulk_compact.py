"""Bulk block-level compaction (the GB/s path, storage/lsm.py).

Parity intent: manual CompactRange over a settled store
(pegasus_manual_compact_service.h:48) — here a pure-L1 store takes a
columnar rewrite with vectorized survivor gathers instead of the
per-record merge. These tests pin the path-specific behaviors: verbatim
re-serialization of untouched blocks, run-capacity rolling, TTL header
patching (and its absence at the raw-engine layer), and equivalence
with the merge path.
"""

import os

import numpy as np
import pytest

from pegasus_tpu.base.key_schema import generate_key
from pegasus_tpu.base.value_schema import (
    epoch_now,
    extract_expire_ts,
    generate_value,
)
from pegasus_tpu.storage.engine import StorageEngine, WriteBatchItem
from pegasus_tpu.storage.wal import OP_PUT


def _fill(eng, n, ets_of=lambda i: 0, prefix=b"hk", start_decree=1):
    items = [WriteBatchItem(OP_PUT, generate_key(b"%s%06d" % (prefix, i),
                                                 b"s"),
                            generate_value(1, b"v%d" % i, ets_of(i)),
                            ets_of(i))
             for i in range(n)]
    d = start_decree
    for off in range(0, n, 1000):
        eng.write_batch(items[off:off + 1000], decree=d)
        d += 1
    eng.flush()
    return d


def test_bulk_path_engages_and_matches_merge(tmp_path):
    """Second compact (pure L1) must produce the same visible records
    the merge compact produced."""
    eng = StorageEngine(str(tmp_path / "e"))
    now = epoch_now()
    _fill(eng, 3000, ets_of=lambda i: (now - 10 if i % 10 == 0 else 0))
    eng.manual_compact()            # merge path (L0 exists)
    assert eng.lsm.bulk_compact_eligible()
    first = [(k, v, e) for k, v, e in eng.iterate()]
    assert len(first) == 2700       # 10% expired dropped
    eng.manual_compact()            # bulk path
    second = [(k, v, e) for k, v, e in eng.iterate()]
    assert first == second
    eng.close()


def test_untouched_blocks_survive_verbatim(tmp_path):
    """A no-op bulk compact preserves every record and the columnar
    layout (hash_lo carried over, values byte-identical)."""
    eng = StorageEngine(str(tmp_path / "e"))
    _fill(eng, 2500)
    eng.manual_compact()
    before = [(k, v, e) for k, v, e in eng.iterate()]
    runs_before = [t.path for t in eng.lsm.l1_runs]
    eng.manual_compact()            # bulk, nothing to drop
    after = [(k, v, e) for k, v, e in eng.iterate()]
    assert before == after
    # files were rewritten (new names), blocks intact with hash_lo
    assert [t.path for t in eng.lsm.l1_runs] != runs_before
    for run in eng.lsm.l1_runs:
        for i in range(len(run.blocks)):
            assert run.read_block(i).hash_lo is not None
    eng.close()


def test_run_capacity_rolling(tmp_path):
    """Bulk rewrite honors the L1 run size cap: many blocks roll into
    multiple output runs, in key order, nothing lost."""
    eng = StorageEngine(str(tmp_path / "e"), block_capacity=128)
    eng.lsm._l1_run_capacity = 500
    _fill(eng, 4000)
    eng.manual_compact()
    eng.manual_compact()            # bulk path with rolling
    assert len(eng.lsm.l1_runs) >= 8
    keys = [k for k, _v, _e in eng.iterate()]
    assert len(keys) == 4000
    assert keys == sorted(keys)
    # runs are non-overlapping and ordered
    for a, b in zip(eng.lsm.l1_runs, eng.lsm.l1_runs[1:]):
        assert a.last_key < b.first_key
    eng.close()


def test_default_ttl_patches_headers_only_for_server_tables(tmp_path):
    """The expire column is authoritative at the engine layer; the
    embedded value header is patched only when the engine is told values
    are pegasus-encoded (PartitionServer tables set the flag)."""
    now = epoch_now()
    # raw engine: values opaque -> header untouched, column updated
    eng = StorageEngine(str(tmp_path / "raw"))
    key = generate_key(b"h", b"s")
    eng.write_batch([WriteBatchItem(OP_PUT, key, b"xy", 0)], decree=1)
    eng.manual_compact(default_ttl=100, now=now)
    eng.manual_compact(default_ttl=100, now=now)  # bulk path too
    v, ets = eng.get(key)
    assert v == b"xy" and ets == now + 100
    eng.close()

    # encoded-values engine: both the column AND the header move
    eng2 = StorageEngine(str(tmp_path / "enc"),
                         values_carry_expire_header=True)
    eng2.write_batch([WriteBatchItem(
        OP_PUT, key, generate_value(1, b"payload", 0), 0)], decree=1)
    eng2.manual_compact(default_ttl=100, now=now)   # merge path
    v, ets = eng2.get(key)
    assert ets == now + 100 and extract_expire_ts(1, v) == now + 100
    eng2.manual_compact(default_ttl=0, now=now)     # bulk no-op keeps it
    v, ets = eng2.get(key)
    assert extract_expire_ts(1, v) == now + 100
    eng2.close()


def test_bulk_ttl_header_patch_and_reopen(tmp_path):
    """Bulk-path default-TTL rewrite patches the BE-u32 header via the
    vectorized scatter, and the result survives a cold reopen."""
    now = epoch_now()
    path = str(tmp_path / "e")
    eng = StorageEngine(path, values_carry_expire_header=True)
    _fill(eng, 1500)
    eng.manual_compact()                        # merge -> pure L1
    eng.manual_compact(default_ttl=500, now=now)  # BULK ttl rewrite
    key = generate_key(b"hk000007", b"s")
    v, ets = eng.get(key)
    assert ets == now + 500 and extract_expire_ts(1, v) == now + 500
    eng.close()
    eng2 = StorageEngine(path, values_carry_expire_header=True)
    v, ets = eng2.get(key)
    assert ets == now + 500 and extract_expire_ts(1, v) == now + 500
    assert sum(1 for _ in eng2.iterate()) == 1500
    eng2.close()


def test_mixed_key_widths_bucket_correctly(tmp_path):
    """Blocks with different key-width buckets share one compaction wave
    without cross-contamination."""
    eng = StorageEngine(str(tmp_path / "e"))
    now = epoch_now()
    short = [WriteBatchItem(OP_PUT, generate_key(b"a%d" % i, b"s"),
                            generate_value(1, b"s%d" % i, 0), 0)
             for i in range(400)]
    long_ = [WriteBatchItem(
        OP_PUT, generate_key(b"zzzz-%064d" % i, b"sort-%032d" % i),
        generate_value(1, b"L%d" % i, now - 5 if i % 2 else 0),
        now - 5 if i % 2 else 0)
        for i in range(400)]
    eng.write_batch(short, decree=1)
    eng.write_batch(long_, decree=2)
    eng.flush()
    eng.manual_compact()
    eng.manual_compact()   # bulk across two width buckets
    rows = list(eng.iterate())
    assert sum(1 for k, _v, _e in rows if k[2:3] == b"a") == 400
    # half the long keys were expired and dropped
    assert len(rows) == 400 + 200
    eng.close()


def test_rules_and_stale_split_through_bulk(tmp_path):
    """Ruleset delete + stale-split drop both work through the bulk
    path (fused program), matching host-side expectations."""
    from pegasus_tpu.base.key_schema import key_hash
    from pegasus_tpu.ops.compaction_rules import compile_rules

    eng = StorageEngine(str(tmp_path / "e"))
    keys = [generate_key(b"user_%d" % i, b"s") for i in range(300)]
    eng.write_batch([WriteBatchItem(OP_PUT, k,
                                    generate_value(1, b"v", 0), 0)
                     for k in keys], decree=1)
    eng.flush()
    eng.manual_compact()
    # stale-split: keep only partition 3 of 8
    eng.manual_compact(validate_hash=True, pidx=3, partition_version=7)
    for k in keys:
        mine = (key_hash(k) & 7) == 3
        assert (eng.get(k) is not None) == mine
    # ruleset: delete hashkey prefix user_1 (bulk path again)
    rules = compile_rules([{"op": "delete_key", "rules": [
        {"type": "hashkey_pattern", "match": "prefix",
         "pattern": "user_1"}]}])
    eng.manual_compact(rules_filter=rules)
    for k, _v, _e in eng.iterate():
        assert not k[2:].startswith(b"user_1")
    eng.close()
