"""CI smoke for the pressure tier (tools/pressure_test.py): the same
mixed-load + online-verification loop the minutes-long operator run
uses, driven for a few seconds in-process. Parity:
src/test/pressure_test/ + kill_test/data_verifier.cpp."""

import io
import json

import pytest

from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.tools.pressure_test import PressureWorkload, run


@pytest.fixture
def cluster(tmp_path):
    c = SimCluster(str(tmp_path / "cl"), n_nodes=3)
    yield c
    c.close()


def test_pressure_smoke_no_violations(cluster):
    cluster.create_table("pressure", partition_count=4)
    client = cluster.client("pressure")
    out = io.StringIO()
    summary = run(client, duration_s=4.0, report_every=1.0, out=out)
    assert summary["violations"] == 0, summary["violation_samples"]
    assert summary["ops"] > 500  # sustained throughput, not a stall
    assert summary["keys"] > 0
    # periodic ops/s-over-time reports were emitted as JSON lines
    lines = [json.loads(ln) for ln in out.getvalue().splitlines()]
    assert len(lines) >= 3
    assert all("ops_per_s" in ln for ln in lines[:-1])


def test_pressure_workload_catches_divergence(cluster):
    """The verifier must actually DETECT corruption: wedge the model to
    disagree with the store and the next verified read must flag it."""
    cluster.create_table("pv", partition_count=2)
    client = cluster.client("pv")
    w = PressureWorkload(client, seed=3)
    assert client.set(b"pt0000001", b"s00", b"truth") == 0
    w.model[b"pt0000001"] = {b"s00": b"corrupted-expectation"}
    w._op_get()
    assert w.violations, "divergence went undetected"


def test_pressure_mix_covers_all_ops(cluster):
    cluster.create_table("pm", partition_count=2)
    client = cluster.client("pm")
    w = PressureWorkload(client, seed=11)
    for _ in range(400):
        w.step()
    assert w.violations == []
    assert w.ops == 400
    # deletions happened and the model tracked them
    assert w.rejected == 0
