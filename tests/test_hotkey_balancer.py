"""Hotkey detection, hotspot partitions, and the load balancer."""

import numpy as np
import pytest

from pegasus_tpu.meta.balancer import (
    propose_primary_moves,
    propose_secondary_moves,
)
from pegasus_tpu.meta.server_state import PartitionConfig
from pegasus_tpu.server.hotkey import (
    HotkeyCollector,
    HotkeyState,
    hotspot_partition_indices,
)


def test_hotkey_two_phase_detection():
    rng = np.random.default_rng(0)
    hc = HotkeyCollector()
    assert hc.state == HotkeyState.STOPPED
    hc.capture([b"ignored"])  # stopped: no effect
    hc.start()
    # 70% of traffic hits one key, the rest spreads
    batch = []
    for i in range(3000):
        if rng.random() < 0.7:
            batch.append(b"celebrity")
        else:
            batch.append(b"user_%d" % int(rng.integers(0, 500)))
    for off in range(0, len(batch), 256):
        hc.capture(batch[off:off + 256])
        if hc.state == HotkeyState.FINISHED:
            break
    assert hc.state == HotkeyState.FINISHED
    assert hc.result == b"celebrity"


def test_hotkey_uniform_traffic_never_fires():
    hc = HotkeyCollector()
    hc.start()
    keys = [b"user_%d" % i for i in range(5000)]
    for off in range(0, len(keys), 500):
        hc.capture(keys[off:off + 500])
    assert hc.state == HotkeyState.COARSE  # no outlier bucket
    assert hc.result is None


def test_hotspot_partition_zscore():
    qps = [100.0] * 63 + [5000.0]
    assert hotspot_partition_indices(qps) == [63]
    assert hotspot_partition_indices([100.0] * 64) == []
    assert hotspot_partition_indices([5.0]) == []


def test_primary_move_proposals():
    nodes = ["n0", "n1", "n2"]
    # n0 hogs all 6 primaries; each partition has secondaries elsewhere
    configs = {(1, i): PartitionConfig(1, "n0", ["n1", "n2"])
               for i in range(6)}
    props = propose_primary_moves(configs, nodes)
    assert len(props) == 4  # 6,0,0 -> 2,2,2
    assert all(p.kind == "move_primary" and p.from_node == "n0"
               for p in props)
    # already balanced -> nothing
    balanced = {(1, i): PartitionConfig(1, nodes[i % 3],
                                        [nodes[(i + 1) % 3]])
                for i in range(6)}
    assert propose_primary_moves(balanced, nodes) == []


def test_secondary_move_proposals():
    nodes = ["n0", "n1", "n2", "n3"]
    # n3 holds nothing; replicas pile on n0/n1/n2
    configs = {(1, i): PartitionConfig(1, "n0", ["n1", "n2"])
               for i in range(4)}
    props = propose_secondary_moves(configs, nodes)
    assert props and all(p.kind == "copy_secondary" and p.to_node == "n3"
                         for p in props)


def test_rebalance_end_to_end(tmp_path):
    from tests.test_meta import ClusterHarness
    c = ClusterHarness(tmp_path, n_nodes=3)
    try:
        # all primaries forced onto node0
        app_id = c.meta.create_app("t", partition_count=6, replica_count=3)
        c.loop.run_until_idle()
        for pidx in range(6):
            pc = c.meta.state.get_partition(app_id, pidx)
            forced = PartitionConfig(pc.ballot + 1, "node0",
                                     [n for n in pc.members()
                                      if n != "node0"])
            c.meta.state.update_partition(app_id, pidx, forced)
            c.meta._propose(app_id, pidx, forced)
        c.loop.run_until_idle()
        props = c.meta.rebalance()
        c.loop.run_until_idle()
        assert props
        counts = {n: 0 for n in ("node0", "node1", "node2")}
        for pidx in range(6):
            counts[c.meta.state.get_partition(app_id, pidx).primary] += 1
        assert max(counts.values()) - min(counts.values()) <= 1
        # the moved-to primaries actually serve
        from pegasus_tpu.replica.replica import PartitionStatus
        for pidx in range(6):
            pc = c.meta.state.get_partition(app_id, pidx)
            r = c.stubs[pc.primary].get_replica((app_id, pidx))
            assert r.status == PartitionStatus.PRIMARY
    finally:
        c.close()


def test_maxflow_routes_multihop_primary_moves():
    """The case greedy cannot solve: A's movable primaries reach only B,
    B's reach only C — flow schedules A->B and B->C together."""
    from pegasus_tpu.meta.balancer import (
        propose_primary_moves,
        propose_primary_moves_maxflow,
    )
    from pegasus_tpu.meta.server_state import PartitionConfig

    nodes = ["A", "B", "C"]
    configs = {
        # A: 3 primaries, all with secondaries ONLY on B
        (1, 0): PartitionConfig(1, "A", ["B"]),
        (1, 1): PartitionConfig(1, "A", ["B"]),
        (1, 2): PartitionConfig(1, "A", ["B"]),
        # B: 1 primary whose secondary is on C; C: none
        (1, 3): PartitionConfig(1, "B", ["C"]),
    }
    flow = propose_primary_moves_maxflow(configs, nodes)
    # final counts must be [2,1,1] in some arrangement: A->B one move AND
    # B->C one move
    counts = {"A": 3, "B": 1, "C": 0}
    for p in flow:
        assert p.gpid in configs
        pc = configs[p.gpid]
        assert pc.primary == p.from_node and p.to_node in pc.secondaries
        counts[p.from_node] -= 1
        counts[p.to_node] += 1
    assert max(counts.values()) - min(counts.values()) <= 1, (flow, counts)
    # the single-hop greedy CANNOT fully balance this topology
    greedy = propose_primary_moves(configs, nodes)
    gcounts = {"A": 3, "B": 1, "C": 0}
    for p in greedy:
        gcounts[p.from_node] -= 1
        gcounts[p.to_node] += 1
    assert max(gcounts.values()) - min(gcounts.values()) > 1


def test_balancer_simulator_property():
    """balancer_simulator parity: random clusters converge to spread<=1
    per app under repeated proposal application, with every proposal
    legal (move to an existing secondary / copy to a non-member)."""
    import random

    from pegasus_tpu.meta.balancer import propose_app_balanced_moves
    from pegasus_tpu.meta.server_state import PartitionConfig

    rng = random.Random(42)
    for trial in range(10):
        nodes = [f"n{i}" for i in range(rng.randint(3, 6))]
        configs = {}
        for app_id in range(1, rng.randint(2, 4)):
            for pidx in range(rng.choice([4, 8])):
                members = rng.sample(nodes, k=min(3, len(nodes)))
                configs[(app_id, pidx)] = PartitionConfig(
                    1, members[0], members[1:])
        for _round in range(20):
            proposals = propose_app_balanced_moves(configs, nodes)
            if not proposals:
                break
            for p in proposals:
                pc = configs[p.gpid]
                if p.kind == "move_primary":
                    assert pc.primary == p.from_node
                    assert p.to_node in pc.secondaries
                    configs[p.gpid] = PartitionConfig(
                        pc.ballot + 1, p.to_node,
                        [s for s in pc.secondaries if s != p.to_node]
                        + [pc.primary])
                else:
                    assert p.from_node in pc.secondaries
                    assert p.to_node not in pc.members()
                    configs[p.gpid] = PartitionConfig(
                        pc.ballot + 1, pc.primary,
                        [s for s in pc.secondaries if s != p.from_node]
                        + [p.to_node])
        # per-app primary spread settled to <= 1
        from collections import defaultdict

        per_app = defaultdict(lambda: {n: 0 for n in nodes})
        for (app_id, _pidx), pc in configs.items():
            per_app[app_id][pc.primary] += 1
        for app_id, counts in per_app.items():
            assert max(counts.values()) - min(counts.values()) <= 1, (
                trial, app_id, counts)


def test_hotkey_detection_wired_into_serving(tmp_path):
    """on_detect_hotkey parity: start detection on a partition, drive a
    skewed workload through the REPLICATED paths, query the hot key."""
    from pegasus_tpu.tools.cluster import SimCluster

    cluster = SimCluster(str(tmp_path / "c"), n_nodes=2)
    try:
        app_id = cluster.create_table("hot", partition_count=1,
                                      replica_count=1)
        c = cluster.client("hot")
        pc = cluster.meta.state.get_partition(app_id, 0)
        stub = cluster.stubs[pc.primary]
        assert stub.commands.call(
            "hotkey", ["start", str(app_id), "0", "write"]) == "started"
        # skewed writes: one hashkey dominates
        for i in range(400):
            hk = b"whale" if i % 4 else b"minnow%d" % i
            assert c.set(hk, b"s%03d" % i, b"v") == 0
        out = stub.commands.call("hotkey",
                                 ["query", str(app_id), "0", "write"])
        assert out["state"] == "finished" and out["hot_key"] == "whale"
        # read-side detection over point gets
        assert stub.commands.call(
            "hotkey", ["start", str(app_id), "0", "read"]) == "started"
        for i in range(400):
            hk = b"whale" if i % 4 else b"minnow%d" % (i % 40)
            c.get(hk, b"s%03d" % (i if i % 4 == 0 else 0))
        out = stub.commands.call("hotkey",
                                 ["query", str(app_id), "0", "read"])
        assert out["state"] in ("finished", "fine", "coarse")
        if out["state"] == "finished":
            assert out["hot_key"] == "whale"
    finally:
        cluster.close()


def test_drain_node_moves_all_primaries(tmp_path):
    """Graceful offline (pegasus_offline_node.sh parity): drain_node
    promotes a secondary for every partition the node leads, the node
    keeps serving as secondary, and acked data stays readable."""
    from pegasus_tpu.tools.cluster import SimCluster

    cluster = SimCluster(str(tmp_path / "c"), n_nodes=4)
    try:
        app_id = cluster.create_table("d", partition_count=8)
        c = cluster.client("d")
        for i in range(40):
            assert c.set(b"k%03d" % i, b"s", b"v%d" % i) == 0
        primaries = {cluster.meta.state.get_partition(app_id, p).primary
                     for p in range(8)}
        victim = sorted(primaries)[0]
        had = sum(cluster.meta.state.get_partition(app_id, p).primary
                  == victim for p in range(8))
        assert had > 0
        moved = cluster.meta.drain_node(victim)
        assert moved == had
        cluster.step(rounds=3)
        for p in range(8):
            pc = cluster.meta.state.get_partition(app_id, p)
            assert pc.primary != victim, (p, pc)
        for i in range(40):
            assert c.get(b"k%03d" % i, b"s") == (0, b"v%d" % i)
        # draining an already-drained node is a no-op
        assert cluster.meta.drain_node(victim) == 0
    finally:
        cluster.close()
