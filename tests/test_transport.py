"""Wire codec + TCP transport + multi-process onebox tests.

Parity: the message-header framing contract (rpc/rpc_message.h:81-126),
and the reference's function-test-against-onebox tier (SURVEY §4.3).
"""

import shutil
import time

import pytest

from pegasus_tpu.rpc.message import decode_message, encode_message, read_frames
from pegasus_tpu.server.types import (
    GetScannerRequest,
    IncrRequest,
    KeyValue,
    MultiGetRequest,
    MultiGetResponse,
    MultiPutRequest,
)


def roundtrip(payload):
    frame = encode_message("a", "b", "t", payload)
    buf = bytearray(frame)
    bodies = read_frames(buf)
    assert len(bodies) == 1 and not buf
    src, dst, mt, out = decode_message(bodies[0])
    assert (src, dst, mt) == ("a", "b", "t")
    return out


def test_message_roundtrip_primitives():
    for v in (None, True, False, 0, -1, 2**40, -(2**40), 2**63,
              0xFFFFFFFFFFFFFFFF, 2**100, -(2**100), 3.5, b"", b"bytes",
              "str", [1, [2, 3]], (4, (5,)), {"k": b"v", 1: None}):
        out = roundtrip(v)
        assert out == v and type(out) is type(v)


def test_message_roundtrip_dataclasses():
    req = MultiPutRequest(b"hk", [KeyValue(b"a", b"1")], 60)
    out = roundtrip({"ops": [(3, req)]})
    assert out["ops"][0][1] == req
    resp = MultiGetResponse(error=0, kvs=[KeyValue(b"x", b"y")])
    assert roundtrip(resp) == resp
    assert roundtrip(IncrRequest(b"k", -5, 0)) == IncrRequest(b"k", -5, 0)
    scan = GetScannerRequest(start_key=b"s", batch_size=7, full_scan=True)
    assert roundtrip(scan) == scan
    assert roundtrip(MultiGetRequest(b"hk", sort_keys=[b"a"])) == \
        MultiGetRequest(b"hk", sort_keys=[b"a"])


def test_partial_frames_reassemble():
    frame = encode_message("x", "y", "z", {"big": b"A" * 10_000})
    buf = bytearray()
    out = []
    for i in range(0, len(frame), 997):
        buf.extend(frame[i:i + 997])
        out.extend(read_frames(buf))
    assert len(out) == 1
    assert decode_message(out[0])[3] == {"big": b"A" * 10_000}


def test_corrupt_frame_raises():
    frame = bytearray(encode_message("x", "y", "z", b"payload"))
    frame[-1] ^= 0xFF
    with pytest.raises(ValueError):
        read_frames(frame)


def test_tcp_transport_request_reply():
    from pegasus_tpu.rpc.transport import TcpTransport

    server = TcpTransport(("127.0.0.1", 0), {})
    host, port = server.listen_addr
    client = TcpTransport(None, {"srv": (host, port)})
    got = []

    def srv_handler(src, msg_type, payload):
        got.append((src, msg_type, payload))
        # reply rides the learned inbound route — the client listens on
        # nothing
        server.send("srv", src, "pong", payload["n"] + 1)

    replies = []
    server.register("srv", srv_handler)
    client.register("cli", lambda s, mt, p: replies.append((s, mt, p)))
    client.send("cli", "srv", "ping", {"n": 41})
    deadline = time.monotonic() + 5
    while not replies and time.monotonic() < deadline:
        time.sleep(0.01)
    assert got == [("cli", "ping", {"n": 41})]
    assert replies == [("srv", "pong", 42)]
    client.close()
    server.close()


def _pair():
    """A listening server transport + client transport dialing it."""
    from pegasus_tpu.rpc.transport import TcpTransport

    server = TcpTransport(("127.0.0.1", 0), {})
    host, port = server.listen_addr
    client = TcpTransport(None, {"srv": (host, port)})
    return server, client


def _wait_for(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not pred() and time.monotonic() < deadline:
        time.sleep(0.01)
    return pred()


def test_dispatcher_fast_fails_expired_deadline():
    """A client request whose end-to-end deadline lapsed in flight is
    never served: the dispatcher answers typed ERR_TIMEOUT without
    touching the handler (abandoned work sheds itself)."""
    from pegasus_tpu.utils.errors import ErrorCode

    server, client = _pair()
    served, replies = [], []
    try:
        server.register("srv", lambda s, mt, p: served.append(p))
        client.register("cli", lambda s, mt, p: replies.append((mt, p)))
        client.send("cli", "srv", "client_read", {
            "rid": 7, "gpid": (1, 0), "op": "get", "args": b"k",
            "deadline": time.time() - 1.0})
        assert _wait_for(lambda: replies)
        mt, p = replies[0]
        assert mt == "client_read_reply"
        assert p == {"rid": 7, "err": int(ErrorCode.ERR_TIMEOUT),
                     "result": None}
        assert served == []
        # an unexpired deadline passes straight through to the handler
        client.send("cli", "srv", "client_read", {
            "rid": 8, "gpid": (1, 0), "op": "get", "args": b"k",
            "deadline": time.time() + 30.0})
        assert _wait_for(lambda: served)
    finally:
        client.close()
        server.close()


def test_read_shedding_err_busy():
    """Aged/deep-queued client reads shed with typed ERR_BUSY; writes
    are exempt (the mutation path degrades last)."""
    from pegasus_tpu.utils.errors import ErrorCode
    from pegasus_tpu.utils.flags import FLAGS

    server, client = _pair()
    served, replies = [], []
    FLAGS.set("pegasus.rpc", "read_shed_queue_age_ms", 50)
    try:
        server.register("srv", lambda s, mt, p: served.append((mt, p)))
        client.register("cli", lambda s, mt, p: replies.append((mt, p)))
        # hold the node lock so queued messages AGE in the inbox (the
        # dispatcher pops the first one pre-aging and blocks on the
        # lock; everything behind it crosses the age threshold)
        with server.lock:
            for i in range(6):
                client.send("cli", "srv", "client_read",
                            {"rid": i, "op": "get", "args": b"k"})
            client.send("cli", "srv", "client_write",
                        {"rid": 100, "gpid": (1, 0), "ops": []})
            # wait for arrival, then let them age past the threshold
            time.sleep(0.4)
        assert _wait_for(lambda: len(replies) >= 4)
        assert all(mt == "client_read_reply"
                   and p["err"] == int(ErrorCode.ERR_BUSY)
                   for mt, p in replies), replies
        # the equally-aged write was NOT shed: it reached the handler
        assert _wait_for(lambda: ("client_write", {
            "rid": 100, "gpid": (1, 0), "ops": []}) in served)
        # fresh reads after the storm drains serve normally
        client.send("cli", "srv", "client_read",
                    {"rid": 200, "op": "get", "args": b"k"})
        assert _wait_for(lambda: any(mt == "client_read"
                                     and p.get("rid") == 200
                                     for mt, p in served))
    finally:
        FLAGS.set("pegasus.rpc", "read_shed_queue_age_ms", 5000)
        client.close()
        server.close()


def test_fault_plan_drop_delay_duplicate_partition():
    """rpc/fault.FaultPlan gives the REAL transport SimNetwork's chaos
    surface, gated by the fail-point registry."""
    from pegasus_tpu.rpc.fault import FaultPlan
    from pegasus_tpu.utils.fail_point import FAIL_POINTS

    server, client = _pair()
    got = []
    try:
        server.register("srv", lambda s, mt, p: got.append(p))
        plan = FaultPlan(seed=3)
        client.install_fault_plan(plan)  # arms FAIL_POINTS too
        # drop: total loss on the link
        plan.set_drop(1.0, "cli", "srv")
        client.send("cli", "srv", "ping", 1)
        time.sleep(0.3)
        assert got == [] and plan.dropped == 1
        # delay: held by the sender for the extra latency
        plan.set_drop(0.0, "cli", "srv")
        plan.set_delay(0.25, "cli", "srv")
        t0 = time.monotonic()
        client.send("cli", "srv", "ping", 2)
        assert _wait_for(lambda: 2 in got)
        assert time.monotonic() - t0 >= 0.25
        # duplicate: redelivery TCP alone can never produce
        plan.set_delay(0.0, "cli", "srv")
        plan.set_duplicate(1.0, "cli", "srv")
        client.send("cli", "srv", "ping", 3)
        assert _wait_for(lambda: got.count(3) == 2)
        # partition: both directions dark, then heal
        plan.set_duplicate(0.0, "cli", "srv")
        plan.partition("srv")
        client.send("cli", "srv", "ping", 4)
        time.sleep(0.2)
        assert 4 not in got
        plan.heal("srv")
        client.send("cli", "srv", "ping", 5)
        assert _wait_for(lambda: 5 in got)
        # the fail-point registry is the global kill-switch: teardown
        # disarms the installed plan without un-wiring it
        FAIL_POINTS.teardown()
        plan.set_drop(1.0, "cli", "srv")
        client.send("cli", "srv", "ping", 6)
        assert _wait_for(lambda: 6 in got)
    finally:
        FAIL_POINTS.teardown()
        client.close()
        server.close()


def test_multiprocess_onebox(tmp_path):
    """The function-test tier: real processes, real TCP, kill -9 cure.

    1 meta + 3 replica processes; DDL + data ops through wire clients;
    kill -9 the primary of partition 0; acked writes survive the cure.
    """
    from pegasus_tpu.tools import onebox_cluster as ob

    d = str(tmp_path / "onebox")
    shutil.rmtree(d, ignore_errors=True)
    ob.start(d, n_replica=3)
    admin = None
    try:
        from pegasus_tpu.utils.errors import PegasusError as _PE

        admin = ob.OneboxAdmin(d)
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                if len(admin.call("list_nodes", timeout=6)) == 3:
                    break
            except _PE:
                pass
            time.sleep(0.5)
        assert len(admin.call("list_nodes")) == 3
        admin.create_table("fn", partition_count=4, replica_count=3)
        c = ob.connect("fn", d)
        from pegasus_tpu.utils.errors import PegasusError

        # settle: a loaded machine can lag config propagation/leases
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                if c.set(b"warm", b"s", b"w") == 0:
                    break
            except PegasusError:
                time.sleep(1)
        acked = []
        for i in range(20):
            if c.set(b"k%02d" % i, b"s", b"v%d" % i) == 0:
                acked.append(i)
        assert len(acked) == 20
        assert c.multi_set(b"mh", {b"a": b"1"}) == 0
        assert c.multi_get(b"mh") == (0, {b"a": b"1"})
        c.refresh_config()
        victim = c._configs[0]["primary"]
        ob.kill_node(victim, d)
        for i in range(20, 30):
            # a write that exhausts retries during the outage is simply
            # un-acked — only OK-acked writes must survive
            try:
                if c.set(b"k%02d" % i, b"s", b"v%d" % i) == 0:
                    acked.append(i)
            except PegasusError:
                pass
        # wait for the guardian cure to finish before verifying (the
        # FD grace + cure can take >10s on a loaded machine)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            c.refresh_config()
            if all(victim not in [pc["primary"]] + pc["secondaries"]
                   and pc["primary"] for pc in c._configs):
                break
            time.sleep(1)
        for pc in c._configs:
            assert victim not in [pc["primary"]] + pc["secondaries"]
            assert pc["primary"]
        for i in acked:
            assert c.get(b"k%02d" % i, b"s") == (0, b"v%d" % i), i
    finally:
        if admin is not None:
            admin.close()
        ob.stop(d)


def test_kill_test_harness_short(tmp_path):
    """A bounded chaos run (parity: kill_test + data_verifier): random
    kill -9s under continuous verification, zero acked-write loss."""
    from pegasus_tpu.tools import onebox_cluster as ob
    from pegasus_tpu.tools.kill_test import run_kill_test

    d = str(tmp_path / "kt")
    ob.start(d, n_replica=3)
    try:
        report = run_kill_test(d, duration_s=25, kill_every_s=10, seed=5)
        assert report["violations"] == [], report
        assert report["writes_acked"] > 20
        assert report["kills"] >= 1
    finally:
        ob.stop(d)


def test_no_write_loss_during_env_compaction(tmp_path):
    """Acked writes racing an env-triggered manual compaction must all
    survive: the replicated apply path and the async compaction thread
    share the partition's single-writer lock — without it, the
    compaction's overlay reset wiped mutations applied after its merge
    snapshot (found by the combined-chaos drive)."""
    import threading

    from pegasus_tpu.tools import onebox_cluster as ob
    from pegasus_tpu.utils.errors import PegasusError

    d = str(tmp_path / "onebox")
    ob.start(d, n_replica=1)
    try:
        admin = ob.OneboxAdmin(d)
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                if len(admin.call("list_nodes", timeout=6)) == 1:
                    break
            except PegasusError:
                pass
            time.sleep(0.5)
        admin.create_table("wlapp", partition_count=4, replica_count=1)
        pc = ob.connect("wlapp", d)
        acked = {}
        stop = threading.Event()
        errors = []

        def writer():
            i = 0
            try:
                while not stop.is_set():
                    k = b"wl%05d" % i
                    if pc.set(k, b"s", b"v%d" % i) == 0:
                        acked[k] = b"v%d" % i
                    i += 1
            except Exception as exc:  # noqa: BLE001 - surfaced below
                errors.append(repr(exc))

        t = threading.Thread(target=writer)
        t.start()
        time.sleep(1.0)  # let writes accumulate in the memtables
        admin.call("update_app_envs", app_name="wlapp",
                   envs={"manual_compact.once.trigger_time":
                         str(int(time.time()))})
        # the compaction must PROVABLY run while writes flow: wait for
        # the L1 runs it publishes to appear on disk (no fixed sleep —
        # a vacuous pass would defeat the regression)
        import glob
        import os

        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if glob.glob(os.path.join(d, "data", "node0", "*", "app",
                                      "sst", "l1-*.sst")):
                break
            time.sleep(0.2)
        l1s = glob.glob(os.path.join(d, "data", "node0", "*", "app",
                                     "sst", "l1-*.sst"))
        assert l1s, "env-triggered compaction never published L1 runs"
        time.sleep(1.0)  # a little more racing traffic post-publish
        stop.set()
        t.join(timeout=20)
        assert not errors, errors
        assert len(acked) > 200, len(acked)
        pc2 = ob.connect("wlapp", d)  # fresh client: server truth only
        lost = [k for k, v in acked.items() if pc2.get(k, b"s") != (0, v)]
        assert not lost, f"{len(lost)} acked writes lost: {lost[:5]}"
    finally:
        ob.stop(d)
