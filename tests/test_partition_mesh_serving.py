"""Resident mesh SPMD serving acceptance: one whole-table device
dispatch must answer every partition's scan waves and pushdown
aggregates BYTE-IDENTICALLY to the host kernels over every store shape
(stores written under mixed none/dcz/dcz2 codecs, empty-hashkey
overflow rows, unflushed overlay), refresh incrementally at
flush/compaction publish (never serving a stale image), and degrade
through the tunnel watchdog to host serving with zero hung scans when
dispatches overrun their deadline."""

import os
import time

# idempotent with conftest: the virtual 8-device CPU mesh must exist
# before jax initializes (standalone runs of this module included)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

import pytest

from pegasus_tpu.client.client import PegasusClient
from pegasus_tpu.client.table import Table
from pegasus_tpu.ops.predicates import (
    FT_MATCH_ANYWHERE,
    FT_MATCH_PREFIX,
)
from pegasus_tpu.ops.pushdown import PushdownSpec
from pegasus_tpu.parallel.mesh_resident import MESH_SERVING
from pegasus_tpu.server.types import (
    GetScannerRequest,
    SCAN_CONTEXT_ID_COMPLETED,
)
from pegasus_tpu.utils.errors import StorageStatus
from pegasus_tpu.utils.flags import FLAGS

OK = int(StorageStatus.OK)
N_PARTS = 8


@pytest.fixture
def mesh_guard():
    """Flag + singleton isolation: every test leaves the process-global
    MESH_SERVING detached and the touched flags restored."""
    saved = [(sec, name, FLAGS.get(sec, name)) for sec, name in (
        ("pegasus.storage", "block_codec"),
        ("pegasus.mesh", "serving_enabled"),
        ("pegasus.mesh", "dispatch_deadline_s"),
        ("pegasus.server", "rocksdb_max_iteration_count"),
    )]
    MESH_SERVING.reset()
    yield
    MESH_SERVING.reset()
    for sec, name, val in saved:
        FLAGS.set(sec, name, val)


def drain(s, req):
    rows, shipped = [], 0
    resp = s.on_get_scanner(req)
    while True:
        assert resp.error == OK
        shipped += resp.wire_bytes()
        rows.extend((kv.key, kv.value) for kv in resp.kvs)
        if resp.context_id == SCAN_CONTEXT_ID_COMPLETED:
            return rows, shipped, resp.agg
        resp = s.on_scan(resp.context_id)


def vf_req(pat, ft=FT_MATCH_ANYWHERE, agg="", k=0, seed=0, **kw):
    pd = PushdownSpec(value_filter_type=ft, value_filter_pattern=pat,
                      aggregate=agg, k=k, seed=seed)
    return GetScannerRequest(pushdown=pd, **kw)


def build_mixed_table(tmp_path, rows=240, compact_codec=None):
    """8 partitions whose history crosses every storage shape: rows
    written under three SST codec generations (none/dcz/dcz2) plus
    empty-hashkey rows (dcz2's group-overflow slots). The wave/aggregate
    serving paths only exist over pure sorted runs, so when
    `compact_codec` is set every partition is compacted under it."""
    table = Table(str(tmp_path), partition_count=N_PARTS)
    c = PegasusClient(table)
    i = 0
    for codec in ("none", "dcz", "dcz2"):
        FLAGS.set("pegasus.storage", "block_codec", codec)
        for _ in range(rows // 3):
            v = b"blue-%04d" % i if i % 5 == 0 else b"red-%04d" % i
            assert c.set(b"hk%02d" % (i % 13), b"s%05d" % i, v) == 0
            i += 1
        assert c.set(b"", b"osk%02d" % (i % 7), b"blue-ovf-%d" % i) == 0
        i += 1
        table.flush_all()
    if compact_codec is not None:
        FLAGS.set("pegasus.storage", "block_codec", compact_codec)
        for s in table.partitions.values():
            s.engine.flush()
            s.engine.manual_compact()
    return table, c


def all_rows(table, req_factory):
    """Per-partition full drains (fresh request per drain)."""
    return {p: drain(s, req_factory())[0]
            for p, s in sorted(table.partitions.items())}


def clear_mask_caches(table):
    """Static keep masks cache per (ckey, filters): clear so each arm
    evaluates REAL waves instead of replaying the other arm's masks."""
    for s in table.partitions.values():
        with s._mask_lock:
            s._mask_cache.clear()


def force_mesh_pays(monkeypatch):
    """Tiny test fixtures never amortize a dispatch; the identity tests
    pin the routing gate open so every wave exercises the mesh path (the
    real gate has its own test + the bench's 8-partition phase)."""
    from pegasus_tpu.ops import placement
    monkeypatch.setattr(placement, "mesh_wave_pays", lambda *_a: True)


def attach_all(table):
    for s in table.partitions.values():
        MESH_SERVING.attach(s)


REQS = (
    ("plain", lambda: GetScannerRequest(batch_size=171)),
    ("value-filter", lambda: vf_req(b"blue", batch_size=64)),
    ("hash-prefix", lambda: GetScannerRequest(
        hash_key_filter_type=FT_MATCH_PREFIX,
        hash_key_filter_pattern=b"hk0", batch_size=97)),
)


@pytest.mark.parametrize("codec", ["none", "dcz", "dcz2"])
def test_wave_identity_mixed_codecs(tmp_path, mesh_guard, monkeypatch,
                                    codec):
    table, _c = build_mixed_table(tmp_path, compact_codec=codec)
    try:
        host = {name: all_rows(table, f) for name, f in REQS}
        assert any(host["value-filter"].values()), "degenerate fixture"
        assert any(host["hash-prefix"].values()), "degenerate fixture"
        clear_mask_caches(table)
        force_mesh_pays(monkeypatch)
        attach_all(table)
        st0 = MESH_SERVING.status()
        for name, f in REQS:
            assert all_rows(table, f) == host[name], (codec, name)
        st1 = MESH_SERVING.status()
        # the mesh actually served (not silently declined to host)
        assert MESH_SERVING.wave_dispatches > 0
        assert st1["mesh_dispatch_count"] > st0["mesh_dispatch_count"]
        assert st1["mesh_verdict_share"] > 0.0
    finally:
        table.close()


def test_wave_identity_with_overlay(tmp_path, mesh_guard, monkeypatch):
    """An unflushed overlay generation must not poison identity: the
    overlay merge shadows on top of whatever arm serves the base."""
    table, c = build_mixed_table(tmp_path, compact_codec="dcz2")
    try:
        force_mesh_pays(monkeypatch)
        attach_all(table)
        base = all_rows(table, REQS[1][1])
        assert MESH_SERVING.wave_dispatches > 0
        assert c.set(b"hk00", b"s00000", b"red-shadowed") == 0
        assert c.set(b"hknew", b"s0", b"blue-overlay-only") == 0
        clear_mask_caches(table)
        with_overlay = all_rows(table, REQS[1][1])
        assert with_overlay != base  # the overlay is visible
        MESH_SERVING.reset()
        clear_mask_caches(table)
        assert all_rows(table, REQS[1][1]) == with_overlay
    finally:
        table.close()


def test_aggregates_mesh_vs_host_single_dispatch(tmp_path, mesh_guard):
    table, _c = build_mixed_table(tmp_path, compact_codec="dcz2")
    try:
        def agg_wires(kind, k=0, seed=0):
            return {p: drain(s, vf_req(b"blue", agg=kind, k=k,
                                       seed=seed))[2]
                    for p, s in sorted(table.partitions.items())}

        host = {kind: agg_wires(kind, k=3, seed=9)
                for kind in ("count", "sum", "top_k", "sample")}
        assert sum(w["count"] for w in host["count"].values()) > 0
        attach_all(table)
        # all four aggregates: psum counts/sums and host-edge top_k /
        # sample folds must match the host arm byte for byte — and ALL
        # 32 (kind, partition) folds share TWO dispatches (one per
        # with_sum flavor; count/top_k/sample reuse the same cached
        # static+counts image), tolerance for wall-clock-second ticks
        # splitting a run into extra cache generations
        for kind in ("count", "sum", "top_k", "sample"):
            assert agg_wires(kind, k=3, seed=9) == host[kind], kind
        assert 2 <= MESH_SERVING.agg_dispatches <= 6
        assert MESH_SERVING.status()["mesh_dispatch_count"] > 0
    finally:
        table.close()


def test_incremental_refresh_no_stale_image(tmp_path, mesh_guard,
                                            monkeypatch):
    table, c = build_mixed_table(tmp_path, rows=120, compact_codec="dcz")
    try:
        force_mesh_pays(monkeypatch)
        attach_all(table)
        before = all_rows(table, REQS[0][1])
        assert MESH_SERVING.wave_dispatches > 0
        sb0, stk0 = MESH_SERVING.slab_builds, MESH_SERVING.stack_builds
        assert sb0 >= N_PARTS  # first image staged every partition
        # dirty exactly ONE partition: new rows, flush + compact publish
        target = table.resolve(b"hot-hk")
        for j in range(40):
            assert c.set(b"hot-hk", b"z%03d" % j, b"blue-hot-%d" % j) == 0
        target.engine.flush()
        target.engine.manual_compact()
        clear_mask_caches(table)
        w0 = MESH_SERVING.wave_dispatches
        after = all_rows(table, REQS[0][1])
        # the REFRESHED image served these waves — not a host fallback
        assert MESH_SERVING.wave_dispatches > w0
        grew = {p for p in after if len(after[p]) != len(before[p])}
        assert grew == {target.pidx}, "stale (or over-fresh) mesh image"
        got = {v for _k, v in after[target.pidx]}
        assert all(b"blue-hot-%d" % j in got for j in range(40))
        # incremental: only the published partition restaged
        assert MESH_SERVING.slab_builds == sb0 + 1
        assert MESH_SERVING.stack_builds == stk0 + 1
        # a second compaction publish must invalidate again (same rows)
        target.engine.manual_compact()
        clear_mask_caches(table)
        assert all_rows(table, REQS[0][1]) == after
        assert MESH_SERVING.slab_builds <= sb0 + 2
    finally:
        table.close()


def test_watchdog_trip_degrades_to_host_mid_scan(tmp_path, mesh_guard,
                                                 monkeypatch):
    table, _c = build_mixed_table(tmp_path, rows=120, compact_codec="none")
    try:
        host = all_rows(table, REQS[1][1])
        clear_mask_caches(table)
        force_mesh_pays(monkeypatch)
        attach_all(table)
        # every dispatch now overruns: the second consecutive failure
        # must trip the tunnel; on the CPU mesh a trip disables mesh
        # serving outright and the host kernels carry the rest
        MESH_SERVING.watchdog.deadline_s = 1e-9
        t0 = time.monotonic()
        degraded = all_rows(table, REQS[1][1])
        wall = time.monotonic() - t0
        assert degraded == host, "fallback rows differ from host arm"
        assert wall < 60.0, "a wedged dispatch hung the scan"
        st = MESH_SERVING.status()
        assert st["mesh_fallback_count"] >= 2
        assert st["watchdog"]["trips"] >= 1
        assert st["tunnel_wedged"] is True
        assert MESH_SERVING.disabled and not MESH_SERVING.enabled
        # wedged is a verdict, not a wedge: later scans still correct
        clear_mask_caches(table)
        assert all_rows(table, REQS[1][1]) == host
    finally:
        table.close()


def test_make_mesh_single_device_degrades():
    from pegasus_tpu.parallel.partition_mesh import make_mesh

    with pytest.warns(RuntimeWarning, match="single-device host"):
        pm = make_mesh(n_devices=1, dp=8)
    assert pm.dp == 1 and pm.sp == 1
    # multi-device invalid factorizations still fail loudly
    with pytest.raises(ValueError):
        make_mesh(dp=3)


def test_mesh_cost_gate_and_verdict():
    from pegasus_tpu.ops import placement

    # single-chunk waves share the host dispatch floor: nothing to
    # amortize, the mesh must decline
    assert not placement.mesh_wave_pays(1, 4096)
    # multi-chunk / multi-partition waves collapse to one round and win
    assert placement.mesh_wave_pays(8, 1 << 20)
    assert placement.placement_verdict("mesh") == "mesh"
    assert placement.predict_kernel_seconds("mesh", 1 << 20) > 0.0


def test_explain_reports_mesh_ride(tmp_path, mesh_guard, monkeypatch):
    from pegasus_tpu.server import explain as explain_mod

    # codec "none": compressed blocks resolve their static masks via
    # the encoded-domain host probe and never reach the wave path
    table, _c = build_mixed_table(tmp_path, rows=120, compact_codec="none")
    try:
        force_mesh_pays(monkeypatch)
        attach_all(table)
        clear_mask_caches(table)  # prefreshed masks would skip the wave
        s = table.partitions[0]
        # a FULL-range scan: the shape that rides the stacked wave path
        # (hashkey-scoped scans take the block-probe path, no waves)
        spec = explain_mod.spec_from_words(
            ["scan", "filter=blue", "batch_size=1000"])
        op, args, ph = explain_mod.op_from_spec(spec)
        report = explain_mod.explain_op(s, op, args, partition_hash=ph)
        assert report["perf"]["placement"] == "mesh"
        assert report["perf"]["mesh_partitions"] >= 1
        assert report["perf"]["mesh_wave_ms"] > 0.0
        rendered = explain_mod.render_report(report)
        assert "mesh: partitions=" in rendered
        # the aggregate explain rides the mesh aggregate arm
        spec = explain_mod.spec_from_words(["scan", "filter=blue",
                                            "agg=count"])
        op, args, ph = explain_mod.op_from_spec(spec)
        report = explain_mod.explain_op(s, op, args, partition_hash=ph)
        assert report["perf"]["placement"] == "mesh"
        assert report["perf"]["rows_aggregated"] == \
            report["result"]["agg"]["count"]
    finally:
        table.close()


def test_aggregate_declines_paged_and_overlay(tmp_path, mesh_guard):
    """The mesh aggregate only answers folds the host arm would serve in
    ONE page over pure sorted runs; paging budgets smaller than the
    resident range and overlay generations keep riding the host arm
    (and stay correct)."""
    table, c = build_mixed_table(tmp_path, compact_codec="dcz2")
    try:
        host = {p: drain(s, vf_req(b"blue", agg="count"))[2]
                for p, s in sorted(table.partitions.items())}
        attach_all(table)
        # paged: a budget below the resident row count forces the host
        # paging protocol (partial rides the context, ships last)
        FLAGS.set("pegasus.server", "rocksdb_max_iteration_count", 10)
        a0 = MESH_SERVING.agg_dispatches
        got = {p: drain(s, vf_req(b"blue", agg="count"))[2]
               for p, s in sorted(table.partitions.items())}
        assert got == host and MESH_SERVING.agg_dispatches == a0
        FLAGS.set("pegasus.server", "rocksdb_max_iteration_count", 0)
        # overlay: an unflushed write reopens the merge path
        assert c.set(b"hk01", b"blue-snew", b"blue-overlay") == 0
        target = table.resolve(b"hk01")
        a0 = MESH_SERVING.agg_dispatches
        agg = drain(target, vf_req(b"blue", agg="count"))[2]
        assert agg["count"] == host[target.pidx]["count"] + 1
        assert MESH_SERVING.agg_dispatches == a0
    finally:
        table.close()


def test_mesh_metrics_lint_and_health_rule():
    from pegasus_tpu.tools.metrics_lint import lint
    from pegasus_tpu.utils.health import default_rules

    assert not [c for c in lint() if "mesh" in c or "tunnel" in c]
    rules = [r for r in default_rules() if r.name == "tunnel_wedged"]
    assert len(rules) == 1 and rules[0].hold == 2
