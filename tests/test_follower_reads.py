"""Lease-gated follower reads: per-op consistency levels end to end.

The tentpole invariants, each proven on the deterministic SimCluster:

- bounded_stale reads served AT SECONDARIES are byte-identical to
  linearizable reads at the primary once the group check has advanced
  the committed watermark (PacificA applies mutations on COMMIT, so a
  secondary can never expose an uncommitted write by construction).
- A secondary whose beacon lease lapsed bounces typed
  ERR_STALE_REPLICA; the client re-flies ONLY the bounced subset, to
  the primary, without burning a config refresh (the PR 6 misrouted-
  subset discipline applied to replica choice).
- The monotonic session token (per-partition observed committed
  decree) means a client never reads below its own history, even when
  its reads fan out across replicas mid-failover.
- A split flip moves rows between partitions; a follower read of a
  moved row bounces through the SAME split-staleness gate as a primary
  read and re-resolves — never a stale parent row.

Plus the chaos proof: the DataVerifier monotonic-reads ledger runs
MONOTONIC-consistency reads through node kills and a beacon-drop lease
lapse with zero violations (the onebox twin soaks the same invariant
over real processes under `-m slow`).
"""

import random

import pytest

from pegasus_tpu.base.key_schema import generate_key, key_hash_parts
from pegasus_tpu.client.cluster_client import MONOTONIC, bounded_stale
from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.tools.kill_test import DataVerifier
from pegasus_tpu.utils.errors import ErrorCode
from pegasus_tpu.utils.fail_point import FAIL_POINTS

OK = 0
STALE = int(ErrorCode.ERR_STALE_REPLICA)


def _sum_counter(cluster, attr: str) -> int:
    return sum(getattr(stub, attr).value()
               for stub in cluster.stubs.values())


def test_bounded_stale_at_secondary_byte_identical(tmp_path):
    """Caught-up secondaries serve bounded_stale reads with the exact
    bytes the primary serves, and the follower_read counter proves the
    answers really came from secondaries."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=5)
    try:
        cluster.create_table("fr", partition_count=4)
        client = cluster.client("fr")
        keys = [b"user_%03d" % i for i in range(24)]
        for i, hk in enumerate(keys):
            assert client.set(hk, b"s", b"payload-%03d" % i) == OK
        # group check piggybacks last_committed: secondaries commit
        # everything and stamp their freshness watermark
        cluster.step(rounds=2)
        lin = {hk: client.get(hk, b"s") for hk in keys}
        before = _sum_counter(cluster, "_follower_reads")
        stale = {hk: client.get(hk, b"s",
                                consistency=bounded_stale(60_000))
                 for hk in keys}
        assert stale == lin  # byte-identity, err codes included
        served = _sum_counter(cluster, "_follower_reads") - before
        # the rotation spreads over primary + 2 secondaries, so ~2/3
        # of the reads were answered at secondaries
        assert served >= len(keys) // 2
        assert _sum_counter(cluster, "_stale_bounces") == 0
        # ...and the session tokens ratcheted from the reply decrees
        assert client._session_tokens
        assert all(v > 0 for v in client._session_tokens.values())
    finally:
        cluster.close()


def test_monotonic_bounce_retries_only_the_stale_subset(tmp_path):
    """A lagging secondary bounces a monotonic read below the session
    token; the client re-flies ONLY the bounced partition's ops, to the
    primary — the fresh partition's ops never fly twice."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=7)
    try:
        app_id = cluster.create_table("mono", partition_count=4)
        client = cluster.client("mono")
        # two keys on two distinct partitions
        by_pidx = {}
        for i in range(64):
            hk = b"k%03d" % i
            ph = key_hash_parts(hk, b"s")
            by_pidx.setdefault(ph % 4, (hk, ph))
            if len(by_pidx) >= 2:
                break
        (p0, (hk0, ph0)), (p1, (hk1, ph1)) = sorted(by_pidx.items())[:2]
        assert client.set(hk0, b"s", b"old0") == OK
        assert client.set(hk1, b"s", b"v1") == OK
        cluster.step(rounds=2)  # secondaries catch up on both
        assert client.get(hk1, b"s") == (OK, b"v1")  # token(p1) = tip
        # now advance ONLY p0 past its secondaries: the prepare commits
        # decree d at the primary while secondaries sit at d-1, and the
        # linearizable read ratchets the session token to d
        assert client.set(hk0, b"s", b"new0") == OK
        assert client.get(hk0, b"s") == (OK, b"new0")
        tok0 = client._session_tokens[p0]
        sent = []
        orig = client._send_request

        def spy(dst, method, payload, **kw):
            if method == "client_read_batch":
                sent.append((dst, payload))
            return orig(dst, method, payload, **kw)

        client._send_request = spy
        bounced_before = _sum_counter(cluster, "_stale_bounces")
        res = client.point_read_multi(
            {p0: [("get", generate_key(hk0, b"s"), ph0)],
             p1: [("get", generate_key(hk1, b"s"), ph1)]},
            consistency=MONOTONIC)
        assert res[p0][0] == (OK, b"new0")  # never the stale old0
        assert res[p1][0] == (OK, b"v1")
        assert _sum_counter(cluster, "_stale_bounces") > bounced_before
        # the wire discipline: p1 flew exactly once; p0's retry flew
        # alone, to the primary, carrying the session token
        def pidxs_of(payload):
            return {gpid[1] for gpid, _ops in payload["groups"]}

        first = [s for s in sent if p1 in pidxs_of(s[1])]
        assert len(first) == 1  # the fresh partition never re-flew
        retries = [s for s in sent if pidxs_of(s[1]) == {p0}]
        assert retries, sent
        retry_dst, retry_payload = retries[-1]
        assert retry_dst == cluster.primaries(app_id)[p0]
        assert dict(retry_payload["min_decrees"])[p0] >= tok0
        assert client._session_tokens[p0] >= tok0  # never regressed
    finally:
        cluster.close()


def test_beacon_drop_lapses_lease_and_fences_follower(tmp_path):
    """The fd::beacon_drop fail point starves ONE node's beacon acks;
    its lease lapses, its follower gate bounces ERR_STALE_REPLICA with
    the lease-reject counters ticked and the beacon_ack_age_s gauge
    stamped replica-side AT the decision — and client reads stay
    correct throughout. Healing the fail point restores serving."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=9)
    try:
        app_id = cluster.create_table("lease", partition_count=1)
        client = cluster.client("lease")
        assert client.set(b"hk", b"s", b"v") == OK
        cluster.step(rounds=2)
        pc = cluster.meta.state.get_partition(app_id, 0)
        victim = pc.secondaries[0]
        stub = cluster.stubs[victim]
        FAIL_POINTS.setup()
        try:
            FAIL_POINTS.cfg(f"fd::beacon_drop:{victim}", "return(x)")
            acked_at = stub._last_beacon_ack
            # 4 beacon intervals > the 9s worker lease: the node keeps
            # "sending" but the fail point eats every beacon
            cluster.step(rounds=4)
            assert stub._last_beacon_ack == acked_at  # no ack landed
            assert not stub.lease_valid()
            rejects = stub._lease_rejects.value()
            bounces = stub._stale_bounces.value()
            err, r = stub._client_read_gate(
                {"gpid": (app_id, 0), "auth": None,
                 "consistency": {"level": "bounded_stale",
                                 "max_lag_ms": 600_000.0}}, "cx")
            assert err == STALE and r is None
            assert stub._lease_rejects.value() == rejects + 1
            assert stub._stale_bounces.value() == bounces + 1
            # the gauge shows the age the lease decision actually read
            assert stub._beacon_age_gauge.value() > 9.0
            # end to end: the op lands correctly anyway (bounce at the
            # fenced follower -> subset retry -> a serving replica)
            assert client.get(b"hk", b"s",
                              consistency=bounded_stale(600_000)) \
                == (OK, b"v")
        finally:
            FAIL_POINTS.teardown()
        cluster.step(rounds=2)  # beacons flow again: lease recovers
        assert stub.lease_valid()
        assert stub.beacon_ack_age() <= 9.0
    finally:
        cluster.close()


def test_monotonic_ledger_chaos_sim(tmp_path):
    """The acceptance chaos: seeded kills + a beacon-drop lease lapse
    while the DataVerifier monotonic ledger reads at MONOTONIC
    consistency through secondaries — zero regressions observed."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=4, seed=17)
    try:
        app_id = cluster.create_table("chaos", partition_count=4)
        client = cluster.client("chaos")
        client.op_timeout_ms = 600_000  # sim-seconds, spans failovers
        verifier = DataVerifier(client, random.Random(17),
                                monotonic_ledger=True,
                                read_consistency=MONOTONIC)
        for _ in range(10):
            verifier.step()
        FAIL_POINTS.setup()
        try:
            # lease-lapse chaos on one node while the stream continues
            lame = sorted(cluster.stubs)[-1]
            FAIL_POINTS.cfg(f"fd::beacon_drop:{lame}", "return(x)")
            cluster.step(rounds=4)
            for _ in range(8):
                verifier.step()
        finally:
            FAIL_POINTS.teardown()
        # crash a primary outright mid-stream
        victim = next(p for p in cluster.primaries(app_id) if p)
        cluster.kill(victim)
        for _ in range(8):
            verifier.step()
        cluster.revive(victim)
        cluster.step(rounds=4)
        for _ in range(6):
            verifier.step()
        assert verifier.violations == [], verifier.violations
        assert verifier.ledger_reads > 0
        assert verifier.write_ok > 15
        # the ledger really exercised follower serving
        assert _sum_counter(cluster, "_follower_reads") > 0
    finally:
        cluster.close()


def test_split_flip_never_serves_stale_parent_row(tmp_path):
    """After an online 2x split, follower reads of moved rows pass the
    SAME split-staleness gate as primary reads: every key reads back
    byte-identical at bounded_stale, none from a stale parent half."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=23)
    try:
        app_id = cluster.create_table("fs", partition_count=2)
        client = cluster.client("fs")
        client.op_timeout_ms = 600_000
        keys = {b"user_%03d" % i: b"val-%03d" % i for i in range(32)}
        for hk, v in keys.items():
            assert client.set(hk, b"s", v) == OK
        cluster.step(rounds=2)
        assert cluster.meta.split.start_partition_split("fs") == 4
        for _ in range(30):
            cluster.step()
            if not cluster.meta.split.split_status("fs")["splitting"]:
                break
        assert not cluster.meta.split.split_status("fs")["splitting"]
        assert cluster.meta.state.apps[app_id].partition_count == 4
        cluster.step(rounds=2)
        for hk, want in keys.items():
            assert client.get(hk, b"s",
                              consistency=bounded_stale(600_000)) \
                == (OK, want), hk
        # post-split follower serving really happened
        assert _sum_counter(cluster, "_follower_reads") > 0
    finally:
        cluster.close()


def test_linearizable_rejected_at_secondary(tmp_path):
    """A consistency-less read reaching a secondary (stale client
    routing) still gets ERR_INVALID_STATE — follower serving never
    silently weakens the default level."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=29)
    try:
        app_id = cluster.create_table("lin", partition_count=1)
        client = cluster.client("lin")
        assert client.set(b"hk", b"s", b"v") == OK
        cluster.step(rounds=2)
        pc = cluster.meta.state.get_partition(app_id, 0)
        stub = cluster.stubs[pc.secondaries[0]]
        err, r = stub._client_read_gate(
            {"gpid": (app_id, 0), "auth": None}, "cx")
        assert err == int(ErrorCode.ERR_INVALID_STATE) and r is None
        # unknown levels are rejected, not guessed at
        err, r = stub._client_read_gate(
            {"gpid": (app_id, 0), "auth": None,
             "consistency": {"level": "eventual"}}, "cx")
        assert err == int(ErrorCode.ERR_INVALID_STATE) and r is None
        with pytest.raises(ValueError):
            client.get(b"hk", b"s", consistency={"level": "eventual"})
    finally:
        cluster.close()


def test_scanner_follower_paging_and_aggregate(tmp_path):
    """A bounded_stale scanner pins a secondary, pages its context
    there, and drains the same rows a linearizable scan drains —
    including the aggregate-pushdown path."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=31)
    try:
        cluster.create_table("scan", partition_count=2)
        client = cluster.client("scan")
        hk = b"stream"
        want = {}
        for i in range(40):
            sk = b"s%03d" % i
            v = b"v%03d" % i
            assert client.set(hk, sk, v) == OK
            want[sk] = v
        cluster.step(rounds=2)
        before = _sum_counter(cluster, "_follower_reads")
        sc = client.get_scanner(hk, consistency=bounded_stale(60_000))
        got = {sk: v for _hk, sk, v in sc}
        assert got == want
        assert _sum_counter(cluster, "_follower_reads") > before
        agg = client.get_scanner(hk, consistency=bounded_stale(60_000))
        assert agg.count() == len(want)
        agg.close()
    finally:
        cluster.close()


@pytest.mark.slow
def test_onebox_chaos_monotonic_ledger(tmp_path):
    """Onebox twin of the sim chaos proof: real processes, kill -9
    chaos, ledger reads at MONOTONIC consistency — zero monotonic-reads
    violations and zero acked-write loss."""
    from pegasus_tpu.tools import onebox_cluster as ob
    from pegasus_tpu.tools.kill_test import run_kill_test

    d = str(tmp_path / "frbox")
    ob.start(d, n_replica=3)
    try:
        report = run_kill_test(d, duration_s=45, kill_every_s=18,
                               seed=33, mode="kill",
                               op_timeout_ms=30_000,
                               monotonic_ledger=True)
        assert report["violations"] == [], report
        assert report["kills"] >= 1
        assert report["ledger_reads"] > 0
        assert report["writes_acked"] > 10
    finally:
        ob.stop(d)
