"""Storage engine tests: memtable, WAL, SSTable, LSM, engine discipline."""

import os
import struct

import numpy as np
import pytest

from pegasus_tpu.base.key_schema import generate_key, generate_next_bytes
from pegasus_tpu.storage import (
    LSMStore,
    Memtable,
    OP_DEL,
    OP_PUT,
    SSTable,
    SSTableWriter,
    StorageEngine,
    TOMBSTONE,
    WalRecord,
    WriteAheadLog,
    WriteBatchItem,
)


def k(h, s=""):
    return generate_key(h.encode() if isinstance(h, str) else h,
                        s.encode() if isinstance(s, str) else s)


# ---- memtable ---------------------------------------------------------


def test_memtable_basic():
    mt = Memtable()
    mt.put(k("b"), b"v1")
    mt.put(k("a"), b"v2")
    mt.put(k("c"), b"v3", expire_ts=7)
    assert mt.get(k("a")) == (b"v2", 0)
    assert mt.get(k("c")) == (b"v3", 7)
    assert mt.get(k("zzz")) is None
    mt.delete(k("b"))
    assert mt.get(k("b")) == (TOMBSTONE, 0)
    keys = [key for key, _, _ in mt.items_sorted()]
    assert keys == sorted(keys)


def test_memtable_range_and_reverse():
    mt = Memtable()
    for i in range(10):
        mt.put(k("h", "s%02d" % i), b"v%d" % i)
    got = [v for _, v, _ in mt.iterate(k("h", "s03"), k("h", "s07"))]
    assert got == [b"v3", b"v4", b"v5", b"v6"]
    rev = [v for _, v, _ in mt.iterate(k("h", "s03"), k("h", "s07"),
                                       reverse=True)]
    assert rev == [b"v6", b"v5", b"v4", b"v3"]


# ---- WAL --------------------------------------------------------------


def test_wal_roundtrip_and_torn_tail(tmp_path):
    path = str(tmp_path / "wal.log")
    wal = WriteAheadLog(path)
    wal.append_batch(1, [WalRecord(OP_PUT, k("a"), b"va", 0)])
    wal.append_batch(2, [WalRecord(OP_PUT, k("b"), b"vb", 9),
                         WalRecord(OP_DEL, k("a"), b"", 0)])
    wal.close()

    batches = list(WriteAheadLog.replay(path))
    assert [d for d, _ in batches] == [1, 2]
    assert batches[1][1][1].op == OP_DEL

    # torn tail: append garbage half-frame — replay must stop cleanly
    with open(path, "ab") as f:
        f.write(struct.pack("<II", 1000, 0) + b"short")
    assert [d for d, _ in WriteAheadLog.replay(path)] == [1, 2]

    # corrupt a crc in the middle: replay stops before it
    data = bytearray(open(path, "rb").read())
    data[4] ^= 0xFF  # crc of first frame
    open(path, "wb").write(bytes(data))
    assert list(WriteAheadLog.replay(path)) == []


def test_wal_appends_after_torn_tail_survive(tmp_path):
    # regression: a frame appended after a torn tail must be replayable —
    # the torn garbage is truncated when the WAL reopens.
    path = str(tmp_path / "wal.log")
    wal = WriteAheadLog(path)
    wal.append_batch(1, [WalRecord(OP_PUT, k("a"), b"va", 0)])
    wal.close()
    with open(path, "ab") as f:
        f.write(struct.pack("<II", 1000, 0) + b"torn")
    wal2 = WriteAheadLog(path)  # must truncate the garbage
    wal2.append_batch(2, [WalRecord(OP_PUT, k("b"), b"vb", 0)])
    wal2.close()
    assert [d for d, _ in WriteAheadLog.replay(path)] == [1, 2]


# ---- SSTable ----------------------------------------------------------


def test_sstable_roundtrip(tmp_path):
    path = str(tmp_path / "t.sst")
    w = SSTableWriter(path, block_capacity=4, meta={"last_flushed_decree": 42})
    records = [(k("h%02d" % i, "s"), b"val%d" % i, i * 10) for i in range(11)]
    for key, v, e in records:
        w.add(key, v, e)
    w.finish()

    t = SSTable(path)
    assert t.total_count == 11
    assert t.meta["last_flushed_decree"] == 42
    assert len(t.blocks) == 3  # 4+4+3
    for key, v, e in records:
        assert t.get(key) == (v, e)
    assert t.get(k("nope")) is None
    got = list(t.iterate())
    assert [key for key, _, _ in got] == [key for key, _, _ in records]
    # range iterate
    sub = list(t.iterate(k("h03", "s"), k("h07", "s")))
    assert [v for _, v, _ in sub] == [b"val3", b"val4", b"val5", b"val6"]
    # reverse
    rev = [v for _, v, _ in t.iterate(reverse=True)]
    assert rev == [v for _, v, _ in records][::-1]
    t.close()


def test_sstable_hash_lo_column_matches_key_hash(tmp_path):
    # the writer's precomputed crc column IS what validate_hash scans
    # compare against — it must equal pegasus_key_hash's lo lane, including
    # the empty-hashkey fallback
    from pegasus_tpu.base.key_schema import key_hash
    path = str(tmp_path / "t.sst")
    w = SSTableWriter(path, block_capacity=4)
    keys = sorted([k("user%d" % i, "s%d" % i) for i in range(9)]
                  + [k("", "sortonly")])
    for key in keys:
        w.add(key, b"v")
    w.finish()
    t = SSTable(path)
    got = {}
    for _, blk in t.iter_blocks():
        for i in range(blk.count):
            got[blk.key_at(i)] = int(blk.hash_lo[i])
    for key in keys:
        assert got[key] == (key_hash(key) & 0xFFFFFFFF), key
    t.close()


def test_sstable_rejects_unsorted(tmp_path):
    w = SSTableWriter(str(tmp_path / "x.sst"))
    w.add(k("b"), b"v")
    with pytest.raises(ValueError):
        w.add(k("a"), b"v")
    w.abandon()


def test_sstable_tombstone_and_blocks(tmp_path):
    path = str(tmp_path / "t.sst")
    w = SSTableWriter(path, block_capacity=8)
    w.add(k("a"), b"", tombstone=True)
    w.add(k("b"), b"vb", 5)
    w.finish()
    t = SSTable(path)
    assert t.get(k("a")) == (None, 0)
    blocks = list(t.iter_blocks())
    assert len(blocks) == 1
    _, blk = blocks[0]
    assert blk.count == 2
    assert blk.is_tombstone(0) and not blk.is_tombstone(1)
    assert blk.key_at(1) == k("b") and blk.value_at(1) == b"vb"
    t.close()


# ---- LSM --------------------------------------------------------------


def test_lsm_shadowing_and_merge(tmp_path):
    lsm = LSMStore(str(tmp_path / "d"))
    lsm.put(k("a"), b"v1")
    lsm.put(k("b"), b"v1")
    lsm.flush()
    lsm.put(k("a"), b"v2")       # newer L0 shadows older
    lsm.delete(k("b"))
    lsm.flush()
    lsm.put(k("c"), b"v3")       # memtable newest
    assert lsm.get(k("a")) == (b"v2", 0)
    assert lsm.get(k("b")) is None
    assert lsm.get(k("c")) == (b"v3", 0)
    merged = [(key, v) for key, v, _ in lsm.iterate()]
    assert merged == [(k("a"), b"v2"), (k("c"), b"v3")]
    lsm.close()


def test_lsm_compact_drops_tombstones(tmp_path):
    lsm = LSMStore(str(tmp_path / "d"))
    for i in range(20):
        lsm.put(k("h", "s%02d" % i), b"v%d" % i)
    lsm.flush()
    for i in range(0, 20, 2):
        lsm.delete(k("h", "s%02d" % i))
    lsm.compact()
    assert lsm.l1_runs and not lsm.l0 and len(lsm.memtable) == 0
    assert sum(t.total_count for t in lsm.l1_runs) == 10
    assert lsm.get(k("h", "s00")) is None
    assert lsm.get(k("h", "s01")) == (b"v1", 0)
    assert lsm.sorted_runs() is not None
    lsm.put(k("h", "zzz"), b"x")
    assert lsm.sorted_runs() is None  # overlay disqualifies the fast path
    lsm.close()


def test_lsm_reopen(tmp_path):
    d = str(tmp_path / "d")
    lsm = LSMStore(d)
    lsm.put(k("a"), b"v1")
    lsm.flush()
    lsm.put(k("a"), b"v2")
    lsm.flush()
    lsm.close()
    lsm2 = LSMStore(d)
    assert lsm2.get(k("a")) == (b"v2", 0)  # L0 recency order preserved
    lsm2.close()


def test_lsm_crash_between_compact_and_cleanup(tmp_path):
    # simulate a crash after the new L1 landed but before old files were
    # deleted: on reload, obsolete inputs (seq < L1 seq) must be purged so
    # compaction-dropped records don't resurrect.
    import shutil
    d = str(tmp_path / "d")
    lsm = LSMStore(d)
    lsm.put(k("a"), b"old")
    lsm.flush()
    # preserve the pre-compaction files to "restore the crash state" after
    backup = str(tmp_path / "backup")
    shutil.copytree(d, backup)
    lsm.delete(k("a"))
    lsm.compact()  # tombstone drops 'a' entirely
    assert lsm.get(k("a")) is None
    lsm.close()
    # put back the old L0 next to the new L1 (as if removal never ran)
    for name in os.listdir(backup):
        dst = os.path.join(d, name)
        if not os.path.exists(dst):
            shutil.copy(os.path.join(backup, name), dst)
    lsm2 = LSMStore(d)
    assert lsm2.get(k("a")) is None  # old L0 was purged, not resurrected
    assert not lsm2.l0
    lsm2.close()


def test_engine_data_version_recovery_prefers_newest(tmp_path):
    d = str(tmp_path / "e")
    eng = StorageEngine(d, data_version=1)
    eng.write_batch([WriteBatchItem(OP_PUT, k("a"), b"v")], decree=1)
    eng.manual_compact()  # L1 meta: data_version=1, decree=1
    eng.data_version = 2  # schema upgrade
    eng.write_batch([WriteBatchItem(OP_PUT, k("b"), b"v")], decree=2)
    eng.flush()           # L0 meta: data_version=2, decree=2
    eng.close()
    eng2 = StorageEngine(d)
    assert eng2.data_version == 2  # newest watermark wins, not L1's v1
    eng2.close()


# ---- engine -----------------------------------------------------------


def test_engine_decree_discipline_and_recovery(tmp_path):
    d = str(tmp_path / "e")
    eng = StorageEngine(d)
    eng.write_batch([WriteBatchItem(OP_PUT, k("a"), b"va")], decree=1)
    eng.write_batch([WriteBatchItem(OP_PUT, k("b"), b"vb", 9)], decree=2)
    eng.flush()
    assert eng.last_flushed_decree == 2
    eng.write_batch([WriteBatchItem(OP_PUT, k("c"), b"vc")], decree=3)
    eng.write_batch([WriteBatchItem(OP_DEL, k("a"))], decree=4)
    with pytest.raises(ValueError):
        eng.write_batch([WriteBatchItem(OP_PUT, k("x"), b"v")], decree=4)
    eng.close()

    # crash before flush: WAL replay must restore decrees 3-4
    eng2 = StorageEngine(d)
    assert eng2.last_flushed_decree == 2
    assert eng2.last_committed_decree == 4
    assert eng2.get(k("a")) is None
    assert eng2.get(k("b")) == (b"vb", 9)
    assert eng2.get(k("c")) == (b"vc", 0)
    eng2.close()


def test_engine_manual_compact_ttl(tmp_path):
    from pegasus_tpu.base.value_schema import epoch_now
    now = epoch_now()
    eng = StorageEngine(str(tmp_path / "e"))
    items = [
        WriteBatchItem(OP_PUT, k("h", "live"), b"v", expire_ts=now + 10_000),
        WriteBatchItem(OP_PUT, k("h", "dead"), b"v", expire_ts=now - 10),
        WriteBatchItem(OP_PUT, k("h", "eternal"), b"v", expire_ts=0),
    ]
    eng.write_batch(items, decree=1)
    eng.manual_compact(now=now)
    assert eng.get(k("h", "dead")) is None
    assert eng.get(k("h", "live")) is not None
    assert eng.get(k("h", "eternal")) is not None
    assert eng.lsm.l1_runs[0].meta["last_flushed_decree"] == 1
    eng.close()


def test_engine_manual_compact_default_ttl_rewrite(tmp_path):
    eng = StorageEngine(str(tmp_path / "e"))
    eng.write_batch([WriteBatchItem(OP_PUT, k("h", "x"), b"v", expire_ts=0)],
                    decree=1)
    eng.manual_compact(default_ttl=100, now=1000)
    # no-TTL record got expire_ts = now + default_ttl
    assert eng.get(k("h", "x")) == (b"v", 1100)
    eng.close()


def test_engine_manual_compact_stale_split(tmp_path):
    from pegasus_tpu.base.key_schema import key_hash
    eng = StorageEngine(str(tmp_path / "e"))
    pc = 8
    keys = [k("user_%d" % i, "s") for i in range(40)]
    eng.write_batch([WriteBatchItem(OP_PUT, key, b"v") for key in keys],
                    decree=1)
    pidx = 2
    eng.manual_compact(validate_hash=True, pidx=pidx, partition_version=pc - 1)
    for key in keys:
        mine = (key_hash(key) & (pc - 1)) == pidx
        assert (eng.get(key) is not None) == mine
    eng.close()


def test_engine_compact_pv_negative_keeps_all(tmp_path):
    # check_if_stale_split_data: pv < 0 -> keep (opposite of scan path)
    eng = StorageEngine(str(tmp_path / "e"))
    keys = [k("user_%d" % i, "s") for i in range(10)]
    eng.write_batch([WriteBatchItem(OP_PUT, key, b"v") for key in keys],
                    decree=1)
    eng.manual_compact(validate_hash=True, pidx=0, partition_version=-1)
    assert all(eng.get(key) is not None for key in keys)
    eng.close()


def test_multi_run_l1_compaction_and_recovery(tmp_path):
    """Range-capped compaction: output splits into non-overlapping runs,
    reads/scans stay correct, and the manifest makes recovery exact."""
    from pegasus_tpu.storage.lsm import LSMStore

    d = str(tmp_path / "lsm")
    lsm = LSMStore(d, l1_run_capacity=100)
    for i in range(350):
        lsm.put(b"k%05d" % i, b"v%d" % i)
    lsm.flush()
    for i in range(350, 700):
        lsm.put(b"k%05d" % i, b"v%d" % i)
    lsm.flush()
    lsm.compact()
    assert len(lsm.l1_runs) == 7  # 700 records / 100-cap runs
    # non-overlapping + ordered
    for a, b in zip(lsm.l1_runs, lsm.l1_runs[1:]):
        assert a.last_key < b.first_key
    # point reads route to the right run
    for i in (0, 99, 100, 350, 699):
        assert lsm.get(b"k%05d" % i) == (b"v%d" % i, 0)
    # ranged scans merge across run boundaries
    got = [k for k, _v, _e in lsm.iterate(b"k00095", b"k00105")]
    assert got == [b"k%05d" % i for i in range(95, 105)]
    assert lsm.sorted_runs() is not None and len(lsm.sorted_runs()) == 7
    lsm.close()

    # recovery via manifest: all runs come back
    lsm2 = LSMStore(d, l1_run_capacity=100)
    assert len(lsm2.l1_runs) == 7
    assert lsm2.get(b"k00500") == (b"v500", 0)
    # a second compaction after more writes keeps working
    lsm2.put(b"k00500", b"updated")
    lsm2.delete(b"k00000")
    lsm2.flush()
    lsm2.compact()
    assert lsm2.get(b"k00500") == (b"updated", 0)
    assert lsm2.get(b"k00000") is None
    lsm2.close()


def test_manifest_cleans_crash_leftovers(tmp_path):
    """An l1 file not in the manifest (incomplete compaction output) is
    removed at boot; l0 files older than the horizon too."""
    import os

    from pegasus_tpu.storage.lsm import LSMStore

    d = str(tmp_path / "lsm")
    lsm = LSMStore(d, l1_run_capacity=50)
    for i in range(120):
        lsm.put(b"a%04d" % i, b"v")
    lsm.flush()
    lsm.compact()
    runs_before = [os.path.basename(t.path) for t in lsm.l1_runs]
    lsm.close()
    # simulate a crashed compaction: an orphan l1 output + stale l0 input
    open(os.path.join(d, "l1-9999.sst"), "wb").write(b"garbage")
    open(os.path.join(d, "l0-0.sst"), "wb").write(b"garbage")
    lsm2 = LSMStore(d)
    assert sorted(os.path.basename(t.path) for t in lsm2.l1_runs) == \
        sorted(runs_before)
    assert not os.path.exists(os.path.join(d, "l1-9999.sst"))
    assert not os.path.exists(os.path.join(d, "l0-0.sst"))
    assert lsm2.get(b"a0050") == (b"v", 0)
    lsm2.close()


def test_checkpoint_carries_manifest(tmp_path):
    """A checkpoint of a multi-run store restores with ALL runs (the
    manifest travels with the SSTs)."""
    from pegasus_tpu.storage.engine import StorageEngine, WriteBatchItem
    from pegasus_tpu.storage.wal import OP_PUT
    from pegasus_tpu.base.value_schema import generate_value

    eng = StorageEngine(str(tmp_path / "e"))
    eng.lsm._l1_run_capacity = 50
    items = [WriteBatchItem(OP_PUT, b"c%04d" % i,
                            generate_value(1, b"v%d" % i, 0), 0)
             for i in range(160)]
    eng.write_batch(items, 1)
    eng.manual_compact()
    assert len(eng.lsm.l1_runs) > 1
    ck = str(tmp_path / "ckpt")
    eng.checkpoint(ck)
    restored = StorageEngine.restore_from_checkpoint(
        ck, str(tmp_path / "r"))
    assert len(restored.lsm.l1_runs) == len(eng.lsm.l1_runs)
    for i in (0, 70, 159):
        hit = restored.lsm.get(b"c%04d" % i)
        assert hit is not None
    eng.close()
    restored.close()


def test_empty_compaction_keeps_seq_horizon(tmp_path):
    """Review regression: an all-tombstone compaction leaves no .sst files;
    the next boot must still honor the manifest's seq horizon or freshly
    flushed L0 files get deleted as 'consumed compaction inputs'."""
    from pegasus_tpu.storage.lsm import LSMStore

    d = str(tmp_path / "lsm")
    lsm = LSMStore(d)
    lsm.put(b"k", b"v")
    lsm.flush()
    lsm.delete(b"k")
    lsm.flush()
    lsm.compact()
    assert not lsm.l1_runs  # everything dropped
    lsm.close()

    lsm2 = LSMStore(d)
    lsm2.put(b"new", b"data")
    lsm2.flush()
    lsm2.close()

    lsm3 = LSMStore(d)  # the boot that used to eat the fresh flush
    assert lsm3.get(b"new") == (b"data", 0)
    lsm3.close()


def test_auto_flush_and_compact_bound_growth(tmp_path):
    """A write-heavy engine flushes at the memtable trigger and compacts
    at the L0 trigger without any manual call (the rocksdb write-buffer +
    level-0 trigger parity)."""
    from pegasus_tpu.base.value_schema import generate_value
    from pegasus_tpu.storage.engine import StorageEngine, WriteBatchItem
    from pegasus_tpu.storage.wal import OP_PUT

    eng = StorageEngine(str(tmp_path / "e"))
    eng.memtable_flush_trigger = 500
    d = 0
    for batch in range(12):
        items = [WriteBatchItem(
            OP_PUT, b"a%06d" % (batch * 200 + i),
            generate_value(1, b"v", 0), 0) for i in range(200)]
        d += 1
        eng.write_batch(items, d)
    # flush trigger fired (memtable bounded) and at least one compaction
    assert len(eng.lsm.memtable) < 500
    assert eng._ev_flush_count._value >= 3
    assert eng._ev_compact_count._value >= 1
    assert len(eng.lsm.l0) < 4 + 1
    # everything still readable
    assert eng.get(b"a000000") is not None
    assert eng.get(b"a%06d" % (12 * 200 - 1)) is not None
    eng.close()


def test_usage_scenarios_rewire_maintenance(tmp_path):
    from pegasus_tpu.server.partition_server import PartitionServer

    srv = PartitionServer(str(tmp_path / "p"))
    srv.update_app_envs({"rocksdb.usage_scenario": "bulk_load"})
    assert srv.engine.auto_compact is False
    assert srv.engine.memtable_flush_trigger == 500_000
    srv.update_app_envs({"rocksdb.usage_scenario": "prefer_write"})
    assert srv.engine.auto_compact and srv.engine.lsm._l0_trigger == 8
    srv.update_app_envs({"rocksdb.usage_scenario": "normal"})
    assert srv.engine.lsm._l0_trigger == 4
    import pytest as _pytest

    with _pytest.raises(ValueError):
        srv.update_app_envs({"rocksdb.usage_scenario": "warp_speed"})
    srv.close()
