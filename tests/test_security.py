"""Auth + table-ACL tests (parity: security/negotiation.h:37 role,
ranger table allow-lists enforced at the client gates)."""

import pytest

from pegasus_tpu.security.auth import check_client, make_credentials, sign, verify
from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.utils.errors import ErrorCode, PegasusError, StorageStatus

OK = int(StorageStatus.OK)


def test_hmac_roundtrip():
    user, token = make_credentials("alice", "s3cret")
    assert verify(user, token, "s3cret")
    assert not verify(user, token, "other")
    assert not verify("bob", token, "s3cret")
    assert sign("alice", "s3cret") != sign("alice", "s3cret2")


def test_check_client_matrix():
    good = make_credentials("alice", "k")
    assert check_client(good, "k")
    assert not check_client(None, "k")
    assert not check_client(("alice", "bad"), "k")
    # allow-list gates even authenticated users
    assert check_client(good, "k", allowed_users="alice,bob")
    assert not check_client(good, "k", allowed_users="bob")
    # open cluster (no secret): allow-list still applies by claimed user
    assert check_client(("alice", ""), None, allowed_users="alice")
    assert not check_client(("eve", ""), None, allowed_users="alice")
    assert check_client(None, None)


@pytest.fixture
def secure_cluster(tmp_path):
    c = SimCluster(str(tmp_path / "c"), n_nodes=3, auth_secret="topsecret")
    yield c
    c.close()


def test_authenticated_cluster_rejects_anonymous(secure_cluster):
    secure_cluster.create_table("sec", partition_count=2)
    good = secure_cluster.client("sec", user="alice")
    assert good.set(b"k", b"s", b"v") == OK
    assert good.get(b"k", b"s") == (OK, b"v")
    # a client with no credentials is denied (ACL_DENY is not retryable)
    anon = secure_cluster.client("sec", name="anon")
    anon.auth = None
    with pytest.raises(PegasusError) as e:
        anon.set(b"k2", b"s", b"v")
    assert e.value.code == ErrorCode.ERR_ACL_DENY
    with pytest.raises(PegasusError):
        anon.get(b"k", b"s")
    # forged token denied too
    bad = secure_cluster.client("sec", name="forger")
    bad.auth = ("alice", "deadbeef")
    with pytest.raises(PegasusError):
        bad.get(b"k", b"s")


def test_table_acl_allow_list(secure_cluster):
    secure_cluster.create_table("acl", partition_count=2)
    secure_cluster.meta.update_app_envs(
        "acl", {"replica.allowed_users": "alice"})
    secure_cluster.step()
    alice = secure_cluster.client("acl", name="c-alice", user="alice")
    mallory = secure_cluster.client("acl", name="c-mal", user="mallory")
    assert alice.set(b"k", b"s", b"v") == OK
    with pytest.raises(PegasusError) as e:
        mallory.get(b"k", b"s")
    assert e.value.code == ErrorCode.ERR_ACL_DENY
    # widening the list admits the second user
    secure_cluster.meta.update_app_envs(
        "acl", {"replica.allowed_users": "alice,mallory"})
    secure_cluster.step()
    assert mallory.get(b"k", b"s") == (OK, b"v")


def test_access_policy_parse():
    from pegasus_tpu.security.auth import parse_policy

    pol = parse_policy("alice=rw;bob=r; ops = rwa ;broken;*=r")
    assert pol["alice"] == {"r", "w"}
    assert pol["bob"] == {"r"}
    assert pol["ops"] == {"r", "w", "a"}
    assert pol["*"] == {"r"}
    assert "broken" not in pol
    # unknown grant chars are dropped, not granted
    assert parse_policy("eve=rx")["eve"] == {"r"}


def test_per_verb_access_policy(secure_cluster):
    """Ranger-style per-verb split (access_type.h): a read-only user is
    denied writes at the client gate; a writer without read is denied
    reads; wildcard grants any authenticated user."""
    secure_cluster.create_table("rbac", partition_count=2)
    secure_cluster.meta.update_app_envs(
        "rbac", {"replica.access_policy": "writer=rw;reader=r"})
    secure_cluster.step()
    writer = secure_cluster.client("rbac", name="c-w", user="writer")
    reader = secure_cluster.client("rbac", name="c-r", user="reader")
    assert writer.set(b"k", b"s", b"v") == OK
    assert reader.get(b"k", b"s") == (OK, b"v")
    # read-only user denied the write verb
    with pytest.raises(PegasusError) as e:
        reader.set(b"k2", b"s", b"v")
    assert e.value.code == ErrorCode.ERR_ACL_DENY
    # scans are reads: allowed for reader, and an unlisted user is
    # denied both verbs
    assert [x for x in reader.get_scanner(b"k")] == [(b"k", b"s", b"v")]
    nobody = secure_cluster.client("rbac", name="c-n", user="nobody")
    with pytest.raises(PegasusError):
        nobody.get(b"k", b"s")
    with pytest.raises(PegasusError):
        nobody.set(b"k3", b"s", b"v")
    # wildcard read grant admits any AUTHENTICATED user to reads only
    secure_cluster.meta.update_app_envs(
        "rbac", {"replica.access_policy": "writer=rw;*=r"})
    secure_cluster.step()
    assert nobody.get(b"k", b"s") == (OK, b"v")
    with pytest.raises(PegasusError):
        nobody.set(b"k3", b"s", b"v")


def test_duplication_works_on_secured_cluster(secure_cluster):
    """Inter-node duplication authenticates as the reserved node user."""
    secure_cluster.create_table("sm", partition_count=2)
    secure_cluster.create_table("sf", partition_count=2)
    c = secure_cluster.client("sm", user="alice")
    secure_cluster.meta.duplication.add_duplication("sm", "meta", "sf")
    secure_cluster.step(rounds=3)
    assert c.set(b"sk", b"s", b"sv") == OK
    for _ in range(6):
        secure_cluster.step()
    fc = secure_cluster.client("sf", user="alice")
    assert fc.get(b"sk", b"s") == (OK, b"sv")
