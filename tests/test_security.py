"""Auth + table-ACL tests (parity: security/negotiation.h:37 role,
ranger table allow-lists enforced at the client gates)."""

import pytest

from pegasus_tpu.security.auth import check_client, make_credentials, sign, verify
from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.utils.errors import ErrorCode, PegasusError, StorageStatus

OK = int(StorageStatus.OK)


def test_hmac_roundtrip():
    user, token = make_credentials("alice", "s3cret")
    assert verify(user, token, "s3cret")
    assert not verify(user, token, "other")
    assert not verify("bob", token, "s3cret")
    assert sign("alice", "s3cret") != sign("alice", "s3cret2")


def test_check_client_matrix():
    good = make_credentials("alice", "k")
    assert check_client(good, "k")
    assert not check_client(None, "k")
    assert not check_client(("alice", "bad"), "k")
    # allow-list gates even authenticated users
    assert check_client(good, "k", allowed_users="alice,bob")
    assert not check_client(good, "k", allowed_users="bob")
    # open cluster (no secret): allow-list still applies by claimed user
    assert check_client(("alice", ""), None, allowed_users="alice")
    assert not check_client(("eve", ""), None, allowed_users="alice")
    assert check_client(None, None)


@pytest.fixture
def secure_cluster(tmp_path):
    c = SimCluster(str(tmp_path / "c"), n_nodes=3, auth_secret="topsecret")
    yield c
    c.close()


def test_authenticated_cluster_rejects_anonymous(secure_cluster):
    secure_cluster.create_table("sec", partition_count=2)
    good = secure_cluster.client("sec", user="alice")
    assert good.set(b"k", b"s", b"v") == OK
    assert good.get(b"k", b"s") == (OK, b"v")
    # a client with no credentials is denied (ACL_DENY is not retryable)
    anon = secure_cluster.client("sec", name="anon")
    anon.auth = None
    with pytest.raises(PegasusError) as e:
        anon.set(b"k2", b"s", b"v")
    assert e.value.code == ErrorCode.ERR_ACL_DENY
    with pytest.raises(PegasusError):
        anon.get(b"k", b"s")
    # forged token denied too
    bad = secure_cluster.client("sec", name="forger")
    bad.auth = ("alice", "deadbeef")
    with pytest.raises(PegasusError):
        bad.get(b"k", b"s")


def test_table_acl_allow_list(secure_cluster):
    secure_cluster.create_table("acl", partition_count=2)
    secure_cluster.meta.update_app_envs(
        "acl", {"replica.allowed_users": "alice"})
    secure_cluster.step()
    alice = secure_cluster.client("acl", name="c-alice", user="alice")
    mallory = secure_cluster.client("acl", name="c-mal", user="mallory")
    assert alice.set(b"k", b"s", b"v") == OK
    with pytest.raises(PegasusError) as e:
        mallory.get(b"k", b"s")
    assert e.value.code == ErrorCode.ERR_ACL_DENY
    # widening the list admits the second user
    secure_cluster.meta.update_app_envs(
        "acl", {"replica.allowed_users": "alice,mallory"})
    secure_cluster.step()
    assert mallory.get(b"k", b"s") == (OK, b"v")


def test_access_policy_parse():
    from pegasus_tpu.security.auth import parse_policy

    pol = parse_policy("alice=rw;bob=r; ops = rwa ;broken;*=r")
    assert pol["alice"] == {"r", "w"}
    assert pol["bob"] == {"r"}
    assert pol["ops"] == {"r", "w", "a"}
    assert pol["*"] == {"r"}
    assert "broken" not in pol
    # unknown grant chars are dropped, not granted
    assert parse_policy("eve=rx")["eve"] == {"r"}


def test_per_verb_access_policy(secure_cluster):
    """Ranger-style per-verb split (access_type.h): a read-only user is
    denied writes at the client gate; a writer without read is denied
    reads; wildcard grants any authenticated user."""
    secure_cluster.create_table("rbac", partition_count=2)
    secure_cluster.meta.update_app_envs(
        "rbac", {"replica.access_policy": "writer=rw;reader=r"})
    secure_cluster.step()
    writer = secure_cluster.client("rbac", name="c-w", user="writer")
    reader = secure_cluster.client("rbac", name="c-r", user="reader")
    assert writer.set(b"k", b"s", b"v") == OK
    assert reader.get(b"k", b"s") == (OK, b"v")
    # read-only user denied the write verb
    with pytest.raises(PegasusError) as e:
        reader.set(b"k2", b"s", b"v")
    assert e.value.code == ErrorCode.ERR_ACL_DENY
    # scans are reads: allowed for reader, and an unlisted user is
    # denied both verbs
    assert [x for x in reader.get_scanner(b"k")] == [(b"k", b"s", b"v")]
    nobody = secure_cluster.client("rbac", name="c-n", user="nobody")
    with pytest.raises(PegasusError):
        nobody.get(b"k", b"s")
    with pytest.raises(PegasusError):
        nobody.set(b"k3", b"s", b"v")
    # wildcard read grant admits any AUTHENTICATED user to reads only
    secure_cluster.meta.update_app_envs(
        "rbac", {"replica.access_policy": "writer=rw;*=r"})
    secure_cluster.step()
    assert nobody.get(b"k", b"s") == (OK, b"v")
    with pytest.raises(PegasusError):
        nobody.set(b"k3", b"s", b"v")


def test_duplication_works_on_secured_cluster(secure_cluster):
    """Inter-node duplication authenticates as the reserved node user."""
    secure_cluster.create_table("sm", partition_count=2)
    secure_cluster.create_table("sf", partition_count=2)
    c = secure_cluster.client("sm", user="alice")
    secure_cluster.meta.duplication.add_duplication("sm", "meta", "sf")
    secure_cluster.step(rounds=3)
    assert c.set(b"sk", b"s", b"sv") == OK
    for _ in range(6):
        secure_cluster.step()
    fc = secure_cluster.client("sf", user="alice")
    assert fc.get(b"sk", b"s") == (OK, b"sv")


def test_negotiation_state_machine_unit():
    """Unit-level transitions (parity: negotiation.cpp rejects invalid
    transitions): happy path, out-of-order stages, bad proof, restart
    voiding the old identity."""
    from pegasus_tpu.security.negotiation import (
        NegotiationClient,
        NegotiationServer,
    )

    srv = NegotiationServer("k")

    def drive(payloads):
        return [srv.on_message("peer", p) for p in payloads]

    # out-of-order: respond before anything
    (r,) = drive([{"stage": "respond", "proof": "x"}])
    assert r["stage"] == "fail"
    # select before list
    (r,) = drive([{"stage": "select", "mechanism": "HMAC-SHA256",
                   "user": "u"}])
    assert r["stage"] == "fail"
    # happy path through the client driver
    def call(payload):
        return srv.on_message("peer", payload)

    assert NegotiationClient("alice", "k").negotiate(call)
    assert srv.identity("peer") == "alice"
    # wrong secret fails at the proof step and clears the identity
    assert not NegotiationClient("alice", "WRONG").negotiate(call)
    assert srv.identity("peer") is None
    # restart voids a previously negotiated identity immediately
    assert NegotiationClient("bob", "k").negotiate(call)
    srv.on_message("peer", {"stage": "list_mechanisms"})
    assert srv.identity("peer") is None
    srv.forget("peer")


def test_negotiated_session_serves_without_per_request_tokens(
        secure_cluster):
    """End-to-end: an anonymous client is denied; after the handshake
    its SESSION identity authenticates requests (and the per-verb
    policy applies to that identity)."""
    secure_cluster.create_table("neg", partition_count=1)
    secure_cluster.meta.update_app_envs(
        "neg", {"replica.access_policy": "alice=rw"})
    secure_cluster.step()
    c = secure_cluster.client("neg", name="c-neg")
    c.auth = None  # no per-request credentials at all
    with pytest.raises(PegasusError):
        c.set(b"k", b"s", b"v")
    c.refresh_config()
    node = c._primary_of(0)
    # wrong secret: handshake fails, still denied
    assert not c.negotiate(node, "alice", "WRONG")
    with pytest.raises(PegasusError):
        c.set(b"k", b"s", b"v")
    # correct handshake: session identity serves both verbs
    assert c.negotiate(node, "alice", "topsecret")
    assert c.set(b"k", b"s", b"v") == OK
    assert c.get(b"k", b"s") == (OK, b"v")
    # the session identity is still subject to the ACL policy
    secure_cluster.meta.update_app_envs(
        "neg", {"replica.access_policy": "alice=r"})
    secure_cluster.step()
    with pytest.raises(PegasusError) as e:
        c.set(b"k2", b"s", b"v")
    assert e.value.code == ErrorCode.ERR_ACL_DENY


def test_negotiated_identity_binds_to_connection_not_name():
    """Over REAL TCP, a negotiated identity must bind to the
    connection, not to the frame's self-reported src name — a second
    connection claiming the same name must NOT inherit the identity
    (the impersonation the session keying exists to stop)."""
    import time as _time

    from pegasus_tpu.rpc.transport import TcpTransport
    from pegasus_tpu.security.negotiation import NegotiationServer

    server = TcpTransport(("127.0.0.1", 0), {})
    host, port = server.listen_addr
    neg = NegotiationServer("shh")
    seen = []

    def srv_handler(src, msg_type, payload):
        sess = server.current_session()
        key = (src, sess)
        if msg_type == "negotiate":
            server.send("srv", src, "negotiate_reply",
                        neg.on_message(key, payload))
        elif msg_type == "whoami":
            seen.append(neg.identity(key))
    server.register("srv", srv_handler)
    server.on_session_closed(neg.forget_session)

    def mk_client(name):
        t = TcpTransport(None, {"srv": (host, port)})
        replies = []
        t.register(name, lambda s, mt, p: replies.append(p))
        return t, replies

    c1, r1 = mk_client("cli")

    def call(t, replies, payload):
        n = len(replies)
        t.send("cli", "srv", "negotiate", payload)
        deadline = _time.monotonic() + 5
        while len(replies) == n and _time.monotonic() < deadline:
            _time.sleep(0.005)
        return replies[-1] if len(replies) > n else {}

    from pegasus_tpu.security.negotiation import NegotiationClient

    ok = NegotiationClient("alice", "shh").negotiate(
        lambda p: call(c1, r1, p))
    assert ok
    c1.send("cli", "srv", "whoami", {})
    # ATTACKER: a fresh TCP connection forging src="cli", no handshake
    c2, _r2 = mk_client("cli")
    c2.send("cli", "srv", "whoami", {})
    deadline = _time.monotonic() + 5
    while len(seen) < 2 and _time.monotonic() < deadline:
        _time.sleep(0.005)
    assert seen[0] == "alice"      # the negotiated connection
    assert seen[1] is None, "forged src inherited the identity!"
    # teardown drops the identity with the connection
    c1.close()
    _time.sleep(0.3)
    c3, _ = mk_client("cli")
    c3.send("cli", "srv", "whoami", {})
    deadline = _time.monotonic() + 5
    while len(seen) < 3 and _time.monotonic() < deadline:
        _time.sleep(0.005)
    assert seen[2] is None
    c2.close()
    c3.close()
    server.close()
