"""Bulk load: offline SST generation -> block service -> ingestion."""

import pytest

from pegasus_tpu.client import PegasusClient, Table
from pegasus_tpu.server.bulk_load import (
    BulkLoader,
    BulkLoadStatus,
    SSTGenerator,
)
from pegasus_tpu.storage.block_service import LocalBlockService


def test_generate_and_load(tmp_path):
    bs = LocalBlockService(str(tmp_path / "bucket"))
    gen = SSTGenerator(bs, "imports", partition_count=4)
    records = [(b"user_%03d" % i, b"field", b"v%d" % i, 0)
               for i in range(200)]
    counts = gen.generate(records)
    assert sum(counts.values()) == 200

    t = Table(str(tmp_path / "t"), app_name="imports", partition_count=4)
    try:
        c = PegasusClient(t)
        c.set(b"pre_existing", b"s", b"old")  # normal writes coexist
        loader = BulkLoader(bs)
        total = loader.load_into(t)
        assert total == 200
        assert all(s == BulkLoadStatus.SUCCEED
                   for s in loader.status.values())
        for i in range(200):
            assert c.get(b"user_%03d" % i, b"field") == (0, b"v%d" % i)
        assert c.get(b"pre_existing", b"s") == (0, b"old")
        # ingested data participates in scans + compaction like any other
        t.manual_compact_all()
        assert c.get(b"user_042", b"field") == (0, b"v42")
        # writes continue after ingestion (decree discipline intact)
        assert c.set(b"user_000", b"field", b"updated") == 0
        assert c.get(b"user_000", b"field") == (0, b"updated")
    finally:
        t.close()


def test_load_rejects_partition_mismatch(tmp_path):
    bs = LocalBlockService(str(tmp_path / "bucket"))
    SSTGenerator(bs, "imports", partition_count=8).generate(
        [(b"h", b"s", b"v", 0)])
    t = Table(str(tmp_path / "t"), app_name="imports", partition_count=4)
    try:
        with pytest.raises(ValueError):
            BulkLoader(bs).load_into(t)
    finally:
        t.close()


def test_generator_last_writer_wins_on_duplicates(tmp_path):
    bs = LocalBlockService(str(tmp_path / "bucket"))
    gen = SSTGenerator(bs, "imports", partition_count=2)
    # a REAL duplicate: the later record must win, and counts must not
    # include the dropped one
    counts = gen.generate([(b"h", b"s", b"old", 0), (b"h", b"s", b"new", 0),
                           (b"h", b"s2", b"x", 0)])
    assert sum(counts.values()) == 2
    t = Table(str(tmp_path / "t"), app_name="imports", partition_count=2)
    try:
        assert BulkLoader(bs).load_into(t) == 2
        c = PegasusClient(t)
        assert c.get(b"h", b"s") == (0, b"new")
        assert c.sortkey_count(b"h") == (0, 2)
    finally:
        t.close()


def test_empty_hashkey_routes_like_reads(tmp_path):
    # regression: the generator must bucket by the same routing the client
    # uses — an empty hashkey previously landed where reads never look
    bs = LocalBlockService(str(tmp_path / "bucket"))
    SSTGenerator(bs, "imports", partition_count=4).generate(
        [(b"", b"sortonly", b"v", 0)])
    t = Table(str(tmp_path / "t"), app_name="imports", partition_count=4)
    try:
        BulkLoader(bs).load_into(t)
        assert PegasusClient(t).get(b"", b"sortonly") == (0, b"v")
    finally:
        t.close()


def test_load_rejects_data_version_mismatch(tmp_path):
    bs = LocalBlockService(str(tmp_path / "bucket"))
    SSTGenerator(bs, "imports", partition_count=2,
                 data_version=0).generate([(b"h", b"s", b"v", 0)])
    t = Table(str(tmp_path / "t"), app_name="imports", partition_count=2)
    try:
        with pytest.raises(ValueError):
            BulkLoader(bs).load_into(t)  # table is v1
    finally:
        t.close()


def test_ingest_flushes_memtable_first(tmp_path):
    # regression: unflushed earlier writes must survive a restart after an
    # ingest (the ingest decree becomes the flushed watermark) and must
    # not outrank the newer ingested run
    from pegasus_tpu.storage.engine import StorageEngine, WriteBatchItem
    from pegasus_tpu.storage.sstable import SSTableWriter
    from pegasus_tpu.storage.wal import OP_PUT
    from pegasus_tpu.base.key_schema import generate_key

    key = generate_key(b"h", b"s")
    ext = str(tmp_path / "ext.sst")
    w = SSTableWriter(ext)
    # note: encoded-key order sorts by hashkey LENGTH first (u16 prefix)
    w.add(key, b"\x00\x00\x00\x00ingested")
    w.add(generate_key(b"earlier", b"s"), b"\x00\x00\x00\x00kept")
    w.finish()

    eng = StorageEngine(str(tmp_path / "e"))
    eng.write_batch([WriteBatchItem(OP_PUT, key, b"\x00\x00\x00\x00memv")],
                    decree=1)
    eng.ingest_sst_file(ext, decree=2)
    # the ingested (newer-decree) value wins over the flushed decree-1 one
    assert eng.get(key)[0] == b"\x00\x00\x00\x00ingested"
    eng.close()
    eng2 = StorageEngine(str(tmp_path / "e"))
    # nothing lost on restart
    assert eng2.get(generate_key(b"earlier", b"s")) is not None
    assert eng2.get(key)[0] == b"\x00\x00\x00\x00ingested"
    eng2.close()


def test_ingest_decree_discipline(tmp_path):
    from pegasus_tpu.storage.engine import StorageEngine
    from pegasus_tpu.storage.sstable import SSTableWriter
    from pegasus_tpu.base.key_schema import generate_key

    path = str(tmp_path / "ext.sst")
    w = SSTableWriter(path)
    w.add(generate_key(b"h", b"s"), b"\x00\x00\x00\x00v")
    w.finish()
    eng = StorageEngine(str(tmp_path / "e"))
    try:
        eng.ingest_sst_file(path, decree=5)
        assert eng.last_committed_decree == 5
        assert eng.last_flushed_decree == 5
        with pytest.raises(ValueError):
            eng.ingest_sst_file(path, decree=5)  # regression guard
        # the ingested meta carries the decree -> recovery sees it
        eng.close()
        eng2 = StorageEngine(str(tmp_path / "e"))
        assert eng2.last_flushed_decree == 5
        assert eng2.get(generate_key(b"h", b"s")) is not None
        eng2.close()
    finally:
        pass
