"""Pure-Perl wire client (clients/perl/PegasusTpu.pm): a THIRD client
language speaking PGT1 natively (no FFI), driven against a live
multi-process onebox with both-ways interop. Parity role: the
reference's multi-language client family (go/java/nodejs/scala)."""

import os
import shutil
import subprocess
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PERL_DIR = os.path.join(REPO, "clients", "perl")


def _perl():
    return shutil.which("perl")


def test_perl_crc64_matches_golden():
    """The Perl crc64 must be bit-identical to base/crc.py (which is
    pinned to the reference by golden vectors)."""
    if not _perl():
        pytest.skip("no perl")
    from pegasus_tpu.base.crc import crc64

    script = (
        'use lib "%s"; use PegasusTpu; '
        'for my $s ("", "a", "hello world", "user00000042") '
        '{ printf "%%s\\n", PegasusTpu::crc64($s); }' % PERL_DIR)
    out = subprocess.run([_perl(), "-e", script], capture_output=True,
                         text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    got = [int(x) for x in out.stdout.split()]
    want = [crc64(b""), crc64(b"a"), crc64(b"hello world"),
            crc64(b"user00000042")]
    assert got == want


def test_perl_client_against_onebox(tmp_path):
    if not _perl():
        pytest.skip("no perl")
    from pegasus_tpu.tools import onebox_cluster as ob
    from pegasus_tpu.utils.errors import PegasusError

    d = str(tmp_path / "onebox")
    ob.start(d, n_replica=2)
    try:
        admin = ob.OneboxAdmin(d)
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                if len(admin.call("list_nodes", timeout=6)) == 2:
                    break
            except PegasusError:
                pass
            time.sleep(0.5)
        admin.create_table("perlapp", partition_count=4,
                           replica_count=2)
        admin.close()
        # python writes something perl will NOT touch, for interop
        pc = ob.connect("perlapp", d)
        assert pc.set(b"python-wrote", b"s", b"hello-from-python") == 0

        out = subprocess.run(
            [_perl(), os.path.join(PERL_DIR, "pegasus_demo.pl"),
             os.path.join(d, "cluster.json"), "perlapp"],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr + out.stdout
        assert "PERL CLIENT OK" in out.stdout, out.stdout
        for line in ("ok set 20", "ok get 20", "ok notfound",
                     "ok multi_get 10", "ok scan 30 paged",
                     "ok scan ranged 10", "ok del", "ok marker"):
            assert line in out.stdout, out.stdout

        # both-ways interop: python reads what perl wrote
        assert pc.get(b"perl-wrote", b"s") == (0, b"hello-from-perl")
    finally:
        ob.stop(d)
