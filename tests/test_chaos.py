"""Chaos robustness: deadlines + backoff + fault injection end to end.

Parity: the reference's kill-test + data-verifier harness
(src/test/kill_test/data_verifier.cpp) run against BOTH network layers —
the deterministic SimNetwork schedule (drop/delay/duplicate/partition
from one seed) and the real TcpTransport with an rpc/fault.FaultPlan
installed in every onebox process. The invariant everywhere: zero
acked-write loss, and every client op either succeeds or raises a typed
PegasusError within its end-to-end deadline — no hangs, no zero-sleep
retry spin.
"""

import random
import time

import pytest

from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.tools.kill_test import DataVerifier
from pegasus_tpu.utils.errors import ErrorCode, PegasusError

OK = 0


def test_chaos_smoke_sim(tmp_path):
    """<10s seeded smoke: lossy/slow/duplicating network, then a full
    node partition, then a primary kill — all from seed 11, replayable."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=4, seed=11)
    try:
        app_id = cluster.create_table("chaos", partition_count=4)
        client = cluster.client("chaos")
        client.op_timeout_ms = 600_000  # 600 sim-seconds, spans retries
        verifier = DataVerifier(client, random.Random(11))
        # phase 1: 10% loss, +20ms latency, 3% duplicates, everywhere
        cluster.net.set_drop(0.10)
        cluster.net.set_delay(0.02)
        cluster.net.set_duplicate(0.03)
        for _ in range(20):
            verifier.step()
        # phase 2: one primary's node fully partitioned; writes keep
        # flowing because retries + refresh re-resolve after the cure
        victim = cluster.primaries(app_id)[0]
        cluster.net.partition(victim)
        for _ in range(10):
            verifier.step()
        cluster.net.heal(victim)
        # phase 3: crash another primary outright (kill -9 analogue)
        victim2 = next(p for p in cluster.primaries(app_id)
                       if p and p != victim)
        cluster.kill(victim2)
        for _ in range(10):
            verifier.step()
        # calm the network; let cures and stragglers finish
        cluster.net.set_drop(0.0)
        cluster.net.set_delay(0.0)
        cluster.net.set_duplicate(0.0)
        cluster.step(rounds=4)
        assert verifier.violations == [], verifier.violations
        assert verifier.write_ok >= 20
        # the DataVerifier invariant: every acked write stays readable
        for hk, want in verifier.acked.items():
            assert client.get(hk, b"s") == (OK, want), hk
        # retries showed MEASURED backoff sleep — the zero-sleep retry
        # spin this PR removes would leave slept empty under this much
        # loss (sleeps advance virtual time, so the wall stays fast)
        assert client.backoff.slept, "no backoff recorded under chaos"
        assert min(client.backoff.slept) > 0
        assert cluster.net.dropped > 0 and cluster.net.delivered > 0
    finally:
        cluster.close()


def test_client_deadline_typed_and_bounded(tmp_path):
    """With every replica unreachable, an op neither hangs nor spins:
    it raises typed ERR_TIMEOUT once its end-to-end deadline lapses."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=2)
    try:
        cluster.create_table("dl", partition_count=2)
        client = cluster.client("dl")
        assert client.set(b"k", b"s", b"v") == OK  # resolve config first
        for name in list(cluster.stubs):
            cluster.net.partition(name)
        client.op_timeout_ms = 10_000  # 10 sim-seconds
        t0 = time.monotonic()
        with pytest.raises(PegasusError) as ei:
            client.set(b"k2", b"s", b"v2")
        assert ei.value.code == ErrorCode.ERR_TIMEOUT
        assert time.monotonic() - t0 < 30  # bounded in wall time too
    finally:
        cluster.close()


def test_server_fast_fails_expired_deadline(tmp_path):
    """Replica-side gates drop work whose deadline already passed:
    reads AND writes get a typed ERR_TIMEOUT reply without touching
    the storage app or the 2PC."""
    cluster = SimCluster(str(tmp_path / "c"), n_nodes=3, seed=3)
    try:
        app_id = cluster.create_table("ex", partition_count=2)
        client = cluster.client("ex")
        assert client.set(b"k", b"s", b"v") == OK
        primary = cluster.primaries(app_id)[0]
        stub = cluster.stubs[primary]
        past = stub.clock() - 5.0
        # read gate
        err, r = stub._client_read_gate(
            {"gpid": (app_id, 0), "deadline": past, "auth": None}, "cx")
        assert err == int(ErrorCode.ERR_TIMEOUT) and r is None
        # write path, through the wire: reply is typed, 2PC never ran
        decrees_before = {
            gpid: rep.last_committed_decree
            for gpid, rep in stub.replicas.items()}
        rid = client._send_request(primary, "client_write", {
            "gpid": (app_id, 0), "ops": [], "auth": None,
            "partition_hash": None}, deadline=past)
        reply = client._await(rid)
        assert reply is not None
        assert reply["err"] == int(ErrorCode.ERR_TIMEOUT)
        assert decrees_before == {
            gpid: rep.last_committed_decree
            for gpid, rep in stub.replicas.items()}
    finally:
        cluster.close()


def test_tcp_chaos_smoke_faultplan(tmp_path):
    """Real processes, real TCP, config-armed FaultPlan (drop + delay on
    every link) PLUS a kill -9 mid-run: the data-verifier invariant must
    hold on the transport the production path uses."""
    from pegasus_tpu.tools import onebox_cluster as ob
    from pegasus_tpu.tools.kill_test import run_kill_test

    d = str(tmp_path / "chaosbox")
    ob.start(d, n_replica=3, fault_plan={
        "seed": 5,
        "drop": [{"prob": 0.02}],
        "delay": [{"extra_s": 0.002}],
    })
    try:
        # light faults: every dropped request/reply costs the verifier
        # client its full per-attempt pump window, so loss directly
        # taxes throughput — the invariant matters here, not the rate
        report = run_kill_test(d, duration_s=20, kill_every_s=14,
                               seed=9, op_timeout_ms=30_000)
        assert report["violations"] == [], report
        assert report["writes_acked"] > 5
        assert report["kills"] >= 1
    finally:
        ob.stop(d)


@pytest.mark.slow
def test_chaos_soak_pause_mode(tmp_path):
    """Long soak: SIGSTOP/SIGCONT chaos (hung-node detection — the
    pause outlives the FD grace, so meta must cure around a node that
    never crashed) under sustained link faults. Excluded from tier-1 by
    the slow marker; run with `pytest -m slow tests/test_chaos.py`."""
    from pegasus_tpu.tools import onebox_cluster as ob
    from pegasus_tpu.tools.kill_test import run_kill_test

    d = str(tmp_path / "soakbox")
    ob.start(d, n_replica=3, fault_plan={
        "seed": 13,
        "drop": [{"prob": 0.05}],
        "delay": [{"extra_s": 0.01}],
    })
    try:
        # pause ~12s (kill_every/2) > the 10s FD grace: lease expiry
        # and the guardian cure MUST fire while the victim is hung
        report = run_kill_test(d, duration_s=50, kill_every_s=24,
                               seed=21, mode="pause",
                               op_timeout_ms=30_000)
        assert report["mode"] == "pause"
        assert report["violations"] == [], report
        assert report["kills"] >= 1
        # loss taxes throughput hard (a dropped frame costs the client
        # a full pump window): the invariant is the assertion, the rate
        # just proves the verifier actually ran
        assert report["writes_acked"] > 10
    finally:
        ob.stop(d)
