"""Operator-surface meta features: function level, online replica-count
update, rename, DDD diagnosis, manual proposals, backup-policy controls,
bulk-load pause/cancel, duplication pause/fail-mode.

Parity: meta_service.cpp admin RPC surface (RPC_CM_CONTROL_META,
RPC_CM_SET_MAX_REPLICA_COUNT, RPC_CM_RENAME_APP, ddd_diagnose,
RPC_CM_PROPOSE_BALANCER), meta_backup_service policy RPCs,
meta_bulk_load_service control RPCs, duplication fail_mode.
"""

import pytest

from pegasus_tpu.tools.cluster import SimCluster
from pegasus_tpu.utils.errors import PegasusError, StorageStatus

OK = int(StorageStatus.OK)


@pytest.fixture
def cluster(tmp_path):
    c = SimCluster(str(tmp_path / "cluster"), n_nodes=4)
    yield c
    c.close()


def _fill(client, n=20, prefix=b"k"):
    for i in range(n):
        assert client.set(b"%s%03d" % (prefix, i), b"s", b"v%d" % i) == OK


# ---- meta function level -------------------------------------------------

def test_freezed_level_blocks_cures_until_unfrozen(cluster):
    app_id = cluster.create_table("fl", partition_count=4)
    c = cluster.client("fl")
    _fill(c)
    assert cluster.meta.set_meta_level("freezed") == "freezed"
    victim = cluster.meta.state.get_partition(app_id, 0).primary
    ballot_before = cluster.meta.state.get_partition(app_id, 0).ballot
    cluster.kill(victim)
    cluster.step(rounds=10)
    # frozen: nothing was declared dead, no promote happened
    pc = cluster.meta.state.get_partition(app_id, 0)
    assert pc.primary == victim
    assert pc.ballot == ballot_before
    # unfreeze: the missed death is declared and the cure runs
    cluster.meta.set_meta_level("steady")
    cluster.step(rounds=8)
    pc = cluster.meta.state.get_partition(app_id, 0)
    assert pc.primary and pc.primary != victim
    assert c.get(b"k000", b"s") == (OK, b"v0")


def test_meta_level_persists_and_validates(cluster):
    with pytest.raises(PegasusError):
        cluster.meta.set_meta_level("bogus")
    cluster.meta.set_meta_level("lively")
    assert cluster.meta.storage.get("/meta_level") == "lively"
    assert cluster.meta.cluster_info()["meta_level"] == "lively"


# ---- online replica count ------------------------------------------------

def test_set_replica_count_grows_membership(cluster):
    app_id = cluster.create_table("rc", partition_count=4,
                                  replica_count=2)
    c = cluster.client("rc")
    _fill(c)
    assert cluster.meta.set_app_replica_count("rc", 3) == 3
    for _ in range(20):
        cluster.step()
        if all(len(cluster.meta.state.get_partition(app_id, p).members())
               == 3 for p in range(4)):
            break
    for p in range(4):
        assert len(cluster.meta.state.get_partition(
            app_id, p).members()) == 3, p
    # data still served
    assert c.get(b"k001", b"s") == (OK, b"v1")


def test_set_replica_count_sheds_extras(cluster):
    app_id = cluster.create_table("rcd", partition_count=4,
                                  replica_count=3)
    c = cluster.client("rcd")
    _fill(c)
    cluster.meta.set_app_replica_count("rcd", 2)
    for _ in range(20):
        cluster.step()
        if all(len(cluster.meta.state.get_partition(app_id, p).members())
               == 2 for p in range(4)):
            break
    for p in range(4):
        pc = cluster.meta.state.get_partition(app_id, p)
        assert len(pc.members()) == 2, (p, pc)
        assert pc.primary  # the primary is never the shed victim
    assert c.get(b"k002", b"s") == (OK, b"v2")


# ---- rename --------------------------------------------------------------

def test_rename_app(cluster):
    cluster.create_table("old_name", partition_count=2)
    c = cluster.client("old_name")
    _fill(c, 5)
    cluster.meta.rename_app("old_name", "new_name")
    assert cluster.meta.state.find_app("old_name") is None
    c2 = cluster.client("new_name")
    assert c2.get(b"k000", b"s") == (OK, b"v0")
    with pytest.raises(PegasusError):
        cluster.meta.rename_app("nope", "other")
    cluster.create_table("third", partition_count=2)
    with pytest.raises(PegasusError):
        cluster.meta.rename_app("third", "new_name")  # collision


def test_del_app_envs_unapplies_on_replicas(cluster):
    """A deleted env must be UN-applied, not just stop updating: deny
    gate lifted, throttle removed, default TTL back to none."""
    cluster.create_table("ev", partition_count=2)
    c = cluster.client("ev")
    _fill(c, 3)
    cluster.meta.update_app_envs(
        "ev", {"replica.deny_client_request": "timeout*all",
               "default_ttl": "60"})
    cluster.step(rounds=2)
    assert c.set(b"blocked", b"s", b"x") != OK  # deny active
    assert cluster.meta.del_app_envs(
        "ev", ["replica.deny_client_request", "default_ttl"]) == 2
    cluster.step(rounds=2)
    assert c.set(b"unblocked", b"s", b"x") == OK  # deny lifted
    _err, ttl = c.ttl(b"unblocked", b"s")
    assert ttl < 0  # default_ttl reset: no implicit ttl
    # clear_app_envs converges too
    cluster.meta.update_app_envs(
        "ev", {"replica.deny_client_request": "timeout*write"})
    cluster.step(rounds=2)
    assert c.set(b"again", b"s", b"x") != OK
    cluster.meta.clear_app_envs("ev")
    cluster.step(rounds=2)
    assert c.set(b"again", b"s", b"x") == OK


# ---- DDD diagnose + propose ----------------------------------------------

def test_ddd_diagnose_and_manual_propose(cluster):
    app_id = cluster.create_table("dd", partition_count=2,
                                  replica_count=3)
    c = cluster.client("dd")
    _fill(c, 10)
    members = cluster.meta.state.get_partition(app_id, 0).members()
    for m in members:
        cluster.kill(m)
    cluster.step(rounds=8)  # FD grace expiry; no cure possible
    ddd = cluster.meta.ddd_diagnose()
    assert any(tuple(d["gpid"]) == (app_id, 0) for d in ddd), ddd
    # operator revives one former member and forces primaryship onto it
    cluster.revive(members[0])
    cluster.step(rounds=6)
    if cluster.meta.state.get_partition(app_id, 0).primary != members[0]:
        cluster.meta.propose("dd", 0, "assign_primary", members[0])
        cluster.step(rounds=4)
    pc = cluster.meta.state.get_partition(app_id, 0)
    assert pc.primary == members[0]
    # downgrade proposal removes a secondary
    app2 = cluster.create_table("dd2", partition_count=1,
                                replica_count=3)
    cluster.step(rounds=2)
    pc2 = cluster.meta.state.get_partition(app2, 0)
    sec = pc2.secondaries[0]
    cluster.meta.propose("dd2", 0, "downgrade", sec)
    pc2 = cluster.meta.state.get_partition(app2, 0)
    assert sec not in pc2.members()


def test_propose_accepts_revived_node_with_stored_replica(cluster):
    """A revived ex-member is out of pc.members() after reconciliation
    but still holds the partition on disk (its config-sync report proves
    it) — propose assign_primary must accept it WITHOUT force. Parity:
    DDD recovery, shell propose/recover (commands.h:209-211)."""
    app_id = cluster.create_table("pr", partition_count=1,
                                  replica_count=3)
    c = cluster.client("pr")
    _fill(c, 10)
    members = cluster.meta.state.get_partition(app_id, 0).members()
    for m in members:
        cluster.kill(m)
    cluster.step(rounds=8)
    cluster.revive(members[0])
    cluster.step(rounds=6)
    pc = cluster.meta.state.get_partition(app_id, 0)
    if pc.primary != members[0]:
        # no force: the stored-replica report must carry the gate
        cluster.meta.propose("pr", 0, "assign_primary", members[0])
        cluster.step(rounds=4)
    assert cluster.meta.state.get_partition(app_id, 0).primary == \
        members[0]
    # data survived: the revived replica serves what was written
    c2 = cluster.client("pr")
    assert c2.get(b"k000", b"s")[1] == b"v0"


def test_propose_rejects_empty_node_without_force(cluster):
    """A live node holding NEITHER membership NOR stored data is still
    rejected without force — promoting it would serve empty reads."""
    app_id = cluster.create_table("pe", partition_count=1,
                                  replica_count=2)
    _fill(cluster.client("pe"), 5)
    pc = cluster.meta.state.get_partition(app_id, 0)
    outsider = next(n for n in cluster.meta.fd.alive_workers()
                    if n not in pc.members())
    cluster.step(rounds=2)  # let config_sync report stored replicas
    with pytest.raises(PegasusError):
        cluster.meta.propose("pe", 0, "assign_primary", outsider)
    # force is the explicit data-loss override and still works
    cluster.meta.propose("pe", 0, "assign_primary", outsider,
                         force=True)
    assert cluster.meta.state.get_partition(app_id, 0).primary == \
        outsider


# ---- backup policy controls ----------------------------------------------

def test_backup_policy_enable_disable_modify(cluster, tmp_path):
    cluster.create_table("bp", partition_count=2)
    c = cluster.client("bp")
    _fill(c, 8)
    root = str(tmp_path / "bucket")
    cluster.meta.backup.add_policy("daily", ["bp"], root,
                                   interval_seconds=5)
    cluster.meta.backup.enable_policy("daily", False)
    cluster.step(rounds=8)
    from pegasus_tpu.server.backup import BackupEngine
    from pegasus_tpu.storage.block_service import LocalBlockService

    be = BackupEngine(LocalBlockService(root), "daily")
    assert be.list_backups() == []  # disabled: nothing scheduled
    cluster.meta.backup.enable_policy("daily", True)
    cluster.step(rounds=8)
    assert len(be.list_backups()) >= 1
    pol = cluster.meta.backup.modify_policy(
        "daily", add_apps=["bp2"], interval_seconds=60)
    assert pol["interval_seconds"] == 60
    assert "bp2" in pol["app_names"]
    pol = cluster.meta.backup.modify_policy("daily",
                                            remove_apps=["bp2"])
    assert "bp2" not in pol["app_names"]
    q = cluster.meta.backup.query_policy("daily")
    assert q["name"] == "daily" and q["recent_backups"]
    with pytest.raises(PegasusError):
        cluster.meta.backup.query_policy("nope")


# ---- bulk load controls --------------------------------------------------

def test_bulk_load_pause_restart_cancel_clear(cluster, tmp_path):
    from pegasus_tpu.server.bulk_load import SSTGenerator
    from pegasus_tpu.storage.block_service import LocalBlockService

    cluster.create_table("bl", partition_count=4)
    root = str(tmp_path / "staged")
    gen = SSTGenerator(LocalBlockService(root), "bl", partition_count=4)
    gen.generate([(b"bl%04d" % i, b"s", b"v%d" % i, 0)
                  for i in range(40)])
    cluster.meta.bulk_load.max_concurrent = 1
    cluster.meta.bulk_load.start_bulk_load("bl", root)
    cluster.meta.bulk_load.pause_bulk_load("bl")
    cluster.step(rounds=6)
    st = cluster.meta.bulk_load.bulk_load_status("bl")
    assert not st["complete"] and st["paused"]
    assert st["pending"]  # the window never refilled while paused
    cluster.meta.bulk_load.restart_bulk_load("bl")
    for _ in range(15):
        cluster.step()
        if cluster.meta.bulk_load.bulk_load_status("bl")["complete"]:
            break
    assert cluster.meta.bulk_load.bulk_load_status("bl")["complete"]
    c = cluster.client("bl")
    cluster.step(rounds=2)
    assert c.get(b"bl0000", b"s") == (OK, b"v0")

    # cancel: visible failure record; clear: clean slate for a re-run
    gen2 = SSTGenerator(LocalBlockService(str(tmp_path / "s2")), "bl2",
                        partition_count=2)
    gen2.generate([(b"x%d" % i, b"s", b"y", 0) for i in range(10)])
    cluster.create_table("bl2", partition_count=2)
    cluster.meta.bulk_load.max_concurrent = 0  # stall: nothing ingests
    cluster.meta.bulk_load.start_bulk_load("bl2", str(tmp_path / "s2"))
    cluster.meta.bulk_load.cancel_bulk_load("bl2")
    st = cluster.meta.bulk_load.bulk_load_status("bl2")
    assert st["failed"] and "cancel" in st["reason"]
    cluster.meta.bulk_load.clear_bulk_load("bl2")
    st = cluster.meta.bulk_load.bulk_load_status("bl2")
    assert not st["failed"]
    with pytest.raises(PegasusError):
        cluster.meta.bulk_load.pause_bulk_load("bl2")  # nothing running


# ---- duplication pause / fail mode ---------------------------------------

def test_dup_pause_resume_and_fail_mode(cluster):
    cluster.create_table("dm", partition_count=2)
    cluster.create_table("df", partition_count=2)
    c = cluster.client("dm")
    _fill(c, 10, prefix=b"d")
    dupid = cluster.meta.duplication.add_duplication("dm", "meta", "df")
    for _ in range(8):
        cluster.step()
    fc = cluster.client("df")
    assert fc.get(b"d000", b"s") == (OK, b"v0")

    cluster.meta.duplication.pause_duplication(dupid)
    cluster.step(rounds=2)
    assert c.set(b"paused", b"s", b"pv") == OK
    cluster.step(rounds=6)
    assert fc.get(b"paused", b"s")[0] != OK  # not shipped while paused
    st = cluster.meta.duplication.query_duplication("dm")[0]
    assert st["status"] == "pause"

    cluster.meta.duplication.resume_duplication(dupid)
    for _ in range(8):
        cluster.step()
    assert fc.get(b"paused", b"s") == (OK, b"pv")

    # fail mode reaches the live replica session
    cluster.meta.duplication.set_fail_mode(dupid, "skip")
    cluster.step(rounds=3)
    sessions = [s for stub in cluster.stubs.values()
                for k, s in stub._dup_sessions.items()
                if k[1] == dupid]
    assert sessions and all(s.fail_mode == "skip" for s in sessions)
    with pytest.raises(PegasusError):
        cluster.meta.duplication.set_fail_mode(dupid, "bogus")
