"""Multi-process onebox: 1 meta + N replica server PROCESSES on one box.

Parity: the reference onebox (run.sh:60-66 start_onebox — real meta and
replica-server processes on one machine, the target of all function
tests). `start()` writes the cluster topology, spawns node processes via
`python -m pegasus_tpu.server.node_main`, and waits for liveness;
`connect()`/`admin()` return wire clients; `stop()` tears down.

CLI:
    python -m pegasus_tpu.tools.onebox_cluster start  [--dir D] [--nodes 3]
    python -m pegasus_tpu.tools.onebox_cluster status [--dir D]
    python -m pegasus_tpu.tools.onebox_cluster stop   [--dir D]
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

from pegasus_tpu.utils.errors import ErrorCode, PegasusError

DEFAULT_DIR = "/tmp/pegasus_tpu_onebox"
_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _cluster_paths(directory: str) -> Dict[str, str]:
    return {"config": os.path.join(directory, "cluster.json"),
            "pids": os.path.join(directory, "pids.json"),
            "logs": os.path.join(directory, "logs")}


def start(directory: str = DEFAULT_DIR, n_replica: int = 3,
          n_meta: int = 1, auth_secret: Optional[str] = None,
          name_prefix: str = "",
          extra_peers: Optional[Dict[str, Tuple[str, int]]] = None,
          fault_plan: Optional[dict] = None,
          disk_fault_plan: Optional[dict] = None,
          cluster_id: int = 1) -> dict:
    """`name_prefix` namespaces this cluster's node names (two oneboxes
    on one host must not both own "meta"); `extra_peers` maps REMOTE
    node names to (host, port) — written into the address book with
    role "external" so this cluster's nodes can dial another cluster
    (cross-cluster duplication), but never spawned or health-checked
    here. Remote names must match the peer cluster's own node names:
    the wire frame's dst field is how the receiving dispatcher finds
    its handler."""
    paths = _cluster_paths(directory)
    os.makedirs(paths["logs"], exist_ok=True)
    if n_meta <= 1:
        nodes = {f"{name_prefix}meta": {
            "host": "127.0.0.1", "port": _free_port(), "role": "meta"}}
    else:
        nodes = {f"{name_prefix}meta{i}": {
            "host": "127.0.0.1", "port": _free_port(), "role": "meta"}
            for i in range(n_meta)}
    for i in range(n_replica):
        nodes[f"{name_prefix}node{i}"] = {
            "host": "127.0.0.1", "port": _free_port(),
            "role": "replica"}
    for name, (host, port) in (extra_peers or {}).items():
        if name in nodes:
            raise ValueError(
                f"extra peer {name!r} collides with a local node — "
                "give one cluster a name_prefix")
        nodes[name] = {"host": host, "port": port, "role": "external"}
    cfg = {"data_root": os.path.join(directory, "data"), "nodes": nodes,
           # this cluster's identity in value timetags + the dup
           # origin-echo filter (geo-replicated clusters must differ)
           "cluster_id": cluster_id}
    if fault_plan:
        # chaos wiring for REAL processes: every node installs this
        # rpc/fault.FaultPlan schedule on its transport at boot (see
        # node_main), so kill_test/integration runs inject network
        # faults without any in-process hook
        cfg["fault_plan"] = fault_plan
    if disk_fault_plan:
        # the disk twin: storage/vfs.py fail-point actions (bit_flip /
        # torn_write / eio / enospc), armed in every node process at
        # boot from one seed so the run replays
        cfg["disk_fault_plan"] = disk_fault_plan
    if auth_secret:
        # onebox-grade key distribution: the secret lives in the cluster
        # config file (the keytab-file analogue)
        cfg["auth_secret"] = auth_secret
    with open(paths["config"], "w") as f:
        json.dump(cfg, f, indent=1)

    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    # server processes must never touch the accelerator tunnel: they are
    # the control/storage plane; device work happens via jax lazily only
    # when the read path runs — force CPU for the onebox (the single-chip
    # bench uses the in-process cluster instead)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)

    pids = {}
    for name in nodes:
        if nodes[name]["role"] == "external":
            continue  # book-only remote peer (another cluster's node)
        log = open(os.path.join(paths["logs"], f"{name}.log"), "ab")
        p = subprocess.Popen(
            [sys.executable, "-m", "pegasus_tpu.server.node_main",
             "--config", paths["config"], "--name", name],
            stdout=log, stderr=subprocess.STDOUT, env=env,
            cwd=_REPO_ROOT)
        pids[name] = p.pid
    with open(paths["pids"], "w") as f:
        json.dump(pids, f)

    # liveness: every node's port accepts within the deadline
    deadline = time.monotonic() + 30
    for name, n in nodes.items():
        if n["role"] == "external":
            continue
        while True:
            try:
                socket.create_connection((n["host"], n["port"]),
                                         timeout=1.0).close()
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise RuntimeError(f"{name} did not come up")
                time.sleep(0.2)
    return cfg


def stop(directory: str = DEFAULT_DIR) -> List[str]:
    paths = _cluster_paths(directory)
    stopped = []
    if not os.path.exists(paths["pids"]):
        return stopped
    with open(paths["pids"]) as f:
        pids = json.load(f)
    for name, pid in pids.items():
        try:
            os.kill(pid, signal.SIGTERM)
            stopped.append(name)
        except ProcessLookupError:
            pass
    os.remove(paths["pids"])
    return stopped


def status(directory: str = DEFAULT_DIR) -> Dict[str, bool]:
    paths = _cluster_paths(directory)
    if not os.path.exists(paths["pids"]):
        return {}
    with open(paths["pids"]) as f:
        pids = json.load(f)
    out = {}
    for name, pid in pids.items():
        try:
            os.kill(pid, 0)
            out[name] = True
        except ProcessLookupError:
            out[name] = False
    return out


def kill_node(name: str, directory: str = DEFAULT_DIR) -> None:
    """kill -9 one node (parity: the kill_test harness)."""
    paths = _cluster_paths(directory)
    with open(paths["pids"]) as f:
        pids = json.load(f)
    os.kill(pids[name], signal.SIGKILL)


def pause_node(name: str, directory: str = DEFAULT_DIR) -> None:
    """SIGSTOP one node: the process is alive but serves nothing and
    beacons nothing — the hung-node shape (GC pause, disk stall) that
    exercises FD lease expiry instead of crash recovery."""
    paths = _cluster_paths(directory)
    with open(paths["pids"]) as f:
        pids = json.load(f)
    os.kill(pids[name], signal.SIGSTOP)


def resume_node(name: str, directory: str = DEFAULT_DIR) -> None:
    """SIGCONT a paused node. It wakes believing it is still serving;
    the worker-side lease check must fence it until meta re-admits."""
    paths = _cluster_paths(directory)
    with open(paths["pids"]) as f:
        pids = json.load(f)
    os.kill(pids[name], signal.SIGCONT)


class OneboxAdmin:
    """Wire admin client: DDL against the onebox meta."""

    def __init__(self, directory: str = DEFAULT_DIR,
                 name: str = "admin-cli") -> None:
        from pegasus_tpu.rpc.transport import TcpTransport

        paths = _cluster_paths(directory)
        with open(paths["config"]) as f:
            self.cfg = json.load(f)
        book = {n: (c["host"], c["port"])
                for n, c in self.cfg["nodes"].items()}
        self.net = TcpTransport(None, book)
        self.name = name
        self._rids = itertools.count(1)
        self._replies: Dict[int, dict] = {}
        from pegasus_tpu.utils.backoff import Backoff

        self._backoff = Backoff()
        self.net.register(name, self._on_message)

    def _on_message(self, src: str, msg_type: str, payload) -> None:
        if msg_type in ("admin_reply", "remote_command_reply"):
            self._replies[payload["rid"]] = payload

    def remote_command(self, node: str, verb: str, args=None,
                       timeout: float = 10.0):
        """Invoke a registered control verb on one node (the chaos
        harness uses this to force flushes and read the integrity
        counters; the shell's wire mode has its own copy)."""
        rid = next(self._rids)
        self.net.send(self.name, node, "remote_command",
                      {"rid": rid, "cmd": verb, "args": args or []})
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if rid in self._replies:
                reply = self._replies.pop(rid)
                if reply["err"] != 0:
                    raise PegasusError(ErrorCode.ERR_HANDLER_NOT_FOUND,
                                       str(reply["result"]))
                return reply["result"]
            time.sleep(0.01)
        raise PegasusError(ErrorCode.ERR_TIMEOUT,
                           f"remote_command {verb} to {node}")

    def call(self, cmd: str, timeout: float = 15.0, **args):
        """One OVERALL deadline shared across the meta-group rotation —
        the caller's timeout bound holds in both directions."""
        metas = [n for n, c in self.cfg["nodes"].items()
                 if c["role"] == "meta"]
        overall = time.monotonic() + timeout
        last = None
        for i, meta in enumerate(metas):
            if i:
                # jittered pause before the next group member — the
                # same anti-storm pacing the data clients apply
                self._backoff.sleep(i)
            remaining = overall - time.monotonic()
            if remaining <= 0:
                break
            rid = next(self._rids)
            self.net.send(self.name, meta, "admin",
                          {"rid": rid, "cmd": cmd, "args": args})
            slice_deadline = time.monotonic() + remaining / (len(metas) - i)
            while time.monotonic() < slice_deadline:
                if rid in self._replies:
                    reply = self._replies.pop(rid)
                    if reply["err"] != int(ErrorCode.ERR_OK):
                        raise PegasusError(ErrorCode(reply["err"]),
                                           str(reply.get("result")))
                    return reply["result"]
                time.sleep(0.01)
            last = PegasusError(ErrorCode.ERR_TIMEOUT,
                                f"admin {cmd} via {meta}")
        raise last or PegasusError(ErrorCode.ERR_TIMEOUT, f"admin {cmd}")

    def create_table(self, app_name: str, partition_count: int = 8,
                     replica_count: int = 3,
                     envs: Optional[Dict[str, str]] = None) -> int:
        return self.call("create_app", app_name=app_name,
                         partition_count=partition_count,
                         replica_count=replica_count, envs=envs)

    def close(self) -> None:
        self.net.close()


def connect(app_name: str, directory: str = DEFAULT_DIR,
            client_name: Optional[str] = None, user: str = "admin",
            op_timeout_ms: Optional[float] = None,
            tenant: Optional[str] = None):
    """Wire data client for a onebox table. `op_timeout_ms` bounds each
    op end-to-end (all retries included); None keeps the
    client_op_timeout_ms flag default. `tenant` tags every request for
    server-side QoS accounting (None adopts the table's
    qos.default_tenant env, if any)."""
    from pegasus_tpu.client.cluster_client import ClusterClient
    from pegasus_tpu.rpc.transport import TcpTransport

    paths = _cluster_paths(directory)
    with open(paths["config"]) as f:
        cfg = json.load(f)
    book = {n: (c["host"], c["port"]) for n, c in cfg["nodes"].items()}
    net = TcpTransport(None, book)
    metas = [n for n, c in cfg["nodes"].items() if c["role"] == "meta"]
    auth = None
    if cfg.get("auth_secret"):
        from pegasus_tpu.security.auth import make_credentials

        auth = make_credentials(user, cfg["auth_secret"])
    return ClusterClient(
        net, client_name or f"client-{os.getpid()}", metas, app_name,
        pump=lambda: time.sleep(0.01), max_retries=8, pump_rounds=400,
        auth=auth, op_timeout_ms=op_timeout_ms, tenant=tenant)


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("action", choices=["start", "stop", "status"])
    ap.add_argument("--dir", default=DEFAULT_DIR)
    ap.add_argument("--nodes", type=int, default=3)
    ap.add_argument("--metas", type=int, default=1)
    args = ap.parse_args()
    if args.action == "start":
        cfg = start(args.dir, args.nodes, args.metas)
        print(json.dumps(cfg["nodes"], indent=1))
    elif args.action == "stop":
        print("stopped:", ", ".join(stop(args.dir)) or "(nothing)")
    else:
        print(json.dumps(status(args.dir), indent=1))


if __name__ == "__main__":
    main()
