"""bench_report: fold the scattered BENCH_r*.json files into one
perf-trajectory table.

Every bench round writes a BENCH_r<NN>.json with a ``phases`` dict;
the trajectory across rounds (phase × round → headline ops/s, ratio vs
the prior round that measured that phase) previously lived only in
PERF.md prose. This tool derives it from the artifacts:

    python -m pegasus_tpu.tools.bench_report [--dir REPO] [--json]

Per phase the HEADLINE metric is chosen by preference (the batched/
filtered number a round was run to prove, falling back to the first
numeric), so rounds that renamed their headline key still line up.
Absolute numbers across rounds ran on different boxes — the PERF.md
caveat — so the table prints the measured value AND the same-phase
ratio; trust trends, not cross-round absolutes.
"""

from __future__ import annotations

import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

_ROUND_RE = re.compile(r"^BENCH_r(\d+)\.json$")

# headline-metric preference per phase key suffix: first present+numeric
# wins. Ordered most-specific first; "qps"-ish generic keys last.
_HEADLINE_PREFS = (
    "aggregate_read_qps", "compliant_p99_ratio",
    "phash_qps", "filtered_qps", "row_cache_qps",
    "accel_qps", "read_qps", "write_qps", "qps", "records_per_s",
    "accel_records_per_s", "effective_gbps", "mesh_speedup",
    "pushdown_speedup", "filter_speedup", "speedup", "ratio",
)


def _numeric(v: Any) -> Optional[float]:
    if isinstance(v, bool):
        return None
    if isinstance(v, (int, float)):
        return float(v)
    return None


def headline(phase: Dict[str, Any]) -> Optional[Tuple[str, float]]:
    """(key, value) of one phase dict's headline metric."""
    for pref in _HEADLINE_PREFS:
        for k, v in phase.items():
            n = _numeric(v)
            if n is not None and (k == pref or k.endswith(pref)):
                return k, n
    for k in sorted(phase):
        n = _numeric(phase[k])
        if n is not None:
            return k, n
    return None


def load_rounds(bench_dir: str) -> List[Tuple[int, Dict[str, Any]]]:
    """[(round_number, phases dict)] for every BENCH_r*.json, sorted."""
    rounds = []
    for fn in sorted(os.listdir(bench_dir)):
        m = _ROUND_RE.match(fn)
        if not m:
            continue
        try:
            with open(os.path.join(bench_dir, fn)) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue  # torn artifact: skip, never crash the report
        phases = data.get("phases")
        if isinstance(phases, dict):
            rounds.append((int(m.group(1)), phases))
    rounds.sort()
    return rounds


def trajectory(bench_dir: str) -> Dict[str, Any]:
    """The folded table: phase -> [{round, metric, value, ratio}] where
    ratio compares against the PRIOR ROUND THAT MEASURED THE SAME
    METRIC of that phase (renamed headline keys restart the ratio
    chain rather than comparing apples to oranges)."""
    rounds = load_rounds(bench_dir)
    table: Dict[str, List[dict]] = {}
    for rnd, phases in rounds:
        for phase, body in sorted(phases.items()):
            if not isinstance(body, dict):
                continue
            hl = headline(body)
            if hl is None:
                continue
            key, value = hl
            rows = table.setdefault(phase, [])
            ratio = None
            for prior in reversed(rows):
                if prior["metric"] == key and prior["value"]:
                    ratio = round(value / prior["value"], 3)
                    break
            rows.append({"round": rnd, "metric": key,
                         "value": round(value, 3), "ratio": ratio})
    return {"rounds": [r for r, _p in rounds], "phases": table}


def render(report: Dict[str, Any]) -> str:
    lines = [f"perf trajectory over rounds {report['rounds']}"
             " (ratio = vs prior round measuring the same metric;"
             " boxes differ across rounds — trust trends)"]
    for phase, rows in sorted(report["phases"].items()):
        lines.append(f"{phase}:")
        for row in rows:
            ratio = (f"  ({row['ratio']:.3f}x)"
                     if row["ratio"] is not None else "")
            lines.append(
                f"  r{row['round']:>02}  {row['metric']:<28} "
                f"{row['value']:>14,.3f}{ratio}")
    return "\n".join(lines)


def main(argv=None) -> int:
    args = list(argv if argv is not None else sys.argv[1:])
    as_json = "--json" in args
    args = [a for a in args if a != "--json"]
    bench_dir = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    if "--dir" in args:
        i = args.index("--dir")
        if i + 1 >= len(args):
            print("bench_report: --dir needs a directory argument")
            return 2
        bench_dir = args[i + 1]
    elif args:
        bench_dir = args[0]
    report = trajectory(bench_dir)
    if not report["phases"]:
        print(f"bench_report: no BENCH_r*.json under {bench_dir}")
        return 1
    if as_json:
        print(json.dumps(report, indent=1))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
